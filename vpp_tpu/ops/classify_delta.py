"""Persistent incremental RuleTables builder — O(changed) ACL compiles.

``compile_pod_tables`` rebuilds EVERYTHING from Python objects on every
transaction: every rule re-encoded, every tensor re-uploaded, for any
single-key change.  At the roadmap scale (64k rules / 4k pods with
constant pod churn) that makes control-plane convergence O(cluster) per
event — the classifier-update wall RVH identifies (PAPERS.md).

:class:`AclTableBuilder` keeps the host-side numpy mirrors and the
table-interning map alive across transactions:

- **diff**: ``sync(state)`` diffs the incoming pod-entry dict against
  the builder's copy (identity check first, so unchanged keys cost one
  ``is``), and only dirty keys are touched;
- **interning**: identical rule lists share one table id with a
  refcount (the reference ACL renderer's table sharing); a policy flip
  re-interns one list — rules of other pods are never re-encoded;
- **rule rows**: each table owns a contiguous row span from a first-fit
  free-span allocator (spans keep the within-table first-match order);
  freed spans are zeroed (so padding stays canonical) and recycled;
- **pod slots**: the pod arrays stay IP-sorted (the device lookup is a
  binary search), so a pod add/delete memmoves the host suffix and
  ships only the slots whose values changed;
- **bucketing**: the pow2 rule/pod buckets grow on overflow (full-group
  reship, same XLA-recompile discipline as before) and shrink ONLY with
  4x hysteresis via a compacting full rebuild — churn at a bucket
  boundary cannot thrash device programs;
- **delta apply**: dirty rows ship through one jitted scatter per
  (group, pow2-index-bucket) — ``ops/delta.apply_rows`` — producing new
  device arrays without touching the old buffers (in-flight dispatches
  keep theirs);
- **incremental fingerprint**: per-leaf uint32 wrap-sums are maintained
  under every patch, so the applicator's expected-side fingerprint is a
  host fold, not a device reduction.

A FULL build (first sync, or a shrink compaction) resets the builder
through the same canonical insertion order as ``compile_pod_tables``
(pods sorted by str(key), ingress interned before egress), so a fresh
builder's arrays are bit-identical to the from-scratch compile.  After
arbitrary churn the delta layout may permute rows and table ids —
:func:`canonical_rule_tables` maps any layout back to the canonical one
for the equivalence property tests.
"""

from __future__ import annotations

import bisect
import time
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from .classify import (
    NO_TABLE,
    POD_PAD_IP,
    RuleTables,
    _next_pow2,
    rule_fields,
)
from .delta import apply_rows, fold_fingerprint, group_nbytes, u32_wrap_sum
from .delta import DeltaStats  # re-exported: builder.stats type

_U32 = 0xFFFFFFFF

# Column (name, dtype, pad value) specs — ORDER MUST MATCH
# RuleTables.tree_flatten (the fingerprint folds leaves in that order).
RULE_LEAVES: Tuple[Tuple[str, type], ...] = (
    ("rule_valid", np.bool_),
    ("rule_tid", np.int32),
    ("rule_src_base", np.uint32),
    ("rule_src_mask", np.uint32),
    ("rule_dst_base", np.uint32),
    ("rule_dst_mask", np.uint32),
    ("rule_proto", np.int32),
    ("rule_src_port", np.int32),
    ("rule_dst_port", np.int32),
    ("rule_action", np.int32),
)
POD_LEAVES: Tuple[Tuple[str, type, int], ...] = (
    ("pod_ip", np.uint32, POD_PAD_IP),
    ("pod_ingress_tid", np.int32, NO_TABLE),
    ("pod_egress_tid", np.int32, NO_TABLE),
)
# rule_fields() order -> rule column names 2..9.
_FIELD_COLS = (
    "rule_src_base", "rule_src_mask", "rule_dst_base", "rule_dst_mask",
    "rule_proto", "rule_src_port", "rule_dst_port", "rule_action",
)


class _SpanAlloc:
    """First-fit free-span allocator over ``[0, cap)`` row indices."""

    def __init__(self, cap: int):
        self.cap = cap
        self._spans: List[List[int]] = [[0, cap]]  # sorted [start, len]

    def alloc(self, n: int) -> Optional[int]:
        for i, (start, length) in enumerate(self._spans):
            if length >= n:
                if length == n:
                    self._spans.pop(i)
                else:
                    self._spans[i] = [start + n, length - n]
                return start
        return None

    def free(self, start: int, n: int) -> None:
        spans = self._spans
        i = bisect.bisect_left(spans, [start, 0])
        spans.insert(i, [start, n])
        if i + 1 < len(spans) and spans[i][0] + spans[i][1] == spans[i + 1][0]:
            spans[i][1] += spans[i + 1][1]
            spans.pop(i + 1)
        if i > 0 and spans[i - 1][0] + spans[i - 1][1] == spans[i][0]:
            spans[i - 1][1] += spans[i][1]
            spans.pop(i)

    def grow(self, newcap: int) -> None:
        self.free(self.cap, newcap - self.cap)
        self.cap = newcap

    @property
    def used(self) -> int:
        return self.cap - sum(length for _, length in self._spans)


@dataclass
class _TableRec:
    tid: int
    start: int
    n: int
    refs: int


class AclTableBuilder:
    """Incremental compiler for the classify RuleTables."""

    def __init__(self, bucket_min: int = 8):
        self.bucket_min = bucket_min
        self.stats = DeltaStats()
        self.last_tables: Optional[RuleTables] = None
        self.fingerprint: Optional[int] = None
        self._state: Dict[object, tuple] = {}
        self._reset(bucket_min, bucket_min)

    # ------------------------------------------------------------ lifecycle

    def _reset(self, rule_cap: int, pod_cap: int) -> None:
        self._r: Dict[str, np.ndarray] = {
            name: np.zeros(rule_cap, dtype=dt) for name, dt in RULE_LEAVES
        }
        self._p: Dict[str, np.ndarray] = {
            name: np.full(pod_cap, pad, dtype=dt) for name, dt, pad in POD_LEAVES
        }
        self._spans = _SpanAlloc(rule_cap)
        self._tables: Dict[tuple, _TableRec] = {}
        self._free_tids: List[int] = []
        self._next_tid = 0
        # pod ip -> {state key -> (ingress, egress, in_tid, eg_tid)}:
        # multiple pod keys can claim one IP; the winner matches
        # compile_pod_tables' dict-overwrite (largest str(key) wins).
        self._claims: Dict[int, Dict[object, tuple]] = {}
        self._p_live = 0
        self._sums: Dict[str, int] = {}
        for name, _ in RULE_LEAVES:
            self._sums[name] = u32_wrap_sum(self._r[name])
        for name, _, _ in POD_LEAVES:
            self._sums[name] = u32_wrap_sum(self._p[name])
        self._dirty_rules: set = set()
        self._dirty_pods: set = set()
        self._reship_rules = True
        self._reship_pods = True

    # ----------------------------------------------------------------- sync

    def sync(self, state: Mapping[object, tuple]) -> RuleTables:
        """Bring the compiled tables to ``state`` (key -> (pod_ip_u32,
        ingress rules, egress rules)); returns the new RuleTables with
        only changed rows shipped to the device."""
        t0 = time.perf_counter()
        self.stats.begin_build()
        changes: Dict[object, Optional[tuple]] = {}
        for key, entry in state.items():
            old = self._state.get(key)
            if old is not entry and old != entry:
                changes[key] = entry
        for key in self._state:
            if key not in state:
                changes[key] = None
        if self.last_tables is None:
            tables = self._full(dict(state))
        elif changes:
            tables = self._delta(changes)
        else:
            tables = self.last_tables
        dt = time.perf_counter() - t0
        self.stats.build_seconds += dt
        self.stats.last_build_seconds = dt
        return tables

    # ---------------------------------------------------------- delta build

    def _delta(self, changes: Dict[object, Optional[tuple]]) -> RuleTables:
        self._dirty_rules = set()
        self._dirty_pods = set()
        self._reship_rules = False
        self._reship_pods = False
        for key, entry in sorted(changes.items(), key=lambda kv: str(kv[0])):
            self._apply_change(key, entry)
        live = self._spans.used
        pod_cap = len(self._p["pod_ip"])
        if (self._spans.cap > self.bucket_min and live * 4 <= self._spans.cap) or (
            pod_cap > self.bucket_min and self._p_live * 4 <= pod_cap
        ):
            # Hysteresis shrink: compact through a full rebuild, landing
            # at 2x headroom so a regrow needs the live set to double.
            self.stats.shrinks += 1
            return self._full(
                self._state,
                rule_cap_min=_next_pow2(max(2 * live, 1), self.bucket_min),
                pod_cap_min=_next_pow2(max(2 * self._p_live, 1), self.bucket_min),
            )
        self.stats.delta_builds += 1
        return self._ship()

    def _apply_change(self, key: object, entry: Optional[tuple]) -> None:
        old = self._state.get(key)
        if entry is None:
            if old is not None:
                self._remove_pod(key, old)
                del self._state[key]
            return
        ip, ing, eg = int(entry[0]), tuple(entry[1]), tuple(entry[2])
        if old is not None:
            if int(old[0]) == ip:
                self._update_pod(key, ip, ing, eg)
                self._state[key] = entry
                return
            self._remove_pod(key, old)
        self._add_pod(key, ip, ing, eg)
        self._state[key] = entry

    def _add_pod(self, key: object, ip: int, ing: tuple, eg: tuple) -> None:
        in_tid = self._intern(ing)
        eg_tid = self._intern(eg)
        self._claims.setdefault(ip, {})[key] = (ing, eg, in_tid, eg_tid)
        self._set_slot(ip)

    def _update_pod(self, key: object, ip: int, ing: tuple, eg: tuple) -> None:
        claims = self._claims[ip]
        oing, oeg, _, _ = claims[key]
        # Intern BEFORE deref: a flip back to identical content must
        # keep the shared table alive instead of freeing + reallocating.
        in_tid = self._intern(ing)
        eg_tid = self._intern(eg)
        self._deref(oing)
        self._deref(oeg)
        claims[key] = (ing, eg, in_tid, eg_tid)
        self._set_slot(ip)

    def _remove_pod(self, key: object, old: tuple) -> None:
        ip = int(old[0])
        claims = self._claims.get(ip, {})
        rec = claims.pop(key, None)
        if rec is not None:
            self._deref(rec[0])
            self._deref(rec[1])
        if not claims:
            self._claims.pop(ip, None)
            self._del_slot(ip)
        else:
            self._set_slot(ip)

    # ------------------------------------------------------------ interning

    def _intern(self, rules: tuple) -> int:
        if not rules:
            return NO_TABLE  # no rules = allow: no table attached
        rec = self._tables.get(rules)
        if rec is not None:
            rec.refs += 1
            return rec.tid
        n = len(rules)
        while True:
            start = self._spans.alloc(n)
            if start is not None:
                break
            target = _next_pow2(self._spans.used + n, self.bucket_min)
            if target <= self._spans.cap:  # fragmentation, not capacity
                target = self._spans.cap * 2
            self._grow_rules(target)
        tid = self._free_tids.pop() if self._free_tids else self._alloc_tid()
        self._tables[rules] = _TableRec(tid, start, n, 1)
        sl = slice(start, start + n)
        rows = np.array([rule_fields(r) for r in rules], dtype=np.int64)
        self._patch_r("rule_valid", sl, np.ones(n, dtype=np.bool_))
        self._patch_r("rule_tid", sl, np.full(n, tid, dtype=np.int32))
        for j, col in enumerate(_FIELD_COLS):
            self._patch_r(col, sl, rows[:, j])
        return tid

    def _alloc_tid(self) -> int:
        tid = self._next_tid
        self._next_tid += 1
        return tid

    def _deref(self, rules: tuple) -> None:
        if not rules:
            return
        rec = self._tables[rules]
        rec.refs -= 1
        if rec.refs:
            return
        del self._tables[rules]
        self._free_tids.append(rec.tid)
        sl = slice(rec.start, rec.start + rec.n)
        for name, dt in RULE_LEAVES:
            self._patch_r(name, sl, np.zeros(rec.n, dtype=dt))
        self._spans.free(rec.start, rec.n)

    # ------------------------------------------------------------ pod slots

    def _winner(self, ip: int) -> Tuple[int, int]:
        claims = self._claims[ip]
        _, _, in_tid, eg_tid = claims[max(claims, key=str)]
        return in_tid, eg_tid

    def _set_slot(self, ip: int) -> None:
        in_tid, eg_tid = self._winner(ip)
        live = self._p_live
        pos = int(np.searchsorted(self._p["pod_ip"][:live], np.uint32(ip)))
        if pos < live and int(self._p["pod_ip"][pos]) == ip:
            if int(self._p["pod_ingress_tid"][pos]) != in_tid:
                self._patch_p("pod_ingress_tid", slice(pos, pos + 1),
                              np.full(1, in_tid, dtype=np.int32))
            if int(self._p["pod_egress_tid"][pos]) != eg_tid:
                self._patch_p("pod_egress_tid", slice(pos, pos + 1),
                              np.full(1, eg_tid, dtype=np.int32))
            return
        if live + 1 > len(self._p["pod_ip"]):
            self._grow_pods(_next_pow2(live + 1, self.bucket_min))
        for name, value in (("pod_ip", ip), ("pod_ingress_tid", in_tid),
                            ("pod_egress_tid", eg_tid)):
            arr = self._p[name]
            seg = np.concatenate(
                [np.asarray([value], dtype=arr.dtype), arr[pos:live]]
            )
            self._patch_p(name, slice(pos, live + 1), seg)
        self._p_live += 1

    def _del_slot(self, ip: int) -> None:
        live = self._p_live
        pos = int(np.searchsorted(self._p["pod_ip"][:live], np.uint32(ip)))
        if pos >= live or int(self._p["pod_ip"][pos]) != ip:
            return
        for (name, _, pad) in POD_LEAVES:
            arr = self._p[name]
            seg = np.concatenate(
                [arr[pos + 1:live], np.asarray([pad], dtype=arr.dtype)]
            )
            self._patch_p(name, slice(pos, live), seg)
        self._p_live -= 1

    # ------------------------------------------------------- array plumbing

    def _patch_r(self, name: str, sl: slice, values: np.ndarray) -> None:
        arr = self._r[name]
        old_sum = u32_wrap_sum(arr[sl])
        arr[sl] = values
        self._sums[name] = (
            self._sums[name] + u32_wrap_sum(arr[sl]) - old_sum
        ) & _U32
        self._dirty_rules.update(range(sl.start, sl.stop))

    def _patch_p(self, name: str, sl: slice, values: np.ndarray) -> None:
        arr = self._p[name]
        old_sum = u32_wrap_sum(arr[sl])
        arr[sl] = values
        self._sums[name] = (
            self._sums[name] + u32_wrap_sum(arr[sl]) - old_sum
        ) & _U32
        self._dirty_pods.update(range(sl.start, sl.stop))

    def _grow_rules(self, newcap: int) -> None:
        for name, dt in RULE_LEAVES:
            arr = np.zeros(newcap, dtype=dt)
            arr[: self._spans.cap] = self._r[name]
            self._r[name] = arr  # appended zeros: sums unchanged
        self._spans.grow(newcap)
        self._reship_rules = True
        self.stats.grows += 1

    def _grow_pods(self, newcap: int) -> None:
        oldcap = len(self._p["pod_ip"])
        for name, dt, pad in POD_LEAVES:
            arr = np.full(newcap, pad, dtype=dt)
            arr[:oldcap] = self._p[name]
            self._p[name] = arr
            self._sums[name] = (
                self._sums[name]
                + (newcap - oldcap) * u32_wrap_sum(np.asarray(pad, dtype=dt))
            ) & _U32
        self._reship_pods = True
        self.stats.grows += 1

    # --------------------------------------------------------- device apply

    def _ship(self) -> RuleTables:
        prev = self.last_tables
        if self._reship_rules or prev is None:
            rule_leaves = tuple(
                jnp.asarray(self._r[name]) for name, _ in RULE_LEAVES
            )
            self.stats.ship(self._spans.cap,
                            sum(self._r[name].nbytes for name, _ in RULE_LEAVES))
        elif self._dirty_rules:
            idx = np.asarray(sorted(self._dirty_rules), dtype=np.int32)
            rows = tuple(self._r[name][idx] for name, _ in RULE_LEAVES)
            prev_leaves = tuple(getattr(prev, name) for name, _ in RULE_LEAVES)
            rule_leaves = apply_rows(prev_leaves, idx, rows)
            self.stats.ship(len(idx), group_nbytes(idx, rows))
        else:
            rule_leaves = tuple(getattr(prev, name) for name, _ in RULE_LEAVES)
        if self._reship_pods or prev is None:
            pod_leaves = tuple(
                jnp.asarray(self._p[name]) for name, _, _ in POD_LEAVES
            )
            self.stats.ship(len(self._p["pod_ip"]),
                            sum(self._p[name].nbytes for name, _, _ in POD_LEAVES))
        elif self._dirty_pods:
            idx = np.asarray(sorted(self._dirty_pods), dtype=np.int32)
            rows = tuple(self._p[name][idx] for name, _, _ in POD_LEAVES)
            prev_leaves = tuple(getattr(prev, name) for name, _, _ in POD_LEAVES)
            pod_leaves = apply_rows(prev_leaves, idx, rows)
            self.stats.ship(len(idx), group_nbytes(idx, rows))
        else:
            pod_leaves = tuple(getattr(prev, name) for name, _, _ in POD_LEAVES)
        tables = RuleTables(
            *rule_leaves, *pod_leaves,
            num_rules=self._spans.used,
            num_tables=len(self._tables),
            num_pods=self._p_live,
        )
        self.last_tables = tables
        self.fingerprint = fold_fingerprint(
            [(self._sums[name], self._r[name].shape) for name, _ in RULE_LEAVES]
            + [(self._sums[name], self._p[name].shape) for name, _, _ in POD_LEAVES]
        )
        self._dirty_rules = set()
        self._dirty_pods = set()
        self._reship_rules = False
        self._reship_pods = False
        return tables

    # ----------------------------------------------------------- full build

    def _full(
        self,
        state: Dict[object, tuple],
        rule_cap_min: Optional[int] = None,
        pod_cap_min: Optional[int] = None,
    ) -> RuleTables:
        """From-scratch rebuild in the CANONICAL layout (interning in
        sorted-key order, rows concatenated in table-id order, pods
        IP-sorted) — bit-identical to compile_pod_tables, built
        VECTORIZED: one pass to intern, one array fill, registries
        re-derived, no per-pod suffix memmoves (the incremental insert
        path would make a 4k-pod resync O(P^2) host work).
        ``*_cap_min`` keep shrink compactions at 2x headroom."""
        self.stats.full_builds += 1
        tables: Dict[tuple, _TableRec] = {}
        order: List[tuple] = []  # table contents in tid order
        claims: Dict[int, Dict[object, tuple]] = {}
        assignments: Dict[int, Tuple[int, int]] = {}

        def intern(rules: tuple) -> int:
            if not rules:
                return NO_TABLE
            rec = tables.get(rules)
            if rec is not None:
                rec.refs += 1
                return rec.tid
            tid = len(order)
            tables[rules] = _TableRec(tid, 0, len(rules), 1)
            order.append(rules)
            return tid

        for key, entry in sorted(state.items(), key=lambda kv: str(kv[0])):
            ip, ing, eg = int(entry[0]), tuple(entry[1]), tuple(entry[2])
            in_tid = intern(ing)
            eg_tid = intern(eg)
            claims.setdefault(ip, {})[key] = (ing, eg, in_tid, eg_tid)
            assignments[ip] = (in_tid, eg_tid)  # last sorted key wins

        n_rows = sum(rec.n for rec in tables.values())
        rule_cap = max(_next_pow2(max(n_rows, 1), self.bucket_min),
                       rule_cap_min or 0)
        p = len(assignments)
        pod_cap = max(_next_pow2(max(p, 1), self.bucket_min),
                      pod_cap_min or 0)
        self._reset(rule_cap, pod_cap)

        rows: List[Tuple] = []
        start = 0
        for rules in order:
            rec = tables[rules]
            rec.start = start
            start += rec.n
            for r in rules:
                rows.append((rec.tid,) + rule_fields(r))
        if rows:
            arr = np.asarray(rows, dtype=np.int64)
            self._r["rule_valid"][:n_rows] = True
            self._r["rule_tid"][:n_rows] = arr[:, 0]
            for j, col in enumerate(_FIELD_COLS):
                self._r[col][:n_rows] = arr[:, j + 1]
        for i, (ip, (in_tid, eg_tid)) in enumerate(sorted(assignments.items())):
            self._p["pod_ip"][i] = ip
            self._p["pod_ingress_tid"][i] = in_tid
            self._p["pod_egress_tid"][i] = eg_tid

        self._state = dict(state)
        self._tables = tables
        self._claims = claims
        self._next_tid = len(order)
        self._p_live = p
        if n_rows:
            self._spans.alloc(n_rows)  # rows occupy one canonical prefix
        for name, _ in RULE_LEAVES:
            self._sums[name] = u32_wrap_sum(self._r[name])
        for name, _, _ in POD_LEAVES:
            self._sums[name] = u32_wrap_sum(self._p[name])
        self.last_tables = None
        return self._ship()

    # -------------------------------------------------------------- queries

    @property
    def num_rules(self) -> int:
        return self._spans.used

    @property
    def num_tables(self) -> int:
        return len(self._tables)

    @property
    def num_pods(self) -> int:
        return self._p_live


# --------------------------------------------------------------------------
# Canonicalization (equivalence testing)
# --------------------------------------------------------------------------


def canonical_rule_tables(t: RuleTables) -> RuleTables:
    """Map ANY RuleTables layout (delta-permuted rows / recycled table
    ids / hysteresis padding) to the canonical from-scratch layout:
    table ids relabeled by first appearance in pod-slot order, rows
    repacked contiguously in that order, pow2 padding recomputed.  Two
    tables are semantically identical iff their canonical forms are
    array-identical — the equivalence property the churn tests assert."""
    valid = np.asarray(t.rule_valid)
    tid = np.asarray(t.rule_tid)
    field_cols = {name: np.asarray(getattr(t, name)) for name in _FIELD_COLS}
    pod_ip = np.asarray(t.pod_ip)
    pod_in = np.asarray(t.pod_ingress_tid)
    pod_eg = np.asarray(t.pod_egress_tid)
    live = pod_ip != POD_PAD_IP

    order: List[int] = []
    seen = set()
    for side in zip(pod_in[live], pod_eg[live]):
        for old_tid in side:
            old_tid = int(old_tid)
            if old_tid != NO_TABLE and old_tid not in seen:
                seen.add(old_tid)
                order.append(old_tid)
    remap = {old: new for new, old in enumerate(order)}

    rows: List[Tuple] = []
    for old_tid in order:
        for i in np.nonzero(valid & (tid == old_tid))[0]:
            rows.append(
                (remap[old_tid],)
                + tuple(int(field_cols[name][i]) for name in _FIELD_COLS)
            )
    n = len(rows)
    padded = _next_pow2(max(n, 1), 8)
    arr = np.zeros((padded, 9), dtype=np.int64)
    if rows:
        arr[:n] = np.asarray(rows, dtype=np.int64)
    new_valid = np.zeros(padded, dtype=bool)
    new_valid[:n] = True

    p = int(live.sum())
    p_padded = _next_pow2(max(p, 1), 8)
    new_ip = np.full(p_padded, POD_PAD_IP, dtype=np.uint32)
    new_in = np.full(p_padded, NO_TABLE, dtype=np.int32)
    new_eg = np.full(p_padded, NO_TABLE, dtype=np.int32)
    new_ip[:p] = pod_ip[live]
    new_in[:p] = [remap.get(int(x), NO_TABLE) for x in pod_in[live]]
    new_eg[:p] = [remap.get(int(x), NO_TABLE) for x in pod_eg[live]]

    return RuleTables(
        rule_valid=jnp.asarray(new_valid),
        rule_tid=jnp.asarray(arr[:, 0].astype(np.int32)),
        rule_src_base=jnp.asarray(arr[:, 1].astype(np.uint32)),
        rule_src_mask=jnp.asarray(arr[:, 2].astype(np.uint32)),
        rule_dst_base=jnp.asarray(arr[:, 3].astype(np.uint32)),
        rule_dst_mask=jnp.asarray(arr[:, 4].astype(np.uint32)),
        rule_proto=jnp.asarray(arr[:, 5].astype(np.int32)),
        rule_src_port=jnp.asarray(arr[:, 6].astype(np.int32)),
        rule_dst_port=jnp.asarray(arr[:, 7].astype(np.int32)),
        rule_action=jnp.asarray(arr[:, 8].astype(np.int32)),
        pod_ip=jnp.asarray(new_ip),
        pod_ingress_tid=jnp.asarray(new_in),
        pod_egress_tid=jnp.asarray(new_eg),
        num_rules=n,
        num_tables=len(order),
        num_pods=p,
    )
