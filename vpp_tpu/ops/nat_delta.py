"""Persistent incremental NatTables builder — O(changed) NAT compiles.

The HyperNAT problem (PAPERS.md): NAT table churn at cloud scale —
endpoint adds/removes arrive continuously, and rebuilding the whole
mapping set (plus a full device upload) per change makes convergence
O(cluster).  :class:`NatTableBuilder` keeps numpy mirrors of every
NatTables leaf alive across transactions and patches in place:

- **service diff**: ``sync`` takes the per-service mapping dict; only
  changed services are diffed, mapping-by-mapping on the external
  (ip, port, proto) key.  An endpoint add/remove rewrites ONE backend
  ring row; policy knobs (twice-NAT, affinity) patch single columns;
- **row slots**: mapping rows come from a free list; freed rows are
  zeroed (canonical padding) and recycled;
- **ring width**: the table-wide backend-ring width K is semantic
  (``flow_hash % K`` picks the slot), so it tracks
  ``effective_bucket_size`` exactly — a K crossing rebuilds all rings
  (one wide reship), never silently diverges from a full build;
- **exact-match index**: the open-addressed hmap is maintained
  incrementally — the device lookup gathers ALL ``MAP_PROBE_WAYS``
  slots unconditionally, so a delete simply clears the slot and an
  insert takes any empty slot in the probe window; growth (or the
  adversarial same-hash bound) falls back to the canonical rebuild;
- **buckets**: the pow2 row bucket grows on overflow and shrinks only
  with 4x hysteresis via a compacting full rebuild;
- **fingerprint**: per-leaf uint32 wrap-sums are maintained under every
  patch (host fold == device ``table_fingerprint``, property-tested).

Correctness fallbacks (rare, full-rebuild-per-txn until they clear):
duplicate external keys (within or across services — first-match-wins
needs the canonical row order) and the hmap's adversarial growth bound.

``canonical_nat_tables`` maps any layout to a canonical row-sorted form
for the equivalence property tests.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from .classify import _next_pow2
from .delta import apply_rows, fold_fingerprint, group_nbytes, u32_wrap_sum
from .nat import (
    MAP_PROBE_WAYS,
    NatMapping,
    NatTables,
    _build_map_hash,
    _map_key_hash_py,
    _pick_use_hmap,
    bucket_ring,
    build_nat_host,
)
from .packets import ip_to_u32

_U32 = 0xFFFFFFFF

# Per-mapping-row columns (name, dtype) — subset of the NatTables leaves
# scattered together as one group.
ROW_LEAVES: Tuple[Tuple[str, type], ...] = (
    ("map_ext_ip", np.uint32),
    ("map_ext_port", np.int32),
    ("map_proto", np.int32),
    ("map_twice_nat", np.int32),
    ("map_affinity", np.int32),
    ("map_valid", np.bool_),
    ("map_aff_timeout", np.int32),
)
RING_LEAVES: Tuple[Tuple[str, type], ...] = (
    ("backend_ip", np.uint32),
    ("backend_port", np.int32),
)
SCALAR_LEAVES: Tuple[str, ...] = (
    "nat_loopback", "snat_ip", "snat_enabled",
    "pod_subnet_base", "pod_subnet_mask",
)
# NatTables.tree_flatten leaf order (the fingerprint fold order).
NAT_LEAF_ORDER: Tuple[str, ...] = (
    "map_ext_ip", "map_ext_port", "map_proto", "map_twice_nat",
    "map_affinity", "map_valid", "backend_ip", "backend_port", "hmap_idx",
    "nat_loopback", "snat_ip", "snat_enabled",
    "pod_subnet_base", "pod_subnet_mask", "map_aff_timeout",
)

ExtKey = Tuple[int, int, int]  # (ext_ip_u32, ext_port, proto)


def _ext_key(m: NatMapping) -> ExtKey:
    return (ip_to_u32(m.external_ip), int(m.external_port), int(m.protocol))


def _sorted_keys(services: Mapping) -> list:
    try:
        return sorted(services)
    except TypeError:  # mixed/unorderable keys: fall back to str order
        return sorted(services, key=str)


class NatTableBuilder:
    """Incremental compiler for the NAT44 NatTables."""

    def __init__(self, bucket_size: int = 64):
        self.bucket_base = bucket_size
        from .delta import DeltaStats

        self.stats = DeltaStats()
        self.last_tables: Optional[NatTables] = None
        self.fingerprint: Optional[int] = None
        self._services: Dict[object, Tuple[NatMapping, ...]] = {}
        self._glob: Optional[tuple] = None
        self._claim_count: Dict[ExtKey, int] = {}
        self._ndup = 0  # ext keys with >1 claim -> full-rebuild mode
        # True while the LAST build ran in a correctness-fallback mode
        # (dups / hmap growth bound): the incremental registries are
        # stale then, so the first post-fallback sync must also be full.
        self._fallback_prev = False
        self._hmap_ok = True

    # ----------------------------------------------------------------- sync

    def sync(
        self,
        services: Mapping[object, Sequence[NatMapping]],
        nat_loopback: str = "0.0.0.0",
        snat_ip: str = "0.0.0.0",
        snat_enabled: bool = False,
        pod_subnet: str = "10.1.0.0/16",
    ) -> NatTables:
        """Bring the compiled NatTables to the given per-service mapping
        dict + global knobs, shipping only changed rows."""
        t0 = time.perf_counter()
        self.stats.begin_build()
        services = {k: tuple(v) for k, v in services.items()}
        glob = (nat_loopback, snat_ip, bool(snat_enabled), pod_subnet)
        changed = [
            k for k in set(services) | set(self._services)
            if self._services.get(k) != services.get(k)
        ]
        # Claim accounting first: duplicate external keys (within or
        # across services) force the canonical full build, because
        # first-match-wins depends on the canonical row order.
        for key in changed:
            for m in self._services.get(key, ()):
                self._claim(_ext_key(m), -1)
            for m in services.get(key, ()):
                self._claim(_ext_key(m), +1)
        if self.last_tables is not None and not changed and glob == self._glob:
            tables = self.last_tables  # no-op txn
        elif (
            self.last_tables is None
            or self._ndup
            or not self._hmap_ok
            or self._fallback_prev
        ):
            tables = self._full(services, glob)
            self._fallback_prev = bool(self._ndup) or not self._hmap_ok
        else:
            tables = self._delta(services, changed, glob)
            self._fallback_prev = not self._hmap_ok
        dt = time.perf_counter() - t0
        self.stats.build_seconds += dt
        self.stats.last_build_seconds = dt
        return tables

    def _claim(self, ek: ExtKey, d: int) -> None:
        c = self._claim_count.get(ek, 0)
        n = c + d
        if c > 1 and n <= 1:
            self._ndup -= 1
        elif c <= 1 and n > 1:
            self._ndup += 1
        if n:
            self._claim_count[ek] = n
        else:
            self._claim_count.pop(ek, None)

    # ---------------------------------------------------------- delta build

    def _delta(self, services: Dict[object, tuple], changed: list,
               glob: tuple) -> NatTables:
        self._dirty_rows: set = set()
        self._dirty_rings: set = set()
        self._dirty_hslots: set = set()
        self._reship_rows = False
        self._reship_rings = False
        self._reship_hmap = False
        self._reship_scalars = False
        # Removals first across all services: a mapping moving between
        # services in one txn must free its row before the add claims it.
        adds: List[Tuple[ExtKey, NatMapping]] = []
        patches: List[Tuple[ExtKey, NatMapping]] = []
        for key in _sorted_keys({k: None for k in changed}):
            old_by = {_ext_key(m): m for m in self._services.get(key, ())}
            new_by = {_ext_key(m): m for m in services.get(key, ())}
            for ek, m in old_by.items():
                if ek not in new_by:
                    self._remove_mapping(ek)
            for ek, m in new_by.items():
                if ek not in old_by:
                    adds.append((ek, m))
                elif old_by[ek] != m:
                    patches.append((ek, m))
            if key in services:
                self._services[key] = services[key]
            else:
                self._services.pop(key, None)
        # Ring width is semantic (flow_hash % K) and must track the
        # canonical effective_bucket_size exactly — and it must be
        # decided BEFORE any ring row is written: a txn that raises a
        # mapping's backend count past the current K would otherwise
        # feed bucket_ring a too-narrow ring (its one-slot-per-backend
        # floor can't fit) mid-apply.  The maxes are maintained
        # incrementally (O(changed) per txn; a rescan only when the
        # argmax row itself left), with the pending adds/patches folded
        # into the prospective maximum here.
        for ek, m in patches:
            self._set_weights(self._row_of[ek], m)
        need_max, n_max = self._current_maxes()
        for _, m in adds:
            need_max = max(need_max, self._need(m))
            n_max = max(n_max, len(m.backends))
        k_target = self._k_from(need_max, n_max)
        if k_target != self._K:
            # Rebuild with the PENDING patch content in place of stale
            # rows: on a shrink the old content may not fit the new
            # width (that is exactly why K is shrinking).
            self._rebuild_rings(
                k_target,
                override={self._row_of[ek]: m for ek, m in patches},
            )

        for ek, m in adds:
            self._add_mapping(ek, m)
        for ek, m in patches:
            self._patch_mapping(ek, m)
        self._maybe_shrink_hmap()
        if glob != self._glob:
            self._set_glob(glob)

        live = len(self._map_of)
        cap = len(self._cols["map_valid"])
        if cap > _next_pow2(1) and live * 4 <= cap:
            self.stats.shrinks += 1
            return self._full(
                dict(self._services), self._glob,
                row_cap_min=_next_pow2(max(2 * live, 1)),
            )
        self.stats.delta_builds += 1
        return self._ship()

    # --------------------------------------------------- ring-width (K)

    @staticmethod
    def _need(m: NatMapping) -> int:
        """One mapping's weighted-expansion demand (0 when backend-less)
        — the per-mapping term of effective_bucket_size."""
        return sum(max(1, w) for _, _, w in m.backends) if m.backends else 0

    def _k_from(self, need: int, n_max: int) -> int:
        """effective_bucket_size over maintained maxima — must stay in
        lockstep with the canonical formula (the churn property test
        compares bucket_size against full builds every step)."""
        k = self.bucket_base
        if need > k:
            k = max(k, _next_pow2(min(need, 4096)))
        if n_max > k:
            k = _next_pow2(n_max)
        return k

    def _set_weights(self, row: int, m: NatMapping) -> None:
        old = self._weights.get(row)
        new = (self._need(m), len(m.backends))
        self._weights[row] = new
        if old is not None and (
            old[0] >= self._need_max or old[1] >= self._nmax
        ) and (new[0] < old[0] or new[1] < old[1]):
            self._max_dirty = True  # the argmax row may have shrunk
        self._need_max = max(self._need_max, new[0])
        self._nmax = max(self._nmax, new[1])

    def _drop_weights(self, row: int) -> None:
        old = self._weights.pop(row, None)
        if old is not None and (
            old[0] >= self._need_max or old[1] >= self._nmax
        ):
            self._max_dirty = True

    def _current_maxes(self) -> Tuple[int, int]:
        if self._max_dirty:
            self._need_max = max(
                (v[0] for v in self._weights.values()), default=0)
            self._nmax = max(
                (v[1] for v in self._weights.values()), default=0)
            self._max_dirty = False
        return self._need_max, self._nmax

    # ------------------------------------------------------- mapping CRUD

    def _alloc_row(self) -> int:
        if self._free_rows:
            return self._free_rows.pop()
        row = self._row_high
        cap = len(self._cols["map_valid"])
        if row >= cap:
            self._grow_rows(cap * 2)
        self._row_high += 1
        return row

    def _add_mapping(self, ek: ExtKey, m: NatMapping) -> None:
        row = self._alloc_row()
        valid = bool(m.backends)
        self._patch_row(row, {
            "map_ext_ip": ek[0], "map_ext_port": ek[1], "map_proto": ek[2],
            "map_twice_nat": m.twice_nat,
            "map_affinity": 1 if m.session_affinity_timeout > 0 else 0,
            "map_valid": valid,
            "map_aff_timeout": m.session_affinity_timeout,
        })
        self._write_ring(row, m if valid else None)
        self._row_of[ek] = row
        self._map_of[row] = m
        self._set_weights(row, m)
        if valid:
            self._n_valid += 1
            self._hmap_add(ek, row)
        if m.session_affinity_timeout > 0:
            self._n_affinity += 1

    def _patch_mapping(self, ek: ExtKey, m: NatMapping) -> None:
        row = self._row_of[ek]
        old = self._map_of[row]
        was_valid = bool(old.backends)
        valid = bool(m.backends)
        self._patch_row(row, {
            "map_twice_nat": m.twice_nat,
            "map_affinity": 1 if m.session_affinity_timeout > 0 else 0,
            "map_valid": valid,
            "map_aff_timeout": m.session_affinity_timeout,
        })
        if old.backends != m.backends:
            self._write_ring(row, m if valid else None)
        self._map_of[row] = m
        self._n_valid += int(valid) - int(was_valid)
        self._n_affinity += int(m.session_affinity_timeout > 0) - int(
            old.session_affinity_timeout > 0)
        if valid and not was_valid:
            self._hmap_add(ek, row)
        elif was_valid and not valid:
            self._hmap_remove(ek)

    def _remove_mapping(self, ek: ExtKey) -> None:
        row = self._row_of.pop(ek)
        old = self._map_of.pop(row)
        self._patch_row(row, {name: 0 for name, _ in ROW_LEAVES})
        self._write_ring(row, None)
        self._drop_weights(row)
        if bool(old.backends):
            self._n_valid -= 1
            self._hmap_remove(ek)
        if old.session_affinity_timeout > 0:
            self._n_affinity -= 1
        self._free_rows.append(row)

    # -------------------------------------------------------- row plumbing

    def _patch_row(self, row: int, values: Dict[str, Any]) -> None:
        for name, value in values.items():
            arr = self._cols[name]
            old = u32_wrap_sum(arr[row:row + 1])
            arr[row] = value
            self._sums[name] = (
                self._sums[name] + u32_wrap_sum(arr[row:row + 1]) - old
            ) & _U32
        self._dirty_rows.add(row)

    def _write_ring(self, row: int, m: Optional[NatMapping]) -> None:
        ring = bucket_ring(m, self._K) if m is not None else None
        for j, (name, dt) in enumerate(RING_LEAVES):
            arr = self._cols[name]
            old = u32_wrap_sum(arr[row])
            if ring is None:
                arr[row] = 0
            else:
                arr[row] = np.asarray([e[j] for e in ring], dtype=dt)
            self._sums[name] = (
                self._sums[name] + u32_wrap_sum(arr[row]) - old
            ) & _U32
        self._dirty_rings.add(row)

    def _grow_rows(self, newcap: int) -> None:
        oldcap = len(self._cols["map_valid"])
        for name, dt in ROW_LEAVES:
            arr = np.zeros(newcap, dtype=dt)
            arr[:oldcap] = self._cols[name]
            self._cols[name] = arr
        for name, dt in RING_LEAVES:
            arr = np.zeros((newcap, self._K), dtype=dt)
            arr[:oldcap] = self._cols[name]
            self._cols[name] = arr
        self._reship_rows = True
        self._reship_rings = True
        self.stats.grows += 1

    def _rebuild_rings(self, k_new: int,
                       override: Optional[Dict[int, NatMapping]] = None) -> None:
        cap = len(self._cols["map_valid"])
        for name, dt in RING_LEAVES:
            self._cols[name] = np.zeros((cap, k_new), dtype=dt)
        self._K = k_new
        for row, m in self._map_of.items():
            if override and row in override:
                m = override[row]  # this txn's pending patch content
            if not m.backends:
                continue
            ring = bucket_ring(m, k_new)
            for j, (name, dt) in enumerate(RING_LEAVES):
                self._cols[name][row] = np.asarray(
                    [e[j] for e in ring], dtype=dt
                )
        for name, _ in RING_LEAVES:
            self._sums[name] = u32_wrap_sum(self._cols[name])
        self._reship_rings = True

    # ------------------------------------------------------- hmap plumbing

    def _hmap_patch(self, slot: int, value: int) -> None:
        arr = self._cols["hmap_idx"]
        old = u32_wrap_sum(arr[slot:slot + 1])
        arr[slot] = value
        self._sums["hmap_idx"] = (
            self._sums["hmap_idx"] + u32_wrap_sum(arr[slot:slot + 1]) - old
        ) & _U32
        self._dirty_hslots.add(slot)

    def _hmap_add(self, ek: ExtKey, row: int) -> None:
        # The device lookup gathers ALL probe-window slots
        # unconditionally (no early termination), so any empty slot in
        # the window is a correct home and deletes can simply clear.
        hmap = self._cols["hmap_idx"]
        cap = len(hmap)
        base = _map_key_hash_py(*ek) & (cap - 1)
        for w in range(MAP_PROBE_WAYS):
            slot = (base + w) & (cap - 1)
            if hmap[slot] < 0:
                self._hmap_patch(slot, row)
                self._hmap_slot[ek] = slot
                return
        self._rebuild_hmap(start=cap * 2)

    def _hmap_remove(self, ek: ExtKey) -> None:
        slot = self._hmap_slot.pop(ek, None)
        if slot is not None:
            self._hmap_patch(slot, -1)

    def _hmap_entries(self) -> List[Tuple[int, ExtKey]]:
        return sorted(
            (row, ek) for ek, row in self._row_of.items()
            if bool(self._map_of[row].backends)
        )

    def _canonical_hmap_start(self) -> int:
        return _next_pow2(max(2 * self._n_valid, 8), minimum=16)

    def _rebuild_hmap(self, start: int) -> None:
        hmap = _build_map_hash(self._hmap_entries(), start_capacity=start)
        if hmap is None:
            # Adversarial same-hash key set: canonical dense fallback.
            # Ship the STUB index (a stale partial index would let
            # retarget_tables re-enable use_hmap on another backend);
            # subsequent syncs run the canonical full build until the
            # colliding keys leave.
            self._hmap_ok = False
            self._cols["hmap_idx"] = np.full(16, -1, dtype=np.int32)
            self._sums["hmap_idx"] = u32_wrap_sum(self._cols["hmap_idx"])
            self._hmap_slot = {}
            self._reship_hmap = True
            return
        self._cols["hmap_idx"] = hmap
        self._sums["hmap_idx"] = u32_wrap_sum(hmap)
        self._hmap_slot = {
            ek: slot
            for row, ek in self._hmap_entries()
            for slot in np.nonzero(hmap == row)[0][:1]
        }
        self._reship_hmap = True

    def _maybe_shrink_hmap(self) -> None:
        cap = len(self._cols["hmap_idx"])
        want = self._canonical_hmap_start()
        if not (cap > 16 and want * 4 <= cap):
            return
        if getattr(self, "_hmap_no_shrink", None) == (cap, want):
            return  # this exact shrink already failed: keys need cap
        cand = _build_map_hash(self._hmap_entries(), start_capacity=want)
        if cand is None or len(cand) >= cap:
            # The probe-window invariant needs the current capacity (or
            # the build hit its bound): remember and stop retrying every
            # txn until the key set or capacity changes.
            self._hmap_no_shrink = (cap, want)
            return
        self._hmap_no_shrink = None
        self._cols["hmap_idx"] = cand
        self._sums["hmap_idx"] = u32_wrap_sum(cand)
        self._hmap_slot = {
            ek: slot
            for row, ek in self._hmap_entries()
            for slot in np.nonzero(cand == row)[0][:1]
        }
        self._reship_hmap = True

    # ------------------------------------------------------------- scalars

    def _set_glob(self, glob: tuple) -> None:
        import ipaddress

        nat_loopback, snat_ip, snat_enabled, pod_subnet = glob
        net = ipaddress.ip_network(pod_subnet)
        mask = (
            (0xFFFFFFFF << (32 - net.prefixlen)) & 0xFFFFFFFF
            if net.prefixlen else 0
        )
        self._cols["nat_loopback"] = np.asarray(
            ip_to_u32(nat_loopback), dtype=np.uint32)
        self._cols["snat_ip"] = np.asarray(ip_to_u32(snat_ip), dtype=np.uint32)
        self._cols["snat_enabled"] = np.asarray(bool(snat_enabled))
        self._cols["pod_subnet_base"] = np.asarray(
            int(net.network_address), dtype=np.uint32)
        self._cols["pod_subnet_mask"] = np.asarray(mask, dtype=np.uint32)
        for name in SCALAR_LEAVES:
            self._sums[name] = u32_wrap_sum(self._cols[name])
        self._glob = glob
        self._reship_scalars = True

    # --------------------------------------------------------- device apply

    def _group(self, names, reship, dirty) -> tuple:
        prev = self.last_tables
        if reship or prev is None:
            leaves = tuple(jnp.asarray(self._cols[n]) for n in names)
            self.stats.ship(
                len(self._cols[names[0]]),
                sum(self._cols[n].nbytes for n in names),
            )
        elif dirty:
            idx = np.asarray(sorted(dirty), dtype=np.int32)
            rows = tuple(self._cols[n][idx] for n in names)
            leaves = apply_rows(
                tuple(getattr(prev, n) for n in names), idx, rows
            )
            self.stats.ship(len(idx), group_nbytes(idx, rows))
        else:
            leaves = tuple(getattr(prev, n) for n in names)
        return leaves

    def _ship(self) -> NatTables:
        row_names = tuple(n for n, _ in ROW_LEAVES)
        ring_names = tuple(n for n, _ in RING_LEAVES)
        rows = dict(zip(row_names, self._group(
            row_names, self._reship_rows, self._dirty_rows)))
        rings = dict(zip(ring_names, self._group(
            ring_names, self._reship_rings, self._dirty_rings)))
        (hmap_leaf,) = self._group(
            ("hmap_idx",), self._reship_hmap, self._dirty_hslots)
        prev = self.last_tables
        if self._reship_scalars or prev is None:
            scalars = {n: jnp.asarray(self._cols[n]) for n in SCALAR_LEAVES}
            self.stats.ship(
                len(SCALAR_LEAVES),
                sum(self._cols[n].nbytes for n in SCALAR_LEAVES),
            )
        else:
            scalars = {n: getattr(prev, n) for n in SCALAR_LEAVES}
        cap = len(self._cols["map_valid"])
        tables = NatTables(
            **rows, **rings, hmap_idx=hmap_leaf, **scalars,
            num_mappings=len(self._map_of),
            bucket_size=self._K,
            use_hmap=_pick_use_hmap(cap, None) if self._hmap_ok else False,
            has_affinity=self._n_affinity > 0,
        )
        self.last_tables = tables
        self.fingerprint = fold_fingerprint(
            (self._sums[n], self._cols[n].shape) for n in NAT_LEAF_ORDER
        )
        self._dirty_rows = set()
        self._dirty_rings = set()
        self._dirty_hslots = set()
        self._reship_rows = self._reship_rings = False
        self._reship_hmap = self._reship_scalars = False
        return tables

    # ----------------------------------------------------------- full build

    def _full(self, services: Dict[object, tuple], glob: tuple,
              row_cap_min: Optional[int] = None) -> NatTables:
        """Canonical rebuild via build_nat_host (mappings flattened in
        sorted-service order — bit-identical to build_nat_tables), then
        re-derive the incremental registries from the result."""
        self.stats.full_builds += 1
        nat_loopback, snat_ip, snat_enabled, pod_subnet = glob
        flat: List[NatMapping] = []
        for key in _sorted_keys(services):
            flat.extend(services[key])
        host = build_nat_host(
            flat, nat_loopback=nat_loopback, snat_ip=snat_ip,
            snat_enabled=snat_enabled, pod_subnet=pod_subnet,
            bucket_size=self.bucket_base,
        )
        self._cols = {n: host[n] for n in NAT_LEAF_ORDER}
        self._K = host["bucket_size"]
        self._hmap_ok = host["hmap_ok"]
        cap = len(self._cols["map_valid"])
        if row_cap_min and row_cap_min > cap:
            # Shrink compactions keep 2x headroom over the canonical cap
            # so boundary churn cannot thrash XLA shape buckets.
            self._grow_rows(row_cap_min)
            cap = row_cap_min
            self.stats.grows -= 1  # not a churn grow, just the hint
        self._services = dict(services)
        self._glob = glob
        self._row_of = {}
        self._map_of = {}
        self._hmap_slot = {}
        for i, m in enumerate(flat):
            ek = _ext_key(m)
            if ek not in self._row_of:  # first claim wins (dense argmax)
                self._row_of[ek] = i
            self._map_of[i] = m
        hmap = self._cols["hmap_idx"]
        for slot in np.nonzero(hmap >= 0)[0]:
            row = int(hmap[slot])
            self._hmap_slot[_ext_key(self._map_of[row])] = int(slot)
        # Incremental aggregates (K maxima, valid/affinity counts) —
        # re-derived here, maintained O(changed) by the delta mutators.
        self._weights = {
            row: (self._need(m), len(m.backends))
            for row, m in self._map_of.items()
        }
        self._max_dirty = True
        self._current_maxes()
        self._n_valid = sum(1 for m in self._map_of.values() if m.backends)
        self._n_affinity = sum(
            1 for m in self._map_of.values()
            if m.session_affinity_timeout > 0
        )
        self._free_rows = list(range(cap - 1, len(flat) - 1, -1))
        self._row_high = cap  # everything beyond flat is on the free list
        self._sums = {n: u32_wrap_sum(self._cols[n]) for n in NAT_LEAF_ORDER}
        self._dirty_rows = set()
        self._dirty_rings = set()
        self._dirty_hslots = set()
        self._reship_rows = self._reship_rings = True
        self._reship_hmap = self._reship_scalars = True
        self.last_tables = None
        return self._ship()

    # -------------------------------------------------------------- queries

    @property
    def num_mappings(self) -> int:
        return len(getattr(self, "_map_of", {}))


# --------------------------------------------------------------------------
# Canonicalization (equivalence testing)
# --------------------------------------------------------------------------


def canonical_nat_tables(t: NatTables) -> NatTables:
    """Map ANY NatTables layout (delta row permutation / recycled rows /
    hysteresis padding / incremental hmap layout) to a canonical form:
    live rows sorted by full content, pow2 padding recomputed, the
    exact-match index rebuilt canonically from the sorted rows.  Two
    tables are semantically identical iff their canonical forms are
    array-identical (the backend pick depends only on row CONTENT and
    the shared ring width K, which canonicalization preserves)."""
    cols = {n: np.asarray(getattr(t, n)) for n in NAT_LEAF_ORDER}
    cap = len(cols["map_valid"])
    live = cols["map_valid"].copy()
    for n in ("map_ext_ip", "map_ext_port", "map_proto", "map_twice_nat",
              "map_affinity", "map_aff_timeout"):
        live |= cols[n] != 0
    live |= cols["backend_ip"].any(axis=1)
    live |= cols["backend_port"].any(axis=1)
    rows = sorted(
        (
            tuple(int(cols[n][i]) for n, _ in ROW_LEAVES[:5])
            + (bool(cols["map_valid"][i]), int(cols["map_aff_timeout"][i]))
            + tuple(cols["backend_ip"][i].tolist())
            + tuple(cols["backend_port"][i].tolist())
        )
        for i in range(cap) if live[i]
    )
    m = len(rows)
    k = cols["backend_ip"].shape[1]
    padded = _next_pow2(max(m, 1))
    out = {name: np.zeros(padded, dtype=dt) for name, dt in ROW_LEAVES}
    b_ip = np.zeros((padded, k), dtype=np.uint32)
    b_port = np.zeros((padded, k), dtype=np.int32)
    for i, row in enumerate(rows):
        for j, (name, _) in enumerate(ROW_LEAVES[:5]):
            out[name][i] = row[j]
        out["map_valid"][i] = row[5]
        out["map_aff_timeout"][i] = row[6]
        b_ip[i] = row[7:7 + k]
        b_port[i] = row[7 + k:7 + 2 * k]
    n_valid = int(out["map_valid"].sum())
    hmap = _build_map_hash(
        [
            (i, (int(out["map_ext_ip"][i]), int(out["map_ext_port"][i]),
                 int(out["map_proto"][i])))
            for i in range(m) if out["map_valid"][i]
        ],
        start_capacity=_next_pow2(max(2 * n_valid, 8), minimum=16),
    )
    hmap_ok = hmap is not None
    if hmap is None:
        hmap = np.full(16, -1, dtype=np.int32)
    return NatTables(
        map_ext_ip=jnp.asarray(out["map_ext_ip"]),
        map_ext_port=jnp.asarray(out["map_ext_port"]),
        map_proto=jnp.asarray(out["map_proto"]),
        map_twice_nat=jnp.asarray(out["map_twice_nat"]),
        map_affinity=jnp.asarray(out["map_affinity"]),
        map_valid=jnp.asarray(out["map_valid"]),
        backend_ip=jnp.asarray(b_ip),
        backend_port=jnp.asarray(b_port),
        hmap_idx=jnp.asarray(hmap),
        nat_loopback=jnp.asarray(cols["nat_loopback"]),
        snat_ip=jnp.asarray(cols["snat_ip"]),
        snat_enabled=jnp.asarray(cols["snat_enabled"]),
        pod_subnet_base=jnp.asarray(cols["pod_subnet_base"]),
        pod_subnet_mask=jnp.asarray(cols["pod_subnet_mask"]),
        map_aff_timeout=jnp.asarray(out["map_aff_timeout"]),
        num_mappings=m,
        bucket_size=k,
        use_hmap=_pick_use_hmap(padded, None) if hmap_ok else False,
        has_affinity=bool(out["map_aff_timeout"].any()),
    )
