"""Host slow path — exact sessions for flows the device table punts.

The TPU session table (:mod:`vpp_tpu.ops.nat`) never evicts a live
flow: a full probe bucket, an ambiguous reply key (SNAT port
collision), or a lost intra-batch scatter race raises ``punt`` for
that packet and the flow is handled here, in exact host-side Python —
the analog of VPP's NAT slow path (nat44 in2out/out2in slowpath nodes
handle session-table misses in C before fast-path entries exist).

Responsibilities:

- **record** punted forward flows so their replies can be restored
  (the device has no session for them);
- **re-allocate SNAT ports** for collided flows from a host-side
  reservation set, returning fix-ups the datapath runner applies to
  the outgoing frames;
- **restore replies** that miss the device table but match a
  host-recorded session;
- expose punt/restore/occupancy counters for /metrics.

The slow path only touches punted flows (rare by construction), so the
dict-based implementation is never on the fast path; the runner skips
the restore scan entirely while no host sessions exist.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

ReplyKey = Tuple[int, int, int, int, int]  # src_ip, dst_ip, proto, sport, dport
Restore = Tuple[int, int, int, int]        # orig src_ip, src_port, dst_ip, dst_port

# Multiplicative key hash used by the vectorized batch pre-filter: the
# same arithmetic runs per-row (numpy uint64, wrapping) and per-key
# (scalar), so a dict-resident key always matches its row hash.  False
# positives only cost an exact dict probe.
_H = tuple(np.uint64(p) for p in (
    0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9,
    0x27D4EB2F165667C5, 0x85EBCA77C2B2AE63,
))


def _hash_rows(src_ip, dst_ip, proto, sport, dport) -> np.ndarray:
    """Vectorized ReplyKey hash over column arrays (uint64)."""
    with np.errstate(over="ignore"):
        return (
            src_ip.astype(np.uint64) * _H[0]
            ^ dst_ip.astype(np.uint64) * _H[1]
            ^ proto.astype(np.uint64) * _H[2]
            ^ sport.astype(np.uint64) * _H[3]
            ^ dport.astype(np.uint64) * _H[4]
        )


def _hash_key(key: ReplyKey) -> int:
    """Scalar twin of :func:`_hash_rows` for one (s,d,p,sp,dp) key."""
    with np.errstate(over="ignore"):
        return int(
            np.uint64(key[0]) * _H[0]
            ^ np.uint64(key[1]) * _H[1]
            ^ np.uint64(key[2]) * _H[2]
            ^ np.uint64(key[3]) * _H[3]
            ^ np.uint64(key[4]) * _H[4]
        )


@dataclass
class SlowSession:
    restore: Restore
    last_seen: int
    # For SNAT-collision flows: the host-reserved source port that
    # replaces the hash-allocated one on every forward packet.
    snat_port_override: Optional[int] = None
    # Forward-direction key (pre-NAT) for flows needing port fix-ups.
    fwd_key: Optional[ReplyKey] = None


@dataclass
class SlowPathCounters:
    punts: int = 0
    snat_reallocs: int = 0
    restores: int = 0
    expired: int = 0
    drops: int = 0

    def as_dict(self) -> Dict[str, int]:
        return {
            "slowpath_punts_total": self.punts,
            "slowpath_snat_reallocs_total": self.snat_reallocs,
            "slowpath_restores_total": self.restores,
            "slowpath_expired_total": self.expired,
            "slowpath_drops_total": self.drops,
        }


class PuntOutcome(NamedTuple):
    """What the runner must do with this batch's punted rows."""

    # (row, new_src_port): patch the frame's source port before TX.
    fixups: List[Tuple[int, int]]
    # Rows that must NOT be transmitted: sending them would misroute
    # (their hash port aliases another flow and no substitute session
    # could be recorded).
    drops: List[int]


class _HashIndex:
    """Refcounted hash-membership index with a cached numpy array.

    The per-batch pre-filter does ONE vectorized ``np.isin`` against
    this array; only rows whose hash is present reach the per-row
    Python dict probes.  Refcounting keeps rare 64-bit hash collisions
    correct (a removal cannot hide a distinct surviving key)."""

    def __init__(self):
        self._counts: Dict[int, int] = {}
        self._arr: Optional[np.ndarray] = None

    def add(self, h: int) -> None:
        self._counts[h] = self._counts.get(h, 0) + 1
        self._arr = None

    def remove(self, h: int) -> None:
        c = self._counts.get(h)
        if c is None:
            return
        if c <= 1:
            del self._counts[h]
        else:
            self._counts[h] = c - 1
        self._arr = None

    def arr(self) -> np.ndarray:
        if self._arr is None:
            self._arr = np.fromiter(
                self._counts.keys(), dtype=np.uint64, count=len(self._counts)
            )
        return self._arr


def resolve_stragglers(
    orig: Dict[str, np.ndarray],
    rewritten: Dict[str, np.ndarray],
    straggler: np.ndarray,
    fwd_mask: np.ndarray,
) -> List[Tuple[int, Restore]]:
    """Same-batch reply join for the ``flat-punt`` dispatch discipline.

    A *straggler* is a reply whose forward packet sits in the SAME
    dispatch: the device probe detected it (it matched a slot this
    batch wrote) and punted it here instead of paying the dependent
    device restore rounds.  Its forward flow's session lives on the
    DEVICE table, so the recorded host sessions cannot restore it — but
    the forward packet itself is in this very batch, already
    materialised, so the join is pure host arithmetic: a forward row's
    expected reply tuple is the src/dst (and port) swap of its
    REWRITTEN headers, and the restore is the swap of its ORIGINAL
    headers — exactly the value row the device session stores.

    ``fwd_mask`` must select the rows whose device session survived
    the dispatch ((dnat|snat) ∧ allowed ∧ ¬punt ∧ ¬reply ∧ ¬straggler);
    the unique-reply-key table invariant makes the join unambiguous.
    Rows that miss (their match was another straggler's undone bogus
    write — crafted aliasing, never organic traffic) are left to the
    ordinary punt path, the same ownership handoff flat-safe makes for
    them.  Returns ``[(row, restore)]`` in :meth:`restore_replies`'
    shape: restore = (src_ip, src_port, dst_ip, dst_port) of the
    restored header."""
    rows = np.nonzero(straggler)[0]
    if not len(rows):
        return []
    fwd_rows = np.nonzero(fwd_mask)[0]
    if not len(fwd_rows):
        return []
    # Stragglers are rare by construction (the forward must land in the
    # same coalesce window); the dict is built per batch only when one
    # was detected.
    by_reply: Dict[ReplyKey, Restore] = {}
    for j in fwd_rows.tolist():
        key: ReplyKey = (
            int(rewritten["dst_ip"][j]), int(rewritten["src_ip"][j]),
            int(orig["protocol"][j]),
            int(rewritten["dst_port"][j]), int(rewritten["src_port"][j]),
        )
        by_reply[key] = (
            int(orig["src_ip"][j]), int(orig["src_port"][j]),
            int(orig["dst_ip"][j]), int(orig["dst_port"][j]),
        )
    out: List[Tuple[int, Restore]] = []
    for i in rows.tolist():
        key = (int(orig["src_ip"][i]), int(orig["dst_ip"][i]),
               int(orig["protocol"][i]),
               int(orig["src_port"][i]), int(orig["dst_port"][i]))
        fwd = by_reply.get(key)
        if fwd is None:
            continue
        o_src_ip, o_src_port, o_dst_ip, o_dst_port = fwd
        # Restore: src <- original dst, dst <- original src (the same
        # mapping nat_reply_restore / restore_replies produce).
        out.append((i, (o_dst_ip, o_dst_port, o_src_ip, o_src_port)))
    return out


class HostSlowPath:
    """Exact host-side session table for punted flows."""

    def __init__(self, max_sessions: int = 65536):
        self.max_sessions = max_sessions
        self.sessions: Dict[ReplyKey, SlowSession] = {}
        # Forward-key -> reply-key index for flows with port overrides.
        self._by_fwd: Dict[ReplyKey, ReplyKey] = {}
        # Reserved (remote_ip, remote_port, proto, snat_ip, port) tuples.
        self._reserved_ports: Dict[Tuple[int, int, int, int], int] = {}
        # Vectorized pre-filters over the dict keys (the fast-path cost
        # of the slow path must stay O(batch) numpy, not O(batch) dict
        # probes — at 16k-packet dispatches the per-row loop was the
        # single largest frame-path cost).
        self._reply_idx = _HashIndex()
        self._fwd_idx = _HashIndex()
        self.counters = SlowPathCounters()

    @staticmethod
    def _batch_hashes(headers: Dict[str, np.ndarray], idx: np.ndarray) -> np.ndarray:
        return _hash_rows(
            headers["src_ip"][idx], headers["dst_ip"][idx],
            headers["protocol"][idx], headers["src_port"][idx],
            headers["dst_port"][idx],
        )

    def __len__(self) -> int:
        return len(self.sessions)

    # ------------------------------------------------------------ recording

    def record_punts(
        self,
        orig: Dict[str, np.ndarray],
        rewritten: Dict[str, np.ndarray],
        punt: np.ndarray,
        snat_hit: np.ndarray,
        timestamp: int,
    ) -> PuntOutcome:
        """Record sessions for punted rows of one batch.

        ``orig`` / ``rewritten`` are SoA header dicts with keys
        src_ip/dst_ip/protocol/src_port/dst_port (host numpy arrays).
        Returns the fix-ups (SNAT port rewrites) and drops the runner
        must apply before transmitting.
        """
        fixups: List[Tuple[int, int]] = []
        drops: List[int] = []
        rows = np.nonzero(punt)[0]
        for i in rows.tolist():
            self.counters.punts += 1
            o = (int(orig["src_ip"][i]), int(orig["src_port"][i]),
                 int(orig["dst_ip"][i]), int(orig["dst_port"][i]))
            proto = int(orig["protocol"][i])
            r_src = int(rewritten["dst_ip"][i])
            r_sport = int(rewritten["dst_port"][i])
            r_dst = int(rewritten["src_ip"][i])
            r_dport = int(rewritten["src_port"][i])
            is_snat = bool(snat_hit[i])

            fwd_key: ReplyKey = (o[0], o[2], proto, o[1], o[3])
            existing_rk = self._by_fwd.get(fwd_key)
            if existing_rk is not None:
                sess = self.sessions.get(existing_rk)
                if sess is not None:
                    sess.last_seen = timestamp
                    if sess.snat_port_override is not None:
                        fixups.append((i, sess.snat_port_override))
                    continue

            if len(self.sessions) >= self.max_sessions:
                # No session can be recorded.  A DNAT punt is still
                # safe to forward (translation was deterministic; only
                # its replies lose the fast restore), but a SNAT punt
                # would transmit a port that aliases another flow.
                if is_snat:
                    drops.append(i)
                    self.counters.drops += 1
                continue

            override: Optional[int] = None
            if is_snat:
                # A SNAT punt can mean the hash port collided with a
                # flow whose session lives on-device (ambiguous reply
                # key) — the host cannot see that table, so always move
                # off the hash-chosen port and onto a host-reserved one.
                endpoint = (r_src, r_sport, proto, r_dst)
                port = self._alloc_port(endpoint, r_dport)
                if port is None:
                    # Port space for this endpoint truly exhausted:
                    # transmitting would misroute — drop instead.
                    drops.append(i)
                    self.counters.drops += 1
                    continue
                override = port
                r_dport = port
                fixups.append((i, port))
                self.counters.snat_reallocs += 1

            reply_key: ReplyKey = (r_src, r_dst, proto, r_sport, r_dport)
            if reply_key not in self.sessions:
                self._reply_idx.add(_hash_key(reply_key))
            self.sessions[reply_key] = SlowSession(
                restore=o, last_seen=timestamp,
                snat_port_override=override, fwd_key=fwd_key,
            )
            if fwd_key not in self._by_fwd:
                self._fwd_idx.add(_hash_key(fwd_key))
            self._by_fwd[fwd_key] = reply_key
        return PuntOutcome(fixups=fixups, drops=drops)

    def _alloc_port(
        self, endpoint: Tuple[int, int, int, int], wanted: int
    ) -> Optional[int]:
        """First free ephemeral port for (remote, proto, snat_ip),
        probing from just past the hash-chosen (collided) one.

        Residual risk: the new port could collide with a different
        device-resident session's reply key the host cannot see; the
        device insert for such a flow punts again and re-enters here,
        converging on a free port.
        """
        for k in range(1, 32768):
            port = 32768 + ((wanted - 32768 + k) % 32768)
            key = endpoint + (port,)
            if key not in self._reserved_ports:
                self._reserved_ports[key] = port
                return port
        return None

    # ---------------------------------------------------------- restoration

    def fixup_forward(
        self, headers: Dict[str, np.ndarray], mask: np.ndarray
    ) -> List[Tuple[int, int]]:
        """Port fix-ups for forward packets of flows with overrides.

        Called per batch only while overrides exist; ``mask`` limits the
        scan to rows the device SNATted (candidates for an override).
        """
        fixups: List[Tuple[int, int]] = []
        idx = np.nonzero(mask)[0]
        if not len(idx) or not self._by_fwd:
            return fixups
        # Vectorized membership pre-filter: only rows whose key hash is
        # in the forward index pay a Python dict probe.
        idx = idx[np.isin(self._batch_hashes(headers, idx), self._fwd_idx.arr())]
        for i in idx.tolist():
            fwd_key = (int(headers["src_ip"][i]), int(headers["dst_ip"][i]),
                       int(headers["protocol"][i]),
                       int(headers["src_port"][i]), int(headers["dst_port"][i]))
            rk = self._by_fwd.get(fwd_key)
            if rk is None:
                continue
            sess = self.sessions.get(rk)
            if sess is not None and sess.snat_port_override is not None:
                fixups.append((i, sess.snat_port_override))
        return fixups

    def restore_replies(
        self,
        headers: Dict[str, np.ndarray],
        candidates: np.ndarray,
        timestamp: int,
    ) -> List[Tuple[int, Restore]]:
        """Match candidate rows (device misses) against host sessions.

        Returns ``[(row, (src_ip, src_port, dst_ip, dst_port))]`` where
        the returned tuple is the RESTORED header: src becomes the
        original destination (VIP/SNAT addr), dst the original source.
        """
        if not self.sessions:
            return []
        out: List[Tuple[int, Restore]] = []
        idx = np.nonzero(candidates)[0]
        if not len(idx):
            return out
        idx = idx[np.isin(self._batch_hashes(headers, idx), self._reply_idx.arr())]
        for i in idx.tolist():
            key = (int(headers["src_ip"][i]), int(headers["dst_ip"][i]),
                   int(headers["protocol"][i]),
                   int(headers["src_port"][i]), int(headers["dst_port"][i]))
            sess = self.sessions.get(key)
            if sess is None:
                continue
            sess.last_seen = timestamp
            o_src_ip, o_src_port, o_dst_ip, o_dst_port = sess.restore
            # Restore: src <- original dst, dst <- original src.
            out.append((i, (o_dst_ip, o_dst_port, o_src_ip, o_src_port)))
            self.counters.restores += 1
        return out

    # ----------------------------------------------------------------- GC

    def sweep(self, now: int, max_age: int) -> int:
        """Expire idle sessions (mirror of ops.nat.sweep_sessions)."""
        stale = [k for k, s in self.sessions.items() if now - s.last_seen > max_age]
        for k in stale:
            sess = self.sessions.pop(k)
            self._reply_idx.remove(_hash_key(k))
            if sess.fwd_key is not None:
                if self._by_fwd.pop(sess.fwd_key, None) is not None:
                    self._fwd_idx.remove(_hash_key(sess.fwd_key))
            if sess.snat_port_override is not None:
                endpoint = (k[0], k[3], k[2], k[1], sess.snat_port_override)
                self._reserved_ports.pop(endpoint, None)
        self.counters.expired += len(stale)
        return len(stale)
