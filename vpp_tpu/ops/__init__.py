"""TPU data-plane kernels.

The per-packet hot path of the framework: where the reference runs VPP
graph nodes in C over 256-packet vectors (SURVEY.md §3.5), this package
runs jit-compiled JAX ops over packet-header batches on TPU:

- ``packets``   packet-header batch representation (struct of arrays)
- ``classify``  ACL rule-table compilation + first-match classify
- ``nat``       NAT44 DNAT/SNAT map compilation + rewrite
- ``infer``     in-network inference: fused MLP/feature-hash scorer +
                the InferTable weights/enrollment device table
- ``pipeline``  the combined ingress-ACL -> DNAT -> routing-tag ->
                SNAT -> egress-ACL (-> score) step (SERVICES.md:300-307
                ordering; the scoring stage is ISSUE 14)

Everything is static-shape: rule tables and NAT maps are padded to
power-of-two buckets so XLA compiles one program per bucket size, and
table *content* updates are pure device-array swaps with no recompile
(the kvscheduler update-vs-resync split mapped onto XLA's compilation
model).
"""

from .packets import PacketBatch, ip_to_u32, u32_to_ip, make_batch, random_batch
from .classify import RuleTables, build_rule_tables, classify, Verdicts

__all__ = [
    "PacketBatch",
    "ip_to_u32",
    "u32_to_ip",
    "make_batch",
    "random_batch",
    "RuleTables",
    "build_rule_tables",
    "classify",
    "Verdicts",
]
