"""Delta device apply — ship only changed table rows to the device.

The control→data plane path used to re-upload WHOLE table tensors on
every transaction (a 64k×9 rule tensor for a one-pod change).  The
incremental builders (:mod:`classify_delta`, :mod:`nat_delta`) patch
host-side numpy mirrors in place and call :func:`apply_rows` to scatter
only the dirty rows into the previous device arrays:

- the scatter is ONE jitted program per (column-group signature, index
  bucket) — indices are padded to a power-of-two bucket with an
  out-of-range sentinel (``mode="drop"``), so churny transactions reuse
  a handful of compiled programs instead of recompiling per delta size;
- the scatter COPIES on device (functional ``.at[].set``): the previous
  arrays stay valid, so in-flight dispatched batches keep the tables
  they saw and the runner's swap semantics are untouched — only the
  host→device traffic shrinks to O(changed rows);
- nothing here donates buffers, deliberately: donation would invalidate
  the tables an in-flight batch still references.

Also home to the host-side fingerprint arithmetic: the device
fingerprint (scheduler/tpu_applicators.table_fingerprint) folds per-leaf
uint32 wrap-sums, which are ADDITIVE — a builder patching row ``i`` from
``old`` to ``new`` maintains each leaf's sum with
``sum += u32(new) - u32(old)``, keeping the expected-side fingerprint a
pure host computation (O(1) per verify, no device reduction).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Iterable, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

# ONE pow2 bucketing policy for tables and scatter-index buckets alike.
from .classify import _next_pow2 as next_pow2

# Fingerprint fold constants (FNV-1a 32-bit), shared by the device
# reduction and the host mirror — the two must stay in lockstep.
FP_SEED = 0x811C9DC5
FP_PRIME = 0x01000193
_U32 = 0xFFFFFFFF

# Smallest scatter-index bucket: deltas of 1..16 rows share one program.
IDX_BUCKET_MIN = 16


# --------------------------------------------------------------------------
# Jitted row scatter
# --------------------------------------------------------------------------


@jax.jit
def _scatter(arrs: Tuple[jnp.ndarray, ...], idx: jnp.ndarray,
             rows: Tuple[jnp.ndarray, ...]) -> Tuple[jnp.ndarray, ...]:
    # Out-of-range padding indices drop; duplicate indices cannot occur
    # (callers pass a de-duplicated sorted dirty set).
    return tuple(a.at[idx].set(r, mode="drop") for a, r in zip(arrs, rows))


def apply_rows(
    arrs: Sequence[jnp.ndarray],
    idx: np.ndarray,
    rows: Sequence[np.ndarray],
) -> Tuple[jnp.ndarray, ...]:
    """Scatter changed rows into a group of same-length device arrays.

    ``arrs`` share their leading dimension; ``rows[j][k]`` is the new
    content of ``arrs[j][idx[k]]``.  Returns NEW device arrays (the old
    buffers are untouched — in-flight consumers keep theirs).  The
    index vector is padded to a pow2 bucket so XLA compiles one scatter
    program per bucket, not per delta size.
    """
    cap = int(arrs[0].shape[0])
    n = len(idx)
    bucket = next_pow2(max(n, 1), IDX_BUCKET_MIN)
    idx_p = np.full(bucket, cap, dtype=np.int32)  # sentinel: dropped
    idx_p[:n] = idx
    rows_p = []
    for r in rows:
        pad = np.zeros((bucket,) + r.shape[1:], dtype=r.dtype)
        pad[:n] = r
        rows_p.append(jnp.asarray(pad))
    return _scatter(tuple(arrs), jnp.asarray(idx_p), tuple(rows_p))


# --------------------------------------------------------------------------
# Host-side fingerprint arithmetic
# --------------------------------------------------------------------------


def u32_wrap_sum(arr) -> int:
    """uint32 wrap-sum of an array, matching the device fingerprint's
    per-leaf conversion rules exactly (bool→u32, f32 bit-view, anything
    else astype-u32 with two's-complement wraparound)."""
    a = np.asarray(arr)
    if a.dtype == np.bool_:
        a = a.astype(np.uint32)
    elif a.dtype.kind == "f":
        a = a.view(np.uint32) if a.dtype.itemsize == 4 else a.astype(np.uint32)
    else:
        a = a.astype(np.uint32)
    return int(a.sum(dtype=np.uint64)) & _U32


def fold_fingerprint(parts: Iterable[Tuple[int, object]]) -> int:
    """Fold per-leaf (u32 wrap-sum, shape) pairs — IN PYTREE LEAF ORDER
    — into the table fingerprint.  Must mirror the device reduction in
    tpu_applicators.table_fingerprint (property-tested)."""
    fp = FP_SEED
    for s, shape in parts:
        fp = (((fp * FP_PRIME) & _U32) ^ (s & _U32) ^ (hash(shape) & _U32)) & _U32
    return fp


# --------------------------------------------------------------------------
# Build/ship observability
# --------------------------------------------------------------------------


@dataclasses.dataclass
class DeltaStats:
    """Compile/ship counters of one incremental table builder — the
    observability the churn bench and `netctl inspect` read."""

    full_builds: int = 0
    delta_builds: int = 0
    rows_shipped: int = 0        # cumulative table rows sent host→device
    bytes_shipped: int = 0       # cumulative payload bytes (rows + indices)
    last_rows_shipped: int = 0   # rows of the most recent build
    last_bytes_shipped: int = 0
    grows: int = 0               # pow2 bucket growths (full-group reships)
    shrinks: int = 0             # hysteresis shrink compactions
    build_seconds: float = 0.0   # cumulative host build wall time
    last_build_seconds: float = 0.0

    def ship(self, rows: int, nbytes: int) -> None:
        self.rows_shipped += rows
        self.bytes_shipped += nbytes
        self.last_rows_shipped += rows
        self.last_bytes_shipped += nbytes

    def begin_build(self) -> None:
        self.last_rows_shipped = 0
        self.last_bytes_shipped = 0

    def as_dict(self) -> Dict[str, float]:
        return dataclasses.asdict(self)


def group_nbytes(idx: np.ndarray, rows: Sequence[np.ndarray]) -> int:
    """Payload bytes of one delta group ship: row data + index vector."""
    return int(sum(r.nbytes for r in rows)) + int(idx.nbytes)
