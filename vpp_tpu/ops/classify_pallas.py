"""Pallas-tiled first-match classify for large rule tables.

The dense XLA path materialises a [B, N] predicate matrix; at N = 64k
rules and a 16k-packet dispatch that is a gigabyte-scale intermediate
streamed through HBM.  This kernel tiles the evaluation over
[TILE_B, TILE_N] blocks held in VMEM and reduces each packet's
first-match rule index ACROSS rule tiles with a running minimum, so the
full matrix never exists (SURVEY §7.3: "10k rules x 256 pkts is a
2.5M-lane predicate eval — needs Pallas tiling").

Semantics are identical to classify._first_match_action: lowest-index
matching rule within the packet's side table wins; the caller maps the
index to an action (no match -> DENY, NO_TABLE side -> PERMIT).

All uint32 inputs are bitcast to int32 before entering the kernel:
masking and equality are bit-pattern operations, and int32 keeps the
kernel inside the best-supported TPU vector types.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_B = 256   # packets per block (the VPP vector size)
TILE_N = 2048  # rules per block

# "No match" sentinel: larger than any rule index (plain int so the
# kernel sees a compile-time constant, not a captured traced value).
_NO_MATCH = 2**31 - 1


def _first_match_kernel(
    side_tid_ref, src_ip_ref, dst_ip_ref, proto_ref, sport_ref, dport_ref,
    rule_valid_ref, rule_tid_ref,
    rule_src_base_ref, rule_src_mask_ref, rule_dst_base_ref, rule_dst_mask_ref,
    rule_proto_ref, rule_src_port_ref, rule_dst_port_ref,
    best_ref,
):
    # Blocks arrive as [1, TILE] rows of the 2-D-reshaped arrays (TPU
    # layouts want >=2-D, 128-aligned last dims).
    j = pl.program_id(1)

    src_ip = src_ip_ref[0, :]     # [TILE_B] int32 (bitcast uint32)
    dst_ip = dst_ip_ref[0, :]
    proto = proto_ref[0, :]
    sport = sport_ref[0, :]
    dport = dport_ref[0, :]
    side_tid = side_tid_ref[0, :]

    rsm = rule_src_mask_ref[0, :]  # [TILE_N]
    rsb = rule_src_base_ref[0, :]
    rdm = rule_dst_mask_ref[0, :]
    rdb = rule_dst_base_ref[0, :]
    rproto = rule_proto_ref[0, :]
    rsp = rule_src_port_ref[0, :]
    rdp = rule_dst_port_ref[0, :]
    rtid = rule_tid_ref[0, :]
    rvalid = rule_valid_ref[0, :]

    # [TILE_B, TILE_N] block predicate, all in VMEM.
    src_ok = (src_ip[:, None] & rsm[None, :]) == rsb[None, :]
    dst_ok = (dst_ip[:, None] & rdm[None, :]) == rdb[None, :]
    proto_any = rproto[None, :] == 0
    proto_ok = proto[:, None] == rproto[None, :]
    sport_ok = (rsp[None, :] == 0) | (sport[:, None] == rsp[None, :])
    dport_ok = (rdp[None, :] == 0) | (dport[:, None] == rdp[None, :])
    l4_ok = proto_any | (proto_ok & sport_ok & dport_ok)
    in_table = (
        (rvalid[None, :] != 0)
        & src_ok & dst_ok & l4_ok
        & (rtid[None, :] == side_tid[:, None])
    )

    col = jax.lax.broadcasted_iota(jnp.int32, in_table.shape, dimension=1)
    local = jnp.min(jnp.where(in_table, col, _NO_MATCH), axis=1)
    cand = jnp.where(local == _NO_MATCH, _NO_MATCH, j * TILE_N + local)

    @pl.when(j == 0)
    def _init():
        best_ref[0, :] = cand

    @pl.when(j > 0)
    def _accum():
        best_ref[0, :] = jnp.minimum(best_ref[0, :], cand)


def _bitcast_i32(a: jnp.ndarray) -> jnp.ndarray:
    if a.dtype == jnp.uint32:
        return jax.lax.bitcast_convert_type(a, jnp.int32)
    return a.astype(jnp.int32)


def first_match_index_pallas(tables, batch, side_tid, *, interpret: bool = False):
    """[B] first-match rule index (``_NO_MATCH`` when none) for each
    packet against its side table.  Requires B % TILE_B == 0 and
    N % TILE_N == 0 (the pow2 bucketing guarantees the latter once the
    table crosses the pallas threshold)."""
    b = batch.src_ip.shape[0]
    n = tables.rule_valid.shape[0]
    assert b % TILE_B == 0 and n % TILE_N == 0, (b, n)

    def brows(a):  # [B] -> [1, B]; blocks slice the last dim
        return _bitcast_i32(a).reshape(1, b)

    def rrows(a):  # [N] -> [1, N]
        return _bitcast_i32(a).reshape(1, n)

    batch_spec = pl.BlockSpec((1, TILE_B), lambda i, j: (0, i))
    rule_spec = pl.BlockSpec((1, TILE_N), lambda i, j: (0, j))

    best = pl.pallas_call(
        _first_match_kernel,
        grid=(b // TILE_B, n // TILE_N),
        in_specs=[batch_spec] * 6 + [rule_spec] * 9,
        out_specs=pl.BlockSpec((1, TILE_B), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.int32),
        interpret=interpret,
    )(
        brows(side_tid),
        brows(batch.src_ip),
        brows(batch.dst_ip),
        brows(batch.protocol),
        brows(batch.src_port),
        brows(batch.dst_port),
        rrows(tables.rule_valid),
        rrows(tables.rule_tid),
        rrows(tables.rule_src_base),
        rrows(tables.rule_src_mask),
        rrows(tables.rule_dst_base),
        rrows(tables.rule_dst_mask),
        rrows(tables.rule_proto),
        rrows(tables.rule_src_port),
        rrows(tables.rule_dst_port),
    )
    return best.reshape(b)
