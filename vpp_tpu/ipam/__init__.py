from .ipam import IPAM, IPAMError

__all__ = ["IPAM", "IPAMError"]
