"""IPAM — node-ID-based address-space arithmetic.

Analog of the reference's ``plugins/ipam``: every node derives all of
its subnets *purely arithmetically* from its cluster-unique integer
node ID, so no cross-node coordination is ever needed for addressing
(docs/NETWORKING.md:25-72):

- ``dissect_subnet_for_node`` (ipam.go :584): carve the node's chunk
  out of a cluster-wide subnet by shifting the node ID into the host
  bits.
- ``compute_node_ip`` (ipam.go :618): node interconnect IP =
  subnet base + node ID (skipping excluded IPs, rejecting part 0).
- pod IP allocation (ipam.go AllocatePodIP :453): round-robin from the
  last assigned index; seq 0 (network), seq 1 (gateway) and the last
  two addresses (NAT loopback = last unicast, broadcast) are reserved.
- resync (ipam.go :220-276): the in-memory pool is re-learned from the
  KubeState pod list — pod IPs are never persisted.
"""

from __future__ import annotations

import ipaddress
import logging
import threading
from typing import Dict, Optional

from ..conf import IPAMConfig
from ..models import Pod, PodID

# Sequence IDs reserved inside each per-node subnet (reference ipam.go:36-45).
log = logging.getLogger(__name__)

POD_GATEWAY_SEQ_ID = 1
HOST_INTERCONNECT_DATAPLANE_SEQ_ID = 1
HOST_INTERCONNECT_HOST_SEQ_ID = 2


class IPAMError(Exception):
    pass


def dissect_subnet_for_node(
    subnet: ipaddress.IPv4Network, one_node_prefix_len: int, node_id: int
) -> ipaddress.IPv4Network:
    """Carve the per-node chunk of ``subnet`` for ``node_id``.

    Mirrors ipam.go dissectSubnetForNode :584: the node ID is placed in
    the bits between the cluster prefix and the node prefix; ID equal to
    2^bits wraps to part 0 (valid for a subnet, not for an IP).
    """
    if one_node_prefix_len <= subnet.prefixlen:
        raise IPAMError(
            f"per-node prefix /{one_node_prefix_len} must be longer than "
            f"the cluster subnet prefix /{subnet.prefixlen}"
        )
    node_bits = one_node_prefix_len - subnet.prefixlen
    node_part = _node_ip_part(node_id, node_bits)
    base = int(subnet.network_address)
    node_subnet_base = base + (node_part << (32 - one_node_prefix_len))
    return ipaddress.ip_network((node_subnet_base, one_node_prefix_len))


def _node_ip_part(node_id: int, bits: int) -> int:
    """ipam/utils.go convertToNodeIPPart: the ID one-past-the-range maps
    to part 0 (usable for subnets); anything larger is an error."""
    if node_id == (1 << bits):
        return 0
    if node_id & ((1 << bits) - 1) != node_id:
        raise IPAMError(f"node ID {node_id} out of range for {bits} bits")
    return node_id


class IPAM:
    """Per-node address manager."""

    def __init__(self, config: IPAMConfig, node_id: int):
        if node_id <= 0:
            raise IPAMError("node ID must be a positive integer")
        self.config = config
        self.node_id = node_id
        self._lock = threading.Lock()

        self.pod_subnet_all_nodes = config.pod_subnet()
        self.pod_subnet_this_node = dissect_subnet_for_node(
            self.pod_subnet_all_nodes, config.pod_subnet_one_node_prefix_len, node_id
        )
        self.host_subnet_all_nodes = config.host_subnet()
        self.host_subnet_this_node = dissect_subnet_for_node(
            self.host_subnet_all_nodes, config.host_subnet_one_node_prefix_len, node_id
        )

        base = int(self.pod_subnet_this_node.network_address)
        self.pod_gateway_ip = ipaddress.ip_address(base + POD_GATEWAY_SEQ_ID)

        # Pod allocation pool state (re-learned on resync, never persisted).
        self._assigned: Dict[int, PodID] = {}  # ip (int) -> pod
        self._pod_to_ip: Dict[PodID, ipaddress.IPv4Address] = {}
        self._last_assigned_seq = 1

    # --------------------------------------------------------------- subnets

    def pod_subnet_other_node(self, node_id: int) -> ipaddress.IPv4Network:
        return dissect_subnet_for_node(
            self.pod_subnet_all_nodes,
            self.config.pod_subnet_one_node_prefix_len,
            node_id,
        )

    def host_subnet_other_node(self, node_id: int) -> ipaddress.IPv4Network:
        return dissect_subnet_for_node(
            self.host_subnet_all_nodes,
            self.config.host_subnet_one_node_prefix_len,
            node_id,
        )

    def service_network(self) -> ipaddress.IPv4Network:
        return self.config.service()

    # ------------------------------------------------- interconnect addresses

    def host_interconnect_ip_dataplane(self) -> ipaddress.IPv4Address:
        """Data-plane-side IP of the host<->data-plane interconnect."""
        base = int(self.host_subnet_this_node.network_address)
        return ipaddress.ip_address(base + HOST_INTERCONNECT_DATAPLANE_SEQ_ID)

    def host_interconnect_ip_host(self) -> ipaddress.IPv4Address:
        """Host(Linux)-side IP of the interconnect."""
        base = int(self.host_subnet_this_node.network_address)
        return ipaddress.ip_address(base + HOST_INTERCONNECT_HOST_SEQ_ID)

    def node_ip(self, node_id: Optional[int] = None) -> ipaddress.IPv4Address:
        """Interconnect IP of a node (ipam.go computeNodeIPAddress :618)."""
        node_id = node_id if node_id is not None else self.node_id
        subnet = self.config.node_interconnect()
        part = _node_ip_part(node_id, 32 - subnet.prefixlen)
        if part == 0:
            raise IPAMError(f"no free node IP for node ID {node_id}")
        computed = int(subnet.network_address) + part
        for excluded in sorted(int(ipaddress.ip_address(e)) for e in self.config.excluded_node_ips):
            if excluded <= computed:
                computed += 1
        return ipaddress.ip_address(computed)

    def vxlan_ip(self, node_id: Optional[int] = None) -> ipaddress.IPv4Address:
        """BVI/VXLAN IP of a node (ipam.go computeVxlanIPAddress)."""
        node_id = node_id if node_id is not None else self.node_id
        subnet = self.config.vxlan()
        part = _node_ip_part(node_id, 32 - subnet.prefixlen)
        if part == 0:
            raise IPAMError(f"no free VXLAN IP for node ID {node_id}")
        return ipaddress.ip_address(int(subnet.network_address) + part)

    def nat_loopback_ip(self) -> ipaddress.IPv4Address:
        """Last unicast IP of this node's pod subnet (ipam.go :443)."""
        return ipaddress.ip_address(int(self.pod_subnet_this_node.broadcast_address) - 1)

    # --------------------------------------------------------- pod allocation

    def allocate_pod_ip(self, pod_id: PodID) -> ipaddress.IPv4Address:
        """Allocate (or return the existing) IP for a pod.

        Round-robin from the last assigned sequence ID, skipping the
        gateway; the last unicast IP is the NAT loopback and is never
        allocated (max seq = 2^host_bits - 2, exclusive).
        """
        with self._lock:
            existing = self._pod_to_ip.get(pod_id)
            if existing is not None:
                return existing
            base = int(self.pod_subnet_this_node.network_address)
            host_bits = 32 - self.pod_subnet_this_node.prefixlen
            max_seq = (1 << host_bits) - 2  # exclusive; reserves loopback+bcast
            start = self._last_assigned_seq + 1
            for seq in list(range(start, max_seq)) + list(range(1, start)):
                if seq == POD_GATEWAY_SEQ_ID:
                    continue
                ip_int = base + seq
                if ip_int in self._assigned:
                    continue
                self._assigned[ip_int] = pod_id
                ip = ipaddress.ip_address(ip_int)
                self._pod_to_ip[pod_id] = ip
                self._last_assigned_seq = seq
                return ip
        raise IPAMError(f"no free pod IP in {self.pod_subnet_this_node}")

    def release_pod_ip(self, pod_id: PodID) -> None:
        with self._lock:
            ip = self._pod_to_ip.pop(pod_id, None)
            if ip is not None:
                self._assigned.pop(int(ip), None)

    def get_pod_ip(self, pod_id: PodID) -> Optional[ipaddress.IPv4Address]:
        with self._lock:
            return self._pod_to_ip.get(pod_id)

    @property
    def allocated_count(self) -> int:
        with self._lock:
            return len(self._assigned)

    # ----------------------------------------------------------------- resync

    def _adopt_locked(self, pod_id: PodID, ip: ipaddress.IPv4Address) -> bool:
        """Register an existing allocation; single source of the
        reserved-address rules.  A conflicting prior owner of the IP (or a
        prior IP of the pod) is evicted — last writer wins, with both maps
        kept consistent.  Caller holds the lock."""
        base = int(self.pod_subnet_this_node.network_address)
        host_bits = 32 - self.pod_subnet_this_node.prefixlen
        max_seq = (1 << host_bits) - 2  # exclusive: NAT loopback + bcast
        seq = int(ip) - base
        if seq == POD_GATEWAY_SEQ_ID or not (0 < seq < max_seq):
            # Reserved address (gateway, NAT loopback, broadcast, network)
            # recorded by stale/foreign state: never adopt, or the
            # allocator could later re-hand it out.
            log.warning("ignoring pod %s with reserved IP %s", pod_id, ip)
            return False
        prior_owner = self._assigned.get(int(ip))
        if prior_owner is not None and prior_owner != pod_id:
            self._pod_to_ip.pop(prior_owner, None)
        prior_ip = self._pod_to_ip.get(pod_id)
        if prior_ip is not None and prior_ip != ip:
            self._assigned.pop(int(prior_ip), None)
        self._assigned[int(ip)] = pod_id
        self._pod_to_ip[pod_id] = ip
        self._last_assigned_seq = max(self._last_assigned_seq, seq)
        return True

    def adopt(self, pod_id: PodID, ip) -> bool:
        """Force-register an existing allocation (used to preserve
        CNI-granted IPs of pods not yet reflected into KubeState across a
        resync). Returns False if the IP is reserved/foreign."""
        ip = ipaddress.ip_address(str(ip))
        with self._lock:
            if ip not in self.pod_subnet_this_node:
                return False
            return self._adopt_locked(pod_id, ip)

    def resync(self, kube_state) -> None:
        """Re-learn the pool from KubeState pods (ipam.go Resync :127):
        adopt every pod whose IP falls into this node's subnet."""
        with self._lock:
            self._assigned.clear()
            self._pod_to_ip.clear()
            self._last_assigned_seq = 1
            for pod in kube_state.get("pod", {}).values():
                if not isinstance(pod, Pod) or not pod.ip_address:
                    continue
                try:
                    ip = ipaddress.ip_address(pod.ip_address)
                except ValueError:
                    continue
                if ip not in self.pod_subnet_this_node:
                    continue
                self._adopt_locked(pod.id, ip)

    def assigned_pods(self) -> Dict[PodID, ipaddress.IPv4Address]:
        """Snapshot of all current pod→IP assignments (the authoritative
        local-pod set after a resync — already filtered by the
        reserved-address rules)."""
        with self._lock:
            return dict(self._pod_to_ip)
