"""RemoteCNI gRPC service — the kubelet↔agent boundary.

Analog of ``plugins/podmanager/cni/cni.proto`` (service RemoteCNI with
Add/Delete taking a CNIRequest and returning a CNIReply) and of the
server registration in ``plugins/podmanager/podmanager.go:97-111``.

The wire protocol is gRPC (HTTP/2) with JSON-encoded messages: the
environment has no protoc service-stub generator, so the service is
registered through ``grpc.method_handlers_generic_handler`` with
explicit serializers — same RPC shape, schema documented by the
dataclasses below (field names follow cni.proto).
"""

from __future__ import annotations

import json
import logging
from concurrent import futures
from dataclasses import asdict
from typing import Optional

import grpc

# Message dataclasses live in the grpc-free .messages module so the
# host-side shim can run without grpcio; re-exported here unchanged.
from .messages import DEFAULT_PORT, CNIReply, CNIRequest  # noqa: F401

log = logging.getLogger(__name__)

SERVICE_NAME = "cni.RemoteCNI"


def _encode(msg) -> bytes:
    return json.dumps(asdict(msg)).encode()


def _decode_request(data: bytes) -> CNIRequest:
    return CNIRequest(**json.loads(data.decode()))


def _decode_reply(data: bytes) -> CNIReply:
    return CNIReply(**json.loads(data.decode()))


class CNIServer:
    """gRPC server bridging CNI RPCs into blocking pod events.

    ``podmanager`` must expose ``add_pod(...) -> PodCNIReply`` and
    ``delete_pod(...)`` (the blocking-event facade).
    """

    def __init__(self, podmanager, port: int = DEFAULT_PORT, host: str = "127.0.0.1"):
        self.podmanager = podmanager
        self.port = port
        self.host = host
        self._server: Optional[grpc.Server] = None

    # ------------------------------------------------------------- handlers

    def _pod_identity(self, request: CNIRequest):
        args = request.extra_args()
        return args.get("K8S_POD_NAME", ""), args.get("K8S_POD_NAMESPACE", "default")

    def add(self, request: CNIRequest, context=None) -> CNIReply:
        from ..controller.drain import CNI_DRAINING_CODE, NodeDraining

        name, namespace = self._pod_identity(request)
        if not name:
            return CNIReply(result=1, error="missing K8S_POD_NAME in extra arguments")
        try:
            reply = self.podmanager.add_pod(
                name=name,
                namespace=namespace,
                container_id=request.container_id,
                network_namespace=request.network_namespace,
            )
        except NodeDraining as err:
            # RETRIABLE by contract (ISSUE 13): the agent is draining,
            # not broken — code 11 ("try again later"), message carries
            # the AGENT_DRAINING marker so callers can distinguish it
            # from a transient outage.  Deliberately not log.exception:
            # an operator drain is not an error condition.
            log.info("CNI Add for %s/%s refused: agent draining",
                     namespace, name)
            return CNIReply(result=CNI_DRAINING_CODE, error=str(err))
        except Exception as err:  # error propagates as non-zero CNI result
            log.exception("CNI Add failed for %s/%s", namespace, name)
            return CNIReply(result=1, error=str(err))
        return CNIReply(result=0, interfaces=list(reply.interfaces),
                        routes=list(reply.routes))

    def delete(self, request: CNIRequest, context=None) -> CNIReply:
        name, namespace = self._pod_identity(request)
        if not name:
            return CNIReply(result=1, error="missing K8S_POD_NAME in extra arguments")
        try:
            self.podmanager.delete_pod(name=name, namespace=namespace)
        except Exception as err:
            log.exception("CNI Delete failed for %s/%s", namespace, name)
            return CNIReply(result=1, error=str(err))
        return CNIReply(result=0)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> int:
        """Start serving; returns the bound port (0 picks a free one)."""
        handlers = {
            "Add": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self.add(req, ctx),
                request_deserializer=_decode_request,
                response_serializer=_encode,
            ),
            "Delete": grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self.delete(req, ctx),
                request_deserializer=_decode_request,
                response_serializer=_encode,
            ),
        }
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()
        log.info("RemoteCNI gRPC server listening on %s:%d", self.host, self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            self._server.stop(grace)
            self._server = None


# ------------------------------------------------------------------ client


def _call(target: str, method: str, request: CNIRequest, timeout: float) -> CNIReply:
    with grpc.insecure_channel(target) as channel:
        rpc = channel.unary_unary(
            f"/{SERVICE_NAME}/{method}",
            request_serializer=_encode,
            response_deserializer=_decode_reply,
        )
        return rpc(request, timeout=timeout)


def remote_cni_add(target: str, request: CNIRequest, timeout: float = 60.0) -> CNIReply:
    """Client side of RemoteCNI.Add (cmd/contiv-cni grpcConnect + Add)."""
    return _call(target, "Add", request, timeout)


def remote_cni_delete(target: str, request: CNIRequest, timeout: float = 60.0) -> CNIReply:
    return _call(target, "Delete", request, timeout)
