"""CNI wire messages — grpc-free so the host-side shim can import them.

Schema follows ``plugins/podmanager/cni/cni.proto`` (CNIRequest /
CNIReply); :mod:`.rpc` re-exports these for the gRPC service, and the
agent REST server serves the same messages over plain HTTP for hosts
whose system python has no grpcio (the shim's stdlib fallback path).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

CNI_VERSION = "0.3.1"
DEFAULT_PORT = 9111  # the reference agent's CNI gRPC port


@dataclass
class CNIRequest:
    """cni.proto CNIRequest."""

    version: str = ""
    container_id: str = ""
    network_namespace: str = ""
    interface_name: str = ""
    extra_nw_config: str = ""
    extra_arguments: str = ""  # "K8S_POD_NAME=..;K8S_POD_NAMESPACE=.."
    ipam_type: str = ""
    ipam_data: str = ""

    def extra_args(self) -> dict:
        out = {}
        for part in self.extra_arguments.split(";"):
            key, sep, value = part.partition("=")
            if sep:
                out[key] = value
        return out


@dataclass
class CNIReply:
    """cni.proto CNIReply (interfaces/routes as plain dicts)."""

    result: int = 0
    error: str = ""
    interfaces: List[dict] = field(default_factory=list)
    routes: List[dict] = field(default_factory=list)
    dns: List[dict] = field(default_factory=list)
