"""External-IPAM delegation for the CNI shim.

Analog of ``cmd/contiv-cni/external_ipam.go:36-142``: when the network
config carries an ``ipam`` section with a ``type``, IP allocation is
delegated to that CNI IPAM plugin, executed per the CNI conventions —
binary resolved on ``CNI_PATH``, network config on stdin, ``CNI_*``
environment forwarded with ``CNI_COMMAND`` set to ADD/DEL.

Special case mirrored from the reference: for the ``host-local``
plugin, an ``ipam.subnet`` of ``usePodCidr`` is rewritten to this
node's ACTUAL pod CIDR before delegation.  The reference reads the
node record from etcd (``getPodCIDR``); here the node's pod CIDR comes
from the agent's ``GET /contiv/v1/ipam`` route (``podSubnetThisNode``)
— the same store-backed information without an etcd client in the
dep-less shim.

ADD returns the delegate's FIRST allocated IP as a JSON string (the
``IpamData`` the agent consumes); DEL releases the allocation.  The
shim invokes DEL after a failed agent ADD so delegated IPs never leak
(contiv_cni.go cmdAdd's deferred cleanup :166-172).
"""

from __future__ import annotations

import copy
import json
import os
import subprocess
from typing import Callable, Optional

HOST_LOCAL = "host-local"
POD_CIDR_SUBST = "usePodCidr"
DEFAULT_CNI_PATH = "/opt/cni/bin"

# A delegate executor: (plugin_name, command, netconf_json_str, env) -> stdout.
ExecPlugin = Callable[[str, str, str, dict], str]


def ipam_type(conf: dict) -> str:
    """The external IPAM plugin name of a network config ('' = none)."""
    ipam = conf.get("ipam")
    if isinstance(ipam, dict):
        return str(ipam.get("type", "") or "")
    return ""


def _find_binary(plugin: str, env: dict) -> str:
    for directory in env.get("CNI_PATH", DEFAULT_CNI_PATH).split(":"):
        cand = os.path.join(directory, plugin)
        if os.path.isfile(cand) and os.access(cand, os.X_OK):
            return cand
    raise FileNotFoundError(
        f"IPAM plugin {plugin!r} not found on CNI_PATH "
        f"{env.get('CNI_PATH', DEFAULT_CNI_PATH)!r}"
    )


def _default_exec(plugin: str, command: str, netconf: str, env: dict) -> str:
    """Run the delegate per the CNI exec protocol."""
    binary = _find_binary(plugin, env)
    run_env = {key: str(val) for key, val in env.items()}
    run_env["CNI_COMMAND"] = command
    proc = subprocess.run(
        [binary], input=netconf.encode(), capture_output=True, env=run_env,
    )
    if proc.returncode != 0:
        detail = proc.stdout.decode(errors="replace").strip() or \
            proc.stderr.decode(errors="replace").strip()
        raise RuntimeError(f"IPAM plugin {plugin} {command} failed: {detail}")
    return proc.stdout.decode()


def replace_pod_cidr(
    conf: dict, pod_cidr: Callable[[], str]
) -> dict:
    """host-local's ``usePodCidr`` substitution (external_ipam.go
    replacePodCIDR :86-115): returns a config copy whose
    ``ipam.subnet`` is this node's pod CIDR.  A failed lookup leaves
    the config unchanged, matching the reference's fail-open logging.
    """
    ipam = conf.get("ipam")
    if not isinstance(ipam, dict):
        return conf
    subnet = str(ipam.get("subnet", ""))
    if subnet.lower() != POD_CIDR_SUBST.lower():
        return conf
    try:
        cidr = pod_cidr()
    except Exception:
        cidr = ""
    if not cidr:
        return conf
    out = copy.deepcopy(conf)
    out["ipam"]["subnet"] = cidr
    return out


def _prepared_netconf(conf: dict, pod_cidr: Callable[[], str]) -> str:
    if ipam_type(conf) == HOST_LOCAL:
        conf = replace_pod_cidr(conf, pod_cidr)
    return json.dumps(conf)


def ipam_add(
    conf: dict,
    env: dict,
    pod_cidr: Callable[[], str],
    exec_plugin: Optional[ExecPlugin] = None,
) -> str:
    """Delegate ADD; returns the first allocated IP as a JSON string
    (empty when the delegate returned no IPs), the ``IpamData``
    payload of execIPAMAdd :36-67."""
    plugin = ipam_type(conf)
    run = exec_plugin or _default_exec
    out = run(plugin, "ADD", _prepared_netconf(conf, pod_cidr), env)
    result = json.loads(out) if out.strip() else {}
    ips = result.get("ips") or []
    if not ips:
        return ""
    return json.dumps(ips[0])


def ipam_del(
    conf: dict,
    env: dict,
    pod_cidr: Callable[[], str],
    exec_plugin: Optional[ExecPlugin] = None,
) -> None:
    """Delegate DEL (release the allocation) — execIPAMDel :69-84."""
    plugin = ipam_type(conf)
    run = exec_plugin or _default_exec
    run(plugin, "DEL", _prepared_netconf(conf, pod_cidr), env)


def agent_pod_cidr(http_target: str, timeout: float = 10.0) -> str:
    """This node's pod CIDR from the agent's /contiv/v1/ipam route
    (the store-backed node record the reference reads from etcd)."""
    import urllib.request

    with urllib.request.urlopen(  # noqa: S310 - loopback agent
        f"http://{http_target}/contiv/v1/ipam", timeout=timeout
    ) as resp:
        return str(json.load(resp).get("podSubnetThisNode", ""))
