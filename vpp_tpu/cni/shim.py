"""The CNI shim binary kubelet executes.

Analog of ``cmd/contiv-cni/contiv_cni.go``: reads the CNI environment
(CNI_COMMAND, CNI_CONTAINERID, CNI_NETNS, CNI_IFNAME, CNI_ARGS) and the
network config from stdin, forwards the request over gRPC to the agent's
RemoteCNI server (cmdAdd :122 / cmdDel :259), and prints the CNI result
JSON (spec 0.3.1) on stdout — errors as the CNI error object with a
non-zero exit code (main :318).

Run as ``python -m vpp_tpu.cni.shim`` with the CNI env set.
"""

from __future__ import annotations

import json
import os
import sys

from .messages import CNI_VERSION, DEFAULT_PORT, CNIReply, CNIRequest

# The primary transport is the cni.proto-parity gRPC service; host
# pythons without grpcio (the common case for the installed shim — only
# the container image pip-installs deps) fall back to the agent REST
# server's /cni/* routes over stdlib HTTP.
try:
    from .rpc import remote_cni_add, remote_cni_delete

    _HAVE_GRPC = True
except ImportError:  # pragma: no cover - exercised on dep-less hosts
    _HAVE_GRPC = False


def _http_cni(target: str, action: str, request: CNIRequest) -> CNIReply:
    import urllib.request
    from dataclasses import asdict

    req = urllib.request.Request(
        f"http://{target}/cni/{action}",
        data=json.dumps(asdict(request)).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:  # noqa: S310
        return CNIReply(**json.load(resp))


def _error_result(code: int, msg: str) -> dict:
    return {"cniVersion": CNI_VERSION, "code": code, "msg": msg}


def _reply_to_result(reply) -> dict:
    """CNIReply → CNI 0.3.1 result JSON (cmdAdd result assembly)."""
    interfaces = []
    ips = []
    for idx, iface in enumerate(reply.interfaces):
        interfaces.append(
            {
                "name": iface.get("name", "eth0"),
                "mac": iface.get("mac", ""),
                "sandbox": iface.get("sandbox", ""),
            }
        )
        if iface.get("ip"):
            ips.append(
                {
                    "version": "4",
                    "address": iface["ip"],
                    "gateway": iface.get("gateway", ""),
                    "interface": idx,
                }
            )
    routes = [
        {"dst": r.get("dst", "0.0.0.0/0"), **({"gw": r["gw"]} if r.get("gw") else {})}
        for r in reply.routes
    ]
    return {
        "cniVersion": CNI_VERSION,
        "interfaces": interfaces,
        "ips": ips,
        "routes": routes,
        "dns": {},
    }


def build_request(env: dict, stdin_config: str) -> CNIRequest:
    return CNIRequest(
        version=CNI_VERSION,
        container_id=env.get("CNI_CONTAINERID", ""),
        network_namespace=env.get("CNI_NETNS", ""),
        interface_name=env.get("CNI_IFNAME", "eth0"),
        extra_nw_config=stdin_config,
        extra_arguments=env.get("CNI_ARGS", ""),
    )


def main(env=None, stdin=None, stdout=None, exec_ipam_plugin=None) -> int:
    env = env if env is not None else os.environ
    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout

    command = env.get("CNI_COMMAND", "")
    config = stdin.read() if command in ("ADD", "DEL") else ""
    try:
        conf = json.loads(config) if config else {}
    except ValueError:
        conf = {}
    target = conf.get("grpcServer", f"127.0.0.1:{DEFAULT_PORT}")
    http_target = conf.get("httpServer", "127.0.0.1:9999")
    request = build_request(env, config)

    if command == "VERSION":
        json.dump({"cniVersion": CNI_VERSION,
                   "supportedVersions": [CNI_VERSION]}, stdout)
        return 0
    if command not in ("ADD", "DEL"):
        json.dump(_error_result(4, f"unsupported CNI_COMMAND {command!r}"), stdout)
        return 1

    # External IPAM delegation (cmd/contiv-cni/external_ipam.go:36-142):
    # an ``ipam.type`` in the netconf routes allocation through that
    # CNI IPAM plugin; the delegate's first IP rides the agent request
    # as ipam_data.  ``exec_ipam_plugin`` is the test seam.
    from . import external_ipam

    delegate = external_ipam.ipam_type(conf)
    pod_cidr = lambda: external_ipam.agent_pod_cidr(http_target)  # noqa: E731
    if delegate and command == "ADD":
        try:
            request.ipam_type = delegate
            request.ipam_data = external_ipam.ipam_add(
                conf, dict(env), pod_cidr, exec_plugin=exec_ipam_plugin
            )
        except Exception as err:
            json.dump(_error_result(11, f"external IPAM ADD failed: {err}"), stdout)
            return 1

    def _release_delegate() -> None:
        # Invoke IPAM DEL after a failed agent ADD so the delegated IP
        # never leaks (contiv_cni.go cmdAdd's deferred cleanup).
        try:
            external_ipam.ipam_del(
                conf, dict(env), pod_cidr, exec_plugin=exec_ipam_plugin
            )
        except Exception:
            pass

    # Transport selection: gRPC when importable, unless the environment
    # pins the stdlib HTTP fallback (VPP_TPU_CNI_TRANSPORT=http) — the
    # kubelet harness uses the knob to exercise the REST path with the
    # SAME exec'd binary a grpc-less host python would run.
    use_grpc = _HAVE_GRPC and env.get("VPP_TPU_CNI_TRANSPORT", "") != "http"
    try:
        if use_grpc:
            if command == "ADD":
                reply = remote_cni_add(target, request)
            else:
                reply = remote_cni_delete(target, request)
        else:
            reply = _http_cni(
                http_target, "add" if command == "ADD" else "del", request
            )
    except Exception as err:
        if delegate and command == "ADD":
            _release_delegate()
        json.dump(_error_result(11, f"agent RPC failed: {err}"), stdout)
        return 1

    if reply.result != 0:
        if delegate and command == "ADD":
            _release_delegate()
        json.dump(_error_result(11, reply.error), stdout)
        return 1
    if command == "ADD":
        json.dump(_reply_to_result(reply), stdout)
    else:
        # Release the external allocation after the agent disconnects
        # the pod (contiv_cni.go cmdDel :303-309).
        if delegate:
            try:
                external_ipam.ipam_del(
                    conf, dict(env), pod_cidr, exec_plugin=exec_ipam_plugin
                )
            except Exception as err:
                json.dump(_error_result(11, f"external IPAM DEL failed: {err}"), stdout)
                return 1
        stdout.write("{}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
