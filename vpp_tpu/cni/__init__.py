"""CNI front end: gRPC server + shim binary.

Analog of the reference's ``plugins/podmanager/cni`` (the RemoteCNI gRPC
service) and ``cmd/contiv-cni`` (the CNI binary kubelet executes).
"""

from .rpc import CNIReply, CNIRequest, CNIServer, remote_cni_add, remote_cni_delete

__all__ = ["CNIReply", "CNIRequest", "CNIServer", "remote_cni_add", "remote_cni_delete"]
