"""Policy stack — K8s NetworkPolicy -> 5-tuple ContivRules -> rule tables.

Mirrors the reference's layering (plugins/policy, SURVEY.md §2.1):

    PolicyPlugin (plugin.py)          event-handler skeleton
      -> PolicyCache (cache.py)       indexed pods/policies/namespaces,
                                      label-selector matching
      -> PolicyProcessor (processor.py) which pods are affected, selector
                                      resolution to concrete peers
      -> PolicyConfigurator (configurator.py) policies -> ingress/egress
                                      ContivRule lists per pod
      -> renderers (renderer/)        rule tables for the TPU data plane

The traffic direction convention is inherited from the reference
(renderer/api.go Render): *ingress*/*egress* are from the vswitch point
of view — a pod's "ingress table" filters traffic the pod sends, its
"egress table" filters traffic delivered to the pod.
"""

from .renderer.api import (
    Action,
    ContivRule,
    RULE_MATCH_ALL_SRC,
    RULE_MATCH_ALL_DST,
)
from .cache import PolicyCache
from .configurator import PolicyConfigurator, ContivPolicy, Match, MatchType, PolicyKind
from .processor import PolicyProcessor
from .plugin import PolicyPlugin

__all__ = [
    "Action",
    "ContivRule",
    "RULE_MATCH_ALL_SRC",
    "RULE_MATCH_ALL_DST",
    "PolicyCache",
    "PolicyConfigurator",
    "ContivPolicy",
    "Match",
    "MatchType",
    "PolicyKind",
    "PolicyProcessor",
    "PolicyPlugin",
]
