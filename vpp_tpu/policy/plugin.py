"""Policy plugin — event-handler skeleton wiring the policy layers.

Analog of ``plugins/policy/plugin_impl_policy.go`` (layer wiring in
Init :74-141): cache -> processor -> configurator -> registered
renderers, driven by KubeStateChange events for pods, policies and
namespaces.
"""

from __future__ import annotations

import logging

from ..controller.api import EventHandler, KubeStateChange
from .cache import PolicyCache
from .configurator import PolicyConfigurator
from .processor import PolicyProcessor

log = logging.getLogger(__name__)


class PolicyPlugin(EventHandler):
    """The policy stack as one event handler."""

    name = "policy"

    def __init__(self, ipam=None):
        self.cache = PolicyCache()
        self.configurator = PolicyConfigurator(self.cache, ipam=ipam)
        self.processor = PolicyProcessor(self.cache, self.configurator)

    def register_renderer(self, renderer) -> None:
        self.configurator.register_renderer(renderer)

    # -------------------------------------------------------- event handling

    def handles_event(self, event) -> bool:
        if isinstance(event, KubeStateChange):
            return event.resource in ("pod", "policy", "namespace")
        return event.method.is_resync

    def resync(self, event, kube_state, resync_count, txn) -> None:
        self.processor.resync(kube_state)

    def update(self, event, txn) -> str:
        if not isinstance(event, KubeStateChange):
            return ""
        if event.resource == "pod":
            if event.new_value is not None:
                self.cache.update_pod(event.new_value)
            elif event.prev_value is not None:
                self.cache.delete_pod(event.prev_value.id)
            self.processor.on_pod_change(event.prev_value, event.new_value)
            return "reconfigured policies after pod change"
        if event.resource == "policy":
            if event.new_value is not None:
                self.cache.update_policy(event.new_value)
            elif event.prev_value is not None:
                self.cache.delete_policy(event.prev_value.id)
            self.processor.on_policy_change(event.prev_value, event.new_value)
            return "reconfigured policies after policy change"
        if event.resource == "namespace":
            if event.new_value is not None:
                self.cache.update_namespace(event.new_value)
            elif event.prev_value is not None:
                self.cache.delete_namespace(event.prev_value.name)
            self.processor.on_namespace_change(event.prev_value, event.new_value)
            return "reconfigured policies after namespace change"
        return ""
