"""Scheduler-routed TPU policy renderer.

Instead of recompiling device tables inside its own commit (the round-1
short-cut), this renderer emits each pod's rendered rule lists as plain
KVs into the CURRENT EVENT TRANSACTION; the ``TpuAclApplicator``
registered with the TxnScheduler owns the compile + atomic device swap.
That restores the reference's contract: all southbound state of one
event — host FIB and TPU tables alike — lands in one atomic, retried
kvscheduler transaction (plugins/controller/txn.go:28-83).

``txn_provider`` returns the transaction of the event being processed
(the controller exposes it as ``Controller.current_txn``).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from ...models import PodID
from ...ops.packets import ip_to_u32
from ...scheduler.tpu_applicators import ACL_POD_PREFIX, TpuAclApplicator
from .api import ContivRule, PolicyRendererAPI, RendererTxn


def acl_pod_key(pod: PodID) -> str:
    return f"{ACL_POD_PREFIX}{pod.namespace}/{pod.name}"


class SchedPolicyRenderer(PolicyRendererAPI):
    """Emits rendered pod tables into the event txn as tpu/acl/pod/* KVs."""

    def __init__(
        self,
        txn_provider: Callable[[], object],
        applicator: Optional[TpuAclApplicator] = None,
    ):
        self._txn_provider = txn_provider
        # Kept so callers can reach the compiled tables through the
        # renderer (the applicator owns them now).
        self.applicator = applicator

    @property
    def tables(self):
        return self.applicator.tables if self.applicator else None

    def stats(self) -> Dict[str, int]:
        return self.applicator.stats() if self.applicator else {}

    def new_txn(self, resync: bool) -> "SchedRendererTxn":
        return SchedRendererTxn(self, resync)


class SchedRendererTxn(RendererTxn):
    def __init__(self, renderer: SchedPolicyRenderer, resync: bool):
        self.renderer = renderer
        self.resync = resync
        self._changes: Dict[PodID, Optional[Tuple[int, Tuple[ContivRule, ...], Tuple[ContivRule, ...]]]] = {}

    def render(self, pod, pod_ip, ingress, egress, removed=False):
        if removed or pod_ip is None:
            self._changes[pod] = None
            return self
        ip_u32 = ip_to_u32(pod_ip.network_address)
        self._changes[pod] = (ip_u32, tuple(ingress), tuple(egress))
        return self

    def commit(self) -> None:
        txn = self.renderer._txn_provider()
        if txn is None:
            raise RuntimeError(
                "SchedPolicyRenderer.commit outside an event transaction"
            )
        for pod, entry in self._changes.items():
            key = acl_pod_key(pod)
            if entry is None:
                if not txn.is_resync:
                    txn.delete(key)
                # In a resync txn, simply not Put()ing the key removes it.
            else:
                txn.put(key, entry)
