"""Policy renderer boundary — the ContivRule n-tuple.

Analog of the reference's ``plugins/policy/renderer/api.go``: the most
basic rule definition the destination network stack must support, plus
the renderer plug-in interface.  This is the seam where the TPU data
plane plugs into the policy stack (BASELINE.json north star).

Networks are represented as ``ipaddress.IPv4Network`` or ``None``
(match all) — the reference uses a zero-length IPNet for match-all.
A total order is defined on rules (api.go Compare :110): if rule A
matches a subset of rule B's traffic then A sorts before B, which
permits first-match table layouts.
"""

from __future__ import annotations

import enum
import ipaddress
from dataclasses import dataclass
from typing import List, Optional, Sequence

from ...models import PodID, ProtocolType

# Sentinels documenting intent at call sites.
RULE_MATCH_ALL_SRC: Optional[ipaddress.IPv4Network] = None
RULE_MATCH_ALL_DST: Optional[ipaddress.IPv4Network] = None


class Action(enum.IntEnum):
    """DENY sorts before PERMIT, completing the rule total order
    (api.go ActionType)."""

    DENY = 0
    PERMIT = 1
    # PERMIT with connection tracking: reply traffic of permitted flows
    # is allowed back through (the ACL renderer's reflective semantics,
    # acl_renderer.go reflectiveACL :253).
    PERMIT_REFLECT = 2


@dataclass(frozen=True)
class ContivRule:
    """A 6-tuple policy rule (api.go ContivRule :65-77)."""

    action: Action
    src_network: Optional[ipaddress.IPv4Network] = None  # None = match all
    dst_network: Optional[ipaddress.IPv4Network] = None  # None = match all
    protocol: ProtocolType = ProtocolType.ANY
    src_port: int = 0  # 0 = match all
    dst_port: int = 0  # 0 = match all

    def matches(
        self,
        src_ip: ipaddress.IPv4Address,
        dst_ip: ipaddress.IPv4Address,
        protocol: ProtocolType,
        src_port: int,
        dst_port: int,
    ) -> bool:
        """Reference-semantics match of one flow against this rule."""
        if self.src_network is not None and src_ip not in self.src_network:
            return False
        if self.dst_network is not None and dst_ip not in self.dst_network:
            return False
        if self.protocol is not ProtocolType.ANY:
            if self.protocol is not protocol:
                return False
            if self.src_port != 0 and self.src_port != src_port:
                return False
            if self.dst_port != 0 and self.dst_port != dst_port:
                return False
        return True

    def __str__(self) -> str:
        src = str(self.src_network) if self.src_network else "ANY"
        dst = str(self.dst_network) if self.dst_network else "ANY"
        sp = self.src_port or "ANY"
        dp = self.dst_port or "ANY"
        return (
            f"Rule <{self.action.name} {src}[{self.protocol.name}:{sp}] -> "
            f"{dst}[{self.protocol.name}:{dp}]>"
        )


def insert_rule(rules: List[ContivRule], rule: ContivRule) -> bool:
    """De-duplicating insert, preserving insertion order.

    The reference keeps two lists (sorted for dedup, insertion-ordered
    for rendering — configurator ContivRules.Insert/CopySlice); since
    all generated rules are PERMITs followed by one final DENY, the
    insertion order is the order renderers must evaluate in.
    """
    if rule in rules:
        return False
    rules.append(rule)
    return True


class RendererTxn:
    """One transaction of a policy renderer (api.go Txn)."""

    def render(
        self,
        pod: PodID,
        pod_ip: Optional[ipaddress.IPv4Network],
        ingress: Sequence[ContivRule],
        egress: Sequence[ContivRule],
        removed: bool = False,
    ) -> "RendererTxn":
        """Replace the rules of one pod.

        Direction is from the vswitch point of view: *ingress* rules
        filter traffic the pod sends (src unset = match all), *egress*
        rules filter traffic delivered to the pod (dst unset).
        An empty rule list allows all traffic in that direction.
        """
        raise NotImplementedError

    def commit(self) -> None:
        raise NotImplementedError


class PolicyRendererAPI:
    """Renderer plug-in interface (api.go PolicyRendererAPI)."""

    def new_txn(self, resync: bool) -> RendererTxn:
        raise NotImplementedError
