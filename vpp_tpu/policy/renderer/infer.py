"""TPU inference renderers — beside the policy renderers (ISSUE 14).

Two renderers behind the InferencePlugin's ``render(model, bindings,
resync)`` boundary, mirroring the policy pair (tpu.py / sched.py):

- :class:`TpuInferRenderer` — direct-compile: maintains a persistent
  incremental builder and hands the freshly compiled
  :class:`~vpp_tpu.ops.infer.InferTable` to an ``on_compiled`` hook.
  For standalone harnesses and benches that run without a scheduler.
- :class:`SchedInferRenderer` — the production path: emits the model
  and the per-pod enrollments as plain ``tpu/infer/*`` KVs into the
  CURRENT EVENT TRANSACTION; the TpuInferApplicator owns the
  incremental compile + atomic device swap, so a model update lands in
  the same atomic, retried, spanned kvscheduler transaction as every
  other southbound value of its event.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set, Tuple

from ...ops.infer import InferTable
from ...ops.infer_delta import (
    INFER_MODEL_KEY,
    INFER_POD_PREFIX,
    InferTableBuilder,
)
from ...ops.packets import u32_to_ip


def infer_pod_key(pod_ip_u32: int) -> str:
    """Enrollment key for one pod IP.  Keyed by the dotted IP (not the
    pod name): the datapath enrolls ADDRESSES, and a pod IP reused
    after a delete/re-add overwrites the same key — exactly the
    desired last-writer semantics."""
    return f"{INFER_POD_PREFIX}{u32_to_ip(pod_ip_u32)}"


class TpuInferRenderer:
    """Direct-compile renderer (the TpuPolicyRenderer analog)."""

    def __init__(self, on_compiled: Optional[Callable[[InferTable], None]] = None):
        self._lock = threading.Lock()
        self._builder = InferTableBuilder()
        self._compiled: Optional[InferTable] = None
        self._on_compiled = on_compiled

    @property
    def tables(self) -> Optional[InferTable]:
        with self._lock:
            return self._compiled

    def stats(self) -> Dict[str, object]:
        with self._lock:
            compiled = self._compiled
            return {
                "enabled": bool(compiled.enabled) if compiled else False,
                "pods": compiled.num_pods if compiled else 0,
                "compile": self._builder.stats.as_dict(),
            }

    def render(self, model, bindings: Dict[int, Tuple[int, int]],
               resync: bool) -> None:
        state: Dict[str, object] = {}
        if model is not None:
            state[INFER_MODEL_KEY] = model
        for ip, (threshold, action) in bindings.items():
            state[infer_pod_key(ip)] = (ip, threshold, action)
        with self._lock:
            compiled = self._builder.sync(state)
            self._compiled = compiled
        if self._on_compiled is not None:
            self._on_compiled(compiled)


class SchedInferRenderer:
    """Scheduler-routed renderer: tpu/infer/* KVs into the event txn.

    Tracks the keys it last rendered so an UPDATE transaction deletes
    enrollments that disappeared (a resync txn removes them by simply
    not Put()ing — the scheduler's resync semantics)."""

    def __init__(self, txn_provider: Callable[[], object],
                 applicator=None):
        self._txn_provider = txn_provider
        # Kept so callers reach the compiled table through the renderer
        # (the applicator owns it now) — same shape as SchedPolicyRenderer.
        self.applicator = applicator
        self._last_keys: Set[str] = set()

    @property
    def tables(self) -> Optional[InferTable]:
        return self.applicator.tables if self.applicator else None

    def stats(self) -> Dict[str, object]:
        return self.applicator.stats() if self.applicator else {}

    def render(self, model, bindings: Dict[int, Tuple[int, int]],
               resync: bool) -> None:
        txn = self._txn_provider()
        if txn is None:
            raise RuntimeError(
                "SchedInferRenderer.render outside an event transaction")
        keys: Set[str] = set()
        if model is not None:
            txn.put(INFER_MODEL_KEY,
                    model.to_dict() if hasattr(model, "to_dict") else model)
            keys.add(INFER_MODEL_KEY)
        for ip, (threshold, action) in bindings.items():
            key = infer_pod_key(ip)
            txn.put(key, (ip, threshold, action))
            keys.add(key)
        if not txn.is_resync:
            for gone in self._last_keys - keys:
                txn.delete(gone)
        self._last_keys = keys
