from .api import Action, ContivRule, PolicyRendererAPI, RendererTxn

__all__ = ["Action", "ContivRule", "PolicyRendererAPI", "RendererTxn"]
