"""Session-rule policy renderer — the host-stack (L4) alternative.

Analog of ``plugins/policy/renderer/vpptcp/`` (vpptcp_renderer.go:35,
rule/session_rule.go:73): instead of compiling rule tensors for the
TPU classify kernel, this renderer programs **session rules** into the
host-stack session layer of the batch shim — filtering at
connect()/accept() time rather than per packet, exactly like the
reference's VPPTCP renderer programmed VPP's session layer over the
GoVPP binary API.

Orientation and table assembly come from the shared RendererCache in
INGRESS orientation (vpptcp_renderer.go Init :61): each pod's local
table (applied in the pod's application namespace at connect time)
holds its ingress-oriented rules, and the global table (applied at
accept time) holds every pod's egress rules narrowed to the pod IP.

Wire fidelity with the reference export rules
(rule/session_rule.go ExportSessionRules :214):
- allow-all destination rules are not installed — allowing is the
  stack's default behaviour;
- local rules whose destination is the pod's own IP are skipped;
- ANY-protocol rules split into a TCP + UDP pair (tag ``-ANY``);
- match-all remote networks split into the two /1 halves of the IPv4
  space (tag ``-SPLIT``) to avoid colliding with stack proxy rules;
- every rule is tagged so a resync dump can identify (and a foreign
  agent can ignore) rules owned by this renderer.

Commits send minimal add/delete batches over a ``SessionRuleChannel``
(the GoVPP channel analog — implemented by the host shim, and by
``vpp_tpu.testing.sessionengine.MockSessionEngine`` in tests); resync
dumps the installed rules, imports them back into ContivRule tables
(ImportSessionRules :358) and removes stale state.
"""

from __future__ import annotations

import ipaddress
import logging
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ...models import PodID, ProtocolType
from .api import Action, ContivRule, PolicyRendererAPI, RendererTxn
from .cache import CacheTxn, Orientation, PodConfig, RendererCache

log = logging.getLogger(__name__)

# Rule ownership tags (rule/session_rule.go :33-44).
TAG_PREFIX = "vpp-tpu/policy"
ANY_PROTOCOL_TAG = "-ANY"
SPLIT_TAG = "-SPLIT"

SCOPE_LOCAL = "local"
SCOPE_GLOBAL = "global"

ACTION_ALLOW = "allow"
ACTION_DENY = "deny"

_HALF1 = ipaddress.IPv4Network("0.0.0.0/1")
_HALF2 = ipaddress.IPv4Network("128.0.0.0/1")


@dataclass(frozen=True)
class SessionRule:
    """One host-stack session-layer rule (session_rule.go SessionRule
    :73-86, minus the IPv6/raw-bytes wire framing)."""

    scope: str                                     # SCOPE_LOCAL / SCOPE_GLOBAL
    appns_index: int                               # 0 for global scope
    transport_proto: ProtocolType                  # TCP or UDP only
    lcl_ip: Optional[ipaddress.IPv4Network]        # None = 0/0
    lcl_port: int
    rmt_ip: Optional[ipaddress.IPv4Network]        # None = 0/0
    rmt_port: int
    action: str                                    # ACTION_ALLOW / ACTION_DENY
    tag: str = TAG_PREFIX

    def __str__(self) -> str:
        lcl = str(self.lcl_ip) if self.lcl_ip else "0.0.0.0/0"
        rmt = str(self.rmt_ip) if self.rmt_ip else "0.0.0.0/0"
        return (
            f"SessionRule <ns:{self.appns_index} {self.scope} {self.action} "
            f"lcl:{lcl}[{self.transport_proto.name}:{self.lcl_port}] "
            f"rmt:{rmt}[{self.transport_proto.name}:{self.rmt_port}] "
            f"tag:{self.tag}>"
        )


class SessionRuleChannel:
    """Transport to the session layer (the GoVPP channel analog)."""

    def apply(
        self, added: Sequence[SessionRule], removed: Sequence[SessionRule]
    ) -> None:
        """Install/uninstall rules; must raise on failure."""
        raise NotImplementedError

    def dump(self) -> List[SessionRule]:
        """All currently installed session rules (any owner)."""
        raise NotImplementedError


# ------------------------------------------------------------------- export


def _convert_rule(
    rule: ContivRule, scope: str, ns_index: int, tag_prefix: str
) -> List[SessionRule]:
    """session_rule.go convertContivRule :263 for one TCP/UDP rule."""
    is_global = scope == SCOPE_GLOBAL
    if is_global:
        lcl_ip, lcl_port = rule.dst_network, rule.dst_port
        rmt_ip, rmt_port = rule.src_network, rule.src_port
    else:
        # Local tables leave lcl at 0/0: they are already namespace-scoped.
        lcl_ip, lcl_port = None, rule.src_port
        rmt_ip, rmt_port = rule.dst_network, rule.dst_port
    action = ACTION_DENY if rule.action is Action.DENY else ACTION_ALLOW
    base = SessionRule(
        scope=scope,
        appns_index=0 if is_global else ns_index,
        transport_proto=rule.protocol,
        lcl_ip=lcl_ip,
        lcl_port=lcl_port,
        rmt_ip=rmt_ip,
        rmt_port=rmt_port,
        action=action,
        tag=tag_prefix,
    )
    if rmt_ip is None:
        # Match-all remote: split the IPv4 space in two halves to avoid
        # collision with the stack's proxy rules.
        tag = tag_prefix + SPLIT_TAG
        return [
            replace(base, rmt_ip=_HALF1, tag=tag),
            replace(base, rmt_ip=_HALF2, tag=tag),
        ]
    return [base]


def export_session_rules(
    rules: Sequence[ContivRule],
    pod_ip: Optional[ipaddress.IPv4Network],
    ns_index: int,
    scope: str,
) -> List[SessionRule]:
    """ContivRules (one table) -> session rules
    (session_rule.go ExportSessionRules :214).  ``scope`` is GLOBAL for
    the global table, LOCAL for a pod's table (then ``pod_ip`` and
    ``ns_index`` identify the pod)."""
    out: List[SessionRule] = []
    is_global = scope == SCOPE_GLOBAL
    for rule in rules:
        all_net = rule.src_network if is_global else rule.dst_network
        if (
            rule.dst_port == 0
            and rule.action is not Action.DENY
            and all_net is None
            and rule.protocol is ProtocolType.ANY
        ):
            # Allow-all destination: the stack's default, don't install.
            # (Restricted to ANY-protocol rules: a protocol-specific
            # permit-all must be installed, or a sibling deny-all's
            # split rules would over-block that protocol.  The
            # reference skips those too but leans on the session
            # layer's specificity matching; first-match needs them.)
            continue
        if (
            not is_global
            and rule.dst_network is not None
            and pod_ip is not None
            and rule.dst_network.prefixlen == 32
            and rule.dst_network.network_address == pod_ip.network_address
        ):
            # Same source as destination.
            continue
        if rule.protocol is ProtocolType.ANY:
            # The session layer only knows TCP and UDP: filter ANY as a pair.
            tag = TAG_PREFIX + ANY_PROTOCOL_TAG
            for proto in (ProtocolType.TCP, ProtocolType.UDP):
                out.extend(
                    _convert_rule(
                        replace_protocol(rule, proto), scope, ns_index, tag
                    )
                )
        else:
            out.extend(_convert_rule(rule, scope, ns_index, TAG_PREFIX))
    return out


def replace_protocol(rule: ContivRule, protocol: ProtocolType) -> ContivRule:
    return ContivRule(
        action=rule.action,
        src_network=rule.src_network,
        dst_network=rule.dst_network,
        protocol=protocol,
        src_port=rule.src_port,
        dst_port=rule.dst_port,
    )


# ------------------------------------------------------------------- import


def import_session_rules(
    rules: Sequence[SessionRule],
    pod_by_ns_index: Callable[[int], Optional[PodID]],
) -> Tuple[Dict[PodID, List[ContivRule]], List[ContivRule]]:
    """Installed session rules -> (local tables by pod, global table),
    merging -SPLIT halves and -ANY pairs back into single ContivRules
    (session_rule.go ImportSessionRules :358).  Rules without this
    renderer's tag prefix must be filtered by the caller."""
    local: Dict[PodID, List[ContivRule]] = {}
    global_table: List[ContivRule] = []
    for rule in rules:
        tag = rule.tag
        rmt_ip = rule.rmt_ip
        if tag.endswith(SPLIT_TAG):
            if rmt_ip == _HALF2:
                continue  # merged into the 0.0.0.0/1 half
            rmt_ip = None
            tag = tag[: -len(SPLIT_TAG)]
        if tag.endswith(ANY_PROTOCOL_TAG):
            if rule.transport_proto is ProtocolType.UDP:
                continue  # merged into the TCP half
            protocol = ProtocolType.ANY
        else:
            protocol = rule.transport_proto
        if rule.scope == SCOPE_GLOBAL:
            contiv = ContivRule(
                action=Action.DENY if rule.action == ACTION_DENY else Action.PERMIT,
                src_network=rmt_ip,
                dst_network=rule.lcl_ip,
                protocol=protocol,
                src_port=rule.rmt_port,
                dst_port=rule.lcl_port,
            )
            global_table.append(contiv)
        else:
            pod = pod_by_ns_index(rule.appns_index)
            if pod is None:
                log.warning("no pod for appns %d; dropping %s", rule.appns_index, rule)
                continue
            contiv = ContivRule(
                action=Action.DENY if rule.action == ACTION_DENY else Action.PERMIT,
                src_network=rule.lcl_ip,
                dst_network=rmt_ip,
                protocol=protocol,
                src_port=rule.lcl_port,
                dst_port=rule.rmt_port,
            )
            local.setdefault(pod, []).append(contiv)
    return local, global_table


# ----------------------------------------------------------------- renderer


class SessionRuleRenderer(PolicyRendererAPI):
    """Renders ContivRules into host-stack session rules
    (vpptcp_renderer.go Renderer :35).

    Deps (vpptcp_renderer.go Deps :43):
    - ``channel``: the session-layer transport;
    - ``ns_index_for``: pod -> application-namespace index (the
      reference's IPv4Net.GetNsIndex);
    - ``pod_by_ns_index``: the reverse lookup, for resync import.
    """

    def __init__(
        self,
        channel: SessionRuleChannel,
        ns_index_for: Callable[[PodID], Optional[int]],
        pod_by_ns_index: Callable[[int], Optional[PodID]],
    ):
        self.channel = channel
        self.ns_index_for = ns_index_for
        self.pod_by_ns_index = pod_by_ns_index
        self.cache = RendererCache(Orientation.INGRESS)

    def new_txn(self, resync: bool) -> "SessionRendererTxn":
        return SessionRendererTxn(self, resync)

    # ----------------------------------------------------------------- export

    def _export_local(
        self,
        pod: PodID,
        rules: Sequence[ContivRule],
        pod_ip: Optional[ipaddress.IPv4Network],
    ) -> List[SessionRule]:
        ns_index = self.ns_index_for(pod)
        if ns_index is None:
            log.warning("no app namespace for pod %s; skipping its rules", pod)
            return []
        return export_session_rules(rules, pod_ip, ns_index, SCOPE_LOCAL)


class SessionRendererTxn(RendererTxn):
    """vpptcp_renderer.go RendererTxn: buffers Render() calls, then
    Commit() computes table diffs and ships minimal add/del batches."""

    def __init__(self, renderer: SessionRuleRenderer, resync: bool):
        self.renderer = renderer
        self.resync = resync
        self.cache_txn: CacheTxn = renderer.cache.new_txn()

    def render(self, pod, pod_ip, ingress, egress, removed=False):
        self.cache_txn.update(
            pod,
            PodConfig(
                pod_ip=pod_ip,
                ingress=tuple(ingress),
                egress=tuple(egress),
                removed=removed,
            ),
        )
        return self

    def commit(self) -> None:
        renderer = self.renderer
        added: List[SessionRule] = []
        removed: List[SessionRule] = []
        if self.resync:
            # Re-synchronize against the actually installed rules first.
            installed = [
                r for r in renderer.channel.dump() if r.tag.startswith(TAG_PREFIX)
            ]
            # Our local-scope rules whose app namespace maps to no known
            # pod are orphans (pod gone while we were down): the diff
            # below can never attribute them, so sweep them here.
            orphans = [
                r
                for r in installed
                if r.scope == SCOPE_LOCAL
                and renderer.pod_by_ns_index(r.appns_index) is None
            ]
            removed.extend(orphans)
            local, global_table = import_session_rules(
                [r for r in installed if r not in orphans],
                renderer.pod_by_ns_index,
            )
            renderer.cache.resync(
                {pod: tuple(rules) for pod, rules in local.items()},
                tuple(global_table),
            )
            # Pods known to the data plane but absent from the txn are gone.
            txn_pods = self.cache_txn.get_updated_pods()
            for pod in renderer.cache.get_all_pods() - txn_pods:
                self.cache_txn.update(pod, PodConfig(removed=True))

        changes = self.cache_txn.get_changes()
        for pod, (old, new) in changes.local.items():
            # The OLD table must be exported with the config it was
            # installed under (the committed one), the NEW with the
            # txn's — a removed pod has pod_ip=None in the txn, but its
            # installed rules were exported against its former IP.
            old_cfg = renderer.cache.get_pod_config(pod)
            new_cfg = self.cache_txn.get_pod_config(pod)
            old_ip = old_cfg.pod_ip if old_cfg is not None else None
            new_ip = new_cfg.pod_ip if new_cfg is not None else None
            old_rules = set(renderer._export_local(pod, old, old_ip))
            new_rules = set(renderer._export_local(pod, new, new_ip))
            added.extend(new_rules - old_rules)
            removed.extend(old_rules - new_rules)
        if changes.global_table is not None:
            old, new = changes.global_table
            old_rules = set(export_session_rules(old, None, 0, SCOPE_GLOBAL))
            new_rules = set(export_session_rules(new, None, 0, SCOPE_GLOBAL))
            added.extend(new_rules - old_rules)
            removed.extend(old_rules - new_rules)

        if added or removed:
            renderer.channel.apply(added, removed)
        self.cache_txn.commit(changes)
