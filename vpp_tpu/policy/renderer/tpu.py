"""TPU policy renderer — compiles pod rule tables for the classify kernel.

The 'tpu' renderer that plugs in behind the policy renderer boundary
(the BASELINE.json north star: a renderer alongside the reference's acl
and vpptcp renderers, plugins/policy/renderer/).  It maintains the
per-pod ingress/egress rule lists rendered by the configurator,
de-duplicates identical tables across pods (the reference ACL
renderer's table sharing, docs/dev-guide/POLICIES.md:394-400 — pods
with the same policy set share one table), and on every commit brings
the ``RuleTables`` tensors up to date INCREMENTALLY through a
persistent builder (ops/classify_delta).

Commit cost model: O(what changed) — dirty rule rows and pod slots are
patched in the host mirrors and shipped with a jitted scatter; the
first commit (and hysteresis shrink compactions) pays a full canonical
build; the classify program itself only recompiles when the pow2
rule-bucket size changes.  See docs/ARCHITECTURE.md "Table compile &
swap" for the full cost model.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ...models import PodID
from ...ops.classify import NO_TABLE, RuleTables, build_rule_tables
from ...ops.packets import ip_to_u32
from .api import ContivRule, PolicyRendererAPI, RendererTxn

log = logging.getLogger(__name__)


PodEntry = Tuple[int, Tuple[ContivRule, ...], Tuple[ContivRule, ...]]


def compile_pod_tables(pods: Dict[object, PodEntry]) -> RuleTables:
    """Compile pod→(ingress, egress) rule lists into device tensors with
    table sharing: identical rule lists intern to one table id (the
    reference ACL renderer's sharing, docs/dev-guide/POLICIES.md:394-400)."""
    table_ids: Dict[Tuple[ContivRule, ...], int] = {}
    tables: List[Tuple[ContivRule, ...]] = []

    def intern(rules: Tuple[ContivRule, ...]) -> int:
        if not rules:
            return NO_TABLE  # no rules = allow: skip table entirely
        tid = table_ids.get(rules)
        if tid is None:
            tid = len(tables)
            table_ids[rules] = tid
            tables.append(rules)
        return tid

    pod_assignments: Dict[int, Tuple[int, int]] = {}
    for _pod, (ip_u32, ingress, egress) in sorted(
        pods.items(), key=lambda kv: str(kv[0])
    ):
        pod_assignments[ip_u32] = (intern(ingress), intern(egress))
    return build_rule_tables(tables, pod_assignments)


class TpuPolicyRenderer(PolicyRendererAPI):
    """Keeps rendered pod tables; compiles tensors on commit."""

    def __init__(self, on_compiled: Optional[Callable[[RuleTables], None]] = None):
        from ...ops.classify_delta import AclTableBuilder

        # pod -> (pod_ip_u32, ingress rules, egress rules)
        self._pods: Dict[PodID, Tuple[int, Tuple[ContivRule, ...], Tuple[ContivRule, ...]]] = {}
        self._lock = threading.Lock()
        self._compiled: Optional[RuleTables] = None
        # Persistent incremental compiler: commits cost O(dirty keys).
        self._builder = AclTableBuilder()
        # Hook for the runtime: called with fresh tables after each commit.
        self._on_compiled = on_compiled

    # -------------------------------------------------------------- renderer

    def new_txn(self, resync: bool) -> "TpuRendererTxn":
        return TpuRendererTxn(self, resync)

    # --------------------------------------------------------------- queries

    @property
    def tables(self) -> Optional[RuleTables]:
        """The latest compiled tables (None until first commit)."""
        with self._lock:
            return self._compiled

    def stats(self) -> Dict[str, object]:
        with self._lock:
            compiled = self._compiled
            return {
                "pods": len(self._pods),
                "tables": compiled.num_tables if compiled else 0,
                "rules": compiled.num_rules if compiled else 0,
                "compile": self._builder.stats.as_dict(),
            }

    # ---------------------------------------------------------------- commit

    def _apply(self, changes, resync: bool) -> None:
        with self._lock:
            if resync:
                self._pods.clear()
            for pod, entry in changes.items():
                if entry is None:
                    self._pods.pop(pod, None)
                else:
                    self._pods[pod] = entry
            compiled = self._compile()
            self._compiled = compiled
        if self._on_compiled is not None:
            # Deliver the tables compiled by THIS commit (re-reading
            # self._compiled here could hand the hook a newer commit's
            # tables out of order).
            self._on_compiled(compiled)

    def _compile(self) -> RuleTables:
        compiled = self._builder.sync(self._pods)
        log.debug(
            "compiled %d rules in %d tables for %d pods "
            "(%d rows shipped this commit)",
            compiled.num_rules, compiled.num_tables, compiled.num_pods,
            self._builder.stats.last_rows_shipped,
        )
        return compiled


class TpuRendererTxn(RendererTxn):
    def __init__(self, renderer: TpuPolicyRenderer, resync: bool):
        self.renderer = renderer
        self.resync = resync
        self._changes: Dict[PodID, Optional[Tuple[int, Tuple[ContivRule, ...], Tuple[ContivRule, ...]]]] = {}

    def render(self, pod, pod_ip, ingress, egress, removed=False):
        if removed or pod_ip is None:
            self._changes[pod] = None
            return self
        ip_u32 = ip_to_u32(pod_ip.network_address)
        self._changes[pod] = (ip_u32, tuple(ingress), tuple(egress))
        return self

    def commit(self) -> None:
        self.renderer._apply(self._changes, self.resync)
