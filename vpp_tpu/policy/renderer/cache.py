"""Renderer cache — orientation-normalised local/global rule tables.

Analog of ``plugins/policy/renderer/cache/cache_impl.go``: renderers
that can only apply rules on ONE side of a connection (the session
renderer filters at connect()/accept() time, the reference's VPPTCP and
ACL renderers at one interface direction) feed per-pod ingress+egress
ContivRules through this cache, which re-orients them into

- one **local table** per pod, holding rules in the cache orientation
  (EGRESS: the pod's egress rules; INGRESS: the pod's ingress rules),
  with the opposite-direction rules of every other pod on the node
  *combined in* via allowed-port intersection
  (cache_impl.go installLocalRules :519), and
- one **global table** holding every pod's opposite-orientation rules
  narrowed to the pod's IP (installGlobalRules :638).

Local tables with identical content are shared between pods (the
reference's table sharing, docs/dev-guide/POLICIES.md:394-400), and
commits yield a minimal changeset (GetChanges :217).

Rules inside a table follow the ContivRule total order
(renderer/api.go Compare :110): a rule matching a subset of another's
traffic sorts first, so tables are directly usable for first-match.
"""

from __future__ import annotations

import functools
import ipaddress
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ...models import PodID, ProtocolType
from .api import Action, ContivRule

ANY_PORT = 0


class Orientation:
    EGRESS = "egress"
    INGRESS = "ingress"


# ---------------------------------------------------------------- rule order


def compare_ints(a: int, b: int) -> int:
    return -1 if a < b else (1 if a > b else 0)


def compare_ports(a: int, b: int) -> int:
    """Specific ports sort before ANY (utils.ComparePorts)."""
    if a == b:
        return 0
    if a == ANY_PORT:
        return 1
    if b == ANY_PORT:
        return -1
    return -1 if a < b else 1


def compare_ip_nets(
    a: Optional[ipaddress.IPv4Network], b: Optional[ipaddress.IPv4Network]
) -> int:
    """Total order on networks: subnets sort before their supernets;
    match-all (None) sorts last (utils.CompareIPNets)."""
    if a is None:
        return 0 if b is None else 1
    if b is None:
        return -1
    common = min(a.prefixlen, b.prefixlen)
    mask = (0xFFFFFFFF << (32 - common)) & 0xFFFFFFFF if common else 0
    if (int(a.network_address) & mask) == (int(b.network_address) & mask):
        # Same prefix: longer (more specific) mask first.
        return compare_ints(b.prefixlen, a.prefixlen)
    # Disjoint: longer mask first, then address bytes.
    order = compare_ints(b.prefixlen, a.prefixlen)
    if order != 0:
        return order
    return compare_ints(int(a.network_address), int(b.network_address))


# ProtocolType is IANA-numbered (ANY=0); the total order needs specific
# protocols before ANY (the reference enum has ANY last, api.go:170).
_PROTO_RANK = {
    ProtocolType.TCP: 0,
    ProtocolType.UDP: 1,
    ProtocolType.OTHER: 2,
    ProtocolType.ANY: 3,
}


def compare_rules(a: ContivRule, b: ContivRule) -> int:
    """The ContivRule total order (renderer/api.go Compare :110)."""
    order = compare_ip_nets(a.src_network, b.src_network)
    if order != 0:
        return order
    order = compare_ip_nets(a.dst_network, b.dst_network)
    if order != 0:
        return order
    order = compare_ints(_PROTO_RANK[a.protocol], _PROTO_RANK[b.protocol])
    if order != 0:
        return order
    if a.protocol is not ProtocolType.ANY:
        order = compare_ports(a.src_port, b.src_port)
        if order != 0:
            return order
        order = compare_ports(a.dst_port, b.dst_port)
        if order != 0:
            return order
    return compare_ints(int(a.action), int(b.action))


_RULE_KEY = functools.cmp_to_key(compare_rules)


def finalize_table(rules: List[ContivRule]) -> Tuple[ContivRule, ...]:
    """Dedup (first occurrence wins) and order by the rule total order —
    the collect-then-sort equivalent of the reference's per-insert
    ordered ContivRuleTable.InsertRule."""
    seen = set()
    out = []
    for rule in rules:
        if rule not in seen:
            seen.add(rule)
            out.append(rule)
    out.sort(key=_RULE_KEY)
    return tuple(out)


# ----------------------------------------------------------------- port sets


def ports_has(ports: Set[int], port: int) -> bool:
    return ANY_PORT in ports or port in ports


def ports_is_subset(p1: Set[int], p2: Set[int]) -> bool:
    if ANY_PORT in p2:
        return True
    if ANY_PORT in p1:
        return False
    return all(ports_has(p2, port) for port in p1)


def ports_intersection(p1: Set[int], p2: Set[int]) -> Set[int]:
    if ANY_PORT in p1:
        return p2
    if ANY_PORT in p2:
        return p1
    return p1 & p2


def _allowed_ports(
    ip: Optional[ipaddress.IPv4Network],
    rules: Sequence[ContivRule],
    network_of,
) -> Tuple[Set[int], Set[int], bool]:
    """Allowed destination (tcp, udp, any) ports for traffic involving
    ``ip``, per the rule list (cache/ports.go getAllowed*Ports: assumes
    configurator output — PERMITs plus at most one final deny-all)."""
    tcp: Set[int] = set()
    udp: Set[int] = set()
    any_proto = False
    has_deny = False
    for rule in rules:
        if rule.action is Action.DENY:
            has_deny = True
            continue
        net = network_of(rule)
        if net is not None and (ip is None or ip.network_address not in net):
            continue
        if rule.protocol is ProtocolType.TCP:
            tcp.add(rule.dst_port)
        elif rule.protocol is ProtocolType.UDP:
            udp.add(rule.dst_port)
        elif rule.protocol is ProtocolType.ANY:
            tcp.add(ANY_PORT)
            udp.add(ANY_PORT)
            any_proto = True
        # OTHER-protocol permits are ignored, matching the reference's
        # getAllowed*Ports switch (cache/ports.go), which has no case for
        # them — they must not wildcard the port intersection.
    if not has_deny:
        return {ANY_PORT}, {ANY_PORT}, True
    return tcp, udp, any_proto


def allowed_egress_ports(src_ip, egress):
    """Ports a source at ``src_ip`` may reach per these egress rules."""
    return _allowed_ports(src_ip, egress, lambda r: r.src_network)


def allowed_ingress_ports(dst_ip, ingress):
    """Ports reachable at ``dst_ip`` per these ingress rules."""
    return _allowed_ports(dst_ip, ingress, lambda r: r.dst_network)


# -------------------------------------------------------------------- tables


_ALLOW_ALL = ContivRule(action=Action.PERMIT)


@dataclass
class PodConfig:
    """Snapshot of one pod's rendered configuration (cache_impl.go
    PodConfig)."""

    pod_ip: Optional[ipaddress.IPv4Network] = None  # host /32
    ingress: Tuple[ContivRule, ...] = ()
    egress: Tuple[ContivRule, ...] = ()
    removed: bool = False


@dataclass
class CacheChanges:
    """Minimal changeset of one committed transaction."""

    # pod -> (original local-table rules, new local-table rules)
    local: Dict[PodID, Tuple[Tuple[ContivRule, ...], Tuple[ContivRule, ...]]] = field(
        default_factory=dict
    )
    global_table: Optional[
        Tuple[Tuple[ContivRule, ...], Tuple[ContivRule, ...]]
    ] = None


class RendererCache:
    """Committed state: pod configs + derived local/global tables."""

    def __init__(self, orientation: str = Orientation.INGRESS):
        self.orientation = orientation
        self.pod_configs: Dict[PodID, PodConfig] = {}
        self.local_tables: Dict[PodID, Tuple[ContivRule, ...]] = {}
        self.global_table: Tuple[ContivRule, ...] = ()

    def flush(self) -> None:
        self.pod_configs.clear()
        self.local_tables.clear()
        self.global_table = ()

    # ---------------------------------------------------------------- access

    def get_pod_config(self, pod: PodID) -> Optional[PodConfig]:
        return self.pod_configs.get(pod)

    def get_all_pods(self) -> Set[PodID]:
        return set(self.pod_configs)

    def get_isolated_pods(self) -> Set[PodID]:
        """Pods with a (non-empty) local table — K8s "isolated" pods."""
        return {pod for pod, rules in self.local_tables.items() if rules}

    def get_local_table_by_pod(self, pod: PodID) -> Optional[Tuple[ContivRule, ...]]:
        return self.local_tables.get(pod)

    def shared_tables(self) -> Dict[Tuple[ContivRule, ...], Set[PodID]]:
        """Distinct table contents -> pods sharing them."""
        shared: Dict[Tuple[ContivRule, ...], Set[PodID]] = {}
        for pod, rules in self.local_tables.items():
            shared.setdefault(rules, set()).add(pod)
        return shared

    def resync(
        self,
        local_tables: Dict[PodID, Tuple[ContivRule, ...]],
        global_table: Tuple[ContivRule, ...],
    ) -> None:
        """Replace cache content with state imported from the data plane
        (cache_impl.go Resync :99: configs cannot be reconstructed, but
        the pod set and tables can)."""
        self.flush()
        for pod, rules in local_tables.items():
            if rules:
                self.local_tables[pod] = tuple(rules)
            self.pod_configs[pod] = PodConfig()
        self.global_table = tuple(global_table)

    def new_txn(self) -> "CacheTxn":
        return CacheTxn(self)


class CacheTxn:
    """One cache transaction: buffered pod updates, tables rebuilt and
    diffed on commit."""

    def __init__(self, cache: RendererCache):
        self.cache = cache
        self.updated: Dict[PodID, PodConfig] = {}

    def update(self, pod: PodID, config: PodConfig) -> "CacheTxn":
        self.updated[pod] = config
        return self

    # ----------------------------------------------------------- txn queries

    def get_updated_pods(self) -> Set[PodID]:
        return set(self.updated)

    def get_pod_config(self, pod: PodID) -> Optional[PodConfig]:
        if pod in self.updated:
            return self.updated[pod]
        return self.cache.get_pod_config(pod)

    def get_all_pods(self) -> Set[PodID]:
        pods = self.cache.get_all_pods()
        for pod, cfg in self.updated.items():
            if cfg.removed:
                pods.discard(pod)
            else:
                pods.add(pod)
        return pods

    # ------------------------------------------------------- table building

    def _build_local_table(self, dst_pod: PodID) -> Tuple[ContivRule, ...]:
        """cache_impl.go buildLocalTable :469."""
        cfg = self.get_pod_config(dst_pod)
        if cfg is None or cfg.removed:
            return ()

        rules: List[ContivRule] = []
        own = cfg.egress if self.cache.orientation == Orientation.EGRESS else cfg.ingress
        for rule in own:
            rules.append(rule)

        for src_pod in self.get_all_pods():
            src_cfg = self.get_pod_config(src_pod)
            if src_cfg is not None:
                self._install_local_rules(rules, cfg, src_cfg)

        # Allow traffic not matched by any rule, unless an all-matching
        # rule is already present.
        if rules and not any(
            r.protocol is ProtocolType.ANY
            and r.dst_port == ANY_PORT
            and r.src_network is None
            and r.dst_network is None
            for r in rules
        ):
            rules.append(_ALLOW_ALL)
        return finalize_table(rules)

    def _install_local_rules(
        self, rules: List[ContivRule], dst_cfg: PodConfig, src_cfg: PodConfig
    ) -> None:
        """Combine the opposite-direction rules of ``src_cfg``'s pod into
        the local table of ``dst_cfg``'s pod via allowed-port
        intersection (cache_impl.go installLocalRules :519)."""
        egress_o = self.cache.orientation == Orientation.EGRESS
        if egress_o:
            src_tcp, src_udp, src_any = allowed_ingress_ports(
                dst_cfg.pod_ip, src_cfg.ingress
            )
            dst_tcp, dst_udp, dst_any = allowed_egress_ports(
                src_cfg.pod_ip, dst_cfg.egress
            )
        else:
            src_tcp, src_udp, src_any = allowed_egress_ports(
                dst_cfg.pod_ip, src_cfg.egress
            )
            dst_tcp, dst_udp, dst_any = allowed_ingress_ports(
                src_cfg.pod_ip, dst_cfg.ingress
            )

        if src_any:
            return

        if dst_any or not ports_is_subset(dst_tcp, src_tcp) or not ports_is_subset(
            dst_udp, src_udp
        ):
            src_ip = src_cfg.pod_ip
            if src_ip is None:
                return
            # Drop the rule subtree rooted at the source pod's /32.
            side = (lambda r: r.src_network) if egress_o else (lambda r: r.dst_network)
            rules[:] = [
                r
                for r in rules
                if not (
                    side(r) is not None
                    and side(r).prefixlen == 32
                    and side(r).network_address == src_ip.network_address
                )
            ]
            self._install_allowed_ports(
                rules, src_ip, ports_intersection(dst_tcp, src_tcp), ProtocolType.TCP
            )
            self._install_allowed_ports(
                rules, src_ip, ports_intersection(dst_udp, src_udp), ProtocolType.UDP
            )
            deny = ContivRule(
                action=Action.DENY,
                src_network=src_ip if egress_o else None,
                dst_network=None if egress_o else src_ip,
            )
            rules.append(deny)

    def _install_allowed_ports(
        self,
        rules: List[ContivRule],
        src_ip: ipaddress.IPv4Network,
        allowed: Set[int],
        protocol: ProtocolType,
    ) -> None:
        """cache_impl.go installAllowedPorts :590."""
        egress_o = self.cache.orientation == Orientation.EGRESS
        if ANY_PORT in allowed:
            rules.append(
                ContivRule(
                    action=Action.PERMIT,
                    src_network=src_ip if egress_o else None,
                    dst_network=None if egress_o else src_ip,
                    protocol=protocol,
                )
            )
            return
        for port in allowed:
            rules.append(
                ContivRule(
                    action=Action.PERMIT,
                    src_network=src_ip if egress_o else None,
                    dst_network=None if egress_o else src_ip,
                    protocol=protocol,
                    dst_port=port,
                )
            )

    def _rebuild_global_table(self) -> Tuple[ContivRule, ...]:
        """cache_impl.go rebuildGlobalTable :622."""
        rules: List[ContivRule] = []
        egress_o = self.cache.orientation == Orientation.EGRESS
        for pod in self.get_all_pods():
            cfg = self.get_pod_config(pod)
            if cfg is None or cfg.pod_ip is None:
                continue
            opposite = cfg.ingress if egress_o else cfg.egress
            for rule in opposite:
                if egress_o:
                    narrowed = ContivRule(
                        action=rule.action,
                        src_network=cfg.pod_ip,
                        dst_network=rule.dst_network,
                        protocol=rule.protocol,
                        src_port=rule.src_port,
                        dst_port=rule.dst_port,
                    )
                else:
                    narrowed = ContivRule(
                        action=rule.action,
                        src_network=rule.src_network,
                        dst_network=cfg.pod_ip,
                        protocol=rule.protocol,
                        src_port=rule.src_port,
                        dst_port=rule.dst_port,
                    )
                rules.append(narrowed)
        if rules:
            rules.append(_ALLOW_ALL)
        return finalize_table(rules)

    # ----------------------------------------------------------------- commit

    def get_changes(self) -> CacheChanges:
        """Minimal changeset of this txn (cache_impl.go GetChanges)."""
        changes = CacheChanges()
        affected = set(self.updated)
        # A pod's local table also depends on every other pod's opposite
        # rules; rebuild all to catch combination fallout.
        for pod in self.get_all_pods() | affected:
            old = self.cache.local_tables.get(pod, ())
            new = self._build_local_table(pod)
            if old != new:
                changes.local[pod] = (old, new)
        new_global = self._rebuild_global_table()
        if new_global != self.cache.global_table:
            changes.global_table = (self.cache.global_table, new_global)
        return changes

    def commit(self, changes: Optional[CacheChanges] = None) -> CacheChanges:
        if changes is None:
            changes = self.get_changes()
        for pod, (_, new) in changes.local.items():
            if new:
                self.cache.local_tables[pod] = new
            else:
                self.cache.local_tables.pop(pod, None)
        if changes.global_table is not None:
            self.cache.global_table = changes.global_table[1]
        for pod, cfg in self.updated.items():
            if cfg.removed:
                self.cache.pod_configs.pop(pod, None)
                self.cache.local_tables.pop(pod, None)
            else:
                self.cache.pod_configs[pod] = cfg
        self.updated.clear()
        return changes
