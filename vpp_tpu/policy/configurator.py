"""Policy configurator — translates sets of policies into ContivRules.

Analog of ``plugins/policy/configurator/configurator_impl.go``:

- ``generate_rules`` (:264): one direction's rule list for a set of
  policies — peer-pod one-host subnets, IPBlocks with except-CIDR
  subtraction, port combinations, allow-from-NAT-loopback, final
  deny-all.
- direction swap (Commit :196-200): policy *ingress* matches produce
  the pod's vswitch-*egress* table (traffic delivered to the pod) and
  policy *egress* matches the vswitch-*ingress* table.
- processed-set memoisation (Commit :146-210): pods sharing an
  identical policy set share one generated rule pair (the basis for
  table sharing downstream).
- ``subtract_subnet`` (:562): CIDR-minus-CIDR as a minimal set of
  non-overlapping CIDRs.
"""

from __future__ import annotations

import enum
import ipaddress
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..models import PodID, PolicyID, ProtocolType
from .cache import PolicyCache
from .renderer.api import (
    Action,
    ContivRule,
    PolicyRendererAPI,
    insert_rule,
)

log = logging.getLogger(__name__)


class MatchType(enum.Enum):
    """Direction of a match, from the *pod's* point of view."""

    INGRESS = "ingress"
    EGRESS = "egress"


class PolicyKind(enum.Enum):
    """Which directions the policy restricts (configurator PolicyType)."""

    INGRESS = "ingress"
    EGRESS = "egress"
    BOTH = "both"


@dataclass(frozen=True)
class Match:
    """One pre-resolved ingress/egress rule of a policy
    (configurator_api Match): label selectors already resolved by the
    processor to concrete peer pods; named ports to numbers."""

    type: MatchType
    # None = peers unspecified (match anything on L3);
    # empty tuple = peers specified but none matched (match nothing).
    pods: Optional[Tuple[PodID, ...]] = None
    ip_blocks: Optional[Tuple[Tuple[ipaddress.IPv4Network, Tuple[ipaddress.IPv4Network, ...]], ...]] = None
    # (protocol, port number) pairs; empty = all ports.
    ports: Tuple[Tuple[ProtocolType, int], ...] = ()


@dataclass(frozen=True)
class ContivPolicy:
    """A policy with pre-resolved matches (configurator_api ContivPolicy)."""

    id: PolicyID
    kind: PolicyKind
    matches: Tuple[Match, ...] = ()


def subtract_subnet(
    net1: ipaddress.IPv4Network, net2: ipaddress.IPv4Network
) -> List[ipaddress.IPv4Network]:
    """All IPs in net1 but not in net2, as non-overlapping CIDRs
    (configurator_impl.go subtractSubnet :562)."""
    if net1.prefixlen > net2.prefixlen:
        # net2 is higher in the tree: either covers net1 fully or not at all.
        return [] if net2.supernet_of(net1) else [net1]
    if net1.prefixlen == net2.prefixlen:
        return [] if net1 == net2 else [net1]
    if not net1.supernet_of(net2):
        return [net1]
    # net2 strictly inside net1: walk down the tree, emitting the sibling
    # of each step towards net2.
    result = []
    for bit in range(net1.prefixlen, net2.prefixlen):
        sibling_base = int(net2.network_address) ^ (1 << (31 - bit))
        sibling = ipaddress.ip_network((sibling_base, bit + 1), strict=False)
        result.append(ipaddress.ip_network((sibling.network_address, bit + 1)))
    return result


def one_host_subnet(ip: str) -> Optional[ipaddress.IPv4Network]:
    """Pod IP as a /32 (policy/utils GetOneHostSubnet)."""
    try:
        return ipaddress.ip_network(f"{ip}/32")
    except ValueError:
        return None


class PolicyConfigurator:
    """Translates per-pod policy sets to rules and drives the renderers
    (configurator_impl.go PolicyConfigurator)."""

    def __init__(self, cache: PolicyCache, ipam=None):
        self.cache = cache
        self.ipam = ipam  # for the NAT-loopback allow rule
        self.renderers: List[PolicyRendererAPI] = []
        # pod -> last known IP (to render removals after the pod is gone).
        self._pod_ips: Dict[PodID, ipaddress.IPv4Network] = {}

    def register_renderer(self, renderer: PolicyRendererAPI) -> None:
        self.renderers.append(renderer)

    # ------------------------------------------------------------------ txn

    def new_txn(self, resync: bool) -> "ConfiguratorTxn":
        return ConfiguratorTxn(self, resync)

    # ------------------------------------------------------- rule generation

    def generate_rules(
        self, direction: MatchType, policies: Sequence[ContivPolicy]
    ) -> List[ContivRule]:
        """One direction's rule list (generateRules :264).

        ``direction`` is the *policy* direction being implemented:
        INGRESS produces rules matching on source (who may reach the
        pod), EGRESS rules matching on destination.
        """
        rules: List[ContivRule] = []
        has_policy = False
        all_allowed = False

        for policy in sorted(policies, key=lambda p: p.id):
            if policy.kind is PolicyKind.INGRESS and direction is MatchType.EGRESS:
                continue
            if policy.kind is PolicyKind.EGRESS and direction is MatchType.INGRESS:
                continue
            has_policy = True

            for match in policy.matches:
                if match.type is not direction:
                    continue

                # Resolve peer pods to one-host subnets.
                peer_nets: List[ipaddress.IPv4Network] = []
                for peer in match.pods or ():
                    peer_data = self.cache.lookup_pod(peer)
                    if peer_data is None or not peer_data.ip_address:
                        continue
                    net = one_host_subnet(peer_data.ip_address)
                    if net is not None:
                        peer_nets.append(net)

                # Expand IPBlocks minus their excepts.
                block_nets: List[ipaddress.IPv4Network] = []
                for block, excepts in match.ip_blocks or ():
                    subnets = [block]
                    for exc in excepts:
                        subnets = [
                            out for net in subnets for out in subtract_subnet(net, exc)
                        ]
                    block_nets.extend(subnets)

                if match.pods is None and match.ip_blocks is None:
                    # Unspecified peers = anything on L3.
                    if not match.ports:
                        insert_rule(rules, ContivRule(action=Action.PERMIT))
                        all_allowed = True
                    else:
                        for proto, port in match.ports:
                            insert_rule(
                                rules,
                                ContivRule(
                                    action=Action.PERMIT,
                                    protocol=proto,
                                    dst_port=port,
                                ),
                            )

                for net in peer_nets + block_nets:
                    src = net if direction is MatchType.INGRESS else None
                    dst = net if direction is MatchType.EGRESS else None
                    if not match.ports:
                        insert_rule(
                            rules,
                            ContivRule(
                                action=Action.PERMIT,
                                src_network=src,
                                dst_network=dst,
                            ),
                        )
                    else:
                        for proto, port in match.ports:
                            insert_rule(
                                rules,
                                ContivRule(
                                    action=Action.PERMIT,
                                    src_network=src,
                                    dst_network=dst,
                                    protocol=proto,
                                    dst_port=port,
                                ),
                            )

        if has_policy and not all_allowed:
            if direction is MatchType.INGRESS and self.ipam is not None:
                # Allow the virtual NAT loopback (a pod accessing a service
                # load-balanced back to itself; generateRules :447).
                nat_net = one_host_subnet(str(self.ipam.nat_loopback_ip()))
                insert_rule(
                    rules,
                    ContivRule(action=Action.PERMIT, src_network=nat_net),
                )
            insert_rule(rules, ContivRule(action=Action.DENY))

        return rules


@dataclass
class _PendingConfig:
    policies: Tuple[ContivPolicy, ...]


class ConfiguratorTxn:
    """One configurator transaction (PolicyConfiguratorTxn)."""

    def __init__(self, configurator: PolicyConfigurator, resync: bool):
        self.configurator = configurator
        self.resync = resync
        self._config: Dict[PodID, Tuple[ContivPolicy, ...]] = {}

    def configure(self, pod: PodID, policies: Sequence[ContivPolicy]) -> "ConfiguratorTxn":
        """Replace the set of policies assigned to a pod (order-free)."""
        self._config[pod] = tuple(policies)
        return self

    def commit(self) -> None:
        cfg = self.configurator
        pod_ips = {} if self.resync else dict(cfg._pod_ips)

        # Memoise rule generation per policy set (Commit :146).  The key is
        # the full resolved-policy content, not just the IDs: named-port
        # resolution makes matches per-pod, so pods only share generated
        # rules when their resolved matches are truly identical (the
        # reference keys on IDs only and hands every pod the first pod's
        # rules — a named-port defect not worth inheriting).
        processed: Dict[Tuple[ContivPolicy, ...], Tuple[List[ContivRule], List[ContivRule]]] = {}

        renderer_txns = [r.new_txn(self.resync) for r in cfg.renderers]
        for pod, policies in sorted(self._config.items()):
            pod_data = cfg.cache.lookup_pod(pod)
            removed = pod_data is None or not pod_data.ip_address
            if removed:
                had_ip = pod in pod_ips
                pod_ip = pod_ips.pop(pod, None)
                if not had_ip:
                    continue  # already unconfigured
                ingress: List[ContivRule] = []
                egress: List[ContivRule] = []
            else:
                pod_ip = one_host_subnet(pod_data.ip_address)
                if pod_ip is None:
                    log.warning("pod %s has invalid IP %r", pod, pod_data.ip_address)
                    continue
                pod_ips[pod] = pod_ip
                key = tuple(sorted(policies, key=lambda p: p.id))
                if key in processed:
                    ingress, egress = processed[key]
                else:
                    # Direction swap: policy-ingress -> vswitch-egress table.
                    egress = cfg.generate_rules(MatchType.INGRESS, policies)
                    ingress = cfg.generate_rules(MatchType.EGRESS, policies)
                    processed[key] = (ingress, egress)

            for txn in renderer_txns:
                txn.render(pod, pod_ip, list(ingress), list(egress), removed=removed)

        errors = []
        for txn in renderer_txns:
            try:
                txn.commit()
            except Exception as e:  # noqa: BLE001 - keep other renderers going
                errors.append(e)
        cfg._pod_ips = pod_ips
        if errors:
            raise errors[0]
