"""Policy processor — decides which pods need (re)configuration and
resolves policies into pre-computed matches.

Analog of ``plugins/policy/processor``:

- ``calculate_matches`` (matches_calculator.go :14): per (policy, pod)
  resolution of label selectors to concrete peer pod IDs, IPBlock
  parsing, and named-port resolution — ingress named ports resolve
  against the *target* pod's container ports, egress named ports expand
  into extra per-peer-pod matches (portNameToNumber :197).
- ``process`` (processor.go Process :73): re-run the
  configurator for a set of possibly-outdated pods.
- affected-pod computation on pod/policy/namespace changes
  (getPoliciesReferencingPod :378) — conservatively widened to all
  policy-holding pods for peer-affecting changes, matching the
  reference's own "possibly outdated" over-approximation.
"""

from __future__ import annotations

import ipaddress
import logging
from typing import List, Optional, Sequence, Set, Tuple

from ..models import (
    Namespace,
    Pod,
    PodID,
    Policy,
    PolicyType,
    ProtocolType,
)
from .cache import PolicyCache
from .configurator import (
    ContivPolicy,
    Match,
    MatchType,
    PolicyConfigurator,
    PolicyKind,
)

log = logging.getLogger(__name__)


def _policy_kind(policy: Policy) -> PolicyKind:
    if policy.applies_to_ingress and policy.applies_to_egress:
        return PolicyKind.BOTH
    if policy.applies_to_egress:
        return PolicyKind.EGRESS
    return PolicyKind.INGRESS


class PolicyProcessor:
    """Drives the configurator from resolved policy data."""

    def __init__(self, cache: PolicyCache, configurator: PolicyConfigurator):
        self.cache = cache
        self.configurator = configurator
        # Pods that currently have at least one policy configured, so we
        # know when to render a policy *removal*.
        self._pods_with_policy: Set[PodID] = set()

    # ------------------------------------------------------------ resolution

    def calculate_matches(self, policy: Policy, pod_id: PodID) -> List[Match]:
        """Resolve one policy's rules for one target pod."""
        matches: List[Match] = []
        namespace = policy.namespace

        for rule in policy.ingress_rules:
            peers, blocks = self._resolve_peers(namespace, rule.from_peers)
            ports: List[Tuple[ProtocolType, int]] = []
            for p in rule.ports:
                if isinstance(p.port, str):
                    # Named ingress port: resolve on the target pod.
                    pod = self.cache.lookup_pod(pod_id)
                    for number in _named_ports(pod, p.port):
                        ports.append((p.protocol, number))
                else:
                    ports.append((p.protocol, int(p.port or 0)))
            if rule.ports and not ports:
                # The rule restricts ports but none resolved on this pod:
                # it matches no traffic — emitting ports=() here would
                # wrongly mean "all ports".
                continue
            matches.append(
                Match(type=MatchType.INGRESS, pods=peers, ip_blocks=blocks, ports=tuple(ports))
            )

        for rule in policy.egress_rules:
            peers, blocks = self._resolve_peers(namespace, rule.to_peers)
            ports = []
            for p in rule.ports:
                if isinstance(p.port, str):
                    # Named egress port: expands into one match per peer pod
                    # that defines it (matches_calculator.go :172-185).
                    # peers None = unrestricted -> resolve against all pods;
                    # peers () = selector matched nothing -> no candidates.
                    if peers is None:
                        candidates = tuple(pod.id for pod in self.cache.all_pods())
                    else:
                        candidates = peers
                    for peer_id in candidates:
                        peer = self.cache.lookup_pod(peer_id)
                        for number in _named_ports(peer, p.port):
                            matches.append(
                                Match(
                                    type=MatchType.EGRESS,
                                    pods=(peer_id,),
                                    ip_blocks=(),
                                    ports=((p.protocol, number),),
                                )
                            )
                else:
                    ports.append((p.protocol, int(p.port or 0)))
            if rule.ports and not ports:
                # All ports were named (already expanded per peer above, or
                # unresolvable): the residual match would mean "all ports".
                continue
            matches.append(
                Match(type=MatchType.EGRESS, pods=peers, ip_blocks=blocks, ports=tuple(ports))
            )

        return matches

    def _resolve_peers(self, namespace: str, peers) -> Tuple[
        Optional[Tuple[PodID, ...]],
        Optional[Tuple[Tuple[ipaddress.IPv4Network, Tuple[ipaddress.IPv4Network, ...]], ...]],
    ]:
        """Peers -> (pod IDs, IP blocks); (None, None) when unrestricted."""
        if not peers:
            return None, None
        pod_ids: List[PodID] = []
        blocks: List[Tuple[ipaddress.IPv4Network, Tuple[ipaddress.IPv4Network, ...]]] = []
        for peer in peers:
            if peer.pods is not None:
                pod_ids.extend(p.id for p in self.cache.pods_matching_selector(namespace, peer.pods))
            if peer.namespaces is not None:
                pod_ids.extend(p.id for p in self.cache.pods_matching_namespace_selector(peer.namespaces))
            if peer.ip_block is not None:
                try:
                    net = ipaddress.ip_network(peer.ip_block.cidr, strict=False)
                    excepts = tuple(
                        ipaddress.ip_network(e, strict=False)
                        for e in peer.ip_block.except_cidrs
                    )
                except ValueError:
                    log.warning("ignoring malformed IPBlock %r", peer.ip_block)
                    continue
                blocks.append((net, excepts))
        # Dedup while keeping deterministic order.
        seen: Set[PodID] = set()
        unique = tuple(p for p in pod_ids if not (p in seen or seen.add(p)))
        return unique, tuple(blocks)

    # -------------------------------------------------------------- process

    def process(self, pods: Sequence[PodID], resync: bool = False) -> None:
        """Re-run the configurator for possibly-outdated pods."""
        txn = self.configurator.new_txn(resync)
        touched = False
        for pod_id in pods:
            pod = self.cache.lookup_pod(pod_id)
            policies: List[ContivPolicy] = []
            if pod is not None:
                for policy in sorted(self.cache.policies_selecting_pod(pod), key=lambda p: p.id):
                    policies.append(
                        ContivPolicy(
                            id=policy.id,
                            kind=_policy_kind(policy),
                            matches=tuple(self.calculate_matches(policy, pod_id)),
                        )
                    )
            if policies:
                self._pods_with_policy.add(pod_id)
            elif pod_id in self._pods_with_policy or resync:
                self._pods_with_policy.discard(pod_id)
            elif pod is not None:
                continue  # never had policies; nothing to render
            txn.configure(pod_id, policies)
            touched = True
        if touched or resync:
            txn.commit()

    def resync(self, kube_state) -> None:
        self.cache.resync(kube_state)
        self._pods_with_policy.clear()
        self.process([pod.id for pod in self.cache.all_pods()], resync=True)

    # ------------------------------------------------------- event reactions

    def on_pod_change(self, old: Optional[Pod], new: Optional[Pod]) -> None:
        affected: Set[PodID] = set()
        changed = new if new is not None else old
        if changed is not None:
            affected.add(changed.id)
        # The changed pod may appear as a *peer* in rules of any pod that
        # has policies (cross-namespace via namespace selectors).
        affected.update(self._pods_with_policy)
        # Pods newly selected by policies because of label changes.
        if new is not None:
            for policy in self.cache.policies_selecting_pod(new):
                affected.update(
                    p.id for p in self.cache.pods_matching_selector(policy.namespace, policy.pods)
                )
        self.process(sorted(affected))

    def on_policy_change(self, old: Optional[Policy], new: Optional[Policy]) -> None:
        affected: Set[PodID] = set()
        for policy in (old, new):
            if policy is None:
                continue
            affected.update(
                p.id for p in self.cache.pods_matching_selector(policy.namespace, policy.pods)
            )
        # Pods that *had* the old policy but are no longer selected.
        affected.update(self._pods_with_policy)
        self.process(sorted(affected))

    def on_namespace_change(self, old: Optional[Namespace], new: Optional[Namespace]) -> None:
        # Namespace labels affect peer resolution everywhere.
        affected: Set[PodID] = set(self._pods_with_policy)
        ns = new if new is not None else old
        if ns is not None:
            affected.update(p.id for p in self.cache.pods_in_namespace(ns.name))
        self.process(sorted(affected))


def _named_ports(pod: Optional[Pod], name: str) -> List[int]:
    out: List[int] = []
    if pod is None:
        return out
    for container in pod.containers:
        for port in container.ports:
            if port.name == name:
                out.append(port.container_port)
    return out
