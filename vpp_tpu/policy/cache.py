"""Policy cache — indexed view of pods / policies / namespaces with
label-selector matching.

Analog of ``plugins/policy/cache`` (cache_impl.go + match_expression.go
+ the idxmap indexes): keeps the policy-relevant slice of KubeState
indexed for the lookups the processor needs.  The reference implements
selector matching as set intersections over label indexes; the
per-object predicate here is semantically identical (K8s semantics:
NOT_IN and DOES_NOT_EXIST also match objects lacking the key) and is
verified against the same corpus of cases
(cache/match_expression_test.go).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..models import (
    Endpoints,
    ExpressionOperator,
    LabelSelector,
    Namespace,
    Pod,
    PodID,
    Policy,
    PolicyID,
)


def selector_matches(selector: Optional[LabelSelector], labels) -> bool:
    """Evaluate a label selector against a label mapping.

    ``None`` (nil selector) matches nothing; the empty selector matches
    everything (policy.proto LabelSelector doc).  match_labels and all
    match_expressions are ANDed.
    """
    if selector is None:
        return False
    for key, value in selector.match_labels.items():
        if labels.get(key) != value:
            return False
    for expr in selector.match_expressions:
        has = expr.key in labels
        if expr.operator is ExpressionOperator.IN:
            if not has or labels[expr.key] not in expr.values:
                return False
        elif expr.operator is ExpressionOperator.NOT_IN:
            if has and labels[expr.key] in expr.values:
                return False
        elif expr.operator is ExpressionOperator.EXISTS:
            if not has:
                return False
        elif expr.operator is ExpressionOperator.DOES_NOT_EXIST:
            if has:
                return False
    return True


class PolicyCache:
    """The indexed state. Fed by the policy plugin from KubeState."""

    def __init__(self):
        self._pods: Dict[PodID, Pod] = {}
        self._policies: Dict[PolicyID, Policy] = {}
        self._namespaces: Dict[str, Namespace] = {}
        self._pods_by_ns: Dict[str, Set[PodID]] = {}

    # ----------------------------------------------------------------- feeds

    def resync(self, kube_state) -> None:
        self._pods.clear()
        self._policies.clear()
        self._namespaces.clear()
        self._pods_by_ns.clear()
        for pod in kube_state.get("pod", {}).values():
            self.update_pod(pod)
        for policy in kube_state.get("policy", {}).values():
            self.update_policy(policy)
        for ns in kube_state.get("namespace", {}).values():
            self.update_namespace(ns)

    def update_pod(self, pod: Pod) -> Optional[Pod]:
        old = self._pods.get(pod.id)
        self._pods[pod.id] = pod
        self._pods_by_ns.setdefault(pod.namespace, set()).add(pod.id)
        return old

    def delete_pod(self, pod_id: PodID) -> Optional[Pod]:
        old = self._pods.pop(pod_id, None)
        if old is not None:
            self._pods_by_ns.get(pod_id.namespace, set()).discard(pod_id)
        return old

    def update_policy(self, policy: Policy) -> Optional[Policy]:
        old = self._policies.get(policy.id)
        self._policies[policy.id] = policy
        return old

    def delete_policy(self, policy_id: PolicyID) -> Optional[Policy]:
        return self._policies.pop(policy_id, None)

    def update_namespace(self, ns: Namespace) -> Optional[Namespace]:
        old = self._namespaces.get(ns.name)
        self._namespaces[ns.name] = ns
        return old

    def delete_namespace(self, name: str) -> Optional[Namespace]:
        return self._namespaces.pop(name, None)

    # --------------------------------------------------------------- lookups

    def lookup_pod(self, pod_id: PodID) -> Optional[Pod]:
        return self._pods.get(pod_id)

    def lookup_policy(self, policy_id: PolicyID) -> Optional[Policy]:
        return self._policies.get(policy_id)

    def all_pods(self) -> List[Pod]:
        return list(self._pods.values())

    def all_policies(self) -> List[Policy]:
        return list(self._policies.values())

    def pods_in_namespace(self, namespace: str) -> List[Pod]:
        return [self._pods[pid] for pid in self._pods_by_ns.get(namespace, ())]

    # ------------------------------------------------------------- selectors

    def pods_matching_selector(
        self, namespace: str, selector: Optional[LabelSelector]
    ) -> List[Pod]:
        """Pods in ``namespace`` matched by a pod label selector
        (cache getPodsByNSLabelSelector / getMatchExpressionPodsInsideNs)."""
        if selector is None:
            return []
        return [
            pod
            for pod in self.pods_in_namespace(namespace)
            if selector_matches(selector, pod.labels)
        ]

    def namespaces_matching_selector(
        self, selector: Optional[LabelSelector]
    ) -> List[Namespace]:
        """Namespaces matched by a cluster-scoped label selector."""
        if selector is None:
            return []
        return [
            ns
            for ns in self._namespaces.values()
            if selector_matches(selector, ns.labels)
        ]

    def pods_matching_namespace_selector(
        self, selector: Optional[LabelSelector]
    ) -> List[Pod]:
        """All pods of all namespaces matched by a namespace selector
        (policy.proto Peer.namespaces semantics)."""
        out: List[Pod] = []
        for ns in self.namespaces_matching_selector(selector):
            out.extend(self.pods_in_namespace(ns.name))
        return out

    def policies_selecting_pod(self, pod: Pod) -> List[Policy]:
        """Policies whose ``pods`` selector covers the pod — only policies
        in the pod's own namespace apply (processor getPoliciesReferencingPod
        :378)."""
        return [
            pol
            for pol in self._policies.values()
            if pol.namespace == pod.namespace
            and selector_matches(pol.pods, pod.labels)
        ]
