from .pci import DeviceInfo, device_info, driver_bind, driver_unbind

__all__ = ["DeviceInfo", "device_info", "driver_bind", "driver_unbind"]
