"""sysfs PCI driver bind/unbind.

Analog of ``pkg/pci/pci.go`` (DriverBind :40, DriverUnbind :96): moves
a NIC between kernel drivers through the sysfs PCI interface, used by
the bootstrap path to hand the uplink to a kernel-bypass driver before
the batch shim takes it over (the reference binds vmxnet3 uplinks to
vfio-pci before giving them to DPDK, cmd/contiv-init/main.go:359).

The sysfs root is injectable so tests (and containerised agents with
an alternate /sys mount) can point elsewhere.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

log = logging.getLogger(__name__)

SYS_BUS_PCI = "/sys/bus/pci"


class PCIError(RuntimeError):
    pass


@dataclass(frozen=True)
class DeviceInfo:
    """Identity of one PCI device."""

    address: str        # e.g. "0000:00:08.0"
    vendor_id: int
    device_id: int
    driver: Optional[str]  # currently bound driver, if any


def _read(path: Path) -> str:
    try:
        return path.read_text().strip()
    except OSError as exc:
        raise PCIError(f"error reading {path}: {exc}") from exc


def _write(path: Path, content: str) -> None:
    log.debug("writing %r into %s", content, path)
    try:
        with open(path, "w") as f:
            f.write(content)
    except OSError as exc:
        raise PCIError(f"error writing to {path}: {exc}") from exc


def device_info(pci_addr: str, sys_bus_pci: str = SYS_BUS_PCI) -> DeviceInfo:
    """Read a device's vendor/device IDs and current driver binding."""
    dev = Path(sys_bus_pci) / "devices" / pci_addr
    vendor = int(_read(dev / "vendor"), 16)
    device = int(_read(dev / "device"), 16)
    driver_link = dev / "driver"
    driver = None
    if driver_link.exists():
        driver = os.path.basename(os.path.realpath(driver_link))
    return DeviceInfo(address=pci_addr, vendor_id=vendor, device_id=device, driver=driver)


def driver_unbind(pci_addr: str, sys_bus_pci: str = SYS_BUS_PCI) -> None:
    """Unbind the device from its current driver (DriverUnbind :96)."""
    log.info("unbinding %s from its current driver", pci_addr)
    unbind = Path(sys_bus_pci) / "devices" / pci_addr / "driver" / "unbind"
    _write(unbind, pci_addr)


def driver_bind(pci_addr: str, driver: str, sys_bus_pci: str = SYS_BUS_PCI) -> None:
    """Bind the device to ``driver`` (DriverBind :40).

    Mirrors the reference's tolerances: binding to the already-bound
    driver is a no-op; a failed unbind is ignored (the device may be
    unbound already); new_id/bind write failures are non-fatal (some
    kernels report an error even when the bind takes effect).
    """
    root = Path(sys_bus_pci)
    driver_dir = root / "drivers" / driver
    if not driver_dir.exists():
        raise PCIError(f"{driver} driver is not loaded")

    if (driver_dir / pci_addr).exists():
        log.info("%s already bound to driver %s", pci_addr, driver)
        return

    try:
        driver_unbind(pci_addr, sys_bus_pci)
    except PCIError:
        pass  # may not be bound to anything

    log.info("binding %s to driver %s", pci_addr, driver)
    info = device_info(pci_addr, sys_bus_pci)

    # Teach the driver the (vendor, device) pair, then bind explicitly.
    try:
        _write(driver_dir / "new_id", f"{info.vendor_id:4x} {info.device_id:4x}")
    except PCIError as exc:
        log.warning("(non-fatal) %s", exc)
    try:
        _write(driver_dir / "bind", pci_addr)
    except PCIError as exc:
        log.warning("(non-fatal) %s", exc)
