from .scheduler import (
    TxnScheduler,
    Applicator,
    ValueState,
    ValueStatus,
    DependencyFn,
)

__all__ = [
    "TxnScheduler",
    "Applicator",
    "ValueState",
    "ValueStatus",
    "DependencyFn",
]
