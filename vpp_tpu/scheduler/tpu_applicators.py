"""TPU device-table applicators — the southbound backends that own
rule-tensor recompiles.

Round-1 verdict item 4: renderers used to recompile device tables
directly inside their commit, bypassing the txn scheduler, so the
reference's guarantee — one atomic, retried, dependency-ordered
transaction per event covering ALL southbound state
(plugins/controller/txn.go:28-83) — did not hold for the most important
backend.  Now the renderers emit plain KVs into the event transaction
(policy/renderer/sched.py, service/renderer/sched.py) and these
applicators compile them into device tensors, with:

- ONE atomic table swap per transaction: CRUD calls mark state dirty;
  the compile + swap happens in ``end_txn()`` (the scheduler brackets
  every commit/retry/replay with begin/end).
- scheduler-managed retries: a failed compile leaves the affected keys
  FAILED and retried with backoff like any other southbound value.
- resync semantics for free: a resync txn that no longer mentions a
  pod/service key deletes it here, exactly like host-FIB keys.

Keyspace (under the scheduler's longest-prefix applicator routing):

    tpu/acl/pod/<namespace>/<name>   -> (pod_ip_u32, ingress, egress)
    tpu/nat/global                   -> NatGlobalConfig
    tpu/nat/service/<namespace>/<name> -> tuple of NatMapping
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..ops.classify import RuleTables
# In-network inference keyspace (ISSUE 14) — canonical definitions in
# ops/infer_delta (the builder owns the key shapes), re-exported here
# beside the ACL/NAT prefixes the scheduler routes on.
from ..ops.infer import InferTable
from ..ops.infer_delta import (
    INFER_MODEL_KEY,
    INFER_POD_PREFIX,
    INFER_PREFIX,
)
from ..ops.nat import NatMapping, NatTables
from ..telemetry import record_stage
from .scheduler import Applicator

ACL_POD_PREFIX = "tpu/acl/pod/"
NAT_PREFIX = "tpu/nat/"
NAT_GLOBAL_KEY = "tpu/nat/global"
NAT_SERVICE_PREFIX = "tpu/nat/service/"


@dataclasses.dataclass(frozen=True)
class NatGlobalConfig:
    """The NAT44 global knobs (nat44_renderer.go Resync's global part):
    SNAT address pool, the NAT loopback, and the pod subnet the SNAT
    feature exempts."""

    nat_loopback: str = "0.0.0.0"
    snat_ip: str = "0.0.0.0"
    snat_enabled: bool = False
    pod_subnet: str = "10.1.0.0/16"


def _fp_fold_device(arr_leaves: tuple, plan: tuple):
    """The fused fingerprint program: per-leaf uint32 wrap-sums folded
    ON DEVICE with the static shape/aux constants, returning ONE uint32
    scalar.  ``plan`` is static: ``(is_array, const)`` per pytree leaf
    (const = hash(shape) for arrays, hash(leaf) otherwise)."""
    import jax.numpy as jnp

    from ..ops.delta import FP_PRIME, FP_SEED

    fp = jnp.uint32(FP_SEED)
    it = iter(arr_leaves)
    for is_array, const in plan:
        fp = fp * jnp.uint32(FP_PRIME)
        if is_array:
            arr = next(it)
            if arr.dtype == jnp.bool_:
                arr = arr.astype(jnp.uint32)
            elif arr.dtype.kind == "f":
                arr = (
                    arr.view(jnp.uint32) if arr.dtype.itemsize == 4
                    else arr.astype(jnp.uint32)
                )
            else:
                arr = arr.astype(jnp.uint32)
            fp = fp ^ jnp.sum(arr, dtype=jnp.uint32) ^ jnp.uint32(const)
        else:
            fp = fp ^ jnp.uint32(const)
    return fp


_fp_fold_jit = None  # lazily jitted (keeps module import light)


def table_fingerprint(tables: Any) -> int:
    """Content checksum of a compiled table pytree, computed ON DEVICE
    as ONE fused reduction returning a single uint32 scalar — exactly
    one host transfer per fingerprint.  (The per-leaf ``int(jnp.sum)``
    predecessor did one device→host sync per leaf; NOTES_r05 measured
    that flipping a remote TPU tunnel into its ~100x degraded d2h
    mode.)  uint32 wrap-sums are permutation-invariant per leaf and
    ADDITIVE, so the incremental builders maintain the expected-side
    value on the host (ops/delta.fold_fingerprint — the two folds are
    property-tested equal).  Equal content → equal fingerprint on any
    placement: retargeting (aux-only) and mesh re-sharding preserve it,
    so the drift check compares what the data plane actually holds
    against what the scheduler last compiled."""
    import jax
    import jax.numpy as jnp

    global _fp_fold_jit
    if _fp_fold_jit is None:
        _fp_fold_jit = jax.jit(_fp_fold_device, static_argnums=(1,))

    plan = []
    arrs = []
    for leaf in jax.tree_util.tree_leaves(tables):
        if hasattr(leaf, "dtype"):
            arrs.append(jnp.asarray(leaf))
            plan.append((True, hash(tuple(leaf.shape)) & 0xFFFFFFFF))
        else:
            plan.append((False, hash(leaf) & 0xFFFFFFFF))
    return int(_fp_fold_jit(tuple(arrs), tuple(plan)))


class _CompilingApplicator(Applicator):
    """Shared begin/end-txn bracket: subclasses mutate ``_state`` in
    create/update/delete and compile once per transaction."""

    # Short stage label for propagation spans ("compile:acl" etc.);
    # subclasses override.
    telemetry_name = "tables"

    def __init__(self, on_compiled: Optional[Callable[[Any], None]] = None,
                 installed_fn: Optional[Callable[[], Any]] = None):
        self._state: Dict[str, Any] = {}
        self._dirty = False
        self._compiled: Any = None
        self._lock = threading.Lock()
        # Public hook: called with the freshly-compiled tables after each
        # transaction's atomic swap (the datapath runner attaches here).
        self.on_compiled = on_compiled
        # Readback hook for drift detection: returns the tables the
        # data plane is ACTUALLY running (runner.acl / runner.nat).
        self.installed_fn = installed_fn
        self.compile_count = 0  # atomic-swap observability for tests/metrics
        # True while a compiled artifact has not (yet) been swapped into
        # the data plane: set before each on_compiled call, cleared on
        # success.  A swap that fails (runner TableSwapError — the
        # tables were rolled back to last-good) leaves it set, so the
        # scheduler's retry re-attempts the SWAP even though the state
        # is no longer dirty (the retry's _try_apply sees applied ==
        # desired and issues no CRUD call, so without this flag the
        # recompiled-but-never-installed tables would be stranded).
        self._swap_pending = False

    update_destroys_on_failure = False  # swaps are atomic in-place updates

    def create(self, key: str, value: Any) -> None:
        with self._lock:
            self._state[key] = value
            self._dirty = True
            self._keyset_changed(key)

    def update(self, key: str, old_value: Any, new_value: Any) -> None:
        with self._lock:
            self._state[key] = new_value
            self._dirty = True

    def delete(self, key: str, value: Any) -> None:
        with self._lock:
            self._state.pop(key, None)
            self._dirty = True
            self._keyset_changed(key)

    def _keyset_changed(self, key: str) -> None:
        """Hook: a key appeared/disappeared (updates keep the keyset).
        Subclasses caching key-order artifacts invalidate here."""

    def begin_txn(self) -> None:
        pass

    def end_txn(self) -> None:
        with self._lock:
            # Compile when state changed — or on the very first
            # transaction, so empty tables exist from the first resync on
            # (the data plane must never see None tables).  A pending
            # swap (an earlier on_compiled failed and rolled back)
            # re-fires with the cached compile even when nothing is
            # dirty — that is the scheduler-retry path for swap faults.
            if not self._dirty and self._compiled is not None \
                    and not self._swap_pending:
                return
            if self._dirty or self._compiled is None:
                # Propagation span: the compile stage, labelled with
                # whether the PERSISTENT builder took the O(changed)
                # delta path or fell back to a full rebuild (PR 2's
                # compile stats, read before/after so one stage = one
                # compile's mode, not the lifetime totals).
                builder = getattr(self, "_builder", None)
                full0 = builder.stats.full_builds if builder else 0
                delta0 = builder.stats.delta_builds if builder else 0
                t0 = time.perf_counter()
                self._compiled = self._compile(dict(self._state))
                dt = time.perf_counter() - t0
                if builder is not None and \
                        builder.stats.delta_builds > delta0:
                    mode = "delta"
                elif builder is not None and \
                        builder.stats.full_builds > full0:
                    mode = "full"
                else:
                    mode = "direct"  # test subclasses compiling inline
                record_stage(f"compile:{self.telemetry_name}", dt, mode=mode)
                self._dirty = False
                self.compile_count += 1
            compiled = self._compiled
            self._swap_pending = self.on_compiled is not None
        if self.on_compiled is not None:
            # May raise (e.g. a runner TableSwapError): the scheduler's
            # _end_txns absorbs it into FAILED/retry state, and the
            # still-set _swap_pending makes the retry re-swap.  The
            # swap stage brackets the runner's update_tables, whose
            # per-shard adoption stages nest inside it.
            t0 = time.perf_counter()
            try:
                self.on_compiled(compiled)
            finally:
                record_stage(f"swap:{self.telemetry_name}",
                             time.perf_counter() - t0)
        with self._lock:
            self._swap_pending = False

    def _compile(self, state: Dict[str, Any]):
        raise NotImplementedError

    def _expected_fingerprint(self, expected: Any) -> int:
        """Fingerprint of the last compile.  When the tables came from
        this applicator's incremental builder, the builder maintained
        the per-leaf wrap-sums under its delta patches — the expected
        side is a pure host fold, O(1), no device reduction.  Anything
        else (e.g. a test subclass compiling directly) pays the one
        fused device reduction."""
        builder = getattr(self, "_builder", None)
        if (
            builder is not None
            and builder.last_tables is expected
            and builder.fingerprint is not None
        ):
            return builder.fingerprint
        return table_fingerprint(expected)

    def verify(self, applied: Dict[str, Any]):
        """Device-table drift check: fingerprint the tables the data
        plane is RUNNING (installed_fn → runner) against the last
        compile.  The tables are one atomic artifact, so any divergence
        drifts ALL keys — the repair recompiles and reswaps once (the
        whole-txn bracket coalesces it).  Without a readback hook the
        backend is uninspectable (None → blind re-push), which for a
        compiling applicator is still just one recompile."""
        if self.installed_fn is None:
            return None
        with self._lock:
            expected = self._compiled
        if expected is None:
            return set(applied)
        installed = self.installed_fn()
        if installed is None or (
            table_fingerprint(installed) != self._expected_fingerprint(expected)
        ):
            return set(applied)
        return set()


class TpuAclApplicator(_CompilingApplicator):
    """Compiles ``tpu/acl/pod/*`` entries into classify RuleTables
    through a PERSISTENT incremental builder: the host numpy mirrors
    and the table-interning map live across transactions, so a txn
    costs O(its dirty keys) — dirty rule rows and pod slots ship to the
    device via a jitted scatter instead of a full tensor re-upload
    (ops/classify_delta)."""

    prefix = ACL_POD_PREFIX
    telemetry_name = "acl"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        from ..ops.classify_delta import AclTableBuilder

        self._builder = AclTableBuilder()

    @property
    def tables(self) -> Optional[RuleTables]:
        with self._lock:
            return self._compiled

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            compiled = self._compiled
            return {
                "pods": len(self._state),
                "tables": compiled.num_tables if compiled else 0,
                "rules": compiled.num_rules if compiled else 0,
                "compile": {
                    "swaps": self.compile_count,
                    **self._builder.stats.as_dict(),
                },
            }

    def _compile(self, state: Dict[str, Any]) -> RuleTables:
        return self._builder.sync(state)


class TpuNatApplicator(_CompilingApplicator):
    """Compiles ``tpu/nat/*`` (global + per-service mapping lists) into
    NatTables for the rewrite kernel — incrementally: the persistent
    builder diffs only the dirty service keys and patches mapping rows /
    backend rings / hash-index slots in place (ops/nat_delta)."""

    prefix = NAT_PREFIX
    telemetry_name = "nat"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        from ..ops.nat_delta import NatTableBuilder

        self._builder = NatTableBuilder()
        # Sorted-service-key cache: _flatten used to re-sort the FULL
        # service keyspace on every call; the keyset only changes on
        # create/delete, so sort once and invalidate on those.
        self._sorted_services: Optional[List[str]] = None

    @property
    def tables(self) -> Optional[NatTables]:
        with self._lock:
            return self._compiled

    def mappings(self) -> List[NatMapping]:
        with self._lock:
            return self._flatten(dict(self._state))

    def _keyset_changed(self, key: str) -> None:
        self._sorted_services = None

    def _service_keys(self) -> List[str]:
        if self._sorted_services is None:
            self._sorted_services = sorted(
                k for k in self._state if k.startswith(NAT_SERVICE_PREFIX)
            )
        return self._sorted_services

    def _flatten(self, state: Dict[str, Any]) -> List[NatMapping]:
        out: List[NatMapping] = []
        for key in self._service_keys():
            out.extend(state.get(key, ()))
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            compiled = self._compiled
            return {
                "services": sum(
                    1 for k in self._state if k.startswith(NAT_SERVICE_PREFIX)
                ),
                "mappings": compiled.num_mappings if compiled else 0,
                "compile": {
                    "swaps": self.compile_count,
                    **self._builder.stats.as_dict(),
                },
            }

    def _compile(self, state: Dict[str, Any]) -> NatTables:
        glob: NatGlobalConfig = state.get(NAT_GLOBAL_KEY) or NatGlobalConfig()
        services = {
            k: v for k, v in state.items() if k.startswith(NAT_SERVICE_PREFIX)
        }
        return self._builder.sync(
            services,
            nat_loopback=glob.nat_loopback,
            snat_ip=glob.snat_ip,
            snat_enabled=glob.snat_enabled,
            pod_subnet=glob.pod_subnet,
        )


class TpuInferApplicator(_CompilingApplicator):
    """Compiles ``tpu/infer/*`` (the model under ``tpu/infer/model`` +
    one ``(pod_ip_u32, threshold, action)`` enrollment per
    ``tpu/infer/pod/<ns>/<name>`` key) into an InferTable for the
    in-datapath scoring stage (ISSUE 14) — incrementally: the
    persistent builder diffs weight rows and enrollment slots against
    its host mirrors and ships only the dirty rows through the shared
    delta scatter (ops/infer_delta).  A model update is therefore a
    normal control-plane transaction: spanned (``compile:infer`` /
    ``swap:infer`` stages), retried, drift-verified, and swapped into
    the runner atomically under the last-good rollback."""

    prefix = INFER_PREFIX
    telemetry_name = "infer"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        from ..ops.infer_delta import InferTableBuilder

        self._builder = InferTableBuilder()

    @property
    def tables(self) -> Optional[InferTable]:
        with self._lock:
            return self._compiled

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            compiled = self._compiled
            return {
                "enabled": bool(compiled.enabled) if compiled else False,
                "pods": compiled.num_pods if compiled else 0,
                "compile": {
                    "swaps": self.compile_count,
                    **self._builder.stats.as_dict(),
                },
            }

    def _compile(self, state: Dict[str, Any]) -> InferTable:
        return self._builder.sync(state)
