"""TPU device-table applicators — the southbound backends that own
rule-tensor recompiles.

Round-1 verdict item 4: renderers used to recompile device tables
directly inside their commit, bypassing the txn scheduler, so the
reference's guarantee — one atomic, retried, dependency-ordered
transaction per event covering ALL southbound state
(plugins/controller/txn.go:28-83) — did not hold for the most important
backend.  Now the renderers emit plain KVs into the event transaction
(policy/renderer/sched.py, service/renderer/sched.py) and these
applicators compile them into device tensors, with:

- ONE atomic table swap per transaction: CRUD calls mark state dirty;
  the compile + swap happens in ``end_txn()`` (the scheduler brackets
  every commit/retry/replay with begin/end).
- scheduler-managed retries: a failed compile leaves the affected keys
  FAILED and retried with backoff like any other southbound value.
- resync semantics for free: a resync txn that no longer mentions a
  pod/service key deletes it here, exactly like host-FIB keys.

Keyspace (under the scheduler's longest-prefix applicator routing):

    tpu/acl/pod/<namespace>/<name>   -> (pod_ip_u32, ingress, egress)
    tpu/nat/global                   -> NatGlobalConfig
    tpu/nat/service/<namespace>/<name> -> tuple of NatMapping
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..ops.classify import RuleTables
from ..ops.nat import NatMapping, NatTables, build_nat_tables
from ..policy.renderer.tpu import compile_pod_tables
from .scheduler import Applicator

ACL_POD_PREFIX = "tpu/acl/pod/"
NAT_PREFIX = "tpu/nat/"
NAT_GLOBAL_KEY = "tpu/nat/global"
NAT_SERVICE_PREFIX = "tpu/nat/service/"


@dataclasses.dataclass(frozen=True)
class NatGlobalConfig:
    """The NAT44 global knobs (nat44_renderer.go Resync's global part):
    SNAT address pool, the NAT loopback, and the pod subnet the SNAT
    feature exempts."""

    nat_loopback: str = "0.0.0.0"
    snat_ip: str = "0.0.0.0"
    snat_enabled: bool = False
    pod_subnet: str = "10.1.0.0/16"


def table_fingerprint(tables: Any) -> int:
    """Content checksum of a compiled table pytree, computed ON DEVICE
    (one scalar transfer per leaf): uint32 wrap-sums of every array
    leaf, folded with shapes.  Equal content → equal fingerprint on any
    placement — retargeting (aux-only) and mesh re-sharding preserve
    it, so the drift check compares what the data plane actually holds
    against what the scheduler last compiled."""
    import jax
    import jax.numpy as jnp

    fp = 0x811C9DC5
    for leaf in jax.tree_util.tree_leaves(tables):
        if not hasattr(leaf, "dtype"):
            fp = (fp * 0x01000193) ^ (hash(leaf) & 0xFFFFFFFF)
            continue
        arr = jnp.asarray(leaf)
        if arr.dtype == jnp.bool_:
            arr = arr.astype(jnp.uint32)
        elif arr.dtype.kind == "f":
            arr = arr.view(jnp.uint32) if arr.dtype.itemsize == 4 else arr.astype(jnp.uint32)
        else:
            arr = arr.astype(jnp.uint32)
        s = int(jnp.sum(arr)) & 0xFFFFFFFF
        fp = (fp * 0x01000193) ^ s ^ (hash(arr.shape) & 0xFFFFFFFF)
        fp &= 0xFFFFFFFFFFFFFFFF
    return fp


class _CompilingApplicator(Applicator):
    """Shared begin/end-txn bracket: subclasses mutate ``_state`` in
    create/update/delete and compile once per transaction."""

    def __init__(self, on_compiled: Optional[Callable[[Any], None]] = None,
                 installed_fn: Optional[Callable[[], Any]] = None):
        self._state: Dict[str, Any] = {}
        self._dirty = False
        self._compiled: Any = None
        self._lock = threading.Lock()
        # Public hook: called with the freshly-compiled tables after each
        # transaction's atomic swap (the datapath runner attaches here).
        self.on_compiled = on_compiled
        # Readback hook for drift detection: returns the tables the
        # data plane is ACTUALLY running (runner.acl / runner.nat).
        self.installed_fn = installed_fn
        self.compile_count = 0  # atomic-swap observability for tests/metrics

    update_destroys_on_failure = False  # swaps are atomic in-place updates

    def create(self, key: str, value: Any) -> None:
        with self._lock:
            self._state[key] = value
            self._dirty = True

    def update(self, key: str, old_value: Any, new_value: Any) -> None:
        with self._lock:
            self._state[key] = new_value
            self._dirty = True

    def delete(self, key: str, value: Any) -> None:
        with self._lock:
            self._state.pop(key, None)
            self._dirty = True

    def begin_txn(self) -> None:
        pass

    def end_txn(self) -> None:
        with self._lock:
            # Compile when state changed — or on the very first
            # transaction, so empty tables exist from the first resync on
            # (the data plane must never see None tables).
            if not self._dirty and self._compiled is not None:
                return
            compiled = self._compile(dict(self._state))
            self._compiled = compiled
            self._dirty = False
            self.compile_count += 1
        if self.on_compiled is not None:
            self.on_compiled(compiled)

    def _compile(self, state: Dict[str, Any]):
        raise NotImplementedError

    def verify(self, applied: Dict[str, Any]):
        """Device-table drift check: fingerprint the tables the data
        plane is RUNNING (installed_fn → runner) against the last
        compile.  The tables are one atomic artifact, so any divergence
        drifts ALL keys — the repair recompiles and reswaps once (the
        whole-txn bracket coalesces it).  Without a readback hook the
        backend is uninspectable (None → blind re-push), which for a
        compiling applicator is still just one recompile."""
        if self.installed_fn is None:
            return None
        with self._lock:
            expected = self._compiled
        if expected is None:
            return set(applied)
        installed = self.installed_fn()
        if installed is None or (
            table_fingerprint(installed) != table_fingerprint(expected)
        ):
            return set(applied)
        return set()


class TpuAclApplicator(_CompilingApplicator):
    """Compiles ``tpu/acl/pod/*`` entries into classify RuleTables."""

    prefix = ACL_POD_PREFIX

    @property
    def tables(self) -> Optional[RuleTables]:
        with self._lock:
            return self._compiled

    def stats(self) -> Dict[str, int]:
        with self._lock:
            compiled = self._compiled
            return {
                "pods": len(self._state),
                "tables": compiled.num_tables if compiled else 0,
                "rules": compiled.num_rules if compiled else 0,
            }

    def _compile(self, state: Dict[str, Any]) -> RuleTables:
        return compile_pod_tables(state)


class TpuNatApplicator(_CompilingApplicator):
    """Compiles ``tpu/nat/*`` (global + per-service mapping lists) into
    NatTables for the rewrite kernel."""

    prefix = NAT_PREFIX

    @property
    def tables(self) -> Optional[NatTables]:
        with self._lock:
            return self._compiled

    def mappings(self) -> List[NatMapping]:
        with self._lock:
            return self._flatten(dict(self._state))

    @staticmethod
    def _flatten(state: Dict[str, Any]) -> List[NatMapping]:
        out: List[NatMapping] = []
        for key in sorted(state):
            if key.startswith(NAT_SERVICE_PREFIX):
                out.extend(state[key])
        return out

    def _compile(self, state: Dict[str, Any]) -> NatTables:
        glob: NatGlobalConfig = state.get(NAT_GLOBAL_KEY) or NatGlobalConfig()
        return build_nat_tables(
            self._flatten(state),
            nat_loopback=glob.nat_loopback,
            snat_ip=glob.snat_ip,
            snat_enabled=glob.snat_enabled,
            pod_subnet=glob.pod_subnet,
        )
