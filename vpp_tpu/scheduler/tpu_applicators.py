"""TPU device-table applicators — the southbound backends that own
rule-tensor recompiles.

Round-1 verdict item 4: renderers used to recompile device tables
directly inside their commit, bypassing the txn scheduler, so the
reference's guarantee — one atomic, retried, dependency-ordered
transaction per event covering ALL southbound state
(plugins/controller/txn.go:28-83) — did not hold for the most important
backend.  Now the renderers emit plain KVs into the event transaction
(policy/renderer/sched.py, service/renderer/sched.py) and these
applicators compile them into device tensors, with:

- ONE atomic table swap per transaction: CRUD calls mark state dirty;
  the compile + swap happens in ``end_txn()`` (the scheduler brackets
  every commit/retry/replay with begin/end).
- scheduler-managed retries: a failed compile leaves the affected keys
  FAILED and retried with backoff like any other southbound value.
- resync semantics for free: a resync txn that no longer mentions a
  pod/service key deletes it here, exactly like host-FIB keys.

Keyspace (under the scheduler's longest-prefix applicator routing):

    tpu/acl/pod/<namespace>/<name>   -> (pod_ip_u32, ingress, egress)
    tpu/nat/global                   -> NatGlobalConfig
    tpu/nat/service/<namespace>/<name> -> tuple of NatMapping
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..ops.classify import RuleTables
from ..ops.nat import NatMapping, NatTables, build_nat_tables
from ..policy.renderer.tpu import compile_pod_tables
from .scheduler import Applicator

ACL_POD_PREFIX = "tpu/acl/pod/"
NAT_PREFIX = "tpu/nat/"
NAT_GLOBAL_KEY = "tpu/nat/global"
NAT_SERVICE_PREFIX = "tpu/nat/service/"


@dataclasses.dataclass(frozen=True)
class NatGlobalConfig:
    """The NAT44 global knobs (nat44_renderer.go Resync's global part):
    SNAT address pool, the NAT loopback, and the pod subnet the SNAT
    feature exempts."""

    nat_loopback: str = "0.0.0.0"
    snat_ip: str = "0.0.0.0"
    snat_enabled: bool = False
    pod_subnet: str = "10.1.0.0/16"


class _CompilingApplicator(Applicator):
    """Shared begin/end-txn bracket: subclasses mutate ``_state`` in
    create/update/delete and compile once per transaction."""

    def __init__(self, on_compiled: Optional[Callable[[Any], None]] = None):
        self._state: Dict[str, Any] = {}
        self._dirty = False
        self._compiled: Any = None
        self._lock = threading.Lock()
        # Public hook: called with the freshly-compiled tables after each
        # transaction's atomic swap (the datapath runner attaches here).
        self.on_compiled = on_compiled
        self.compile_count = 0  # atomic-swap observability for tests/metrics

    update_destroys_on_failure = False  # swaps are atomic in-place updates

    def create(self, key: str, value: Any) -> None:
        with self._lock:
            self._state[key] = value
            self._dirty = True

    def update(self, key: str, old_value: Any, new_value: Any) -> None:
        with self._lock:
            self._state[key] = new_value
            self._dirty = True

    def delete(self, key: str, value: Any) -> None:
        with self._lock:
            self._state.pop(key, None)
            self._dirty = True

    def begin_txn(self) -> None:
        pass

    def end_txn(self) -> None:
        with self._lock:
            # Compile when state changed — or on the very first
            # transaction, so empty tables exist from the first resync on
            # (the data plane must never see None tables).
            if not self._dirty and self._compiled is not None:
                return
            compiled = self._compile(dict(self._state))
            self._compiled = compiled
            self._dirty = False
            self.compile_count += 1
        if self.on_compiled is not None:
            self.on_compiled(compiled)

    def _compile(self, state: Dict[str, Any]):
        raise NotImplementedError


class TpuAclApplicator(_CompilingApplicator):
    """Compiles ``tpu/acl/pod/*`` entries into classify RuleTables."""

    prefix = ACL_POD_PREFIX

    @property
    def tables(self) -> Optional[RuleTables]:
        with self._lock:
            return self._compiled

    def stats(self) -> Dict[str, int]:
        with self._lock:
            compiled = self._compiled
            return {
                "pods": len(self._state),
                "tables": compiled.num_tables if compiled else 0,
                "rules": compiled.num_rules if compiled else 0,
            }

    def _compile(self, state: Dict[str, Any]) -> RuleTables:
        return compile_pod_tables(state)


class TpuNatApplicator(_CompilingApplicator):
    """Compiles ``tpu/nat/*`` (global + per-service mapping lists) into
    NatTables for the rewrite kernel."""

    prefix = NAT_PREFIX

    @property
    def tables(self) -> Optional[NatTables]:
        with self._lock:
            return self._compiled

    def mappings(self) -> List[NatMapping]:
        with self._lock:
            return self._flatten(dict(self._state))

    @staticmethod
    def _flatten(state: Dict[str, Any]) -> List[NatMapping]:
        out: List[NatMapping] = []
        for key in sorted(state):
            if key.startswith(NAT_SERVICE_PREFIX):
                out.extend(state[key])
        return out

    def _compile(self, state: Dict[str, Any]) -> NatTables:
        glob: NatGlobalConfig = state.get(NAT_GLOBAL_KEY) or NatGlobalConfig()
        return build_nat_tables(
            self._flatten(state),
            nat_loopback=glob.nat_loopback,
            snat_ip=glob.snat_ip,
            snat_enabled=glob.snat_enabled,
            pod_subnet=glob.pod_subnet,
        )
