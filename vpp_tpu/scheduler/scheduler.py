"""Declarative-config transaction scheduler.

Analog of the ligato kvscheduler the reference vendors
(vendor/github.com/ligato/vpp-agent/plugins/kvscheduler/ — txn_exec.go,
plugin_scheduler.go; SURVEY.md §1 L3, §2.3): the reference consumes it
as a library, so this is a first-party re-implementation of the
behaviors Contiv-VPP actually relies on:

- **desired-state diffing**: resync transactions *replace* the desired
  state; the scheduler computes the minimal create/update/delete set
  against what is currently applied.
- **dependency resolution**: values may depend on other keys; a value
  whose dependencies are unmet is held PENDING and applied automatically
  once they appear, and is removed (back to PENDING) when a dependency
  disappears — cascading in reverse dependency order.
- **retries**: failed CRUD operations are retried with exponential
  backoff (the reference enables this for its config,
  plugin_controller.go:58-69).
- **pluggable applicators**: per-prefix sinks that push config into the
  actual backends — in this framework the TPU pipeline tables and the
  host FIB; in tests the mock engines.

Commits normally come only from the controller's event-loop thread (the
reference's model), but retries fire from timer threads, so all public
entry points (commit/replay/dump and the retry callback) serialize on an
internal lock.
"""

from __future__ import annotations

import enum
import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..controller.txn import RecordedTxn, TxnSink

log = logging.getLogger(__name__)

# Given (key, value) returns the set of keys this value depends on.
DependencyFn = Callable[[str, Any], Set[str]]


class ValueState(enum.Enum):
    """Lifecycle state of one configured value."""

    APPLIED = "applied"
    PENDING = "pending"      # waiting for dependencies
    FAILED = "failed"        # last CRUD op errored; awaiting retry
    REMOVED = "removed"      # transiently, during cascades


@dataclass
class ValueStatus:
    """Status of one key as exposed by dump()."""

    key: str
    desired: Any
    applied: Any
    state: ValueState
    last_error: str = ""
    retries: int = 0


class Applicator:
    """A southbound sink for a key prefix (vppv2-plugin analog).

    Implementations push values into a concrete backend: TPU rule
    tables, host FIB, Linux netns config, or a mock engine in tests.
    """

    prefix: str = ""

    # Whether a *failed* update() may have destroyed the old incarnation.
    # True for the default delete+create implementation; subclasses with an
    # atomic in-place update() should set this False so the scheduler keeps
    # tracking (and eventually deletes) the still-programmed old value.
    update_destroys_on_failure: bool = True

    def create(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def update(self, key: str, old_value: Any, new_value: Any) -> None:
        # Default modify = re-create.
        self.delete(key, old_value)
        self.create(key, value=new_value)

    def delete(self, key: str, value: Any) -> None:
        raise NotImplementedError

    # Transaction boundaries.  The scheduler brackets every commit (and
    # every retry/replay batch) with begin_txn()/end_txn() so applicators
    # that compile state into an atomic artifact — the TPU device tables —
    # can coalesce all of a transaction's CRUD calls into ONE swap
    # (the reference's one-kvscheduler-txn-per-event contract,
    # plugins/controller/txn.go:28-83).
    def begin_txn(self) -> None:
        pass

    def end_txn(self) -> None:
        pass

    # Southbound READBACK (the kvscheduler SB-refresh analog the
    # reference's downstream/healing resyncs ride on —
    # plugins/controller/plugin_controller.go:968).  Given this
    # backend's currently-APPLIED key→value map, return the subset
    # whose ACTUAL backend state is missing or materially diverged
    # (someone deleted a veth out-of-band, a route vanished with its
    # device, the device tables were swapped behind the scheduler's
    # back), or None when the backend cannot be inspected — drift
    # repair then degrades to a blind re-push of its keys, the old
    # replay() behavior.
    def verify(self, applied: Dict[str, Any]) -> Optional[Set[str]]:
        return None


@dataclass
class _ValueRecord:
    desired: Any = None
    applied: Any = None
    state: ValueState = ValueState.PENDING
    last_error: str = ""
    retries: int = 0


class TxnScheduler(TxnSink):
    """The scheduler. Register applicators and dependency resolvers, then
    feed it RecordedTxns (it is the controller's TxnSink)."""

    def __init__(
        self,
        retry_delay: float = 1.0,
        max_retries: int = 3,
        schedule_retry: Optional[Callable[[Callable[[], None], float], None]] = None,
        on_unrecoverable: Optional[Callable[[str, str], None]] = None,
    ):
        self._applicators: List[Applicator] = []
        self._dependency_fns: Dict[str, DependencyFn] = {}
        self._values: Dict[str, _ValueRecord] = {}
        self.retry_delay = retry_delay
        self.max_retries = max_retries
        self._schedule_retry = schedule_retry or self._default_schedule
        self._txn_log: List[RecordedTxn] = []
        self._lock = threading.RLock()
        # Called (key, error) when a value exhausts its retries; the wiring
        # uses it to schedule a healing resync through the controller.
        self._on_unrecoverable = on_unrecoverable

    # -------------------------------------------------------------- registry

    def register_applicator(self, applicator: Applicator) -> None:
        with self._lock:
            self._applicators.append(applicator)

    def unregister_applicator(self, applicator: Applicator) -> None:
        """Remove a backend (e.g. swapping the mock host FIB for the real
        Linux applicator); follow with replay() to push applied state
        into whichever applicator now owns the keys.  Serialized against
        in-flight commits/retries/replays."""
        with self._lock:
            if applicator in self._applicators:
                self._applicators.remove(applicator)

    def register_dependencies(self, prefix: str, fn: DependencyFn) -> None:
        """Declare how to compute dependencies for values under ``prefix``."""
        self._dependency_fns[prefix] = fn

    def _applicator_for(self, key: str) -> Optional[Applicator]:
        best = None
        for a in self._applicators:
            if key.startswith(a.prefix):
                if best is None or len(a.prefix) > len(best.prefix):
                    best = a
        return best

    def _dependencies(self, key: str, value: Any) -> Set[str]:
        # A value may carry its own dependencies; otherwise use the
        # longest-prefix registered resolver.
        deps = getattr(value, "dependencies", None)
        if deps is not None:
            return set(deps() if callable(deps) else deps)
        best: Optional[Tuple[str, DependencyFn]] = None
        for prefix, fn in self._dependency_fns.items():
            if key.startswith(prefix):
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, fn)
        return set(best[1](key, value)) if best else set()

    # ---------------------------------------------------------------- commit

    def commit(self, txn: RecordedTxn) -> None:
        """Apply one transaction. Raises only on unexpected internal errors;
        per-value CRUD failures are absorbed into FAILED state + retries."""
        with self._lock:
            self._txn_log.append(txn)
            for a in self._applicators:
                a.begin_txn()
            try:
                if txn.is_resync:
                    self._commit_resync(txn)
                else:
                    self._commit_update(txn)
            finally:
                # One atomic swap per transaction for compiling applicators.
                self._end_txns()

    def _end_txns(self) -> None:
        """Close the transaction bracket on every applicator.  A failed
        end_txn (e.g. a device-table compile error) is absorbed into the
        ordinary FAILED/retry machinery: every value owned by that
        applicator is marked FAILED and retried with backoff — the retry's
        create() re-marks the state dirty and its own end_txn re-attempts
        the compile.  Other applicators still get their end_txn."""
        for a in self._applicators:
            try:
                a.end_txn()
            except Exception as e:  # noqa: BLE001 - backend errors become state
                log.warning("end_txn of %s failed: %s", type(a).__name__, e)
                for key, rec in self._values.items():
                    if self._applicator_for(key) is a and rec.desired is not None:
                        rec.state = ValueState.FAILED
                        rec.last_error = str(e)
                        self._schedule_retry_for(key)

    def _commit_resync(self, txn: RecordedTxn) -> None:
        desired = txn.values
        # Deletes: everything known that the resync no longer mentions.
        for key in sorted(set(self._values) - set(desired)):
            self._request_delete(key)
        for key, value in desired.items():
            self._request_put(key, value)
        self._resolve_pending()

    def _commit_update(self, txn: RecordedTxn) -> None:
        for key, value in txn.values.items():
            if value is None:
                self._request_delete(key)
            else:
                self._request_put(key, value)
        self._resolve_pending()

    # ------------------------------------------------------------ operations

    def _request_put(self, key: str, value: Any) -> None:
        rec = self._values.setdefault(key, _ValueRecord())
        rec.desired = value
        rec.retries = 0
        self._try_apply(key, rec)

    def _request_delete(self, key: str) -> None:
        rec = self._values.get(key)
        if rec is None:
            return
        rec.desired = None
        rec.retries = 0
        self._cascade_unapply(key)
        if rec.applied is None:
            self._values.pop(key, None)
        else:
            # Backend delete failed: keep the record in FAILED state so the
            # retry timer can finish the removal (no stale config forever).
            rec.state = ValueState.FAILED
            self._schedule_retry_for(key)

    def _try_apply(self, key: str, rec: _ValueRecord) -> None:
        deps = self._dependencies(key, rec.desired)
        unmet = [d for d in deps if not self._is_available(d)]
        if unmet:
            if rec.applied is not None:
                # The new desired value has unmet dependencies while an old
                # incarnation is applied: take it (and its dependents) out.
                self._cascade_unapply(key)
            if rec.applied is not None:
                # The backend delete failed; retry the removal first.
                rec.state = ValueState.FAILED
                self._schedule_retry_for(key)
            else:
                rec.state = ValueState.PENDING
            return
        applicator = self._applicator_for(key)
        if applicator is None:
            # No backend claims this prefix; treat as applied (pure model
            # value) so dependents can proceed.
            rec.applied = rec.desired
            rec.state = ValueState.APPLIED
            return
        try:
            if rec.applied is None:
                applicator.create(key, rec.desired)
            elif rec.applied != rec.desired:
                applicator.update(key, rec.applied, rec.desired)
            rec.applied = rec.desired
            rec.state = ValueState.APPLIED
            rec.last_error = ""
        except Exception as e:  # noqa: BLE001 - backend errors become state
            log.warning("apply of %s failed: %s", key, e)
            if rec.applied is not None and applicator.update_destroys_on_failure:
                # The failed update destroyed the old incarnation (default
                # update = delete+create): forget it so the retry re-creates
                # instead of re-deleting a missing value.
                rec.applied = None
            rec.state = ValueState.FAILED
            rec.last_error = str(e)
            self._schedule_retry_for(key)

    def _unapply(self, key: str, rec: _ValueRecord) -> None:
        if rec.applied is None:
            return
        applicator = self._applicator_for(key)
        if applicator is not None:
            try:
                applicator.delete(key, rec.applied)
            except Exception as e:  # noqa: BLE001
                log.warning("delete of %s failed: %s", key, e)
                rec.last_error = str(e)
                # Leave rec.applied set: the value is still in the backend
                # and the caller must keep the record for a delete retry.
                return
        rec.applied = None

    def _cascade_unapply(self, key: str) -> None:
        """Unapply ``key`` and, first, every applied value depending on it
        (reverse dependency order). Dependents whose backend delete
        succeeded become PENDING; a failed delete leaves them FAILED with
        a removal retry scheduled (stale config must not linger silently)."""
        for dep_key, dep_rec in list(self._values.items()):
            if dep_key == key or dep_rec.applied is None:
                continue
            if key in self._dependencies(dep_key, dep_rec.applied):
                self._cascade_unapply(dep_key)
                if dep_rec.applied is not None:
                    dep_rec.state = ValueState.FAILED
                    self._schedule_retry_for(dep_key)
                else:
                    dep_rec.state = ValueState.PENDING
        rec = self._values.get(key)
        if rec is not None:
            self._unapply(key, rec)

    def _is_available(self, key: str) -> bool:
        rec = self._values.get(key)
        return rec is not None and rec.state is ValueState.APPLIED

    def _resolve_pending(self) -> None:
        """Fixed-point iteration applying PENDING values whose dependencies
        became satisfied (the kvscheduler's graph walk)."""
        progress = True
        while progress:
            progress = False
            for key, rec in list(self._values.items()):
                if rec.state is ValueState.PENDING and rec.desired is not None:
                    self._try_apply(key, rec)
                    if rec.state is ValueState.APPLIED:
                        progress = True

    # ----------------------------------------------------------------- retry

    def _schedule_retry_for(self, key: str) -> None:
        rec = self._values.get(key)
        if rec is None:
            return
        if rec.retries >= self.max_retries:
            # Retries exhausted: escalate so the controller can heal with a
            # full resync instead of leaving the value FAILED forever.
            if self._on_unrecoverable is not None:
                self._on_unrecoverable(key, rec.last_error)
            return
        rec.retries += 1
        delay = self.retry_delay * (2 ** (rec.retries - 1))

        def retry():
            with self._lock:
                r = self._values.get(key)
                if r is None or r.state is not ValueState.FAILED:
                    return
                for a in self._applicators:
                    a.begin_txn()
                try:
                    if r.desired is None:
                        # Unfinished removal: retry the backend delete.
                        self._unapply(key, r)
                        if r.applied is None:
                            self._values.pop(key, None)
                        else:
                            self._schedule_retry_for(key)
                        return
                    self._try_apply(key, r)
                    self._resolve_pending()
                finally:
                    self._end_txns()

        self._schedule_retry(retry, delay)

    @staticmethod
    def _default_schedule(fn: Callable[[], None], delay: float) -> None:
        timer = threading.Timer(delay, fn)
        timer.daemon = True
        timer.start()

    # ------------------------------------------------------------- downstream

    def replay(self) -> None:
        """Downstream resync: re-push every *applied* value into its backend
        (used by periodic healing; DownstreamResync events).  PENDING values
        keep waiting for their dependencies — replay must not bypass the
        dependency gating."""
        with self._lock:
            for a in self._applicators:
                a.begin_txn()
            try:
                for key, rec in list(self._values.items()):
                    if rec.desired is None:
                        # An unfinished removal: retry the backend delete.
                        if rec.applied is not None:
                            self._unapply(key, rec)
                            if rec.applied is None:
                                self._values.pop(key, None)
                        continue
                    if rec.state is ValueState.FAILED:
                        # Replay is the recovery point for values that exhausted
                        # their retries: give them a fresh budget and re-try.
                        rec.retries = 0
                        self._try_apply(key, rec)
                        continue
                    if rec.state is not ValueState.APPLIED:
                        continue
                    applicator = self._applicator_for(key)
                    if applicator is None:
                        continue
                    try:
                        applicator.update(key, rec.applied, rec.desired)
                        rec.applied = rec.desired
                    except Exception as e:  # noqa: BLE001
                        if applicator.update_destroys_on_failure:
                            rec.applied = None
                        rec.state = ValueState.FAILED
                        rec.last_error = str(e)
                        self._schedule_retry_for(key)
                self._resolve_pending()
            finally:
                self._end_txns()

    def resync_downstream(self) -> Dict[str, List[str]]:
        """Verify-first downstream resync: ask every applicator to READ
        BACK its applied keys (:meth:`Applicator.verify`) and repair
        only the DRIFTED ones — delete the divergent remnant (absorbed
        if already gone; every hostnet delete tolerates absence), then
        re-create through the ordinary dependency-gated apply.  Backends
        that cannot be inspected fall back to the blind re-push
        :meth:`replay` performs for all keys.  FAILED values and
        unfinished removals recover exactly as in replay.  Returns
        ``{"repaired": [...], "replayed": [...]}`` for the event record
        / REST observability.

        This is what the controller's DOWNSTREAM_RESYNC (healing) runs:
        out-of-band damage is detected and fixed WITHOUT re-pushing
        every healthy value (the reference's kvscheduler likewise
        refreshes SB state and diffs, rather than blindly re-applying —
        SURVEY §2.3 kvscheduler row)."""
        with self._lock:
            for a in self._applicators:
                a.begin_txn()
            repaired: List[str] = []
            replayed: List[str] = []
            try:
                groups: Dict[int, Tuple[Applicator, Dict[str, Any]]] = {}
                for key, rec in self._values.items():
                    if rec.applied is None:
                        continue
                    a = self._applicator_for(key)
                    if a is None:
                        continue
                    groups.setdefault(id(a), (a, {}))[1][key] = rec.applied
                drifted_all: Set[str] = set()
                for a, applied in groups.values():
                    try:
                        drifted = a.verify(dict(applied))
                    except Exception as e:  # noqa: BLE001 - degrade, not die
                        log.warning("verify of %s failed (%s); falling back "
                                    "to blind re-push", type(a).__name__, e)
                        drifted = None
                    if drifted is None:
                        # Uninspectable backend: blind re-push (replay
                        # semantics) for its keys.
                        for key in sorted(applied):
                            rec = self._values[key]
                            if rec.desired is None or rec.applied is None:
                                continue
                            try:
                                a.update(key, rec.applied, rec.desired)
                                rec.applied = rec.desired
                                replayed.append(key)
                            except Exception as e:  # noqa: BLE001
                                if a.update_destroys_on_failure:
                                    rec.applied = None
                                rec.state = ValueState.FAILED
                                rec.last_error = str(e)
                                self._schedule_retry_for(key)
                        continue
                    drifted_all |= {k for k in drifted if k in applied}
                # Re-creating a drifted value can destroy its INTACT
                # dependents as a side effect (deleting a device drops
                # the kernel routes through it), so the repair cascades
                # to the applied-dependents closure — they re-create
                # right after their dependency does.
                changed = True
                while changed:
                    changed = False
                    for key, rec in self._values.items():
                        if key in drifted_all or rec.applied is None:
                            continue
                        if self._dependencies(key, rec.applied) & drifted_all:
                            drifted_all.add(key)
                            changed = True
                for key in sorted(drifted_all):
                    rec = self._values.get(key)
                    if rec is None or rec.applied is None:
                        continue
                    a = self._applicator_for(key)
                    # Clear the divergent remnant first so the re-create
                    # starts clean even when the drift is "exists but
                    # wrong" (every hostnet delete tolerates absence).
                    if a is not None:
                        try:
                            a.delete(key, rec.applied)
                        except Exception as e:  # noqa: BLE001
                            log.debug("repair pre-delete of %s: %s", key, e)
                    rec.applied = None
                    rec.state = ValueState.PENDING
                    rec.retries = 0
                    repaired.append(key)
                # FAILED values + unfinished removals recover as in replay.
                for key, rec in list(self._values.items()):
                    if rec.desired is None:
                        if rec.applied is not None:
                            self._unapply(key, rec)
                            if rec.applied is None:
                                self._values.pop(key, None)
                        continue
                    if rec.state is ValueState.FAILED:
                        rec.retries = 0
                        self._try_apply(key, rec)
                self._resolve_pending()
            finally:
                self._end_txns()
        if repaired:
            log.info("downstream resync repaired %d drifted value(s): %s",
                     len(repaired), ", ".join(repaired[:8]))
        return {"repaired": repaired, "replayed": replayed}

    # ------------------------------------------------------------------ dump

    def dump(self, prefix: str = "") -> List[ValueStatus]:
        """Current status of all values under ``prefix`` (the kvscheduler
        REST dump analog, consumed by telemetry/netctl)."""
        out = []
        with self._lock:
            values = dict(self._values)
        for key in sorted(values):
            if not key.startswith(prefix):
                continue
            rec = values[key]
            out.append(
                ValueStatus(
                    key=key,
                    desired=rec.desired,
                    applied=rec.applied,
                    state=rec.state,
                    last_error=rec.last_error,
                    retries=rec.retries,
                )
            )
        return out

    @property
    def txn_log(self) -> List[RecordedTxn]:
        return list(self._txn_log)
