"""Fake K8s API server for tests — the ListWatch backend.

Analog of the reference's ``mockK8sListWatch`` used by every
``plugins/ksr/*_reflector_test.go``: tests apply/delete K8s-JSON-shaped
objects and subscribed reflectors receive add/update/delete events; the
``list`` call returns the current object set (the informer's initial
listing).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..ksr.listwatch import ListWatchHandler


def _obj_key(obj: Dict) -> Tuple[str, str]:
    meta = obj.get("metadata", {})
    return meta.get("namespace", "default"), meta.get("name", "")


class FakeK8sCluster:
    """In-memory K8s API: per-kind object stores + change notification."""

    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[Tuple[str, str], Dict]] = {}
        self._handlers: Dict[str, List[ListWatchHandler]] = {}

    # ----------------------------------------------------- ListWatch API

    def list(self, kind: str) -> List[Dict]:
        with self._lock:
            return list(self._objects.get(kind, {}).values())

    def subscribe(self, kind: str, handler: ListWatchHandler) -> None:
        with self._lock:
            self._handlers.setdefault(kind, []).append(handler)

    def unsubscribe(self, kind: str, handler: ListWatchHandler) -> None:
        with self._lock:
            handlers = self._handlers.get(kind, [])
            if handler in handlers:
                handlers.remove(handler)

    # ------------------------------------------------------- test driver

    def apply(self, kind: str, obj: Dict) -> None:
        """Create or update an object (kubectl apply analog)."""
        key = _obj_key(obj)
        with self._lock:
            store = self._objects.setdefault(kind, {})
            old = store.get(key)
            store[key] = obj
            handlers = list(self._handlers.get(kind, []))
        event = "update" if old is not None else "add"
        for h in handlers:
            h(event, obj, old)

    def delete(self, kind: str, name: str, namespace: str = "default") -> Optional[Dict]:
        key = (namespace, name)
        with self._lock:
            store = self._objects.setdefault(kind, {})
            old = store.pop(key, None)
            handlers = list(self._handlers.get(kind, []))
        if old is not None:
            for h in handlers:
                h("delete", old, old)
        return old
