"""Mock host-FIB engine — applicator for ipv4net's typed config.

Analog of the reference's mock ifplugin/vpp-plugins consumed through
mock/localclient: receives Interface/Route/Arp/BD/L2FIB/Vrf values from
the txn scheduler, keeps them queryable, and validates basic
referential integrity (the scheduler's dependency tracking should make
violations impossible — the mock raises if not).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..ipv4net.model import (
    ArpEntry,
    BridgeDomain,
    Interface,
    L2FibEntry,
    Route,
    VrfTable,
    ARP_PREFIX,
    BD_PREFIX,
    CONFIG_PREFIX,
    IF_PREFIX,
    L2FIB_PREFIX,
    ROUTE_PREFIX,
    VRF_PREFIX,
)
from ..scheduler import Applicator


class MockHostFIB(Applicator):
    """The applicator + assertion surface."""

    prefix = CONFIG_PREFIX
    update_destroys_on_failure = False

    def __init__(self):
        self.state: Dict[str, object] = {}

    # ------------------------------------------------------------ applicator

    def create(self, key: str, value) -> None:
        self._check_deps(key, value)
        self.state[key] = value

    def update(self, key: str, old_value, new_value) -> None:
        self._check_deps(key, new_value)
        self.state[key] = new_value

    def delete(self, key: str, value) -> None:
        self.state.pop(key, None)

    def _check_deps(self, key: str, value) -> None:
        deps = value.dependencies() if hasattr(value, "dependencies") else set()
        missing = [d for d in deps if d not in self.state]
        if missing:
            raise RuntimeError(f"{key} applied before dependencies: {missing}")

    # ------------------------------------------------------------ assertions

    def interfaces(self) -> List[Interface]:
        return [v for k, v in self.state.items() if k.startswith(IF_PREFIX)]

    def get_interface(self, name: str) -> Optional[Interface]:
        return self.state.get(IF_PREFIX + name)

    def routes(self, vrf: Optional[int] = None) -> List[Route]:
        out = [v for k, v in self.state.items() if k.startswith(ROUTE_PREFIX)]
        if vrf is not None:
            out = [r for r in out if r.vrf == vrf]
        return out

    def has_route(self, dst_network: str, vrf: int = 0) -> bool:
        return any(r.dst_network == dst_network for r in self.routes(vrf))

    def arp_entries(self) -> List[ArpEntry]:
        return [v for k, v in self.state.items() if k.startswith(ARP_PREFIX)]

    def bridge_domain(self, name: str) -> Optional[BridgeDomain]:
        return self.state.get(BD_PREFIX + name)

    def l2_fib_entries(self) -> List[L2FibEntry]:
        return [v for k, v in self.state.items() if k.startswith(L2FIB_PREFIX)]

    def vrfs(self) -> List[VrfTable]:
        return [v for k, v in self.state.items() if k.startswith(VRF_PREFIX)]
