"""Mock ACL engine — the policy-verdict oracle.

Analog of ``mock/aclengine/aclengine_mock.go``: consumes the rule
tables produced by the policy stack (through OracleRenderer, which
implements the PolicyRendererAPI boundary) and evaluates simulated
connections:

- a connection pod->pod must pass the source pod's *ingress* table
  (traffic entering the vswitch from the pod) and the destination
  pod's *egress* table (traffic leaving the vswitch into the pod) —
  both on this or different nodes (ConnectionPodToPod :273);
- empty table = allow all in that direction (renderer/api.go Render doc);
- first matching rule decides (VPP ACL first-match semantics);
- reply traffic of a permitted connection is implicitly allowed
  (reflective-ACL semantics, acl_renderer.go reflectiveACL :253) —
  evaluation here is therefore for the *initiating* direction only.

This oracle defines the exact per-packet semantics the TPU classify
kernel must reproduce bit-for-bit on randomized connections.
"""

from __future__ import annotations

import enum
import ipaddress
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..models import PodID, ProtocolType
from ..policy.renderer.api import Action, ContivRule, PolicyRendererAPI, RendererTxn


class Verdict(enum.Enum):
    ALLOWED = "allowed"
    DENIED = "denied"


@dataclass
class PodTables:
    """Rendered rule tables of one pod."""

    pod_ip: Optional[ipaddress.IPv4Network]
    ingress: List[ContivRule] = field(default_factory=list)  # pod -> vswitch
    egress: List[ContivRule] = field(default_factory=list)   # vswitch -> pod


def evaluate_table(
    rules: Sequence[ContivRule],
    src_ip: ipaddress.IPv4Address,
    dst_ip: ipaddress.IPv4Address,
    protocol: ProtocolType,
    src_port: int,
    dst_port: int,
) -> Verdict:
    """First-match evaluation; empty table allows everything."""
    for rule in rules:
        if rule.matches(src_ip, dst_ip, protocol, src_port, dst_port):
            if rule.action is Action.DENY:
                return Verdict.DENIED
            return Verdict.ALLOWED
    return Verdict.ALLOWED if not rules else Verdict.DENIED


class MockACLEngine(PolicyRendererAPI):
    """The engine; also a policy renderer (plug it into the configurator)."""

    def __init__(self):
        self.tables: Dict[PodID, PodTables] = {}
        # pod registry: IP + locality (RegisterPod :144 anotherNode flag).
        self._pod_ips: Dict[PodID, ipaddress.IPv4Address] = {}
        self._local: Dict[PodID, bool] = {}

    # ----------------------------------------------------------- pod registry

    def register_pod(self, pod_id: PodID, ip: str, another_node: bool = False) -> None:
        self._pod_ips[pod_id] = ipaddress.ip_address(ip)
        self._local[pod_id] = not another_node

    # -------------------------------------------------------------- renderer

    def new_txn(self, resync: bool) -> "OracleTxn":
        return OracleTxn(self, resync)

    # ------------------------------------------------------------ connections

    def connection_pod_to_pod(
        self,
        src: PodID,
        dst: PodID,
        protocol: ProtocolType = ProtocolType.TCP,
        src_port: int = 12345,
        dst_port: int = 80,
    ) -> Verdict:
        """Evaluate a connection attempt between two registered pods
        (aclengine_mock.go ConnectionPodToPod :273)."""
        src_ip = self._pod_ips[src]
        dst_ip = self._pod_ips[dst]
        return self._test_connection(src, src_ip, dst, dst_ip, protocol, src_port, dst_port)

    def connection_pod_to_internet(
        self,
        src: PodID,
        dst_ip: str,
        protocol: ProtocolType = ProtocolType.TCP,
        src_port: int = 12345,
        dst_port: int = 80,
    ) -> Verdict:
        """Pod-initiated connection to an external IP
        (ConnectionPodToInternet :334): only the source side filters."""
        return self._test_connection(
            src, self._pod_ips[src], None, ipaddress.ip_address(dst_ip),
            protocol, src_port, dst_port,
        )

    def connection_internet_to_pod(
        self,
        src_ip: str,
        dst: PodID,
        protocol: ProtocolType = ProtocolType.TCP,
        src_port: int = 12345,
        dst_port: int = 80,
    ) -> Verdict:
        """External connection to a pod (ConnectionInternetToPod :379):
        only the destination side filters."""
        return self._test_connection(
            None, ipaddress.ip_address(src_ip), dst, self._pod_ips[dst],
            protocol, src_port, dst_port,
        )

    def _test_connection(
        self,
        src: Optional[PodID],
        src_ip: ipaddress.IPv4Address,
        dst: Optional[PodID],
        dst_ip: ipaddress.IPv4Address,
        protocol: ProtocolType,
        src_port: int,
        dst_port: int,
    ) -> Verdict:
        # Source side: the pod's ingress table filters what it may send
        # — applied on the node hosting the source pod.
        if src is not None and self._local.get(src, False):
            tables = self.tables.get(src)
            if tables is not None:
                verdict = evaluate_table(
                    tables.ingress, src_ip, dst_ip, protocol, src_port, dst_port
                )
                if verdict is Verdict.DENIED:
                    return Verdict.DENIED
        # Destination side: the pod's egress table filters what reaches it.
        if dst is not None and self._local.get(dst, False):
            tables = self.tables.get(dst)
            if tables is not None:
                verdict = evaluate_table(
                    tables.egress, src_ip, dst_ip, protocol, src_port, dst_port
                )
                if verdict is Verdict.DENIED:
                    return Verdict.DENIED
        return Verdict.ALLOWED


# Alias making the renderer role explicit at wiring sites.
OracleRenderer = MockACLEngine


class OracleTxn(RendererTxn):
    def __init__(self, engine: MockACLEngine, resync: bool):
        self.engine = engine
        self.resync = resync
        self._changes: Dict[PodID, Optional[PodTables]] = {}

    def render(self, pod, pod_ip, ingress, egress, removed=False):
        if removed:
            self._changes[pod] = None
        else:
            self._changes[pod] = PodTables(
                pod_ip=pod_ip, ingress=list(ingress), egress=list(egress)
            )
        return self

    def commit(self) -> None:
        if self.resync:
            self.engine.tables.clear()
        for pod, tables in self._changes.items():
            if tables is None:
                self.engine.tables.pop(pod, None)
            else:
                self.engine.tables[pod] = tables
