"""Frame-level cluster harness — SimCluster + real datapath runners.

Extends the in-process cluster simulation (:mod:`.cluster`) from
5-tuple evaluation to REAL Ethernet frames: every node gets a
:class:`DataplaneRunner` whose uplink is attached to a virtual wire
that delivers VXLAN-encapped frames between nodes by outer destination
IP — the e2e topology of the reference's two_node robot suites
(tests/robot/suites/two_node_two_pods.robot), with the TPU pipeline
in the role of VPP.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..datapath import DataplaneRunner, NativeRing, VxlanOverlay
from ..ops.packets import ip_to_u32
from ..ops.pipeline import make_route_config
from ..shim.hostshim import HostShim
from .cluster import SimCluster, SimNode


def _outer_dst_ip(frame: bytes) -> int:
    """Destination IP of the outermost IPv4 header."""
    ethertype = struct.unpack("!H", frame[12:14])[0]
    off = 18 if ethertype == 0x8100 else 14
    return int.from_bytes(frame[off + 16:off + 20], "big")


class VirtualWire:
    """The inter-node 'physical' network: frames sent to a node's VTEP
    IP land in that node's uplink rx ring; anything else goes to the
    external-world bucket."""

    def __init__(self):
        self._by_ip: Dict[int, NativeRing] = {}
        self.external: List[bytes] = []

    def attach(self, ip: int, ring: NativeRing) -> None:
        self._by_ip[ip] = ring

    def send(self, frames: Sequence[bytes]) -> None:
        # Group by destination ring so each ring pays ONE batched push.
        batches: Dict[int, List[bytes]] = {}
        for f in frames:
            dst = _outer_dst_ip(f)
            if dst in self._by_ip:
                batches.setdefault(dst, []).append(f)
            else:
                self.external.append(bytes(f))
        for dst, batch in batches.items():
            self._by_ip[dst].send(batch)


class FrameNode:
    """One node's datapath attachment: uplink rx ring + native-engine
    runner + local pod delivery ring.  The runner's TX ring holds
    encapped frames bound for other nodes; :meth:`pump_wire` carries
    them across the virtual wire by outer destination IP."""

    def __init__(self, sim: SimNode, wire: VirtualWire, shim: Optional[HostShim] = None):
        self.sim = sim
        self.wire = wire
        self.node_id = sim.nodesync.node_id
        self.node_ip = ip_to_u32(f"192.168.16.{self.node_id}")
        self.rx = NativeRing()
        self.tx = NativeRing()           # encapped frames for other nodes
        self.delivered = NativeRing()    # frames delivered to local pods
        self.to_host = NativeRing()      # handed to the host stack / uplink
        wire.attach(self.node_ip, self.rx)
        self.runner = DataplaneRunner(
            acl=sim.policy_renderer.tables,
            nat=sim.nat_renderer.tables,
            route=make_route_config(sim.ipam),
            batch_size=sim.config.batch_size,
            max_vectors=sim.config.max_vectors,
            coalesce=sim.config.coalesce,
            coalesce_slo_us=sim.config.coalesce_slo_us,
            max_inflight=sim.config.max_inflight,
            # NOT coalesce_prewarm: a per-test compile burst of every
            # pow2 bucket up to the ceiling would swamp suite runtime;
            # prewarm is covered by its own tests.
            overlay=VxlanOverlay(local_ip=self.node_ip, local_node_id=self.node_id),
            source=self.rx,
            tx=self.tx,
            local=self.delivered,
            host=self.to_host,
            shim=shim,
        )
        assert self.runner.engine == "native"
        # The scheduler's TPU applicators push each transaction's atomic
        # table swap straight into the runner (VERDICT r1 #4), and read
        # the runner's RESIDENT tables back for drift verification
        # (VERDICT r4 #2 southbound readback).
        sim.acl_applicator.on_compiled = lambda t: self.runner.update_tables(acl=t)
        sim.nat_applicator.on_compiled = lambda t: self.runner.update_tables(nat=t)
        sim.acl_applicator.installed_fn = lambda: self.runner.acl
        sim.nat_applicator.installed_fn = lambda: self.runner.nat

    def sync_tables(self) -> None:
        """Refresh tables not owned by the scheduler applicators (route
        config from IPAM) plus any swap that predated hook attachment."""
        self.runner.update_tables(
            acl=self.sim.policy_renderer.tables,
            nat=self.sim.nat_renderer.tables,
            route=make_route_config(self.sim.ipam),
        )

    def pump_wire(self) -> int:
        """Carry this node's encapped TX frames across the wire."""
        frames = self.tx.recv_batch(1 << 20)
        if frames:
            self.wire.send(frames)
        return len(frames)

    def drain(self) -> int:
        """Drain the runner, then deliver its TX frames over the wire."""
        sent = self.runner.drain()
        self.pump_wire()
        return sent


class FrameCluster(SimCluster):
    """SimCluster whose nodes also carry frame-level datapaths."""

    def __init__(self, store=None):
        super().__init__(store=store)
        self.wire = VirtualWire()
        self.frame_nodes: Dict[str, FrameNode] = {}
        self._shim = HostShim()  # shared library handle for all nodes

    def add_node(self, name: str) -> SimNode:
        node = super().add_node(name)
        self.frame_nodes[name] = FrameNode(node, self.wire, shim=self._shim)
        self._refresh_overlays()
        return node

    def _refresh_overlays(self) -> None:
        for fn in self.frame_nodes.values():
            for other in self.frame_nodes.values():
                if other.node_id != fn.node_id:
                    fn.runner.overlay.set_remote(other.node_id, other.node_ip)

    # ------------------------------------------------------------- traffic

    def inject(self, node_name: str, frames: Sequence[bytes]) -> None:
        """Frames arriving at a node from its pods (pre-routing)."""
        self.frame_nodes[node_name].rx.send(frames)

    def run_datapaths(self, max_rounds: int = 8) -> None:
        """Drive every runner until all rx rings are quiescent (frames
        forwarded across the wire are processed by their destination)."""
        for fn in self.frame_nodes.values():
            fn.sync_tables()
        for _ in range(max_rounds):
            for fn in self.frame_nodes.values():
                fn.drain()  # leaves no in-flight work behind; pumps wire
            if not any(len(fn.rx) for fn in self.frame_nodes.values()):
                break

    def delivered_frames(self, node_name: str) -> List[bytes]:
        ring = self.frame_nodes[node_name].delivered
        return ring.recv_batch(1 << 30)

    def host_frames(self, node_name: str) -> List[bytes]:
        return self.frame_nodes[node_name].to_host.recv_batch(1 << 30)
