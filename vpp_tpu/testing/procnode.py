"""Separate-process agent — the OS-process SPMD harness.

Round-1 verdict item 5: SimCluster nodes shared one Python store object,
so the per-node-agent SPMD story (docs/ARCHITECTURE.md:51-56 — identical
agents, zero direct agent↔agent communication, all coordination through
the cluster store) never crossed a process/socket boundary.  This module
runs ONE full agent stack in its own OS process, connected to the
cluster's KVStoreServer over gRPC:

    python -m vpp_tpu.testing.procnode --store 127.0.0.1:PORT \\
        --name node-2 [--mirror /tmp/node-2.db] [--heartbeat-prefix P]

The agent is the same plugin wiring as SimNode (controller, dbwatcher
with sqlite mirror, nodesync ID allocation through atomic store ops,
policy/service stacks with scheduler-routed TPU tables).  A heartbeat
key is written back to the store every interval carrying what the agent
currently believes (resync count, known pods, table swap counts), which
is how tests observe cross-process convergence.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time
import types

from ..kvstore.remote import RemoteKVStore

HEARTBEAT_PREFIX = "/vpp-tpu/test/heartbeat/"


def run_agent(
    store_address: str,
    name: str,
    mirror_path: str = "",
    heartbeat_prefix: str = HEARTBEAT_PREFIX,
    heartbeat_interval: float = 0.1,
    stop_event=None,
    hostnet_netns: str = "",
    rest_port: int = -1,
) -> None:
    from .cluster import SimNode

    store = RemoteKVStore(store_address)
    # SimNode only consumes ``cluster.store`` — a remote client slots in
    # where the in-process store object sat.
    shim = types.SimpleNamespace(store=store)
    node = SimNode(shim, name, mirror_path=mirror_path or None)
    rest = None
    rest_bound = 0
    if rest_port >= 0:
        # Serve the agent REST API (ipam/dump/nodes/pods/...) so
        # cross-process harnesses — the CRD telemetry crawl above all —
        # can interrogate this agent like a production one.  The bound
        # port rides the heartbeat for discovery (0 = ephemeral).
        from ..rest.server import AgentRestServer

        rest = AgentRestServer(
            node_name=name, controller=node.controller,
            dbwatcher=node.watcher, ipam=node.ipam,
            nodesync=node.nodesync, podmanager=node.podmanager,
            scheduler=node.scheduler, store=store, port=rest_port,
        )
        rest_bound = rest.start()
    hostnet = None
    if hostnet_netns:
        # Program REAL kernel networking (confined to the named netns):
        # the Linux applicator REPLACES the mock host FIB (both claim the
        # config/ prefix and the scheduler routes each key to one
        # backend), and a replay pushes the already-applied state into
        # the kernel.
        from ..hostnet import LinuxNetApplicator

        hostnet = LinuxNetApplicator(netns=hostnet_netns, create_netns=True)
        node.scheduler.unregister_applicator(node.fib)
        node.scheduler.register_applicator(hostnet)
        node.scheduler.replay()

    seq = 0
    try:
        while stop_event is None or not stop_event.is_set():
            seq += 1
            beat = {
                "name": name,
                "seq": seq,
                "node_id": node.nodesync.node_id,
                "resync_count": node.controller._resync_count,
                "mirror_resyncs": node.watcher.resynced_from_mirror,
                "pods": sorted(
                    f"{p.namespace}/{p.name}" for p in node.policy.cache._pods
                ),
                "acl_swaps": node.acl_applicator.compile_count,
                "nat_mappings": len(node.nat_applicator.mappings()),
                "rest": f"127.0.0.1:{rest_bound}" if rest_bound else "",
            }
            try:
                store.put(heartbeat_prefix + name, beat)
            except Exception:  # noqa: BLE001 - store outage: keep beating
                pass
            time.sleep(heartbeat_interval)
    finally:
        if rest is not None:
            rest.stop()
        node.stop()
        store.close()
        if hostnet is not None:
            hostnet.close(delete_netns=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--store", required=True,
                        help="host:port of the KVStoreServer, or a comma-"
                             "separated HA ensemble member list (the client "
                             "follows the leader and fails over on its own)")
    parser.add_argument("--name", required=True)
    parser.add_argument("--mirror", default="")
    parser.add_argument("--heartbeat-prefix", default=HEARTBEAT_PREFIX)
    parser.add_argument("--hostnet-netns", default="",
                        help="program real kernel networking inside this netns")
    parser.add_argument("--rest-port", type=int, default=-1,
                        help="serve the agent REST API (0 = ephemeral port, "
                             "published in the heartbeat; -1 = off)")
    args = parser.parse_args(argv)

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    print(json.dumps({"agent": args.name, "store": args.store}), flush=True)
    run_agent(args.store, args.name, mirror_path=args.mirror,
              heartbeat_prefix=args.heartbeat_prefix,
              hostnet_netns=args.hostnet_netns, rest_port=args.rest_port)
    return 0


if __name__ == "__main__":
    sys.exit(main())
