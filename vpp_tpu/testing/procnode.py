"""Separate-process agent — the OS-process SPMD harness.

Round-1 verdict item 5: SimCluster nodes shared one Python store object,
so the per-node-agent SPMD story (docs/ARCHITECTURE.md:51-56 — identical
agents, zero direct agent↔agent communication, all coordination through
the cluster store) never crossed a process/socket boundary.  This module
runs ONE full agent stack in its own OS process, connected to the
cluster's KVStoreServer over gRPC:

    python -m vpp_tpu.testing.procnode --store 127.0.0.1:PORT \\
        --name node-2 [--mirror /tmp/node-2.db] [--heartbeat-prefix P] \\
        [--cni-port 0] [--datapath N] [--rest-port 0]

The agent is the same plugin wiring as SimNode (controller, dbwatcher
with sqlite mirror, nodesync ID allocation through atomic store ops,
policy/service stacks with scheduler-routed TPU tables).  A heartbeat
key is written back to the store every interval carrying what the agent
currently believes (resync count, known pods, table swap counts, the
controller resilience snapshot, parity-probe results), which is how
tests observe cross-process convergence.

ISSUE 9 additions for the cluster-scale chaos soak:

- ``--cni-port`` serves the agent's RemoteCNI gRPC endpoint so a
  kubelet-shaped harness (:mod:`.kubelet`) can exec the REAL shim
  binary against this agent for pod ADD/DEL; the bound port rides the
  heartbeat (``cni``) for discovery.
- ``--datapath N`` attaches an N-shard :class:`ShardedDataplane`
  (native rings, tables swapped by the scheduler applicators exactly
  like the production agent), so the soak's fault scheduler can arm
  PR 3 shard faults over this agent's REST surface and watch
  ejection/steer/rejoin happen in a REAL process under REAL frames.
- **parity probes**: the conductor bumps a round counter under
  ``PROBE_KEY``; the agent then evaluates a deterministic flow sample
  through BOTH the jit pipeline (and the sharded datapath, when
  attached) and the mock-engine oracle its policy stack feeds, and
  reports agreement in the heartbeat — the soak's bit-for-bit verdict
  oracle, per node, across processes.
- **boot retry**: constructing the agent while the store is unreachable
  (agent SIGKILLed and restarted inside a store-outage window) retries
  with capped backoff instead of crashing — the crash-looping
  DaemonSet-pod analog; an agent that was ALREADY up rides the outage
  out on its sqlite mirror.
"""

from __future__ import annotations

import argparse
import ipaddress
import json
import logging
import random
import signal
import sys
import time
import types
from typing import Dict, List, Optional, Set, Tuple

from ..kvstore.remote import RemoteKVStore

log = logging.getLogger(__name__)

HEARTBEAT_PREFIX = "/vpp-tpu/test/heartbeat/"
# The conductor bumps {"round": N} here to trigger a parity-probe round
# on every agent (see _ParityProber); results ride the heartbeat.
PROBE_KEY = "/vpp-tpu/test/soak/probe"

# Probe flows use src ports in [PROBE_SPORT, BACKGROUND_SPORT); the
# datapath keep-alive traffic uses >= BACKGROUND_SPORT and is excluded
# from every parity comparison (the test_chaos sacrificial convention).
PROBE_SPORT = 40000
BACKGROUND_SPORT = 50000

PROBE_BATCH = 32          # fixed probe batch shape: ONE pipeline compile
PROBE_PORTS = (80, 443, 9, 8080)


def _is_outage(exc: Exception) -> bool:
    from ..controller.dbwatcher import is_store_unavailable

    return is_store_unavailable(exc)


# ---------------------------------------------------------------------------
# Sharded-datapath attachment (the soak's shard-fault target)
# ---------------------------------------------------------------------------


class AgentDatapath:
    """An N-shard datapath wired to the agent's table applicators the
    way the production agent wires its runner: compiled tables swap in
    atomically per transaction, a swap failure propagates into the txn
    (→ healing escalation), and the REST surface serves health/faults/
    flight for this engine."""

    def __init__(self, node, shards: int, batch_size: int = 8,
                 max_vectors: int = 2):
        from ..datapath import NativeRing, ShardedDataplane, VxlanOverlay
        from ..ops.classify import build_rule_tables
        from ..ops.nat import build_nat_tables
        from ..ops.packets import ip_to_u32
        from ..ops.pipeline import make_route_config
        from .cluster import timeout_mult

        self.node = node
        self.ios = [tuple(NativeRing() for _ in range(4))
                    for _ in range(shards)]
        node_ip = f"192.168.16.{node.nodesync.node_id}"
        self.dp = ShardedDataplane(
            acl=node.policy_renderer.tables
            if node.policy_renderer.tables is not None
            else build_rule_tables([], {}),
            nat=node.nat_renderer.tables
            if node.nat_renderer.tables is not None
            else build_nat_tables([]),
            route=make_route_config(node.ipam),
            overlay=VxlanOverlay(local_ip=ip_to_u32(node_ip),
                                 local_node_id=node.nodesync.node_id),
            shard_ios=self.ios,
            batch_size=batch_size,
            max_vectors=max_vectors,
            session_capacity=1 << 12,
            # Short enough that a soak's dispatch-hang drill blows the
            # deadline within its window, long enough that the FIRST
            # dispatch's jit compile (no prewarm; N agents compiling
            # concurrently on a loaded box) never falsely ejects.
            dispatch_deadline=15.0 * timeout_mult(),
            prewarm=False,
        )
        # Same hook discipline as Agent._start_datapath: hook FIRST,
        # then pull whatever is already compiled, so no compile can fall
        # between.  A TableSwapError raised here propagates through the
        # applicator into the event transaction — the PR 3 healing
        # escalation path the soak's swap-fail drill exercises.
        node.acl_applicator.on_compiled = \
            lambda t: self.dp.update_tables(acl=t)
        node.nat_applicator.on_compiled = \
            lambda t: self.dp.update_tables(nat=t)
        self.dp.update_tables(acl=node.policy_renderer.tables,
                              nat=node.nat_renderer.tables)
        self._bg_seq = 0
        # Background frames land on a high host address of this node's
        # pod subnet: routed local (delivered), never a real pod.
        subnet = node.ipam.pod_subnet_this_node
        self._bg_dst = str(subnet.network_address + subnet.num_addresses - 2)
        self._bg_src = str(subnet.network_address + subnet.num_addresses - 3)

    def pump(self) -> None:
        """One keep-alive turn: a sacrificial frame per shard (so armed
        dispatch faults actually fire and ejected shards re-probe), one
        supervised poll, rings drained so nothing accumulates."""
        from .frames import build_frame

        self._bg_seq += 1
        sport = BACKGROUND_SPORT + (self._bg_seq % 8000)
        for io_set in self.ios:
            io_set[0].send([build_frame(self._bg_src, self._bg_dst, 6,
                                        sport, 80)])
        self.dp.poll()
        self.drain_outputs()

    def drain_outputs(self) -> List[bytes]:
        """Empty every shard's tx/local/host ring; returns the local
        (delivered-to-pod) frames for callers that inspect them."""
        delivered: List[bytes] = []
        for io_set in self.ios:
            io_set[1].recv_batch(1 << 12)
            delivered += io_set[2].recv_batch(1 << 12)
            io_set[3].recv_batch(1 << 12)
        return delivered

    def probe(self, flows: List[Tuple[str, str, int, int, int]]
              ) -> Set[Tuple[str, str, int, int, int]]:
        """Drive probe flows as real frames round-robin over ALL shard
        rings (ejected shards' frames must steer to survivors) and
        return the delivered 5-tuples in the probe port range."""
        from .frames import build_frame, frame_tuple

        self.drain_outputs()
        for i, flow in enumerate(flows):
            self.ios[i % len(self.ios)][0].send([build_frame(*flow)])
        self.dp.drain()
        out = {
            frame_tuple(f) for f in self.drain_outputs()
            if PROBE_SPORT <= frame_tuple(f)[3] < BACKGROUND_SPORT
        }
        return out

    def close(self) -> None:
        self.dp.close()


# ---------------------------------------------------------------------------
# Mock-engine parity probing (the soak's verdict oracle)
# ---------------------------------------------------------------------------


def known_pods(node) -> List:
    """Snapshot of the policy cache's pods, safe against the controller
    thread mutating the dict mid-iteration (retried; a torn read here
    crashed the heartbeat loop under soak churn)."""
    for _ in range(8):
        try:
            return list(node.policy.cache._pods.values())
        except RuntimeError:  # dict changed size during iteration
            continue
    return []


def probe_flows(node, round_no: int, count: int = PROBE_BATCH,
                local_only: bool = False,
                ) -> List[Tuple[str, str, int, int, int]]:
    """A deterministic flow sample over the pods this agent currently
    knows (seeded by the probe round, so every process draws the same
    sample for the same cluster view).  Service VIPs are never targeted
    — NAT rewrite would make the plain-ACL oracle the wrong reference.
    """
    pods = sorted(p.ip_address for p in known_pods(node) if p.ip_address)
    if local_only:
        subnet = node.ipam.pod_subnet_this_node
        pods = [ip for ip in pods
                if ipaddress.ip_address(ip) in subnet]
    if not pods:
        return []
    rng = random.Random(0xA5 ^ (round_no * 1000003))
    flows = []
    for i in range(count):
        src = rng.choice(pods)
        dst = rng.choice(pods)
        sport = PROBE_SPORT + ((round_no * count + i) % 9000)
        flows.append((src, dst, 6, sport, rng.choice(PROBE_PORTS)))
    return flows


def oracle_verdicts(node, flows) -> List[bool]:
    """The mock-engine verdict per flow: the source pod's ingress table
    and the destination pod's egress table (the MockACLEngine
    connection semantics), over the SAME rendered tables the TPU
    pipeline compiled from — absence of tables means allow."""
    from ..models import ProtocolType
    from .aclengine import Verdict, evaluate_table

    tables = dict(node.oracle.tables)  # consistent shallow view
    by_ip = {}
    for pod_tables in tables.values():
        if pod_tables.pod_ip is not None:
            by_ip[str(pod_tables.pod_ip.network_address)] = pod_tables
    out = []
    for src, dst, proto, sport, dport in flows:
        src_ip = ipaddress.ip_address(src)
        dst_ip = ipaddress.ip_address(dst)
        ok = True
        src_t = by_ip.get(src)
        if src_t is not None:
            ok = evaluate_table(src_t.ingress, src_ip, dst_ip,
                                ProtocolType.TCP, sport, dport) \
                is Verdict.ALLOWED
        if ok:
            dst_t = by_ip.get(dst)
            if dst_t is not None:
                ok = evaluate_table(dst_t.egress, src_ip, dst_ip,
                                    ProtocolType.TCP, sport, dport) \
                    is Verdict.ALLOWED
        out.append(ok)
    return out


class _ParityProber:
    """Runs one parity round when the conductor bumps PROBE_KEY.

    A probe racing an in-flight policy commit can legitimately disagree
    (oracle renderer commits inside the handler, device tables swap at
    txn commit), so a round only REPORTS a mismatch when it persists
    across retries with a stable table generation — the conductor
    additionally quiesces churn before probing.
    """

    RETRIES = 3

    def __init__(self, node, datapath: Optional[AgentDatapath]):
        self.node = node
        self.datapath = datapath
        self.last = {"round": 0, "checked": 0, "mismatches": 0,
                     "detail": []}

    def maybe_run(self, probe_value) -> None:
        if not isinstance(probe_value, dict):
            return
        round_no = int(probe_value.get("round", 0))
        if round_no <= self.last["round"]:
            return
        self.last = self.run(round_no)

    def run(self, round_no: int) -> dict:
        import numpy as np

        result = {"round": round_no, "checked": 0, "mismatches": 0,
                  "detail": []}
        for attempt in range(self.RETRIES):
            gen_before = self.node.acl_applicator.compile_count
            mismatches: List[str] = []
            checked = 0

            # ---- pipeline-level: jit pipeline vs oracle ------------
            flows = probe_flows(self.node, round_no + attempt)
            if flows:
                padded = flows + [flows[0]] * (PROBE_BATCH - len(flows))
                res = self.node.send(padded)
                tpu = np.asarray(res.allowed)[:len(flows)]
                oracle = oracle_verdicts(self.node, flows)
                checked += len(flows)
                for flow, t, o in zip(flows, tpu, oracle):
                    if bool(t) != bool(o):
                        mismatches.append(
                            f"pipeline {flow}: tpu={bool(t)} oracle={o}")

            # ---- datapath-level: delivered frames vs oracle --------
            if self.datapath is not None:
                dflows = probe_flows(self.node, round_no + attempt,
                                     count=16, local_only=True)
                if dflows:
                    dflows = list(dict.fromkeys(dflows))  # unique frames
                    delivered = self.datapath.probe(dflows)
                    oracle = oracle_verdicts(self.node, dflows)
                    expect = {f for f, ok in zip(dflows, oracle) if ok}
                    checked += len(dflows)
                    for f in sorted(expect - delivered):
                        mismatches.append(f"datapath {f}: oracle=True "
                                          "not delivered")
                    for f in sorted(delivered - expect):
                        mismatches.append(f"datapath {f}: oracle=False "
                                          "delivered")

            stable = (self.node.acl_applicator.compile_count == gen_before)
            result["checked"] = checked
            result["mismatches"] = len(mismatches)
            result["detail"] = mismatches[:4]
            if not mismatches or attempt == self.RETRIES - 1:
                # A final attempt that disagreed while tables were still
                # moving is INCONCLUSIVE, not clean: surface the counts
                # and flag it — the conductor must never read a raced
                # round as a passing one.
                if mismatches and not stable:
                    result["unstable"] = True
                return result
            time.sleep(0.2)  # tables moved (or about to): settle, retry
        return result


# ---------------------------------------------------------------------------
# The agent process
# ---------------------------------------------------------------------------


def run_agent(
    store_address: str,
    name: str,
    mirror_path: str = "",
    heartbeat_prefix: str = HEARTBEAT_PREFIX,
    heartbeat_interval: float = 0.1,
    stop_event=None,
    hostnet_netns: str = "",
    rest_port: int = -1,
    cni_port: int = -1,
    datapath_shards: int = 0,
) -> None:
    from .cluster import SimNode

    store = RemoteKVStore(store_address)
    # SimNode only consumes ``cluster.store`` — a remote client slots in
    # where the in-process store object sat.
    shim = types.SimpleNamespace(store=store)
    # Boot retry: a restart landing inside a store-outage window (the
    # soak's SIGKILL-during-outage combo) must wait the outage out, not
    # die — kubelet would crash-loop the DaemonSet pod the same way.
    node = None
    backoff = 0.2
    while stop_event is None or not stop_event.is_set():
        try:
            node = SimNode(shim, name, mirror_path=mirror_path or None)
            break
        except Exception as err:  # noqa: BLE001 - classified below
            if not _is_outage(err):
                raise
            log.warning("store unreachable during agent boot (%s); "
                        "retrying in %.1fs", err, backoff)
            if stop_event is not None and stop_event.wait(backoff):
                break
            if stop_event is None:
                time.sleep(backoff)
            backoff = min(backoff * 2, 2.0)
    if node is None:
        store.close()
        return

    datapath = None
    if datapath_shards > 0:
        datapath = AgentDatapath(node, datapath_shards)

    # Graceful drain/rejoin (ISSUE 13): `netctl drain` gates CNI ADDs,
    # quiesces the datapath and flips the heartbeat to a *drained*
    # tombstone (state rides every beat below).
    from ..controller.drain import DrainCoordinator

    drainer = DrainCoordinator(
        podmanager=node.podmanager,
        datapath=(lambda: datapath.dp) if datapath is not None else None,
        node_name=name,
    )

    rest = None
    rest_bound = 0
    if rest_port >= 0:
        # Serve the agent REST API (ipam/dump/nodes/pods/health/faults/
        # ...) so cross-process harnesses — the CRD telemetry crawl, the
        # soak's fault scheduler — can interrogate and ARM this agent
        # like a production one.  The bound port rides the heartbeat
        # for discovery (0 = ephemeral).
        from ..rest.server import AgentRestServer

        rest = AgentRestServer(
            node_name=name, controller=node.controller,
            dbwatcher=node.watcher, ipam=node.ipam,
            nodesync=node.nodesync, podmanager=node.podmanager,
            scheduler=node.scheduler, store=store, port=rest_port,
            datapath=datapath.dp if datapath is not None else None,
            spans=node.controller.spans,
            drain=drainer,
        )
        rest_bound = rest.start()

    cni = None
    cni_bound = 0
    if cni_port >= 0:
        # The kubelet↔agent boundary: the REAL RemoteCNI gRPC service,
        # exec'd against by the fake-kubelet harness's shim subprocess.
        from ..cni.rpc import CNIServer

        cni = CNIServer(node.podmanager, port=cni_port)
        cni_bound = cni.start()

    hostnet = None
    if hostnet_netns:
        # Program REAL kernel networking (confined to the named netns):
        # the Linux applicator REPLACES the mock host FIB (both claim the
        # config/ prefix and the scheduler routes each key to one
        # backend), and a replay pushes the already-applied state into
        # the kernel.
        from ..hostnet import LinuxNetApplicator

        hostnet = LinuxNetApplicator(netns=hostnet_netns, create_netns=True)
        node.scheduler.unregister_applicator(node.fib)
        node.scheduler.register_applicator(hostnet)
        node.scheduler.replay()

    from ..kvstore import compat

    prober = _ParityProber(node, datapath)
    seq = 0
    try:
        while stop_event is None or not stop_event.is_set():
            seq += 1
            drain_state = drainer.state
            if datapath is not None and drain_state == "active":
                # A drained datapath stays quiesced: the keep-alive
                # pump would re-admit frames into the engine the drain
                # just proved idle.
                try:
                    datapath.pump()
                except Exception:  # noqa: BLE001 - chaos drills inject here
                    log.exception("datapath pump error")
            beat = {
                "name": name,
                "seq": seq,
                # Version stamp + drain tombstone (ISSUE 13): readers
                # tolerate adjacent versions; "drained" is explicitly
                # distinct from crash-dead (a missing/stale beat).
                "pv": compat.effective_version(),
                "state": drain_state,
                "node_id": node.nodesync.node_id,
                "resync_count": node.controller._resync_count,
                "mirror_resyncs": node.watcher.resynced_from_mirror,
                "mirror_recreated": (
                    node.watcher._mirror.recreated
                    if node.watcher._mirror is not None else 0),
                "pods": sorted(
                    f"{p.namespace}/{p.name}" for p in known_pods(node)
                ),
                "acl_swaps": node.acl_applicator.compile_count,
                "nat_mappings": len(node.nat_applicator.mappings()),
                "controller": node.controller.status(),
                "rest": f"127.0.0.1:{rest_bound}" if rest_bound else "",
                "cni": f"127.0.0.1:{cni_bound}" if cni_bound else "",
            }
            if drain_state == "drained":
                beat["drained_at"] = drainer.status().get("drained_at")
            if datapath is not None:
                h = datapath.dp.health()
                beat["datapath"] = {
                    "shards_total": h["shards_total"],
                    "shards_serving": h["shards_serving"],
                    "ejections": h["ejections"],
                    "rejoins": h["rejoins"],
                    "swap_rollbacks": h["swap_rollbacks"],
                }
            beat["parity"] = dict(prober.last)
            probe_value = None
            try:
                store.put(heartbeat_prefix + name, beat)
                probe_value = store.get(PROBE_KEY)
            except Exception:  # noqa: BLE001 - store outage: keep beating
                pass
            # The probe runs OUTSIDE the store-outage swallow: a real
            # probe bug (pipeline eval crash, datapath drain failure)
            # must be logged and reported as a failed round, not
            # silently retried into a conductor-side timeout.
            if probe_value is not None:
                try:
                    prober.maybe_run(probe_value)
                except Exception as err:  # noqa: BLE001 - reported below
                    log.exception("parity probe crashed")
                    prober.last = {
                        "round": int(probe_value.get("round", 0))
                        if isinstance(probe_value, dict) else 0,
                        "checked": 0, "mismatches": 1,
                        "detail": [f"probe crashed: {err}"],
                    }
            time.sleep(heartbeat_interval)
    finally:
        if cni is not None:
            cni.stop()
        if rest is not None:
            rest.stop()
        if datapath is not None:
            datapath.close()
        node.stop()
        store.close()
        if hostnet is not None:
            hostnet.close(delete_netns=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--store", required=True,
                        help="host:port of the KVStoreServer, or a comma-"
                             "separated HA ensemble member list (the client "
                             "follows the leader and fails over on its own)")
    parser.add_argument("--name", required=True)
    parser.add_argument("--mirror", default="")
    parser.add_argument("--heartbeat-prefix", default=HEARTBEAT_PREFIX)
    parser.add_argument("--heartbeat-interval", type=float, default=0.1)
    parser.add_argument("--hostnet-netns", default="",
                        help="program real kernel networking inside this netns")
    parser.add_argument("--rest-port", type=int, default=-1,
                        help="serve the agent REST API (0 = ephemeral port, "
                             "published in the heartbeat; -1 = off)")
    parser.add_argument("--cni-port", type=int, default=-1,
                        help="serve the RemoteCNI gRPC endpoint for "
                             "kubelet-exec'd shims (0 = ephemeral port, "
                             "published in the heartbeat; -1 = off)")
    parser.add_argument("--datapath", type=int, default=0,
                        help="attach an N-shard frame datapath (0 = off) — "
                             "the soak's shard-fault target")
    args = parser.parse_args(argv)

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    print(json.dumps({"agent": args.name, "store": args.store}), flush=True)
    run_agent(args.store, args.name, mirror_path=args.mirror,
              heartbeat_prefix=args.heartbeat_prefix,
              heartbeat_interval=args.heartbeat_interval,
              hostnet_netns=args.hostnet_netns, rest_port=args.rest_port,
              cni_port=args.cni_port, datapath_shards=args.datapath)
    return 0


if __name__ == "__main__":
    sys.exit(main())
