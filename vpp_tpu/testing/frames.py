"""Pure-Python Ethernet/IPv4 frame builder + checksum verifier.

The reference oracle for the native host shim tests: frames built here
have full (non-incremental) checksums, and ``verify_checksums`` recomputes
them from scratch — so the C++ incremental RFC 1624 updates are checked
against ground truth.
"""

from __future__ import annotations

import ipaddress
import struct
from typing import Optional


def _csum(data: bytes) -> int:
    if len(data) % 2:
        data += b"\x00"
    total = sum(struct.unpack(f"!{len(data) // 2}H", data))
    while total >> 16:
        total = (total & 0xFFFF) + (total >> 16)
    return ~total & 0xFFFF


def _ip(addr) -> bytes:
    return int(ipaddress.ip_address(str(addr))).to_bytes(4, "big")


def build_frame(
    src_ip: str,
    dst_ip: str,
    protocol: int = 6,
    src_port: int = 1234,
    dst_port: int = 80,
    payload: bytes = b"hello",
    vlan: Optional[int] = None,
    src_mac: bytes = b"\x02\x00\x00\x00\x00\x01",
    dst_mac: bytes = b"\x02\x00\x00\x00\x00\x02",
    udp_checksum: bool = True,
    ttl: int = 64,
) -> bytes:
    """Ethernet II (+optional 802.1Q) / IPv4 / {TCP,UDP,other} frame with
    correct checksums."""
    if protocol == 6:
        # Minimal TCP header: ports, seq/ack, offset, flags, window.
        l4_wo_csum = struct.pack(
            "!HHIIBBH", src_port, dst_port, 1, 0, 5 << 4, 0x18, 8192
        )
        l4 = l4_wo_csum + b"\x00\x00" + struct.pack("!H", 0) + payload
        csum_off = 16
    elif protocol == 17:
        length = 8 + len(payload)
        l4 = struct.pack("!HHHH", src_port, dst_port, length, 0) + payload
        csum_off = 6
    else:
        l4 = payload
        csum_off = None

    total_len = 20 + len(l4)
    ip_hdr = struct.pack(
        "!BBHHHBBH4s4s",
        0x45, 0, total_len, 0x1234, 0, ttl, protocol, 0,
        _ip(src_ip), _ip(dst_ip),
    )
    ip_hdr = ip_hdr[:10] + struct.pack("!H", _csum(ip_hdr)) + ip_hdr[12:]

    if csum_off is not None:
        pseudo = _ip(src_ip) + _ip(dst_ip) + struct.pack("!BBH", 0, protocol, len(l4))
        c = _csum(pseudo + l4)
        if protocol == 17:
            if not udp_checksum:
                c = 0
            elif c == 0:
                c = 0xFFFF
        l4 = l4[:csum_off] + struct.pack("!H", c) + l4[csum_off + 2:]

    eth = dst_mac + src_mac
    if vlan is not None:
        eth += struct.pack("!HH", 0x8100, vlan) + struct.pack("!H", 0x0800)
    else:
        eth += struct.pack("!H", 0x0800)
    return eth + ip_hdr + l4


def _l3_offset(frame: bytes) -> int:
    ethertype = struct.unpack("!H", frame[12:14])[0]
    return 18 if ethertype == 0x8100 else 14


def verify_checksums(frame: bytes) -> bool:
    """Recompute IPv4 + L4 checksums from scratch; True iff both hold."""
    off = _l3_offset(frame)
    ip = frame[off:]
    ihl = (ip[0] & 0x0F) * 4
    if _csum(ip[:10] + b"\x00\x00" + ip[12:ihl]) != struct.unpack("!H", ip[10:12])[0]:
        return False
    proto = ip[9]
    l4 = ip[ihl:]
    if proto == 6:
        csum_off = 16
    elif proto == 17:
        if struct.unpack("!H", l4[6:8])[0] == 0:
            return True  # UDP checksum disabled
        csum_off = 6
    else:
        return True
    pseudo = ip[12:16] + ip[16:20] + struct.pack("!BBH", 0, proto, len(l4))
    zeroed = l4[:csum_off] + b"\x00\x00" + l4[csum_off + 2:]
    expect = _csum(pseudo + zeroed)
    if proto == 17 and expect == 0:
        expect = 0xFFFF
    return expect == struct.unpack("!H", l4[csum_off:csum_off + 2])[0]


def frame_tuple(frame: bytes):
    """(src_ip, dst_ip, proto, sport, dport) parsed pythonically."""
    off = _l3_offset(frame)
    ip = frame[off:]
    ihl = (ip[0] & 0x0F) * 4
    proto = ip[9]
    src = str(ipaddress.ip_address(ip[12:16]))
    dst = str(ipaddress.ip_address(ip[16:20]))
    sport = dport = 0
    if proto in (6, 17):
        sport, dport = struct.unpack("!HH", ip[ihl:ihl + 4])
    return src, dst, proto, sport, dport
