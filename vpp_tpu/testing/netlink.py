"""Fake host network (netlink mock) for STN/bootstrap tests.

Plays the role the real netlink layer plays for ``cmd/contiv-stn``:
interfaces with addresses and routes that can be read, removed and
restored.  Tests drive failures by raising from injected hooks.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple


@dataclass
class HostRoute:
    dst: str                      # CIDR ("0.0.0.0/0" for default)
    gateway: str = ""
    interface: str = ""


@dataclass
class HostInterface:
    name: str
    up: bool = True
    addresses: Tuple[str, ...] = ()     # CIDR notation
    mac: str = ""


class FakeHostNetwork:
    """The host's links + routing table."""

    def __init__(self):
        self.interfaces: Dict[str, HostInterface] = {}
        self.routes: List[HostRoute] = []

    # ---------------------------------------------------------------- setup

    def add_interface(self, name: str, addresses=(), mac="", up=True) -> None:
        self.interfaces[name] = HostInterface(
            name=name, addresses=tuple(addresses), mac=mac, up=up
        )

    def add_route(self, dst: str, gateway: str = "", interface: str = "") -> None:
        self.routes.append(HostRoute(dst=dst, gateway=gateway, interface=interface))

    # ------------------------------------------------------- netlink-like API

    def get_interface(self, name: str) -> HostInterface:
        if name not in self.interfaces:
            raise LookupError(f"no such interface {name}")
        return self.interfaces[name]

    def interface_routes(self, name: str) -> List[HostRoute]:
        return [r for r in self.routes if r.interface == name]

    def flush_interface(self, name: str) -> None:
        """Remove all addresses + routes (the 'steal' operation)."""
        iface = self.get_interface(name)
        self.interfaces[name] = replace(iface, addresses=(), up=False)
        self.routes = [r for r in self.routes if r.interface != name]

    def configure_interface(self, name: str, addresses, routes, up=True) -> None:
        iface = self.get_interface(name)
        self.interfaces[name] = replace(iface, addresses=tuple(addresses), up=up)
        self.routes = [r for r in self.routes if r.interface != name] + list(routes)
