"""Test harness engines — semantic oracles for the TPU data plane.

Analog of the reference's ``mock/`` tree (SURVEY.md §4.2): simulated
data-plane engines that consume *rendered* config and evaluate
connections, so policy/service correctness is verified end-to-end
without real hardware — and, here, they double as the ground truth the
TPU kernels are verified against bit-for-bit.
"""

from .aclengine import MockACLEngine, OracleRenderer, Verdict

__all__ = ["MockACLEngine", "OracleRenderer", "Verdict"]
