"""Chaos soak conductor — the cluster-scale combined-fault proof.

ROADMAP #3 / ISSUE 9 tentpole: correctness under churn-PLUS-failover is
a different property from correctness under either alone (HyperNAT,
arXiv:2111.08193, makes the same argument for cloud NAT), and the PR 1
leader-kill, PR 3 shard-fault and PR 2 delta-swap machinery had never
been fired *simultaneously* at scale.  This conductor drives a procnode
mega-cluster — every agent a full control-plane stack in its own OS
process over a 3-replica HA store of OS processes — through recorded,
replayable pod/policy/service churn whose pod ADD/DELs exec the REAL
CNI shim binary via the fake-kubelet harness (:mod:`.kubelet`), while a
fault scheduler concurrently fires:

- **leader SIGKILL** (PR 1): the HA store leader dies mid-churn, a
  follower takes over, the corpse rejoins and catches up;
- **store-outage windows**: every replica SIGSTOPped — agents ride the
  outage out headless on their sqlite mirrors (REST-triggered resyncs
  prove the mirror fallback), CNI ADDs keep landing agent-locally, and
  the deferred K8s reflections flush on recovery;
- **shard faults** (PR 3): dispatch-raise ejections, dispatch-hang
  deadline ejections, and swap-fail rollbacks — armed over each
  agent's REST fault surface, healed through probation/rejoin and the
  controller's healing resync;
- **agent SIGKILL-and-restart**: the whole agent process dies and a
  replacement (same name, same mirror) adopts its node ID and
  reconverges.

The oracle after every phase: each agent's heartbeat must report the
conductor's expected pod set (convergence), a healthy healing ledger
(scheduled == completed, none failed, none pending — "no silent healing
loop"), serving shards, and a **mock-engine verdict-parity probe** with
zero mismatches (procnode evaluates a deterministic flow sample through
the jit pipeline AND its sharded datapath against the ACL oracle).
Every event is appended to a JSONL record (``SOAK_r08.jsonl``) together
with PR 6 telemetry evidence (config-propagation spans + latency
histograms pulled from agent REST).

ISSUE 10 (drill evidence timelines): the binary converged/parity
verdict says nothing about *how long* a drill took to heal fleet-wide.
A :class:`ClusterScraper` now rides along — a monitor thread sweeps
every agent's REST health during each drill — and every drill emits a
structured ``drill-timeline`` event: fault armed → first node observed
degraded (named) → fault cleared (store recovered / injection
disarmed / corpse respawned) → last node converged, with per-node
first-converged stamps.  After convergence points the conductor also
records **stitched cluster propagation spans** (``cluster-span``
events): one store write traced across every agent that adopted it,
with first/p50/p99/last adoption lags — the quantitative healing
evidence the fleet-scope observability plane exists to produce.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import pathlib
import random
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Set, Tuple

from ..statscollector.cluster import ClusterScraper
from .cluster import free_ports, timeout_mult, wait_for
from .kubelet import FakeKubelet, pod_ip
from .procnode import HEARTBEAT_PREFIX, PROBE_KEY

log = logging.getLogger(__name__)

REPO = pathlib.Path(__file__).resolve().parents[2]

WEB = {"app": "web"}
DB = {"app": "db"}


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SoakConfig:
    """One soak run.  ``smoke()`` is the tier-1 shape (seconds-scale,
    every fault class fired at least once); ``full()`` is the `make
    soak` acceptance shape (≥50 agents, ≥1000 CNI ops, ≥2 leader
    kills, ≥2 outage windows, ≥4 shard faults, ≥2 agent restarts)."""

    agents: int = 8
    datapath_agents: int = 2      # first N agents carry sharded datapaths
    datapath_shards: int = 2
    parity_agents: int = 4        # heartbeat parity probes asserted on first N
    pods: int = 12                # initial deploy (counted as CNI ADDs)
    churn_ops: int = 28           # further churn ops on top of the deploys
    churn_rate: float = 12.0      # target ops/sec within a churn slice
    cni_parallelism: int = 8      # concurrent shim subprocesses
    leader_kills: int = 1
    store_outages: int = 1
    outage_seconds: float = 2.5
    agent_kills: int = 1
    shard_faults: int = 3         # rotates eject / hang / swap-fail
    # Planned-operations drills (ISSUE 13).
    rolling_upgrades: int = 0     # serial agent restarts under emulated skew
    upgrade_agents: int = 2       # agents restarted per rolling-upgrade drill
    membership_changes: int = 0   # store ensemble grow 3→4 + shrink 4→3
    drains: int = 0               # netctl-drain / undrain round trips
    ha_replicas: int = 3
    store_heartbeat: float = 0.1
    store_lease: float = 0.8
    heartbeat_interval: float = 0.25
    convergence_timeout: float = 90.0
    seed: int = 8
    workdir: str = ""             # mirrors + child logs ("" = tmp)
    out_path: str = ""            # JSONL event record ("" = off)
    churn_script_path: str = ""   # replay a recorded script instead

    @staticmethod
    def smoke(workdir: str, out_path: str = "") -> "SoakConfig":
        return SoakConfig(workdir=workdir, out_path=out_path)

    @staticmethod
    def ops_smoke(workdir: str, out_path: str = "") -> "SoakConfig":
        """The planned-operations smoke (ISSUE 13): every OPERATIONS
        drill — rolling upgrade under emulated version skew, store
        membership grow+shrink, drain/rejoin — fired at least once over
        a small cluster, with churn + parity probes running throughout.
        The crash drills have their own smoke (``smoke()``)."""
        return SoakConfig(
            agents=4, datapath_agents=1, parity_agents=2, pods=6,
            churn_ops=10, churn_rate=8.0, leader_kills=0,
            store_outages=0, agent_kills=0, shard_faults=0,
            rolling_upgrades=1, upgrade_agents=2, membership_changes=1,
            drains=1, workdir=workdir, out_path=out_path,
        )

    @staticmethod
    def full(workdir: str, out_path: str = "SOAK_r08.jsonl") -> "SoakConfig":
        # ~20% of churn ops are policy/service toggles, so the pod-op
        # budget (initial deploys + ~80% of churn_ops) clears the
        # acceptance floor of 1000 CNI ADD/DELs with margin.
        return SoakConfig(
            agents=50, datapath_agents=4, datapath_shards=2,
            parity_agents=8, pods=150, churn_ops=1250, churn_rate=40.0,
            cni_parallelism=16, leader_kills=2, store_outages=2,
            outage_seconds=4.0, agent_kills=2, shard_faults=4,
            rolling_upgrades=1, upgrade_agents=4, membership_changes=1,
            drains=1,
            heartbeat_interval=0.5, convergence_timeout=300.0,
            workdir=workdir, out_path=out_path,
        )


# ---------------------------------------------------------------------------
# Churn scripts — recorded, deterministic, replayable
# ---------------------------------------------------------------------------


def generate_churn(cfg: SoakConfig) -> List[Dict[str, Any]]:
    """A deterministic op list: pod ADD/DEL (through the CNI shim),
    NetworkPolicy apply/withdraw, Service+Endpoints apply/withdraw.
    Plain JSON dicts so a script saves/replays byte-identically."""
    rng = random.Random(cfg.seed)
    ops: List[Dict[str, Any]] = []
    live: List[Tuple[str, str]] = []     # (pod, node)
    n_pod = 0
    policies_live: Set[str] = set()
    svc_live = False

    def add_pod():
        nonlocal n_pod
        n_pod += 1
        name = f"soak-{n_pod}"
        node = f"node-{rng.randrange(cfg.agents) + 1}"
        labels = WEB if n_pod % 3 else DB
        live.append((name, node))
        ops.append({"op": "pod-add", "pod": name, "node": node,
                    "labels": dict(labels)})

    for _ in range(cfg.pods):
        add_pod()
    for _ in range(cfg.churn_ops):
        roll = rng.random()
        if roll < 0.42 or len(live) < max(2, cfg.pods // 2):
            add_pod()
        elif roll < 0.78 and live:
            name, node = live.pop(rng.randrange(len(live)))
            ops.append({"op": "pod-del", "pod": name, "node": node})
        elif roll < 0.90:
            if "deny-web" in policies_live and rng.random() < 0.5:
                policies_live.discard("deny-web")
                ops.append({"op": "policy-del", "name": "deny-web"})
            else:
                policies_live.add("deny-web")
                ops.append({
                    "op": "policy-apply",
                    "manifest": {
                        "metadata": {"name": "deny-web",
                                     "namespace": "default"},
                        "spec": {"podSelector": {"matchLabels": dict(WEB)},
                                 "policyTypes": ["Ingress"],
                                 "ingress": [{"from": [{"podSelector": {
                                     "matchLabels": dict(WEB)}}]}]},
                    },
                })
        else:
            svc_live = not svc_live
            ops.append({"op": "svc-apply" if svc_live else "svc-del",
                        "name": "web"})
    return ops


def save_churn(ops: List[Dict[str, Any]], path: str) -> None:
    with open(path, "w") as fh:
        for op in ops:
            fh.write(json.dumps(op, sort_keys=True) + "\n")


def load_churn(path: str) -> List[Dict[str, Any]]:
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# ---------------------------------------------------------------------------
# Process helpers
# ---------------------------------------------------------------------------


def _child_env() -> Dict[str, str]:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # A mega-cluster of jax processes on one box: keep each child's
    # BLAS/compile pools narrow or N agents oversubscribe every core.
    env.setdefault("OMP_NUM_THREADS", "1")
    env.setdefault("OPENBLAS_NUM_THREADS", "1")
    return env


class _Proc:
    """A child process with its log file (stdout+stderr), so a crashed
    agent leaves forensics and a chatty one cannot fill a pipe.
    ``extra_env`` overlays the child environment — the rolling-upgrade
    drill spawns emulated-previous-version agents this way
    (``VPP_TPU_COMPAT_SKEW``)."""

    def __init__(self, argv: List[str], log_path: pathlib.Path,
                 extra_env: Optional[Dict[str, str]] = None):
        self.log_path = log_path
        self.log_file = open(log_path, "ab")
        env = _child_env()
        if extra_env:
            env.update(extra_env)
        self.proc = subprocess.Popen(
            argv, cwd=str(REPO), env=env,
            stdout=self.log_file, stderr=subprocess.STDOUT,
        )

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self, sig=signal.SIGKILL) -> None:
        if self.alive():
            self.proc.send_signal(sig)

    def reap(self, timeout: float = 10.0) -> None:
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=timeout)
        self.log_file.close()


def _http(server: str, path: str, method: str = "GET",
          timeout: float = 30.0):
    req = urllib.request.Request(f"http://{server}{path}", method=method)
    with urllib.request.urlopen(req, timeout=timeout) as resp:  # noqa: S310
        body = resp.read().decode()
    try:
        return json.loads(body)
    except ValueError:
        return body


class _DrillMonitor:
    """Samples fleet health over REST during ONE fault drill (ISSUE 10)
    and assembles the drill's evidence timeline.

    A sampler thread runs light (health-only) aggregator sweeps; the
    first sweep in which a node reports degraded — unreachable, shards
    not all serving, or healing pending/failed — stamps
    ``first_degraded``.  The drill code marks the instant the fault was
    *cleared* (store SIGCONTed, injection disarmed, corpse respawned)
    via :meth:`mark`; convergence stamps come from the conductor's
    ``wait_converged`` per-node first-ok times.  Everything is wall
    clock, same box as the drills themselves."""

    def __init__(self, scraper: ClusterScraper, kind: str,
                 interval: float = 0.5):
        self.scraper = scraper
        self.kind = kind
        self.interval = interval
        self.armed_at = time.time()
        self.first_degraded_at: Optional[float] = None
        self.first_degraded_node: Optional[str] = None
        self.degraded_nodes: Set[str] = set()
        self.marks: Dict[str, float] = {}
        self.samples = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="drill-monitor", daemon=True)
        self._thread.start()

    @staticmethod
    def _degraded(scrape) -> bool:
        if getattr(scrape, "state", "") == "drained":
            # Intentionally gone (ISSUE 13): a drained node is never
            # "degraded" — that is the whole point of the tombstone.
            return False
        if not scrape.ok:
            return True
        health = scrape.health or {}
        total = health.get("shards_total")
        if total is not None and health.get("shards_serving") != total:
            return True
        ctl = health.get("controller") or {}
        return bool(ctl.get("healing_pending") or ctl.get("healing_failed"))

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                sweep = self.scraper.scrape(light=True)
            except Exception:  # noqa: BLE001 - store outage mid-resolve
                sweep = []
            now = time.time()
            self.samples += 1
            for scrape in sweep:
                if self._degraded(scrape):
                    self.degraded_nodes.add(scrape.node)
                    if self.first_degraded_at is None:
                        self.first_degraded_at = now
                        self.first_degraded_node = scrape.node
            self._stop.wait(self.interval)

    def mark(self, name: str) -> None:
        """Stamp a drill instant (e.g. ``cleared``) once — the first
        call wins, later re-marks of the same phase are ignored."""
        self.marks.setdefault(name, time.time())

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def timeline(self, convergence: Optional[dict]) -> Dict[str, Any]:
        """The drill's evidence record (→ ``drill-timeline`` jsonl)."""
        conv = convergence or {}
        last_at = conv.get("last_converged_at")
        out: Dict[str, Any] = {
            "drill": self.kind,
            "armed_at": round(self.armed_at, 3),
            "samples": self.samples,
            "first_degraded_at": (round(self.first_degraded_at, 3)
                                  if self.first_degraded_at else None),
            "first_degraded_node": self.first_degraded_node,
            "degraded_nodes": sorted(self.degraded_nodes),
            "cleared_at": (round(self.marks["cleared"], 3)
                           if "cleared" in self.marks else None),
            "last_converged_at": (round(last_at, 3) if last_at else None),
            "last_converged_node": conv.get("last_node"),
            "converged": bool(conv.get("ok")),
        }
        if self.first_degraded_at is not None:
            out["detect_s"] = round(self.first_degraded_at - self.armed_at, 3)
        if last_at:
            out["heal_s"] = round(last_at - self.armed_at, 3)
        return out


# ---------------------------------------------------------------------------
# The conductor
# ---------------------------------------------------------------------------


class SoakCluster:
    """Owns every process of one soak run and conducts the phases."""

    def __init__(self, cfg: SoakConfig):
        self.cfg = cfg
        self.workdir = pathlib.Path(cfg.workdir or "/tmp/vpp-tpu-soak")
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.mult = timeout_mult()
        self.rng = random.Random(cfg.seed ^ 0xC1A0)
        self.store_ports: List[int] = []
        self.store_procs: Dict[int, _Proc] = {}       # port -> proc
        self.agent_procs: Dict[str, _Proc] = {}       # name -> proc
        self.kubelets: Dict[str, FakeKubelet] = {}    # name -> harness
        self.client = None                            # conductor's store
        self.k8s = None
        self.ksr = None
        self.names = [f"node-{i + 1}" for i in range(cfg.agents)]
        self._model_lock = threading.Lock()
        self.live_pods: Dict[str, str] = {}           # pod -> node
        self.pod_ips: Dict[str, str] = {}
        self._container_ids: Dict[str, str] = {}
        self._deferred_k8s: List[Tuple[str, dict]] = []
        self._outage_on = False
        self.probe_round = 0
        # Per-agent env overlay, preserved across respawns (a killed
        # emulated-old agent must come back emulated-old) — written by
        # the drill thread, read by respawns on the same thread.
        self._agent_env: Dict[str, Dict[str, str]] = {}
        # Nodes currently draining: churn pod-ADDs reroute to another
        # node (what the scheduler does for a cordoned node); guarded
        # by _model_lock (churn pool threads read it per op).
        self.draining_nodes: set = set()
        # Fleet aggregator (ISSUE 10): REST addresses resolved from
        # heartbeats, cached so sweeps keep working while the store is
        # SIGSTOPped; the monitor + cluster-span/latency evidence all
        # ride this one scraper.
        self.scraper = ClusterScraper(self._scraper_servers, timeout=5.0)
        self._servers_cache: Dict[str, str] = {}
        self._states_cache: Dict[str, str] = {}
        self._drill_monitor: Optional[_DrillMonitor] = None
        self.last_convergence: Dict[str, Any] = {}
        self.events: List[dict] = []
        self._out_fh = open(cfg.out_path, "a") if cfg.out_path else None
        self.report: Dict[str, Any] = {
            "agents": cfg.agents,
            "cni_adds": 0, "cni_dels": 0, "cni_errors": 0,
            "leader_kills": 0, "store_outages": 0,
            "agent_restarts": 0, "shard_faults": 0,
            "rolling_upgrades": 0, "membership_changes": 0, "drains": 0,
            "drain_rejected_adds": 0,
            "parity_rounds": 0, "parity_checked": 0,
            "parity_mismatches": 0, "unconverged": 0,
            "mirror_resyncs": 0, "healing_failed": 0,
            "errors": [],
        }

    # ------------------------------------------------------------ recording

    def record(self, event: str, **fields) -> None:
        rec = {"ts": round(time.time(), 3), "event": event, **fields}
        self.events.append(rec)
        if self._out_fh is not None:
            self._out_fh.write(json.dumps(rec, sort_keys=True,
                                          default=str) + "\n")
            self._out_fh.flush()

    # ---------------------------------------------------------------- store

    def _spawn_replica(self, port: int) -> _Proc:
        members = ",".join(f"127.0.0.1:{p}" for p in self.store_ports)
        return _Proc(
            [sys.executable, "-m", "vpp_tpu.kvstore",
             "--host", "127.0.0.1", "--port", str(port),
             "--join", members,
             "--heartbeat-interval", str(self.cfg.store_heartbeat),
             "--lease-timeout", str(self.cfg.store_lease * self.mult),
             "--max-watchers", str(max(64, self.cfg.agents * 2 + 16))],
            self.workdir / f"store-{port}.log",
        )

    @property
    def members(self) -> str:
        return ",".join(f"127.0.0.1:{p}" for p in self.store_ports)

    def _leader_address(self) -> Optional[str]:
        for port in self.store_ports:
            addr = f"127.0.0.1:{port}"
            try:
                if self.client.ha_status(addr)["role"] == "leader":
                    return addr
            except Exception:  # noqa: BLE001 - replica down/electing
                continue
        return None

    # ---------------------------------------------------------------- start

    def start(self) -> None:
        from ..ksr import KSRPlugin, KVBroker
        from ..kvstore.remote import RemoteKVStore
        from .k8s import FakeK8sCluster

        cfg = self.cfg
        self.record("start", config=dataclasses.asdict(cfg))
        self.store_ports = free_ports(cfg.ha_replicas)
        for port in self.store_ports:
            self.store_procs[port] = self._spawn_replica(port)
        self.client = RemoteKVStore(
            self.members, timeout=2.0,
            failover_deadline=20.0 * self.mult)
        assert wait_for(lambda: self._leader_address() is not None,
                        timeout=60.0), "HA store never elected a leader"

        self.k8s = FakeK8sCluster()
        self.ksr = KSRPlugin(self.k8s, KVBroker(self.client))
        self.ksr.init(start_monitor=False)

        # Agents, staggered to soften the ID-allocation storm.
        for name in self.names:
            self.agent_procs[name] = self._spawn_agent(name)
            time.sleep(0.05)
        deadline_per = max(120.0, 3.0 * cfg.agents)
        assert wait_for(
            lambda: all(self.heartbeat(n) is not None
                        for n in self.agent_procs),
            timeout=deadline_per,
        ), ("agents never all heartbeat: missing="
            + ",".join(n for n in self.agent_procs
                       if self.heartbeat(n) is None))
        for name in self.names:
            beat = self.heartbeat(name)
            # One designated agent execs the shim over the stdlib HTTP
            # fallback — the grpc-less-host path, same binary.
            transport = "http" if name == "node-2" and beat["rest"] \
                else "grpc"
            self.kubelets[name] = FakeKubelet(
                grpc_server=beat["cni"], http_server=beat["rest"],
                transport=transport,
            )
        self.record("agents-up", count=len(self.agent_procs))

    def _spawn_agent(self, name: str) -> _Proc:
        cfg = self.cfg
        idx = int(name.split("-")[1]) - 1
        argv = [sys.executable, "-m", "vpp_tpu.testing.procnode",
                "--store", self.members, "--name", name,
                "--mirror", str(self.workdir / f"{name}.db"),
                "--rest-port", "0", "--cni-port", "0",
                "--heartbeat-interval", str(cfg.heartbeat_interval)]
        if idx < cfg.datapath_agents:
            argv += ["--datapath", str(cfg.datapath_shards)]
        return _Proc(argv, self.workdir / f"{name}.log",
                     extra_env=self._agent_env.get(name))

    def heartbeat(self, name: str) -> Optional[dict]:
        try:
            return self.client.get(HEARTBEAT_PREFIX + name)
        except Exception:  # noqa: BLE001 - store mid-fault
            return None

    def rest_of(self, name: str) -> Optional[str]:
        beat = self.heartbeat(name)
        return beat.get("rest") if beat else None

    def _scraper_servers(self) -> Dict[str, str]:
        """REST targets for the fleet scraper, re-resolved from the
        heartbeats each sweep (agent restarts rebind ports) with the
        last good map cached — a store-outage window must not blind the
        monitor to agents whose REST is still perfectly reachable."""
        try:
            from ..statscollector.cluster import heartbeat_roster

            roster = heartbeat_roster(self.client)
            servers = {n: s for n, s in roster["servers"].items()
                       if n in self.agent_procs}
            states = {n: s for n, s in roster["states"].items()
                      if n in self.agent_procs}
        except Exception:  # noqa: BLE001 - store mid-outage: use cache
            servers, states = {}, {}
        if servers:
            self._servers_cache = servers
            self._states_cache = states
        return {"servers": dict(self._servers_cache),
                "states": dict(getattr(self, "_states_cache", {}) or {})}

    # ---------------------------------------------------------------- churn

    def _apply_k8s(self, kind: str, manifest: dict) -> None:
        """Apply through KSR unless the store is in an outage window —
        then defer (the apiserver is alive, its reflection queues) and
        flush on recovery."""
        if self._outage_on:
            self._deferred_k8s.append((kind, manifest))
            return
        self.k8s.apply(kind, manifest)

    def _delete_k8s(self, kind: str, name: str) -> None:
        if self._outage_on:
            self._deferred_k8s.append((f"{kind}-del", {"name": name}))
            return
        self.k8s.delete(kind, name, "default")

    def _flush_deferred(self) -> None:
        deferred, self._deferred_k8s = self._deferred_k8s, []
        for kind, manifest in deferred:
            if kind.endswith("-del"):
                self.k8s.delete(kind[:-4], manifest["name"], "default")
            else:
                self.k8s.apply(kind, manifest)
        if deferred:
            self.record("deferred-flush", count=len(deferred))

    def _cni(self, node: str, fn_name: str, *args, **kw):
        """One CNI exec with bounded retry: kubelet retries a node whose
        agent is mid-restart (our agent-SIGKILL drill runs concurrently
        with churn), and the harness is re-bound to the respawned
        agent's fresh ports between attempts."""
        last: Optional[Exception] = None
        for attempt in range(8):
            try:
                return getattr(self.kubelets[node], fn_name)(*args, **kw)
            except Exception as err:  # noqa: BLE001 - retried, then surfaced
                last = err
                time.sleep(1.5 * self.mult)
        raise last

    def _schedulable(self, node: str) -> str:
        """The node a pod-ADD actually lands on: the scripted node,
        unless it is DRAINING — then the first non-draining agent (what
        the scheduler does for a cordoned node).  The substitution is
        recorded in live_pods, so the DEL goes to the right agent."""
        with self._model_lock:
            if node not in self.draining_nodes:
                return node
            for fallback in self.names:
                if fallback not in self.draining_nodes:
                    return fallback
        return node  # everything draining: let the retriable error show

    def _exec_op(self, op: Dict[str, Any]) -> None:
        kind = op["op"]
        try:
            if kind == "pod-add":
                node = self._schedulable(op["node"])
                result = self._cni(node, "add", op["pod"])
                ip = pod_ip(result)
                with self._model_lock:
                    self.report["cni_adds"] += 1
                    self.live_pods[op["pod"]] = node
                    self.pod_ips[op["pod"]] = ip
                    self._container_ids[op["pod"]] = \
                        self.kubelets[node].invocations[-1][
                            "container_id"]
                self._apply_k8s("pods", {
                    "metadata": {"name": op["pod"], "namespace": "default",
                                 "labels": op.get("labels", {})},
                    "spec": {"nodeName": node},
                    "status": {"podIP": ip},
                })
            elif kind == "pod-del":
                with self._model_lock:
                    container = self._container_ids.pop(op["pod"], None)
                    # The ADD may have been rerouted off a draining
                    # node: tear down where the pod actually lives.
                    node = self.live_pods.get(op["pod"], op["node"])
                self._cni(node, "delete", op["pod"],
                          container_id=container)
                with self._model_lock:
                    self.report["cni_dels"] += 1
                    self.live_pods.pop(op["pod"], None)
                    self.pod_ips.pop(op["pod"], None)
                self._delete_k8s("pods", op["pod"])
            elif kind == "policy-apply":
                self._apply_k8s("networkpolicies", op["manifest"])
            elif kind == "policy-del":
                self._delete_k8s("networkpolicies", op["name"])
            elif kind == "svc-apply":
                self._apply_service()
            elif kind == "svc-del":
                self._delete_k8s("services", "web")
            else:
                raise ValueError(f"unknown churn op {kind!r}")
        except Exception as err:  # noqa: BLE001 - recorded, run continues
            self.report["cni_errors"] += 1
            self.report["errors"].append(f"{kind} {op.get('pod', '')}: {err}")
            self.record("churn-error", op=kind, error=str(err))

    def _apply_service(self) -> None:
        with self._model_lock:
            snapshot = [(p, self.pod_ips[p], n)
                        for p, n in self.live_pods.items()
                        if p in self.pod_ips]
        backends = snapshot[:4]
        self._apply_k8s("services", {
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"clusterIP": "10.96.0.10", "selector": dict(WEB),
                     "ports": [{"name": "http", "protocol": "TCP",
                                "port": 80, "targetPort": 8080}]},
        })
        self._apply_k8s("endpoints", {
            "metadata": {"name": "web", "namespace": "default"},
            "subsets": [{
                "addresses": [
                    {"ip": ip, "nodeName": node,
                     "targetRef": {"kind": "Pod", "name": pod,
                                   "namespace": "default"}}
                    for pod, ip, node in backends],
                "ports": [{"name": "http", "port": 8080,
                           "protocol": "TCP"}],
            }] if backends else [],
        })

    def run_churn(self, ops: List[Dict[str, Any]]) -> threading.Thread:
        """Execute a churn slice at the configured rate on a worker
        pool (CNI execs are subprocesses; parallelism hides their exec
        latency).  Per-pod ordering is preserved because a pod's DEL
        only ever appears after its ADD in the script and ops are
        submitted in order to a pool keyed FIFO."""
        def runner():
            with ThreadPoolExecutor(self.cfg.cni_parallelism) as pool:
                t0 = time.monotonic()
                pending = []
                by_pod: Dict[str, Any] = {}
                for i, op in enumerate(ops):
                    due = t0 + i / self.cfg.churn_rate
                    delay = due - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                    dep = by_pod.get(op.get("pod"))
                    if dep is not None:
                        # same-pod ordering: DEL waits for its ADD
                        dep.result()
                    fut = pool.submit(self._exec_op, op)
                    if op.get("pod"):
                        by_pod[op["pod"]] = fut
                    pending.append(fut)
                for fut in pending:
                    fut.result()

        thread = threading.Thread(target=runner, name="soak-churn")
        thread.start()
        return thread

    # ---------------------------------------------------------------- faults

    def _mark_drill(self, name: str) -> None:
        if self._drill_monitor is not None:
            self._drill_monitor.mark(name)

    def fault_leader_kill(self) -> None:
        leader = self._leader_address()
        assert leader is not None, "no leader to kill"
        port = int(leader.rsplit(":", 1)[1])
        self.record("fault", kind="leader-kill", leader=leader)
        proc = self.store_procs[port]
        proc.kill()           # SIGKILL
        proc.reap()
        assert wait_for(
            lambda: self._leader_address() not in (None, leader),
            timeout=30.0 * self.mult,
        ), "no new leader after SIGKILL"
        self._mark_drill("cleared")  # a leader serves again
        # Rejoin the corpse; it catches up via snapshot install.
        self.store_procs[port] = self._spawn_replica(port)
        assert wait_for(lambda: self._replica_ok(port), timeout=60.0), \
            f"replica :{port} never rejoined"
        self.report["leader_kills"] += 1
        self.record("fault-done", kind="leader-kill",
                    new_leader=self._leader_address())

    def _replica_ok(self, port: int) -> bool:
        try:
            self.client.ha_status(f"127.0.0.1:{port}")
            return True
        except Exception:  # noqa: BLE001
            return False

    def fault_store_outage(self) -> None:
        """SIGSTOP every replica: a full store outage window.  Agents
        must ride it out headless — REST-triggered resyncs during the
        window land on the sqlite mirror (asserted via the heartbeat's
        mirror_resyncs after recovery), CNI ADDs keep working
        agent-locally, data planes keep forwarding."""
        self.record("fault", kind="store-outage",
                    seconds=self.cfg.outage_seconds)
        mirror_before = self._mirror_resyncs_total()
        # Resolve REST addresses BEFORE freezing the store — heartbeats
        # are unreadable during the window.
        probed = [(n, self.rest_of(n)) for n in self.names[:3]]
        probed = [(n, r) for n, r in probed if r]
        self._outage_on = True
        for proc in self.store_procs.values():
            proc.kill(signal.SIGSTOP)
        # Ask a few agents to resync WHILE headless: the snapshot RPC
        # fails over, exhausts the window, and falls back to the mirror.
        headless_adds = 0
        for name, rest in probed:
            try:
                _http(rest, "/controller/resync", method="POST",
                      timeout=60.0)
            except Exception as err:  # noqa: BLE001
                self.record("churn-error", op="headless-resync",
                            error=f"{name}: {err}")
        # Headless CNI: the agent allocates pod state with no store.
        for i, name in enumerate(self.names[:2]):
            pod = f"headless-{self.report['store_outages']}-{i}"
            try:
                # Retried: the agent's event loop can be parked for a
                # failover window inside a mirror resync mid-outage; a
                # later attempt lands once the loop frees up.
                result = self._cni(name, "add", pod)
                headless_adds += 1
                with self._model_lock:
                    self.report["cni_adds"] += 1
                    self.live_pods[pod] = name
                    self.pod_ips[pod] = pod_ip(result)
                    self._container_ids[pod] = \
                        self.kubelets[name].invocations[-1]["container_id"]
                self._apply_k8s("pods", {      # defers until recovery
                    "metadata": {"name": pod, "namespace": "default",
                                 "labels": dict(WEB)},
                    "spec": {"nodeName": name},
                    "status": {"podIP": self.pod_ips[pod]},
                })
            except Exception as err:  # noqa: BLE001
                self.report["errors"].append(f"headless CNI: {err}")
        time.sleep(self.cfg.outage_seconds)
        for proc in self.store_procs.values():
            proc.kill(signal.SIGCONT)
        self._outage_on = False
        assert wait_for(lambda: self._leader_address() is not None,
                        timeout=30.0 * self.mult), \
            "store never recovered from SIGSTOP window"
        self._mark_drill("cleared")  # store recovered
        self._flush_deferred()
        mirror_after_ok = wait_for(
            lambda: self._mirror_resyncs_total() > mirror_before,
            timeout=30.0 * self.mult)
        self.report["mirror_resyncs"] = self._mirror_resyncs_total()
        self.report["store_outages"] += 1
        self.record("fault-done", kind="store-outage",
                    headless_adds=headless_adds,
                    mirror_resyncs=self.report["mirror_resyncs"],
                    mirror_fallback_observed=mirror_after_ok)
        if not mirror_after_ok:
            self.report["errors"].append(
                "no mirror-fallback resync observed across the outage")

    def _mirror_resyncs_total(self) -> int:
        total = 0
        for name in self.agent_procs:
            beat = self.heartbeat(name)
            if beat:
                total += int(beat.get("mirror_resyncs", 0))
        return total

    def fault_agent_kill(self) -> None:
        # Kill a non-datapath agent (a datapath corpse loses its armed-
        # fault target role for later drills; any agent works, this
        # just keeps the drill schedule independent).
        pool = self.names[self.cfg.datapath_agents:] or self.names
        name = pool[self.report["agent_restarts"] % len(pool)]
        old = self.heartbeat(name) or {}
        self.record("fault", kind="agent-kill", agent=name,
                    node_id=old.get("node_id"))
        proc = self.agent_procs[name]
        proc.kill()           # SIGKILL, mid-whatever-it-was-doing
        proc.reap()
        # Drop the corpse's last heartbeat so the wait below cannot pass
        # on stale state (and the kubelet cannot re-bind to dead ports).
        self.client.delete(HEARTBEAT_PREFIX + name)
        self.agent_procs[name] = self._spawn_agent(name)
        assert wait_for(
            lambda: self.heartbeat(name) is not None,
            timeout=90.0 * self.mult,
        ), f"restarted agent {name} never heartbeat"
        self._mark_drill("cleared")  # the replacement process beats
        beat = self.heartbeat(name)
        assert beat["node_id"] == old.get("node_id", beat["node_id"]), \
            f"{name} lost its node ID across restart"
        # Rebind the kubelet to the fresh ephemeral ports.
        self.kubelets[name] = FakeKubelet(
            grpc_server=beat["cni"], http_server=beat["rest"],
            transport=self.kubelets[name].transport,
        )
        self.report["agent_restarts"] += 1
        self.record("fault-done", kind="agent-kill", agent=name,
                    resync_count=beat.get("resync_count"))

    def fault_shard(self, flavor: str) -> None:
        """One PR 3 drill on a datapath agent, armed over REST:
        ``eject`` (dispatch-raise), ``hang`` (dispatch-hang deadline),
        ``swap-fail`` (atomic-swap rollback + healing retry)."""
        idx = self.report["shard_faults"] % max(1, self.cfg.datapath_agents)
        name = f"node-{idx + 1}"
        rest = self.rest_of(name)
        assert rest, f"no REST for datapath agent {name}"
        self.record("fault", kind=f"shard-{flavor}", agent=name)
        shard = self.rng.randrange(self.cfg.datapath_shards)

        def dp_health():
            try:
                return _http(rest, "/contiv/v1/health")
            except Exception:  # noqa: BLE001
                return {}

        if flavor in ("eject", "hang"):
            site = "dispatch-raise" if flavor == "eject" else "dispatch-hang"
            # The hang must outlive the agent datapath's dispatch
            # deadline (procnode arms 15s*mult) or it resolves before
            # the supervisor ever ejects; disarm below releases the
            # wedged worker once the ejection is observed.
            seconds = 120.0 * self.mult if flavor == "hang" else 8.0
            _http(rest, f"/contiv/v1/faults/arm?site={site}&shard={shard}"
                        f"&seconds={seconds}", method="POST")
            assert wait_for(
                lambda: (dp_health().get("shards") or [{}] * (shard + 1)
                         )[shard].get("state") == "ejected",
                timeout=60.0 * self.mult,
            ), f"{name} shard {shard} never ejected under {site}"
            _http(rest, "/contiv/v1/faults/disarm", method="POST")
            self._mark_drill("cleared")  # injection disarmed
            _http(rest, f"/contiv/v1/health/recover?shard={shard}",
                  method="POST")
            assert wait_for(
                lambda: dp_health().get("shards_serving")
                == dp_health().get("shards_total"),
                timeout=90.0 * self.mult,
            ), f"{name} shard {shard} never rejoined"
        elif flavor == "swap-fail":
            before = dp_health().get("swap_rollbacks", 0)
            _http(rest, "/contiv/v1/faults/arm?site=swap-fail&count=1",
                  method="POST")
            # Force a compile+swap through the control plane.
            self._apply_k8s("networkpolicies", {
                "metadata": {"name": f"swapfail-{self.report['shard_faults']}",
                             "namespace": "default"},
                "spec": {"podSelector": {"matchLabels": dict(DB)},
                         "policyTypes": ["Ingress"], "ingress": []},
            })
            assert wait_for(
                lambda: dp_health().get("swap_rollbacks", 0) > before,
                timeout=60.0 * self.mult,
            ), f"{name} swap-fail never rolled back"
            self._mark_drill("cleared")  # count=1 plan exhausted firing
            # The healing resync must land the swap on retry.
            assert wait_for(self._healing_settled(name),
                            timeout=90.0 * self.mult), \
                f"{name} healing never completed after swap-fail"
            self._delete_k8s("networkpolicies",
                             f"swapfail-{self.report['shard_faults']}")
        else:
            raise ValueError(flavor)
        self.report["shard_faults"] += 1
        self.record("fault-done", kind=f"shard-{flavor}", agent=name,
                    health={k: v for k, v in dp_health().items()
                            if not isinstance(v, (list, dict))})

    # ---------------------------------------- planned operations (ISSUE 13)

    def fault_rolling_upgrade(self) -> None:
        """Serial agent restarts under emulated version skew — the
        rolling-DaemonSet-upgrade drill: each agent in the cohort is
        SIGTERMed and respawned as an emulated PREVIOUS-version build
        (``VPP_TPU_COMPAT_SKEW=-1``) or back to current, alternating —
        so the fleet runs MIXED versions from here on, with churn and
        parity probes exercising the skew-tolerant paths throughout."""
        from ..kvstore import compat

        cfg = self.cfg
        pool = self.names[cfg.datapath_agents:] or self.names
        cohort = [pool[i % len(pool)] for i in range(cfg.upgrade_agents)]
        cohort = list(dict.fromkeys(cohort))
        self.record("fault", kind="rolling-upgrade", agents=cohort)
        for i, name in enumerate(cohort):
            skew = -1 if i % 2 == 0 else 0
            old = self.heartbeat(name) or {}
            proc = self.agent_procs[name]
            proc.kill(signal.SIGTERM)      # the kubelet-rolls-the-pod path
            proc.reap()
            self.client.delete(HEARTBEAT_PREFIX + name)
            self._agent_env[name] = (
                {"VPP_TPU_COMPAT_SKEW": str(skew)} if skew else {})
            self.agent_procs[name] = self._spawn_agent(name)
            assert wait_for(lambda: self.heartbeat(name) is not None,
                            timeout=90.0 * self.mult), \
                f"upgraded agent {name} never heartbeat"
            beat = self.heartbeat(name)
            assert beat["node_id"] == old.get("node_id", beat["node_id"]), \
                f"{name} lost its node ID across the upgrade"
            want_pv = max(1, compat.PROTOCOL_VERSION + skew)
            assert int(beat.get("pv", 0)) == want_pv, \
                f"{name} stamped pv={beat.get('pv')} (want {want_pv})"
            self.kubelets[name] = FakeKubelet(
                grpc_server=beat["cni"], http_server=beat["rest"],
                transport=self.kubelets[name].transport,
            )
            self.record("upgrade-step", agent=name, skew=skew,
                        pv=int(beat.get("pv", 0)),
                        resync_count=beat.get("resync_count"))
        self._mark_drill("cleared")  # the whole cohort beats again
        self.report["rolling_upgrades"] += 1
        self.record("fault-done", kind="rolling-upgrade", agents=cohort,
                    mixed_versions=sorted({
                        int((self.heartbeat(n) or {}).get("pv", 0))
                        for n in self.names
                        if self.heartbeat(n) is not None}))

    def fault_membership(self) -> None:
        """Live store-ensemble membership change mid-traffic: grow
        3→4 (the new empty replica snapshot-catches up as a learner
        BEFORE counting toward quorum), then shrink 4→3 by removing the
        CURRENT LEADER (orderly handoff; zero lost committed writes —
        asserted via revision identity across the survivors)."""
        self.record("fault", kind="membership", members=self.members)
        # ---- grow 3 -> 4 ---------------------------------------------
        new_port = free_ports(1)[0]
        new_addr = f"127.0.0.1:{new_port}"
        self.store_ports.append(new_port)  # future respawns use 4-member list
        self.store_procs[new_port] = self._spawn_replica(new_port)
        assert wait_for(lambda: self._replica_ok(new_port),
                        timeout=60.0 * self.mult), \
            f"new replica :{new_port} never served"
        add_result: Dict[str, Any] = {}
        try:
            add_result = self.client.add_replica(
                new_addr, timeout=60.0 * self.mult)
        except Exception as err:  # noqa: BLE001 - asserted via peers below
            add_result = {"error": str(err)}
        expect = sorted(f"127.0.0.1:{p}" for p in self.store_ports)

        def peers_of(addr: str):
            try:
                return sorted(self.client.ha_status(addr)["peers"])
            except Exception:  # noqa: BLE001
                return None

        assert wait_for(
            lambda: all(peers_of(a) == expect for a in expect),
            timeout=60.0 * self.mult,
        ), f"ensemble never converged on {expect}: " \
           f"{ {a: peers_of(a) for a in expect} }"
        self.record("membership-grow", added=new_addr,
                    peers=expect, result=add_result)

        # ---- shrink 4 -> 3: remove the sitting LEADER ----------------
        leader = self._leader_address()
        assert leader is not None, "no leader to remove"
        remove_result = self.client.remove_replica(
            leader, timeout=60.0 * self.mult)
        survivors = [a for a in expect if a != leader]
        assert wait_for(
            lambda: self._leader_address() not in (None, leader),
            timeout=60.0 * self.mult,
        ), "no successor leader after the orderly handoff"
        self._mark_drill("cleared")  # a survivor leads
        # Zero lost committed writes: every survivor converges to ONE
        # identical (revision, contents) view.
        def survivor_views():
            views = []
            for addr in survivors:
                try:
                    dump = self.client.local_dump("", address=addr)
                except Exception:  # noqa: BLE001 - still settling
                    return None
                views.append((dump["revision"], tuple(sorted(
                    (k, json.dumps(v, sort_keys=True, default=str))
                    for k, v in dump["items"]))))
            return views

        assert wait_for(
            lambda: (v := survivor_views()) is not None
            and len(set(v)) == 1,
            timeout=60.0 * self.mult,
        ), "survivors diverged after the leader removal"
        views = survivor_views()
        # Retire the corpse process and the conductor's record of it.
        old_port = int(leader.rsplit(":", 1)[1])
        self.store_ports.remove(old_port)
        corpse = self.store_procs.pop(old_port)
        corpse.kill(signal.SIGTERM)
        corpse.reap()
        self.report["membership_changes"] += 1
        self.record("fault-done", kind="membership",
                    removed_leader=leader, survivors=survivors,
                    survivor_revision=views[0][0] if views else None,
                    remove_result=remove_result)

    def fault_drain(self) -> None:
        """Graceful drain / rejoin: `netctl drain`-equivalent REST on
        one agent — new CNI ADDs refused RETRIABLY (code 11,
        AGENT_DRAINING), heartbeat flips to the drained tombstone, the
        cluster scraper reports it as *drained* (never a gap), then
        undrain rejoins and a fresh ADD lands on it again."""
        cfg = self.cfg
        reserved = max(cfg.datapath_agents, cfg.parity_agents)
        pool = self.names[reserved:] or self.names[-1:]
        name = pool[self.report["drains"] % len(pool)]
        rest = self.rest_of(name)
        assert rest, f"no REST for drain target {name}"
        self.record("fault", kind="drain", agent=name)
        with self._model_lock:
            self.draining_nodes.add(name)
        res = _http(rest, "/contiv/v1/drain", method="POST")
        assert res["state"] == "drained", res
        # Retriable CNI rejection through the REAL exec'd shim.
        probe_pod = f"drain-probe-{self.report['drains']}"
        rejected = False
        try:
            self.kubelets[name].add(probe_pod)
        except Exception as err:  # noqa: BLE001 - classified below
            code = getattr(err, "code", None)
            msg = getattr(err, "msg", str(err))
            rejected = code == 11 and "AGENT_DRAINING" in str(msg)
            if not rejected:
                raise
        assert rejected, \
            f"drained {name} accepted (or mis-refused) a CNI ADD"
        # Tombstone on the heartbeat + the scraper's drained contract.
        assert wait_for(
            lambda: (self.heartbeat(name) or {}).get("state") == "drained",
            timeout=30.0 * self.mult,
        ), f"{name} heartbeat never flipped to drained"
        summary = self.scraper.summary(self.scraper.scrape(light=True))
        assert name in (summary.get("drained") or []), \
            f"scraper did not report {name} as drained: {summary.get('drained')}"
        assert all(g.get("node") != name
                   for g in summary.get("gaps") or []), \
            f"drained {name} mis-reported as an unreachable gap"
        drain_status = _http(rest, "/contiv/v1/health").get("drain") or {}
        assert int(drain_status.get("rejected_adds") or 0) >= 1, \
            f"{name} never counted the rejected ADD: {drain_status}"
        self.record("drain-observed", agent=name,
                    scraper_drained=summary.get("drained"),
                    rejected_adds=drain_status.get("rejected_adds"),
                    last_flush=drain_status.get("last_flush"))
        # ---- undrain: clean rejoin -----------------------------------
        res = _http(rest, "/contiv/v1/undrain", method="POST")
        assert res["state"] == "active", res
        with self._model_lock:
            self.draining_nodes.discard(name)
        assert wait_for(
            lambda: (self.heartbeat(name) or {}).get("state") == "active",
            timeout=30.0 * self.mult,
        ), f"{name} heartbeat never flipped back to active"
        # A fresh ADD lands on the rejoined agent (counted as churn).
        rejoin_pod = f"drain-rejoin-{self.report['drains']}"
        result = self._cni(name, "add", rejoin_pod)
        with self._model_lock:
            self.report["cni_adds"] += 1
            self.live_pods[rejoin_pod] = name
            self.pod_ips[rejoin_pod] = pod_ip(result)
            self._container_ids[rejoin_pod] = \
                self.kubelets[name].invocations[-1]["container_id"]
        self._apply_k8s("pods", {
            "metadata": {"name": rejoin_pod, "namespace": "default",
                         "labels": dict(WEB)},
            "spec": {"nodeName": name},
            "status": {"podIP": self.pod_ips[rejoin_pod]},
        })
        self._mark_drill("cleared")
        self.report["drains"] += 1
        self.report["drain_rejected_adds"] += int(
            drain_status.get("rejected_adds") or 0)
        self.record("fault-done", kind="drain", agent=name,
                    rejoin_pod=rejoin_pod)

    def _healing_settled(self, name: str):
        def check() -> bool:
            beat = self.heartbeat(name)
            if not beat:
                return False
            ctl = beat.get("controller") or {}
            return (not ctl.get("healing_pending")
                    and ctl.get("healing_scheduled", 0)
                    == ctl.get("healing_completed", 0)
                    and ctl.get("healing_failed", 0) == 0)
        return check

    # ------------------------------------------------------------ the oracle

    def expected_pods(self) -> Set[str]:
        with self._model_lock:
            return {f"default/{p}" for p in self.live_pods}

    def wait_converged(self, context: str) -> bool:
        """Every agent's heartbeat must agree with the conductor's pod
        set, be alive (seq advancing), and show a settled healing
        ledger.  Datapath agents must serve every shard."""
        expected = self.expected_pods()
        # Liveness: each agent's seq must ADVANCE past what it was when
        # this check began (a frozen heartbeat with a perfect snapshot
        # is a dead agent, not a converged one).
        start_seqs = {n: (self.heartbeat(n) or {}).get("seq", -1)
                      for n in self.agent_procs}

        def agent_ok(name: str) -> bool:
            beat = self.heartbeat(name)
            if beat is None:
                return False
            if beat.get("seq", 0) <= start_seqs.get(name, -1) \
                    and start_seqs.get(name, -1) >= 0:
                return False  # heartbeat has not advanced: stalled
            if set(beat.get("pods", ())) != expected:
                return False
            ctl = beat.get("controller") or {}
            if ctl.get("healing_pending") or ctl.get("healing_failed", 0):
                return False
            if ctl.get("healing_scheduled", 0) != \
                    ctl.get("healing_completed", 0):
                return False
            dp = beat.get("datapath")
            if dp and dp["shards_serving"] != dp["shards_total"]:
                return False
            return True

        # Per-node convergence wavefront (ISSUE 10): stamp each agent's
        # FIRST ok (dropped again if it regresses before everyone else
        # arrives) — the drill timeline's "last node converged" and the
        # straggler name come from here.
        first_ok: Dict[str, float] = {}

        def sweep_ok() -> bool:
            all_good = True
            for n in self.agent_procs:
                if agent_ok(n):
                    first_ok.setdefault(n, time.time())
                else:
                    first_ok.pop(n, None)
                    all_good = False
            return all_good

        ok = wait_for(sweep_ok,
                      timeout=self.cfg.convergence_timeout,
                      interval=0.25)
        last_node, last_at = None, None
        if ok and first_ok:
            last_node = max(first_ok, key=first_ok.get)
            last_at = first_ok[last_node]
        self.last_convergence = {
            "context": context,
            "ok": ok,
            "last_node": last_node,
            "last_converged_at": last_at,
            "per_node_first_ok": {n: round(t, 3)
                                  for n, t in sorted(first_ok.items())},
        }
        if not ok:
            bad = [n for n in self.names if not agent_ok(n)]
            self.report["unconverged"] += len(bad)
            detail = {}
            for n in bad[:4]:
                beat = self.heartbeat(n) or {}
                detail[n] = {
                    "pods_delta": sorted(
                        set(beat.get("pods", ())) ^ expected)[:6],
                    "controller": beat.get("controller"),
                    "datapath": beat.get("datapath"),
                }
            self.record("unconverged", context=context, agents=bad,
                        detail=detail)
            self.report["errors"].append(
                f"unconverged after {context}: {bad}")
        else:
            self.record("converged", context=context,
                        pods=len(expected))
        # Recomputed (not accumulated): each agent's counter is already
        # cumulative over its lifetime.
        self.report["healing_failed"] = sum(
            int(((self.heartbeat(n) or {}).get("controller") or {})
                .get("healing_failed", 0))
            for n in self.agent_procs)
        return ok

    def parity_round(self, context: str) -> bool:
        """Trigger a probe round on every agent and assert zero
        mock-engine verdict mismatches on the parity cohort."""
        self.probe_round += 1
        round_no = self.probe_round
        self.client.put(PROBE_KEY, {"round": round_no})
        cohort = self.names[:self.cfg.parity_agents]

        def done(name: str) -> bool:
            beat = self.heartbeat(name)
            return bool(beat) and \
                (beat.get("parity") or {}).get("round", 0) >= round_no

        ok = wait_for(lambda: all(done(n) for n in cohort),
                      timeout=self.cfg.convergence_timeout)
        mismatches = 0
        checked = 0
        details = []
        for name in cohort:
            parity = (self.heartbeat(name) or {}).get("parity") or {}
            if parity.get("round", 0) >= round_no:
                checked += int(parity.get("checked", 0))
                mismatches += int(parity.get("mismatches", 0))
                if parity.get("mismatches"):
                    details.append({name: parity.get("detail")})
        self.report["parity_rounds"] += 1
        self.report["parity_checked"] += checked
        self.report["parity_mismatches"] += mismatches
        if not ok:
            late = [n for n in cohort if not done(n)]
            self.report["unconverged"] += len(late)
            self.report["errors"].append(
                f"parity round {round_no} never completed on {late}")
        self.record("parity", context=context, round=round_no,
                    checked=checked, mismatches=mismatches,
                    detail=details)
        return ok and mismatches == 0

    def record_cluster_evidence(self, context: str) -> None:
        """ISSUE 10 evidence: ONE full aggregator sweep over every
        agent → the best-coverage stitched propagation span (one store
        write traced across the fleet, adoption-lag percentiles,
        stragglers named) and the cluster-merged latency rollup, both
        into the jsonl record."""
        try:
            scrapes = self.scraper.scrape()
        except Exception as err:  # noqa: BLE001 - evidence, not oracle
            self.record("churn-error", op="cluster-evidence",
                        error=str(err))
            return
        spans = self.scraper.cluster_spans(scrapes, limit=0)
        stitched = spans.get("stitched") or []
        full_coverage = [s for s in stitched
                         if s["nodes"] >= len(self.agent_procs)]
        best = max(stitched, key=lambda s: (s["nodes"], s["revision"]),
                   default=None)
        if best is not None:
            self.record("cluster-span", context=context,
                        agents=len(self.agent_procs),
                        stitched_total=len(stitched),
                        full_coverage=len(full_coverage), span=best)
        latency = self.scraper.cluster_latency(scrapes)
        trimmed = {
            name: {k: v for k, v in (snap or {}).items() if k != "buckets"}
            for name, snap in (latency.get("latency") or {}).items()
        }
        skew = latency.get("skew") or {}
        self.record("cluster-latency", context=context,
                    nodes_reporting=latency.get("nodes_reporting", 0),
                    gaps=latency.get("gaps"), latency=trimmed,
                    cluster_median_us=skew.get("cluster_median_us"),
                    stragglers=skew.get("stragglers"))

    def collect_telemetry(self) -> None:
        """PR 6 evidence: propagation spans + latency histograms from a
        sample of agents, recorded alongside the soak events."""
        for name in self.names[:3]:
            rest = self.rest_of(name)
            if not rest:
                continue
            try:
                spans = _http(rest, "/contiv/v1/spans?limit=0")
                self.record("telemetry-spans", agent=name,
                            status=spans.get("status"))
            except Exception as err:  # noqa: BLE001
                self.record("churn-error", op="telemetry", error=str(err))
        for name in self.names[:self.cfg.datapath_agents]:
            rest = self.rest_of(name)
            if not rest:
                continue
            try:
                inspect = _http(rest, "/contiv/v1/inspect")
                self.record("telemetry-latency", agent=name,
                            latency=inspect.get("latency"),
                            counters={
                                k: v for k, v in
                                (inspect.get("counters") or {}).items()
                                if k.endswith("_total")})
            except Exception as err:  # noqa: BLE001
                self.record("churn-error", op="telemetry", error=str(err))

    # ------------------------------------------------------------- conduct

    def _fault_plan(self) -> List[Tuple[str, Optional[str]]]:
        cfg = self.cfg
        shard_flavors = ["eject", "swap-fail", "hang", "eject"]
        plan: List[Tuple[str, Optional[str]]] = []
        plan += [("leader-kill", None)] * cfg.leader_kills
        plan += [("shard", shard_flavors[i % len(shard_flavors)])
                 for i in range(cfg.shard_faults)]
        plan += [("agent-kill", None)] * cfg.agent_kills
        plan += [("store-outage", None)] * cfg.store_outages
        # Planned-operations drills (ISSUE 13) ride the same shuffled
        # schedule as the crash drills — churn runs through all of them.
        plan += [("rolling-upgrade", None)] * cfg.rolling_upgrades
        plan += [("membership", None)] * cfg.membership_changes
        plan += [("drain", None)] * cfg.drains
        self.rng.shuffle(plan)
        # A store outage as the very first drill would stall the first
        # churn slice's reflections before any state exists — rotate
        # until a churn-compatible drill leads (bounded: a plan of only
        # outages stays as shuffled).
        for _ in range(len(plan)):
            if plan[0][0] != "store-outage":
                break
            plan.append(plan.pop(0))
        return plan

    def conduct(self) -> Dict[str, Any]:
        cfg = self.cfg
        t0 = time.time()
        if cfg.churn_script_path:
            ops = load_churn(cfg.churn_script_path)
        else:
            ops = generate_churn(cfg)
        script_path = self.workdir / "churn_script.jsonl"
        save_churn(ops, str(script_path))   # the replayable record
        self.record("churn-script", ops=len(ops), path=str(script_path))

        plan = self._fault_plan()
        # Phase 0 churn (the initial deploys) runs alone so fault drills
        # hit a cluster that has state; from phase 1 on, churn and
        # faults run CONCURRENTLY — the combined-fire property this
        # soak exists to demonstrate.
        initial, rest = ops[:cfg.pods], ops[cfg.pods:]
        per_drill = max(1, (len(rest) + max(1, len(plan)) - 1)
                        // max(1, len(plan)))
        slices = [rest[i * per_drill:(i + 1) * per_drill]
                  for i in range(max(1, len(plan)))]

        churn = self.run_churn(initial)
        churn.join()
        self.wait_converged("initial-deploy")
        self.parity_round("initial-deploy")
        self.record_cluster_evidence("initial-deploy")

        for i, (kind, arg) in enumerate(plan):
            churn_slice = slices[i] if i < len(slices) else []
            churn = self.run_churn(churn_slice)
            # Drill evidence timeline (ISSUE 10): the monitor sweeps
            # fleet health over REST for the whole drill — armed →
            # first degraded → cleared → last converged lands in the
            # jsonl whether the drill passes or fails.
            monitor = _DrillMonitor(self.scraper, kind,
                                    interval=0.5 * self.mult)
            self._drill_monitor = monitor
            try:
                if kind == "leader-kill":
                    self.fault_leader_kill()
                elif kind == "store-outage":
                    self.fault_store_outage()
                elif kind == "agent-kill":
                    self.fault_agent_kill()
                elif kind == "shard":
                    self.fault_shard(arg)
                elif kind == "rolling-upgrade":
                    self.fault_rolling_upgrade()
                elif kind == "membership":
                    self.fault_membership()
                elif kind == "drain":
                    self.fault_drain()
            except Exception as err:  # noqa: BLE001 - incl. REST I/O errors
                # ANY drill failure (assertion or a mid-drill transport
                # error against a dying agent) is recorded and the run
                # continues — report["ok"] goes false via errors, and
                # the timeline below still ships: the crashed drill is
                # exactly the one whose forensics matter.
                self.report["errors"].append(f"{kind}: {err}")
                self.record("fault-failed", kind=kind, error=str(err))
            finally:
                churn.join()
                self.wait_converged(f"after-{kind}")
                monitor.stop()
                self._drill_monitor = None
                self.record("drill-timeline",
                            **monitor.timeline(self.last_convergence))
            self.parity_round(f"after-{kind}")

        self.record_cluster_evidence("final")
        self.collect_telemetry()
        self.report["duration_s"] = round(time.time() - t0, 1)
        self.report["churn_ops"] = len(ops)
        self.report["ok"] = (
            self.report["parity_mismatches"] == 0
            and self.report["unconverged"] == 0
            and self.report["healing_failed"] == 0
            and not self.report["errors"]
        )
        self.record("summary", **self.report)
        return self.report

    # ----------------------------------------------------------------- stop

    def stop(self) -> None:
        for proc in self.store_procs.values():
            proc.kill(signal.SIGCONT)  # un-freeze before killing
        for proc in list(self.agent_procs.values()):
            proc.kill(signal.SIGTERM)
        for proc in list(self.agent_procs.values()):
            proc.reap()
        for proc in self.store_procs.values():
            proc.kill()
            proc.reap()
        if self.client is not None:
            self.client.close()
        if self._out_fh is not None:
            self._out_fh.close()


def run_soak(cfg: SoakConfig) -> Dict[str, Any]:
    cluster = SoakCluster(cfg)
    try:
        cluster.start()
        return cluster.conduct()
    finally:
        cluster.stop()
