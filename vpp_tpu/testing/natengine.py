"""Mock NAT engine — the NAT44 semantics oracle.

Analog of ``mock/natplugin/natplugin_mock.go``: consumes the compiled
DNAT mapping state and simulates per-flow NAT processing in plain
Python, defining the exact semantics the TPU ``nat_step`` kernel must
reproduce — including the flow-hash backend pick (same mixer, same
bucket ring) so backend choices are bit-for-bit comparable.

Also exposes the mapping-level assertions the reference mock provides
(HasStaticMapping :502 etc.) for control-plane tests.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.nat import (
    NatMapping,
    PROBE_WAYS,
    TWICE_NAT_ENABLED,
    TWICE_NAT_SELF,
    _mix_py as _mix,
    bucket_ring,
    effective_bucket_size,
)
from ..ops.packets import ip_to_u32, u32_to_ip


def flow_hash_py(src_ip: int, dst_ip: int, proto: int, src_port: int, dst_port: int) -> int:
    """Python replica of ops.nat.flow_hash (must stay in lockstep)."""
    h = (src_ip * 0x9E3779B1) & 0xFFFFFFFF
    h = _mix(h ^ dst_ip)
    h = _mix(h ^ ((proto << 16) & 0xFFFFFFFF) ^ src_port)
    h = _mix(h ^ dst_port)
    return h


@dataclass
class Flow:
    src_ip: int
    dst_ip: int
    proto: int
    src_port: int
    dst_port: int

    @classmethod
    def make(cls, src_ip, dst_ip, proto, src_port, dst_port) -> "Flow":
        return cls(ip_to_u32(src_ip), ip_to_u32(dst_ip), int(proto), int(src_port), int(dst_port))

    def key(self) -> Tuple:
        return (self.src_ip, self.dst_ip, self.proto, self.src_port, self.dst_port)

    def __str__(self) -> str:
        return (
            f"{u32_to_ip(self.src_ip)}:{self.src_port} -> "
            f"{u32_to_ip(self.dst_ip)}:{self.dst_port} ({self.proto})"
        )


@dataclass
class FlowResult:
    flow: Flow
    dnat: bool = False
    reply: bool = False
    snat: bool = False
    punt: bool = False  # session not recordable -> host slow path


class MockNatEngine:
    """Semantics mirror of the nat_step kernel."""

    def __init__(
        self,
        nat_loopback: str = "0.0.0.0",
        snat_ip: str = "0.0.0.0",
        snat_enabled: bool = False,
        pod_subnet: str = "10.1.0.0/16",
        bucket_size: int = 64,
        session_capacity: int = 65536,
    ):
        self.mappings: List[NatMapping] = []
        self._k_ring = bucket_size
        self._rings: List[Optional[List[Tuple[int, int]]]] = []
        self.nat_loopback = ip_to_u32(nat_loopback)
        self.snat_ip = ip_to_u32(snat_ip)
        self.snat_enabled = snat_enabled
        self.pod_subnet = ipaddress.ip_network(pod_subnet)
        self.bucket_size = bucket_size
        self.session_capacity = session_capacity
        # slot -> (reply key tuple, restore (src_ip, src_port, dst_ip, dst_port))
        self.sessions: Dict[int, Tuple[Tuple, Tuple]] = {}
        # ClientIP affinity pins: (client_ip, ext_ip, ext_port, proto)
        # -> (backend_ip, backend_port, last_seen).  Mirrors the
        # kernel's AFFINITY_FLAG entries, which key by the EXTERNAL
        # tuple — never by mapping-row index, which table rebuilds
        # reorder.  Expiry happens only via sweep_affinity (device
        # entries likewise expire only via the host sweep).
        self.affinity: Dict[Tuple[int, int, int, int], Tuple[int, int, int]] = {}

    # ---------------------------------------------------------- assertions

    def set_mappings(self, mappings: Sequence[NatMapping]) -> None:
        self.mappings = list(mappings)
        # Ring layout cached here — the only place mappings change —
        # using the SAME helpers the compiled tables use (lockstep by
        # construction, no per-flow rebuild).
        self._k_ring = effective_bucket_size(self.mappings, self.bucket_size)
        self._rings = [
            bucket_ring(m, self._k_ring) if m.backends else None
            for m in self.mappings
        ]

    def sweep_affinity(self, now: int, ts_per_second: float = 1.0) -> int:
        """Expire affinity pins idle past their mapping's timeout
        (mirror of ops.nat.sweep_affinity); returns entries removed.

        The pin's mapping is resolved from its external tuple against
        the CURRENT mappings, exactly like the kernel: a pin whose
        tuple no longer names an affinity mapping is dropped outright,
        while a mapping whose backends transiently emptied still
        anchors its pins (the ride-out-the-endpoint-flap semantic)."""
        removed = 0
        for key, (_bip, _bport, seen) in list(self.affinity.items()):
            _client, ext_ip, ext_port, proto = key
            timeout = next(
                (m.session_affinity_timeout for m in self.mappings
                 if ip_to_u32(m.external_ip) == ext_ip
                 and m.external_port == ext_port
                 and m.protocol == proto
                 and m.session_affinity_timeout > 0),
                None,
            )
            if timeout is None or now - seen > timeout * ts_per_second:
                del self.affinity[key]
                removed += 1
        return removed

    def has_static_mapping(self, external_ip: str, external_port: int, protocol: int) -> bool:
        ip = ip_to_u32(external_ip)
        return any(
            ip_to_u32(m.external_ip) == ip
            and m.external_port == external_port
            and m.protocol == protocol
            and m.backends
            for m in self.mappings
        )

    def backends_of(self, external_ip: str, external_port: int) -> List[Tuple[str, int, int]]:
        ip = ip_to_u32(external_ip)
        for m in self.mappings:
            if ip_to_u32(m.external_ip) == ip and m.external_port == external_port:
                return list(m.backends)
        return []

    # ------------------------------------------------------------- traffic

    def process(self, flow: Flow, timestamp: int = 0) -> FlowResult:
        """Mirror of nat_step for one flow: reply -> DNAT -> SNAT."""
        result = FlowResult(flow=Flow(*flow.key()))
        f = result.flow

        # 1. Reply restoration (W-way probe ring, matching the kernel).
        base = flow_hash_py(*f.key()) & (self.session_capacity - 1)
        for w in range(PROBE_WAYS):
            entry = self.sessions.get((base + w) & (self.session_capacity - 1))
            if entry is not None and entry[0] == f.key():
                orig_src_ip, orig_src_port, orig_dst_ip, orig_dst_port = entry[1]
                f.src_ip, f.src_port = orig_dst_ip, orig_dst_port
                f.dst_ip, f.dst_port = orig_src_ip, orig_src_port
                result.reply = True
                return result

        orig = flow.key()

        # 2. DNAT (first mapping wins, matching the kernel's argmax).
        for mi, mapping in enumerate(self.mappings):
            if not mapping.backends:
                continue
            if (
                ip_to_u32(mapping.external_ip) == f.dst_ip
                and mapping.external_port == f.dst_port
                and mapping.protocol == f.proto
            ):
                if mapping.session_affinity_timeout > 0:
                    h = _mix((f.src_ip * 0x9E3779B1) & 0xFFFFFFFF)
                else:
                    h = flow_hash_py(*f.key())
                ring = self._rings[mi]
                b_ip, b_port = ring[h % len(ring)]
                if mapping.session_affinity_timeout > 0:
                    # A live pin overrides the hash pick and refreshes;
                    # a miss pins the pick made this packet.  Keyed by
                    # the external tuple (like the kernel's key row).
                    akey = (f.src_ip, f.dst_ip, f.dst_port, f.proto)
                    pin = self.affinity.get(akey)
                    if pin is not None:
                        b_ip, b_port = pin[0], pin[1]
                    self.affinity[akey] = (b_ip, b_port, timestamp)
                hairpin = (
                    mapping.twice_nat == TWICE_NAT_ENABLED
                    or (mapping.twice_nat == TWICE_NAT_SELF and b_ip == f.src_ip)
                )
                f.dst_ip, f.dst_port = b_ip, b_port
                if hairpin:
                    f.src_ip = self.nat_loopback
                result.dnat = True
                break

        # 3. SNAT for pod egress.
        if not result.dnat:
            in_cluster = ipaddress.ip_address(f.dst_ip) in self.pod_subnet
            from_pod = ipaddress.ip_address(f.src_ip) in self.pod_subnet
            if self.snat_enabled and from_pod and not in_cluster:
                h = flow_hash_py(*orig)
                f.src_ip = self.snat_ip
                f.src_port = (h % 32768) + 32768
                result.snat = True

        # 4. Session recording, keyed by the expected reply tuple, with
        # W-way probed insertion (no eviction; collision/overflow punts).
        if result.dnat or result.snat:
            reply_key = (f.dst_ip, f.src_ip, f.proto, f.dst_port, f.src_port)
            base = flow_hash_py(*reply_key) & (self.session_capacity - 1)
            orig_src_ip, orig_dst_ip, _, orig_src_port, orig_dst_port = orig
            restore = (orig_src_ip, orig_src_port, orig_dst_ip, orig_dst_port)
            chosen = None
            collision = False
            for w in range(PROBE_WAYS):
                slot = (base + w) & (self.session_capacity - 1)
                entry = self.sessions.get(slot)
                if entry is None:
                    if chosen is None:
                        chosen = slot
                elif entry[0] == reply_key:
                    if entry[1] == restore:
                        chosen = slot  # refresh own session
                        break
                    collision = True  # another flow owns this reply key
                    break
            if collision or chosen is None:
                result.punt = True
            else:
                self.sessions[chosen] = (reply_key, restore)
        return result
