"""Mock session-layer engine — the SessionRuleChannel test double.

Analog of ``mock/sessionrules/sessionrules_mock.go``: stands in for the
host shim's session layer, accepting add/delete batches from the
session renderer and maintaining the rule tables the way the real
stack would — one global table plus one table per application
namespace.  Exposes the same assertion surface as the reference mock
(LocalTable(ns).NumOfRules/HasRule :99-135, GetReqCount/GetErrCount
:89-96) plus ``preinstall`` and ``dump`` for resync scenarios.

Error accounting mirrors addDelRule :344: removing a rule that is not
installed (exact match including tag) or adding a duplicate counts as
an error and leaves the tables unchanged.
"""

from __future__ import annotations

import ipaddress
from typing import Dict, List, Optional, Sequence, Set

from ..models import ProtocolType
from ..policy.renderer.session import (
    ACTION_ALLOW,
    ACTION_DENY,
    SCOPE_GLOBAL,
    SCOPE_LOCAL,
    SessionRule,
    SessionRuleChannel,
)


def _net(cidr: str) -> Optional[ipaddress.IPv4Network]:
    if not cidr:
        return None
    if "/" not in cidr:
        cidr += "/32"
    return ipaddress.IPv4Network(cidr, strict=False)


class _TableCheck:
    """Assertion helpers over one rule set (sessionrules_mock.go
    LocalTableCheck/GlobalTableCheck)."""

    def __init__(self, rules: Set[SessionRule]):
        self._rules = rules

    def num_rules(self) -> int:
        return len(self._rules)

    def has_rule(
        self,
        lcl_ip: str,
        lcl_port: int,
        rmt_ip: str,
        rmt_port: int,
        proto: str,
        action: str,
    ) -> bool:
        """Presence check by value; ``""`` means 0/0, a bare IP means
        /32; tag is ignored (hasRule :137)."""
        lcl = _net(lcl_ip)
        rmt = _net(rmt_ip)
        want_proto = ProtocolType[proto]
        want_action = ACTION_ALLOW if action.upper() == "ALLOW" else ACTION_DENY
        return any(
            r.lcl_ip == lcl
            and r.lcl_port == lcl_port
            and r.rmt_ip == rmt
            and r.rmt_port == rmt_port
            and r.transport_proto is want_proto
            and r.action == want_action
            for r in self._rules
        )


class MockSessionEngine(SessionRuleChannel):
    """In-memory session-rule tables with reference-mock semantics."""

    def __init__(self):
        self._global: Set[SessionRule] = set()
        self._local: Dict[int, Set[SessionRule]] = {}
        self.req_count = 0
        self.err_count = 0

    # ------------------------------------------------------------- channel

    def apply(
        self, added: Sequence[SessionRule], removed: Sequence[SessionRule]
    ) -> None:
        for rule in removed:
            self.req_count += 1
            table = self._table_for(rule)
            if rule in table:
                table.discard(rule)
            else:
                self.err_count += 1
        for rule in added:
            self.req_count += 1
            table = self._table_for(rule)
            if rule in table:
                self.err_count += 1
            else:
                table.add(rule)

    def dump(self) -> List[SessionRule]:
        rules = list(self._global)
        for table in self._local.values():
            rules.extend(table)
        return rules

    # -------------------------------------------------------------- helpers

    def _table_for(self, rule: SessionRule) -> Set[SessionRule]:
        if rule.scope == SCOPE_GLOBAL:
            return self._global
        return self._local.setdefault(rule.appns_index, set())

    def preinstall(self, rule: SessionRule) -> None:
        """Install a rule behind the renderer's back (resync tests)."""
        self._table_for(rule).add(rule)

    def clear(self) -> None:
        self._global.clear()
        self._local.clear()
        self.req_count = 0
        self.err_count = 0

    # --------------------------------------------------------------- checks

    def local_table(self, ns_index: int) -> _TableCheck:
        return _TableCheck(self._local.get(ns_index, set()))

    def global_table(self) -> _TableCheck:
        return _TableCheck(self._global)

    def num_tables(self) -> int:
        """Namespaces with at least one rule, plus the global table if
        non-empty."""
        n = sum(1 for t in self._local.values() if t)
        return n + (1 if self._global else 0)
