"""In-process cluster simulation — the Robot/Vagrant suite analog.

The reference's system tests (tests/robot/suites/: one_node_two_pods,
two_node_two_pods, the policy suite) bring up real multi-VM clusters
with kubeadm and assert connectivity + ``vppctl`` dump contents.  This
harness stands up the same topology in one process:

- a shared ``KVStore`` (the cluster etcd),
- a ``FakeK8sCluster`` + KSR on the master (the K8s API path),
- per node a FULL agent — NodeSync, PodManager, IPv4Net (+host-FIB
  mock), policy stack (TPU renderer + verdict oracle), service stack
  (TPU NAT renderer) — under a real controller event loop + dbwatcher,
- the TPU data plane evaluated through the real jit pipeline.

Connectivity checks run the actual classify->NAT->route pipeline on the
source node's tensors and (for cross-node flows) the destination
node's, mirroring where the reference enforces each ACL side.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..conf import NetworkConfig
from ..controller.dbwatcher import DBWatcher
from ..controller.eventloop import Controller
from ..ipam import IPAM
from ..ipv4net import IPv4Net
from ..ksr import KSRPlugin, KVBroker
from ..kvstore import KVStore
from ..models import PodID
from ..nodesync import NodeSync
from ..ops.nat import empty_sessions
from ..ops.packets import make_batch
from ..ops.pipeline import ROUTE_REMOTE, make_route_config, pipeline_step
from ..podmanager import PodManager
from ..policy import PolicyPlugin
from ..policy.renderer.sched import SchedPolicyRenderer
from ..scheduler import TxnScheduler
from ..scheduler.tpu_applicators import TpuAclApplicator, TpuNatApplicator
from ..service import ServicePlugin
from ..service.renderer.sched import SchedNatRenderer
from .aclengine import MockACLEngine, Verdict
from .hostfib import MockHostFIB
from .k8s import FakeK8sCluster


_TIMEOUT_MULT: Optional[float] = None


def timeout_mult() -> float:
    """Machine-speed timeout multiplier for every test wait (VERDICT r4
    item 4: fixed wall-clock deadlines on a loaded 1-core box flake).

    ``VPP_TPU_TEST_TIMEOUT_MULT`` pins it explicitly; otherwise a
    one-shot CPU probe measures how slow this machine currently is
    relative to an unloaded fast core and scales every ``wait_for``
    (and the tests' manual deadlines) accordingly — a box running a
    competing full-load process probes ~2x and gets double deadlines.
    Never below 1.0: fast machines keep the written timeouts.
    """
    global _TIMEOUT_MULT
    if _TIMEOUT_MULT is None:
        env = float(os.environ.get("VPP_TPU_TEST_TIMEOUT_MULT", 0) or 0)
        if env > 0:
            _TIMEOUT_MULT = env
        else:
            # ~25 ms of pure-Python work on this class of core when
            # unloaded (masked accumulator — an unbounded int would
            # grow into bignum arithmetic and skew the probe).
            t0 = time.perf_counter()
            acc = 0
            for i in range(300_000):
                acc = (acc + (i ^ (acc >> 3))) & 0xFFFFFFFF
            probe = time.perf_counter() - t0
            _TIMEOUT_MULT = min(8.0, max(1.0, probe / 0.025))
    return _TIMEOUT_MULT


def free_ports(n: int) -> List[int]:
    """``n`` currently-free TCP ports (bind :0, read, close) — the one
    shared allocator for every multi-process harness (HA ensembles, the
    chaos soak, the OS-process tests); inherently racy between close
    and the child's bind, like every ephemeral-port scheme."""
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def wait_for(cond, timeout: float = 5.0, interval: float = 0.02) -> bool:
    """Poll ``cond`` until true or until ``timeout`` (scaled by the
    machine-speed multiplier) expires."""
    deadline = time.time() + timeout * timeout_mult()
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return bool(cond())


class SimNode:
    """One simulated vswitch node: the full agent plugin stack."""

    def __init__(self, cluster: "SimCluster", name: str,
                 mirror_path: Optional[str] = None):
        self.cluster = cluster
        self.name = name
        store = cluster.store

        self.nodesync = NodeSync(store, node_name=name)
        self.nodesync.allocate_id()
        self.config = NetworkConfig()
        self.ipam = IPAM(self.config.ipam, self.nodesync.node_id)

        self.podmanager = PodManager()
        self.fib = MockHostFIB()
        self.ipv4net = IPv4Net(
            self.config, self.nodesync, ipam=self.ipam,
            podmanager=self.podmanager,
        )

        # TPU device tables go through the txn scheduler (VERDICT r1 #4):
        # renderers emit KVs into the event txn, applicators own the
        # atomic compile+swap per transaction.
        self.acl_applicator = TpuAclApplicator()
        self.policy_renderer = SchedPolicyRenderer(
            lambda: self.controller.current_txn, applicator=self.acl_applicator
        )
        self.oracle = MockACLEngine()
        self.policy = PolicyPlugin(ipam=self.ipam)
        self.policy.register_renderer(self.policy_renderer)
        self.policy.register_renderer(self.oracle)

        self.nat_applicator = TpuNatApplicator()
        self.nat_renderer = SchedNatRenderer(
            lambda: self.controller.current_txn,
            nat_loopback=str(self.ipam.nat_loopback_ip()),
            snat_ip=f"192.168.16.{self.nodesync.node_id}",
            snat_enabled=True,
            pod_subnet=str(self.ipam.pod_subnet_all_nodes),
            applicator=self.nat_applicator,
        )
        self.service = ServicePlugin(name, ipam=self.ipam, nodesync=self.nodesync)
        self.service.register_renderer(self.nat_renderer)

        self.scheduler = TxnScheduler()
        self.scheduler.register_applicator(self.fib)
        self.scheduler.register_applicator(self.acl_applicator)
        self.scheduler.register_applicator(self.nat_applicator)
        self.controller = Controller(
            handlers=[
                self.nodesync, self.podmanager, self.ipv4net,
                self.service, self.policy,
            ],
            sink=self.scheduler,
            healing_delay=0.05,
        )
        self.podmanager.event_loop = self.controller
        self.nodesync.event_loop = self.controller
        self.controller.start()
        self.watcher = DBWatcher(self.controller, store, mirror_path=mirror_path)
        self.watcher.start()

    # ----------------------------------------------------------- data plane

    def send(self, flows: List[Tuple], sessions=None, ts: int = 0):
        """Run a batch of 5-tuples through this node's pipeline."""
        acl = self.policy_renderer.tables
        nat = self.nat_renderer.tables
        if acl is None:  # before the first committed resync
            from ..ops.classify import build_rule_tables

            acl = build_rule_tables([], {})
        if nat is None:
            from ..ops.nat import build_nat_tables

            nat = build_nat_tables([])
        route = make_route_config(self.ipam)
        sessions = sessions if sessions is not None else empty_sessions(1024)
        return pipeline_step(
            acl, nat, route, sessions, make_batch(flows), jnp.int32(ts)
        )

    def stop(self) -> None:
        self.watcher.stop()
        self.controller.stop()


class SimCluster:
    """The cluster: shared state store, K8s API + KSR, N agent nodes.

    ``store`` defaults to an in-process :class:`KVStore`; chaos/HA
    harnesses inject a networked client instead (a ``RemoteKVStore``
    pointed at a ``KVStoreServer`` or at an HA ensemble's member list),
    and every component — KSR writes, nodesync allocation, dbwatcher
    streams — crosses the socket exactly as in a real deployment.
    """

    def __init__(self, store=None):
        self.store = store if store is not None else KVStore()
        self.k8s = FakeK8sCluster()
        self.ksr = KSRPlugin(self.k8s, KVBroker(self.store))
        self.ksr.init(start_monitor=False)
        self.nodes: Dict[str, SimNode] = {}
        self._pod_nodes: Dict[PodID, str] = {}

    # -------------------------------------------------------------- topology

    def add_node(self, name: str) -> SimNode:
        node = SimNode(self, name)
        self.nodes[name] = node
        return node

    def stop(self) -> None:
        for node in self.nodes.values():
            node.stop()

    # ------------------------------------------------------------- "kubectl"

    def deploy_pod(
        self,
        node_name: str,
        name: str,
        namespace: str = "default",
        labels: Optional[Dict[str, str]] = None,
    ) -> str:
        """CNI Add on the node + reflected K8s pod object; returns IP."""
        node = self.nodes[node_name]
        reply = node.podmanager.add_pod(name, namespace)
        ip = reply.ip_address.split("/")[0]
        self.k8s.apply("pods", {
            "metadata": {"name": name, "namespace": namespace,
                         "labels": labels or {}},
            "spec": {"nodeName": node_name},
            "status": {"podIP": ip},
        })
        pod_id = PodID(name, namespace)
        self._pod_nodes[pod_id] = node_name
        # Register with every node's oracle (local vs remote).
        for n in self.nodes.values():
            n.oracle.register_pod(pod_id, ip, another_node=(n.name != node_name))
        return ip

    def delete_pod(self, name: str, namespace: str = "default") -> None:
        pod_id = PodID(name, namespace)
        node = self.nodes[self._pod_nodes.pop(pod_id)]
        node.podmanager.delete_pod(name, namespace)
        self.k8s.delete("pods", name, namespace)

    def apply_policy(self, manifest: Dict) -> None:
        self.k8s.apply("networkpolicies", manifest)

    def delete_policy(self, name: str, namespace: str = "default") -> None:
        self.k8s.delete("networkpolicies", name, namespace)

    def apply_service(self, manifest: Dict) -> None:
        self.k8s.apply("services", manifest)

    def apply_endpoints(self, manifest: Dict) -> None:
        self.k8s.apply("endpoints", manifest)

    # ----------------------------------------------------------- connectivity

    def pod_ip(self, name: str, namespace: str = "default") -> str:
        node = self.nodes[self._pod_nodes[PodID(name, namespace)]]
        return str(node.ipam.get_pod_ip(PodID(name, namespace)))

    def can_connect(
        self,
        src: str,
        dst: str,
        dst_port: int = 80,
        protocol: int = 6,
        namespace: str = "default",
        src_port: int = 12345,
    ) -> bool:
        """End-to-end connection check through the real pipeline.

        Evaluates on the source pod's node; if the flow routes to
        another node, the (possibly rewritten) packet is re-evaluated on
        the destination node — each ACL side is enforced where the
        reference enforces it.
        """
        src_id, dst_id = PodID(src, namespace), PodID(dst, namespace)
        src_node = self.nodes[self._pod_nodes[src_id]]
        flow = (
            self.pod_ip(src, namespace), self.pod_ip(dst, namespace),
            protocol, src_port, dst_port,
        )
        res = src_node.send([flow])
        if not bool(res.allowed[0]):
            return False
        if int(res.route[0]) == ROUTE_REMOTE:
            # Re-evaluate with the tuple the wire would carry: the source
            # node's pipeline may have NAT-rewritten the packet (service
            # DNAT/SNAT), and the destination node judges what arrives.
            wire_flow = (
                int(res.batch.src_ip[0]), int(res.batch.dst_ip[0]),
                int(res.batch.protocol[0]),
                int(res.batch.src_port[0]), int(res.batch.dst_port[0]),
            )
            dst_node = self.nodes[self._pod_nodes[dst_id]]
            res2 = dst_node.send([wire_flow])
            return bool(res2.allowed[0])
        return True

    def oracle_verdict(
        self,
        src: str,
        dst: str,
        dst_port: int = 80,
        protocol=None,
        namespace: str = "default",
    ) -> bool:
        """The mock-ACL-engine verdict for the same connection, combined
        across the source and destination nodes' oracles."""
        from ..models import ProtocolType

        protocol = protocol or ProtocolType.TCP
        src_id, dst_id = PodID(src, namespace), PodID(dst, namespace)
        for node_name in {self._pod_nodes[src_id], self._pod_nodes[dst_id]}:
            verdict = self.nodes[node_name].oracle.connection_pod_to_pod(
                src_id, dst_id, protocol=protocol, dst_port=dst_port
            )
            if verdict is not Verdict.ALLOWED:
                return False
        return True

    def assert_matrix_matches_oracle(self, pods: List[str], ports: List[int]) -> None:
        """Every (src, dst, port) combination must agree between the TPU
        pipeline and the oracle engine — the bit-for-bit parity check."""
        for src in pods:
            for dst in pods:
                if src == dst:
                    continue
                for port in ports:
                    tpu = self.can_connect(src, dst, dst_port=port)
                    oracle = self.oracle_verdict(src, dst, dst_port=port)
                    assert tpu == oracle, (
                        f"verdict mismatch {src}->{dst}:{port} "
                        f"tpu={tpu} oracle={oracle}"
                    )
