"""Fake kubelet — execs the REAL CNI shim the way kubelet does.

Every prior CNI test called the shim's ``main()`` in-process; nothing
kubelet-shaped had ever touched the artifacts a cluster actually runs
on: the conflist the DaemonSet installs into ``/etc/cni/net.d``, the
wrapper binary it writes into ``/opt/cni/bin``, and the CNI exec
protocol (CNI_* environment + netconf on stdin + result JSON on stdout)
between them.  This harness closes that gap (ROADMAP #3 / VERDICT r5
gaps #2-#3):

- it PARSES the real ``deploy/cni/10-vpp-tpu.conflist`` (the file the
  install-cni init container copies onto every host) and refuses to run
  if the ``vpp-tpu-cni`` plugin entry is missing;
- ``add``/``delete`` EXEC the real shim binary (``python -m
  vpp_tpu.cni.shim`` — exactly what the installed ``vpp-tpu-cni``
  wrapper script execs) as a subprocess with kubelet's CNI_* env and
  the conflist-derived netconf on stdin, against a LIVE agent's CNI
  gRPC server — or its REST fallback route (``transport="http"``, the
  grpc-less-host path, forced via ``VPP_TPU_CNI_TRANSPORT``);
- :func:`validate_manifests` cross-checks the rendered chart and the
  static k8s manifest against what the harness actually invoked: same
  conflist file, same plugin-type→binary name, same shim module, same
  gRPC/REST ports — so the manifests can no longer drift from the
  tested path.

The only divergence from a host kubelet: the conflist's grpcServer/
httpServer addresses are overridden per invocation to reach the target
agent's ephemeral test ports (the DaemonSet reaches its agent on fixed
host ports; tests cannot).  The override rides the netconf exactly
where the production values sit, so the shim's parsing path is
identical.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import threading
from typing import Any, Dict, List, Optional

PLUGIN_TYPE = "vpp-tpu-cni"
REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
DEFAULT_CONFLIST = REPO_ROOT / "deploy" / "cni" / "10-vpp-tpu.conflist"
SHIM_MODULE = "vpp_tpu.cni.shim"


class CNIError(RuntimeError):
    """A CNI invocation failed: carries the spec error object."""

    def __init__(self, command: str, code: int, msg: str, returncode: int):
        super().__init__(f"CNI {command} failed (code {code}): {msg}")
        self.command = command
        self.code = code
        self.msg = msg
        self.returncode = returncode


def pod_ip(result: Dict[str, Any]) -> str:
    """The allocated pod IP of an ADD result (address sans prefix)."""
    return result["ips"][0]["address"].split("/")[0]


class FakeKubelet:
    """Drives pod ADD/DEL through the real CNI shim binary."""

    def __init__(
        self,
        grpc_server: Optional[str] = None,
        http_server: Optional[str] = None,
        conflist_path: Optional[str] = None,
        transport: str = "grpc",
        python: str = sys.executable,
        timeout: float = 60.0,
    ):
        if transport not in ("grpc", "http"):
            raise ValueError(f"transport must be grpc|http, not {transport!r}")
        self.conflist_path = pathlib.Path(conflist_path or DEFAULT_CONFLIST)
        with open(self.conflist_path) as fh:
            self.conflist = json.load(fh)
        plugins = [p for p in self.conflist.get("plugins", [])
                   if p.get("type") == PLUGIN_TYPE]
        if not plugins:
            raise ValueError(
                f"{self.conflist_path} has no plugin of type "
                f"{PLUGIN_TYPE!r} — nothing for kubelet to exec")
        self.plugin = plugins[0]
        self.grpc_server = grpc_server
        self.http_server = http_server
        self.transport = transport
        self.python = python
        self.timeout = timeout
        self._lock = threading.Lock()
        self._seq = 0
        self.invocations: List[Dict[str, Any]] = []  # exec evidence

    # ----------------------------------------------------------- netconf

    def netconf(self) -> Dict[str, Any]:
        """The network config kubelet passes on stdin: the conflist's
        vpp-tpu-cni plugin entry plus the list-level name/cniVersion
        (the CNI runtime's plugin-conf merge), with the agent address
        override applied in place of the production host ports."""
        conf = dict(self.plugin)
        conf["name"] = self.conflist.get("name", "")
        conf["cniVersion"] = self.conflist.get("cniVersion", "")
        if self.grpc_server:
            conf["grpcServer"] = self.grpc_server
        if self.http_server:
            conf["httpServer"] = self.http_server
        return conf

    # -------------------------------------------------------------- exec

    def _exec(self, command: str, pod_name: str, namespace: str,
              container_id: Optional[str], netns: Optional[str]) -> dict:
        with self._lock:
            self._seq += 1
            seq = self._seq
        container_id = container_id or f"cni-{pod_name}-{seq}"
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "CNI_COMMAND": command,
            "CNI_CONTAINERID": container_id,
            "CNI_NETNS": netns or f"/proc/{seq}/ns/net",
            "CNI_IFNAME": "eth0",
            "CNI_ARGS": (
                f"IgnoreUnknown=1;K8S_POD_NAMESPACE={namespace};"
                f"K8S_POD_NAME={pod_name};"
                f"K8S_POD_INFRA_CONTAINER_ID={container_id}"
            ),
            "CNI_PATH": "/opt/cni/bin",
        })
        if self.transport == "http":
            env["VPP_TPU_CNI_TRANSPORT"] = "http"
        proc = subprocess.run(
            [self.python, "-m", SHIM_MODULE],
            input=json.dumps(self.netconf()),
            capture_output=True, text=True,
            cwd=str(REPO_ROOT), env=env, timeout=self.timeout,
        )
        record = {
            "command": command,
            "pod": f"{namespace}/{pod_name}",
            "container_id": container_id,
            "transport": self.transport,
            "rc": proc.returncode,
        }
        with self._lock:
            self.invocations.append(record)
        try:
            result = json.loads(proc.stdout) if proc.stdout.strip() else {}
        except ValueError as err:
            raise CNIError(
                command, -1,
                f"shim printed non-JSON: {proc.stdout!r} "
                f"(stderr: {proc.stderr!r})", proc.returncode) from err
        if proc.returncode != 0:
            raise CNIError(command, int(result.get("code", -1)),
                           str(result.get("msg", proc.stderr)),
                           proc.returncode)
        return result

    def add(self, pod_name: str, namespace: str = "default",
            container_id: Optional[str] = None,
            netns: Optional[str] = None) -> dict:
        """CNI ADD; returns the spec 0.3.1 result JSON (ips/routes)."""
        result = self._exec("ADD", pod_name, namespace, container_id, netns)
        if result.get("cniVersion") != self.conflist.get("cniVersion"):
            raise CNIError("ADD", -1,
                           f"result cniVersion {result.get('cniVersion')!r}"
                           f" != conflist {self.conflist.get('cniVersion')!r}",
                           0)
        if not result.get("ips"):
            raise CNIError("ADD", -1, f"result has no ips: {result}", 0)
        return result

    def delete(self, pod_name: str, namespace: str = "default",
               container_id: Optional[str] = None,
               netns: Optional[str] = None) -> dict:
        return self._exec("DEL", pod_name, namespace, container_id, netns)

    def version(self) -> dict:
        """CNI VERSION through the exec protocol (no agent involved)."""
        env = dict(os.environ, CNI_COMMAND="VERSION")
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [self.python, "-m", SHIM_MODULE], input="",
            capture_output=True, text=True,
            cwd=str(REPO_ROOT), env=env, timeout=self.timeout,
        )
        return json.loads(proc.stdout)


# ---------------------------------------------------------------------------
# Manifest cross-validation: the deploy artifacts must describe exactly
# the invocation path the harness exercises.
# ---------------------------------------------------------------------------


def _agent_daemonset(docs) -> Dict[str, Any]:
    for doc in docs:
        if doc and doc.get("kind") == "DaemonSet" \
                and doc["metadata"]["name"] == "vpp-tpu-agent":
            return doc
    raise AssertionError("no vpp-tpu-agent DaemonSet in the manifests")


def _arg_value(args: List[str], flag: str) -> Optional[str]:
    """``--flag=value`` or ``--flag value`` from a container args list."""
    for i, arg in enumerate(args):
        if arg.startswith(flag + "="):
            return arg.split("=", 1)[1]
        if arg == flag and i + 1 < len(args):
            return args[i + 1]
    return None


def _validate_daemonset(kubelet: FakeKubelet, docs,
                        source: str) -> Dict[str, Any]:
    ds = _agent_daemonset(docs)
    spec = ds["spec"]["template"]["spec"]
    install = next(c for c in spec["initContainers"]
                   if c["name"] == "install-cni")
    install_text = " ".join(install.get("args", []))

    # 1. The conflist the init container installs is the FILE this
    # harness parsed (path inside the image mirrors the repo layout).
    rel = kubelet.conflist_path.relative_to(REPO_ROOT).as_posix()
    assert rel in install_text, (
        f"{source}: install-cni does not install {rel} "
        f"(args: {install_text!r})")
    assert kubelet.conflist_path.name in install_text

    # 2. The binary name written into /opt/cni/bin matches the plugin
    # type kubelet resolves from the conflist — a renamed plugin type
    # would leave kubelet exec'ing a binary that does not exist.
    assert f"/host/opt/cni/bin/{PLUGIN_TYPE}" in install_text, (
        f"{source}: install-cni does not write the {PLUGIN_TYPE!r} binary")

    # 3. The wrapper execs the SAME shim module this harness execs.
    assert SHIM_MODULE in install_text, (
        f"{source}: the CNI wrapper does not exec {SHIM_MODULE}")

    # 4. The agent's ports match the conflist's server addresses: the
    # shim dials grpcServer/httpServer from the netconf, so a port
    # drift between ConfigMap-land and conflist-land bricks every ADD.
    agent = spec["containers"][0]
    cni_port = _arg_value(agent["args"], "--cni-port")
    rest_port = _arg_value(agent["args"], "--rest-port")
    grpc_port = kubelet.plugin["grpcServer"].rsplit(":", 1)[1]
    http_port = kubelet.plugin["httpServer"].rsplit(":", 1)[1]
    assert cni_port == grpc_port, (
        f"{source}: agent --cni-port={cni_port} but conflist grpcServer "
        f"port is {grpc_port}")
    assert rest_port == http_port, (
        f"{source}: agent --rest-port={rest_port} but conflist httpServer "
        f"port is {http_port}")
    return {
        "source": source,
        "conflist": rel,
        "plugin_type": PLUGIN_TYPE,
        "shim_module": SHIM_MODULE,
        "cni_port": cni_port,
        "rest_port": rest_port,
    }


def validate_manifests(kubelet: FakeKubelet) -> List[Dict[str, Any]]:
    """Validate the static k8s manifest AND the default chart render
    against the invocation path the harness exercises; returns one
    evidence record per source, raises AssertionError on any drift."""
    import importlib.util

    import yaml

    results = []
    static = list(yaml.safe_load_all(
        (REPO_ROOT / "deploy" / "k8s" / "vpp-tpu.yaml").read_text()))
    results.append(_validate_daemonset(kubelet, static, "deploy/k8s"))

    # Render the chart with default values through its real entrypoint.
    spec = importlib.util.spec_from_file_location(
        "render_chart", REPO_ROOT / "scripts" / "render_chart.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    import contextlib
    import io

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = mod.main([])
    assert rc == 0, "chart render failed"
    rendered = list(yaml.safe_load_all(out.getvalue()))
    results.append(_validate_daemonset(kubelet, rendered, "deploy/chart"))
    return results
