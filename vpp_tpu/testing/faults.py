"""Fault-injection harness for the datapath fault-domain layer.

The reference validates its resilience story with Robot chaos suites
that kill whole agents; the TPU-native data plane has failure modes a
process kill cannot reach — a JAX dispatch raising on one shard, a
device call that never returns, a table swap failing halfway through a
multi-shard fan-out, a frame source erroring under it.  This module
gives every such mode a NAMED INJECTION SITE, armed programmatically
(tests) or over REST (`POST /contiv/v1/faults/arm`), so chaos tests
drive them through the production code paths instead of monkeypatching
runner internals.

Sites (fired by hook points in ``datapath/runner.py`` /
``datapath/shards.py`` / ``datapath/io.py``):

- ``dispatch-raise``   — the jit dispatch raises (device error analog);
  with a ``match`` predicate it only fires when the batch contains a
  matching frame, which is how poisoned-batch quarantine is driven.
- ``dispatch-hang``    — the dispatch thread wedges (stuck device call);
  released by :meth:`FaultInjector.disarm` or the plan's ``seconds``
  timeout, so tests never leak permanently-stuck threads.
- ``swap-fail``        — ``update_tables`` / ``_adopt_tables`` raises on
  the selected shard before any table reference is mutated.
- ``frame-source-error`` — the frame source errors during admit
  (flapping NIC / dead socket analog).

The injector is SHARED across all shards of a :class:`ShardedDataplane`
(plans select shards via ``shard=``; ``None`` matches every shard) and
costs one attribute read per hook point while disarmed — safe to leave
compiled into production paths.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Any, Dict, List, Optional

SITE_DISPATCH_RAISE = "dispatch-raise"
SITE_DISPATCH_HANG = "dispatch-hang"
SITE_SWAP_FAIL = "swap-fail"
SITE_FRAME_SOURCE_ERROR = "frame-source-error"

SITES = (
    SITE_DISPATCH_RAISE,
    SITE_DISPATCH_HANG,
    SITE_SWAP_FAIL,
    SITE_FRAME_SOURCE_ERROR,
)

# Fields a poison predicate may match on (the parsed 5-tuple).
MATCH_FIELDS = ("src_ip", "dst_ip", "protocol", "src_port", "dst_port")


class FaultInjected(RuntimeError):
    """Raised at an armed injection site."""

    def __init__(self, site: str, shard: Optional[int], message: str = ""):
        super().__init__(
            message or f"injected fault at {site}"
            + (f" (shard {shard})" if shard is not None else "")
        )
        self.site = site
        self.shard = shard


@dataclasses.dataclass
class _Plan:
    plan_id: int
    site: str
    shard: Optional[int]          # None = any shard
    count: Optional[int]          # remaining fires; None = unlimited
    mode: str                     # "raise" | "hang"
    message: str
    match: Optional[Dict[str, int]]  # 5-tuple field -> value (poison predicate)
    seconds: float                # hang timeout (upper bound)
    release: threading.Event = dataclasses.field(default_factory=threading.Event)
    fired: int = 0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "id": self.plan_id,
            "site": self.site,
            "shard": self.shard,
            "remaining": self.count,
            "mode": self.mode,
            "match": dict(self.match) if self.match else None,
            "seconds": self.seconds,
            "fired": self.fired,
        }


class FaultInjector:
    """Registry of armed fault plans, consulted at the named sites."""

    def __init__(self):
        self._plans: List[_Plan] = []
        # Plans with a thread currently wedged in their hang: kept here
        # (even after a count-exhausted plan leaves _plans) so disarm()
        # can ALWAYS release them.
        self._wedged: List[_Plan] = []
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # Volatile fast-path flag: hook points read this WITHOUT the
        # lock; it is only ever True while plans exist, so a disarmed
        # injector costs one attribute read per hook.
        self.armed = False

    # ------------------------------------------------------------- arming

    def arm(
        self,
        site: str,
        shard: Optional[int] = None,
        count: Optional[int] = None,
        mode: Optional[str] = None,
        message: str = "",
        match: Optional[Dict[str, int]] = None,
        seconds: float = 30.0,
    ) -> int:
        """Arm one plan; returns its id.  ``count=None`` fires until
        disarmed; ``match`` restricts ``dispatch-raise`` to batches
        containing a frame whose listed 5-tuple fields all equal the
        given values (the poisoned-frame predicate)."""
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r} (have {SITES})")
        if mode is None:
            mode = "hang" if site == SITE_DISPATCH_HANG else "raise"
        if mode not in ("raise", "hang"):
            raise ValueError(f"unknown fault mode {mode!r}")
        if match is not None:
            bad = set(match) - set(MATCH_FIELDS)
            if bad:
                raise ValueError(f"unmatchable fields {sorted(bad)}")
            match = {k: int(v) for k, v in match.items()}
        plan = _Plan(
            plan_id=next(self._ids), site=site, shard=shard,
            count=count, mode=mode, message=message, match=match,
            seconds=float(seconds),
        )
        with self._lock:
            self._plans.append(plan)
            self.armed = True
        return plan.plan_id

    def disarm(self, site: Optional[str] = None,
               plan_id: Optional[int] = None) -> int:
        """Remove matching plans (all of them by default), releasing any
        thread currently wedged in a hang.  Returns how many were
        removed."""
        with self._lock:
            keep, gone = [], []
            for plan in self._plans:
                if (site is None or plan.site == site) and (
                    plan_id is None or plan.plan_id == plan_id
                ):
                    gone.append(plan)
                else:
                    keep.append(plan)
            self._plans = keep
            self.armed = bool(keep)
            # Release matching wedged plans too — a count-exhausted hang
            # plan is no longer in _plans but its thread is still stuck.
            for plan in self._wedged:
                if (site is None or plan.site == site) and (
                    plan_id is None or plan.plan_id == plan_id
                ) and plan not in gone:
                    gone.append(plan)
        for plan in gone:
            plan.release.set()
        return len(gone)

    # -------------------------------------------------------------- firing

    def fire(self, site: str, shard: Optional[int] = None,
             batch: Optional[Dict[str, Any]] = None) -> None:
        """Hook point: no-op unless a plan matches ``site``/``shard``
        (and, for poison plans, the batch contains a matching frame).
        Raises :class:`FaultInjected` or blocks (hang mode)."""
        if not self.armed:
            return
        with self._lock:
            plan = None
            for p in self._plans:
                if p.site != site:
                    continue
                if p.shard is not None and shard is not None and p.shard != shard:
                    continue
                if p.match is not None and not self._batch_matches(p.match, batch):
                    continue
                plan = p
                break
            if plan is None:
                return
            plan.fired += 1
            if plan.count is not None:
                plan.count -= 1
                if plan.count <= 0:
                    self._plans.remove(plan)
                    self.armed = bool(self._plans)
        if plan.mode == "hang":
            # Wedge until disarmed (or the safety timeout) — the analog
            # of a device call that never returns.  The plan registers
            # as wedged first so disarm() can un-stick this thread even
            # after a count-exhausted plan left _plans.
            with self._lock:
                self._wedged.append(plan)
            try:
                plan.release.wait(plan.seconds)
            finally:
                with self._lock:
                    if plan in self._wedged:
                        self._wedged.remove(plan)
            return
        raise FaultInjected(site, shard, plan.message)

    @staticmethod
    def _batch_matches(match: Dict[str, int], batch) -> bool:
        if batch is None:
            return False
        import numpy as np

        rows = None
        for field_name, value in match.items():
            arr = batch.get(field_name) if isinstance(batch, dict) \
                else getattr(batch, field_name, None)
            if arr is None:
                return False
            # The ONLY place the injector touches batch contents: runs
            # when a poison-match plan is armed (a chaos drill), never
            # on undisturbed production dispatches.
            hit = np.asarray(arr) == value  # static: allow(hot-path-sync) — fires only under an armed poison-match plan

            rows = hit if rows is None else (rows & hit)
        return bool(rows is not None and rows.any())

    # -------------------------------------------------------------- status

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "armed": self.armed,
                "sites": list(SITES),
                "plans": [p.as_dict() for p in self._plans],
            }
