from .model import (
    Interface,
    InterfaceType,
    Route,
    ArpEntry,
    BridgeDomain,
    L2FibEntry,
    VrfTable,
    CONFIG_PREFIX,
)
from .plugin import DHCPLeaseChange, IPv4Net

__all__ = [
    "Interface",
    "InterfaceType",
    "Route",
    "ArpEntry",
    "BridgeDomain",
    "L2FibEntry",
    "VrfTable",
    "CONFIG_PREFIX",
    "IPv4Net",
    "DHCPLeaseChange",
]
