"""IPv4Net — builds ALL connectivity configuration.

Analog of ``plugins/ipv4net`` (SURVEY.md §2.1): renders, as typed KVs
into event transactions,

- the vswitch base config: VRF tables, the host interconnect (TAP pair
  analog), the VXLAN BVI loopback + bridge domain
  (resync_events.go configureVswitchConnectivity);
- per-pod connectivity: TAP interface, static ARP, /32 route in the pod
  VRF (pod.go podConnectivityConfig :57, podVPPTap :129) — and fills
  the CNI reply of AddPod events;
- the full-mesh overlay: one VXLAN tunnel per other node, static L2FIB
  entry to its BVI MAC, routes to its pod/host subnets via its BVI IP
  (node.go vxlanBridgeDomain :482, vxlanIfToOtherNode :524,
  routesPodToMainVRF :338).

MACs are derived deterministically from IPs (the reference hardcodes
generation schemes per interface kind).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from ..conf import NetworkConfig
from ..controller.api import EventHandler, KubeStateChange, UpdateEvent
from ..ipam import IPAM
from ..models import PodID
from ..nodesync import NodeSync, NodeUpdate
from ..podmanager import AddPod, DeletePod
from .model import (
    ArpEntry,
    BridgeDomain,
    Interface,
    InterfaceType,
    L2FibEntry,
    Route,
    VrfTable,
)

log = logging.getLogger(__name__)


class DHCPLeaseChange(UpdateEvent):
    """A DHCP lease arrived / changed on an interface (the notification
    the reference's handleDHCPNotification consumes, ipv4net/node.go
    :188-240).  Pushed into the event loop by the platform's DHCP-client
    integration."""

    name = "DHCP Lease Change"

    def __init__(self, interface: str, ip_address: str, gateway: str = ""):
        super().__init__()
        self.interface = interface
        self.ip_address = ip_address  # "a.b.c.d/len"
        self.gateway = gateway

    def describe(self) -> str:
        return f"{self.interface}: {self.ip_address} gw {self.gateway}"


VXLAN_BVI_NAME = "vxlanBVI"
VXLAN_BD_NAME = "vxlanBD"
HOST_INTERCONNECT_IF = "tap-vpp2"
POD_IF_PREFIX = "tap-"
VXLAN_VNI = 10  # the reference uses VNI 10 for the pod overlay


def mac_from_ip(ip: str, prefix: int = 0x02) -> str:
    """Deterministic locally-administered MAC from an IPv4 address."""
    octets = [int(o) for o in str(ip).split(".")]
    return ":".join(f"{b:02x}" for b in [prefix, 0xFE] + octets)


class IPv4Net(EventHandler):
    """The connectivity event handler."""

    name = "ipv4net"

    def __init__(
        self,
        config: NetworkConfig,
        nodesync: NodeSync,
        ipam: Optional[IPAM] = None,
        podmanager=None,
    ):
        self.config = config
        self.nodesync = nodesync
        # IPAM is constructed after nodesync allocates the node ID; the
        # first resync wires it (matching the reference's plugin order).
        self.ipam = ipam
        # PodManager supplies CNI-added local pods not (yet) reflected
        # into KubeState, so resyncs do not tear their wiring down.
        self.podmanager = podmanager
        # DHCP mode for the main interface (UseDHCP / NodeInterconnectDHCP):
        # the node IP comes from the lease, not IPAM arithmetic.
        self.use_dhcp = (
            config.interface.use_dhcp or config.ipam.node_interconnect_dhcp
        )
        self._dhcp_lease: Optional[DHCPLeaseChange] = None

    # --------------------------------------------------------------- resync

    def handles_event(self, event) -> bool:
        if isinstance(event, (AddPod, DeletePod, NodeUpdate, DHCPLeaseChange)):
            return True
        if isinstance(event, KubeStateChange):
            return False
        return event.method.is_resync

    def resync(self, event, kube_state, resync_count, txn) -> None:
        if self.ipam is None:
            if self.nodesync.node_id is None:
                self.nodesync.allocate_id()
            self.ipam = IPAM(self.config.ipam, self.nodesync.node_id)

        # Re-learn the allocation pool from KubeState on EVERY resync,
        # preserving CNI-granted IPs of live local pods that KubeState
        # does not (yet) reflect — otherwise a healing resync could hand
        # out duplicate IPs or tear down running pods.
        preserved = {}
        if self.podmanager is not None:
            for pod_id in self.podmanager.local_pods:
                ip = self.ipam.get_pod_ip(pod_id)
                if ip is not None:
                    preserved[pod_id] = ip
        self.ipam.resync(kube_state)
        for pod_id, ip in preserved.items():
            self.ipam.adopt(pod_id, ip)

        for kv in self.vswitch_connectivity_config():
            txn.put(kv.key, kv)
        for node in self.nodesync.other_nodes().values():
            for kv in self.node_connectivity_config(node.id):
                txn.put(kv.key, kv)
        # Re-render all local pods.  The authoritative set is IPAM's
        # post-resync assignment map (KubeState pods + preserved CNI pods),
        # which already excludes reserved addresses (gateway, NAT loopback,
        # broadcast) that stale/foreign KubeState records could carry —
        # rendering those would hijack e.g. the pod gateway IP.
        for pod_id, ip in sorted(self.ipam.assigned_pods().items()):
            for kv in self.pod_connectivity_config(pod_id, str(ip)):
                txn.put(kv.key, kv)

        # Publish our data-plane IPs for other nodes.  In DHCP mode the
        # node IP is known only once a lease arrives (node.go
        # handleDHCPNotification publishes then).
        if not self.use_dhcp:
            self._publish_node_ips(
                (f"{self.ipam.node_ip()}/{self.config.ipam.node_interconnect().prefixlen}",),
            )
        elif self._dhcp_lease is not None:
            self._publish_node_ips((self._dhcp_lease.ip_address,))

    def _publish_node_ips(self, ips) -> None:
        """Northbound publish of this node's data-plane IPs, outage-
        tolerant: a resync served from the sqlite MIRROR (store
        unreachable) must not fail on this store write — failing the
        handler schedules healing, the healing resync fails on the same
        write, and a failed healing is FATAL: the agent would kill
        itself precisely while riding an outage out on local state
        (found by the ISSUE 9 chaos soak's store-outage window).  The
        publish is an idempotent refresh of our own record; the
        reconnect resync re-runs it as soon as the store returns."""
        from ..controller.dbwatcher import is_store_unavailable

        try:
            self.nodesync.publish_node_ips(ips)
        except Exception as err:  # noqa: BLE001 - outage-classified below
            if not is_store_unavailable(err):
                raise
            log.warning("node-IP publish deferred (store unreachable): %s",
                        err)

    # ------------------------------------------------------- config builders

    def vswitch_connectivity_config(self) -> List:
        """Base vswitch config (configureVswitchConnectivity analog)."""
        ipam = self.ipam
        routing = self.config.routing
        kvs: List = [
            VrfTable(id=routing.main_vrf_id, label="main"),
            VrfTable(id=routing.pod_vrf_id, label="pods"),
            # Host interconnect (the host side of the memif/TAP shim).
            Interface(
                name=HOST_INTERCONNECT_IF,
                type=InterfaceType.TAP,
                ip_addresses=(f"{ipam.host_interconnect_ip_dataplane()}/{ipam.host_subnet_this_node.prefixlen}",),
                vrf=routing.main_vrf_id,
                host_if_name="vpp1",
                mtu=self.config.interface.mtu,
            ),
            # Route host-side traffic to the host interconnect peer.
            Route(
                dst_network=f"{ipam.host_interconnect_ip_host()}/32",
                outgoing_interface=HOST_INTERCONNECT_IF,
                vrf=routing.main_vrf_id,
            ),
        ]
        if routing.use_vxlan:
            bvi_ip = ipam.vxlan_ip()
            kvs += [
                Interface(
                    name=VXLAN_BVI_NAME,
                    type=InterfaceType.LOOPBACK,
                    ip_addresses=(f"{bvi_ip}/{self.config.ipam.vxlan().prefixlen}",),
                    vrf=routing.pod_vrf_id,
                    physical_address=mac_from_ip(bvi_ip, prefix=0x12),
                    mtu=self.config.interface.mtu,
                ),
                self._render_bridge_domain(),
            ]
        # Pod VRF default: leak to main VRF (two-VRF layout).
        kvs.append(
            Route(
                dst_network="0.0.0.0/0",
                vrf=self.config.routing.pod_vrf_id,
                via_vrf=self.config.routing.main_vrf_id,
            )
        )
        # Main (physical) data-plane interface: static IP from IPAM
        # arithmetic, or a DHCP client (node.go configureVswitchNICs —
        # UseDHCP path) whose address/gateway arrive via DHCPLeaseChange.
        main_if = self.config.interface.main_interface
        if main_if:
            if self.use_dhcp:
                kvs.append(
                    Interface(
                        name=main_if,
                        type=InterfaceType.DPDK,
                        dhcp=True,
                        vrf=routing.main_vrf_id,
                        mtu=self.config.interface.mtu,
                    )
                )
                if self._dhcp_lease is not None and self._dhcp_lease.gateway:
                    kvs.append(
                        Route(
                            dst_network="0.0.0.0/0",
                            next_hop=self._dhcp_lease.gateway,
                            outgoing_interface=main_if,
                            vrf=routing.main_vrf_id,
                        )
                    )
            else:
                prefix = self.config.ipam.node_interconnect().prefixlen
                kvs.append(
                    Interface(
                        name=main_if,
                        type=InterfaceType.DPDK,
                        ip_addresses=(f"{ipam.node_ip()}/{prefix}",),
                        vrf=routing.main_vrf_id,
                        mtu=self.config.interface.mtu,
                    )
                )
        # Non-main physical interfaces (contivconf GetOtherVPPInterfaces
        # :574-586, configured by node.go configureVswitchNICs).
        for other in self.config.interface.other_interfaces:
            if not other.name:
                continue  # malformed CRD entry: never render a nameless NIC
            kvs.append(
                Interface(
                    name=other.name,
                    type=InterfaceType.DPDK,
                    dhcp=other.use_dhcp,
                    ip_addresses=(other.ip,) if other.ip else (),
                    vrf=routing.main_vrf_id,
                    mtu=self.config.interface.mtu,
                )
            )
        return kvs

    def _vxlan_if_name(self, node_id: int) -> str:
        return f"vxlan{node_id}"

    def _this_node_ip(self) -> str:
        """This node's underlay address: the DHCP lease when in DHCP mode
        (before a lease arrives the arithmetic address is a placeholder,
        re-rendered on DHCPLeaseChange), IPAM arithmetic otherwise."""
        if self.use_dhcp and self._dhcp_lease is not None:
            return self._dhcp_lease.ip_address.split("/")[0]
        return str(self.ipam.node_ip())

    def _other_node_ip(self, node_id: int) -> str:
        """Another node's underlay address: its PUBLISHED VppNode record
        is authoritative (it may run DHCP too); arithmetic fallback."""
        for rec in self.nodesync.other_nodes().values():
            if rec.id == node_id and rec.ip_addresses:
                return rec.ip_addresses[0].split("/")[0]
        return str(self.ipam.node_ip(node_id))

    def node_connectivity_config(self, node_id: int) -> List:
        """Connectivity to one other node (vxlanIfToOtherNode :524 +
        routesToOtherNode)."""
        ipam = self.ipam
        routing = self.config.routing
        kvs: List = []
        if routing.use_vxlan:
            this_bvi = ipam.vxlan_ip()
            other_bvi = ipam.vxlan_ip(node_id)
            vxlan_if = self._vxlan_if_name(node_id)
            kvs += [
                Interface(
                    name=vxlan_if,
                    type=InterfaceType.VXLAN,
                    vxlan_src=self._this_node_ip(),
                    vxlan_dst=self._other_node_ip(node_id),
                    vxlan_vni=VXLAN_VNI,
                    mtu=self.config.interface.mtu,
                ),
                # The remote BVI is reachable through the tunnel.
                ArpEntry(
                    interface=VXLAN_BVI_NAME,
                    ip_address=str(other_bvi),
                    physical_address=mac_from_ip(other_bvi, prefix=0x12),
                ),
                L2FibEntry(
                    bridge_domain=VXLAN_BD_NAME,
                    physical_address=mac_from_ip(other_bvi, prefix=0x12),
                    outgoing_interface=vxlan_if,
                ),
            ]
            next_hop = str(other_bvi)
            out_if = VXLAN_BVI_NAME
        else:
            next_hop = self._other_node_ip(node_id)
            out_if = ""
        kvs += [
            Route(
                dst_network=str(ipam.pod_subnet_other_node(node_id)),
                next_hop=next_hop,
                outgoing_interface=out_if,
                vrf=routing.pod_vrf_id,
            ),
            Route(
                dst_network=str(ipam.host_subnet_other_node(node_id)),
                next_hop=next_hop,
                outgoing_interface=out_if,
                vrf=routing.pod_vrf_id,
            ),
        ]
        return kvs

    def pod_connectivity_config(self, pod_id: PodID, pod_ip: str) -> List:
        """One pod's wiring (podConnectivityConfig :57)."""
        if_name = f"{POD_IF_PREFIX}{pod_id.namespace}-{pod_id.name}"
        pod_mac = mac_from_ip(pod_ip)
        # The pod's actual network namespace comes from the CNI request
        # (LocalPod.network_namespace); KubeState-only pods fall back to
        # a deterministic name.
        netns = ""
        if self.podmanager is not None:
            local = self.podmanager.get_local_pod(pod_id)
            if local is not None:
                netns = local.network_namespace
        return [
            Interface(
                name=if_name,
                type=InterfaceType.TAP,
                vrf=self.config.routing.pod_vrf_id,
                host_if_name="eth0",
                namespace=netns or f"pod-{pod_id.namespace}-{pod_id.name}",
                # The pod (peer) side carries the address, like the
                # reference's Linux TAP half (pod.go podLinuxTAP).
                ip_addresses=(f"{pod_ip}/32",),
                mtu=self.config.interface.mtu,
            ),
            ArpEntry(interface=if_name, ip_address=pod_ip, physical_address=pod_mac),
            Route(
                dst_network=f"{pod_ip}/32",
                outgoing_interface=if_name,
                vrf=self.config.routing.pod_vrf_id,
            ),
        ]

    # --------------------------------------------------------------- update

    def update(self, event, txn) -> str:
        if isinstance(event, AddPod):
            return self._add_pod(event, txn)
        if isinstance(event, DeletePod):
            return self._delete_pod(event, txn)
        if isinstance(event, NodeUpdate):
            return self._node_update(event, txn)
        if isinstance(event, DHCPLeaseChange):
            return self._dhcp_lease_change(event, txn)
        return ""

    def _dhcp_lease_change(self, event: DHCPLeaseChange, txn) -> str:
        """handleDHCPNotification analog (node.go :188-240): validate the
        lease, learn the node IP, publish it, install the default route."""
        if not self.use_dhcp:
            return ""  # dynamic assignment disabled
        if event.interface != self.config.interface.main_interface:
            return ""  # not the main interface
        prev = self._dhcp_lease
        if (
            prev is not None
            and prev.ip_address == event.ip_address
            and prev.gateway == event.gateway
        ):
            return ""  # lease already processed
        self._dhcp_lease = event
        route = Route(
            dst_network="0.0.0.0/0",
            next_hop=event.gateway,
            outgoing_interface=self.config.interface.main_interface,
            vrf=self.config.routing.main_vrf_id,
        )
        if event.gateway:
            txn.put(route.key, route)
        elif prev is not None and prev.gateway:
            # Renewed lease without a gateway: the old default route must
            # not linger.
            txn.delete(route.key)
        # The node IP feeds VXLAN tunnel sources: re-render the overlay
        # with the leased address.
        for node in self.nodesync.other_nodes().values():
            for kv in self.node_connectivity_config(node.id):
                txn.put(kv.key, kv)
        self._publish_node_ips((event.ip_address,))
        return f"DHCP lease on {event.interface}: {event.ip_address}"

    def _add_pod(self, event: AddPod, txn) -> str:
        pod_id = event.pod.id
        ip = self.ipam.allocate_pod_ip(pod_id)
        for kv in self.pod_connectivity_config(pod_id, str(ip)):
            txn.put(kv.key, kv)
        event.reply.ip_address = f"{ip}/32"
        event.reply.interfaces.append(
            {
                "name": "eth0",
                "ip": f"{ip}/{self.ipam.pod_subnet_this_node.prefixlen}",
                "gateway": str(self.ipam.pod_gateway_ip),
                "sandbox": event.pod.network_namespace,
            }
        )
        event.reply.routes.append(
            {"dst": "0.0.0.0/0", "gw": str(self.ipam.pod_gateway_ip)}
        )
        return f"wired pod {pod_id} at {ip}"

    def _delete_pod(self, event: DeletePod, txn) -> str:
        ip = self.ipam.get_pod_ip(event.pod_id)
        if ip is None:
            return ""
        for kv in self.pod_connectivity_config(event.pod_id, str(ip)):
            txn.delete(kv.key)
        self.ipam.release_pod_ip(event.pod_id)
        return f"unwired pod {event.pod_id}"

    def _node_update(self, event: NodeUpdate, txn) -> str:
        if event.prev is not None and event.new is None:
            for kv in self.node_connectivity_config(event.prev.id):
                txn.delete(kv.key)
            self._refresh_bridge_domain(txn)
            return f"removed connectivity to {event.node_name}"
        if event.new is not None:
            for kv in self.node_connectivity_config(event.new.id):
                txn.put(kv.key, kv)
            self._refresh_bridge_domain(txn)
            return f"configured connectivity to {event.node_name}"
        return ""

    def _render_bridge_domain(self) -> BridgeDomain:
        """The VXLAN bridge domain with the current tunnel membership —
        single construction point for resync and NodeUpdate paths."""
        return BridgeDomain(
            name=VXLAN_BD_NAME,
            bvi_interface=VXLAN_BVI_NAME,
            interfaces=tuple(
                self._vxlan_if_name(node.id)
                for node in self.nodesync.other_nodes().values()
            ),
        )

    def _refresh_bridge_domain(self, txn) -> None:
        if not self.config.routing.use_vxlan:
            return
        bd = self._render_bridge_domain()
        txn.put(bd.key, bd)

    def revert(self, event) -> None:
        if isinstance(event, AddPod):
            self.ipam.release_pod_ip(event.pod.id)
