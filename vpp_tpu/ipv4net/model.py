"""Typed network-config values — the southbound model layer.

Analog of the vpp-agent proto models the reference renders into
(vpp_interfaces.Interface, vpp_l3.Route, vpp_l2.BridgeDomain, ... —
consumed through the vendored vppv2 configurators, SURVEY.md §1 L2).
These are the values ipv4net Put()s into event transactions; the txn
scheduler diffs them and drives the host-FIB applicator (and, for the
TPU path, route-table updates).

Each value type carries its dependency semantics (interfaces before
routes/ARP referencing them, bridge domains before L2 FIB entries) via
``dependencies()`` — picked up generically by the scheduler.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Set, Tuple

CONFIG_PREFIX = "/vpp-tpu/config/"
IF_PREFIX = CONFIG_PREFIX + "interface/"
ROUTE_PREFIX = CONFIG_PREFIX + "route/"
ARP_PREFIX = CONFIG_PREFIX + "arp/"
BD_PREFIX = CONFIG_PREFIX + "bd/"
L2FIB_PREFIX = CONFIG_PREFIX + "l2fib/"
VRF_PREFIX = CONFIG_PREFIX + "vrf/"


class InterfaceType(enum.Enum):
    TAP = "tap"            # pod-side interconnect (reference: VPP TAP + Linux TAP)
    VETH = "veth"
    LOOPBACK = "loopback"  # e.g. the BVI
    VXLAN = "vxlan"        # overlay tunnel to another node
    DPDK = "dpdk"          # physical uplink (name kept for familiarity)
    MEMIF = "memif"        # host<->data-plane shim attachment


@dataclass(frozen=True)
class Interface:
    """One interface (vpp_interfaces.Interface analog)."""

    name: str
    type: InterfaceType
    enabled: bool = True
    ip_addresses: Tuple[str, ...] = ()  # "a.b.c.d/len"
    vrf: int = 0
    mtu: int = 1450
    # VXLAN specifics.
    vxlan_src: str = ""
    vxlan_dst: str = ""
    vxlan_vni: int = 0
    # TAP specifics: the pod/host peer namespace.
    host_if_name: str = ""
    namespace: str = ""
    physical_address: str = ""
    # Acquire the address via DHCP instead of ip_addresses
    # (vpp_interfaces.Interface SetDhcpClient analog).
    dhcp: bool = False

    @property
    def key(self) -> str:
        return IF_PREFIX + self.name

    def dependencies(self) -> Set[str]:
        return set()


@dataclass(frozen=True)
class VrfTable:
    """A routing table (vpp_l3.VrfTable analog)."""

    id: int
    label: str = ""

    @property
    def key(self) -> str:
        return f"{VRF_PREFIX}{self.id}"

    def dependencies(self) -> Set[str]:
        return set()


@dataclass(frozen=True)
class Route:
    """A static route (vpp_l3.Route analog)."""

    dst_network: str
    next_hop: str = ""
    outgoing_interface: str = ""
    vrf: int = 0
    # Route leaking between VRFs (the reference's inter-VRF routes).
    via_vrf: Optional[int] = None

    @property
    def key(self) -> str:
        return f"{ROUTE_PREFIX}vrf{self.vrf}/{self.dst_network}"

    def dependencies(self) -> Set[str]:
        deps = {f"{VRF_PREFIX}{self.vrf}"}
        if self.outgoing_interface:
            deps.add(IF_PREFIX + self.outgoing_interface)
        return deps


@dataclass(frozen=True)
class ArpEntry:
    """A static ARP entry (vpp_l3.ARPEntry analog)."""

    interface: str
    ip_address: str
    physical_address: str
    static: bool = True

    @property
    def key(self) -> str:
        return f"{ARP_PREFIX}{self.interface}/{self.ip_address}"

    def dependencies(self) -> Set[str]:
        return {IF_PREFIX + self.interface}


@dataclass(frozen=True)
class BridgeDomain:
    """An L2 bridge domain (vpp_l2.BridgeDomain analog)."""

    name: str
    interfaces: Tuple[str, ...] = ()
    bvi_interface: str = ""

    @property
    def key(self) -> str:
        return BD_PREFIX + self.name

    def dependencies(self) -> Set[str]:
        # The BD exists as soon as the BVI does; member interfaces attach
        # as they appear (matching vpp-agent's partial-BD semantics).
        deps = set()
        if self.bvi_interface:
            deps.add(IF_PREFIX + self.bvi_interface)
        return deps


@dataclass(frozen=True)
class L2FibEntry:
    """A static L2 FIB entry (vpp_l2.FIBEntry analog)."""

    bridge_domain: str
    physical_address: str
    outgoing_interface: str

    @property
    def key(self) -> str:
        return f"{L2FIB_PREFIX}{self.bridge_domain}/{self.physical_address}"

    def dependencies(self) -> Set[str]:
        return {BD_PREFIX + self.bridge_domain, IF_PREFIX + self.outgoing_interface}
