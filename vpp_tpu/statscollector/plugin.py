"""StatsCollector plugin.

Analog of ``plugins/statscollector/plugin_impl_statscollector.go``: the
data plane pushes per-interface counters into ``put()`` (:213, the
datasync-sink analog), the collector maps interface names to pods
through the ipv4net naming scheme, and exports one Prometheus gauge per
(metric, pod, interface) — pruned when the pod is deleted
(:213-357).  System interfaces (host interconnect, BVI, uplink) are
skipped exactly like the reference's ``systemIfNames`` filter.

Metric/label names match the reference so dashboards carry over.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from prometheus_client import CollectorRegistry, Gauge

from ..controller.api import EventHandler
from ..ipv4net.plugin import HOST_INTERCONNECT_IF, POD_IF_PREFIX, VXLAN_BVI_NAME
from ..models import PodID
from ..podmanager import DeletePod

log = logging.getLogger(__name__)

POD_NAME_LABEL = "podName"
POD_NAMESPACE_LABEL = "podNamespace"
INTERFACE_NAME_LABEL = "interfaceName"

METRICS = (
    ("inPackets", "Number of received packets for interface"),
    ("outPackets", "Number of transmitted packets for interface"),
    ("inBytes", "Number of received bytes for interface"),
    ("outBytes", "Number of transmitted bytes for interface"),
    ("dropPackets", "Number of dropped packets for interface"),
    ("puntPackets", "Number of punted packets for interface"),
    ("inErrorPackets", "Number of received packets with error for interface"),
    ("outErrorPackets", "Number of transmitted packets with error for interface"),
)

SYSTEM_IF_NAMES = (HOST_INTERCONNECT_IF, VXLAN_BVI_NAME, "vpp2", "loopbackNIC")


@dataclass
class InterfaceStats:
    """One interface's counters (vpp_interfaces.InterfaceState analog)."""

    in_packets: int = 0
    out_packets: int = 0
    in_bytes: int = 0
    out_bytes: int = 0
    drop_packets: int = 0
    punt_packets: int = 0
    in_error_packets: int = 0
    out_error_packets: int = 0

    def as_metric_values(self) -> Dict[str, float]:
        return {
            "inPackets": self.in_packets,
            "outPackets": self.out_packets,
            "inBytes": self.in_bytes,
            "outBytes": self.out_bytes,
            "dropPackets": self.drop_packets,
            "puntPackets": self.punt_packets,
            "inErrorPackets": self.in_error_packets,
            "outErrorPackets": self.out_error_packets,
        }


def _pod_from_if_name(if_name: str) -> Optional[PodID]:
    """tap-<namespace>-<name> → PodID (ipv4net naming scheme)."""
    if not if_name.startswith(POD_IF_PREFIX) or if_name in SYSTEM_IF_NAMES:
        return None
    rest = if_name[len(POD_IF_PREFIX):]
    namespace, sep, name = rest.partition("-")
    if not sep or not name:
        return None
    return PodID(name=name, namespace=namespace)


@dataclass
class _Entry:
    pod: PodID
    if_name: str
    stats: InterfaceStats = field(default_factory=InterfaceStats)


class _DatapathCollector:
    """Custom Prometheus collector: one consistent runner.metrics()
    snapshot per scrape (occupancy involves a device reduction — doing
    it once per scrape, not once per gauge, keeps scrapes off the hot
    path and the exported counters mutually consistent).

    Monotonic ``*_total`` counters export as COUNTER families (ISSUE 8
    satellite): Prometheus ``rate()``/``increase()`` handle counter
    resets (agent restarts) only for the counter type — exported as
    gauges, every restart looked like a traffic cliff.  Gauges (active
    sessions, ring depths, governor K) stay gauges.

    Latency histograms (ISSUE 8 tentpole) export as HISTOGRAM families
    in cumulative-le form so ``histogram_quantile()`` works natively;
    the derived p50/p90/p99/p99.9 export alongside as gauges for
    dashboards without PromQL (reading the SAME ``snapshot()`` keys the
    REST/netctl/dashboard surfaces read — the obs-parity checker holds
    exporter and inspect() schema together)."""

    def __init__(self, runner):
        self.runner = runner

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
            HistogramMetricFamily,
        )

        snapshot = self.runner.metrics()
        for name, value in snapshot.items():
            if name.endswith("_total"):
                yield CounterMetricFamily(
                    name, f"datapath counter {name}", value=float(value))
            else:
                yield GaugeMetricFamily(
                    name, f"datapath gauge {name}", value=float(value))
        # In-network inference score histogram (ISSUE 14): one counter
        # per log2 score band (band k = score >= 1 - 2^-k), labelled —
        # the Prometheus face of inspect()["inference"]["score_bands"]
        # (the datapath_inference_*_total action counters ride the
        # generic counter export above).
        bands_fn = getattr(self.runner, "inference_bands", None)
        if bands_fn is not None:
            family = CounterMetricFamily(
                "datapath_inference_score_band_total",
                "packets scored into each log2 score band "
                "(band k: score >= 1 - 2^-k)",
                labels=["band"],
            )
            for band, count in enumerate(bands_fn()):
                family.add_metric([str(band)], float(count))
            yield family
        hist_fn = getattr(self.runner, "latency_histograms", None)
        if hist_fn is None:
            return
        for name, hist in hist_fn().items():
            buckets, sum_us = hist.cumulative()
            yield HistogramMetricFamily(
                f"datapath_latency_{name}_us",
                f"datapath {name} latency distribution (µs, log2 buckets)",
                buckets=buckets, sum_value=sum_us,
            )
            snap = hist.snapshot()
            for q_name, q_value in (
                ("p50", snap.get("p50")),
                ("p90", snap.get("p90")),
                ("p99", snap.get("p99")),
                ("p999", snap.get("p999")),
            ):
                yield GaugeMetricFamily(
                    f"datapath_latency_{name}_{q_name}_us",
                    f"datapath {name} latency {q_name} (µs, derived on read)",
                    value=float(q_value or 0.0),
                )


class _SpanCollector:
    """Control-plane propagation telemetry: the config-propagation
    latency histogram plus span counters, from the controller's
    SpanTracker (ISSUE 8)."""

    def __init__(self, tracker):
        self.tracker = tracker

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            HistogramMetricFamily,
        )

        status = self.tracker.status()
        yield CounterMetricFamily(
            "controlplane_spans_total",
            "propagation spans started (one per controller event)",
            value=float(status.get("spans_started") or 0))
        yield CounterMetricFamily(
            "controlplane_spans_propagated_total",
            "spans whose config reached compile/swap/adoption",
            value=float(status.get("spans_propagated") or 0))
        buckets, sum_us = self.tracker.propagation.cumulative()
        yield HistogramMetricFamily(
            "controlplane_config_propagation_us",
            "K8s event → device-table adoption latency (µs, log2 buckets)",
            buckets=buckets, sum_value=sum_us,
        )


class _ControllerCollector:
    """Control-plane resilience telemetry (ISSUE 9 satellite): healing
    resync counters, event errors and last-resync age from
    ``Controller.status()`` — the Prometheus face of the same snapshot
    REST ``/contiv/v1/health`` and ``netctl health`` serve, so alerting
    can catch a silent healing loop (scheduled climbing, completed
    flat) without scraping REST."""

    def __init__(self, controller):
        self.controller = controller

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        status = self.controller.status()
        for name, key, help_text in (
            ("controlplane_resyncs_total", "resync_count",
             "resync events processed"),
            ("controlplane_events_total", "events_processed",
             "controller events processed"),
            ("controlplane_event_errors_total", "event_errors",
             "controller events that ended in error"),
            ("controlplane_healing_scheduled_total", "healing_scheduled",
             "healing resyncs scheduled after event errors"),
            ("controlplane_healing_completed_total", "healing_completed",
             "healing resyncs that completed cleanly"),
            ("controlplane_healing_failed_total", "healing_failed",
             "healing resyncs that failed (fatal)"),
        ):
            yield CounterMetricFamily(
                name, help_text, value=float(status.get(key) or 0))
        age = status.get("last_resync_age_s")
        yield GaugeMetricFamily(
            "controlplane_last_resync_age_seconds",
            "seconds since the last resync landed (-1 = never)",
            value=-1.0 if age is None else float(age))


class StatsCollector(EventHandler):
    """Maps data-plane interface counters to pods and exports gauges."""

    name = "statscollector"

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self.registry = registry if registry is not None else CollectorRegistry()
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._gauges: Dict[str, Gauge] = {
            metric: Gauge(
                metric, help_text,
                [POD_NAME_LABEL, POD_NAMESPACE_LABEL, INTERFACE_NAME_LABEL],
                registry=self.registry,
            )
            for metric, help_text in METRICS
        }
        self._datapath_collector: Optional[_DatapathCollector] = None
        self._span_collector: Optional[_SpanCollector] = None
        self._controller_collector: Optional[_ControllerCollector] = None

    # ------------------------------------------------------------- datapath

    def register_datapath(self, runner) -> None:
        """Export the datapath runner's counters — frames, drops by
        cause, NAT session occupancy, slow-path state, punts — via a
        custom collector that reads ONE runner.metrics() snapshot per
        scrape (VERDICT r1 #3: session eviction/occupancy observability
        via /metrics).  Re-registering swaps the runner (restart case);
        one StatsCollector exports one datapath."""
        if self._datapath_collector is None:
            self._datapath_collector = _DatapathCollector(runner)
            self.registry.register(self._datapath_collector)
        else:
            self._datapath_collector.runner = runner

    def register_spans(self, tracker) -> None:
        """Export the controller's propagation-span telemetry
        (config-propagation histogram + span counters); re-registering
        swaps the tracker like register_datapath swaps the runner."""
        if self._span_collector is None:
            self._span_collector = _SpanCollector(tracker)
            self.registry.register(self._span_collector)
        else:
            self._span_collector.tracker = tracker

    def register_controller(self, controller) -> None:
        """Export the controller's resilience counters (healing resyncs
        scheduled/completed/failed, event errors, last-resync age);
        re-registering swaps the controller (restart case)."""
        if self._controller_collector is None:
            self._controller_collector = _ControllerCollector(controller)
            self.registry.register(self._controller_collector)
        else:
            self._controller_collector.controller = controller

    # ----------------------------------------------------------- data plane

    def put(self, if_name: str, stats: InterfaceStats) -> None:
        """Ingest one interface's counters (the datasync Put analog)."""
        pod = _pod_from_if_name(if_name)
        if pod is None:
            return  # system interface or unknown naming — not exported
        with self._lock:
            entry = self._entries.get(if_name)
            if entry is None:
                entry = _Entry(pod=pod, if_name=if_name)
                self._entries[if_name] = entry
            entry.stats = stats
            self._update_gauges(entry)

    def _update_gauges(self, entry: _Entry) -> None:
        labels = {
            POD_NAME_LABEL: entry.pod.name,
            POD_NAMESPACE_LABEL: entry.pod.namespace,
            INTERFACE_NAME_LABEL: entry.if_name,
        }
        for metric, value in entry.stats.as_metric_values().items():
            self._gauges[metric].labels(**labels).set(value)

    # --------------------------------------------------------------- events

    def handles_event(self, event) -> bool:
        return isinstance(event, DeletePod) or event.method.is_resync

    def update(self, event, txn) -> str:
        if isinstance(event, DeletePod):
            self.prune_pod(event.pod_id)
            return f"pruned stats of {event.pod_id}"
        return ""

    def resync(self, event, kube_state, resync_count, txn) -> None:
        """Drop entries for pods no longer known (mirrors the reference
        pruning on resync)."""

    def prune_pod(self, pod_id: PodID) -> None:
        with self._lock:
            for if_name in [k for k, e in self._entries.items() if e.pod == pod_id]:
                entry = self._entries.pop(if_name)
                labels = (entry.pod.name, entry.pod.namespace, entry.if_name)
                for gauge in self._gauges.values():
                    try:
                        gauge.remove(*labels)
                    except KeyError:
                        pass

    # -------------------------------------------------------------- queries

    def pod_stats(self, pod_id: PodID) -> Dict[str, InterfaceStats]:
        with self._lock:
            return {
                e.if_name: e.stats for e in self._entries.values() if e.pod == pod_id
            }


def counters_from_result(result, fb=None) -> InterfaceStats:
    """Aggregate one pipeline step's result into interface counters —
    the bridge from the TPU data plane into ``put()``.

    ``fb`` (a shim FrameBatch) supplies byte counts when available.
    """
    import numpy as np

    allowed = np.asarray(result.allowed)
    n = allowed.shape[0]
    forwarded = int(allowed.sum())
    in_bytes = out_bytes = 0
    if fb is not None:
        lens = np.asarray(fb.lens)
        in_bytes = int(lens.sum())
        out_bytes = int(lens[: len(allowed)][allowed[: len(lens)] > 0].sum())
    # puntPackets was exported-but-never-set (a dead gauge the ISSUE 7
    # obs-parity sweep flushed out): pipeline results carry the punt
    # verdict column — surface it like the reference's punt counter.
    punt = getattr(result, "punt", None)
    punts = int(np.asarray(punt).sum()) if punt is not None else 0
    return InterfaceStats(
        in_packets=n,
        out_packets=forwarded,
        in_bytes=in_bytes,
        out_bytes=out_bytes,
        drop_packets=n - forwarded,
        punt_packets=punts,
    )
