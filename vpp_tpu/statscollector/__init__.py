"""StatsCollector — per-pod data-plane statistics → Prometheus."""

from .plugin import InterfaceStats, StatsCollector, counters_from_result

__all__ = ["InterfaceStats", "StatsCollector", "counters_from_result"]
