"""StatsCollector — per-pod data-plane statistics → Prometheus, plus
the fleet-scope REST aggregator (ISSUE 10, :mod:`.cluster`)."""

from .cluster import ClusterScraper, NodeScrape, heartbeat_servers
from .plugin import InterfaceStats, StatsCollector, counters_from_result

__all__ = [
    "ClusterScraper",
    "InterfaceStats",
    "NodeScrape",
    "StatsCollector",
    "counters_from_result",
    "heartbeat_servers",
]
