"""Fleet telemetry aggregator — scrape every agent, merge, report.

ISSUE 10 tentpole, pillar 2: every observability surface up to PR 7
stopped at the node boundary (per-agent REST, per-agent histograms,
per-agent spans).  This scraper is the fleet face: it polls N agents'
REST surfaces **concurrently with per-request timeouts**, tolerates
partial failure as a first-class outcome (an unreachable node is a
*reported gap* — name, error, last-seen age — never a hang and never a
silent omission), and produces:

- **cluster latency**: the agents' log2 histograms merged bucket-wise
  (exact, not percentile-averaged) into cluster p50/p90/p99/p99.9 per
  pillar — :func:`vpp_tpu.telemetry.cluster.merge_latency_snapshots`;
- **node skew / stragglers**: nodes whose p99 (or adoption lag) exceeds
  k× the cluster median — :func:`vpp_tpu.telemetry.cluster.latency_skew`;
- **stitched propagation spans**: one store write traced across every
  node that adopted it, by revision —
  :func:`vpp_tpu.telemetry.cluster.stitch_spans`;
- **per-node health rollups**: shards serving, healing ledger, event
  errors, span counts — the `netctl cluster top` table.

Used three ways (one implementation): as a library (the soak conductor
builds drill evidence timelines from its scrapes), as ``netctl cluster
top|latency|spans``, and as ``scripts/cluster_obs.py`` (which can
discover agents from the store's heartbeats).

Timeout discipline: a SIGSTOPped agent's REST socket ACCEPTS (the
kernel backlog answers) and then never responds — only a per-request
read timeout turns that into a bounded, reported gap.  Every request
carries one, and the pool fans out so one frozen node cannot serialize
the sweep: up to ``pool`` nodes (default 128 — past the 100-node
design point), ``scrape()``'s wall time is bounded by ~one timeout,
not ``N ×``.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

from ..telemetry.cluster import (
    DEFAULT_STRAGGLER_FACTOR,
    latency_skew,
    merge_latency_snapshots,
    stitch_spans,
)

DEFAULT_TIMEOUT = 3.0
# Upper cap on concurrent scrape threads.  The sweep's "~one timeout,
# not N×" wall-time bound holds while the fleet fits the pool — the
# threads are idle-on-I/O, so the default comfortably covers the
# 100-node design point.
DEFAULT_POOL = 128


@dataclasses.dataclass
class NodeScrape:
    """One agent's slice of one scrape sweep."""

    node: str
    server: str
    ok: bool = False
    error: str = ""
    # Heartbeat-reported lifecycle state (ISSUE 13): "active",
    # "draining", or "drained".  A DRAINED node is intentionally gone —
    # reported under `drained`, never as a gap or a straggler; its
    # sweep slot is skipped entirely (it deregistered).
    state: str = "active"
    elapsed_ms: float = 0.0
    last_seen_age_s: Optional[float] = None  # None = never seen
    inspect: Optional[dict] = None
    spans: Optional[dict] = None
    health: Optional[dict] = None


class ClusterScraper:
    """Concurrent, partial-failure-tolerant poller over agent REST.

    ``servers`` maps node name → ``host:port`` of its AgentRestServer;
    pass a callable to re-resolve each sweep (agents restart onto fresh
    ephemeral ports — the soak's kill drills — and a fleet scraper must
    follow).  The map (or the callable's result) may instead be a
    ROSTER dict ``{"servers": {...}, "states": {name: state}}`` —
    :func:`heartbeat_roster` produces one — so intentionally-DRAINED
    nodes (ISSUE 13) are reported as drained, never as unreachable
    gaps.  ``fetch`` is injectable for tests.
    """

    def __init__(
        self,
        servers,
        timeout: float = DEFAULT_TIMEOUT,
        pool: int = DEFAULT_POOL,
        straggler_factor: float = DEFAULT_STRAGGLER_FACTOR,
        fetch: Optional[Callable[[str, str, float], dict]] = None,
    ):
        self._servers = servers
        self.timeout = timeout
        self.pool = pool
        self.straggler_factor = straggler_factor
        self._fetch = fetch or _http_json
        # Wall timestamp of the last SUCCESSFUL scrape per node, kept
        # across sweeps: a gap is reported with how stale our view of
        # that node is, which is what paging decisions need.
        self._last_seen: Dict[str, float] = {}
        # Latest heartbeat lifecycle state per node (when the servers
        # source is roster-shaped) — re-resolved with the servers each
        # sweep, read by the caller's thread only between those points.
        self._states: Dict[str, str] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    # ------------------------------------------------------------ scraping

    def servers(self) -> Dict[str, str]:
        resolved = self._servers() if callable(self._servers) else self._servers
        if isinstance(resolved, dict) and "servers" in resolved \
                and isinstance(resolved.get("servers"), dict):
            states = {str(k): str(v)
                      for k, v in (resolved.get("states") or {}).items()}
            with self._lock:
                self._states = states
            return dict(resolved["servers"])
        with self._lock:
            self._states = {}
        return dict(resolved)

    def node_state(self, node: str) -> str:
        with self._lock:
            return self._states.get(node, "active")

    def _scrape_one(self, node: str, server: str, light: bool = False,
                    include_spans: bool = True) -> NodeScrape:
        import urllib.error

        out = NodeScrape(node=node, server=server)
        t0 = time.monotonic()
        transport_dead = False
        if not light:
            try:
                out.inspect = self._fetch(server, "/contiv/v1/inspect",
                                          self.timeout)
            except urllib.error.HTTPError as err:
                # The agent ANSWERED (e.g. 404: no datapath attached) —
                # a partial stack, not an outage; the control-plane
                # surfaces below still count.
                out.inspect = None
                out.error = str(err)
            except Exception as err:  # noqa: BLE001 - timeout/refused/reset
                # Transport-level failure: a frozen (SIGSTOPped) agent's
                # socket accepts and never answers, a dead one refuses.
                # Don't pay two more timeouts on the same corpse — one
                # gap, bounded at ~one timeout.
                out.inspect = None
                out.error = str(err) or type(err).__name__
                transport_dead = True
        if not transport_dead:
            if not light and include_spans:
                try:
                    out.spans = self._fetch(
                        server, "/contiv/v1/spans?limit=0", self.timeout)
                except urllib.error.HTTPError:
                    # Answered without a span tracker (partial stack —
                    # the REST contract 404s absent components): same
                    # rule as inspect above, NOT an outage.
                    out.spans = None
                except Exception as err:  # noqa: BLE001
                    out.error = str(err) or type(err).__name__
                    transport_dead = True
        if not transport_dead:
            try:
                out.health = self._fetch(server, "/contiv/v1/health",
                                         self.timeout)
                out.ok = True
                out.error = ""
            except Exception as err:  # noqa: BLE001 - the reported gap
                out.ok = False
                out.error = str(err) or type(err).__name__
        out.elapsed_ms = round((time.monotonic() - t0) * 1e3, 1)
        now = time.time()
        with self._lock:
            if out.ok:
                self._last_seen[node] = now
            seen = self._last_seen.get(node)
        out.last_seen_age_s = (round(now - seen, 3)
                               if seen is not None else None)
        return out

    def scrape(self, light: bool = False,
               include_spans: bool = True) -> List[NodeScrape]:
        """One concurrent sweep over every agent.  Always returns one
        entry per configured node — reachable or not — and its wall
        time is bounded by the per-request timeout, not by node count,
        for fleets up to ``pool`` nodes (a frozen agent costs its own
        slot, nobody else's; beyond the pool cap sweeps serialize in
        pool-sized waves).  ``light``
        fetches health only — the cheap sweep a high-frequency monitor
        (the soak's drill timeline sampler) runs; ``include_spans=
        False`` skips the per-agent span-ring dumps for callers that
        render no spans (latency/top sweeps over a 100-node fleet
        should not pay 100 ring transfers per call)."""
        servers = self.servers()
        if not servers:
            return []
        # A DRAINED node deregistered on purpose (ISSUE 13): its slot
        # is filled without a scrape — state says why it is dark, so it
        # can never read as an unreachable gap or cost a timeout.
        drained = {n for n in servers if self.node_state(n) == "drained"}
        live = {n: s for n, s in servers.items() if n not in drained}
        out = {
            node: NodeScrape(node=node, server=servers[node], ok=False,
                             state="drained", error="drained")
            for node in drained
        }
        if live:
            with ThreadPoolExecutor(min(self.pool,
                                        max(1, len(live)))) as ex:
                futures = {
                    node: ex.submit(self._scrape_one, node, server, light,
                                    include_spans)
                    for node, server in sorted(live.items())
                }
                for node in futures:
                    scrape = futures[node].result()
                    scrape.state = self.node_state(node)
                    out[node] = scrape
        return [out[node] for node in sorted(out)]

    # ----------------------------------------------------------- rollups

    def cluster_latency(self, scrapes: Optional[List[NodeScrape]] = None
                        ) -> dict:
        """Cluster-merged latency distributions + per-node skew."""
        if scrapes is None:
            scrapes = self.scrape(include_spans=False)
        per_node = {
            s.node: (s.inspect or {}).get("latency") or {}
            for s in scrapes if s.ok and s.inspect
        }
        return {
            "nodes_reporting": len(per_node),
            "latency": merge_latency_snapshots(per_node),
            "skew": latency_skew(per_node,
                                 straggler_factor=self.straggler_factor),
            "gaps": self._gaps(scrapes),
        }

    def cluster_spans(self, scrapes: Optional[List[NodeScrape]] = None,
                      min_nodes: int = 2, limit: int = 0) -> dict:
        """Stitched cross-node propagation spans, newest first."""
        scrapes = self.scrape() if scrapes is None else scrapes
        per_node = {
            s.node: (s.spans or {}).get("spans") or []
            for s in scrapes if s.ok and s.spans
        }
        return {
            "nodes_reporting": len(per_node),
            "stitched": stitch_spans(
                per_node, min_nodes=min_nodes,
                straggler_factor=self.straggler_factor, limit=limit),
            "gaps": self._gaps(scrapes),
        }

    def summary(self, scrapes: Optional[List[NodeScrape]] = None) -> dict:
        """The fleet rollup (`netctl cluster top` / dashboard panel):
        reachability, per-node health one-liners, cluster latency, and
        the freshest stitched spans, in one pass over one sweep."""
        scrapes = self.scrape() if scrapes is None else scrapes
        rows = []
        for s in scrapes:
            ctl = (s.health or {}).get("controller") or {}
            lat = ((s.inspect or {}).get("latency") or {}
                   ).get("dispatch_rt") or {}
            spans_status = (s.spans or {}).get("status") or {}
            rows.append({
                "node": s.node,
                "server": s.server,
                "ok": s.ok,
                "state": s.state,
                "error": s.error,
                "last_seen_age_s": s.last_seen_age_s,
                "scrape_ms": s.elapsed_ms,
                "shards_serving": (s.health or {}).get("shards_serving"),
                "shards_total": (s.health or {}).get("shards_total"),
                "events": ctl.get("events_processed", 0),
                "event_errors": ctl.get("event_errors", 0),
                "resyncs": ctl.get("resync_count", 0),
                "healing_pending": bool(ctl.get("healing_pending")),
                "healing_failed": ctl.get("healing_failed", 0),
                "spans_propagated": spans_status.get("spans_propagated", 0),
                "p99_dispatch_us": lat.get("p99"),
            })
        latency = self.cluster_latency(scrapes)
        spans = self.cluster_spans(scrapes, limit=8)
        return {
            "nodes_total": len(scrapes),
            "nodes_ok": sum(1 for s in scrapes if s.ok),
            "nodes_unreachable": sum(
                1 for s in scrapes if not s.ok and s.state != "drained"),
            "nodes_drained": sum(
                1 for s in scrapes if s.state == "drained"),
            "drained": sorted(
                s.node for s in scrapes if s.state == "drained"),
            "gaps": self._gaps(scrapes),
            "per_node": rows,
            "latency": latency.get("latency"),
            "skew": latency.get("skew"),
            "spans": spans.get("stitched"),
        }

    @staticmethod
    def _gaps(scrapes: List[NodeScrape]) -> List[dict]:
        """Unreachable nodes as explicit records — the aggregator's
        partial-failure contract (a gap is data, not an exception).
        DRAINED nodes are excluded by contract (ISSUE 13): they are
        intentionally gone and reported under their own heading — a
        drained node is never a gap and never a straggler."""
        return [
            {"node": s.node, "server": s.server, "error": s.error,
             "last_seen_age_s": s.last_seen_age_s}
            for s in scrapes if not s.ok and s.state != "drained"
        ]


def _http_json(server: str, path: str, timeout: float) -> dict:
    req = urllib.request.Request(f"http://{server}{path}", method="GET")
    with urllib.request.urlopen(req, timeout=timeout) as resp:  # noqa: S310
        return json.loads(resp.read().decode())


def heartbeat_servers(store, prefix: str = "/vpp-tpu/test/heartbeat/"
                      ) -> Dict[str, str]:
    """Agent discovery off the cluster store's heartbeats (the procnode
    convention: each beat carries its REST address) — what
    ``scripts/cluster_obs.py --store`` and the soak conductor use, so
    the scraper follows agents across SIGKILL-restarts onto their fresh
    ephemeral ports."""
    return heartbeat_roster(store, prefix)["servers"]


def heartbeat_roster(store, prefix: str = "/vpp-tpu/test/heartbeat/"
                     ) -> Dict[str, Dict[str, str]]:
    """Like :func:`heartbeat_servers`, but roster-shaped: the REST
    address map PLUS each agent's heartbeat lifecycle state (ISSUE 13
    — ``active`` / ``draining`` / ``drained``).  Feed the roster to
    :class:`ClusterScraper` so drained nodes are reported as drained,
    never scraped into timeout gaps."""
    servers: Dict[str, str] = {}
    states: Dict[str, str] = {}
    for key, beat in store.list(prefix):
        if not isinstance(beat, dict):
            continue
        name = beat.get("name") or key[len(prefix):]
        states[name] = str(beat.get("state") or "active")
        if beat.get("rest"):
            servers[name] = beat["rest"]
    return {"servers": servers, "states": states}
