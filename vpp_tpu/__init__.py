"""vpp_tpu — a TPU-native packet-processing framework.

A brand-new framework with the capabilities of Contiv-VPP (reference:
/root/reference): an event-driven Kubernetes-style control plane that
compiles NetworkPolicies into 5-tuple ACL rule tables and Services into
NAT44 DNAT/load-balancing maps — with the per-packet classify->rewrite
data plane implemented as a jit-compiled JAX/Pallas pipeline operating on
256-packet header batches on TPU, instead of VPP graph nodes in C.

Package layout (mirrors the reference's layer map, SURVEY.md §1):

- ``models``      K8s-state data models + resource registry
                  (analog of plugins/ksr/model + dbresources)
- ``kvstore``     in-memory etcd-like KV store with watch/snapshot
- ``controller``  event loop, events, transactions, dbwatcher
                  (analog of plugins/controller)
- ``scheduler``   declarative-config txn scheduler with dependency
                  resolution (analog of ligato kvscheduler)
- ``ipam``, ``nodesync``, ``podmanager``, ``ipv4net``
                  domain plugins (same names as the reference)
- ``policy``      NetworkPolicy -> ContivRule stack
- ``service``     Service -> NAT44 stack
- ``ops``         JAX/Pallas TPU kernels: classify, NAT rewrite, pipeline
- ``parallel``    device-mesh sharding of rule tables and packet batches
- ``runtime``     host-side batch runner driving the TPU pipeline
"""

__version__ = "0.1.0"
