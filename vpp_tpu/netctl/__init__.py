"""netctl — CLI for cluster runtime state."""

from .cli import main

__all__ = ["main"]
