"""netctl CLI.

Analog of ``plugins/netctl`` + ``cmd/contiv-netctl`` (cmd/root.go
:55-134): subcommands reading each agent's REST API —

- ``nodes``      cluster nodes and their data-plane IPs
- ``pods``       local pods of an agent
- ``ipam``       the agent's IPAM state
- ``dump``       data-plane config dump from the txn scheduler; with
                 ``--key-class <prefix>`` an arbitrary keyspace dump of
                 the agent's cluster-store view instead (the full
                 ``vppdump`` analog: any key class, any node), and
                 ``--key-classes`` lists the selectable classes
- ``log``        runtime log levels: list all components, or set one
                 (``netctl log vpp_tpu.policy DEBUG``)
- ``history``    controller event history
- ``resync``     trigger an on-demand full resync
- ``metrics``    Prometheus metrics passthrough
- ``inspect``    live datapath interrogation (the ``vppcli`` analog):
                 classify/NAT table stats, session + affinity
                 occupancy, ring depths, punt counters; ``--watch N``
                 streams
- ``health``     datapath fault-domain health: per-shard supervision
                 state (healthy/degraded/ejected/probation/rejoined),
                 ejection/rejoin/steer counters, poisoned-batch
                 quarantine totals, table-swap rollbacks
- ``fault``      fault-injection harness control: list armed plans,
                 ``fault arm dispatch-raise --shard 1 --count 4``,
                 ``fault disarm [--site s]`` (chaos drills / testing)
- ``spans``      recent config-propagation spans: per-stage timings of
                 event → compile → device swap → shard adoption, plus
                 the end-to-end propagation latency histogram
- ``flight``     the datapath flight recorder: the last N dispatch
                 records per shard (K, backlog, in-flight depth, table
                 generation, verdicts, round-trip µs) for post-mortems
- ``drain``      graceful node drain (ISSUE 13): gate new CNI ADDs
                 (retriable rejection), quiesce in-flight dispatch,
                 flush flight/telemetry, flip the heartbeat to a
                 *drained* tombstone (reported as drained, never as an
                 unreachable gap)
- ``undrain``    rejoin a drained agent cleanly (ADDs accepted again)
- ``cluster``    fleet scope (ISSUE 10): scrape MANY agents at once —
                 ``cluster top`` per-node health rollup, ``cluster
                 latency`` cluster-merged p50/p99/p99.9 + straggler
                 detection, ``cluster spans`` store writes stitched
                 across every node that adopted them; unreachable
                 agents are reported gaps, never hangs (exit 0)

Run: ``python -m vpp_tpu.netctl <command> [--server host:port]``;
``cluster`` takes ``--servers name=host:port,...`` instead.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Any, List, Optional


def _fetch(server: str, path: str, method: str = "GET") -> Any:
    req = urllib.request.Request(f"http://{server}{path}", method=method)
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = resp.read().decode()
        if resp.headers.get_content_type() == "application/json":
            return json.loads(body)
        return body


def _table(rows: List[List[str]], header: List[str]) -> str:
    all_rows = [header] + [[str(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in all_rows) for i in range(len(header))]
    lines = []
    for idx, row in enumerate(all_rows):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip())
        if idx == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def cmd_nodes(server: str, out) -> int:
    nodes = _fetch(server, "/contiv/v1/nodes")
    rows = [
        [n.get("id", ""), n.get("name", ""),
         ",".join(n.get("ip_addresses", []) or [])]
        for n in sorted(nodes, key=lambda n: n.get("id", 0))
    ]
    print(_table(rows, ["ID", "NAME", "DATA-PLANE-IPS"]), file=out)
    return 0


def cmd_pods(server: str, out) -> int:
    pods = _fetch(server, "/contiv/v1/pods")
    rows = []
    for p in pods:
        pid = p.get("id", {})
        rows.append([pid.get("namespace", ""), pid.get("name", ""),
                     p.get("container_id", ""), p.get("network_namespace", "")])
    print(_table(sorted(rows), ["NAMESPACE", "NAME", "CONTAINER", "NETNS"]), file=out)
    return 0


def cmd_ipam(server: str, out) -> int:
    print(json.dumps(_fetch(server, "/contiv/v1/ipam"), indent=1), file=out)
    return 0


def cmd_dump(server: str, out, prefix: str = "") -> int:
    values = _fetch(server, f"/scheduler/dump?prefix={prefix}")
    rows = [
        [v.get("key", ""), v.get("state", ""), v.get("last_error", "")]
        for v in values
    ]
    print(_table(sorted(rows), ["KEY", "STATE", "ERROR"]), file=out)
    return 0


def cmd_store_dump(server: str, out, key_class: str) -> int:
    """Arbitrary keyspace dump with key-class selection (the reference's
    ``netctl vppdump <class>``, plugins/netctl/cmdimpl/vppdump.go):
    reads the agent's own view of the cluster store, so it works
    against ANY node — leader-served for remote-store agents, local for
    in-process ones."""
    from urllib.parse import quote

    items = _fetch(server, f"/contiv/v1/store?prefix={quote(key_class)}")
    rows = [[i["key"], json.dumps(i["value"], sort_keys=True, default=str)]
            for i in items]
    print(_table(sorted(rows), ["KEY", "VALUE"]), file=out)
    return 0


def cmd_store_classes(server: str, out) -> int:
    classes = _fetch(server, "/contiv/v1/store/classes")
    rows = [[c["keyword"], c["prefix"]] for c in classes]
    print(_table(sorted(rows), ["CLASS", "PREFIX"]), file=out)
    return 0


def cmd_log(server: str, out, logger: str = "", level: str = "") -> int:
    """Runtime log-level control (cn-infra logmanager analog)."""
    if logger and level:
        res = _fetch(server, f"/logging?logger={logger}&level={level}",
                     method="POST")
        print(f"{res['logger']} -> {res['level']}", file=out)
        return 0
    levels = _fetch(server, "/logging")
    rows = [[name, v["level"] + (" (inherited)" if v["inherited"] else "")]
            for name, v in sorted(levels.items())
            if not logger or name.startswith(logger)]
    print(_table(rows, ["LOGGER", "LEVEL"]), file=out)
    return 0


def cmd_history(server: str, out) -> int:
    for rec in _fetch(server, "/controller/event-history"):
        handlers = ",".join(h.get("handler", "") for h in rec.get("handlers", []))
        print(f"#{rec.get('seq_num')} {rec.get('description')} "
              f"[{handlers}] {rec.get('duration_ms', 0):.1f}ms", file=out)
    return 0


def cmd_resync(server: str, out) -> int:
    print(json.dumps(_fetch(server, "/controller/resync", method="POST")), file=out)
    return 0


def cmd_metrics(server: str, out) -> int:
    print(_fetch(server, "/metrics"), file=out)
    return 0


def cmd_trace(server: str, out, action: str = "", sample: int = 1) -> int:
    """Packet tracing (scripts/vpptrace.sh analog): enable/disable/clear
    sampled traces or dump the buffer."""
    if action:
        q = f"?sample={sample}" if action == "enable" else ""
        res = _fetch(server, f"/contiv/v1/trace/{action}{q}", method="POST")
        print(json.dumps(res), file=out)
        return 0
    res = _fetch(server, "/contiv/v1/trace")
    st = res["status"]
    print(
        f"trace: enabled={st['enabled']} sample=1/{st['sample_every']} "
        f"recorded={st['recorded']}/{st['capacity']} seen={st['total_seen']}",
        file=out,
    )
    rows = []
    for e in res["entries"]:
        flags = "".join(
            c for c, on in (("D", e["dnat"]), ("S", e["snat"]),
                            ("R", e["reply"]), ("P", e["punt"])) if on
        )
        rows.append([
            str(e["seq"]),
            f"{e['src']}:{e['src_port']}",
            f"{e['dst']}:{e['dst_port']}",
            str(e["protocol"]),
            f"{e['rw_src']}:{e['rw_src_port']}",
            f"{e['rw_dst']}:{e['rw_dst_port']}",
            "allow" if e["allowed"] else "deny",
            e["route"] + (f"#{e['node_id']}" if e["route"] == "remote" else ""),
            flags,
            # Correlation stamps (ISSUE 8): the table generation the
            # batch dispatched under + the governor-chosen K — join
            # keys into `netctl flight` rows and propagation spans.
            str(e.get("table_gen", 0)),
            str(e.get("k", 0)),
            # Inference stage (ISSUE 14): score band + fired action.
            f"{e.get('infer_band', 0)}"
            + (f"!{e.get('infer_action')}" if e.get("infer_action") else ""),
        ])
    print(_table(rows, ["SEQ", "SRC", "DST", "PROTO", "RW-SRC", "RW-DST",
                        "VERDICT", "ROUTE", "FLAGS", "GEN", "K", "INF"]),
          file=out)
    return 0


def cmd_spans(server: str, out, raw: bool = False, limit: int = 20) -> int:
    """Config-propagation spans: how long from the K8s event until the
    rule was live on the device, stage by stage."""
    d = _fetch(server, f"/contiv/v1/spans?limit={limit}")
    if raw:
        print(json.dumps(d, indent=2), file=out)
        return 0
    st = d["status"]
    p = st.get("propagation_us") or {}
    print(f"node {d.get('node', '?')}  spans={st['spans_started']} "
          f"propagated={st['spans_propagated']}  recorded="
          f"{st['recorded']}/{st['capacity']}", file=out)
    print(f"propagation: n={p.get('count', 0)}  p50={p.get('p50', 0)}us "
          f"p90={p.get('p90', 0)}us  p99={p.get('p99', 0)}us  "
          f"p99.9={p.get('p999', 0)}us", file=out)
    rows = []
    for s in d["spans"]:
        stages = " ".join(
            f"{g['stage']}={g['us']:.0f}us"
            + (f"({g['mode']})" if g.get("mode") else "")
            for g in s["stages"]
        )
        rows.append([s["span_id"], s["event"],
                     f"{s['total_us']:.0f}",
                     "yes" if s["propagated"] else "-",
                     stages[:120]])
    print(_table(rows, ["SPAN", "EVENT", "TOTAL-US", "DEVICE", "STAGES"]),
          file=out)
    return 0


def cmd_flight(server: str, out, raw: bool = False, limit: int = 20) -> int:
    """Flight-recorder dump: the per-shard ring of recent dispatches."""
    d = _fetch(server, f"/contiv/v1/flight?limit={limit}")
    if raw:
        print(json.dumps(d, indent=2), file=out)
        return 0
    for shard in d["shards"]:
        print(f"node {d.get('node', '?')}  shard {shard['shard']}  "
              f"dispatches={shard['dispatches_total']}  recorded="
              f"{shard['recorded']}/{shard['capacity']}", file=out)
        rows = [
            [r["seq"], r["ts"], r["k"], r["frames"], r["sent"], r["denied"],
             r["backlog"], r["inflight"], r["table_gen"], r["rt_us"]]
            for r in shard["records"]
        ]
        if rows:
            print(_table(rows, ["SEQ", "TS", "K", "FRAMES", "SENT", "DENIED",
                                "BACKLOG", "INFLIGHT", "GEN", "RT-US"]),
                  file=out)
    return 0


def parse_servers(spec: str) -> dict:
    """``name=host:port,name2=host:port`` (or bare ``host:port`` items,
    named after themselves) → {name: server} for the cluster scraper."""
    servers = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        name, sep, addr = item.partition("=")
        servers[name if sep else item] = addr if sep else item
    return servers


def _fmt_age(age) -> str:
    return "never" if age is None else f"{age:.1f}s ago"


def cmd_cluster(out, action: str, servers_spec: str = "", raw: bool = False,
                limit: int = 10, timeout: float = 3.0,
                factor: float = 3.0, scraper=None) -> int:
    """Fleet-scope commands (ISSUE 10): one concurrent sweep over every
    agent in ``--servers``; an unreachable agent is printed as a GAP
    row with its last-seen age and the command still exits 0 — partial
    visibility beats none during exactly the incidents that cause
    partial visibility.  Exit 1 only when NO agent answered.

    ``scraper`` lets a long-lived caller (``cluster_obs.py --watch``)
    reuse one ClusterScraper across sweeps so gap rows carry real
    last-seen ages; a one-shot CLI invocation has no history and
    prints ``never``.  The ``latency`` action renders no span data, so
    its sweep skips the per-agent span-ring transfers (cheap at fleet
    scale); ``top``/``spans`` consume them (per-node propagated counts,
    the stitched table), and ``--raw`` always fetches everything — a
    raw dump must never render unfetched fields as plausible zeros."""
    from ..statscollector.cluster import ClusterScraper

    if scraper is None:
        servers = parse_servers(servers_spec)
        if not servers:
            print("netctl: cluster needs --servers name=host:port,...",
                  file=sys.stderr)
            return 1
        scraper = ClusterScraper(servers, timeout=timeout,
                                 straggler_factor=factor)
    scrapes = scraper.scrape(include_spans=(action != "latency" or raw))
    summary = scraper.summary(scrapes)
    if raw:
        print(json.dumps(summary, indent=2), file=out)
        return 0 if summary.get("nodes_ok") else 1
    print(f"cluster: {summary.get('nodes_ok', 0)}/"
          f"{summary.get('nodes_total', 0)} agents reporting"
          f"  unreachable={summary.get('nodes_unreachable', 0)}"
          f"  drained={summary.get('nodes_drained', 0)}", file=out)
    for name in summary.get("drained") or []:
        # Intentionally gone (ISSUE 13): its own line, never a GAP.
        print(f"DRAINED {name}", file=out)
    for gap in summary.get("gaps") or []:
        print(f"GAP {gap.get('node')} ({gap.get('server')}): "
              f"{gap.get('error')}  last-seen "
              f"{_fmt_age(gap.get('last_seen_age_s'))}", file=out)
    if action in ("", "top"):
        rows = []
        for r in summary.get("per_node") or []:
            shards = ("-" if r.get("shards_total") is None
                      else f"{r.get('shards_serving')}/{r.get('shards_total')}")
            healing = ("pending" if r.get("healing_pending")
                       else f"failed={r.get('healing_failed')}"
                       if r.get("healing_failed") else "ok")
            state = ("up" if r.get("ok")
                     else "drained" if r.get("state") == "drained"
                     else "GAP")
            rows.append([
                r.get("node"), state, shards,
                r.get("events"), r.get("event_errors"), r.get("resyncs"),
                healing, r.get("spans_propagated"),
                "-" if r.get("p99_dispatch_us") is None
                else r.get("p99_dispatch_us"),
            ])
        print(_table(rows, ["NODE", "STATE", "SHARDS", "EVENTS", "ERRS",
                            "RESYNCS", "HEALING", "SPANS", "P99-US"]),
              file=out)
    elif action == "latency":
        lat = summary.get("latency") or {}
        for name in ("admit_wait", "dispatch_rt", "harvest", "frame_e2e"):
            h = lat.get(name) or {}
            print(f"{name}: n={h.get('count', 0)}  p50={h.get('p50', 0)}us"
                  f"  p90={h.get('p90', 0)}us  p99={h.get('p99', 0)}us"
                  f"  p99.9={h.get('p999', 0)}us", file=out)
        skew = summary.get("skew") or {}
        print(f"skew[{skew.get('metric')}/{skew.get('quantile')}]: "
              f"cluster-median={skew.get('cluster_median_us', 0)}us "
              f"straggler>{skew.get('factor')}x", file=out)
        for s in skew.get("stragglers") or []:
            print(f"STRAGGLER {s.get('node')}: {s.get('value_us')}us "
                  f"({s.get('samples')} samples)", file=out)
    elif action == "spans":
        rows = []
        for sp in (summary.get("spans") or [])[:limit]:
            stragglers = ",".join(
                s.get("node", "") for s in sp.get("stragglers") or []) or "-"
            rows.append([
                sp.get("revision"), sp.get("event"), sp.get("nodes"),
                sp.get("propagated_nodes"),
                f"{sp.get('first_lag_us', 0):.0f}",
                f"{sp.get('p50_lag_us', 0):.0f}",
                f"{sp.get('p99_lag_us', 0):.0f}",
                f"{sp.get('last_lag_us', 0):.0f}",
                sp.get("last_node"), stragglers,
            ])
        print(_table(rows, ["REV", "EVENT", "NODES", "DEV", "FIRST-US",
                            "P50-US", "P99-US", "LAST-US", "LAST-NODE",
                            "STRAGGLERS"]), file=out)
    else:
        print(f"netctl: unknown cluster action {action!r}", file=sys.stderr)
        return 1
    return 0 if summary.get("nodes_ok") else 1


def _render_inference(inf: dict, out) -> None:
    """The `netctl inspect` inference line (ISSUE 14): enrollment +
    per-action counters + the score log2-histogram.  Consumes ONLY
    keys ``DataplaneRunner.inspect_inference`` produces as literals —
    the obs-parity checker pins the pair, so a renamed counter can
    never silently blank this line."""
    bands = inf.get("score_bands") or []
    bands_s = " ".join(
        f"{i}:{c}" for i, c in enumerate(bands) if c) or "-"
    print(f"inference: {'on' if inf.get('enabled') else 'off'}  "
          f"pods={inf.get('pods')}  model={inf.get('features')}x"
          f"{inf.get('hidden')}  swaps={inf.get('swaps')}  "
          f"scored={inf.get('scored')}  log={inf.get('logged')}  "
          f"deprio={inf.get('deprioritized')}  quarantined="
          f"{inf.get('quarantined')}  bands: {bands_s}", file=out)


def cmd_inspect(server: str, out, watch: float = 0.0, raw: bool = False) -> int:
    """Live datapath interrogation (the ``vppcli`` analog, reference
    plugins/netctl/cmd/root.go:55-134): classify/NAT table stats,
    session + affinity occupancy, ring depths, punt counters and the
    dispatch configuration of a RUNNING agent.  ``--watch N`` streams
    a fresh snapshot every N seconds (Ctrl-C stops)."""
    import time

    def render() -> None:
        d = _fetch(server, "/contiv/v1/inspect")
        if raw:
            print(json.dumps(d, indent=2), file=out)
            return
        dp, cl, nt = d["dispatch"], d["classify"], d["nat"]
        se, sp, c = d["sessions"], d["slowpath"], d["counters"]
        n_shards = len(d.get("shards") or [])
        print(f"node {d.get('node', '?')}  engine={d['engine']}  "
              f"dispatch={dp['discipline']} {dp['max_vectors']}x"
              f"{dp['batch_size']}  inflight={dp['inflight']}/"
              f"{dp['max_inflight']}  bypass="
              f"{'on' if dp['bypass_eligible'] else 'off'}"
              f"{'  shards=' + str(n_shards) if n_shards else ''}"
              f"{'  mesh=' + dp['mesh'] if dp['mesh'] else ''}", file=out)
        gov = dp.get("governor") or {}
        if gov:
            hist = gov.get("k_histogram") or {}
            hist_s = " ".join(f"{k}:{v}" for k, v in hist.items()) or "-"
            floor = gov.get("floor_us")
            vec = gov.get("vec_us")
            if floor is None:
                model = "model=warming"
            else:
                # vec stays unknown while every sample sits at one K
                # (quiet link): the fit is degenerate, not absent.
                model = (f"floor={floor}us "
                         f"vec={'?' if vec is None else vec}us")
            print(f"governor: {'adaptive' if gov.get('enabled') else 'fixed'}"
                  f"  K={gov.get('current_k')}/{gov.get('ceiling')}"
                  f"  backlog={gov.get('backlog')}"
                  f"  slo={gov.get('slo_us')}us cap={gov.get('slo_cap')}"
                  f" breaches={gov.get('slo_breaches')}"
                  f"  {model}  K-hist: {hist_s}", file=out)
        led = gov.get("ledger") or {}
        if led:
            claims = " ".join(
                f"{i}:{c}" for i, c in
                enumerate(led.get("per_shard_claim_us") or []))
            print(f"ledger: budget={led.get('slo_us')}us "
                  f"committed={led.get('committed_us')}us "
                  f"constrained={led.get('constrained_total')}"
                  f"  claims: {claims or '-'}", file=out)
        placement = dp.get("placement") or {}
        if placement:
            pairs = []
            applied = placement.get("applied") or []
            for i, want in enumerate(placement.get("shard_cores") or []):
                got = applied[i] if i < len(applied) else None
                want_s = ",".join(str(c) for c in want) if want else "-"
                if got is None:
                    got_s = "unspawned"
                elif got == "":
                    got_s = "unpinned"
                else:
                    got_s = got
                pairs.append(f"{i}:{want_s}->{got_s}")
            print(f"placement: {' '.join(pairs) or '-'} "
                  f"(host cores {placement.get('host_cores')})", file=out)
        print(f"classify: {cl['rules']} rules / {cl['tables']} tables / "
              f"{cl['pods']} pods    nat: {nt['mappings']} mappings "
              f"ring={nt['bucket_size']} "
              f"lookup={'hash' if nt['use_hmap'] else 'dense'}"
              f"{' affinity' if nt['has_affinity'] else ''}"
              f"{' snat' if nt['snat_enabled'] else ''}", file=out)
        print(f"sessions: {se['active']}/{se['capacity']} active, "
              f"{se['affinity_pins']} affinity pins   slowpath: "
              f"{sp['sessions']} sessions", file=out)
        lat = d.get("latency") or {}
        if lat:
            parts = []
            for name in ("admit_wait", "dispatch_rt", "harvest", "frame_e2e"):
                h = lat.get(name) or {}
                if h.get("count"):
                    parts.append(f"{name} p50={h['p50']}us p99={h['p99']}us "
                                 f"p99.9={h['p999']}us")
            if parts:
                print("latency: " + "   ".join(parts), file=out)
        rounds = dp.get("rounds") or {}
        parts = []
        for name in ("wait", "materialize", "restore", "stitch"):
            h = rounds.get(name) or {}
            if h.get("count"):
                parts.append(f"{name} p50={h['p50']}us p99={h['p99']}us")
        if parts:
            print("rounds: " + "   ".join(parts), file=out)
        inf = d.get("inference") or {}
        if inf.get("enabled") or inf.get("scored"):
            _render_inference(inf, out)
        comp = d.get("compile") or {}
        if comp:
            parts = [f"swaps acl={comp.get('acl_swaps', 0)} "
                     f"nat={comp.get('nat_swaps', 0)}"]
            for name in ("acl", "nat", "infer"):
                cs = comp.get(name) or {}
                if cs:
                    parts.append(
                        f"{name}: {cs.get('delta_builds', 0)} delta / "
                        f"{cs.get('full_builds', 0)} full compiles, "
                        f"{cs.get('rows_shipped', 0)} rows "
                        f"({cs.get('bytes_shipped', 0)} B) shipped"
                    )
            print("compile: " + "   ".join(parts), file=out)
        rows = [[name, info.get("frames", "-"), info.get("dropped", "-")]
                for name, info in d["rings"].items() if info]
        if rows:
            print(_table(rows, ["RING", "FRAMES", "DROPPED"]), file=out)
        keys = ("datapath_rx_frames_total", "datapath_tx_local_total",
                "datapath_tx_remote_total", "datapath_tx_host_total",
                "datapath_dropped_denied_total", "datapath_punts_total",
                "datapath_batches_total", "datapath_bypass_batches_total")
        print("  ".join(f"{k.replace('datapath_', '').replace('_total', '')}"
                        f"={c[k]}" for k in keys if k in c), file=out)

    render()
    try:
        while watch > 0:
            time.sleep(watch)
            print("", file=out)
            render()
    except KeyboardInterrupt:
        pass  # Ctrl-C stops the stream cleanly, as documented
    return 0


def cmd_health(server: str, out, raw: bool = False,
               recover: Optional[int] = None) -> int:
    """Datapath fault-domain health: the shard supervisor's view of a
    RUNNING agent — which shards serve, which are ejected and why, how
    much traffic was steered/quarantined/dropped.  ``--recover [N]``
    expedites ejected shards into probation."""
    if recover is not None:
        q = f"?shard={recover}" if recover >= 0 else ""
        res = _fetch(server, f"/contiv/v1/health/recover{q}", method="POST")
        print(f"recovering {res['recovering']} shard(s)", file=out)
        return 0
    d = _fetch(server, "/contiv/v1/health")
    if raw:
        print(json.dumps(d, indent=2), file=out)
        return 0
    drain = d.get("drain")
    if drain and drain.get("state") != "active":
        print(f"drain: {drain['state']}  rejected_adds="
              f"{drain.get('rejected_adds', 0)}", file=out)
    ctl = d.get("controller")
    if ctl:
        age = ctl.get("last_resync_age_s")
        print(f"controller: resyncs={ctl.get('resync_count', 0)}  events="
              f"{ctl.get('events_processed', 0)}  event_errors="
              f"{ctl.get('event_errors', 0)}  healing="
              f"{ctl.get('healing_completed', 0)}/"
              f"{ctl.get('healing_scheduled', 0)} done/sched "
              f"(failed={ctl.get('healing_failed', 0)}"
              f"{', pending' if ctl.get('healing_pending') else ''})"
              f"  last-resync="
              f"{'never' if age is None else f'{age:.1f}s ago'}", file=out)
    if "shards" not in d and "dispatch_errors" not in d:
        # Control-plane-only agent: no datapath section to render.
        return 0
    if "shards" not in d:
        # Solo runner: flat health dict, no supervisor.
        q = d.get("quarantine") or {}
        print(f"node {d.get('node', '?')}  dispatch_errors="
              f"{d.get('dispatch_errors', 0)}  source_errors="
              f"{d.get('source_errors', 0)}  swap_rollbacks="
              f"{d.get('swap_rollbacks', 0)}  quarantined="
              f"{q.get('batches', 0)} batches/"
              f"{q.get('poisoned_frames', 0)} frames", file=out)
        if d.get("last_error"):
            print(f"last error: {d['last_error']}", file=out)
        return 0
    print(f"node {d.get('node', '?')}  shards {d['shards_serving']}/"
          f"{d['shards_total']} serving  all-down policy="
          f"{d['policy_all_down']}"
          f"{'  ALL DOWN' if d['all_down'] else ''}", file=out)
    print(f"ejections={d['ejections']}  rejoins={d['rejoins']}  "
          f"steered={d['steered_frames']}  quarantined="
          f"{d['quarantined_batches']} batches/"
          f"{d['poisoned_frames']} frames  swap_rollbacks="
          f"{d['swap_rollbacks']}  failclosed_drops="
          f"{d['failclosed_drops']}  bypass_forwards="
          f"{d['bypass_forwards']}", file=out)
    rows = [
        [s["shard"], s["state"], s["consecutive_errors"], s["ejections"],
         s["rejoins"], s["dispatch_errors"], s["poisoned_frames"],
         (s["last_error"][:48] if s["last_error"] else "-")]
        for s in d["shards"]
    ]
    print(_table(rows, ["SHARD", "STATE", "ERRS", "EJECT", "REJOIN",
                        "DISP-ERRS", "POISONED", "LAST-ERROR"]), file=out)
    return 0


def cmd_drain(server: str, out, undrain: bool = False) -> int:
    """Graceful drain / rejoin of one agent (ISSUE 13): the planned
    node-maintenance path — distinct from a crash in every surface
    (heartbeat tombstone, cluster scraper, CNI rejection class)."""
    action = "undrain" if undrain else "drain"
    res = _fetch(server, f"/contiv/v1/{action}", method="POST")
    flush = res.get("last_flush") or {}
    extra = ""
    if not undrain and flush:
        parts = []
        if "quiesced_frames" in flush:
            parts.append(f"quiesced {flush['quiesced_frames']} frames")
        if flush.get("flight"):
            parts.append(f"flight flushed ({flush['flight'].get('shards', 0)}"
                         " shards)")
        if parts:
            extra = "  (" + ", ".join(parts) + ")"
    print(f"{server}: {res['state']}{extra}  drains={res['drains']} "
          f"undrains={res['undrains']} "
          f"rejected_adds={res['rejected_adds']}", file=out)
    return 0


def cmd_fault(server: str, out, action: str = "", site: str = "",
              shard: Optional[int] = None, count: Optional[int] = None,
              mode: str = "", seconds: float = 30.0) -> int:
    """Fault-injection harness control (chaos drills): list the armed
    plans, arm a named site, or disarm."""
    if action in ("", "list"):
        st = _fetch(server, "/contiv/v1/faults")
        print(f"armed={st['armed']}  sites: {', '.join(st['sites'])}",
              file=out)
        rows = [[p["id"], p["site"],
                 p["shard"] if p["shard"] is not None else "any",
                 p["remaining"] if p["remaining"] is not None else "inf",
                 p["mode"], p["fired"]]
                for p in st["plans"]]
        if rows:
            print(_table(rows, ["ID", "SITE", "SHARD", "REMAINING", "MODE",
                                "FIRED"]), file=out)
        return 0
    if action == "arm":
        if not site:
            print("netctl: fault arm needs a site", file=sys.stderr)
            return 1
        q = f"site={site}&seconds={seconds}"
        if shard is not None:
            q += f"&shard={shard}"
        if count is not None:
            q += f"&count={count}"
        if mode:
            q += f"&mode={mode}"
        res = _fetch(server, f"/contiv/v1/faults/arm?{q}", method="POST")
        print(f"armed plan #{res['armed_plan']} at {site}", file=out)
        return 0
    if action == "disarm":
        q = f"?site={site}" if site else ""
        res = _fetch(server, f"/contiv/v1/faults/disarm{q}", method="POST")
        print(f"disarmed {res['disarmed']} plan(s)", file=out)
        return 0
    print(f"netctl: unknown fault action {action!r}", file=sys.stderr)
    return 1


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out if out is not None else sys.stdout
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--server", default="127.0.0.1:9999",
                        help="agent REST endpoint (host:port)")
    parser = argparse.ArgumentParser(
        prog="netctl", description="vpp-tpu cluster runtime state CLI"
    )
    sub = parser.add_subparsers(dest="command", required=True)
    for name in ("nodes", "pods", "ipam", "history", "resync", "metrics"):
        sub.add_parser(name, parents=[common])
    dump = sub.add_parser("dump", parents=[common])
    dump.add_argument("prefix", nargs="?", default="")
    dump.add_argument("--key-class", default=None,
                      help="dump the agent's cluster-store view under this "
                           "key prefix instead of the scheduler state "
                           "('' dumps every key)")
    dump.add_argument("--key-classes", action="store_true",
                      help="list the selectable key classes")
    logcmd = sub.add_parser("log", parents=[common])
    logcmd.add_argument("logger", nargs="?", default="",
                        help="component logger (prefix filter when listing)")
    logcmd.add_argument("level", nargs="?", default="",
                        help="new level (DEBUG/INFO/WARNING/ERROR); "
                             "omit to list")
    trace = sub.add_parser("trace", parents=[common])
    trace.add_argument("action", nargs="?", default="",
                       choices=["", "enable", "disable", "clear"])
    trace.add_argument("--sample", type=int, default=1,
                       help="record every Nth packet")
    inspect = sub.add_parser("inspect", parents=[common])
    inspect.add_argument("--watch", type=float, default=0.0,
                         help="stream a snapshot every N seconds")
    inspect.add_argument("--raw", action="store_true",
                         help="full JSON instead of the summary view")
    sub.add_parser("drain", parents=[common])
    sub.add_parser("undrain", parents=[common])
    healthcmd = sub.add_parser("health", parents=[common])
    healthcmd.add_argument("--raw", action="store_true",
                           help="full JSON instead of the summary view")
    healthcmd.add_argument("--recover", type=int, nargs="?", const=-1,
                           default=None, metavar="SHARD",
                           help="expedite ejected shards into probation "
                                "(all, or one shard index)")
    fault = sub.add_parser("fault", parents=[common])
    fault.add_argument("action", nargs="?", default="",
                       choices=["", "list", "arm", "disarm"])
    fault.add_argument("site", nargs="?", default="",
                       help="injection site (dispatch-raise, dispatch-hang, "
                            "swap-fail, frame-source-error)")
    fault.add_argument("--shard", type=int, default=None,
                       help="restrict to one shard (default: any)")
    fault.add_argument("--count", type=int, default=None,
                       help="fire at most N times (default: until disarmed)")
    fault.add_argument("--mode", default="", choices=["", "raise", "hang"])
    fault.add_argument("--seconds", type=float, default=30.0,
                       help="hang-mode safety timeout")
    spanscmd = sub.add_parser("spans", parents=[common])
    spanscmd.add_argument("--raw", action="store_true",
                          help="full JSON instead of the summary view")
    spanscmd.add_argument("--limit", type=int, default=20,
                          help="show the most recent N spans")
    flightcmd = sub.add_parser("flight", parents=[common])
    flightcmd.add_argument("--raw", action="store_true",
                           help="full JSON instead of the summary view")
    flightcmd.add_argument("--limit", type=int, default=20,
                           help="show the most recent N records per shard")
    clustercmd = sub.add_parser("cluster")
    clustercmd.add_argument("action", nargs="?", default="top",
                            choices=["top", "latency", "spans"])
    clustercmd.add_argument("--servers", default="",
                            help="comma list of agents to sweep "
                                 "(name=host:port, or bare host:port)")
    clustercmd.add_argument("--raw", action="store_true",
                            help="full JSON instead of the summary view")
    clustercmd.add_argument("--limit", type=int, default=10,
                            help="show the most recent N stitched spans")
    clustercmd.add_argument("--timeout", type=float, default=3.0,
                            help="per-agent scrape timeout (an "
                                 "unreachable agent is a reported gap)")
    clustercmd.add_argument("--straggler-factor", type=float, default=3.0,
                            help="flag nodes above N x the cluster median")
    args = parser.parse_args(argv)

    try:
        if args.command == "dump":
            if args.key_classes:
                return cmd_store_classes(args.server, out)
            if args.key_class is not None:
                return cmd_store_dump(args.server, out, args.key_class)
            return cmd_dump(args.server, out, args.prefix)
        if args.command == "log":
            return cmd_log(args.server, out, args.logger, args.level)
        if args.command == "trace":
            return cmd_trace(args.server, out, args.action, args.sample)
        if args.command == "inspect":
            return cmd_inspect(args.server, out, args.watch, args.raw)
        if args.command == "health":
            return cmd_health(args.server, out, args.raw, args.recover)
        if args.command in ("drain", "undrain"):
            return cmd_drain(args.server, out,
                             undrain=args.command == "undrain")
        if args.command == "fault":
            return cmd_fault(args.server, out, args.action, args.site,
                             args.shard, args.count, args.mode, args.seconds)
        if args.command == "spans":
            return cmd_spans(args.server, out, args.raw, args.limit)
        if args.command == "flight":
            return cmd_flight(args.server, out, args.raw, args.limit)
        if args.command == "cluster":
            return cmd_cluster(out, args.action, args.servers, args.raw,
                               args.limit, args.timeout,
                               args.straggler_factor)
        return {
            "nodes": cmd_nodes,
            "pods": cmd_pods,
            "ipam": cmd_ipam,
            "history": cmd_history,
            "resync": cmd_resync,
            "metrics": cmd_metrics,
        }[args.command](args.server, out)
    except Exception as err:  # noqa: BLE001
        print(f"netctl: {err}", file=sys.stderr)
        return 1
