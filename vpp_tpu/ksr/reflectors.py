"""Per-resource K8s → model converters and reflector construction.

Analog of the reference's per-resource reflectors
(``plugins/ksr/{pod,namespace,policy,service,endpoints,node}_reflector.go``):
each converter parses a K8s-JSON-shaped dict into the corresponding typed
model (the ``podToProto``/``policyToProto``/... analogs) and yields the
data-store key from the model registry.

The input shape is the K8s API wire format (``metadata``/``spec``/
``status``), so a production ListWatch can feed API-server JSON straight
through.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..models import (
    Container,
    ContainerPort,
    EndpointAddress,
    EndpointPort,
    Endpoints,
    EgressRule,
    IPBlock,
    IngressRule,
    LabelExpression,
    LabelSelector,
    Namespace,
    Node,
    NodeAddress,
    Peer,
    Pod,
    PodID,
    Policy,
    PolicyPort,
    PolicyType,
    ExpressionOperator,
    Service,
    ServicePort,
)
from ..models.registry import key_for, resource
from .listwatch import K8sListWatch
from .reflector import Broker, Reflector


def _meta(obj: Dict) -> Tuple[str, str, Dict[str, str]]:
    meta = obj.get("metadata", {})
    return meta.get("name", ""), meta.get("namespace", "default"), meta.get("labels") or {}


# ------------------------------------------------------------------- pod


def pod_to_model(obj: Dict) -> Optional[Tuple[Pod, str]]:
    """podToProto analog (pod_reflector.go:120-160)."""
    name, namespace, labels = _meta(obj)
    if not name:
        return None
    spec = obj.get("spec", {})
    status = obj.get("status", {})
    containers = []
    for c in spec.get("containers", []):
        ports = tuple(
            ContainerPort(
                name=p.get("name", ""),
                host_port=p.get("hostPort", 0),
                container_port=p.get("containerPort", 0),
                protocol=p.get("protocol", "TCP"),
                host_ip_address=p.get("hostIP", ""),
            )
            for p in c.get("ports", [])
        )
        containers.append(Container(name=c.get("name", ""), ports=ports))
    model = Pod(
        name=name,
        namespace=namespace,
        labels=labels,
        ip_address=status.get("podIP", ""),
        host_ip_address=status.get("hostIP", ""),
        containers=tuple(containers),
    )
    return model, key_for(model)


# ------------------------------------------------------------- namespace


def namespace_to_model(obj: Dict) -> Optional[Tuple[Namespace, str]]:
    name, _, labels = _meta(obj)
    if not name:
        return None
    model = Namespace(name=name, labels=labels)
    return model, key_for(model)


# ---------------------------------------------------------------- policy


def _selector(sel: Optional[Dict]) -> Optional[LabelSelector]:
    """K8s LabelSelector dict → model; None stays None (matches nothing)."""
    if sel is None:
        return None
    exprs = tuple(
        LabelExpression(
            key=e["key"],
            operator=ExpressionOperator(e["operator"]),
            values=tuple(e.get("values") or ()),
        )
        for e in sel.get("matchExpressions", [])
    )
    return LabelSelector(match_labels=sel.get("matchLabels") or {}, match_expressions=exprs)


def _peers(peers: List[Dict]) -> Tuple[Peer, ...]:
    out = []
    for p in peers:
        block = p.get("ipBlock")
        out.append(
            Peer(
                pods=_selector(p.get("podSelector")),
                namespaces=_selector(p.get("namespaceSelector")),
                ip_block=IPBlock(
                    cidr=block["cidr"], except_cidrs=tuple(block.get("except") or ())
                )
                if block
                else None,
            )
        )
    return tuple(out)


def _policy_ports(ports: List[Dict]) -> Tuple[PolicyPort, ...]:
    return tuple(
        PolicyPort(protocol=p.get("protocol", "TCP"), port=p.get("port"))
        for p in ports
    )


def policy_to_model(obj: Dict) -> Optional[Tuple[Policy, str]]:
    """policyToProto analog (policy_reflector.go): maps networking/v1
    NetworkPolicy including policyTypes defaulting."""
    name, namespace, labels = _meta(obj)
    if not name:
        return None
    spec = obj.get("spec", {})
    types = spec.get("policyTypes")
    if types is None:
        ptype = PolicyType.DEFAULT
    else:
        ingress, egress = "Ingress" in types, "Egress" in types
        if ingress and egress:
            ptype = PolicyType.INGRESS_AND_EGRESS
        elif egress:
            ptype = PolicyType.EGRESS
        elif ingress:
            ptype = PolicyType.INGRESS
        else:
            ptype = PolicyType.DEFAULT
    ingress_rules = tuple(
        IngressRule(ports=_policy_ports(r.get("ports", [])),
                    from_peers=_peers(r.get("from", [])))
        for r in spec.get("ingress", [])
    )
    egress_rules = tuple(
        EgressRule(ports=_policy_ports(r.get("ports", [])),
                   to_peers=_peers(r.get("to", [])))
        for r in spec.get("egress", [])
    )
    pod_sel = _selector(spec.get("podSelector")) or LabelSelector()
    model = Policy(
        name=name,
        namespace=namespace,
        labels=labels,
        pods=pod_sel,
        policy_type=ptype,
        ingress_rules=ingress_rules,
        egress_rules=egress_rules,
    )
    return model, key_for(model)


# --------------------------------------------------------------- service


def service_to_model(obj: Dict) -> Optional[Tuple[Service, str]]:
    name, namespace, _ = _meta(obj)
    if not name:
        return None
    spec = obj.get("spec", {})
    status = obj.get("status", {})
    ports = tuple(
        ServicePort(
            name=p.get("name", ""),
            protocol=p.get("protocol", "TCP"),
            port=p.get("port", 0),
            target_port=p.get("targetPort"),
            node_port=p.get("nodePort", 0),
        )
        for p in spec.get("ports", [])
    )
    affinity_cfg = (spec.get("sessionAffinityConfig") or {}).get("clientIP") or {}
    lb_ips = tuple(
        ing.get("ip", "")
        for ing in (status.get("loadBalancer") or {}).get("ingress", [])
        if ing.get("ip")
    )
    model = Service(
        name=name,
        namespace=namespace,
        ports=ports,
        selector=spec.get("selector") or {},
        cluster_ip=spec.get("clusterIP", ""),
        service_type=spec.get("type", "ClusterIP"),
        external_ips=tuple(spec.get("externalIPs") or ()),
        lb_ingress_ips=lb_ips,
        session_affinity=spec.get("sessionAffinity", "None"),
        session_affinity_timeout=affinity_cfg.get("timeoutSeconds", 0),
        external_traffic_policy=spec.get("externalTrafficPolicy", "Cluster"),
    )
    return model, key_for(model)


# ------------------------------------------------------------- endpoints


def _endpoint_addresses(addrs: List[Dict]) -> Tuple[EndpointAddress, ...]:
    out = []
    for a in addrs:
        ref = a.get("targetRef") or {}
        target = (
            PodID(name=ref.get("name", ""), namespace=ref.get("namespace", "default"))
            if ref.get("kind") == "Pod"
            else None
        )
        out.append(
            EndpointAddress(
                ip=a.get("ip", ""),
                node_name=a.get("nodeName", ""),
                host_name=a.get("hostname", ""),
                target_pod=target,
            )
        )
    return tuple(out)


def endpoints_to_model(obj: Dict) -> Optional[Tuple[Endpoints, str]]:
    name, namespace, _ = _meta(obj)
    if not name:
        return None
    subsets = []
    from ..models import EndpointSubset

    for s in obj.get("subsets", []):
        subsets.append(
            EndpointSubset(
                addresses=_endpoint_addresses(s.get("addresses", [])),
                not_ready_addresses=_endpoint_addresses(s.get("notReadyAddresses", [])),
                ports=tuple(
                    EndpointPort(
                        name=p.get("name", ""),
                        port=p.get("port", 0),
                        protocol=p.get("protocol", "TCP"),
                    )
                    for p in s.get("ports", [])
                ),
            )
        )
    model = Endpoints(name=name, namespace=namespace, subsets=tuple(subsets))
    return model, key_for(model)


# ------------------------------------------------------------------ node


def sfc_to_model(obj: Dict) -> Optional[Tuple["Sfc", str]]:
    """SFC pod filter (sfc_pod_reflector.go K8s2NodeFunc :56-73): only
    pods labeled ``sfc=true`` are reflected, as {pod, node} records."""
    from ..models import Sfc

    name, namespace, labels = _meta(obj)
    if not name or labels.get("sfc") != "true":
        return None
    model = Sfc(
        pod=name,
        node=obj.get("spec", {}).get("nodeName", ""),
        namespace=namespace,
    )
    return model, key_for(model)


def node_to_model(obj: Dict) -> Optional[Tuple[Node, str]]:
    name, _, labels = _meta(obj)
    if not name:
        return None
    status = obj.get("status", {})
    spec = obj.get("spec", {})
    addresses = tuple(
        NodeAddress(address=a.get("address", ""), type=a.get("type", ""))
        for a in status.get("addresses", [])
    )
    model = Node(
        name=name,
        addresses=addresses,
        pod_cidr=spec.get("podCIDR", ""),
        labels=labels,
    )
    return model, key_for(model)


# --------------------------------------------------------------- factory

# K8s resource kind → (registry keyword, converter).
CONVERTERS = {
    "pods": ("pod", pod_to_model),
    "namespaces": ("namespace", namespace_to_model),
    "networkpolicies": ("policy", policy_to_model),
    "services": ("service", service_to_model),
    "endpoints": ("endpoints", endpoints_to_model),
    "nodes": ("node", node_to_model),
    # Derived reflector: watches pods, reflects only those labeled
    # sfc=true under the sfc/ prefix (sfc_pod_reflector.go).
    "sfc-pods": ("sfc", sfc_to_model),
}

# Reflectors whose watched K8s kind differs from their registry keyword.
WATCH_KINDS = {"sfc-pods": "pods"}
FILTERED = {"sfc-pods"}


def make_reflectors(
    list_watch: K8sListWatch,
    broker: Broker,
    min_resync_timeout: float = 0.1,
    max_resync_timeout: float = 1.0,
) -> Dict[str, Reflector]:
    """One reflector per reflected resource (the reflector set wired by
    plugin_impl_ksr.go Init)."""
    out: Dict[str, Reflector] = {}
    for kind, (keyword, converter) in CONVERTERS.items():
        out[kind] = Reflector(
            kind=kind,
            prefix=resource(keyword).key_prefix,
            converter=converter,
            list_watch=list_watch,
            broker=broker,
            min_resync_timeout=min_resync_timeout,
            max_resync_timeout=max_resync_timeout,
            watch_kind=WATCH_KINDS.get(kind),
            filtered=kind in FILTERED,
        )
    return out
