"""ListWatch interface towards the K8s API server.

Analog of the reference's ``K8sListWatcher`` abstraction
(plugins/ksr/ksr_api.go + client-go informers): a reflector needs (a) a
consistent initial listing of a resource kind and (b) a stream of
add/update/delete notifications.  Production backends implement this
over the real API server; tests use ``vpp_tpu.testing.k8s.FakeK8sCluster``
(the analog of the reference's ``mockK8sListWatch`` used by every
``*_reflector_test.go``).

Objects crossing this interface are K8s-JSON-shaped dicts
(``metadata``/``spec``/``status``), exactly what the API server returns;
the per-resource converters in ``reflectors.py`` parse them into typed
models.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Protocol

# handler(event, obj, old_obj): event is "add" | "update" | "delete".
ListWatchHandler = Callable[[str, Dict, Dict], None]


class K8sListWatch(Protocol):
    """What a reflector needs from the K8s API."""

    def list(self, kind: str) -> List[Dict]:
        """Consistent snapshot of all objects of ``kind``."""
        ...

    def subscribe(self, kind: str, handler: ListWatchHandler) -> None:
        """Register for change notifications of ``kind``."""
        ...

    def unsubscribe(self, kind: str, handler: ListWatchHandler) -> None:
        """Deregister a previously subscribed handler."""
        ...
