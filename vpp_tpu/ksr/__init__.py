"""KSR — Kubernetes State Reflector.

Analog of the reference's ``plugins/ksr``: a generic Reflector framework
over a K8s ListWatch that converts API objects into typed models and
reflects them into the cluster KV store under the registry prefixes
(SURVEY.md §2.2).
"""

from .listwatch import K8sListWatch, ListWatchHandler
from .reflector import Broker, KsrStats, KVBroker, Reflector
from .reflectors import CONVERTERS, make_reflectors
from .registry import ReflectorRegistry
from .plugin import KSRPlugin

__all__ = [
    "Broker",
    "CONVERTERS",
    "K8sListWatch",
    "KSRPlugin",
    "KVBroker",
    "KsrStats",
    "ListWatchHandler",
    "Reflector",
    "ReflectorRegistry",
    "make_reflectors",
]
