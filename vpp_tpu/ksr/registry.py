"""Reflector registry — lifecycle + data-store connectivity fan-out.

Analog of ``plugins/ksr/reflector_registry.go``: start all reflectors,
broadcast data-store down/up events (down = hold updates + abort any
in-progress reconciliation; up = start reconciliation), and aggregate
stats / sync status.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .reflector import KsrStats, Reflector


class ReflectorRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._reflectors: Dict[str, Reflector] = {}

    def add(self, reflector: Reflector) -> None:
        with self._lock:
            if reflector.kind in self._reflectors:
                raise ValueError(f"duplicate reflector for {reflector.kind}")
            self._reflectors[reflector.kind] = reflector

    def get(self, kind: str) -> Optional[Reflector]:
        with self._lock:
            return self._reflectors.get(kind)

    @property
    def kinds(self):
        with self._lock:
            return sorted(self._reflectors)

    def start_reflectors(self) -> None:
        with self._lock:
            reflectors = list(self._reflectors.values())
        for r in reflectors:
            r.start()

    def close(self) -> None:
        with self._lock:
            reflectors = list(self._reflectors.values())
        for r in reflectors:
            r.close()

    def data_store_down_event(self) -> None:
        """Hold back updates and abort reconciliations (dataStoreDownEvent)."""
        with self._lock:
            reflectors = list(self._reflectors.values())
        for r in reflectors:
            r.stop_data_store_updates()
            r.abort_resync()

    def data_store_up_event(self) -> None:
        """Data store is back: reconcile every reflector (dataStoreUpEvent)."""
        with self._lock:
            reflectors = list(self._reflectors.values())
        for r in reflectors:
            r.start_data_store_resync()

    def ksr_has_synced(self) -> bool:
        with self._lock:
            return all(r.has_synced for r in self._reflectors.values())

    def get_stats(self) -> Dict[str, KsrStats]:
        with self._lock:
            return {kind: r.stats for kind, r in self._reflectors.items()}
