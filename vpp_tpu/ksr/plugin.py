"""KSR plugin — wires reflectors and monitors data-store connectivity.

Analog of ``plugins/ksr/plugin_impl_ksr.go``: builds the reflector set
against a ListWatch + broker, starts them, and runs the periodic
data-store connectivity monitor (:255-311 — the etcd monitor that fires
``dataStoreDownEvent``/``dataStoreUpEvent`` on transitions).
"""

from __future__ import annotations

import threading
from typing import Optional

from .listwatch import K8sListWatch
from .reflector import Broker
from .reflectors import make_reflectors
from .registry import ReflectorRegistry


class KSRPlugin:
    def __init__(
        self,
        list_watch: K8sListWatch,
        broker: Broker,
        probe_interval: float = 1.0,
        min_resync_timeout: float = 0.1,
        max_resync_timeout: float = 1.0,
    ):
        self.broker = broker
        self.probe_interval = probe_interval
        self.registry = ReflectorRegistry()
        for reflector in make_reflectors(
            list_watch, broker, min_resync_timeout, max_resync_timeout
        ).values():
            self.registry.add(reflector)
        self._store_up = True
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle

    def init(self, start_monitor: bool = True) -> None:
        self.registry.start_reflectors()
        if start_monitor:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="ksr-store-monitor", daemon=True
            )
            self._monitor.start()

    def close(self) -> None:
        self._stop.set()
        self.registry.close()

    def has_synced(self) -> bool:
        return self.registry.ksr_has_synced()

    def get_stats(self):
        return {k: s.as_dict() for k, s in self.registry.get_stats().items()}

    # ------------------------------------------------------------ monitoring

    def check_data_store(self) -> bool:
        """One probe + transition handling; returns current up/down state."""
        try:
            up = self.broker.probe()
        except Exception:
            up = False
        if up and not self._store_up:
            self._store_up = True
            self.registry.data_store_up_event()
        elif not up and self._store_up:
            self._store_up = False
            self.registry.data_store_down_event()
        return up

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            self.check_data_store()
