"""Real Kubernetes API list/watch — dependency-free HTTP client.

The reference's KSR consumes the K8s API through client-go informers
(cmd/contiv-ksr, plugin_impl_ksr.go); this module implements the same
``K8sListWatch`` contract (``list``/``subscribe``/``unsubscribe`` —
see :mod:`vpp_tpu.ksr.listwatch`) directly over the K8s REST API with
the standard library: LIST via a plain GET, WATCH via the chunked
``?watch=true`` stream of JSON lines, resuming from the last seen
``resourceVersion`` with exponential backoff (410 Gone restarts from a
fresh LIST, exactly like an informer's relist).

In-cluster config is the conventional ServiceAccount mount:
token + CA under /var/run/secrets/kubernetes.io/serviceaccount, API
host from KUBERNETES_SERVICE_HOST/PORT.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import urllib.request
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)

# KSR kind -> (API path prefix, resource). Core group under /api/v1,
# networking group under /apis.
_KIND_PATHS: Dict[str, str] = {
    "pods": "/api/v1/pods",
    "namespaces": "/api/v1/namespaces",
    "services": "/api/v1/services",
    "endpoints": "/api/v1/endpoints",
    "nodes": "/api/v1/nodes",
    "networkpolicies": "/apis/networking.k8s.io/v1/networkpolicies",
}

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_base_url() -> str:
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    return f"https://{host}:{port}"


class K8sApiListWatch:
    """ListWatch over the real K8s API (drop-in for FakeK8sCluster)."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
    ):
        self.base_url = (base_url or in_cluster_base_url()).rstrip("/")
        if token is None and os.path.exists(os.path.join(_SA_DIR, "token")):
            with open(os.path.join(_SA_DIR, "token")) as fh:
                token = fh.read().strip()
        if ca_file is None and os.path.exists(os.path.join(_SA_DIR, "ca.crt")):
            ca_file = os.path.join(_SA_DIR, "ca.crt")
        self.token = token
        if insecure:
            self._ctx = ssl._create_unverified_context()  # noqa: S323 - explicit opt-in
        elif ca_file:
            self._ctx = ssl.create_default_context(cafile=ca_file)
        else:
            self._ctx = ssl.create_default_context()
        self._handlers: Dict[str, List[Callable]] = {}
        self._threads: Dict[str, threading.Thread] = {}
        # Per-kind (namespace, name) -> last seen object, so update and
        # delete notifications can carry old_obj like the contract
        # (and informers) do.
        self._cache: Dict[str, Dict[tuple, Dict]] = {}
        self._stop = threading.Event()

    # ---------------------------------------------------------------- http

    def _request(self, path: str, timeout: Optional[float] = 10.0):
        req = urllib.request.Request(self.base_url + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        ctx = self._ctx if self.base_url.startswith("https") else None
        return urllib.request.urlopen(req, timeout=timeout, context=ctx)  # noqa: S310

    # ------------------------------------------------------------ contract

    def list(self, kind: str) -> List[Dict]:
        path = _KIND_PATHS[kind]
        with self._request(path) as resp:
            body = json.load(resp)
        return body.get("items", [])

    def subscribe(self, kind: str, handler: Callable) -> None:
        self._handlers.setdefault(kind, []).append(handler)
        if kind not in self._threads:
            t = threading.Thread(
                target=self._watch_loop, args=(kind,),
                name=f"k8s-watch-{kind}", daemon=True,
            )
            self._threads[kind] = t
            t.start()

    def unsubscribe(self, kind: str, handler: Callable) -> None:
        if handler in self._handlers.get(kind, []):
            self._handlers[kind].remove(handler)

    def close(self) -> None:
        self._stop.set()

    # --------------------------------------------------------------- watch

    def _watch_loop(self, kind: str) -> None:
        path = _KIND_PATHS[kind]
        backoff = 0.2
        rv = ""
        while not self._stop.is_set():
            try:
                if not rv:
                    # (Re)list to obtain a consistent resourceVersion to
                    # watch from; reflector resyncs absorb the gap.
                    with self._request(path) as resp:
                        body = json.load(resp)
                    rv = body.get("metadata", {}).get("resourceVersion", "0")
                # Server ends the watch after timeoutSeconds (we then
                # re-subscribe from the last RV); the slightly larger
                # socket read timeout bounds half-open connections the
                # server's close can never reach.
                url = (f"{path}?watch=true&resourceVersion={rv}"
                       f"&allowWatchBookmarks=true&timeoutSeconds=300")
                with self._request(url, timeout=330.0) as stream:
                    backoff = 0.2
                    for line in stream:
                        if self._stop.is_set():
                            return
                        if not line.strip():
                            continue
                        event = json.loads(line)
                        etype = event.get("type", "")
                        obj = event.get("object", {})
                        new_rv = obj.get("metadata", {}).get("resourceVersion")
                        if new_rv:
                            rv = new_rv
                        if etype == "BOOKMARK":
                            continue
                        if etype == "ERROR":
                            # 410 Gone: the RV expired — relist.
                            rv = ""
                            break
                        if etype in ("ADDED", "MODIFIED", "DELETED"):
                            self._dispatch(kind, etype, obj)
            except Exception as e:  # noqa: BLE001 - reconnect with backoff
                log.warning("k8s watch %s: %s (retrying in %.1fs)", kind, e, backoff)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 10.0)
                rv = ""

    def _dispatch(self, kind: str, etype: str, obj: Dict) -> None:
        meta = obj.get("metadata", {})
        key = (meta.get("namespace", ""), meta.get("name", ""))
        cache = self._cache.setdefault(kind, {})
        if etype == "DELETED":
            old = cache.pop(key, obj)
            event, new_obj, old_obj = "delete", old, old
        else:
            old = cache.get(key)
            cache[key] = obj
            event = "update" if old is not None else "add"
            new_obj, old_obj = obj, old
        for handler in list(self._handlers.get(kind, [])):
            try:
                handler(event, new_obj, old_obj)
            except Exception:  # noqa: BLE001
                log.exception("k8s watch handler for %s failed", kind)
