"""KSR daemon — the contiv-ksr container analog.

Reflects the K8s API (pods, namespaces, policies, services, endpoints,
nodes, SFC pods) into the cluster store, exactly the role of
cmd/contiv-ksr in the reference (k8s/contiv-vpp.yaml contiv-ksr
Deployment on the master):

    python -m vpp_tpu.ksr --store 127.0.0.1:12379 \\
        [--k8s-api https://10.96.0.1:443 | --in-cluster]

The K8s side uses the dependency-free list/watch client
(:mod:`.k8s_api`); ``--in-cluster`` reads the conventional
ServiceAccount mount.  Reflector stats are printed once per minute
(ksr_reflector.go stats logging analog).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="vpp-tpu KSR (K8s state reflector)")
    parser.add_argument("--store", required=True, help="host:port of the cluster store")
    parser.add_argument("--k8s-api", default="", help="K8s API base URL")
    parser.add_argument("--in-cluster", action="store_true",
                        help="use the in-cluster ServiceAccount config")
    parser.add_argument("--token", default="", help="bearer token (overrides SA mount)")
    parser.add_argument("--ca-file", default="", help="API server CA bundle")
    parser.add_argument("--insecure", action="store_true",
                        help="skip TLS verification (dev only)")
    args = parser.parse_args(argv)

    import logging

    logging.basicConfig(level=logging.INFO)

    from ..kvstore.remote import RemoteKVStore
    from . import KSRPlugin, KVBroker
    from .k8s_api import K8sApiListWatch

    store = RemoteKVStore(args.store)
    from .k8s_api import in_cluster_base_url

    base_url = in_cluster_base_url() if args.in_cluster else (args.k8s_api or None)
    list_watch = K8sApiListWatch(
        base_url=base_url,
        token=args.token or None,
        ca_file=args.ca_file or None,
        insecure=args.insecure,
    )
    ksr = KSRPlugin(list_watch, KVBroker(store))
    ksr.init()
    print(json.dumps({"ksr": "running", "store": args.store,
                      "k8s_api": list_watch.base_url}), flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    while not stop.wait(60.0):
        print(json.dumps({"ksr_stats": ksr.get_stats()}), flush=True)
    ksr.close()
    list_watch.close()
    store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
