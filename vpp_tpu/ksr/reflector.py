"""Generic KSR reflector: K8s cache → data store mark-and-sweep.

Analog of ``plugins/ksr/ksr_reflector.go``:

- change handlers gated on the data-store-synced flag (:408-435);
- equal-value updates skipped (``ksrUpdate`` :342);
- any data-store write error flips the synced flag and kicks off a
  background reconciliation (``ksrAdd``/``ksrUpdate``/``ksrDelete``
  :325-373);
- reconciliation = **mark-and-sweep** between the K8s cache and a data
  store snapshot (``markAndSweep`` :184-227), retried with exponential
  backoff between ``min_resync_timeout`` and ``max_resync_timeout``
  (``dataStoreResyncWait`` :253-275, 100→1000 ms in the reference);
- per-reflector stats gauges (ksrapi ``KsrStats``).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from ..kvstore import KVStore
from .listwatch import K8sListWatch

log = logging.getLogger(__name__)


@dataclass
class KsrStats:
    """Per-reflector usage gauges (plugins/ksr/model/ksrapi)."""

    adds: int = 0
    updates: int = 0
    deletes: int = 0
    add_errors: int = 0
    upd_errors: int = 0
    del_errors: int = 0
    arg_errors: int = 0
    resyncs: int = 0
    res_errors: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dict(self.__dict__)


class BrokerDown(Exception):
    """The data store rejected an operation (etcd-down analog)."""


class Broker(Protocol):
    """Key-value access for one reflector (KeyProtoValBroker analog)."""

    def put(self, key: str, value: object) -> None: ...

    def delete(self, key: str) -> None: ...

    def list_values(self, prefix: str) -> List[Tuple[str, object]]: ...

    def probe(self) -> bool:
        """Cheap connectivity check (plugin_impl_ksr.go etcd monitor)."""
        ...


class KVBroker:
    """Broker over the in-process :class:`KVStore`."""

    def __init__(self, store: KVStore):
        self.store = store

    def put(self, key: str, value: object) -> None:
        self.store.put(key, value)

    def delete(self, key: str) -> None:
        self.store.delete(key)

    def list_values(self, prefix: str) -> List[Tuple[str, object]]:
        return self.store.list(prefix)

    def probe(self) -> bool:
        return True


# converter(k8s_obj_dict) -> (model, full_key) or None on a malformed
# object (K8sToProtoConverter analog).
Converter = Callable[[Dict], Optional[Tuple[object, str]]]


class Reflector:
    """Reflects one K8s resource kind into the data store."""

    def __init__(
        self,
        kind: str,
        prefix: str,
        converter: Converter,
        list_watch: K8sListWatch,
        broker: Broker,
        min_resync_timeout: float = 0.1,
        max_resync_timeout: float = 1.0,
        watch_kind: Optional[str] = None,
        filtered: bool = False,
    ):
        self.kind = kind
        # The K8s kind actually listed/watched; differs for derived
        # reflectors like SFC (watches "pods", writes under sfc/ —
        # reference sfc_pod_reflector.go).
        self.watch_kind = watch_kind or kind
        # A filtered reflector's converter returning None means "object
        # not selected", not "malformed" (the SFC label filter).
        self.filtered = filtered
        self.prefix = prefix
        self.converter = converter
        self.list_watch = list_watch
        self.broker = broker
        self.min_resync_timeout = min_resync_timeout
        self.max_resync_timeout = max_resync_timeout

        self.stats = KsrStats()
        self._lock = threading.RLock()
        self._k8s_cache: Dict[str, object] = {}  # key -> model
        self._k8s_synced = False
        self._ds_synced = False
        self._closed = False
        self._abort = threading.Event()
        self._resync_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Subscribe for changes, list the resource into the K8s cache and
        reconcile the data store (ksrInit + Start + startDataStoreResync).

        Subscribe happens BEFORE the initial listing so an object created
        in between is not lost (the same watch-before-snapshot order the
        controller's dbwatcher uses); early change events simply land in
        the cache (``_ds_synced`` is still False) and the reconciliation
        absorbs duplicates."""
        self.list_watch.subscribe(self.watch_kind, self._on_change)
        with self._lock:
            for obj in self.list_watch.list(self.watch_kind):
                conv = self._convert(obj)
                if conv is not None:
                    model, key = conv
                    self._k8s_cache.setdefault(key, model)
            self._k8s_synced = True
        if not self._try_sync_once():
            self.start_data_store_resync()

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._abort.set()
        unsubscribe = getattr(self.list_watch, "unsubscribe", None)
        if unsubscribe is not None:
            unsubscribe(self.watch_kind, self._on_change)

    @property
    def has_synced(self) -> bool:
        with self._lock:
            return self._ds_synced

    # ------------------------------------------------------- change handling

    def _convert(self, obj: Dict) -> Optional[Tuple[object, str]]:
        try:
            conv = self.converter(obj)
        except Exception:
            conv = None
        if conv is None and not self.filtered:
            self.stats.arg_errors += 1
            log.warning("%s reflector: malformed object dropped", self.kind)
        return conv

    def _on_change(self, event: str, obj: Dict, old_obj: Optional[Dict]) -> None:
        with self._lock:
            if self._closed:
                return
            conv = self._convert(obj)
            if conv is None:
                if self.filtered and event == "update" and old_obj is not None:
                    # Selected before, deselected now (e.g. the sfc=true
                    # label removed): treat as a delete of the old key
                    # (reference sfc_pod_reflector.go updatePod).
                    old_conv = self._convert(old_obj)
                    if old_conv is not None:
                        _, old_key = old_conv
                        self._k8s_cache.pop(old_key, None)
                        if self._ds_synced:
                            try:
                                self.broker.delete(old_key)
                                self.stats.deletes += 1
                            except Exception:
                                self.stats.del_errors += 1
                                self._ds_synced = False
                                self.start_data_store_resync()
                return
            model, key = conv
            if event == "delete":
                self._k8s_cache.pop(key, None)
            else:
                self._k8s_cache[key] = model
            if not self._ds_synced:
                # Updates are held back while out of sync; the ongoing
                # mark-and-sweep will pick the cache change up (:408-435).
                return
            try:
                if event == "add":
                    self.broker.put(key, model)
                    self.stats.adds += 1
                elif event == "update":
                    old_conv = self._convert(old_obj) if old_obj else None
                    if old_conv is not None and old_conv[0] == model:
                        return  # no-op update (ksrUpdate proto.Equal check)
                    self.broker.put(key, model)
                    self.stats.updates += 1
                elif event == "delete":
                    self.broker.delete(key)
                    self.stats.deletes += 1
            except Exception:
                if event == "add":
                    self.stats.add_errors += 1
                elif event == "update":
                    self.stats.upd_errors += 1
                else:
                    self.stats.del_errors += 1
                log.warning("%s reflector: data-store %s failed; resyncing",
                            self.kind, event)
                self._ds_synced = False
                self.start_data_store_resync()

    # ----------------------------------------------------------- resync path

    def stop_data_store_updates(self) -> None:
        """Data store reported down: hold back updates (stopDataStoreUpdates)."""
        with self._lock:
            self._ds_synced = False

    def _mark_and_sweep(self, ds_items: Dict[str, object]) -> None:
        """Reconcile the data store with the K8s cache (markAndSweep
        :184-227).  Raises on the first failed write."""
        for key, model in list(self._k8s_cache.items()):
            if key in ds_items:
                if ds_items[key] != model:
                    try:
                        self.broker.put(key, model)
                    except Exception:
                        self.stats.upd_errors += 1
                        raise
                    self.stats.updates += 1
                del ds_items[key]
            else:
                try:
                    self.broker.put(key, model)
                except Exception:
                    self.stats.add_errors += 1
                    raise
                self.stats.adds += 1
        for key in list(ds_items):
            try:
                self.broker.delete(key)
            except Exception:
                self.stats.del_errors += 1
                raise
            self.stats.deletes += 1
            del ds_items[key]

    def _try_sync_once(self) -> bool:
        """One full reconciliation attempt (syncDataStoreWithK8sCache)."""
        try:
            ds_items = dict(self.broker.list_values(self.prefix))
        except Exception:
            self.stats.res_errors += 1
            return False
        with self._lock:
            self.stats.resyncs += 1
            if not self._k8s_synced:
                self.stats.res_errors += 1
                return False
            try:
                self._mark_and_sweep(ds_items)
            except Exception:
                self.stats.res_errors += 1
                return False
            self._ds_synced = True
            return True

    def start_data_store_resync(self) -> None:
        """Reconcile in the background until it succeeds or is aborted
        (startDataStoreResync :279-323), with exponential backoff.

        Always supersedes any previous reconciliation: the old loop's
        abort event is set and a fresh loop (with its own abort event)
        started — so a down→up flap that aborts a loop mid-attempt cannot
        leave the reflector permanently unsynced."""
        with self._lock:
            if self._closed:
                return
            self._abort.set()  # retire any previous loop
            self._abort = threading.Event()
            abort = self._abort
            self._resync_thread = threading.Thread(
                target=self._resync_loop, args=(abort,),
                name=f"ksr-resync-{self.kind}", daemon=True,
            )
            self._resync_thread.start()

    def abort_resync(self) -> None:
        """Abort an in-progress reconciliation (dataStoreDownEvent path)."""
        self._abort.set()

    def _resync_loop(self, abort: threading.Event) -> None:
        timeout = self.min_resync_timeout
        while not abort.is_set():
            if self._try_sync_once():
                log.info("%s reflector: data sync done, stats %s",
                         self.kind, self.stats.as_dict())
                return
            if abort.wait(timeout):
                return
            timeout = min(timeout * 2, self.max_resync_timeout)
