"""DB watcher — converts KV-store activity into controller events.

Analog of ``plugins/controller/dbwatcher.go``: on start it takes one
consistent snapshot of every registered resource prefix (plus the
external-config prefix) and pushes a DBResync (runResyncFromRemoteDB
:334 / LoadKubeStateForResync :553); afterwards every watched change
becomes a KubeStateChange / ExternalConfigChange event (processChange
:404).  ``resync()`` re-snapshots on demand — the hook used by healing
resyncs and by the REST resync trigger.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..kvstore import KVStore, WatchEvent
from ..kvstore.mirror import LocalMirror
from ..models import registry

# Errors meaning "the remote store is unreachable" (fall back to the
# local mirror).  Anything else — codec bugs, malformed responses,
# server-side INTERNAL errors — must propagate, not masquerade as an
# outage, so RpcErrors are filtered by status code in
# ``is_store_unavailable`` rather than caught wholesale.
try:
    import grpc as _grpc

    from ..kvstore.remote import OUTAGE_CODES as _UNAVAILABLE_CODES

    STORE_UNAVAILABLE_ERRORS: tuple = (ConnectionError, _grpc.RpcError)
except ImportError:  # pragma: no cover - grpc is in the base image
    _grpc = None
    STORE_UNAVAILABLE_ERRORS = (ConnectionError,)
    _UNAVAILABLE_CODES = frozenset()


def is_store_unavailable(exc: Exception) -> bool:
    """True only for transport-level outages; server-side errors
    (INTERNAL, INVALID_ARGUMENT, ...) are real bugs and must propagate."""
    if isinstance(exc, ConnectionError):
        return True
    if _grpc is not None and isinstance(exc, _grpc.RpcError):
        code_fn = getattr(exc, "code", None)
        return code_fn is not None and code_fn() in _UNAVAILABLE_CODES
    return False
from .api import DBResync, ExternalConfigChange, KubeStateChange
from .eventloop import Controller

log = logging.getLogger(__name__)

EXTERNAL_CONFIG_PREFIX = "/vpp-tpu/external-config/"


class DBWatcher:
    """Watches the cluster KV store and feeds the event loop."""

    def __init__(
        self,
        controller: Controller,
        store: KVStore,
        mirror_path: Optional[str] = None,
    ):
        self.controller = controller
        self.store = store
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._prefixes = [r.key_prefix for r in registry.DB_RESOURCES] + [EXTERNAL_CONFIG_PREFIX]
        # Local sqlite mirror (the Bolt analog, dbwatcher.go:111-137):
        # updated on every snapshot/change, used as resync fallback while
        # the remote store is unreachable.
        self._mirror = LocalMirror(mirror_path) if mirror_path else None
        self._watcher = self.store.watch(self._prefixes)
        # A networked store signals watch-stream recovery: resync from the
        # remote DB on every reconnect (dbwatcher.go:252-267).
        if hasattr(self.store, "on_reconnect"):
            self.store.on_reconnect(self.resync)
        # Serializes resync() against the watch thread's event pushes, so a
        # DBResync snapshot can never be overtaken by a change event that it
        # does not contain (and stale pre-snapshot events are dropped by
        # revision).
        self._order_lock = threading.Lock()
        self._resync_revision = -1
        self.resynced_from_mirror = 0  # observability for tests/telemetry

    # ------------------------------------------------------------------ life

    def start(self) -> None:
        """Push the startup DBResync, then stream changes.

        The watch is registered before the snapshot is taken (in
        __init__/here respectively), so no change can fall between
        snapshot and stream; duplicates are resolved by the snapshot
        being authoritative at resync time.  For a networked store the
        registration is asynchronous — wait for the server's
        subscribe-ack before snapshotting, or the guarantee breaks.
        """
        if hasattr(self._watcher, "wait_subscribed"):
            self._watcher.wait_subscribed(timeout=5.0)
        self.resync()
        self._thread = threading.Thread(target=self._watch_loop, name="db-watcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.store.unwatch(self._watcher)
        if self._thread:
            self._thread.join(timeout=5)

    # ---------------------------------------------------------------- resync

    def resync(self) -> DBResync:
        """Take one consistent snapshot of all resources and push a
        DBResync event.

        Holding ``_order_lock`` across snapshot+push guarantees that no
        watch event can slip into the controller queue between them;
        events committed before the snapshot revision are dropped by the
        watch loop afterwards (they are already inside the snapshot).
        """
        with self._order_lock:
            try:
                snap, revision = self.store.snapshot_with_revision(self._prefixes)
            except STORE_UNAVAILABLE_ERRORS as e:
                if not is_store_unavailable(e):
                    raise
                return self._resync_from_mirror(e)
            self._resync_revision = revision
            if self._mirror is not None:
                self._mirror.save_snapshot(snap, revision)
            event = self._push_resync(snap, revision)
        return event

    def _push_resync(self, snap, revision: int = 0) -> DBResync:
        kube_state = {r.keyword: {} for r in registry.DB_RESOURCES}
        external = {}
        for key, value in snap.items():
            if key.startswith(EXTERNAL_CONFIG_PREFIX):
                external[key] = value
                continue
            resource = registry.resource_for_key(key)
            if resource is not None:
                kube_state[resource.keyword][key] = value
        event = DBResync(kube_state=kube_state, external_config=external,
                         revision=revision)
        self.controller.push_event(event)
        return event

    def _resync_from_mirror(self, cause: Exception) -> Optional[DBResync]:
        """Local fallback resync (runResyncFromLocalDB :309): serve the
        last mirrored snapshot; the reconnect hook re-resyncs from the
        remote DB once it is reachable again."""
        loaded = self._mirror.load() if self._mirror is not None else None
        if loaded is None:
            log.warning("remote store unreachable and no local mirror: %s", cause)
            return None
        snap, revision = loaded
        log.warning(
            "remote store unreachable (%s): resyncing from local mirror "
            "(%d keys @ revision %d)", cause, len(snap), revision,
        )
        self._resync_revision = revision
        self.resynced_from_mirror += 1
        return self._push_resync(snap, revision)

    # ----------------------------------------------------------------- watch

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            ev = self._watcher.get(timeout=0.1)
            if ev is None:
                continue
            self._process_change(ev)

    def _process_change(self, ev: WatchEvent) -> None:
        with self._order_lock:
            if ev.revision <= self._resync_revision:
                # Already covered by the last resync snapshot.
                return
            if self._mirror is not None:
                self._mirror.apply_event(ev)
            self._push_change(ev)

    def _push_change(self, ev: WatchEvent) -> None:
        # The watch event's revision rides the controller event into its
        # propagation span (ISSUE 10): one store write lands with the
        # SAME revision on every agent, which is what lets the cluster
        # aggregator stitch all nodes' spans for that write together.
        if ev.key.startswith(EXTERNAL_CONFIG_PREFIX):
            self.controller.push_event(
                ExternalConfigChange(source="db", changes={ev.key: ev.value},
                                     revision=ev.revision)
            )
            return
        resource = registry.resource_for_key(ev.key)
        if resource is None:
            log.warning("change under unknown prefix: %s", ev.key)
            return
        self.controller.push_event(
            KubeStateChange(
                resource=resource.keyword,
                key=ev.key,
                prev_value=ev.prev_value,
                new_value=ev.value,
                revision=ev.revision,
            )
        )
