"""Event model of the controller.

Analog of the reference's ``plugins/controller/api`` package:
event_loop.go (Event, UpdateEvent, EventHandler, method/direction/txn-type
enums), db.go (DBResync, KubeStateChange, ExternalConfigChange),
healing.go (HealingResync), shutdown.go (Shutdown) and error.go
(FatalError, AbortEventError).
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Dict, Optional

# KubeStateData: resource keyword -> {full key -> model instance}
# (analog of api/db.go KubeStateData).
KubeStateData = Dict[str, Dict[str, Any]]


class EventMethod(enum.Enum):
    """How an event must be reacted to (api/event_loop.go EventMethodType)."""

    # Full re-synchronization: control plane -> scheduler <-> data plane.
    FULL_RESYNC = "full-resync"
    # Re-sync between the scheduler and the data plane only; handlers are
    # not involved.
    DOWNSTREAM_RESYNC = "downstream-resync"
    # Re-sync between the control plane and the scheduler (data plane state
    # assumed to be in sync).
    UPSTREAM_RESYNC = "upstream-resync"
    # Incremental change.
    UPDATE = "update"

    @property
    def is_resync(self) -> bool:
        return self is not EventMethod.UPDATE


class UpdateDirection(enum.Enum):
    """Handler iteration order for update events."""

    # Handlers run in registration order (dependencies first).
    FORWARD = "forward"
    # Handlers run in reverse order (dependencies still pre-event).
    REVERSE = "reverse"


class UpdateTxnType(enum.Enum):
    """How to treat partial work of a failed update event."""

    # Keep whatever succeeded (stay as close to desired state as possible).
    BEST_EFFORT = "best-effort"
    # Stop on first error and revert already executed changes.
    REVERT_ON_FAILURE = "revert-on-failure"


class FatalError(Exception):
    """Error after which the agent must restart (api/error.go)."""


class AbortEventError(Exception):
    """Abort event processing without reverting (api/error.go)."""


class Event:
    """Base class of everything flowing through the event loop.

    Subclasses override ``method`` and, for blocking events, construct with
    ``blocking=True`` so producers can ``wait()`` for the processing result.
    """

    name = "Event"

    def __init__(self, blocking: bool = False):
        self._blocking = blocking
        self._done = threading.Event()
        self._error: Optional[Exception] = None

    # -- contract ----------------------------------------------------------

    @property
    def method(self) -> EventMethod:
        return EventMethod.UPDATE

    @property
    def is_blocking(self) -> bool:
        return self._blocking

    def done(self, error: Optional[Exception]) -> None:
        """Mark the event as processed, delivering the result to waiters."""
        self._error = error
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[Exception]:
        """Block until the event has been processed; returns its error."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"event {self.name} not processed in time")
        return self._error

    def __str__(self) -> str:
        return self.name


class UpdateEvent(Event):
    """An event that can be reacted to by an incremental change."""

    @property
    def method(self) -> EventMethod:
        return EventMethod.UPDATE

    @property
    def direction(self) -> UpdateDirection:
        return UpdateDirection.FORWARD

    @property
    def transaction_type(self) -> UpdateTxnType:
        return UpdateTxnType.BEST_EFFORT


class EventHandler:
    """A plugin reacting to events (api/event_loop.go EventHandler).

    Handlers are registered with the Controller in dependency order; for
    every handler processing a Forward event, all its dependencies have
    already reacted to it.
    """

    name = "handler"

    def handles_event(self, event: Event) -> bool:
        return True

    def resync(self, event: Event, kube_state: KubeStateData, resync_count: int, txn) -> None:
        """Handle a full-resync event. ``resync_count`` is 1 for the startup
        resync, higher for run-time resyncs."""

    def update(self, event: Event, txn) -> str:
        """Handle an incremental event; returns a human-readable description
        of the changes performed (may be empty)."""
        return ""

    def revert(self, event: Event) -> None:
        """Revert internal (plugin-state) changes done for a failed
        RevertOnFailure event."""

    def __str__(self) -> str:
        return self.name


# --------------------------------------------------------------------------
# Concrete events
# --------------------------------------------------------------------------


class DBResync(Event):
    """Carries a snapshot of the DB for all watched resources plus external
    config (api/db.go DBResync)."""

    name = "Database Resync"

    def __init__(self, kube_state: Optional[KubeStateData] = None,
                 external_config: Optional[Dict[str, Any]] = None,
                 local: bool = False, revision: int = 0):
        super().__init__()
        self.kube_state: KubeStateData = kube_state if kube_state is not None else {}
        self.external_config: Dict[str, Any] = external_config or {}
        self.local = local
        # Store revision the snapshot corresponds to (ISSUE 10): the
        # cluster-wide anchor that lets one node's propagation span be
        # stitched against every other node's — replicas serve
        # bit-identical revisions (PR 1), so equal revision means "the
        # same cluster state write" on every agent.
        self.revision = revision

    @property
    def method(self) -> EventMethod:
        return EventMethod.FULL_RESYNC

    def __str__(self) -> str:
        where = "Local DB" if self.local else "Remote DB"
        counts = {k: len(v) for k, v in self.kube_state.items() if v}
        return f"{self.name} ({where}) {counts}"


class KubeStateChange(UpdateEvent):
    """One changed value of a watched resource (api/db.go KubeStateChange)."""

    name = "Kubernetes State Change"

    def __init__(self, resource: str, key: str, prev_value: Any,
                 new_value: Any, revision: int = 0):
        super().__init__()
        self.resource = resource
        self.key = key
        self.prev_value = prev_value
        self.new_value = new_value
        # The store revision that carried this change (ISSUE 10): the
        # watch event's revision, identical on every agent that saw the
        # same write — the cross-node span stitch key.
        self.revision = revision

    def __str__(self) -> str:
        op = "update"
        if self.prev_value is None:
            op = "add"
        elif self.new_value is None:
            op = "delete"
        return f"{self.name} [{op} {self.resource}: {self.key}]"


class ExternalConfigChange(UpdateEvent):
    """Change of externally-supplied (non-K8s) config values
    (api/db.go ExternalConfigChange)."""

    name = "External Config Change"

    def __init__(self, source: str, changes: Dict[str, Any],
                 blocking: bool = False, revision: int = 0):
        super().__init__(blocking=blocking)
        self.source = source
        self.changes = changes  # key -> new value (None = delete)
        self.revision = revision  # store revision, 0 when not DB-carried

    def __str__(self) -> str:
        return f"{self.name} [source={self.source}, keys={sorted(self.changes)}]"


class HealingResyncType(enum.Enum):
    PERIODIC = "periodic"
    AFTER_ERROR = "after-error"


class HealingResync(Event):
    """Heals the data-plane state after an error or periodically
    (api/healing.go)."""

    name = "Healing Resync"

    def __init__(self, type_: HealingResyncType, error: Optional[Exception] = None):
        super().__init__()
        self.type = type_
        self.error = error

    @property
    def method(self) -> EventMethod:
        if self.type is HealingResyncType.PERIODIC:
            return EventMethod.DOWNSTREAM_RESYNC
        return EventMethod.FULL_RESYNC

    def __str__(self) -> str:
        if self.type is HealingResyncType.AFTER_ERROR:
            return f"{self.name} (After error: {self.error})"
        return f"{self.name} (Periodic)"


class Shutdown(Event):
    """Final event: cleanup before the agent exits (api/shutdown.go)."""

    name = "Shutdown"

    def __init__(self):
        super().__init__(blocking=True)
