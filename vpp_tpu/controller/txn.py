"""Event transactions.

Analog of ``plugins/controller/txn.go``: every event gets one transaction;
handlers Put()/Delete() typed config values into it and the Controller
commits it to the txn scheduler (or any other TxnSink — the mock txn
tracker in tests plays the reference's mock/localclient role).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class TxnSink:
    """Where committed transactions go (the txn scheduler, or a mock)."""

    def commit(self, txn: "RecordedTxn") -> None:
        raise NotImplementedError


@dataclass
class RecordedTxn:
    """A committed transaction, as recorded in the event history.

    ``is_resync`` distinguishes full-resync commits (desired state is
    *replaced* by ``values``) from incremental commits (``values`` are
    merged, None meaning delete).  ``span_id`` (ISSUE 8) is the
    propagation span minted for the originating event — the join key
    between the event history, the scheduler txn log, and the span
    ring dumped at ``/contiv/v1/spans``.
    """

    seq_num: int = 0
    is_resync: bool = False
    # key -> value; value None = delete (only in non-resync txns)
    values: Dict[str, Any] = field(default_factory=dict)
    span_id: int = 0

    def describe(self) -> str:
        ops = []
        for key in sorted(self.values):
            val = self.values[key]
            ops.append(f"DELETE {key}" if val is None else f"PUT {key}")
        kind = "RESYNC" if self.is_resync else "UPDATE"
        return f"{kind} txn #{self.seq_num}: " + "; ".join(ops)


class Txn:
    """Transaction under construction, exposing the ResyncOperations /
    UpdateOperations contract of api/txn.go (Put/Get/Delete)."""

    def __init__(self, is_resync: bool):
        self.is_resync = is_resync
        self._values: Dict[str, Any] = {}
        # The propagation span of the event this txn belongs to,
        # stamped by the controller when it opens the txn (0 = none).
        self.span_id = 0

    def put(self, key: str, value: Any) -> None:
        """Add or modify a value. ``value`` cannot be None."""
        if value is None:
            raise ValueError(f"txn.put({key!r}) with None value; use delete()")
        self._values[key] = value

    def delete(self, key: str) -> None:
        """Request removal of an existing value (update txns only)."""
        if self.is_resync:
            raise ValueError(
                "delete() is not available in resync transactions: "
                "anything not Put() is removed implicitly"
            )
        self._values[key] = None

    def get(self, key: str) -> Optional[Any]:
        """Value already prepared in this txn (None if absent or deleted)."""
        return self._values.get(key)

    @property
    def values(self) -> Dict[str, Any]:
        return dict(self._values)

    @property
    def empty(self) -> bool:
        return not self._values

    def record(self, seq_num: int) -> RecordedTxn:
        return RecordedTxn(seq_num=seq_num, is_resync=self.is_resync,
                           values=dict(self._values), span_id=self.span_id)
