"""Controller — the single-threaded event loop at the heart of the control
plane.

Analog of the reference's ``plugins/controller`` (SURVEY.md §1 L5): the
dbwatcher converts KV-store changes into events, the event loop runs them
through an ordered chain of event handlers, and every event's config
output is committed as one transaction to the txn scheduler.
"""

from .api import (
    Event,
    UpdateEvent,
    EventHandler,
    EventMethod,
    UpdateDirection,
    UpdateTxnType,
    KubeStateData,
    DBResync,
    KubeStateChange,
    ExternalConfigChange,
    HealingResync,
    HealingResyncType,
    Shutdown,
    FatalError,
    AbortEventError,
)
from .txn import Txn, TxnSink, RecordedTxn
from .eventloop import Controller, EventRecord, HandlerRecord
from .dbwatcher import DBWatcher

__all__ = [
    "Event",
    "UpdateEvent",
    "EventHandler",
    "EventMethod",
    "UpdateDirection",
    "UpdateTxnType",
    "KubeStateData",
    "DBResync",
    "KubeStateChange",
    "ExternalConfigChange",
    "HealingResync",
    "HealingResyncType",
    "Shutdown",
    "FatalError",
    "AbortEventError",
    "Txn",
    "TxnSink",
    "RecordedTxn",
    "Controller",
    "EventRecord",
    "HandlerRecord",
    "DBWatcher",
]
