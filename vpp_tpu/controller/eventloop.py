"""The single-threaded controller event loop.

Analog of ``plugins/controller/plugin_controller.go``: FIFO queue of
events with

- resync-first gating: nothing is processed until the first DBResync
  arrives (receiveEvent :500-513) — events arriving earlier are delayed;
- follow-up priority: events pushed from inside the loop are processed
  before externally queued ones;
- per-event transactions committed to the txn scheduler;
- RevertOnFailure semantics: failed update events get already-executed
  handlers reverted in reverse order (:833-860);
- healing: an error during event processing schedules an AfterError
  HealingResync; a failed healing resync is a FatalError (:873-885, :968);
- event history with per-handler outcomes (:216-237).
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from .api import (
    DBResync,
    Event,
    EventHandler,
    EventMethod,
    ExternalConfigChange,
    FatalError,
    AbortEventError,
    HealingResync,
    HealingResyncType,
    KubeStateChange,
    KubeStateData,
    Shutdown,
    UpdateDirection,
    UpdateEvent,
    UpdateTxnType,
)
from ..telemetry import SpanTracker, current_span_id, record_stage
from .txn import Txn, TxnSink, RecordedTxn

log = logging.getLogger(__name__)


class _StartupResyncCheck(Event):
    """Internal sentinel: the startup-resync deadline elapsed
    (plugin_controller.go startupResyncCheck channel, :454-464)."""

    name = "Startup Resync Check"


@dataclass
class HandlerRecord:
    """Outcome of one handler for one event."""

    handler: str
    revert: bool = False
    change: str = ""
    error: Optional[str] = None


@dataclass
class EventRecord:
    """One entry of the event history (plugin_controller.go eventRecord)."""

    seq_num: int
    name: str
    description: str
    method: EventMethod
    is_followup: bool = False
    handlers: List[HandlerRecord] = field(default_factory=list)
    txn: Optional[RecordedTxn] = None
    txn_error: Optional[str] = None
    started: float = 0.0
    duration_ms: float = 0.0
    # Propagation-span correlation (ISSUE 8): the span minted for this
    # event, findable in /contiv/v1/spans by the same id.
    span_id: int = 0

    @property
    def error(self) -> Optional[str]:
        for rec in self.handlers:
            if rec.error and not rec.revert:
                return f"{rec.handler}: {rec.error}"
        return self.txn_error


class Controller:
    """Runs the event loop in its own thread.

    ``handlers`` must be given in dependency order (the reference's
    fixed chain is built in cmd/contiv-agent/main.go:203-213).
    ``sink`` receives one committed transaction per event.
    """

    def __init__(
        self,
        handlers: Sequence[EventHandler],
        sink: TxnSink,
        healing_delay: float = 5.0,
        on_fatal: Optional[Callable[[Exception], None]] = None,
        history_limit: int = 1000,
        periodic_healing_interval: float = 0.0,
        startup_resync_deadline: float = 0.0,
        spans: Optional[SpanTracker] = None,
    ):
        self.handlers = list(handlers)
        self.sink = sink
        self.healing_delay = healing_delay
        self.on_fatal = on_fatal
        # Propagation spans (ISSUE 8): one span per processed event,
        # stages stamped through handlers → applicator compile → device
        # swap → shard adoption (all on this loop's thread), dumped via
        # REST /contiv/v1/spans + `netctl spans`.  Always present —
        # spans cost two perf_counter calls per stage on the control
        # plane, nowhere near a hot path.
        self.spans = spans if spans is not None else SpanTracker()
        # Optional periodic healing resync (plugin_controller.go
        # periodicHealing :411-425; disabled by default, as in the
        # reference's config).
        self.periodic_healing_interval = periodic_healing_interval
        # Abort if the first resync does not land within the deadline
        # (signalStartupResyncCheck :383-393, check :454-464; the
        # reference restarts the agent via statuscheck).  0 = disabled.
        self.startup_resync_deadline = startup_resync_deadline

        self.kube_state: KubeStateData = {}
        self.external_config: Dict[str, Any] = {}

        self._queue: "queue.Queue[Event]" = queue.Queue()
        self._followup: "collections.deque[Event]" = collections.deque()
        self._delayed: List[Event] = []
        self._started_resync = False
        self._resync_count = 0
        self._event_seq = 0
        self._txn_seq = 0
        # Resilience counters (ISSUE 9 satellite): the healing loop must
        # be OBSERVABLE — a controller stuck scheduling healing resyncs
        # that never complete is a silent failure mode the cluster soak
        # asserts against.  All written on the loop thread (plus the
        # healing timer's scheduled count), read lock-free by status().
        self._healing_scheduled_total = 0
        self._healing_completed_total = 0
        self._healing_failed_total = 0
        self._event_errors_total = 0
        self._last_resync_ts = 0.0
        # The transaction of the event being processed right now, while
        # handlers run (scheduler-routed renderers emit KVs into it).
        self.current_txn: Optional[Txn] = None
        self._shutdown = False
        self._thread: Optional[threading.Thread] = None
        self._loop_thread_id: Optional[int] = None
        # Ring of the last N event records: a long-lived agent processes
        # unbounded events, so the history must be a bounded deque (the
        # old list + slice-trim grew a copy per overflowing event).
        self._history: "collections.deque[EventRecord]" = collections.deque(
            maxlen=history_limit)
        self._history_limit = history_limit
        self._healing_scheduled = False
        self._lock = threading.Lock()
        # Every outstanding threading.Timer, by name — cancelled on
        # shutdown so no timer callback fires after the loop stopped
        # (each callback additionally guards on the stopped flag).
        self._timers: Dict[str, threading.Timer] = {}

    # ----------------------------------------------------------------- life

    def start(self) -> None:
        self._thread = threading.Thread(target=self._event_loop, name="event-loop", daemon=True)
        self._thread.start()
        if self.startup_resync_deadline > 0:
            self._arm_timer("startup-resync", self.startup_resync_deadline,
                            self._startup_resync_check)
        if self.periodic_healing_interval > 0:
            self._schedule_periodic_healing()

    # ------------------------------------------------------------- timers

    def _arm_timer(self, name: str, delay: float, fn: Callable[[], None]) -> None:
        """Start a named daemon timer, replacing (and cancelling) any
        outstanding timer of the same name; refuses to start after
        shutdown so stop() leaves no timer behind."""
        with self._lock:
            old = self._timers.pop(name, None)
            if old is not None:
                old.cancel()
            if self._shutdown:
                return
            timer = threading.Timer(delay, fn)
            timer.daemon = True
            self._timers[name] = timer
        timer.start()

    def _cancel_timers(self) -> None:
        with self._lock:
            timers, self._timers = list(self._timers.values()), {}
        for timer in timers:
            timer.cancel()

    def _startup_resync_check(self) -> None:
        """The startup deadline fired: enqueue a sentinel processed ON THE
        LOOP THREAD (the only legal toucher of ``_delayed``); the loop
        escalates a FatalError if no resync has landed (the reference
        marks the agent not-ready so K8s restarts it)."""
        if not self._shutdown:
            self._queue.put(_StartupResyncCheck())

    def _schedule_periodic_healing(self) -> None:
        def fire():
            if self._shutdown:
                return
            # Heal only once the first resync established state; before
            # that there is nothing to replay (the reference starts
            # periodicHealing alongside the loop but HealingResyncs would
            # otherwise pile up in the delayed queue).
            if self._started_resync:
                self._queue.put(HealingResync(HealingResyncType.PERIODIC))
            self._schedule_periodic_healing()

        self._arm_timer("periodic-healing", self.periodic_healing_interval,
                        fire)

    def stop(self, timeout: float = 10.0) -> None:
        """Push Shutdown and wait for the loop to drain; cancels every
        outstanding timer so none fires into a stopped loop."""
        try:
            if self._thread is None or not self._thread.is_alive():
                return
            ev = Shutdown()
            self.push_event(ev)
            ev.wait(timeout)
            self._thread.join(timeout)
        finally:
            self._cancel_timers()

    # ------------------------------------------------------------ push/queue

    def push_event(self, event: Event) -> None:
        """Add an event to the queue.

        Called from inside the loop (a handler pushing a follow-up), the
        event gets priority over externally queued ones.  Pushing a
        *blocking* event from inside the loop would deadlock and raises
        instead (the reference panics, plugin_controller.go:350-357).
        """
        if threading.get_ident() == self._loop_thread_id:
            if event.is_blocking:
                raise RuntimeError(
                    f"deadlock: blocking event {event.name} pushed from the event loop"
                )
            self._followup.append(event)
        else:
            self._queue.put(event)

    # --------------------------------------------------------------- history

    @property
    def event_history(self) -> List[EventRecord]:
        with self._lock:
            return list(self._history)

    @property
    def resync_count(self) -> int:
        return self._resync_count

    def status(self) -> Dict[str, Any]:
        """Control-plane resilience snapshot: resync/healing/error
        counters + last-resync age.  Served by REST ``/contiv/v1/
        health``/``/contiv/v1/inspect``, printed by ``netctl health``,
        exported by the Prometheus ``_ControllerCollector`` — the soak's
        "no silent healing loop" oracle reads it (scheduled healings
        must complete, never accumulate)."""
        last = self._last_resync_ts
        return {
            "resync_count": self._resync_count,
            "events_processed": self._event_seq,
            "event_errors": self._event_errors_total,
            "healing_scheduled": self._healing_scheduled_total,
            "healing_completed": self._healing_completed_total,
            "healing_failed": self._healing_failed_total,
            "healing_pending": self._healing_scheduled,
            "last_resync_age_s": (
                round(time.time() - last, 3) if last else None),
        }

    # ------------------------------------------------------------------ loop

    def _event_loop(self) -> None:
        self._loop_thread_id = threading.get_ident()
        while not self._shutdown:
            event = self._receive_event()
            if event is None:
                continue
            try:
                self._process_event(event)
            except FatalError as err:
                log.error("fatal error: %s", err)
                event.done(err)
                self._shutdown = True
                if self.on_fatal:
                    self.on_fatal(err)
            if isinstance(event, Shutdown):
                self._shutdown = True
        # Drain: fail any events still queued so blocked producers wake up.
        leftovers = list(self._followup) + self._delayed
        self._followup.clear()
        self._delayed = []
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for ev in leftovers:
            ev.done(FatalError("event loop is shutting down"))
        # A fatal-error exit never reaches stop(): cancel here too so a
        # dead loop leaves no healing/periodic timer ticking behind it.
        self._cancel_timers()

    def _receive_event(self) -> Optional[Event]:
        """Dequeue the next event, honouring follow-up priority and the
        until-first-resync delay (plugin_controller.go receiveEvent :498)."""
        if self._followup:
            event = self._followup.popleft()
            event._from_followup = True
            return event
        try:
            event = self._queue.get(timeout=0.1)
        except queue.Empty:
            return None
        if isinstance(event, _StartupResyncCheck):
            # Deadline sentinel, handled here so all _delayed access stays
            # on the loop thread (plugin_controller.go :454-464).
            if not self._started_resync:
                err = FatalError(
                    f"startup resync has not executed within the first "
                    f"{self.startup_resync_deadline:.0f} seconds"
                )
                log.error(str(err))
                for ev in self._delayed:
                    ev.done(err)
                self._delayed = []
                self._shutdown = True
                if self.on_fatal is not None:
                    self.on_fatal(err)
            return None
        if not self._started_resync:
            if isinstance(event, (DBResync, Shutdown)):
                if isinstance(event, DBResync):
                    self._started_resync = True
                    # Re-queue events that arrived before the first resync.
                    delayed, self._delayed = self._delayed, []
                    for ev in delayed:
                        self._followup.append(ev)
                return event
            log.debug("delaying event %s until first resync", event.name)
            self._delayed.append(event)
            return None
        return event

    # --------------------------------------------------------------- process

    def _process_event(self, event: Event) -> None:
        """The 13-step pipeline of plugin_controller.go processEvent :555."""
        self._event_seq += 1
        record = EventRecord(
            seq_num=self._event_seq,
            name=event.name,
            description=str(event),
            method=event.method,
            is_followup=getattr(event, "_from_followup", False),
            started=time.time(),
        )
        # Propagation span: minted HERE — the moment the K8s/external
        # event reaches the control plane — and finished after commit,
        # so its total is the full event→device propagation latency.
        # Downstream stages (applicator compile, device swap, per-shard
        # adoption) stamp into it through the telemetry thread-local;
        # no context threads through handler signatures.  The store
        # revision that triggered the event (watch delivery / resync
        # snapshot) anchors the span cluster-wide: every agent that
        # adopted the same write minted a span with the same revision
        # (the ISSUE 10 cross-node stitch key).
        span = self.spans.start(event.name, str(event),
                                revision=getattr(event, "revision", 0))
        record.span_id = span.span_id
        try:
            self._process_event_spanned(event, record)
        finally:
            self.spans.finish(span)

    def _process_event_spanned(self, event: Event,
                               record: EventRecord) -> None:

        # 1-2. Update the cached Kubernetes state.
        if isinstance(event, DBResync):
            self.kube_state = {k: dict(v) for k, v in event.kube_state.items()}
            self.external_config = dict(event.external_config)
        elif isinstance(event, KubeStateChange):
            resource_state = self.kube_state.setdefault(event.resource, {})
            if event.new_value is None:
                resource_state.pop(event.key, None)
            else:
                resource_state[event.key] = event.new_value
        elif isinstance(event, ExternalConfigChange):
            for key, value in event.changes.items():
                if value is None:
                    self.external_config.pop(key, None)
                else:
                    self.external_config[key] = value

        err: Optional[Exception] = None
        if event.method is EventMethod.DOWNSTREAM_RESYNC:
            # Handlers are not involved; the sink re-applies its own state.
            err = self._commit(Txn(is_resync=True), record, downstream=True)
        elif event.method.is_resync:
            err = self._process_resync(event, record)
            if err is None and isinstance(event, HealingResync):
                # A full HEALING resync re-derives desired state, but the
                # scheduler's diff only re-pushes values whose desired
                # CHANGED — out-of-band southbound damage (applied ==
                # desired, backend diverged) would survive it.  Follow
                # with the verify-first downstream repair, the point of
                # healing (reference: healing rides on the kvscheduler
                # SB refresh, plugin_controller.go:968).
                err = self._commit(Txn(is_resync=True), record,
                                   downstream=True)
        else:
            err = self._process_update(event, record)

        record.duration_ms = (time.time() - record.started) * 1000
        with self._lock:
            self._history.append(record)  # bounded deque: ring of last N

        # 11. Deliver the result to blocked producers.
        event.done(err)

        # 12-13. Healing / fatal handling.
        if err is not None:
            self._event_errors_total += 1
            if isinstance(event, HealingResync):
                self._healing_failed_total += 1
                raise FatalError(f"healing resync failed: {err}") from err
            if isinstance(err, FatalError):
                raise err
            self._schedule_healing(err)
        elif isinstance(event, HealingResync):
            self._healing_completed_total += 1

    def _process_resync(self, event: Event, record: EventRecord) -> Optional[Exception]:
        self._resync_count += 1
        self._last_resync_ts = time.time()
        txn = Txn(is_resync=True)
        txn.span_id = current_span_id()
        self.current_txn = txn
        first_err: Optional[Exception] = None
        for handler in self.handlers:
            if not handler.handles_event(event):
                continue
            hrec = HandlerRecord(handler=handler.name)
            record.handlers.append(hrec)
            t0 = time.perf_counter()
            try:
                handler.resync(event, self.kube_state, self._resync_count, txn)
            except FatalError:
                raise
            except Exception as e:  # noqa: BLE001 - handler errors are data
                hrec.error = str(e)
                log.warning("handler %s failed resync: %s", handler.name, e)
                if first_err is None:
                    first_err = e
                # Resync is best-effort across handlers (reference continues
                # and reports, scheduling healing afterwards).
            finally:
                # Span stage: processor + renderer work runs inside the
                # handler, so this is the "event processing" leg.
                record_stage(f"handler:{handler.name}",
                             time.perf_counter() - t0)
        self.current_txn = None
        commit_err = self._commit(txn, record)
        return first_err or commit_err

    def _process_update(self, event: Event, record: EventRecord) -> Optional[Exception]:
        direction = UpdateDirection.FORWARD
        txn_type = UpdateTxnType.BEST_EFFORT
        if isinstance(event, UpdateEvent):
            direction = event.direction
            txn_type = event.transaction_type

        ordered = self.handlers if direction is UpdateDirection.FORWARD else list(reversed(self.handlers))
        txn = Txn(is_resync=False)
        txn.span_id = current_span_id()
        self.current_txn = txn
        executed: List[EventHandler] = []
        err: Optional[Exception] = None
        aborted = False
        for handler in ordered:
            if not handler.handles_event(event):
                continue
            hrec = HandlerRecord(handler=handler.name)
            record.handlers.append(hrec)
            t0 = time.perf_counter()
            try:
                hrec.change = handler.update(event, txn) or ""
                executed.append(handler)
            except FatalError:
                raise
            except AbortEventError as e:
                hrec.error = str(e)
                err = e
                aborted = True
                break
            except Exception as e:  # noqa: BLE001
                hrec.error = str(e)
                log.warning("handler %s failed update: %s", handler.name, e)
                if err is None:
                    err = e
                if txn_type is UpdateTxnType.REVERT_ON_FAILURE:
                    break
            finally:
                record_stage(f"handler:{handler.name}",
                             time.perf_counter() - t0)

        self.current_txn = None
        if err is not None and txn_type is UpdateTxnType.REVERT_ON_FAILURE and not aborted:
            # 9. Revert plugin-internal changes in reverse order; the txn is
            # dropped (never committed), reverting the would-be data-plane
            # changes.
            for handler in reversed(executed):
                hrec = HandlerRecord(handler=handler.name, revert=True)
                record.handlers.append(hrec)
                try:
                    handler.revert(event)
                except Exception as e:  # noqa: BLE001
                    hrec.error = str(e)
                    log.error("handler %s failed to revert: %s", handler.name, e)
            return err

        commit_err = self._commit(txn, record)
        return err or commit_err

    def _commit(self, txn: Txn, record: EventRecord, downstream: bool = False) -> Optional[Exception]:
        if txn.empty and not txn.is_resync:
            return None
        if not txn.span_id:  # downstream-repair txns are built inline
            txn.span_id = current_span_id()
        self._txn_seq += 1
        if record.txn is None:  # healing runs commit + downstream repair
            record.txn = txn.record(self._txn_seq)
        t0 = time.perf_counter()
        try:
            if downstream:
                # Verify-first southbound repair when the sink supports
                # readback (TxnScheduler.resync_downstream): detect
                # out-of-band drift and fix only that; otherwise fall
                # back to a blind re-apply of the desired state.
                resync_sb = getattr(self.sink, "resync_downstream", None)
                if resync_sb is not None:
                    resync_sb()
                else:
                    replay = getattr(self.sink, "replay", None)
                    if replay is not None:
                        replay()
            else:
                self.sink.commit(record.txn)
        except Exception as e:  # noqa: BLE001
            record.txn_error = str(e)
            return e
        finally:
            # Span stage bracketing the whole southbound commit (the
            # compile/swap/adopt stages stamped inside it nest here).
            record_stage("commit", time.perf_counter() - t0,
                         downstream=downstream)
        return None

    def _schedule_healing(self, err: Exception) -> None:
        """Schedule an AfterError healing resync (scheduleHealing :968)."""
        if self._healing_scheduled or self._shutdown:
            return
        self._healing_scheduled = True
        self._healing_scheduled_total += 1

        def fire():
            self._healing_scheduled = False
            if not self._shutdown:
                self._queue.put(HealingResync(HealingResyncType.AFTER_ERROR, err))

        self._arm_timer("healing", self.healing_delay, fire)
