"""Graceful node drain / rejoin — the planned-operations FSM (ISSUE 13).

``kubectl drain`` empties a node before maintenance; the CNI agent's
half of that story is this coordinator.  Draining is NOT crashing:

- new CNI ADDs are refused with a RETRIABLE error (CNI result code 11,
  ``AGENT_DRAINING`` — kubelet-shaped callers back off and the
  scheduler places the pod elsewhere); CNI DELs keep working — drain
  exists precisely so pods can leave;
- in-flight dispatch is QUIESCED through the datapath's existing drain
  path (every admitted batch harvested, rings empty — the same idle
  proof the shard supervisor's probation uses);
- the final flight-recorder and latency telemetry are FLUSHED into the
  drain status (the last-breath forensics an operator reads after the
  node is gone);
- the heartbeat flips to a ``drained`` TOMBSTONE — explicitly distinct
  from crash-dead (a missing/stale heartbeat): the cluster scraper and
  ``netctl cluster top`` report the node as *drained*, never as an
  unreachable gap or a straggler (the ISSUE 13 gap-reporting contract).

``undrain`` rejoins cleanly: ADDs accepted again, heartbeat state back
to ``active``.  States: active → draining → drained → (undrain) →
active.  The FSM is driven from the REST thread (``POST
/contiv/v1/drain|undrain`` / ``netctl drain|undrain``) and READ from
the heartbeat and CNI event threads — all shared state sits under one
lock (machine-checked by the lock-discipline battery, not waived).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional

log = logging.getLogger(__name__)

STATE_ACTIVE = "active"
STATE_DRAINING = "draining"
STATE_DRAINED = "drained"

# Marker carried by the retriable CNI rejection (and its message); the
# CNI result code is 11 ("try again later" in the CNI error-code
# convention — the same class as a momentarily unreachable agent).
DRAINING_MARKER = "AGENT_DRAINING"
CNI_DRAINING_CODE = 11


class NodeDraining(RuntimeError):
    """A new CNI ADD hit a draining/drained agent.  Retriable by
    contract: the pod belongs on another node until ``undrain``."""

    retriable = True

    def __init__(self, node: str = ""):
        super().__init__(
            f"{DRAINING_MARKER}: agent{' ' + node if node else ''} is "
            "draining; retry the pod on another node (undrain rejoins)")


class DrainCoordinator:
    """The per-agent drain FSM.

    ``podmanager`` gains/loses its ADD gate here; ``datapath`` is the
    live engine or a zero-arg callable resolving to it (the agent's
    runner attaches after REST construction), quiesced and flushed on
    drain.  Both are optional — a control-plane-only agent drains too.
    """

    def __init__(self, podmanager=None, datapath=None, node_name: str = "",
                 on_state: Optional[Callable[[str], None]] = None):
        self.podmanager = podmanager
        self.datapath = datapath
        self.node_name = node_name
        # Optional notification hook (e.g. an eager heartbeat rewrite);
        # called OUTSIDE the lock with the new state.
        self._on_state = on_state
        self._lock = threading.Lock()
        self._state = STATE_ACTIVE     # guarded-by: _lock
        self._drained_at: Optional[float] = None  # guarded-by: _lock — wall clock, rides the tombstone
        self._last_flush: Dict[str, Any] = {}     # guarded-by: _lock — final flight/latency forensics
        self.drains = 0                # guarded-by: _lock — lifetime counters (observability)
        self.undrains = 0              # guarded-by: _lock
        self.rejected_adds = 0         # guarded-by: _lock — CNI ADDs refused while draining

    # ------------------------------------------------------------- queries

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def gate_add(self) -> None:
        """Called by the CNI ADD path: refuse (retriably) while the
        agent is anything but active."""
        with self._lock:
            if self._state == STATE_ACTIVE:
                return
            self.rejected_adds += 1
        raise NodeDraining(self.node_name)

    def status(self) -> dict:
        with self._lock:
            return self._status_locked()

    # --------------------------------------------------------- transitions

    def drain(self) -> dict:
        """active → draining → drained.  Idempotent: draining a
        drained agent reports the current status."""
        with self._lock:
            if self._state != STATE_ACTIVE:
                return self._status_locked()
            self._state = STATE_DRAINING
        self._notify(STATE_DRAINING)
        # 1. Gate new work FIRST: no ADD admitted after this point.
        if self.podmanager is not None:
            self.podmanager.set_draining(True, gate=self.gate_add)
        # 2. Quiesce in-flight dispatch through the existing drain path
        #    (poll-until-idle: admitted batches harvested, rings empty).
        flush: Dict[str, Any] = {}
        dp = self._resolve_datapath()
        if dp is not None:
            try:
                drained_frames = dp.drain()
                flush["quiesced_frames"] = int(drained_frames)
            except Exception as err:  # noqa: BLE001 - a wedged shard must not block the drain
                log.warning("drain quiesce error (continuing): %s", err)
                flush["quiesce_error"] = str(err)
            # 3. Flush the last-breath telemetry: the flight recorder
            #    rings and the latency snapshot as they stood when the
            #    node left — served from the drain status from now on.
            try:
                dump_flight = getattr(dp, "dump_flight", None)
                if dump_flight is not None:
                    flight = dump_flight(0)
                    flush["flight"] = {
                        "shards": len(flight.get("shards") or []),
                        "dispatches_total": sum(
                            int(s.get("dispatches_total", 0))
                            for s in flight.get("shards") or []),
                    }
                inspect = getattr(dp, "inspect", None)
                if inspect is not None:
                    flush["latency"] = inspect().get("latency")
            except Exception as err:  # noqa: BLE001 - forensics are best-effort
                flush["flush_error"] = str(err)
        with self._lock:
            self._state = STATE_DRAINED
            self._drained_at = time.time()
            self._last_flush = flush
            self.drains += 1
            out = self._status_locked()
        self._notify(STATE_DRAINED)
        log.info("agent %s drained (%s)", self.node_name, flush)
        return out

    def undrain(self) -> dict:
        """drained (or draining) → active: accept CNI ADDs again and
        flip the heartbeat back.  Idempotent on an active agent."""
        with self._lock:
            if self._state == STATE_ACTIVE:
                return self._status_locked()
            self._state = STATE_ACTIVE
            self._drained_at = None
            self.undrains += 1
            out = self._status_locked()
        if self.podmanager is not None:
            self.podmanager.set_draining(False)
        self._notify(STATE_ACTIVE)
        log.info("agent %s undrained; accepting pods again",
                 self.node_name)
        return out

    # ------------------------------------------------------------ internals

    def _status_locked(self) -> dict:  # holds: _lock
        return {
            "state": self._state,
            "drained_at": self._drained_at,
            "drains": self.drains,
            "undrains": self.undrains,
            "rejected_adds": self.rejected_adds,
            "last_flush": dict(self._last_flush),
        }

    def _resolve_datapath(self):
        dp = self.datapath() if callable(self.datapath) else self.datapath
        return dp

    def _notify(self, state: str) -> None:
        if self._on_state is None:
            return
        try:
            self._on_state(state)
        except Exception:  # noqa: BLE001 - notification is best-effort
            log.exception("drain state hook failed")
