"""Steal-the-NIC daemon.

Analog of ``cmd/contiv-stn/main.go``: on single-NIC hosts the data
plane takes over the host's interface.  The daemon

- ``steal_interface`` (:95 + ``unconfigureInterface`` :150): records the
  interface's addresses/routes, flushes them from the host, and returns
  the saved config (the data plane configures the same identity);
- ``release_interface`` (:117 + ``revertInterface`` :187): restores the
  saved config onto the host;
- ``stolen_interface_info`` (:132): returns the saved config without
  touching state (used by the agent after restart);
- **watchdog** (:343-434, ``checkStatusAfterTimeout``): if the agent's
  health check stays down past a timeout, all stolen interfaces are
  reverted so the host regains connectivity.

The host-network access is injected (tests: FakeHostNetwork; production
would bind rtnetlink).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


@dataclass
class StolenInterface:
    """Saved identity of a stolen interface (interfaceData analog)."""

    name: str
    addresses: Tuple[str, ...]
    routes: List  # HostRoute-like objects
    mac: str = ""
    stolen_at: float = field(default_factory=time.time)


class STNDaemon:
    def __init__(self, host_network, agent_alive: Optional[Callable[[], bool]] = None,
                 revert_timeout: float = 10.0):
        self.net = host_network
        self.agent_alive = agent_alive
        self.revert_timeout = revert_timeout
        self._stolen: Dict[str, StolenInterface] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        self._agent_down_since: Optional[float] = None

    # -------------------------------------------------------------- service

    def steal_interface(self, name: str) -> StolenInterface:
        with self._lock:
            if name in self._stolen:
                return self._stolen[name]  # idempotent re-steal
            iface = self.net.get_interface(name)
            saved = StolenInterface(
                name=name,
                addresses=tuple(iface.addresses),
                routes=list(self.net.interface_routes(name)),
                mac=iface.mac,
            )
            self.net.flush_interface(name)
            self._stolen[name] = saved
            log.info("stole interface %s (%s)", name, ", ".join(saved.addresses))
            return saved

    def release_interface(self, name: str) -> None:
        with self._lock:
            saved = self._stolen.pop(name, None)
            if saved is None:
                return
            self.net.configure_interface(name, saved.addresses, saved.routes, up=True)
            log.info("released interface %s", name)

    def stolen_interface_info(self, name: str) -> Optional[StolenInterface]:
        with self._lock:
            return self._stolen.get(name)

    def revert_all(self) -> None:
        with self._lock:
            names = list(self._stolen)
        for name in names:
            self.release_interface(name)

    # ------------------------------------------------------------- watchdog

    def check_agent(self, now: Optional[float] = None) -> bool:
        """One watchdog tick: reverts everything if the agent has been
        down longer than ``revert_timeout``.  Returns agent liveness."""
        if self.agent_alive is None:
            return True
        now = now if now is not None else time.time()
        try:
            alive = bool(self.agent_alive())
        except Exception:  # noqa: BLE001
            alive = False
        if alive:
            self._agent_down_since = None
            return True
        if self._agent_down_since is None:
            self._agent_down_since = now
        elif now - self._agent_down_since >= self.revert_timeout:
            log.warning("agent down for %.1fs — reverting stolen interfaces",
                        now - self._agent_down_since)
            self.revert_all()
            self._agent_down_since = None
        return False

    def start_watchdog(self, interval: float = 1.0) -> None:
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, args=(interval,),
            name="stn-watchdog", daemon=True,
        )
        self._watchdog.start()

    def stop(self) -> None:
        self._stop.set()

    def _watchdog_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self.check_agent()


# ---------------------------------------------------------------------------
# Production host-network binding + daemon entrypoint
# ---------------------------------------------------------------------------


@dataclass
class HostRoute:
    """One host route attached to the stolen interface."""

    dst: str
    gateway: str = ""
    interface: str = ""
    scope: str = ""


@dataclass
class HostIface:
    """Interface identity as read from the kernel."""

    name: str
    addresses: Tuple[str, ...] = ()
    mac: str = ""
    up: bool = True


class LinuxHostNetwork:
    """iproute2-backed host network access for the STN daemon — the
    production implementation of the injected seam (the netlink calls
    of cmd/contiv-stn/main.go unconfigureInterface :150 /
    revertInterface :187), netns-confinable for tests.  Implements the
    same contract as testing.netlink.FakeHostNetwork."""

    def __init__(self, netns: Optional[str] = None):
        self.netns = netns

    def _ip(self, *args: str, check: bool = True, js: bool = False):
        import json as _json
        import subprocess

        cmd = ["ip"]
        if self.netns:
            cmd += ["-n", self.netns]
        if js:
            cmd += ["-j"]
        cmd += list(args)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise RuntimeError(f"{' '.join(cmd)}: {proc.stderr.strip()}")
        if js:
            return _json.loads(proc.stdout or "[]")
        return proc.stdout

    def first_nic(self) -> str:
        """The interface carrying the default route (the reference's
        steal-first-NIC discovery)."""
        for route in self._ip("route", "show", "default", js=True):
            if route.get("dev"):
                return route["dev"]
        raise RuntimeError("no default route: cannot pick a NIC to steal")

    def get_interface(self, name: str) -> HostIface:
        links = self._ip("link", "show", "dev", name, js=True)
        if not links:
            raise LookupError(f"no such interface {name}")
        addrs = []
        for entry in self._ip("addr", "show", "dev", name, js=True):
            for a in entry.get("addr_info", []):
                if a.get("family") == "inet":
                    addrs.append(f"{a['local']}/{a['prefixlen']}")
        return HostIface(
            name=name, addresses=tuple(addrs),
            mac=links[0].get("address", ""),
            up="UP" in (links[0].get("flags") or []),
        )

    def interface_routes(self, name: str) -> List[HostRoute]:
        routes = []
        for r in self._ip("route", "show", "dev", name, js=True):
            routes.append(HostRoute(
                dst=r.get("dst", ""), gateway=r.get("gateway", ""),
                interface=name, scope=str(r.get("scope", "")),
            ))
        return routes

    def flush_interface(self, name: str) -> None:
        """Remove all addresses (+ their attached routes) — the steal."""
        self._ip("addr", "flush", "dev", name)

    def configure_interface(self, name: str, addresses, routes,
                            up: bool = True) -> None:
        """Restore a saved identity onto the interface — the revert."""
        for addr in addresses:
            self._ip("addr", "replace", addr, "dev", name)
        if up:
            self._ip("link", "set", name, "up", check=False)
        for route in routes:
            args = ["route", "replace", route.dst or "default"]
            if route.gateway:
                args += ["via", route.gateway]
            args += ["dev", name]
            scope = getattr(route, "scope", "")
            if scope and scope != "global":
                args += ["scope", scope]
            self._ip(*args, check=False)


def _http_alive(url: str, timeout: float = 2.0) -> bool:
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout):  # noqa: S310
            return True
    except Exception:
        return False


def save_stolen(path: str, stolen: StolenInterface) -> None:
    """Persist the stolen identity for the agent / a restarted daemon
    (the reference's persisted config, main.go :95)."""
    import dataclasses
    import json as _json

    data = dataclasses.asdict(stolen)
    data["routes"] = [dataclasses.asdict(r) for r in stolen.routes]
    with open(path, "w") as fh:
        _json.dump(data, fh, indent=2)


def load_stolen(path: str) -> Optional[StolenInterface]:
    import json as _json
    import os

    if not os.path.exists(path):
        return None
    with open(path) as fh:
        data = _json.load(fh)
    data["routes"] = [HostRoute(**r) for r in data.get("routes", [])]
    data["addresses"] = tuple(data.get("addresses", ()))
    return StolenInterface(**data)


def main(argv=None) -> int:
    """contiv-stn entrypoint: steal the NIC, persist its identity, and
    (unless --oneshot) keep the agent-liveness watchdog running so the
    host regains connectivity if the agent dies."""
    import argparse

    parser = argparse.ArgumentParser(description="steal-the-NIC daemon")
    parser.add_argument("--takeover", action="store_true",
                        help="steal the interface now")
    parser.add_argument("--interface", default="",
                        help="NIC to steal (default: first NIC — the one "
                             "carrying the default route)")
    parser.add_argument("--netns", default="",
                        help="confine to a network namespace (tests)")
    parser.add_argument("--state", default="/var/lib/vpp-tpu/stn.json",
                        help="where to persist the stolen identity")
    parser.add_argument("--agent-url",
                        default="http://127.0.0.1:9999/liveness",
                        help="agent liveness probe for the revert watchdog")
    parser.add_argument("--revert-timeout", type=float, default=10.0)
    parser.add_argument("--oneshot", action="store_true",
                        help="steal + persist + exit (init-container mode; "
                             "no watchdog)")
    args = parser.parse_args(argv)

    net = LinuxHostNetwork(netns=args.netns or None)
    daemon = STNDaemon(
        net, agent_alive=lambda: _http_alive(args.agent_url),
        revert_timeout=args.revert_timeout,
    )
    if args.takeover:
        name = args.interface or net.first_nic()
        stolen = daemon.steal_interface(name)
        save_stolen(args.state, stolen)
        log.info("stole %s (%s)", name, ", ".join(stolen.addresses))
    if args.oneshot:
        return 0
    daemon.start_watchdog()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        daemon.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover - CLI entry
    import sys

    sys.exit(main())
