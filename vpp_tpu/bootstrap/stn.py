"""Steal-the-NIC daemon.

Analog of ``cmd/contiv-stn/main.go``: on single-NIC hosts the data
plane takes over the host's interface.  The daemon

- ``steal_interface`` (:95 + ``unconfigureInterface`` :150): records the
  interface's addresses/routes, flushes them from the host, and returns
  the saved config (the data plane configures the same identity);
- ``release_interface`` (:117 + ``revertInterface`` :187): restores the
  saved config onto the host;
- ``stolen_interface_info`` (:132): returns the saved config without
  touching state (used by the agent after restart);
- **watchdog** (:343-434, ``checkStatusAfterTimeout``): if the agent's
  health check stays down past a timeout, all stolen interfaces are
  reverted so the host regains connectivity.

The host-network access is injected (tests: FakeHostNetwork; production
would bind rtnetlink).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


@dataclass
class StolenInterface:
    """Saved identity of a stolen interface (interfaceData analog)."""

    name: str
    addresses: Tuple[str, ...]
    routes: List  # HostRoute-like objects
    mac: str = ""
    stolen_at: float = field(default_factory=time.time)


class STNDaemon:
    def __init__(self, host_network, agent_alive: Optional[Callable[[], bool]] = None,
                 revert_timeout: float = 10.0):
        self.net = host_network
        self.agent_alive = agent_alive
        self.revert_timeout = revert_timeout
        self._stolen: Dict[str, StolenInterface] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        self._agent_down_since: Optional[float] = None

    # -------------------------------------------------------------- service

    def steal_interface(self, name: str) -> StolenInterface:
        with self._lock:
            if name in self._stolen:
                return self._stolen[name]  # idempotent re-steal
            iface = self.net.get_interface(name)
            saved = StolenInterface(
                name=name,
                addresses=tuple(iface.addresses),
                routes=list(self.net.interface_routes(name)),
                mac=iface.mac,
            )
            self.net.flush_interface(name)
            self._stolen[name] = saved
            log.info("stole interface %s (%s)", name, ", ".join(saved.addresses))
            return saved

    def release_interface(self, name: str) -> None:
        with self._lock:
            saved = self._stolen.pop(name, None)
            if saved is None:
                return
            self.net.configure_interface(name, saved.addresses, saved.routes, up=True)
            log.info("released interface %s", name)

    def stolen_interface_info(self, name: str) -> Optional[StolenInterface]:
        with self._lock:
            return self._stolen.get(name)

    def revert_all(self) -> None:
        with self._lock:
            names = list(self._stolen)
        for name in names:
            self.release_interface(name)

    # ------------------------------------------------------------- watchdog

    def check_agent(self, now: Optional[float] = None) -> bool:
        """One watchdog tick: reverts everything if the agent has been
        down longer than ``revert_timeout``.  Returns agent liveness."""
        if self.agent_alive is None:
            return True
        now = now if now is not None else time.time()
        try:
            alive = bool(self.agent_alive())
        except Exception:  # noqa: BLE001
            alive = False
        if alive:
            self._agent_down_since = None
            return True
        if self._agent_down_since is None:
            self._agent_down_since = now
        elif now - self._agent_down_since >= self.revert_timeout:
            log.warning("agent down for %.1fs — reverting stolen interfaces",
                        now - self._agent_down_since)
            self.revert_all()
            self._agent_down_since = None
        return False

    def start_watchdog(self, interval: float = 1.0) -> None:
        self._watchdog = threading.Thread(
            target=self._watchdog_loop, args=(interval,),
            name="stn-watchdog", daemon=True,
        )
        self._watchdog.start()

    def stop(self) -> None:
        self._stop.set()

    def _watchdog_loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self.check_agent()
