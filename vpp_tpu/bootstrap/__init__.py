"""Agent bootstrap: STN daemon + contiv-init analog."""

from .stn import STNDaemon, StolenInterface
from .init import STNConfig, bootstrap_config, preseed_local_snapshot, load_local_snapshot

__all__ = [
    "STNConfig",
    "STNDaemon",
    "StolenInterface",
    "bootstrap_config",
    "load_local_snapshot",
    "preseed_local_snapshot",
]
