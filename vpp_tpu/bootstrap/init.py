"""Agent bootstrap — the contiv-init analog.

Mirrors ``cmd/contiv-init/main.go``:

- the **config priority merge** of the reference's ContivConf
  (docs/dev-guide/CORE_PLUGINS.md:160-178, contivconf.go :275-446):
  file config < NodeConfig CRD override < STN-reported config;
- STN mode: steal the NIC through the STN daemon and feed its saved
  identity into the merged config (``stealNIC`` :77);
- ``prepareForLocalResync`` (:231): snapshot the remote store into a
  local file so a restart can resync locally while the remote store is
  unreachable (the Bolt pre-seed analog; DBResync(local=True)).
"""

from __future__ import annotations

import json
import logging
import sqlite3
from dataclasses import dataclass, replace
from typing import Optional, Tuple

from ..conf.config import InterfaceConfig, NetworkConfig
from ..crd.models import NodeConfig
from ..kvstore import KVStore

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class STNConfig:
    """What the STN daemon reported for the stolen NIC
    (contivconf_api.go STNConfig :194)."""

    interface: str
    ip_addresses: Tuple[str, ...] = ()
    gateway: str = ""


def bootstrap_config(
    file_config: NetworkConfig,
    node_config: Optional[NodeConfig] = None,
    stn_daemon=None,
) -> Tuple[NetworkConfig, Optional[STNConfig]]:
    """Resolve the effective config by the reference's priority order.

    Returns (merged config, STN-reported config or None).  STN mode is
    entered when the file config requests it or the NodeConfig names a
    stealth interface.
    """
    cfg = file_config

    # NodeConfig CRD overrides the file (priority 2).
    if node_config is not None and node_config.main_interface.name:
        cfg = replace(
            cfg,
            interface=replace(cfg.interface,
                              main_interface=node_config.main_interface.name,
                              use_dhcp=node_config.main_interface.use_dhcp),
        )
    if node_config is not None and node_config.other_interfaces:
        from ..conf import OtherInterface

        cfg = replace(
            cfg,
            interface=replace(
                cfg.interface,
                other_interfaces=tuple(
                    OtherInterface(name=i.name, ip=i.ip, use_dhcp=i.use_dhcp)
                    for i in node_config.other_interfaces
                ),
            ),
        )

    stn_iface = ""
    if node_config is not None and node_config.stealth_interface:
        stn_iface = node_config.stealth_interface
    elif cfg.interface.stn_mode:
        stn_iface = cfg.interface.main_interface

    stn_config: Optional[STNConfig] = None
    if stn_iface:
        if stn_daemon is None:
            raise RuntimeError("STN mode requested but no STN daemon available")
        saved = stn_daemon.steal_interface(stn_iface)
        stn_config = STNConfig(
            interface=stn_iface,
            ip_addresses=tuple(saved.addresses),
            gateway=next((r.gateway for r in saved.routes
                          if r.dst in ("0.0.0.0/0", "default") and r.gateway), ""),
        )
        # STN-reported config overrides everything (priority 3): the data
        # plane takes over the NIC with its host identity.
        cfg = replace(
            cfg,
            interface=replace(cfg.interface, main_interface=stn_iface,
                              stn_mode=True),
        )
    return cfg, stn_config


# ------------------------------------------------------- local pre-seed


def preseed_local_snapshot(store: KVStore, path: str,
                           prefixes: Tuple[str, ...] = ("/vpp-tpu/",)) -> int:
    """Snapshot the remote store into a local sqlite file
    (prepareForLocalResync :231). Returns the number of keys saved."""
    snap = store.snapshot(prefixes)
    conn = sqlite3.connect(path)
    try:
        conn.execute("CREATE TABLE IF NOT EXISTS snapshot (key TEXT PRIMARY KEY, value BLOB)")
        conn.execute("DELETE FROM snapshot")
        import pickle

        conn.executemany(
            "INSERT INTO snapshot (key, value) VALUES (?, ?)",
            [(k, pickle.dumps(v)) for k, v in snap.items()],
        )
        conn.commit()
    finally:
        conn.close()
    log.info("pre-seeded local snapshot: %d keys -> %s", len(snap), path)
    return len(snap)


def load_local_snapshot(store: KVStore, path: str) -> int:
    """Load a pre-seeded snapshot into a (fresh) store for a local
    startup resync while the remote store is down."""
    import pickle

    conn = sqlite3.connect(path)
    try:
        rows = conn.execute("SELECT key, value FROM snapshot").fetchall()
    finally:
        conn.close()
    for key, blob in rows:
        store.put(key, pickle.loads(blob))
    log.info("loaded local snapshot: %d keys from %s", len(rows), path)
    return len(rows)
