"""Production agent entrypoint — the contiv-vswitch container analog.

The reference deploys one vswitch agent per node as a DaemonSet pod
(/root/reference/k8s/contiv-vpp.yaml contiv-vswitch; cmd/contiv-agent)
wired to the cluster etcd, exposing a CNI gRPC endpoint and REST
diagnostics.  This module is the same composition for the TPU-native
stack, runnable as ``python -m vpp_tpu.agent``:

- cluster store:   RemoteKVStore -> KVStoreServer (``python -m
  vpp_tpu.kvstore``, the contiv-etcd analog)
- control plane:   Controller event loop + DBWatcher (sqlite mirror),
  NodeSync ID allocation, PodManager, IPv4Net, policy + service stacks
  rendering through the TxnScheduler into atomic TPU table swaps
- host networking: LinuxNetApplicator programming real kernel state
  (veth/vxlan/bridge/routes), optionally confined to a netns
- pod interface:   CNI gRPC server consumed by the contiv-cni shim
  (vpp_tpu/cni/shim.py, installed via deploy/10-vpp-tpu.conflist)
- data plane:      optional AF_PACKET uplink driven through the native
  C++ runner loop (NativeRing + DataplaneRunner)
- diagnostics:     AgentRestServer (/contiv/v1/*, /metrics, /liveness)

The SimCluster/procnode test harnesses wire the same plugin set; this
module is the production composition (no mock engines, no oracles).
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading
import time
from typing import Optional

log = logging.getLogger(__name__)


class Agent:
    """One node's full TPU-native vswitch agent."""

    def __init__(
        self,
        store,
        name: str,
        config=None,
        mirror_path: Optional[str] = None,
        hostnet: str = "off",          # off | root | netns:<name>
        rest_port: int = 0,
        cni_port: int = 0,
        uplink: str = "",
    ):
        from .conf import NetworkConfig
        from .controller.dbwatcher import DBWatcher
        from .controller.eventloop import Controller
        from .ipam import IPAM
        from .ipv4net import IPv4Net
        from .inference import InferencePlugin
        from .nodesync import NodeSync
        from .podmanager import PodManager
        from .policy import PolicyPlugin
        from .policy.renderer.infer import SchedInferRenderer
        from .policy.renderer.sched import SchedPolicyRenderer
        from .scheduler import TxnScheduler
        from .scheduler.tpu_applicators import (
            TpuAclApplicator,
            TpuInferApplicator,
            TpuNatApplicator,
        )
        from .service import ServicePlugin
        from .service.renderer.sched import SchedNatRenderer

        self.name = name
        self.store = store
        self.config = config or NetworkConfig()

        self.nodesync = NodeSync(store, node_name=name)
        self.nodesync.allocate_id()
        self.ipam = IPAM(self.config.ipam, self.nodesync.node_id)

        self.podmanager = PodManager()
        self.ipv4net = IPv4Net(
            self.config, self.nodesync, ipam=self.ipam,
            podmanager=self.podmanager,
        )

        self.acl_applicator = TpuAclApplicator()
        self.policy_renderer = SchedPolicyRenderer(
            lambda: self.controller.current_txn, applicator=self.acl_applicator
        )
        self.policy = PolicyPlugin(ipam=self.ipam)
        self.policy.register_renderer(self.policy_renderer)

        self.nat_applicator = TpuNatApplicator()
        self.nat_renderer = SchedNatRenderer(
            lambda: self.controller.current_txn,
            nat_loopback=str(self.ipam.nat_loopback_ip()),
            snat_ip=f"192.168.16.{self.nodesync.node_id}",
            snat_enabled=True,
            pod_subnet=str(self.ipam.pod_subnet_all_nodes),
            applicator=self.nat_applicator,
        )
        self.service = ServicePlugin(name, ipam=self.ipam, nodesync=self.nodesync)
        self.service.register_renderer(self.nat_renderer)

        # In-network inference plane (ISSUE 14): InferPolicy CRD events
        # + pod state render through the scheduler into atomic
        # InferTable swaps — same transaction discipline as ACL/NAT.
        self.infer_applicator = None
        self.inference = None
        if self.config.inference:
            self.infer_applicator = TpuInferApplicator()
            self.infer_renderer = SchedInferRenderer(
                lambda: self.controller.current_txn,
                applicator=self.infer_applicator,
            )
            self.inference = InferencePlugin()
            self.inference.register_renderer(self.infer_renderer)

        self.scheduler = TxnScheduler()
        self.hostnet = None
        if hostnet != "off":
            from .hostnet import LinuxNetApplicator

            netns = hostnet.split(":", 1)[1] if hostnet.startswith("netns:") else None
            self.hostnet = LinuxNetApplicator(netns=netns, create_netns=bool(netns))
            self.scheduler.register_applicator(self.hostnet)
        self.scheduler.register_applicator(self.acl_applicator)
        self.scheduler.register_applicator(self.nat_applicator)
        if self.infer_applicator is not None:
            self.scheduler.register_applicator(self.infer_applicator)

        # BGP reflection: production kernel route watcher (iproute2
        # monitor stream) in the same netns the hostnet applicator
        # programs; mirrors BIRD-learned routes into the main VRF.
        from .bgpreflector import BGPReflector
        from .hostnet.monitor import IpRouteSource

        bgp_netns = (
            hostnet.split(":", 1)[1] if hostnet.startswith("netns:") else None
        )
        self.route_source = IpRouteSource(netns=bgp_netns) if hostnet != "off" else None
        self.bgpreflector = BGPReflector(
            self.config, route_source=self.route_source
        )

        handlers = [
            self.nodesync, self.podmanager, self.ipv4net,
            self.service, self.policy, self.bgpreflector,
        ]
        if self.inference is not None:
            handlers.append(self.inference)
        self.controller = Controller(handlers=handlers, sink=self.scheduler)
        self.podmanager.event_loop = self.controller
        self.nodesync.event_loop = self.controller
        self.bgpreflector.event_loop = self.controller
        self.bgpreflector.init()
        # DHCP mode: watch the uplink's addresses for lease changes
        # (the platform DHCP client installs them; we only observe).
        self.dhcp_source = None
        if uplink and (
            self.config.interface.use_dhcp
            or self.config.ipam.node_interconnect_dhcp
        ):
            from .hostnet.monitor import DhcpAddressSource

            self.dhcp_source = DhcpAddressSource(
                uplink, self.controller, netns=bgp_netns
            )
            self.dhcp_source.start()
        self.controller.start()
        self.watcher = DBWatcher(self.controller, store, mirror_path=mirror_path)
        self.watcher.start()

        # ------------------------------------------------------ data plane
        self.runner = None
        self._uplink_io = None
        self._uplink_ios = []
        self._dp_thread: Optional[threading.Thread] = None
        self._dp_threads = []
        self._dp_stop = threading.Event()
        self.datapath_errors = 0  # guarded-by: _dp_err_lock
        # N pump threads + the supervisor all count errors: the bare
        # '+=' read-modify-write would drop increments exactly during
        # the uplink incident the counter exists to explain.
        self._dp_err_lock = threading.Lock()
        if uplink:
            if (self.config.datapath_shards or 1) > 1:
                self._start_datapath_sharded(uplink)
            else:
                self._start_datapath(uplink)

        # ----------------------------------------------------- diagnostics
        from .controller.drain import DrainCoordinator
        from .rest.server import AgentRestServer

        # Graceful drain/rejoin (ISSUE 13): `netctl drain` gates CNI
        # ADDs retriably, quiesces the runner, flushes flight/latency
        # forensics; `netctl undrain` rejoins.
        self.drain = DrainCoordinator(
            podmanager=self.podmanager,
            datapath=lambda: self.runner,
            node_name=name,
        )
        self.rest = AgentRestServer(
            node_name=name,
            controller=self.controller,
            dbwatcher=self.watcher,
            ipam=self.ipam,
            nodesync=self.nodesync,
            podmanager=self.podmanager,
            scheduler=self.scheduler,
            tracer=self.runner.tracer if self.runner else None,
            datapath=lambda: self.runner,
            store=self.store,
            # Propagation spans (ISSUE 8): the controller mints one per
            # event; REST serves the ring at /contiv/v1/spans.
            spans=self.controller.spans,
            drain=self.drain,
            host="0.0.0.0" if rest_port else "127.0.0.1",
            port=rest_port,
        )
        self.rest_port = self.rest.start()

        from .cni.rpc import CNIServer

        self.cni = CNIServer(self.podmanager, port=cni_port)
        self.cni_port = self.cni.start()

    # ---------------------------------------------------------- data plane

    def _wire_runner_tables(self, installed_acl, installed_nat) -> None:
        """Wire self.runner (solo or sharded — same contract) to the
        table applicators.  Hook FIRST, then pull whatever the
        renderers have already compiled — a table compiled in between
        fires the hook, so no window exists where a compile is
        dropped.  ``installed_*`` are the southbound-readback accessors
        for the drift-detecting downstream resync: verify()
        fingerprints the runner's RESIDENT tables against the last
        compile (VERDICT r4 #2).  Compile observability (full-vs-delta
        counts, rows/bytes shipped per swap) surfaces via
        runner.inspect() → REST /contiv/v1/inspect → `netctl
        inspect`."""
        self.acl_applicator.on_compiled = \
            lambda t: self.runner.update_tables(acl=t)
        self.nat_applicator.on_compiled = \
            lambda t: self.runner.update_tables(nat=t)
        self.acl_applicator.installed_fn = installed_acl
        self.nat_applicator.installed_fn = installed_nat
        if self.infer_applicator is not None:
            # The inference table rides the same hook contract: compile
            # → atomic swap with last-good rollback, drift-verified by
            # fingerprinting the runner-resident table (ISSUE 14).
            self.infer_applicator.on_compiled = \
                lambda t: self.runner.update_tables(infer=t)
            self.infer_applicator.installed_fn = lambda: self._runner_infer()

        def compile_stats():
            stats = {
                "acl": self.acl_applicator.stats().get("compile", {}),
                "nat": self.nat_applicator.stats().get("compile", {}),
            }
            if self.infer_applicator is not None:
                stats["infer"] = \
                    self.infer_applicator.stats().get("compile", {})
            return stats

        self.runner.compile_stats_fn = compile_stats
        self.runner.update_tables(
            acl=self.policy_renderer.tables, nat=self.nat_renderer.tables,
            infer=self.infer_applicator.tables
            if self.infer_applicator is not None else None,
        )

    def _runner_infer(self):
        """Southbound readback of the RESIDENT inference table (the
        sharded engine's shards all hold the same object after an
        atomic swap — shard 0 speaks for the node)."""
        runner = self.runner
        shards = getattr(runner, "shards", None)
        return shards[0].infer if shards else runner.infer

    def _start_datapath(self, uplink: str) -> None:
        """Attach the native runner loop to a real interface: AF_PACKET
        bursts feed the rx ring, TX rings burst back out (the
        DPDK-uplink analog on kernel sockets)."""
        from .datapath import AfPacketIO, DataplaneRunner, NativeRing, VxlanOverlay
        from .ops.classify import build_rule_tables
        from .ops.nat import build_nat_tables
        from .ops.packets import ip_to_u32
        from .ops.pipeline import make_route_config

        self._uplink_io = AfPacketIO(uplink)
        rx, tx = NativeRing(), NativeRing()
        local, host = NativeRing(), NativeRing()
        node_ip = f"192.168.16.{self.nodesync.node_id}"
        self.runner = DataplaneRunner(
            acl=build_rule_tables([], {}),
            nat=build_nat_tables([]),
            route=make_route_config(self.ipam),
            overlay=VxlanOverlay(
                local_ip=ip_to_u32(node_ip),
                local_node_id=self.nodesync.node_id,
            ),
            source=rx, tx=tx, local=local, host=host,
            batch_size=self.config.batch_size,
            max_vectors=self.config.max_vectors,
            dispatch=self.config.dispatch,
            coalesce=self.config.coalesce,
            coalesce_slo_us=self.config.coalesce_slo_us,
            prewarm=self.config.coalesce_prewarm,
            max_inflight=self.config.max_inflight,
        )
        self._wire_runner_tables(
            installed_acl=lambda: self.runner.acl,
            installed_nat=lambda: self.runner.nat,
        )
        rings = (rx, tx, local, host)

        def loop():
            burst = self.config.batch_size * self.runner.max_vectors
            while not self._dp_stop.is_set():
                try:
                    got = self._uplink_io.rx_into(rings[0], burst)
                    sent = self.runner.poll()
                    # Remote + local + host frames all leave via the
                    # uplink in this single-interface attachment.
                    moved = 0
                    for ring in rings[1:]:
                        moved += self._uplink_io.tx_from(ring, burst)
                except Exception:  # noqa: BLE001 - interface flap etc.
                    with self._dp_err_lock:
                        self.datapath_errors += 1
                    log.exception("datapath loop error (uplink %s); retrying",
                                  uplink)
                    self._dp_stop.wait(1.0)
                    continue
                if not (got or sent or moved):
                    time.sleep(0.0005)  # idle

        self._dp_thread = threading.Thread(target=loop, name="datapath", daemon=True)
        self._dp_thread.start()

    def _start_datapath_sharded(self, uplink: str) -> None:
        """Many-core host ingress (ISSUE 12): N datapath shards, each
        with its own ring arenas and its own PACKET_FANOUT socket on
        the uplink (the kernel spreads frames flow-sticky across the
        group — DPDK RSS on kernel sockets), N per-shard recvmmsg pump
        threads (pinned alongside their shard when an affinity map is
        configured), one supervisor loop driving the ShardedDataplane,
        ONE shared device session state, and ONE global coalesce-SLO
        budget through the governor ledger."""
        import os

        from .datapath import (
            AfPacketIO,
            NativeRing,
            ShardedDataplane,
            VxlanOverlay,
        )
        from .datapath.shards import parse_core_map
        from .ops.classify import build_rule_tables
        from .ops.nat import build_nat_tables
        from .ops.packets import ip_to_u32
        from .ops.pipeline import make_route_config

        n = self.config.datapath_shards
        cores = parse_core_map(self.config.shard_cores, n)
        # One fanout group per agent process: every socket in the group
        # shares the kernel's flow-hash spread on this interface.
        # Group ids are 16-bit per interface and pid-derived ids can
        # collide (pids wrap above 65535): a MODE-mismatched collision
        # fails the first socket's fanout join — retry with perturbed
        # ids before giving up.  (A same-mode collision is silent — the
        # kernel merges the groups — and undetectable from here; the id
        # stays pid-derived so an operator can map group → process.)
        ios = []
        socks = []
        try:
            join_err: Optional[OSError] = None
            for attempt in range(8):
                group = (os.getpid() + attempt * 7919) & 0xFFFF
                try:
                    socks.append(AfPacketIO(uplink, fanout_group=group,
                                            fanout_mode="hash"))
                    break
                except OSError as err:
                    join_err = err
            else:
                raise join_err  # every candidate group id refused
            ios.append(tuple(NativeRing() for _ in range(4)))
            for _ in range(n - 1):
                socks.append(AfPacketIO(uplink, fanout_group=group,
                                        fanout_mode="hash"))
                ios.append(tuple(NativeRing() for _ in range(4)))
            node_ip = f"192.168.16.{self.nodesync.node_id}"
            self.runner = ShardedDataplane(
                acl=build_rule_tables([], {}),
                nat=build_nat_tables([]),
                route=make_route_config(self.ipam),
                overlay=VxlanOverlay(
                    local_ip=ip_to_u32(node_ip),
                    local_node_id=self.nodesync.node_id,
                ),
                shard_ios=ios,
                batch_size=self.config.batch_size,
                max_vectors=self.config.max_vectors,
                dispatch=self.config.dispatch,
                coalesce=self.config.coalesce,
                coalesce_slo_us=self.config.coalesce_slo_us,
                prewarm=self.config.coalesce_prewarm,
                max_inflight=self.config.max_inflight,
                shard_cores=cores,
            )
        except BaseException:
            # Agent.__init__ propagates this, so stop() never runs —
            # the CAP_NET_RAW fanout sockets must not outlive the
            # failed construction (a retrying supervisor re-building
            # the Agent would accumulate leaked fds AND stale
            # fanout-group members on the uplink).
            for s in socks:
                s.close()
            raise
        self._uplink_ios = socks
        # Table hooks: identical contract to the solo path — the
        # sharded engine's update_tables fans the swap out atomically.
        self._wire_runner_tables(
            installed_acl=lambda: self.runner.shards[0].acl,
            installed_nat=lambda: self.runner.shards[0].nat,
        )
        burst = self.config.batch_size * self.config.max_vectors

        def pump(i: int) -> None:
            # The ingest/egress pump for shard i's fanout socket: pin
            # beside the shard's worker so the rx-arena writes stay
            # core-local to its admit (first-touch locality).
            if cores and cores[i]:
                try:
                    os.sched_setaffinity(0, cores[i])
                except OSError:
                    pass
            rings = ios[i]
            sock = socks[i]
            while not self._dp_stop.is_set():
                try:
                    got = sock.rx_into(rings[0], burst)
                    moved = 0
                    for ring in rings[1:]:
                        moved += sock.tx_from(ring, burst)
                except Exception:  # noqa: BLE001 - interface flap etc.
                    with self._dp_err_lock:
                        self.datapath_errors += 1
                    log.exception(
                        "datapath pump %d error (uplink %s); retrying",
                        i, uplink)
                    self._dp_stop.wait(1.0)
                    continue
                if not (got or moved):
                    time.sleep(0.0005)  # idle

        def supervise() -> None:
            while not self._dp_stop.is_set():
                try:
                    sent = self.runner.poll()
                except Exception:  # noqa: BLE001 - supervisor must survive
                    with self._dp_err_lock:
                        self.datapath_errors += 1
                    log.exception("sharded datapath poll error; retrying")
                    self._dp_stop.wait(1.0)
                    continue
                if not sent:
                    time.sleep(0.0005)

        self._dp_threads = [
            threading.Thread(target=pump, args=(i,),
                             name=f"dp-pump-{i}", daemon=True)
            for i in range(n)
        ]
        self._dp_threads.append(
            threading.Thread(target=supervise, name="dp-supervisor",
                             daemon=True))
        for t in self._dp_threads:
            t.start()

    # ----------------------------------------------------------- lifecycle

    def stop(self) -> None:
        self._dp_stop.set()
        if self._dp_thread is not None:
            self._dp_thread.join(timeout=2)
        for t in self._dp_threads:
            t.join(timeout=2)
        if self._uplink_io is not None:
            self._uplink_io.close()
        for sock in self._uplink_ios:
            sock.close()
        if self.runner is not None and hasattr(self.runner, "close"):
            self.runner.close()
        if self.route_source is not None:
            self.route_source.close()
        if self.dhcp_source is not None:
            self.dhcp_source.stop()
        self.cni.stop()
        self.rest.stop()
        self.watcher.stop()
        self.controller.stop()
        if self.hostnet is not None:
            self.hostnet.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="TPU-native vswitch agent (contiv-vswitch analog)"
    )
    parser.add_argument("--store", required=True, help="host:port of the cluster store")
    parser.add_argument("--name", required=True, help="node name")
    parser.add_argument("--config", default="", help="path to the JSON network "
                        "config (contiv.conf analog; NetworkConfig.from_dict shape)")
    parser.add_argument("--mirror", default="", help="sqlite mirror path (Bolt analog)")
    parser.add_argument("--hostnet", default="off",
                        help="off | root | netns:<name> — where to program "
                             "real kernel networking")
    parser.add_argument("--rest-port", type=int, default=9999)
    parser.add_argument("--cni-port", type=int, default=9111)
    parser.add_argument("--uplink", default="",
                        help="attach the native datapath loop to this interface "
                             "via AF_PACKET (the DPDK-uplink analog)")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )

    from .conf import NetworkConfig
    from .kvstore.remote import RemoteKVStore

    config = NetworkConfig()
    if args.config:
        with open(args.config) as fh:
            config = NetworkConfig.from_dict(json.load(fh))

    store = RemoteKVStore(args.store)
    agent = Agent(
        store, args.name, config=config,
        mirror_path=args.mirror or None,
        hostnet=args.hostnet,
        rest_port=args.rest_port,
        cni_port=args.cni_port,
        uplink=args.uplink,
    )
    print(json.dumps({
        "agent": args.name,
        "node_id": agent.nodesync.node_id,
        "store": args.store,
        "rest_port": agent.rest_port,
        "cni_port": agent.cni_port,
    }), flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        while not stop.is_set():
            stop.wait(1.0)
    finally:
        agent.stop()
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
