"""Host shim — native packet-batch assembly for the TPU pipeline."""

from .hostshim import HostShim, FrameBatch

__all__ = ["HostShim", "FrameBatch"]
