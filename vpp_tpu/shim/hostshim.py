"""ctypes binding + batch plumbing for the native host shim.

The analog of the reference's GoVPP/DPDK transport boundary (SURVEY.md
§2.3): ``HostShim.parse`` turns raw Ethernet frames into the
fixed-shape :class:`PacketBatch` the jit pipeline consumes (padded to
the 256-packet vector size), and ``HostShim.apply`` writes the
pipeline's verdicts + NAT rewrites back into the frames with
incremental checksum updates — all per-byte work in C++.

The shared library is built on demand from ``native/hostshim`` with the
baked-in g++ toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..ops.packets import PacketBatch, VECTOR_SIZE

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC_DIR = os.path.join(_NATIVE_DIR, "hostshim")
_SOURCES = ("hostshim.cpp", "runnerloop.cpp", "common.h")
_LIB = os.path.join(_NATIVE_DIR, "build", "libhostshim.so")


def _build_library() -> str:
    # Explicit flavor override: `make native-sanitize` points this at
    # the ASan+UBSan build (libhostshim.asan.so) so the native-engine
    # test subset runs sanitizer-hardened without touching the
    # production artifact.
    override = os.environ.get("VPP_TPU_HOSTSHIM_LIB")
    if override:
        if not os.path.exists(override):
            raise FileNotFoundError(
                f"VPP_TPU_HOSTSHIM_LIB={override} does not exist "
                "(build it with: make -C native/hostshim SANITIZE=asan)")
        return override
    src_dir = os.path.abspath(_SRC_DIR)
    lib = os.path.abspath(_LIB)
    sources = [os.path.join(src_dir, s) for s in _SOURCES]
    if not all(os.path.exists(s) for s in sources):
        # Prebuilt deployment (container images ship only the .so).
        if os.path.exists(lib):
            return lib
        raise FileNotFoundError(f"{lib} missing and sources not present to build it")
    newest = max(os.path.getmtime(s) for s in sources)
    if not os.path.exists(lib) or os.path.getmtime(lib) < newest:
        subprocess.run(
            ["make", "-s", "-C", src_dir],
            check=True,
            capture_output=True,
        )
    return lib


_u8p = ctypes.POINTER(ctypes.c_uint8)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_i32p = ctypes.POINTER(ctypes.c_int32)


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(_build_library())
    lib.hs_parse_batch.restype = ctypes.c_int32
    lib.hs_parse_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u32p, _u32p, _i32p, _i32p, _i32p, _u8p,
    ]
    lib.hs_apply_batch.restype = ctypes.c_int32
    lib.hs_apply_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u8p, _u32p, _u32p, _i32p, _i32p, _u8p,
    ]
    lib.hs_vxlan_encap_batch.restype = ctypes.c_int32
    lib.hs_vxlan_encap_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u8p, _u8p, _i32p,
        _u32p, ctypes.c_int32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
        _u8p, ctypes.c_uint64, _u64p, _u32p, _i32p, _i32p,
    ]
    lib.hs_vxlan_decap_batch.restype = ctypes.c_int32
    lib.hs_vxlan_decap_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u64p, _u32p, _i32p,
    ]
    # --- native runner loop (runnerloop.cpp) ---
    lib.hs_ring_new.restype = ctypes.c_void_p
    lib.hs_ring_new.argtypes = [ctypes.c_uint64, ctypes.c_uint32]
    lib.hs_ring_free.argtypes = [ctypes.c_void_p]
    lib.hs_ring_count.restype = ctypes.c_uint32
    lib.hs_ring_count.argtypes = [ctypes.c_void_p]
    lib.hs_ring_dropped.restype = ctypes.c_uint64
    lib.hs_ring_dropped.argtypes = [ctypes.c_void_p]
    lib.hs_ring_push.restype = ctypes.c_int32
    lib.hs_ring_push.argtypes = [
        ctypes.c_void_p, _u8p, _u64p, _u32p, ctypes.c_int32,
    ]
    lib.hs_ring_pop.restype = ctypes.c_int32
    lib.hs_ring_pop.argtypes = [
        ctypes.c_void_p, _u8p, ctypes.c_uint64, _u64p, _u32p, ctypes.c_int32,
    ]
    lib.hs_loop_new.restype = ctypes.c_void_p
    lib.hs_loop_new.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
    ]
    lib.hs_loop_free.argtypes = [ctypes.c_void_p]
    lib.hs_loop_release_all.argtypes = [ctypes.c_void_p]
    lib.hs_loop_admit.restype = ctypes.c_int32
    lib.hs_loop_admit.argtypes = [
        ctypes.c_void_p, ctypes.c_int32,
        _u32p, _u32p, _i32p, _i32p, _i32p, _i32p, _u64p, ctypes.c_int32,
    ]
    lib.hs_loop_harvest.restype = ctypes.c_int32
    lib.hs_loop_harvest.argtypes = [
        ctypes.c_void_p, ctypes.c_int32,
        _u8p, _u32p, _u32p, _i32p, _i32p, _i32p, _i32p,
        _u32p, ctypes.c_int32, ctypes.c_uint32, ctypes.c_uint32, _u64p,
    ]
    lib.hs_loop_slot_frame.restype = ctypes.c_int32
    lib.hs_loop_slot_frame.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, _u8p, ctypes.c_uint32,
    ]
    lib.hs_loop_hostpath.restype = ctypes.c_int32
    lib.hs_loop_hostpath.argtypes = [
        ctypes.c_void_p, ctypes.c_int32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_uint32, _u32p, ctypes.c_int32,
        ctypes.c_uint32, ctypes.c_uint32, _u64p, _u64p,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.hs_loop_hostpath_drain.restype = ctypes.c_int32
    lib.hs_loop_hostpath_drain.argtypes = list(lib.hs_loop_hostpath.argtypes)
    lib.hs_afp_rx.restype = ctypes.c_int32
    lib.hs_afp_rx.argtypes = [ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32]
    lib.hs_afp_tx.restype = ctypes.c_int32
    lib.hs_afp_tx.argtypes = [ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32]
    lib.hs_fanout_push.restype = ctypes.c_int32
    lib.hs_fanout_push.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32,
        _u8p, _u64p, _u32p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.hs_afp_rx_fanout.restype = ctypes.c_int32
    lib.hs_afp_rx_fanout.argtypes = [
        ctypes.c_int32, ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
    ]
    return lib


_shared: Optional[ctypes.CDLL] = None


def _shared_lib() -> ctypes.CDLL:
    global _shared
    if _shared is None:
        _shared = _load()
    return _shared


class NativeRing:
    """C++ frame ring: contiguous byte arena + (offset, len) FIFO.

    The native replacement of InMemoryRing (VERDICT r2 item 1): frames
    cross Python only as buffer views, never per-frame ``bytes``.  The
    bytes-based ``send``/``recv_batch`` remain for tests and non-hot
    callers; the native loop and AF_PACKET burst IO never touch them.
    Thread-safe (mutex in C++), full-ring drops are counted like the
    Python ring's.
    """

    # send() ENQUEUES for ingest (unlike AfPacketIO.send, which
    # transmits raw on the wire): the shard supervisor may steer an
    # ejected shard's frames into this source.
    can_enqueue = True

    def __init__(self, arena_bytes: int = 8 << 20, max_frames: int = 1 << 16):
        self._lib = _shared_lib()
        self._ptr = self._lib.hs_ring_new(arena_bytes, max_frames)
        if not self._ptr:
            raise MemoryError("hs_ring_new failed")
        self._arena_bytes = arena_bytes
        self._max_frames = max_frames
        self._pop_buf = None  # allocated on first recv (sinks never pay)
        self._pop_off = None
        self._pop_len = None

    def __len__(self) -> int:
        return int(self._lib.hs_ring_count(self._ptr))

    def backlog_hint(self) -> int:
        """Queued frame count — the coalesce governor's ingress depth
        probe (one C call, no lock contention beyond the ring mutex)."""
        return len(self)

    @property
    def dropped(self) -> int:
        return int(self._lib.hs_ring_dropped(self._ptr))

    # ------------------------------------------------------------ view API

    def send_views(self, buf: np.ndarray, offsets: np.ndarray,
                   lens: np.ndarray) -> int:
        """Push frames described by (offsets, lens) views into buf."""
        n = len(offsets)
        if not n:
            return 0
        offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
        lens = np.ascontiguousarray(lens, dtype=np.uint32)
        return int(self._lib.hs_ring_push(
            self._ptr, buf.ctypes.data_as(_u8p),
            offsets.ctypes.data_as(_u64p), lens.ctypes.data_as(_u32p), n,
        ))

    def recv_views(self, max_frames: int):
        """Pop up to max_frames into the reusable pop buffer; returns
        (buf, offsets, lens) — views valid until the next recv call."""
        if self._pop_buf is None:
            self._pop_buf = np.empty(self._arena_bytes, dtype=np.uint8)
            self._pop_off = np.empty(self._max_frames, dtype=np.uint64)
            self._pop_len = np.empty(self._max_frames, dtype=np.uint32)
        want = min(max_frames, self._max_frames)
        n = int(self._lib.hs_ring_pop(
            self._ptr, self._pop_buf.ctypes.data_as(_u8p),
            self._pop_buf.size, self._pop_off.ctypes.data_as(_u64p),
            self._pop_len.ctypes.data_as(_u32p), want,
        ))
        if n < 0:
            raise RuntimeError(
                "ring has frames pinned by an in-flight zero-copy batch; "
                "harvest it before popping"
            )
        return self._pop_buf, self._pop_off[:n], self._pop_len[:n]

    # ----------------------------------------------------- bytes-compat API

    def send(self, frames) -> None:
        if not frames:
            return
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(len(frames), dtype=np.uint64)
        np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(b"".join(frames), dtype=np.uint8)
        self.send_views(buf, offsets, lens)

    def recv_batch(self, max_frames: int) -> List[bytes]:
        buf, off, lens = self.recv_views(max_frames)
        return [
            buf[int(off[i]):int(off[i]) + int(lens[i])].tobytes()
            for i in range(len(off))
        ]

    def close(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.hs_ring_free(ptr)

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close()
        except Exception:
            pass


class NativeLoop:
    """The C++ admit/harvest engine behind DataplaneRunner.

    One ``admit`` call reads a batch from the rx ring ZERO-COPY (the
    frames stay pinned in the ring arena), VXLAN-declassifies and
    VNI-filters it, and parses the kept frames once into preallocated
    SoA header arrays, caching the IP/L4 offsets; one ``harvest`` call
    applies verdicts/rewrites in place against those cached offsets,
    encapsulates ROUTE_REMOTE frames from a header template, routes
    everything to the TX rings, and releases the batch's arena pin
    (strictly FIFO across in-flight batches).  Python in between only
    dispatches the jit pipeline and services punts.
    """

    ADMIT_COUNTERS = 3    # rx_frames, rx_decapped, dropped_foreign_vni
    HARVEST_COUNTERS = 6  # tx_remote, tx_local, tx_host, denied,
                          # unparseable, unroutable

    def __init__(self, rx: NativeRing, tx_remote: NativeRing,
                 tx_local: NativeRing, tx_host: NativeRing,
                 batch_size: int, max_vectors: int, vni: int, n_slots: int):
        self._lib = _shared_lib()
        self._rings = (rx, tx_remote, tx_local, tx_host)  # keep alive
        self._ptr = self._lib.hs_loop_new(
            rx._ptr, tx_remote._ptr, tx_local._ptr, tx_host._ptr,
            batch_size, max_vectors, vni, n_slots,
        )
        if not self._ptr:
            raise MemoryError("hs_loop_new failed")
        cap = batch_size * max_vectors
        self._soa = [
            {
                "src_ip": np.zeros(cap, dtype=np.uint32),
                "dst_ip": np.zeros(cap, dtype=np.uint32),
                "protocol": np.zeros(cap, dtype=np.int32),
                "src_port": np.zeros(cap, dtype=np.int32),
                "dst_port": np.zeros(cap, dtype=np.int32),
            }
            for _ in range(n_slots)
        ]

    def admit(self, slot: int, counters: np.ndarray, k_cap: int = 0):
        """Returns (n_kept, k, soa_dict); counters (uint64[3]) += deltas.
        ``k_cap`` (pow2, 0 = uncapped) is the coalesce governor's
        per-admit vector cap: the ring read budget and the pow2 bucket
        are both bounded by it, leaving excess backlog queued for the
        next in-flight slot."""
        soa = self._soa[slot]
        k = ctypes.c_int32(0)
        n = int(self._lib.hs_loop_admit(
            self._ptr, slot,
            soa["src_ip"].ctypes.data_as(_u32p),
            soa["dst_ip"].ctypes.data_as(_u32p),
            soa["protocol"].ctypes.data_as(_i32p),
            soa["src_port"].ctypes.data_as(_i32p),
            soa["dst_port"].ctypes.data_as(_i32p),
            ctypes.byref(k),
            counters.ctypes.data_as(_u64p),
            ctypes.c_int32(k_cap),
        ))
        if n < 0:
            raise RuntimeError(f"slot {slot} is still in flight (unharvested)")
        return n, int(k.value), soa

    def harvest(self, slot: int, allowed: np.ndarray, new_src: np.ndarray,
                new_dst: np.ndarray, new_sport: np.ndarray,
                new_dport: np.ndarray, route_tag: np.ndarray,
                node_id: np.ndarray, remote_ips: np.ndarray, local_ip: int,
                local_node_id: int, counters: np.ndarray) -> int:
        remote_ips = np.ascontiguousarray(remote_ips, dtype=np.uint32)
        sent = int(self._lib.hs_loop_harvest(
            self._ptr, slot,
            np.ascontiguousarray(allowed, dtype=np.uint8).ctypes.data_as(_u8p),
            np.ascontiguousarray(new_src, dtype=np.uint32).ctypes.data_as(_u32p),
            np.ascontiguousarray(new_dst, dtype=np.uint32).ctypes.data_as(_u32p),
            np.ascontiguousarray(new_sport, dtype=np.int32).ctypes.data_as(_i32p),
            np.ascontiguousarray(new_dport, dtype=np.int32).ctypes.data_as(_i32p),
            np.ascontiguousarray(route_tag, dtype=np.int32).ctypes.data_as(_i32p),
            np.ascontiguousarray(node_id, dtype=np.int32).ctypes.data_as(_i32p),
            remote_ips.ctypes.data_as(_u32p),
            len(remote_ips) - 1,
            ctypes.c_uint32(local_ip), ctypes.c_uint32(local_node_id),
            counters.ctypes.data_as(_u64p),
        ))
        if sent < 0:
            raise RuntimeError(
                f"slot {slot} harvested out of admit order (batches "
                "release their arena pins FIFO)"
            )
        return sent

    def hostpath(self, slot: int, pod_base: int, pod_mask: int,
                 node_base: int, node_mask: int, host_bits: int,
                 remote_ips: np.ndarray, local_ip: int, local_node_id: int,
                 admit_counters: np.ndarray,
                 harvest_counters: np.ndarray) -> tuple:
        """Fused HOST-BYPASS batch — admit, subnet route classify, and
        harvest in one native call (no device dispatch, no FFI between
        phases).  Only valid when the datapath's tables are trivially
        permissive: every frame is forwarded unrewritten on subnet
        routing alone.  Returns ``(n_admitted, sent)``."""
        remote_ips = np.ascontiguousarray(remote_ips, dtype=np.uint32)
        sent = ctypes.c_int32(0)
        n = int(self._lib.hs_loop_hostpath(
            self._ptr, slot,
            ctypes.c_uint32(pod_base), ctypes.c_uint32(pod_mask),
            ctypes.c_uint32(node_base), ctypes.c_uint32(node_mask),
            ctypes.c_uint32(host_bits),
            remote_ips.ctypes.data_as(_u32p), len(remote_ips) - 1,
            ctypes.c_uint32(local_ip), ctypes.c_uint32(local_node_id),
            admit_counters.ctypes.data_as(_u64p),
            harvest_counters.ctypes.data_as(_u64p),
            ctypes.byref(sent),
        ))
        if n < 0:
            raise RuntimeError(f"slot {slot} is still in flight (unharvested)")
        return n, int(sent.value)

    def hostpath_drain(self, slot: int, pod_base: int, pod_mask: int,
                       node_base: int, node_mask: int, host_bits: int,
                       remote_ips: np.ndarray, local_ip: int,
                       local_node_id: int, admit_counters: np.ndarray,
                       harvest_counters: np.ndarray) -> tuple:
        """Like :meth:`hostpath` but loops until the rx ring is EMPTY
        inside one native call — the many-core front end's per-wakeup
        shape (ISSUE 12): N shard workers each cross the FFI/GIL
        boundary once per wakeup instead of once per batch, so the
        crossings cannot serialise the very work the shards
        parallelise.  Returns ``(n_admitted_total, sent_total)``."""
        remote_ips = np.ascontiguousarray(remote_ips, dtype=np.uint32)
        sent = ctypes.c_int32(0)
        n = int(self._lib.hs_loop_hostpath_drain(
            self._ptr, slot,
            ctypes.c_uint32(pod_base), ctypes.c_uint32(pod_mask),
            ctypes.c_uint32(node_base), ctypes.c_uint32(node_mask),
            ctypes.c_uint32(host_bits),
            remote_ips.ctypes.data_as(_u32p), len(remote_ips) - 1,
            ctypes.c_uint32(local_ip), ctypes.c_uint32(local_node_id),
            admit_counters.ctypes.data_as(_u64p),
            harvest_counters.ctypes.data_as(_u64p),
            ctypes.byref(sent),
        ))
        if n < 0:
            raise RuntimeError(f"slot {slot} is still in flight (unharvested)")
        return n, int(sent.value)

    def slot_frame(self, slot: int, row: int) -> bytes:
        """Copy one admitted frame back out (slow path / tracing only)."""
        out = np.empty(1 << 16, dtype=np.uint8)
        n = int(self._lib.hs_loop_slot_frame(
            self._ptr, slot, row, out.ctypes.data_as(_u8p), out.size,
        ))
        if n < 0:
            raise IndexError(f"slot {slot} row {row}")
        return out[:n].tobytes()

    def close(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            # Unpin any in-flight batches first — but only while the RX
            # ring (the only one release_all dereferences) is still open
            # (GC may finalise rings before the loop when breaking
            # reference cycles; touching a freed ring from C++ would be
            # use-after-free).
            if self._rings[0]._ptr:
                self._lib.hs_loop_release_all(ptr)
            self._lib.hs_loop_free(ptr)

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close()
        except Exception:
            pass


class FanoutHandoff:
    """Single-feeder fanout across N shard rings (ISSUE 12).

    The many-core ingest handoff: ONE writer (recvmmsg pump, virtual
    wire, bench feeder) spreads a frame stream across the per-shard
    ``NativeRing`` arenas in one C call — symmetric flow hash by
    default (a flow's forward and reply land on the same shard, the
    PACKET_FANOUT_HASH cache-locality property) or round-robin.  Each
    shard ring stays single-writer (the feeder) + single-reader (that
    shard's admit thread), so N admit threads never contend on one
    ring head; cross-thread contention is pairwise on each ring's own
    mutex, with ONE lock hold per target ring per call.
    """

    MODES = {"hash": 0, "rr": 1}

    def __init__(self, rings: Sequence[NativeRing], mode: str = "hash"):
        if not rings:
            raise ValueError("need at least one shard ring")
        if mode not in self.MODES:
            raise ValueError(f"unknown fanout mode {mode!r}")
        self._lib = _shared_lib()
        self._rings = tuple(rings)  # keep alive: C holds raw pointers
        self.mode = mode
        self._mode_i = self.MODES[mode]
        self._ptrs = (ctypes.c_void_p * len(rings))(
            *(r._ptr for r in rings))

    def __len__(self) -> int:
        return len(self._rings)

    def send_views(self, buf: np.ndarray, offsets: np.ndarray,
                   lens: np.ndarray) -> int:
        """Distribute frames described by (offsets, lens) views into
        buf across the shard rings; returns frames accepted."""
        n = len(offsets)
        if not n:
            return 0
        offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
        lens = np.ascontiguousarray(lens, dtype=np.uint32)
        return int(self._lib.hs_fanout_push(
            self._ptrs, len(self._rings), buf.ctypes.data_as(_u8p),
            offsets.ctypes.data_as(_u64p), lens.ctypes.data_as(_u32p),
            n, self._mode_i,
        ))

    def send(self, frames: Sequence[bytes]) -> int:
        """bytes-compat feeder (tests / steering / virtual wires)."""
        if not frames:
            return 0
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(len(frames), dtype=np.uint64)
        np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(b"".join(frames), dtype=np.uint8)
        return self.send_views(buf, offsets, lens)

    def rx_from(self, fd: int, max_frames: int = 1 << 12) -> int:
        """Burst-receive from an AF_PACKET socket and fan out across
        the shard rings in the same native call (recvmmsg → hash
        distribute; the single-uplink-socket ingest shape when kernel
        PACKET_FANOUT is unavailable)."""
        return int(self._lib.hs_afp_rx_fanout(
            fd, self._ptrs, len(self._rings), max_frames, self._mode_i,
        ))


def afp_rx_ring(fd: int, ring: NativeRing, max_frames: int) -> int:
    """Burst-receive from an AF_PACKET socket into a ring (recvmmsg)."""
    return int(_shared_lib().hs_afp_rx(fd, ring._ptr, max_frames))


def afp_tx_ring(fd: int, ring: NativeRing, max_frames: int) -> int:
    """Burst-transmit from a ring out of an AF_PACKET socket (sendmmsg)."""
    return int(_shared_lib().hs_afp_tx(fd, ring._ptr, max_frames))


@dataclass
class FrameBatch:
    """Frames packed into one contiguous buffer + parsed header SoA."""

    buf: np.ndarray        # uint8 [total_bytes]
    offsets: np.ndarray    # uint64 [n]
    lens: np.ndarray       # uint32 [n]
    flags: np.ndarray      # uint8 [n]: bit0 IPv4, bit1 ports
    batch: PacketBatch     # padded to VECTOR_SIZE multiples
    n: int

    def frame(self, i: int) -> bytes:
        off, ln = int(self.offsets[i]), int(self.lens[i])
        return self.buf[off:off + ln].tobytes()


class HostShim:
    """The packet-batch assembler/applier."""

    def __init__(self):
        self._lib = _load()

    # --------------------------------------------------------------- parse

    def parse(self, frames: Sequence[bytes],
              pad_to: Optional[int] = VECTOR_SIZE) -> FrameBatch:
        """Parse raw frames into a (padded) PacketBatch."""
        n = len(frames)
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(n, dtype=np.uint64)
        if n:
            np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(b"".join(frames), dtype=np.uint8).copy()
        return self.parse_view(buf, offsets, lens, pad_to=pad_to)

    def parse_view(
        self,
        buf: np.ndarray,
        offsets: np.ndarray,
        lens: np.ndarray,
        pad_to: Optional[int] = VECTOR_SIZE,
    ) -> FrameBatch:
        """Parse frames already packed in one buffer (zero extra copies
        — the decap path hands its adjusted offsets straight in here)."""
        n = len(offsets)
        offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
        lens = np.ascontiguousarray(lens, dtype=np.uint32)

        size = n
        if pad_to:
            size = max(pad_to, ((n + pad_to - 1) // pad_to) * pad_to)
        src_ip = np.zeros(size, dtype=np.uint32)
        dst_ip = np.zeros(size, dtype=np.uint32)
        protocol = np.zeros(size, dtype=np.int32)
        src_port = np.zeros(size, dtype=np.int32)
        dst_port = np.zeros(size, dtype=np.int32)
        flags = np.zeros(n, dtype=np.uint8)

        if n:
            self._lib.hs_parse_batch(
                buf.ctypes.data_as(_u8p),
                offsets.ctypes.data_as(_u64p),
                lens.ctypes.data_as(_u32p),
                n,
                src_ip.ctypes.data_as(_u32p),
                dst_ip.ctypes.data_as(_u32p),
                protocol.ctypes.data_as(_i32p),
                src_port.ctypes.data_as(_i32p),
                dst_port.ctypes.data_as(_i32p),
                flags.ctypes.data_as(_u8p),
            )
        batch = PacketBatch(
            src_ip=src_ip, dst_ip=dst_ip, protocol=protocol,
            src_port=src_port, dst_port=dst_port,
        )
        return FrameBatch(buf=buf, offsets=offsets, lens=lens,
                          flags=flags, batch=batch, n=n)

    # --------------------------------------------------------------- apply

    def apply(self, fb: FrameBatch, allowed, rewritten: PacketBatch) -> List[bytes]:
        """Apply pipeline verdicts + rewrites; returns forwarded frames."""
        fwd = self.apply_masked(fb, allowed, rewritten)
        return [fb.frame(i) for i in range(fb.n) if fwd[i]]

    def apply_masked(self, fb: FrameBatch, allowed, rewritten: PacketBatch) -> np.ndarray:
        """Like :meth:`apply` but returns the forwarded mask instead of
        materialising frame copies (the runner splits by route next)."""
        n = fb.n
        allowed = np.ascontiguousarray(np.asarray(allowed).astype(np.uint8)[:n])
        new_src = np.ascontiguousarray(np.asarray(rewritten.src_ip).astype(np.uint32)[:n])
        new_dst = np.ascontiguousarray(np.asarray(rewritten.dst_ip).astype(np.uint32)[:n])
        new_sport = np.ascontiguousarray(np.asarray(rewritten.src_port).astype(np.int32)[:n])
        new_dport = np.ascontiguousarray(np.asarray(rewritten.dst_port).astype(np.int32)[:n])
        fwd = np.zeros(n, dtype=np.uint8)
        if n:
            self._lib.hs_apply_batch(
                fb.buf.ctypes.data_as(_u8p),
                fb.offsets.ctypes.data_as(_u64p),
                fb.lens.ctypes.data_as(_u32p),
                n,
                allowed.ctypes.data_as(_u8p),
                new_src.ctypes.data_as(_u32p),
                new_dst.ctypes.data_as(_u32p),
                new_sport.ctypes.data_as(_i32p),
                new_dport.ctypes.data_as(_i32p),
                fwd.ctypes.data_as(_u8p),
            )
        return fwd

    # --------------------------------------------------------------- vxlan

    def vxlan_encap(
        self,
        fb: FrameBatch,
        fwd: np.ndarray,
        is_remote: np.ndarray,
        node_ids: np.ndarray,
        remote_ips: np.ndarray,
        local_ip: int,
        local_node_id: int,
        vni: int = 10,
    ):
        """Encap forwarded ROUTE_REMOTE frames for the overlay.

        ``remote_ips`` is indexed by node ID (0 = unknown).  Returns
        ``(out_buf, out_offsets, out_lens, out_rows, unroutable)`` where
        ``out_rows[j]`` is the batch row the j-th encapped frame came
        from.  Mirrors the reference's per-node VXLAN tunnels
        (plugins/ipv4net/node.go vxlanIfToOtherNode :524).
        """
        n = fb.n
        fwd = np.ascontiguousarray(fwd.astype(np.uint8)[:n])
        is_remote = np.ascontiguousarray(is_remote.astype(np.uint8)[:n])
        node_ids = np.ascontiguousarray(node_ids.astype(np.int32)[:n])
        remote_ips = np.ascontiguousarray(remote_ips.astype(np.uint32))
        out_cap = int(fb.buf.size + 50 * max(n, 1))
        out_buf = np.empty(out_cap, dtype=np.uint8)
        out_offsets = np.zeros(max(n, 1), dtype=np.uint64)
        out_lens = np.zeros(max(n, 1), dtype=np.uint32)
        out_rows = np.zeros(max(n, 1), dtype=np.int32)
        unroutable = ctypes.c_int32(0)
        count = 0
        if n:
            count = self._lib.hs_vxlan_encap_batch(
                fb.buf.ctypes.data_as(_u8p),
                fb.offsets.ctypes.data_as(_u64p),
                fb.lens.ctypes.data_as(_u32p),
                n,
                fwd.ctypes.data_as(_u8p),
                is_remote.ctypes.data_as(_u8p),
                node_ids.ctypes.data_as(_i32p),
                remote_ips.ctypes.data_as(_u32p),
                len(remote_ips) - 1,
                ctypes.c_uint32(local_ip),
                ctypes.c_uint32(local_node_id),
                ctypes.c_uint32(vni),
                out_buf.ctypes.data_as(_u8p),
                ctypes.c_uint64(out_cap),
                out_offsets.ctypes.data_as(_u64p),
                out_lens.ctypes.data_as(_u32p),
                out_rows.ctypes.data_as(_i32p),
                ctypes.byref(unroutable),
            )
            if count < 0:
                raise RuntimeError("vxlan encap output buffer overflow")
        return (
            out_buf, out_offsets[:count], out_lens[:count],
            out_rows[:count], int(unroutable.value),
        )

    def vxlan_decap_view(
        self, buf: np.ndarray, offsets: np.ndarray, lens: np.ndarray
    ):
        """De-encapsulate in place: returns ``(inner_offsets,
        inner_lens, vnis)`` describing the inner frames *within the same
        buffer* (offset math only, zero copies); non-VXLAN frames pass
        through with vni -1."""
        n = len(offsets)
        offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
        lens = np.ascontiguousarray(lens, dtype=np.uint32)
        inner_off = np.zeros(n, dtype=np.uint64)
        inner_len = np.zeros(n, dtype=np.uint32)
        vnis = np.zeros(n, dtype=np.int32)
        if n:
            self._lib.hs_vxlan_decap_batch(
                buf.ctypes.data_as(_u8p),
                offsets.ctypes.data_as(_u64p),
                lens.ctypes.data_as(_u32p),
                n,
                inner_off.ctypes.data_as(_u64p),
                inner_len.ctypes.data_as(_u32p),
                vnis.ctypes.data_as(_i32p),
            )
        return inner_off, inner_len, vnis

    def vxlan_decap(self, frames: Sequence[bytes]):
        """Convenience wrapper over :meth:`vxlan_decap_view` returning
        materialised inner frames (tests / non-hot-path callers)."""
        n = len(frames)
        if not n:
            return [], []
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(n, dtype=np.uint64)
        np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(b"".join(frames), dtype=np.uint8).copy()
        inner_off, inner_len, vnis = self.vxlan_decap_view(buf, offsets, lens)
        out = [
            buf[int(inner_off[i]):int(inner_off[i]) + int(inner_len[i])].tobytes()
            for i in range(n)
        ]
        return out, vnis.tolist()
