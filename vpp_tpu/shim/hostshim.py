"""ctypes binding + batch plumbing for the native host shim.

The analog of the reference's GoVPP/DPDK transport boundary (SURVEY.md
§2.3): ``HostShim.parse`` turns raw Ethernet frames into the
fixed-shape :class:`PacketBatch` the jit pipeline consumes (padded to
the 256-packet vector size), and ``HostShim.apply`` writes the
pipeline's verdicts + NAT rewrites back into the frames with
incremental checksum updates — all per-byte work in C++.

The shared library is built on demand from ``native/hostshim`` with the
baked-in g++ toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..ops.packets import PacketBatch, VECTOR_SIZE

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.join(_NATIVE_DIR, "hostshim", "hostshim.cpp")
_LIB = os.path.join(_NATIVE_DIR, "build", "libhostshim.so")


def _build_library() -> str:
    src = os.path.abspath(_SRC)
    lib = os.path.abspath(_LIB)
    if not os.path.exists(lib) or os.path.getmtime(lib) < os.path.getmtime(src):
        subprocess.run(
            ["make", "-s", "-C", os.path.dirname(src)],
            check=True,
            capture_output=True,
        )
    return lib


_u8p = ctypes.POINTER(ctypes.c_uint8)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_i32p = ctypes.POINTER(ctypes.c_int32)


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(_build_library())
    lib.hs_parse_batch.restype = ctypes.c_int32
    lib.hs_parse_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u32p, _u32p, _i32p, _i32p, _i32p, _u8p,
    ]
    lib.hs_apply_batch.restype = ctypes.c_int32
    lib.hs_apply_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u8p, _u32p, _u32p, _i32p, _i32p, _u8p,
    ]
    lib.hs_vxlan_encap_batch.restype = ctypes.c_int32
    lib.hs_vxlan_encap_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u8p, _u8p, _i32p,
        _u32p, ctypes.c_int32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
        _u8p, ctypes.c_uint64, _u64p, _u32p, _i32p, _i32p,
    ]
    lib.hs_vxlan_decap_batch.restype = ctypes.c_int32
    lib.hs_vxlan_decap_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u64p, _u32p, _i32p,
    ]
    return lib


@dataclass
class FrameBatch:
    """Frames packed into one contiguous buffer + parsed header SoA."""

    buf: np.ndarray        # uint8 [total_bytes]
    offsets: np.ndarray    # uint64 [n]
    lens: np.ndarray       # uint32 [n]
    flags: np.ndarray      # uint8 [n]: bit0 IPv4, bit1 ports
    batch: PacketBatch     # padded to VECTOR_SIZE multiples
    n: int

    def frame(self, i: int) -> bytes:
        off, ln = int(self.offsets[i]), int(self.lens[i])
        return self.buf[off:off + ln].tobytes()


class HostShim:
    """The packet-batch assembler/applier."""

    def __init__(self):
        self._lib = _load()

    # --------------------------------------------------------------- parse

    def parse(self, frames: Sequence[bytes],
              pad_to: Optional[int] = VECTOR_SIZE) -> FrameBatch:
        """Parse raw frames into a (padded) PacketBatch."""
        n = len(frames)
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(n, dtype=np.uint64)
        if n:
            np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(b"".join(frames), dtype=np.uint8).copy()
        return self.parse_view(buf, offsets, lens, pad_to=pad_to)

    def parse_view(
        self,
        buf: np.ndarray,
        offsets: np.ndarray,
        lens: np.ndarray,
        pad_to: Optional[int] = VECTOR_SIZE,
    ) -> FrameBatch:
        """Parse frames already packed in one buffer (zero extra copies
        — the decap path hands its adjusted offsets straight in here)."""
        n = len(offsets)
        offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
        lens = np.ascontiguousarray(lens, dtype=np.uint32)

        size = n
        if pad_to:
            size = max(pad_to, ((n + pad_to - 1) // pad_to) * pad_to)
        src_ip = np.zeros(size, dtype=np.uint32)
        dst_ip = np.zeros(size, dtype=np.uint32)
        protocol = np.zeros(size, dtype=np.int32)
        src_port = np.zeros(size, dtype=np.int32)
        dst_port = np.zeros(size, dtype=np.int32)
        flags = np.zeros(n, dtype=np.uint8)

        if n:
            self._lib.hs_parse_batch(
                buf.ctypes.data_as(_u8p),
                offsets.ctypes.data_as(_u64p),
                lens.ctypes.data_as(_u32p),
                n,
                src_ip.ctypes.data_as(_u32p),
                dst_ip.ctypes.data_as(_u32p),
                protocol.ctypes.data_as(_i32p),
                src_port.ctypes.data_as(_i32p),
                dst_port.ctypes.data_as(_i32p),
                flags.ctypes.data_as(_u8p),
            )
        batch = PacketBatch(
            src_ip=src_ip, dst_ip=dst_ip, protocol=protocol,
            src_port=src_port, dst_port=dst_port,
        )
        return FrameBatch(buf=buf, offsets=offsets, lens=lens,
                          flags=flags, batch=batch, n=n)

    # --------------------------------------------------------------- apply

    def apply(self, fb: FrameBatch, allowed, rewritten: PacketBatch) -> List[bytes]:
        """Apply pipeline verdicts + rewrites; returns forwarded frames."""
        fwd = self.apply_masked(fb, allowed, rewritten)
        return [fb.frame(i) for i in range(fb.n) if fwd[i]]

    def apply_masked(self, fb: FrameBatch, allowed, rewritten: PacketBatch) -> np.ndarray:
        """Like :meth:`apply` but returns the forwarded mask instead of
        materialising frame copies (the runner splits by route next)."""
        n = fb.n
        allowed = np.ascontiguousarray(np.asarray(allowed).astype(np.uint8)[:n])
        new_src = np.ascontiguousarray(np.asarray(rewritten.src_ip).astype(np.uint32)[:n])
        new_dst = np.ascontiguousarray(np.asarray(rewritten.dst_ip).astype(np.uint32)[:n])
        new_sport = np.ascontiguousarray(np.asarray(rewritten.src_port).astype(np.int32)[:n])
        new_dport = np.ascontiguousarray(np.asarray(rewritten.dst_port).astype(np.int32)[:n])
        fwd = np.zeros(n, dtype=np.uint8)
        if n:
            self._lib.hs_apply_batch(
                fb.buf.ctypes.data_as(_u8p),
                fb.offsets.ctypes.data_as(_u64p),
                fb.lens.ctypes.data_as(_u32p),
                n,
                allowed.ctypes.data_as(_u8p),
                new_src.ctypes.data_as(_u32p),
                new_dst.ctypes.data_as(_u32p),
                new_sport.ctypes.data_as(_i32p),
                new_dport.ctypes.data_as(_i32p),
                fwd.ctypes.data_as(_u8p),
            )
        return fwd

    # --------------------------------------------------------------- vxlan

    def vxlan_encap(
        self,
        fb: FrameBatch,
        fwd: np.ndarray,
        is_remote: np.ndarray,
        node_ids: np.ndarray,
        remote_ips: np.ndarray,
        local_ip: int,
        local_node_id: int,
        vni: int = 10,
    ):
        """Encap forwarded ROUTE_REMOTE frames for the overlay.

        ``remote_ips`` is indexed by node ID (0 = unknown).  Returns
        ``(out_buf, out_offsets, out_lens, out_rows, unroutable)`` where
        ``out_rows[j]`` is the batch row the j-th encapped frame came
        from.  Mirrors the reference's per-node VXLAN tunnels
        (plugins/ipv4net/node.go vxlanIfToOtherNode :524).
        """
        n = fb.n
        fwd = np.ascontiguousarray(fwd.astype(np.uint8)[:n])
        is_remote = np.ascontiguousarray(is_remote.astype(np.uint8)[:n])
        node_ids = np.ascontiguousarray(node_ids.astype(np.int32)[:n])
        remote_ips = np.ascontiguousarray(remote_ips.astype(np.uint32))
        out_cap = int(fb.buf.size + 50 * max(n, 1))
        out_buf = np.empty(out_cap, dtype=np.uint8)
        out_offsets = np.zeros(max(n, 1), dtype=np.uint64)
        out_lens = np.zeros(max(n, 1), dtype=np.uint32)
        out_rows = np.zeros(max(n, 1), dtype=np.int32)
        unroutable = ctypes.c_int32(0)
        count = 0
        if n:
            count = self._lib.hs_vxlan_encap_batch(
                fb.buf.ctypes.data_as(_u8p),
                fb.offsets.ctypes.data_as(_u64p),
                fb.lens.ctypes.data_as(_u32p),
                n,
                fwd.ctypes.data_as(_u8p),
                is_remote.ctypes.data_as(_u8p),
                node_ids.ctypes.data_as(_i32p),
                remote_ips.ctypes.data_as(_u32p),
                len(remote_ips) - 1,
                ctypes.c_uint32(local_ip),
                ctypes.c_uint32(local_node_id),
                ctypes.c_uint32(vni),
                out_buf.ctypes.data_as(_u8p),
                ctypes.c_uint64(out_cap),
                out_offsets.ctypes.data_as(_u64p),
                out_lens.ctypes.data_as(_u32p),
                out_rows.ctypes.data_as(_i32p),
                ctypes.byref(unroutable),
            )
            if count < 0:
                raise RuntimeError("vxlan encap output buffer overflow")
        return (
            out_buf, out_offsets[:count], out_lens[:count],
            out_rows[:count], int(unroutable.value),
        )

    def vxlan_decap_view(
        self, buf: np.ndarray, offsets: np.ndarray, lens: np.ndarray
    ):
        """De-encapsulate in place: returns ``(inner_offsets,
        inner_lens, vnis)`` describing the inner frames *within the same
        buffer* (offset math only, zero copies); non-VXLAN frames pass
        through with vni -1."""
        n = len(offsets)
        offsets = np.ascontiguousarray(offsets, dtype=np.uint64)
        lens = np.ascontiguousarray(lens, dtype=np.uint32)
        inner_off = np.zeros(n, dtype=np.uint64)
        inner_len = np.zeros(n, dtype=np.uint32)
        vnis = np.zeros(n, dtype=np.int32)
        if n:
            self._lib.hs_vxlan_decap_batch(
                buf.ctypes.data_as(_u8p),
                offsets.ctypes.data_as(_u64p),
                lens.ctypes.data_as(_u32p),
                n,
                inner_off.ctypes.data_as(_u64p),
                inner_len.ctypes.data_as(_u32p),
                vnis.ctypes.data_as(_i32p),
            )
        return inner_off, inner_len, vnis

    def vxlan_decap(self, frames: Sequence[bytes]):
        """Convenience wrapper over :meth:`vxlan_decap_view` returning
        materialised inner frames (tests / non-hot-path callers)."""
        n = len(frames)
        if not n:
            return [], []
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(n, dtype=np.uint64)
        np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(b"".join(frames), dtype=np.uint8).copy()
        inner_off, inner_len, vnis = self.vxlan_decap_view(buf, offsets, lens)
        out = [
            buf[int(inner_off[i]):int(inner_off[i]) + int(inner_len[i])].tobytes()
            for i in range(n)
        ]
        return out, vnis.tolist()
