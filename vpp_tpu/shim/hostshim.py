"""ctypes binding + batch plumbing for the native host shim.

The analog of the reference's GoVPP/DPDK transport boundary (SURVEY.md
§2.3): ``HostShim.parse`` turns raw Ethernet frames into the
fixed-shape :class:`PacketBatch` the jit pipeline consumes (padded to
the 256-packet vector size), and ``HostShim.apply`` writes the
pipeline's verdicts + NAT rewrites back into the frames with
incremental checksum updates — all per-byte work in C++.

The shared library is built on demand from ``native/hostshim`` with the
baked-in g++ toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..ops.packets import PacketBatch, VECTOR_SIZE

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.join(_NATIVE_DIR, "hostshim", "hostshim.cpp")
_LIB = os.path.join(_NATIVE_DIR, "build", "libhostshim.so")


def _build_library() -> str:
    src = os.path.abspath(_SRC)
    lib = os.path.abspath(_LIB)
    if not os.path.exists(lib) or os.path.getmtime(lib) < os.path.getmtime(src):
        subprocess.run(
            ["make", "-s", "-C", os.path.dirname(src)],
            check=True,
            capture_output=True,
        )
    return lib


_u8p = ctypes.POINTER(ctypes.c_uint8)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_i32p = ctypes.POINTER(ctypes.c_int32)


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(_build_library())
    lib.hs_parse_batch.restype = ctypes.c_int32
    lib.hs_parse_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u32p, _u32p, _i32p, _i32p, _i32p, _u8p,
    ]
    lib.hs_apply_batch.restype = ctypes.c_int32
    lib.hs_apply_batch.argtypes = [
        _u8p, _u64p, _u32p, ctypes.c_int32,
        _u8p, _u32p, _u32p, _i32p, _i32p, _u8p,
    ]
    return lib


@dataclass
class FrameBatch:
    """Frames packed into one contiguous buffer + parsed header SoA."""

    buf: np.ndarray        # uint8 [total_bytes]
    offsets: np.ndarray    # uint64 [n]
    lens: np.ndarray       # uint32 [n]
    flags: np.ndarray      # uint8 [n]: bit0 IPv4, bit1 ports
    batch: PacketBatch     # padded to VECTOR_SIZE multiples
    n: int

    def frame(self, i: int) -> bytes:
        off, ln = int(self.offsets[i]), int(self.lens[i])
        return self.buf[off:off + ln].tobytes()


class HostShim:
    """The packet-batch assembler/applier."""

    def __init__(self):
        self._lib = _load()

    # --------------------------------------------------------------- parse

    def parse(self, frames: Sequence[bytes],
              pad_to: Optional[int] = VECTOR_SIZE) -> FrameBatch:
        """Parse raw frames into a (padded) PacketBatch."""
        n = len(frames)
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(n, dtype=np.uint64)
        if n:
            np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(b"".join(frames), dtype=np.uint8).copy()

        size = n
        if pad_to:
            size = max(pad_to, ((n + pad_to - 1) // pad_to) * pad_to)
        src_ip = np.zeros(size, dtype=np.uint32)
        dst_ip = np.zeros(size, dtype=np.uint32)
        protocol = np.zeros(size, dtype=np.int32)
        src_port = np.zeros(size, dtype=np.int32)
        dst_port = np.zeros(size, dtype=np.int32)
        flags = np.zeros(n, dtype=np.uint8)

        if n:
            self._lib.hs_parse_batch(
                buf.ctypes.data_as(_u8p),
                offsets.ctypes.data_as(_u64p),
                lens.ctypes.data_as(_u32p),
                n,
                src_ip.ctypes.data_as(_u32p),
                dst_ip.ctypes.data_as(_u32p),
                protocol.ctypes.data_as(_i32p),
                src_port.ctypes.data_as(_i32p),
                dst_port.ctypes.data_as(_i32p),
                flags.ctypes.data_as(_u8p),
            )
        batch = PacketBatch(
            src_ip=src_ip, dst_ip=dst_ip, protocol=protocol,
            src_port=src_port, dst_port=dst_port,
        )
        return FrameBatch(buf=buf, offsets=offsets, lens=lens,
                          flags=flags, batch=batch, n=n)

    # --------------------------------------------------------------- apply

    def apply(self, fb: FrameBatch, allowed, rewritten: PacketBatch) -> List[bytes]:
        """Apply pipeline verdicts + rewrites; returns forwarded frames."""
        n = fb.n
        allowed = np.asarray(allowed).astype(np.uint8)[:n].copy()
        new_src = np.asarray(rewritten.src_ip).astype(np.uint32)[:n].copy()
        new_dst = np.asarray(rewritten.dst_ip).astype(np.uint32)[:n].copy()
        new_sport = np.asarray(rewritten.src_port).astype(np.int32)[:n].copy()
        new_dport = np.asarray(rewritten.dst_port).astype(np.int32)[:n].copy()
        fwd = np.zeros(n, dtype=np.uint8)
        if n:
            self._lib.hs_apply_batch(
                fb.buf.ctypes.data_as(_u8p),
                fb.offsets.ctypes.data_as(_u64p),
                fb.lens.ctypes.data_as(_u32p),
                n,
                allowed.ctypes.data_as(_u8p),
                new_src.ctypes.data_as(_u32p),
                new_dst.ctypes.data_as(_u32p),
                new_sport.ctypes.data_as(_i32p),
                new_dport.ctypes.data_as(_i32p),
                fwd.ctypes.data_as(_u8p),
            )
        return [fb.frame(i) for i in range(n) if fwd[i]]
