"""Multi-chip sharding of the data plane over a JAX device mesh.

Where the reference scales out with per-node VPP instances coordinated
through etcd (SURVEY.md §2.4 — no collective-communication library at
all), the TPU build adds a genuinely new axis: one node's data plane
can span multiple TPU chips over ICI (SURVEY.md §5.8).

The mesh is 2-D:

- ``data`` axis — packet batches shard across chips (the DP analog);
  every chip classifies its slice of the batch.
- ``rules`` axis — the rule tensor shards across chips (the TP
  analog); each chip evaluates its rule slice and the first-match
  argmax reduces across the axis with an XLA-inserted collective.

Everything goes through ``jax.jit`` with NamedSharding-annotated
inputs: XLA GSPMD partitions the [B, N] predicate matrix and inserts
the cross-chip reductions — no hand-written collectives (the
scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
collectives).

NAT session state is replicated across the ``rules`` axis and sharded
with the batch on ``data``-only meshes; the dryrun keeps sessions
replicated, which is correct (every chip computes identical scatter
values for its batch slice).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.classify import RuleTables
from ..ops.nat import NatSessions, NatTables, empty_sessions
from ..ops.packets import PacketBatch
from ..ops.pipeline import RouteConfig, pipeline_step


def make_mesh(n_devices: Optional[int] = None, rules_axis: Optional[int] = None) -> Mesh:
    """Build a (data x rules) mesh over the first ``n_devices`` devices.

    ``rules_axis`` devices go to the rules dimension (default: 2 when
    n >= 4, else 1 — batches benefit from sharding first).
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, only {len(devices)} available")
    if rules_axis is None:
        rules_axis = 2 if n >= 4 and n % 2 == 0 else 1
    if n % rules_axis != 0:
        raise ValueError(f"{n} devices do not split into rules_axis={rules_axis}")
    data_axis = n // rules_axis
    grid = np.array(devices[:n]).reshape(data_axis, rules_axis)
    return Mesh(grid, ("data", "rules"))


def _sharding_tree(template, mesh: Mesh, spec_fn):
    """Build a pytree of NamedShardings matching ``template``'s structure."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    shardings = [NamedSharding(mesh, spec_fn(leaf)) for leaf in leaves]
    return jax.tree_util.tree_unflatten(treedef, shardings)


def shard_dataplane(
    mesh: Mesh,
    acl: RuleTables,
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    partition_sessions: bool = False,
):
    """Place the data-plane state onto the mesh.

    Rule rows shard over the ``rules`` axis; pod lookup tables, NAT
    mappings and routing scalars replicate.  The session table has two
    supported placements:

    - replicated (default): every chip holds the full table.  Cost at
      the production capacity (2^16 slots) is ~3 MB/chip of HBM plus
      the GSPMD-inserted combine of each step's scatter updates across
      the ``data`` axis (measured by scripts/mesh_overhead.py).
    - ``partition_sessions=True``: slots shard over the ``data`` axis
      (hash-partitioned table).  Any batch shard may probe any slot —
      flow hashes do not respect the slot partition — so GSPMD inserts
      the cross-shard gathers/scatters; HBM per chip drops by the mesh
      width.  Verdict-identical to the replicated placement
      (tests/test_multichip.py asserts both against single-device).
    """
    rule_fields = {
        "rule_valid", "rule_tid", "rule_src_base", "rule_src_mask",
        "rule_dst_base", "rule_dst_mask", "rule_proto", "rule_src_port",
        "rule_dst_port", "rule_action",
    }

    # RuleTables flatten order matches the field order in tree_flatten.
    field_order = [
        "rule_valid", "rule_tid", "rule_src_base", "rule_src_mask",
        "rule_dst_base", "rule_dst_mask", "rule_proto", "rule_src_port",
        "rule_dst_port", "rule_action",
        "pod_ip", "pod_ingress_tid", "pod_egress_tid",
    ]
    leaves, treedef = jax.tree_util.tree_flatten(acl)
    shardings = []
    for name, _leaf in zip(field_order, leaves):
        spec = P("rules") if name in rule_fields else P()
        shardings.append(NamedSharding(mesh, spec))
    acl_sharded = jax.device_put(acl, jax.tree_util.tree_unflatten(treedef, shardings))

    replicate = lambda leaf: P()  # noqa: E731
    nat_sharded = jax.device_put(nat, _sharding_tree(nat, mesh, replicate))
    route_sharded = jax.device_put(route, _sharding_tree(route, mesh, replicate))
    sess_spec = (lambda leaf: P("data")) if partition_sessions else replicate
    sessions_sharded = jax.device_put(sessions, _sharding_tree(sessions, mesh, sess_spec))
    return acl_sharded, nat_sharded, route_sharded, sessions_sharded


def replicate_on_mesh(mesh: Mesh, tree):
    """Place every leaf of a pytree fully REPLICATED on the mesh.

    For small tables with no shardable axis — the inference weights +
    enrollment table (ISSUE 14) are a few KB, so replication is the
    right placement (like the NAT mapping tables inside
    shard_dataplane); what matters is that the leaves carry a mesh
    sharding at all: mixing single-device committed arrays into a
    dispatch whose other arguments are mesh-placed is an
    incompatible-devices error."""
    spec = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, spec), tree)


def shard_batch(mesh: Mesh, batch: PacketBatch) -> PacketBatch:
    """Shard a packet batch over the ``data`` axis.

    Accepts both dispatch shapes: flat ``[B]`` leaves shard on their
    only dim; scan-shaped ``[K, V]`` leaves shard the packet dim (each
    of the K vectors splits across the axis, preserving the scan's
    sequential session semantics)."""

    def put(x):
        spec = P("data") if x.ndim == 1 else P(None, "data")
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, batch)


def sharded_pipeline_step(mesh: Mesh):
    """The jitted pipeline for mesh execution.

    Input shardings follow the operands (set by shard_dataplane /
    shard_batch); GSPMD partitions the [B, N] match matrix on both axes
    and inserts the argmax reduction collective over ``rules`` — no
    extra annotations needed, so this is the ordinary jitted step.
    """
    from ..ops.pipeline import pipeline_step_jit

    return pipeline_step_jit


# ---------------------------------------------------------------------------
# Multi-chip dry run (driver contract: validates sharding compiles + runs)
# ---------------------------------------------------------------------------


def ensure_devices(n: int) -> None:
    """Make sure >= n devices exist BEFORE any jax computation runs.

    Falls back to virtual CPU devices when the hardware has fewer chips
    (the driver's dry-run contract).  Must be called before the backend
    is locked by a first computation; the axon TPU plugin ignores the
    JAX_PLATFORMS env var, so the config API is used.
    """
    import os

    from jax._src import xla_bridge as xb

    if xb.backends_are_initialized():
        if len(jax.devices()) >= n:
            return
        raise RuntimeError(
            f"need {n} devices but the JAX backend is already initialized "
            f"with {len(jax.devices())}; call ensure_devices() before any "
            "jax computation (fresh process)"
        )
    # Decide the platform BEFORE first initialization — in this
    # environment the backend cannot be re-created afterwards.  The
    # dry-run contract is validation on virtual CPU devices, so force the
    # CPU platform (the ambient env may pin JAX_PLATFORMS to the real TPU
    # plugin, which cannot provide n chips here; real multi-chip runs use
    # make_mesh() directly on an already-initialized multi-chip backend).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    jax.config.update("jax_platforms", "cpu")
    if len(jax.devices()) < n:
        raise ValueError(
            f"requested {n} devices, CPU fallback provides {len(jax.devices())}"
        )


def dryrun_multichip(n_devices: int) -> None:
    """Compile and execute the FULL datapath over an ``n_devices``
    mesh: real Ethernet frames through the native runner loop
    (C++ rings, admit/harvest) with every dispatch GSPMD-sharded —
    batch over ``data``, rule tensor over ``rules`` — across MULTIPLE
    steps, so sessions committed by one sharded dispatch restore
    replies in the next (the multi-step sharded-session contract, not
    a one-shot compile check).  The framework's DP x TP analog: there
    is no gradient step in a packet processor; the data-plane step IS
    the full per-iteration workload.
    """
    ensure_devices(n_devices)

    from ..conf import IPAMConfig
    from ..ipam import IPAM
    from ..models import (
        IngressRule,
        LabelSelector,
        Peer,
        Pod,
        PodID,
        Policy,
        PolicyType,
    )
    from ..ops.pipeline import make_route_config
    from ..policy import PolicyPlugin
    from ..policy.renderer.tpu import TpuPolicyRenderer
    from ..service.renderer.tpu import TpuNatRenderer
    from ..ops.nat import NatMapping, build_nat_tables
    from ..ops.packets import make_batch

    mesh = make_mesh(n_devices)

    # Tiny but real state: pods + an isolating policy + one service.
    ipam = IPAM(IPAMConfig(), node_id=1)
    pods = [
        Pod(name=f"p{i}", namespace="default", labels={"app": "web"},
            ip_address=str(ipam.allocate_pod_ip(PodID(f"p{i}", "default"))))
        for i in range(4)
    ]
    # Web pods accept ingress from web pods only (a real rule table on
    # the ``rules`` axis, permitting the dry run's service traffic).
    policy = Policy(
        name="web-only", namespace="default",
        pods=LabelSelector(match_labels={"app": "web"}),
        policy_type=PolicyType.INGRESS,
        ingress_rules=(IngressRule(
            from_peers=(Peer(pods=LabelSelector(match_labels={"app": "web"})),),
        ),),
    )
    tpu_renderer = TpuPolicyRenderer()
    plugin = PolicyPlugin(ipam=ipam)
    plugin.register_renderer(tpu_renderer)
    state = {"pod": {}, "policy": {}, "namespace": {}}
    from ..models import key_for

    for pod in pods:
        state["pod"][key_for(pod)] = pod
    state["policy"][key_for(policy)] = policy
    plugin.resync(None, state, 1, None)
    acl = tpu_renderer.tables

    nat = build_nat_tables(
        [NatMapping("10.96.0.10", 80, 6, [(pods[0].ip_address, 8080, 1)])],
        nat_loopback=str(ipam.nat_loopback_ip()),
        snat_ip="192.168.16.1",
        snat_enabled=True,
        pod_subnet=str(ipam.pod_subnet_all_nodes),
    )
    route = make_route_config(ipam)

    # ---- the runner loop on the mesh (VERDICT r2 item 4) -------------
    from ..datapath import DataplaneRunner, NativeRing, VxlanOverlay
    from ..ops.packets import ip_to_u32
    from ..testing.frames import build_frame, frame_tuple

    data_width = mesh.devices.shape[0]
    # Batch must split over the data axis, whatever its width.
    batch_size = ((max(64, 8 * n_devices) + data_width - 1)
                  // data_width) * data_width
    rings = [NativeRing(arena_bytes=1 << 20, max_frames=1 << 12) for _ in range(4)]
    rx, tx, local_ring, host_ring = rings
    runner = DataplaneRunner(
        acl=acl, nat=nat, route=route,
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"), local_node_id=1),
        source=rx, tx=tx, local=local_ring, host=host_ring,
        batch_size=batch_size, max_vectors=2,
        mesh=mesh,
    )
    assert runner.engine == "native"

    # Step N: forward service flows — DNAT + session commit, sharded.
    n_flows = batch_size
    client = pods[1].ip_address
    backend = pods[0].ip_address
    rx.send([build_frame(client, "10.96.0.10", 6, 40000 + i, 80)
             for i in range(n_flows)])
    runner.drain()
    fwd = local_ring.recv_batch(1 << 12)
    assert len(fwd) == n_flows, f"forward delivery {len(fwd)}/{n_flows}"
    assert all(frame_tuple(f)[1] == backend for f in fwd)

    # Step N+1: replies in a LATER dispatch ride the sessions the
    # sharded step N committed — restored to the VIP.
    rx.send([build_frame(backend, client, 6, 8080, 40000 + i)
             for i in range(n_flows)])
    runner.drain()
    rep = local_ring.recv_batch(1 << 12)
    assert len(rep) == n_flows, f"reply delivery {len(rep)}/{n_flows}"
    restored = sum(1 for f in rep if frame_tuple(f)[0] == "10.96.0.10")
    assert restored == n_flows, f"VIP restored on {restored}/{n_flows} replies"

    # One direct sharded-step sanity check on top of the runner drive.
    sessions = empty_sessions(1024)
    batch = make_batch([
        (pods[i % len(pods)].ip_address, "10.96.0.10", 6, 50000 + i, 80)
        for i in range(batch_size)
    ])
    with mesh:
        acl_s, nat_s, route_s, sess_s = shard_dataplane(mesh, acl, nat, route, sessions)
        batch_s = shard_batch(mesh, batch)
        step = sharded_pipeline_step(mesh)
        result = step(acl_s, nat_s, route_s, sess_s, batch_s, jnp.int32(0))
        result.packed.block_until_ready()
    # Packed single-transfer result: uint32 [4, B] (word|src|dst|ports).
    assert np.asarray(result.packed).shape == (4, batch_size)

    print(
        f"dryrun_multichip OK: mesh {dict(zip(mesh.axis_names, mesh.devices.shape))}, "
        f"runner loop native+sharded, {n_flows} forward + {n_flows} "
        f"session-restored replies across steps"
    )
