from .mesh import (
    make_mesh,
    shard_dataplane,
    sharded_pipeline_step,
    dryrun_multichip,
)

__all__ = [
    "make_mesh",
    "shard_dataplane",
    "sharded_pipeline_step",
    "dryrun_multichip",
]
