// Host shim — native packet batch assembler / applier.
//
// The TPU-native analog of the reference's native transport layer
// (GoVPP shared-memory adapter + DPDK NIC IO, SURVEY.md §2.3): raw
// Ethernet frames are parsed into struct-of-arrays 5-tuple header
// vectors (what the jit pipeline consumes), and the pipeline's verdicts
// + NAT rewrites are applied back onto the frames with RFC 1624
// incremental checksum updates — per-packet byte work stays native,
// the TPU only ever sees fixed-shape header tensors.
//
// C ABI, consumed from Python via ctypes (no pybind11 in the image).
// Frames live in ONE contiguous buffer described by (offset, len)
// arrays — a single memcpy-free view for both sides.
//
// The batch-at-a-time API below serves tests and the Python-loop
// runner; the full native admit/harvest loop lives in runnerloop.cpp.

#include <cstdint>
#include <cstring>

#include "common.h"

using namespace hs;

extern "C" {

// Parse n frames into SoA header arrays. flags: bit0 = IPv4, bit1 =
// ports present. Returns the number of IPv4 frames.
int32_t hs_parse_batch(const uint8_t* buf, const uint64_t* offsets,
                       const uint32_t* lens, int32_t n, uint32_t* src_ip,
                       uint32_t* dst_ip, int32_t* protocol, int32_t* src_port,
                       int32_t* dst_port, uint8_t* flags) {
  int32_t parsed = 0;
  for (int32_t i = 0; i < n; ++i) {
    // parse_frame does not write; const_cast confines the mutable API
    // to hs_apply_batch.
    FrameView v = parse_frame(const_cast<uint8_t*>(buf + offsets[i]), lens[i]);
    if (!v.valid) {
      src_ip[i] = dst_ip[i] = 0;
      protocol[i] = src_port[i] = dst_port[i] = 0;
      flags[i] = 0;
      continue;
    }
    src_ip[i] = load_be32(v.ip + 12);
    dst_ip[i] = load_be32(v.ip + 16);
    protocol[i] = v.proto;
    src_port[i] = v.has_ports ? load_be16(v.l4) : 0;
    dst_port[i] = v.has_ports ? load_be16(v.l4 + 2) : 0;
    flags[i] = static_cast<uint8_t>(1 | (v.has_ports ? 2 : 0));
    ++parsed;
  }
  return parsed;
}

// Apply verdicts + header rewrites in place. allowed[i] == 0 drops the
// frame (fwd[i] = 0). Changed IPs/ports are patched with incremental
// updates of the IPv4 header checksum and the TCP/UDP checksum
// (pseudo-header includes the IPs). Returns the forwarded count.
int32_t hs_apply_batch(uint8_t* buf, const uint64_t* offsets,
                       const uint32_t* lens, int32_t n, const uint8_t* allowed,
                       const uint32_t* new_src_ip, const uint32_t* new_dst_ip,
                       const int32_t* new_src_port, const int32_t* new_dst_port,
                       uint8_t* fwd) {
  int32_t forwarded = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (!allowed[i] ||
        !apply_rewrite(buf + offsets[i], lens[i], new_src_ip[i], new_dst_ip[i],
                       static_cast<uint16_t>(new_src_port[i]),
                       static_cast<uint16_t>(new_dst_port[i]))) {
      fwd[i] = 0;
      continue;
    }
    fwd[i] = 1;
    ++forwarded;
  }
  return forwarded;
}

// ---------------------------------------------------------------------------
// VXLAN encap / decap — the full-mesh overlay data path.
//
// The reference interconnects nodes with a full mesh of VXLAN tunnels
// into one bridge domain (plugins/ipv4net/node.go vxlanIfToOtherNode
// :524, vxlanBridgeDomain :482, VNI 10, port 4789).  Here the pipeline
// tags ROUTE_REMOTE packets with the destination node ID and this shim
// wraps them: outer Ethernet + IPv4 + UDP(4789) + VXLAN, outer source
// port derived from the inner flow for ECMP entropy (RFC 7348 §5).
// ---------------------------------------------------------------------------

// Encapsulate the ROUTE_REMOTE forwarded frames of a batch.
//
// For each frame i with fwd[i] != 0 and is_remote[i] != 0, writes
//   [outer eth][outer ip][udp 4789][vxlan vni][inner frame]
// into out_buf and records (out_offsets, out_lens, out_rows) where
// out_rows[j] = i.  Returns the number of encapped frames, or -1 if
// out_buf (capacity out_cap bytes) is too small.  remote_ips maps
// node_id -> outer destination IP (host-order u32, 0 = unknown ->
// frame skipped and counted in *unroutable).
int32_t hs_vxlan_encap_batch(const uint8_t* buf, const uint64_t* offsets,
                             const uint32_t* lens, int32_t n,
                             const uint8_t* fwd, const uint8_t* is_remote,
                             const int32_t* node_ids,
                             const uint32_t* remote_ips, int32_t max_node_id,
                             uint32_t local_ip, uint32_t local_node_id,
                             uint32_t vni, uint8_t* out_buf, uint64_t out_cap,
                             uint64_t* out_offsets, uint32_t* out_lens,
                             int32_t* out_rows, int32_t* unroutable) {
  int32_t emitted = 0;
  uint64_t used = 0;
  int32_t skipped = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (!fwd[i] || !is_remote[i]) continue;
    int32_t nid = node_ids[i];
    uint32_t dst_ip = (nid >= 0 && nid <= max_node_id) ? remote_ips[nid] : 0;
    if (dst_ip == 0) {
      ++skipped;
      continue;
    }
    uint32_t inner_len = lens[i];
    uint32_t total = kOuterBytes + inner_len;
    if (used + total > out_cap) return -1;
    uint8_t* p = out_buf + used;
    const uint8_t* inner = buf + offsets[i];
    write_vxlan_outer(p, inner_len, local_ip, dst_ip, local_node_id,
                      static_cast<uint32_t>(nid), vni,
                      flow_entropy(inner, inner_len));
    std::memcpy(p + kOuterBytes, inner, inner_len);
    out_offsets[emitted] = used;
    out_lens[emitted] = total;
    out_rows[emitted] = i;
    used += total;
    ++emitted;
  }
  if (unroutable != nullptr) *unroutable = skipped;
  return emitted;
}

// Classify + de-encapsulate VXLAN frames IN PLACE (offset adjustment,
// no copy).  For each frame: if it is a well-formed
// eth/IPv4/UDP(4789)/VXLAN frame, inner_offsets[i]/inner_lens[i]
// describe the inner Ethernet frame inside the same buffer and vnis[i]
// holds the VNI; otherwise inner_offsets[i] = offsets[i],
// inner_lens[i] = lens[i], vnis[i] = -1 (native frame, passthrough).
// Returns the number of decapped frames.
int32_t hs_vxlan_decap_batch(const uint8_t* buf, const uint64_t* offsets,
                             const uint32_t* lens, int32_t n,
                             uint64_t* inner_offsets, uint32_t* inner_lens,
                             int32_t* vnis) {
  int32_t decapped = 0;
  for (int32_t i = 0; i < n; ++i) {
    uint32_t rel_off, rel_len;
    vnis[i] = vxlan_classify(buf + offsets[i], lens[i], &rel_off, &rel_len);
    inner_offsets[i] = offsets[i] + rel_off;
    inner_lens[i] = rel_len;
    if (vnis[i] >= 0) ++decapped;
  }
  return decapped;
}

}  // extern "C"
