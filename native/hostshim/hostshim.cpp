// Host shim — native packet batch assembler / applier.
//
// The TPU-native analog of the reference's native transport layer
// (GoVPP shared-memory adapter + DPDK NIC IO, SURVEY.md §2.3): raw
// Ethernet frames are parsed into struct-of-arrays 5-tuple header
// vectors (what the jit pipeline consumes), and the pipeline's verdicts
// + NAT rewrites are applied back onto the frames with RFC 1624
// incremental checksum updates — per-packet byte work stays native,
// the TPU only ever sees fixed-shape header tensors.
//
// C ABI, consumed from Python via ctypes (no pybind11 in the image).
// Frames live in ONE contiguous buffer described by (offset, len)
// arrays — a single memcpy-free view for both sides.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint16_t kEthertypeIPv4 = 0x0800;
constexpr uint16_t kEthertypeVlan = 0x8100;
constexpr uint8_t kProtoTCP = 6;
constexpr uint8_t kProtoUDP = 17;

inline uint16_t load_be16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) << 8 | p[1];
}
inline uint32_t load_be32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) << 24 | static_cast<uint32_t>(p[1]) << 16 |
         static_cast<uint32_t>(p[2]) << 8 | p[3];
}
inline void store_be16(uint8_t* p, uint16_t v) {
  p[0] = v >> 8;
  p[1] = v & 0xff;
}
inline void store_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24;
  p[1] = (v >> 16) & 0xff;
  p[2] = (v >> 8) & 0xff;
  p[3] = v & 0xff;
}

// RFC 1624 eqn. 3: HC' = ~(~HC + ~m + m'), one 16-bit field update.
inline uint16_t csum_update16(uint16_t hc, uint16_t m_old, uint16_t m_new) {
  uint32_t sum = static_cast<uint32_t>(static_cast<uint16_t>(~hc)) +
                 static_cast<uint16_t>(~m_old) + m_new;
  while (sum >> 16) sum = (sum & 0xffff) + (sum >> 16);
  return static_cast<uint16_t>(~sum);
}

inline uint16_t csum_update32(uint16_t hc, uint32_t m_old, uint32_t m_new) {
  hc = csum_update16(hc, m_old >> 16, m_new >> 16);
  return csum_update16(hc, m_old & 0xffff, m_new & 0xffff);
}

struct FrameView {
  uint8_t* ip = nullptr;   // IPv4 header start
  uint8_t* l4 = nullptr;   // L4 header start (null if truncated/fragment)
  uint8_t proto = 0;
  bool valid = false;
  bool has_ports = false;
};

// Parse one frame: Ethernet II (+ optional single 802.1Q tag) → IPv4 →
// TCP/UDP ports.  Non-IPv4 and truncated frames yield valid=false; a
// non-first fragment keeps valid but has no port view.
FrameView parse_frame(uint8_t* frame, uint32_t len) {
  FrameView v;
  if (len < 14) return v;
  uint32_t off = 12;
  uint16_t ethertype = load_be16(frame + off);
  off += 2;
  if (ethertype == kEthertypeVlan) {
    if (len < off + 4) return v;
    ethertype = load_be16(frame + off + 2);
    off += 4;
  }
  if (ethertype != kEthertypeIPv4) return v;
  if (len < off + 20) return v;
  uint8_t* ip = frame + off;
  if ((ip[0] >> 4) != 4) return v;
  uint32_t ihl = static_cast<uint32_t>(ip[0] & 0x0f) * 4;
  if (ihl < 20 || len < off + ihl) return v;
  v.ip = ip;
  v.proto = ip[9];
  v.valid = true;
  uint16_t frag = load_be16(ip + 6);
  bool first_fragment = (frag & 0x1fff) == 0;
  if (!first_fragment) return v;  // ports live in the first fragment only
  if ((v.proto == kProtoTCP || v.proto == kProtoUDP) && len >= off + ihl + 4) {
    v.l4 = ip + ihl;
    v.has_ports = true;
  }
  return v;
}

}  // namespace

extern "C" {

// Parse n frames into SoA header arrays. flags: bit0 = IPv4, bit1 =
// ports present. Returns the number of IPv4 frames.
int32_t hs_parse_batch(const uint8_t* buf, const uint64_t* offsets,
                       const uint32_t* lens, int32_t n, uint32_t* src_ip,
                       uint32_t* dst_ip, int32_t* protocol, int32_t* src_port,
                       int32_t* dst_port, uint8_t* flags) {
  int32_t parsed = 0;
  for (int32_t i = 0; i < n; ++i) {
    // parse_frame does not write; const_cast confines the mutable API
    // to hs_apply_batch.
    FrameView v = parse_frame(const_cast<uint8_t*>(buf + offsets[i]), lens[i]);
    if (!v.valid) {
      src_ip[i] = dst_ip[i] = 0;
      protocol[i] = src_port[i] = dst_port[i] = 0;
      flags[i] = 0;
      continue;
    }
    src_ip[i] = load_be32(v.ip + 12);
    dst_ip[i] = load_be32(v.ip + 16);
    protocol[i] = v.proto;
    src_port[i] = v.has_ports ? load_be16(v.l4) : 0;
    dst_port[i] = v.has_ports ? load_be16(v.l4 + 2) : 0;
    flags[i] = static_cast<uint8_t>(1 | (v.has_ports ? 2 : 0));
    ++parsed;
  }
  return parsed;
}

// Apply verdicts + header rewrites in place. allowed[i] == 0 drops the
// frame (fwd[i] = 0). Changed IPs/ports are patched with incremental
// updates of the IPv4 header checksum and the TCP/UDP checksum
// (pseudo-header includes the IPs). Returns the forwarded count.
int32_t hs_apply_batch(uint8_t* buf, const uint64_t* offsets,
                       const uint32_t* lens, int32_t n, const uint8_t* allowed,
                       const uint32_t* new_src_ip, const uint32_t* new_dst_ip,
                       const int32_t* new_src_port, const int32_t* new_dst_port,
                       uint8_t* fwd) {
  int32_t forwarded = 0;
  for (int32_t i = 0; i < n; ++i) {
    FrameView v = parse_frame(buf + offsets[i], lens[i]);
    if (!v.valid || !allowed[i]) {
      fwd[i] = 0;
      continue;
    }
    fwd[i] = 1;
    ++forwarded;

    uint32_t old_src = load_be32(v.ip + 12);
    uint32_t old_dst = load_be32(v.ip + 16);
    uint16_t ip_csum = load_be16(v.ip + 10);

    uint8_t* l4_csum_p = nullptr;
    if (v.l4 != nullptr) {
      if (v.proto == kProtoTCP) {
        l4_csum_p = v.l4 + 16;
      } else if (v.proto == kProtoUDP && load_be16(v.l4 + 6) != 0) {
        l4_csum_p = v.l4 + 6;  // UDP checksum 0 = disabled, keep it so
      }
    }
    uint16_t l4_csum = l4_csum_p ? load_be16(l4_csum_p) : 0;

    if (new_src_ip[i] != old_src) {
      ip_csum = csum_update32(ip_csum, old_src, new_src_ip[i]);
      if (l4_csum_p) l4_csum = csum_update32(l4_csum, old_src, new_src_ip[i]);
      store_be32(v.ip + 12, new_src_ip[i]);
    }
    if (new_dst_ip[i] != old_dst) {
      ip_csum = csum_update32(ip_csum, old_dst, new_dst_ip[i]);
      if (l4_csum_p) l4_csum = csum_update32(l4_csum, old_dst, new_dst_ip[i]);
      store_be32(v.ip + 16, new_dst_ip[i]);
    }
    store_be16(v.ip + 10, ip_csum);

    if (v.has_ports) {
      uint16_t old_sport = load_be16(v.l4);
      uint16_t old_dport = load_be16(v.l4 + 2);
      uint16_t sport = static_cast<uint16_t>(new_src_port[i]);
      uint16_t dport = static_cast<uint16_t>(new_dst_port[i]);
      if (sport != old_sport) {
        if (l4_csum_p) l4_csum = csum_update16(l4_csum, old_sport, sport);
        store_be16(v.l4, sport);
      }
      if (dport != old_dport) {
        if (l4_csum_p) l4_csum = csum_update16(l4_csum, old_dport, dport);
        store_be16(v.l4 + 2, dport);
      }
    }
    if (l4_csum_p) store_be16(l4_csum_p, l4_csum);
  }
  return forwarded;
}

// ---------------------------------------------------------------------------
// VXLAN encap / decap — the full-mesh overlay data path.
//
// The reference interconnects nodes with a full mesh of VXLAN tunnels
// into one bridge domain (plugins/ipv4net/node.go vxlanIfToOtherNode
// :524, vxlanBridgeDomain :482, VNI 10, port 4789).  Here the pipeline
// tags ROUTE_REMOTE packets with the destination node ID and this shim
// wraps them: outer Ethernet + IPv4 + UDP(4789) + VXLAN, outer source
// port derived from the inner flow for ECMP entropy (RFC 7348 §5).
// ---------------------------------------------------------------------------

namespace {

constexpr uint16_t kVxlanPort = 4789;
constexpr uint32_t kVxlanHdrBytes = 8;
constexpr uint32_t kOuterBytes = 14 + 20 + 8 + kVxlanHdrBytes;  // 50

// Node-ID-derived locally-administered MAC (the BVI-MAC convention:
// a fixed OUI-style prefix + the node ID).
inline void node_mac(uint32_t node_id, uint8_t* mac) {
  mac[0] = 0x02;
  mac[1] = 0x76;
  mac[2] = 0x70;
  mac[3] = 0x70;
  mac[4] = (node_id >> 8) & 0xff;
  mac[5] = node_id & 0xff;
}

// Full (non-incremental) IPv4 header checksum over 20 bytes.
inline uint16_t ip_header_csum(const uint8_t* hdr) {
  uint32_t sum = 0;
  for (int i = 0; i < 20; i += 2) {
    if (i == 10) continue;  // checksum field itself
    sum += load_be16(hdr + i);
  }
  while (sum >> 16) sum = (sum & 0xffff) + (sum >> 16);
  return static_cast<uint16_t>(~sum);
}

}  // namespace

// Encapsulate the ROUTE_REMOTE forwarded frames of a batch.
//
// For each frame i with fwd[i] != 0 and is_remote[i] != 0, writes
//   [outer eth][outer ip][udp 4789][vxlan vni][inner frame]
// into out_buf and records (out_offsets, out_lens, out_rows) where
// out_rows[j] = i.  Returns the number of encapped frames, or -1 if
// out_buf (capacity out_cap bytes) is too small.  remote_ips maps
// node_id -> outer destination IP (host-order u32, 0 = unknown ->
// frame skipped and counted in *unroutable).
int32_t hs_vxlan_encap_batch(const uint8_t* buf, const uint64_t* offsets,
                             const uint32_t* lens, int32_t n,
                             const uint8_t* fwd, const uint8_t* is_remote,
                             const int32_t* node_ids,
                             const uint32_t* remote_ips, int32_t max_node_id,
                             uint32_t local_ip, uint32_t local_node_id,
                             uint32_t vni, uint8_t* out_buf, uint64_t out_cap,
                             uint64_t* out_offsets, uint32_t* out_lens,
                             int32_t* out_rows, int32_t* unroutable) {
  int32_t emitted = 0;
  uint64_t used = 0;
  int32_t skipped = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (!fwd[i] || !is_remote[i]) continue;
    int32_t nid = node_ids[i];
    uint32_t dst_ip = (nid >= 0 && nid <= max_node_id) ? remote_ips[nid] : 0;
    if (dst_ip == 0) {
      ++skipped;
      continue;
    }
    uint32_t inner_len = lens[i];
    uint32_t total = kOuterBytes + inner_len;
    if (used + total > out_cap) return -1;
    uint8_t* p = out_buf + used;

    // Outer Ethernet.
    node_mac(static_cast<uint32_t>(nid), p);            // dst MAC
    node_mac(local_node_id, p + 6);                     // src MAC
    store_be16(p + 12, kEthertypeIPv4);

    // Outer IPv4 (no options, DF, TTL 64).
    uint8_t* ip = p + 14;
    ip[0] = 0x45;
    ip[1] = 0;
    store_be16(ip + 2, static_cast<uint16_t>(20 + 8 + kVxlanHdrBytes + inner_len));
    store_be16(ip + 4, 0);        // identification
    store_be16(ip + 6, 0x4000);   // DF
    ip[8] = 64;                   // TTL
    ip[9] = kProtoUDP;
    store_be16(ip + 10, 0);
    store_be32(ip + 12, local_ip);
    store_be32(ip + 16, dst_ip);
    store_be16(ip + 10, ip_header_csum(ip));

    // Outer UDP: source port from the inner flow for ECMP entropy
    // (hash the inner IPv4 addresses + ports if present).
    const uint8_t* inner = buf + offsets[i];
    FrameView v = parse_frame(const_cast<uint8_t*>(inner), inner_len);
    uint32_t h = 0;
    if (v.valid) {
      h = load_be32(v.ip + 12) ^ (load_be32(v.ip + 16) * 2654435761u);
      if (v.has_ports) h ^= load_be32(v.l4);
      h ^= h >> 16;
    }
    uint8_t* udp = ip + 20;
    store_be16(udp, static_cast<uint16_t>(49152 + (h % 16384)));
    store_be16(udp + 2, kVxlanPort);
    store_be16(udp + 4, static_cast<uint16_t>(8 + kVxlanHdrBytes + inner_len));
    store_be16(udp + 6, 0);  // UDP checksum optional for v4 (RFC 7348 §5)

    // VXLAN header: flags (I bit), reserved, VNI, reserved.
    uint8_t* vx = udp + 8;
    vx[0] = 0x08;
    vx[1] = vx[2] = vx[3] = 0;
    store_be32(vx + 4, (vni << 8) & 0xffffff00);

    std::memcpy(vx + 4 + 4, inner, inner_len);
    out_offsets[emitted] = used;
    out_lens[emitted] = total;
    out_rows[emitted] = i;
    used += total;
    ++emitted;
  }
  if (unroutable != nullptr) *unroutable = skipped;
  return emitted;
}

// Classify + de-encapsulate VXLAN frames IN PLACE (offset adjustment,
// no copy).  For each frame: if it is a well-formed
// eth/IPv4/UDP(4789)/VXLAN frame, inner_offsets[i]/inner_lens[i]
// describe the inner Ethernet frame inside the same buffer and vnis[i]
// holds the VNI; otherwise inner_offsets[i] = offsets[i],
// inner_lens[i] = lens[i], vnis[i] = -1 (native frame, passthrough).
// Returns the number of decapped frames.
int32_t hs_vxlan_decap_batch(const uint8_t* buf, const uint64_t* offsets,
                             const uint32_t* lens, int32_t n,
                             uint64_t* inner_offsets, uint32_t* inner_lens,
                             int32_t* vnis) {
  int32_t decapped = 0;
  for (int32_t i = 0; i < n; ++i) {
    inner_offsets[i] = offsets[i];
    inner_lens[i] = lens[i];
    vnis[i] = -1;
    FrameView v = parse_frame(const_cast<uint8_t*>(buf + offsets[i]), lens[i]);
    if (!v.valid || v.proto != kProtoUDP || !v.has_ports) continue;
    if (load_be16(v.l4 + 2) != kVxlanPort) continue;
    const uint8_t* vx = v.l4 + 8;
    uint64_t l4_off = static_cast<uint64_t>(v.l4 - (buf + offsets[i]));
    if (lens[i] < l4_off + 8 + kVxlanHdrBytes + 14) continue;  // need inner eth
    if ((vx[0] & 0x08) == 0) continue;  // VNI bit not set
    inner_offsets[i] = offsets[i] + l4_off + 8 + kVxlanHdrBytes;
    inner_lens[i] = lens[i] - static_cast<uint32_t>(l4_off + 8 + kVxlanHdrBytes);
    vnis[i] = static_cast<int32_t>(load_be32(vx + 4) >> 8);
    ++decapped;
  }
  return decapped;
}

}  // extern "C"
