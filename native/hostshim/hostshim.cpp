// Host shim — native packet batch assembler / applier.
//
// The TPU-native analog of the reference's native transport layer
// (GoVPP shared-memory adapter + DPDK NIC IO, SURVEY.md §2.3): raw
// Ethernet frames are parsed into struct-of-arrays 5-tuple header
// vectors (what the jit pipeline consumes), and the pipeline's verdicts
// + NAT rewrites are applied back onto the frames with RFC 1624
// incremental checksum updates — per-packet byte work stays native,
// the TPU only ever sees fixed-shape header tensors.
//
// C ABI, consumed from Python via ctypes (no pybind11 in the image).
// Frames live in ONE contiguous buffer described by (offset, len)
// arrays — a single memcpy-free view for both sides.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint16_t kEthertypeIPv4 = 0x0800;
constexpr uint16_t kEthertypeVlan = 0x8100;
constexpr uint8_t kProtoTCP = 6;
constexpr uint8_t kProtoUDP = 17;

inline uint16_t load_be16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) << 8 | p[1];
}
inline uint32_t load_be32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) << 24 | static_cast<uint32_t>(p[1]) << 16 |
         static_cast<uint32_t>(p[2]) << 8 | p[3];
}
inline void store_be16(uint8_t* p, uint16_t v) {
  p[0] = v >> 8;
  p[1] = v & 0xff;
}
inline void store_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24;
  p[1] = (v >> 16) & 0xff;
  p[2] = (v >> 8) & 0xff;
  p[3] = v & 0xff;
}

// RFC 1624 eqn. 3: HC' = ~(~HC + ~m + m'), one 16-bit field update.
inline uint16_t csum_update16(uint16_t hc, uint16_t m_old, uint16_t m_new) {
  uint32_t sum = static_cast<uint32_t>(static_cast<uint16_t>(~hc)) +
                 static_cast<uint16_t>(~m_old) + m_new;
  while (sum >> 16) sum = (sum & 0xffff) + (sum >> 16);
  return static_cast<uint16_t>(~sum);
}

inline uint16_t csum_update32(uint16_t hc, uint32_t m_old, uint32_t m_new) {
  hc = csum_update16(hc, m_old >> 16, m_new >> 16);
  return csum_update16(hc, m_old & 0xffff, m_new & 0xffff);
}

struct FrameView {
  uint8_t* ip = nullptr;   // IPv4 header start
  uint8_t* l4 = nullptr;   // L4 header start (null if truncated/fragment)
  uint8_t proto = 0;
  bool valid = false;
  bool has_ports = false;
};

// Parse one frame: Ethernet II (+ optional single 802.1Q tag) → IPv4 →
// TCP/UDP ports.  Non-IPv4 and truncated frames yield valid=false; a
// non-first fragment keeps valid but has no port view.
FrameView parse_frame(uint8_t* frame, uint32_t len) {
  FrameView v;
  if (len < 14) return v;
  uint32_t off = 12;
  uint16_t ethertype = load_be16(frame + off);
  off += 2;
  if (ethertype == kEthertypeVlan) {
    if (len < off + 4) return v;
    ethertype = load_be16(frame + off + 2);
    off += 4;
  }
  if (ethertype != kEthertypeIPv4) return v;
  if (len < off + 20) return v;
  uint8_t* ip = frame + off;
  if ((ip[0] >> 4) != 4) return v;
  uint32_t ihl = static_cast<uint32_t>(ip[0] & 0x0f) * 4;
  if (ihl < 20 || len < off + ihl) return v;
  v.ip = ip;
  v.proto = ip[9];
  v.valid = true;
  uint16_t frag = load_be16(ip + 6);
  bool first_fragment = (frag & 0x1fff) == 0;
  if (!first_fragment) return v;  // ports live in the first fragment only
  if ((v.proto == kProtoTCP || v.proto == kProtoUDP) && len >= off + ihl + 4) {
    v.l4 = ip + ihl;
    v.has_ports = true;
  }
  return v;
}

}  // namespace

extern "C" {

// Parse n frames into SoA header arrays. flags: bit0 = IPv4, bit1 =
// ports present. Returns the number of IPv4 frames.
int32_t hs_parse_batch(const uint8_t* buf, const uint64_t* offsets,
                       const uint32_t* lens, int32_t n, uint32_t* src_ip,
                       uint32_t* dst_ip, int32_t* protocol, int32_t* src_port,
                       int32_t* dst_port, uint8_t* flags) {
  int32_t parsed = 0;
  for (int32_t i = 0; i < n; ++i) {
    // parse_frame does not write; const_cast confines the mutable API
    // to hs_apply_batch.
    FrameView v = parse_frame(const_cast<uint8_t*>(buf + offsets[i]), lens[i]);
    if (!v.valid) {
      src_ip[i] = dst_ip[i] = 0;
      protocol[i] = src_port[i] = dst_port[i] = 0;
      flags[i] = 0;
      continue;
    }
    src_ip[i] = load_be32(v.ip + 12);
    dst_ip[i] = load_be32(v.ip + 16);
    protocol[i] = v.proto;
    src_port[i] = v.has_ports ? load_be16(v.l4) : 0;
    dst_port[i] = v.has_ports ? load_be16(v.l4 + 2) : 0;
    flags[i] = static_cast<uint8_t>(1 | (v.has_ports ? 2 : 0));
    ++parsed;
  }
  return parsed;
}

// Apply verdicts + header rewrites in place. allowed[i] == 0 drops the
// frame (fwd[i] = 0). Changed IPs/ports are patched with incremental
// updates of the IPv4 header checksum and the TCP/UDP checksum
// (pseudo-header includes the IPs). Returns the forwarded count.
int32_t hs_apply_batch(uint8_t* buf, const uint64_t* offsets,
                       const uint32_t* lens, int32_t n, const uint8_t* allowed,
                       const uint32_t* new_src_ip, const uint32_t* new_dst_ip,
                       const int32_t* new_src_port, const int32_t* new_dst_port,
                       uint8_t* fwd) {
  int32_t forwarded = 0;
  for (int32_t i = 0; i < n; ++i) {
    FrameView v = parse_frame(buf + offsets[i], lens[i]);
    if (!v.valid || !allowed[i]) {
      fwd[i] = 0;
      continue;
    }
    fwd[i] = 1;
    ++forwarded;

    uint32_t old_src = load_be32(v.ip + 12);
    uint32_t old_dst = load_be32(v.ip + 16);
    uint16_t ip_csum = load_be16(v.ip + 10);

    uint8_t* l4_csum_p = nullptr;
    if (v.l4 != nullptr) {
      if (v.proto == kProtoTCP) {
        l4_csum_p = v.l4 + 16;
      } else if (v.proto == kProtoUDP && load_be16(v.l4 + 6) != 0) {
        l4_csum_p = v.l4 + 6;  // UDP checksum 0 = disabled, keep it so
      }
    }
    uint16_t l4_csum = l4_csum_p ? load_be16(l4_csum_p) : 0;

    if (new_src_ip[i] != old_src) {
      ip_csum = csum_update32(ip_csum, old_src, new_src_ip[i]);
      if (l4_csum_p) l4_csum = csum_update32(l4_csum, old_src, new_src_ip[i]);
      store_be32(v.ip + 12, new_src_ip[i]);
    }
    if (new_dst_ip[i] != old_dst) {
      ip_csum = csum_update32(ip_csum, old_dst, new_dst_ip[i]);
      if (l4_csum_p) l4_csum = csum_update32(l4_csum, old_dst, new_dst_ip[i]);
      store_be32(v.ip + 16, new_dst_ip[i]);
    }
    store_be16(v.ip + 10, ip_csum);

    if (v.has_ports) {
      uint16_t old_sport = load_be16(v.l4);
      uint16_t old_dport = load_be16(v.l4 + 2);
      uint16_t sport = static_cast<uint16_t>(new_src_port[i]);
      uint16_t dport = static_cast<uint16_t>(new_dst_port[i]);
      if (sport != old_sport) {
        if (l4_csum_p) l4_csum = csum_update16(l4_csum, old_sport, sport);
        store_be16(v.l4, sport);
      }
      if (dport != old_dport) {
        if (l4_csum_p) l4_csum = csum_update16(l4_csum, old_dport, dport);
        store_be16(v.l4 + 2, dport);
      }
    }
    if (l4_csum_p) store_be16(l4_csum_p, l4_csum);
  }
  return forwarded;
}

}  // extern "C"
