// Shared packet-plumbing helpers for the native host shim.
//
// Pulled out of hostshim.cpp so the batch API (hostshim.cpp) and the
// native runner loop (runnerloop.cpp) compile against one definition of
// frame parsing, RFC 1624 incremental checksums, and the VXLAN overlay
// header layout (the reference's full-mesh VNI-10 overlay,
// plugins/ipv4net/node.go vxlanIfToOtherNode :524).

#pragma once

#include <cstdint>
#include <cstring>

namespace hs {

constexpr uint16_t kEthertypeIPv4 = 0x0800;
constexpr uint16_t kEthertypeVlan = 0x8100;
constexpr uint8_t kProtoTCP = 6;
constexpr uint8_t kProtoUDP = 17;

constexpr uint16_t kVxlanPort = 4789;
constexpr uint32_t kVxlanHdrBytes = 8;
constexpr uint32_t kOuterBytes = 14 + 20 + 8 + kVxlanHdrBytes;  // 50

inline uint16_t load_be16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0]) << 8 | p[1];
}
inline uint32_t load_be32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) << 24 | static_cast<uint32_t>(p[1]) << 16 |
         static_cast<uint32_t>(p[2]) << 8 | p[3];
}
inline void store_be16(uint8_t* p, uint16_t v) {
  p[0] = v >> 8;
  p[1] = v & 0xff;
}
inline void store_be32(uint8_t* p, uint32_t v) {
  p[0] = v >> 24;
  p[1] = (v >> 16) & 0xff;
  p[2] = (v >> 8) & 0xff;
  p[3] = v & 0xff;
}

// Frame copy.  Measured on the bench Xeon (loopbench A/B): libc's
// memcpy (ERMS/AVX dispatch) beats a hand-rolled 8-byte-chunk inline
// copy even at ~61-byte frames (median 33.9 vs 32.1 Mpps through the
// full loop), so this stays a plain call — kept as a named seam so the
// next machine's A/B is one function swap.
inline void copy_frame_bytes(uint8_t* dst, const uint8_t* src, uint32_t len) {
  std::memcpy(dst, src, len);
}

// RFC 1624 eqn. 3: HC' = ~(~HC + ~m + m'), one 16-bit field update.
inline uint16_t csum_update16(uint16_t hc, uint16_t m_old, uint16_t m_new) {
  uint32_t sum = static_cast<uint32_t>(static_cast<uint16_t>(~hc)) +
                 static_cast<uint16_t>(~m_old) + m_new;
  while (sum >> 16) sum = (sum & 0xffff) + (sum >> 16);
  return static_cast<uint16_t>(~sum);
}

inline uint16_t csum_update32(uint16_t hc, uint32_t m_old, uint32_t m_new) {
  hc = csum_update16(hc, m_old >> 16, m_new >> 16);
  return csum_update16(hc, m_old & 0xffff, m_new & 0xffff);
}

struct FrameView {
  uint8_t* ip = nullptr;   // IPv4 header start
  uint8_t* l4 = nullptr;   // L4 header start (null if truncated/fragment)
  uint8_t proto = 0;
  bool valid = false;
  bool has_ports = false;
};

// Parse one frame: Ethernet II (+ optional single 802.1Q tag) → IPv4 →
// TCP/UDP ports.  Non-IPv4 and truncated frames yield valid=false; a
// non-first fragment keeps valid but has no port view.
inline FrameView parse_frame(uint8_t* frame, uint32_t len) {
  FrameView v;
  if (len < 14) return v;
  uint32_t off = 12;
  uint16_t ethertype = load_be16(frame + off);
  off += 2;
  if (ethertype == kEthertypeVlan) {
    if (len < off + 4) return v;
    ethertype = load_be16(frame + off + 2);
    off += 4;
  }
  if (ethertype != kEthertypeIPv4) return v;
  if (len < off + 20) return v;
  uint8_t* ip = frame + off;
  if ((ip[0] >> 4) != 4) return v;
  uint32_t ihl = static_cast<uint32_t>(ip[0] & 0x0f) * 4;
  if (ihl < 20 || len < off + ihl) return v;
  v.ip = ip;
  v.proto = ip[9];
  v.valid = true;
  uint16_t frag = load_be16(ip + 6);
  bool first_fragment = (frag & 0x1fff) == 0;
  if (!first_fragment) return v;  // ports live in the first fragment only
  if ((v.proto == kProtoTCP || v.proto == kProtoUDP) && len >= off + ihl + 4) {
    v.l4 = ip + ihl;
    v.has_ports = true;
  }
  return v;
}

// Node-ID-derived locally-administered MAC (the BVI-MAC convention:
// a fixed OUI-style prefix + the node ID).
inline void node_mac(uint32_t node_id, uint8_t* mac) {
  mac[0] = 0x02;
  mac[1] = 0x76;
  mac[2] = 0x70;
  mac[3] = 0x70;
  mac[4] = (node_id >> 8) & 0xff;
  mac[5] = node_id & 0xff;
}

// Full (non-incremental) IPv4 header checksum over 20 bytes.
inline uint16_t ip_header_csum(const uint8_t* hdr) {
  uint32_t sum = 0;
  for (int i = 0; i < 20; i += 2) {
    if (i == 10) continue;  // checksum field itself
    sum += load_be16(hdr + i);
  }
  while (sum >> 16) sum = (sum & 0xffff) + (sum >> 16);
  return static_cast<uint16_t>(~sum);
}

// VXLAN-classify one frame: if it is a well-formed
// eth/IPv4/UDP(4789)/VXLAN frame, returns the VNI (>= 0) and sets
// *inner_off / *inner_len to the inner Ethernet frame's position within
// the frame; otherwise returns -1 and leaves the whole frame.
inline int32_t vxlan_classify(const uint8_t* frame, uint32_t len,
                              uint32_t* inner_off, uint32_t* inner_len) {
  *inner_off = 0;
  *inner_len = len;
  FrameView v = parse_frame(const_cast<uint8_t*>(frame), len);
  if (!v.valid || v.proto != kProtoUDP || !v.has_ports) return -1;
  if (load_be16(v.l4 + 2) != kVxlanPort) return -1;
  const uint8_t* vx = v.l4 + 8;
  uint64_t l4_off = static_cast<uint64_t>(v.l4 - frame);
  if (len < l4_off + 8 + kVxlanHdrBytes + 14) return -1;  // need inner eth
  if ((vx[0] & 0x08) == 0) return -1;  // VNI bit not set
  *inner_off = static_cast<uint32_t>(l4_off + 8 + kVxlanHdrBytes);
  *inner_len = len - *inner_off;
  return static_cast<int32_t>(load_be32(vx + 4) >> 8);
}

// Write the 50-byte VXLAN overlay header for an inner frame of
// inner_len bytes into out (outer eth + IPv4 + UDP 4789 + VXLAN).
// entropy_h seeds the outer UDP source port (RFC 7348 §5 ECMP).
inline void write_vxlan_outer(uint8_t* out, uint32_t inner_len,
                              uint32_t local_ip, uint32_t dst_ip,
                              uint32_t local_node_id, uint32_t dst_node_id,
                              uint32_t vni, uint32_t entropy_h) {
  node_mac(dst_node_id, out);          // dst MAC
  node_mac(local_node_id, out + 6);    // src MAC
  store_be16(out + 12, kEthertypeIPv4);

  uint8_t* ip = out + 14;
  ip[0] = 0x45;
  ip[1] = 0;
  store_be16(ip + 2, static_cast<uint16_t>(20 + 8 + kVxlanHdrBytes + inner_len));
  store_be16(ip + 4, 0);        // identification
  store_be16(ip + 6, 0x4000);   // DF
  ip[8] = 64;                   // TTL
  ip[9] = kProtoUDP;
  store_be16(ip + 10, 0);
  store_be32(ip + 12, local_ip);
  store_be32(ip + 16, dst_ip);
  store_be16(ip + 10, ip_header_csum(ip));

  uint8_t* udp = ip + 20;
  store_be16(udp, static_cast<uint16_t>(49152 + (entropy_h & 16383)));
  store_be16(udp + 2, kVxlanPort);
  store_be16(udp + 4, static_cast<uint16_t>(8 + kVxlanHdrBytes + inner_len));
  store_be16(udp + 6, 0);  // UDP checksum optional for v4 (RFC 7348 §5)

  uint8_t* vx = udp + 8;
  vx[0] = 0x08;
  vx[1] = vx[2] = vx[3] = 0;
  store_be32(vx + 4, (vni << 8) & 0xffffff00);
}

// ECMP entropy hash over the inner flow (inner IPv4 addrs + ports).
inline uint32_t flow_entropy(const uint8_t* inner, uint32_t inner_len) {
  FrameView v = parse_frame(const_cast<uint8_t*>(inner), inner_len);
  uint32_t h = 0;
  if (v.valid) {
    h = load_be32(v.ip + 12) ^ (load_be32(v.ip + 16) * 2654435761u);
    if (v.has_ports) h ^= load_be32(v.l4);
    h ^= h >> 16;
  }
  return h;
}

// Apply a verdict + 5-tuple rewrite to one parsed frame in place with
// incremental checksum updates.  Returns false for unparseable frames.
inline bool apply_rewrite(uint8_t* frame, uint32_t len, uint32_t new_src_ip,
                          uint32_t new_dst_ip, uint16_t new_sport,
                          uint16_t new_dport) {
  FrameView v = parse_frame(frame, len);
  if (!v.valid) return false;

  uint32_t old_src = load_be32(v.ip + 12);
  uint32_t old_dst = load_be32(v.ip + 16);
  uint16_t ip_csum = load_be16(v.ip + 10);

  uint8_t* l4_csum_p = nullptr;
  if (v.l4 != nullptr) {
    if (v.proto == kProtoTCP) {
      l4_csum_p = v.l4 + 16;
    } else if (v.proto == kProtoUDP && load_be16(v.l4 + 6) != 0) {
      l4_csum_p = v.l4 + 6;  // UDP checksum 0 = disabled, keep it so
    }
  }
  uint16_t l4_csum = l4_csum_p ? load_be16(l4_csum_p) : 0;

  if (new_src_ip != old_src) {
    ip_csum = csum_update32(ip_csum, old_src, new_src_ip);
    if (l4_csum_p) l4_csum = csum_update32(l4_csum, old_src, new_src_ip);
    store_be32(v.ip + 12, new_src_ip);
  }
  if (new_dst_ip != old_dst) {
    ip_csum = csum_update32(ip_csum, old_dst, new_dst_ip);
    if (l4_csum_p) l4_csum = csum_update32(l4_csum, old_dst, new_dst_ip);
    store_be32(v.ip + 16, new_dst_ip);
  }
  store_be16(v.ip + 10, ip_csum);

  if (v.has_ports) {
    uint16_t old_sport = load_be16(v.l4);
    uint16_t old_dport = load_be16(v.l4 + 2);
    if (new_sport != old_sport) {
      if (l4_csum_p) l4_csum = csum_update16(l4_csum, old_sport, new_sport);
      store_be16(v.l4, new_sport);
    }
    if (new_dport != old_dport) {
      if (l4_csum_p) l4_csum = csum_update16(l4_csum, old_dport, new_dport);
      store_be16(v.l4 + 2, new_dport);
    }
  }
  if (l4_csum_p) store_be16(l4_csum_p, l4_csum);
  return true;
}

}  // namespace hs
