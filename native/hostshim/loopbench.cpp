// Standalone frame-loop microbench + phase profile (NOT part of the
// shipped .so).  Replicates scripts/frame_bench.py --host-path without
// Python in the loop so the C++ admit/harvest path can be profiled in
// isolation: same ring plumbing, same verdict/route arithmetic, same
// traffic shape (pod-to-pod local / cross-node remote / egress host
// mix over minimal TCP frames).
//
// Build: make loopbench   (native/hostshim/Makefile)
// Run:   ../build/loopbench [frames] [rounds]
//
// Prints per-phase cycle costs (rdtsc) and the end-to-end Mpps the
// loop sustains — the profile artifact the round-4 verdict asked for
// before/after the SIMD work on the per-frame path.

#include <algorithm>
#include <atomic>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <x86intrin.h>

#include "common.h"

using namespace hs;

// ---- extern API of runnerloop.cpp (opaque handles) ------------------------
struct HsRing;
struct HsLoop;
extern "C" {
HsRing* hs_ring_new(uint64_t arena_bytes, uint32_t max_frames);
void hs_ring_free(HsRing* r);
uint32_t hs_ring_count(HsRing* r);
int32_t hs_ring_push(HsRing* r, const uint8_t* buf, const uint64_t* offsets,
                     const uint32_t* lens, int32_t n);
int32_t hs_ring_pop(HsRing* r, uint8_t* out_buf, uint64_t out_cap,
                    uint64_t* out_offsets, uint32_t* out_lens,
                    int32_t max_frames);
HsLoop* hs_loop_new(HsRing* rx, HsRing* tx_remote, HsRing* tx_local,
                    HsRing* tx_host, uint32_t batch_size, uint32_t max_vectors,
                    uint32_t vni, uint32_t n_slots);
void hs_loop_free(HsLoop* lp);
int32_t hs_loop_admit(HsLoop* lp, int32_t slot_idx, uint32_t* src_ip,
                      uint32_t* dst_ip, int32_t* protocol, int32_t* src_port,
                      int32_t* dst_port, int32_t* k_out, uint64_t* counters,
                      int32_t k_cap);
int32_t hs_loop_harvest(HsLoop* lp, int32_t slot_idx, const uint8_t* allowed,
                        const uint32_t* new_src, const uint32_t* new_dst,
                        const int32_t* new_sport, const int32_t* new_dport,
                        const int32_t* route_tag, const int32_t* node_id,
                        const uint32_t* remote_ips, int32_t max_node_id,
                        uint32_t local_ip, uint32_t local_node_id,
                        uint64_t* counters);
int32_t hs_loop_hostpath(HsLoop* lp, int32_t slot_idx, uint32_t pod_base,
                         uint32_t pod_mask, uint32_t node_base,
                         uint32_t node_mask, uint32_t host_bits,
                         const uint32_t* remote_ips, int32_t max_node_id,
                         uint32_t local_ip, uint32_t local_node_id,
                         uint64_t* admit_counters, uint64_t* harvest_counters,
                         int32_t* sent_out);
int32_t hs_fanout_push(HsRing* const* rings, int32_t n_rings,
                       const uint8_t* buf, const uint64_t* offsets,
                       const uint32_t* lens, int32_t n, int32_t mode);
}

namespace {

constexpr uint32_t kPodBase = (10u << 24) | (1u << 16);          // 10.1.0.0/16
constexpr uint32_t kPodMask = 0xFFFF0000u;
constexpr uint32_t kNodeBase = (10u << 24) | (1u << 16) | (1u << 8);  // /24
constexpr uint32_t kNodeMask = 0xFFFFFF00u;
constexpr uint32_t kHostBits = 8;
constexpr int32_t kMaxNode = 63;
constexpr int32_t kRouteLocal = 1, kRouteRemote = 2, kRouteHost = 3;

uint16_t csum16(const uint8_t* p, size_t n, uint32_t seed = 0) {
  uint32_t s = seed;
  for (size_t i = 0; i + 1 < n; i += 2) s += load_be16(p + i);
  if (n & 1) s += static_cast<uint32_t>(p[n - 1]) << 8;
  while (s >> 16) s = (s & 0xffff) + (s >> 16);
  return static_cast<uint16_t>(~s);
}

// Minimal Ethernet/IPv4/TCP frame with correct checksums (the
// vpp_tpu.testing.frames.build_frame shape: 5-byte payload, 61 bytes).
size_t build_tcp_frame(uint8_t* out, uint32_t src, uint32_t dst,
                       uint16_t sport, uint16_t dport) {
  static const uint8_t payload[5] = {'h', 'e', 'l', 'l', 'o'};
  uint8_t* p = out;
  std::memset(p, 0, 14);
  p[0] = 0x02; p[5] = 0x02; p[6] = 0x02; p[11] = 0x01;
  store_be16(p + 12, kEthertypeIPv4);
  uint8_t* ip = p + 14;
  ip[0] = 0x45; ip[1] = 0;
  uint16_t l4_len = 20 + sizeof(payload);
  store_be16(ip + 2, 20 + l4_len);
  store_be16(ip + 4, 0x1234);
  store_be16(ip + 6, 0);
  ip[8] = 64; ip[9] = kProtoTCP;
  store_be16(ip + 10, 0);
  store_be32(ip + 12, src);
  store_be32(ip + 16, dst);
  store_be16(ip + 10, ip_header_csum(ip));
  uint8_t* tcp = ip + 20;
  std::memset(tcp, 0, 20);
  store_be16(tcp, sport);
  store_be16(tcp + 2, dport);
  store_be32(tcp + 4, 1);
  tcp[12] = 5 << 4; tcp[13] = 0x18;
  store_be16(tcp + 14, 8192);
  std::memcpy(tcp + 20, payload, sizeof(payload));
  // TCP checksum over pseudo header + segment.
  uint8_t pseudo[12];
  store_be32(pseudo, src);
  store_be32(pseudo + 4, dst);
  pseudo[8] = 0; pseudo[9] = kProtoTCP;
  store_be16(pseudo + 10, l4_len);
  uint32_t s = 0;
  for (int i = 0; i < 12; i += 2) s += load_be16(pseudo + i);
  uint16_t c = csum16(tcp, l4_len, s);
  store_be16(tcp + 16, c);
  return 14 + 20 + l4_len;
}

}  // namespace

int main(int argc, char** argv) {
  const int32_t n_frames = argc > 1 ? atoi(argv[1]) : 16384;
  const int rounds = argc > 2 ? atoi(argv[2]) : 9;
  // mode: mixed (default) | local | remote | host | denied — uniform
  // modes isolate one harvest path each for the phase profile.
  // "fused" runs the mixed mix through hs_loop_hostpath (the runner's
  // host-bypass batch) instead of split admit/route/harvest calls.
  // "threaded" replays the legacy N-pushers-vs-one-consumer shape (N
  // producer threads pushing into ONE rx ring while the main thread
  // admits/harvests concurrently).  "sharded" replays the REAL
  // many-core ShardedDataplane shape (ISSUE 12): one fanout feeder
  // distributing the stream across N independent rings via
  // hs_fanout_push while N consumer threads each drive their own
  // loop's admit→route→harvest — the workload `make native-sanitize`
  // runs under TSan to race-check the fanout handoff + per-ring mutex
  // discipline.
  const char* mode = argc > 3 ? argv[3] : "mixed";
  const bool fused = mode[0] == 'f';
  const bool threaded = mode[0] == 't';
  const bool sharded = mode[0] == 's';
  // Clamp: atoi("garbage") and an explicit 0 both mean "no pushers",
  // which would divide by zero in the slice math below.
  const int n_pushers =
      threaded ? std::max(1, argc > 4 ? atoi(argv[4]) : 4) : 0;
  const uint32_t batch = 256, vectors = 64;

  HsRing* rx = hs_ring_new(64u << 20, 1u << 17);
  HsRing* txr = hs_ring_new(64u << 20, 1u << 17);
  HsRing* txl = hs_ring_new(64u << 20, 1u << 17);
  HsRing* txh = hs_ring_new(64u << 20, 1u << 17);
  HsLoop* lp = hs_loop_new(rx, txr, txl, txh, batch, vectors, 10, 2);

  // Traffic mix ~ frame_bench's stress shape: 60% local pod-to-pod,
  // 30% cross-node remote, 10% egress-to-world (host).
  std::vector<uint8_t> buf(static_cast<size_t>(n_frames) * 64);
  std::vector<uint64_t> offs(n_frames);
  std::vector<uint32_t> lens(n_frames);
  uint64_t off = 0;
  uint32_t rng = 0x5DEECE66u;
  for (int32_t i = 0; i < n_frames; ++i) {
    rng = rng * 1664525u + 1013904223u;
    uint32_t roll = (rng >> 16) % 10;
    if (mode[0] == 'l') roll = 0;        // all local
    else if (mode[0] == 'r') roll = 7;   // all remote
    else if (mode[0] == 'h') roll = 9;   // all host
    uint32_t src = kNodeBase | (2 + (rng % 200));
    uint32_t dst;
    if (roll < 6) {
      dst = kNodeBase | (2 + ((rng >> 8) % 200));          // local
    } else if (roll < 9) {
      uint32_t node = 2 + ((rng >> 8) % 40);               // remote node
      dst = kPodBase | (node << 8) | (2 + ((rng >> 4) % 200));
    } else {
      dst = (93u << 24) | (184u << 16) | (216u << 8) | 34; // egress
    }
    offs[i] = off;
    lens[i] = static_cast<uint32_t>(build_tcp_frame(
        buf.data() + off, src, dst, static_cast<uint16_t>(40000 + (i % 8192)),
        80));
    off += 64;
  }

  std::vector<uint32_t> remote_ips(kMaxNode + 1, 0);
  for (int n = 2; n <= kMaxNode; ++n)
    remote_ips[n] = (192u << 24) | (168u << 16) | (16u << 8) | n;
  const uint32_t local_ip = (192u << 24) | (168u << 16) | (16u << 8) | 1;

  const int32_t budget = batch * vectors;
  std::vector<uint32_t> src_ip(budget), dst_ip(budget);
  std::vector<int32_t> proto(budget), sport(budget), dport(budget);
  std::vector<uint8_t> allowed(budget, mode[0] == 'd' ? 0 : 1);
  std::vector<int32_t> route(budget), node_id(budget);
  uint64_t admit_c[3] = {0, 0, 0}, harv_c[6] = {0, 0, 0, 0, 0, 0};
  std::vector<uint8_t> popbuf(64u << 20);
  std::vector<uint64_t> popoffs(1u << 17);
  std::vector<uint32_t> poplens(1u << 17);

  auto drain = [&]() {
    for (HsRing* r : {txr, txl, txh})
      while (hs_ring_pop(r, popbuf.data(), popbuf.size(), popoffs.data(),
                         poplens.data(), 1 << 17) > 0) {
      }
  };

  if (sharded) {
    // The solo plumbing above is unused here — free it before the
    // N-shard run (loopbench.asan runs with leak detection ON).
    hs_loop_free(lp);
    hs_ring_free(rx);
    hs_ring_free(txr);
    hs_ring_free(txl);
    hs_ring_free(txh);
    const int n_shards = std::max(1, argc > 4 ? atoi(argv[4]) : 4);
    struct Shard {
      HsRing* rx;
      HsRing* txr;
      HsRing* txl;
      HsRing* txh;
      HsLoop* lp;
    };
    std::vector<Shard> shards(static_cast<size_t>(n_shards));
    std::vector<HsRing*> rx_rings(static_cast<size_t>(n_shards));
    for (int s = 0; s < n_shards; ++s) {
      Shard& sh = shards[s];
      sh.rx = hs_ring_new(64u << 20, 1u << 17);
      sh.txr = hs_ring_new(64u << 20, 1u << 17);
      sh.txl = hs_ring_new(64u << 20, 1u << 17);
      sh.txh = hs_ring_new(64u << 20, 1u << 17);
      sh.lp = hs_loop_new(sh.rx, sh.txr, sh.txl, sh.txh, batch, vectors, 10, 2);
      rx_rings[s] = sh.rx;
    }
    auto drain_shards = [&]() {
      for (const Shard& sh : shards)
        for (HsRing* r : {sh.txr, sh.txl, sh.txh})
          while (hs_ring_pop(r, popbuf.data(), popbuf.size(), popoffs.data(),
                             poplens.data(), 1 << 17) > 0) {
          }
    };
    std::vector<double> s_mpps;
    std::vector<double> per_shard_share(static_cast<size_t>(n_shards), 0.0);
    uint64_t tx_total[3] = {0, 0, 0};
    for (int r = 0; r < rounds + 1; ++r) {  // round 0 = warm-up
      std::atomic<int> feeding{1};
      std::atomic<int64_t> done_total{0};
      std::vector<int64_t> done_shard(static_cast<size_t>(n_shards), 0);
      uint64_t t0 = __rdtsc();
      std::thread feeder([&]() {
        const int32_t burst = 512;
        for (int32_t i = 0; i < n_frames; i += burst) {
          int32_t nb = std::min(burst, n_frames - i);
          hs_fanout_push(rx_rings.data(), n_shards, buf.data(),
                         offs.data() + i, lens.data() + i, nb, /*hash*/ 0);
        }
        feeding.store(0);
      });
      std::vector<std::thread> consumers;
      for (int s = 0; s < n_shards; ++s) {
        consumers.emplace_back([&, s]() {
          Shard& sh = shards[s];
          std::vector<uint32_t> c_src(budget), c_dst(budget);
          std::vector<int32_t> c_proto(budget), c_sport(budget),
              c_dport(budget);
          std::vector<uint8_t> c_allowed(budget, 1);
          std::vector<int32_t> c_route(budget), c_node(budget);
          uint64_t c_admit[3] = {0, 0, 0};
          uint64_t c_harv[6] = {0, 0, 0, 0, 0, 0};
          int64_t done = 0;
          bool final_pass = false;
          while (true) {
            int32_t k = 0;
            int32_t n = hs_loop_admit(sh.lp, 0, c_src.data(), c_dst.data(),
                                      c_proto.data(), c_sport.data(),
                                      c_dport.data(), &k, c_admit,
                                      /*k_cap=*/0);
            if (n <= 0) {
              if (feeding.load() > 0) {
                std::this_thread::yield();
                continue;
              }
              if (!final_pass) {
                // One more admit after the feeder provably finished:
                // its last push can land after our empty admit.
                final_pass = true;
                continue;
              }
              break;
            }
            final_pass = false;
            for (int32_t i = 0; i < n; ++i) {
              uint32_t d = c_dst[i];
              c_route[i] = (d & kNodeMask) == kNodeBase   ? kRouteLocal
                           : (d & kPodMask) == kPodBase   ? kRouteRemote
                                                          : kRouteHost;
              c_node[i] = static_cast<int32_t>((d - kPodBase) >> kHostBits);
            }
            hs_loop_harvest(sh.lp, 0, c_allowed.data(), c_src.data(),
                            c_dst.data(), c_sport.data(), c_dport.data(),
                            c_route.data(), c_node.data(), remote_ips.data(),
                            kMaxNode, local_ip, 1, c_harv);
            done += n;
          }
          done_shard[s] = done;
          done_total.fetch_add(done);
          if (r > 0)
            for (int j = 0; j < 3; ++j)
              __atomic_fetch_add(&tx_total[j], c_harv[j], __ATOMIC_RELAXED);
        });
      }
      feeder.join();
      for (auto& th : consumers) th.join();
      uint64_t t1 = __rdtsc();
      drain_shards();
      if (r == 0 || done_total.load() == 0) continue;
      double secs = static_cast<double>(t1 - t0) / 2.1e9;
      s_mpps.push_back(done_total.load() / secs / 1e6);
      for (int s = 0; s < n_shards; ++s)
        per_shard_share[s] +=
            static_cast<double>(done_shard[s]) / done_total.load();
    }
    std::sort(s_mpps.begin(), s_mpps.end());
    double median = s_mpps.empty() ? 0.0 : s_mpps[s_mpps.size() / 2];
    printf("{\"metric\": \"loopbench sharded (fanout feeder -> %d shards)\", "
           "\"shards\": %d, \"frames\": %d, \"rounds\": %d, "
           "\"median_mpps\": %.3f, \"peak_mpps\": %.3f, "
           "\"per_shard_mpps\": %.3f, "
           "\"share_min\": %.3f, \"share_max\": %.3f, "
           "\"tx\": [%" PRIu64 ", %" PRIu64 ", %" PRIu64 "]}\n",
           n_shards, n_shards, n_frames, rounds, median,
           s_mpps.empty() ? 0.0 : s_mpps.back(), median / n_shards,
           rounds ? *std::min_element(per_shard_share.begin(),
                                      per_shard_share.end()) / rounds : 0.0,
           rounds ? *std::max_element(per_shard_share.begin(),
                                      per_shard_share.end()) / rounds : 0.0,
           tx_total[0], tx_total[1], tx_total[2]);
    for (Shard& sh : shards) {
      hs_loop_free(sh.lp);
      hs_ring_free(sh.rx);
      hs_ring_free(sh.txr);
      hs_ring_free(sh.txl);
      hs_ring_free(sh.txh);
    }
    return 0;
  }

  // Per-round phase sums; medians reported (this box shows VM-steal
  // spikes — a mean would fold multi-ms preemptions into the figure).
  std::vector<double> r_admit, r_route, r_harv, mpps;
  double best_mpps = 0, sum_mpps = 0;
  for (int r = 0; r < rounds + 1; ++r) {  // round 0 = warm-up
    std::atomic<int> live_pushers{0};
    std::vector<std::thread> pushers;
    if (threaded) {
      // ShardedDataplane shape: producers feed the rx ring while the
      // consumer admits concurrently — every push/admit contends on
      // the HsRing mutex, which is exactly what TSan must watch.
      live_pushers = n_pushers;
      const int32_t per = n_frames / n_pushers;
      for (int t = 0; t < n_pushers; ++t) {
        const int32_t start = t * per;
        const int32_t end = (t == n_pushers - 1) ? n_frames : start + per;
        pushers.emplace_back([&, start, end]() {
          const int32_t burst = 512;
          for (int32_t i = start; i < end; i += burst) {
            int32_t n = std::min(burst, end - i);
            hs_ring_push(rx, buf.data(), offs.data() + i, lens.data() + i, n);
          }
          live_pushers.fetch_sub(1);
        });
      }
    } else {
      hs_ring_push(rx, buf.data(), offs.data(), lens.data(), n_frames);
    }
    uint64_t cyc_admit = 0, cyc_route = 0, cyc_harvest = 0;
    uint64_t t0 = __rdtsc();
    int32_t done = 0;
    bool final_pass = false;  // one re-admit after the last pusher exits
    while (true) {
      int32_t k = 0;
      if (fused) {
        int32_t sent = 0;
        int32_t n = hs_loop_hostpath(
            lp, 0, kPodBase, kPodMask, kNodeBase, kNodeMask, kHostBits,
            remote_ips.data(), kMaxNode, local_ip, 1, admit_c, harv_c, &sent);
        if (n <= 0) break;
        done += n;
        continue;
      }
      uint64_t a0 = __rdtsc();
      int32_t n = hs_loop_admit(lp, 0, src_ip.data(), dst_ip.data(),
                                proto.data(), sport.data(), dport.data(), &k,
                                admit_c, /*k_cap=*/0);
      uint64_t a1 = __rdtsc();
      if (n <= 0) {
        if (live_pushers.load() > 0) {
          std::this_thread::yield();  // producers still filling the ring
          continue;
        }
        if (threaded && !final_pass) {
          // The last pusher's final push can land after our empty
          // admit but before its counter decrement — admit once more
          // now that live_pushers==0 guarantees every push completed.
          final_pass = true;
          continue;
        }
        break;
      }
      for (int32_t i = 0; i < n; ++i) {  // vectorizable verdict/route
        uint32_t d = dst_ip[i];
        int32_t tag = (d & kNodeMask) == kNodeBase   ? kRouteLocal
                      : (d & kPodMask) == kPodBase   ? kRouteRemote
                                                     : kRouteHost;
        route[i] = tag;
        node_id[i] = static_cast<int32_t>((d - kPodBase) >> kHostBits);
      }
      uint64_t a2 = __rdtsc();
      hs_loop_harvest(lp, 0, allowed.data(), src_ip.data(), dst_ip.data(),
                      sport.data(), dport.data(), route.data(), node_id.data(),
                      remote_ips.data(), kMaxNode, local_ip, 1, harv_c);
      uint64_t a3 = __rdtsc();
      cyc_admit += a1 - a0;
      cyc_route += a2 - a1;
      cyc_harvest += a3 - a2;
      done += n;
    }
    uint64_t t1 = __rdtsc();
    for (auto& th : pushers) th.join();
    drain();
    if (r == 0 || done == 0) continue;
    r_admit.push_back(static_cast<double>(cyc_admit) / done);
    r_route.push_back(static_cast<double>(cyc_route) / done);
    r_harv.push_back(static_cast<double>(cyc_harvest) / done);
    // TSC ticks at the base clock (2.1 GHz on this box).
    double secs = static_cast<double>(t1 - t0) / 2.1e9;
    double m = done / secs / 1e6;
    mpps.push_back(m);
    sum_mpps += m;
    if (m > best_mpps) best_mpps = m;
  }

  auto med = [](std::vector<double>& v) {
    if (v.empty()) return 0.0;  // fused mode has no phase split
    std::sort(v.begin(), v.end());
    return v[v.size() / 2];
  };
  double per_admit = med(r_admit);
  double per_route = med(r_route);
  double per_harv = med(r_harv);
  double per_total = per_admit + per_route + per_harv;
  double median = med(mpps);
  printf("{\"metric\": \"loopbench host frame path\", "
         "\"frames\": %d, \"rounds\": %d, "
         "\"median_mpps\": %.3f, \"peak_mpps\": %.3f, \"mean_mpps\": %.3f, "
         "\"cycles_per_frame\": {\"admit\": %.1f, \"route\": %.1f, "
         "\"harvest\": %.1f, \"total\": %.1f}, "
         "\"tx\": [%" PRIu64 ", %" PRIu64 ", %" PRIu64 "], "
         "\"denied\": %" PRIu64 ", \"unparseable\": %" PRIu64 "}\n",
         n_frames, rounds, median, best_mpps, sum_mpps / rounds,
         per_admit, per_route, per_harv, per_total,
         harv_c[0], harv_c[1], harv_c[2], harv_c[3], harv_c[4]);

  hs_loop_free(lp);
  hs_ring_free(rx);
  hs_ring_free(txr);
  hs_ring_free(txl);
  hs_ring_free(txh);
  return 0;
}
