// Native runner loop — ring buffers + admit/harvest in C++.
//
// Round-2 verdict item 1: the DataplaneRunner's orchestration (ring
// handling, per-frame bytes objects, harvest bookkeeping) was Python
// and capped the frame path at ~0.2 Mpps while the TPU kernel did
// hundreds.  This file moves the whole frame side native — the role
// VPP's C main loop + dpdk-input plays in the reference
// (/root/reference/vpp.env:1-3, docs/ARCHITECTURE.md:20):
//
//   HsRing   — thread-safe frame ring: contiguous byte arena +
//              (offset, len) descriptor FIFO.  Producers (AF_PACKET
//              RX, the virtual wire, Python test harnesses) push
//              frames in; the loop reads them without per-frame Python.
//   HsLoop   — per-node datapath state: admit READS (zero-copy) up to
//              batch_size*max_vectors frames from the rx ring,
//              VXLAN-declassifies, VNI-filters, and parses the inner
//              frames straight out of the ring arena into the SoA
//              header arrays the jit pipeline consumes — ONE ctypes
//              call, ZERO frame copies.  harvest applies verdicts +
//              NAT rewrites in place in the arena (RFC 1624 checksums,
//              against the IP/L4 offsets cached at admit so frames are
//              parsed exactly once), VXLAN-encapsulates ROUTE_REMOTE
//              frames from a precomputed header template, pushes to
//              the remote/local/host TX rings, then RELEASES the
//              batch's arena bytes — ONE ctypes call.
//
// Round-3 verdict item 1 (this round): the admit path used to copy
// every kept frame into a per-slot staging buffer (a value-initialised
// resize + memcpy = every frame byte written twice) and harvest used
// to re-parse every frame from scratch.  Both are gone: frames now
// live in the rx arena from ingest to TX, pinned by a read/release
// cursor split on the ring (read_pos marks descriptors handed to
// in-flight batches; release frees them FIFO after harvest).  The
// VXLAN outer header is stamped from a 50-byte template whose IP
// checksum is patched incrementally for the per-frame fields instead
// of being recomputed over the header.
//
// Python's remaining per-batch work is dispatching the jit pipeline,
// servicing punts through the host slow path, and swapping tables.
// For multi-core hosts, N loops (one per ring shard) driven from N
// Python threads run concurrently — these calls release the GIL, so
// the C++ frame work scales across cores while device dispatches stay
// serialised on the main thread (the VPP worker/handoff model; see
// vpp_tpu/datapath/shards.py).
//
// AF_PACKET ingest/egress ride recvmmsg/sendmmsg directly between the
// socket and a ring (the DPDK-burst analog on kernel sockets);
// multi-queue fanout (PACKET_FANOUT) is configured socket-side in
// vpp_tpu/datapath/io.py.

#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

#include <sys/socket.h>

#include "common.h"

using namespace hs;

namespace {

constexpr uint32_t kAfpBurst = 64;
constexpr uint32_t kAfpFrameCap = 2048;

struct Desc {
  uint64_t off;
  uint32_t len;
};

}  // namespace

// ---------------------------------------------------------------------------
// HsRing
// ---------------------------------------------------------------------------

struct HsRing {
  std::mutex mu;
  std::vector<uint8_t> arena;
  std::vector<Desc> descs;
  uint32_t cap_frames;
  uint32_t head = 0;       // descriptor index of the oldest LIVE frame
  uint32_t count = 0;      // live frames (read-but-pinned + unread)
  uint32_t read_pos = 0;   // frames at the front already read (pinned)
  uint64_t tail_off = 0;   // next arena write offset
  uint64_t dropped = 0;    // frames dropped because the ring was full

  HsRing(uint64_t arena_bytes, uint32_t max_frames)
      : arena(arena_bytes), descs(max_frames), cap_frames(max_frames) {}

  // Contiguous-arena reservation with wraparound (bip-buffer style:
  // frames never straddle the arena end; the writer wraps to 0 when
  // the tail region is too small and the head has moved on).  Pinned
  // (read-but-unreleased) frames count as live — producers can never
  // overwrite a frame an in-flight batch still references.
  // Caller must hold mu.  Returns nullptr when there is no room.
  uint8_t* reserve_locked(uint32_t len) {
    if (count == cap_frames) return nullptr;
    if (count == 0) tail_off = 0;
    uint64_t cap_b = arena.size();
    if (len > cap_b) return nullptr;
    uint64_t head_off = count ? descs[head].off : 0;
    if (count == 0 || head_off <= tail_off) {
      // Live bytes (if any) sit in [head_off, tail_off); free space is
      // the tail segment plus the wrapped prefix before head_off.
      if (tail_off + len <= cap_b) return arena.data() + tail_off;
      if (len < head_off) {
        tail_off = 0;  // wrap; the skipped tail bytes are implicitly free
        return arena.data();
      }
      return nullptr;
    }
    // Wrapped: live bytes in [head_off, end) + [0, tail_off); free is
    // [tail_off, head_off).  Strict < keeps tail != head while live.
    if (tail_off + len < head_off) return arena.data() + tail_off;
    return nullptr;
  }

  void commit_locked(uint32_t len) {
    // head < cap and count <= cap, so one conditional subtract replaces
    // the % — a runtime modulus is a ~20-cycle divide PER FRAME, which
    // profiling showed near the top of the whole loop's cycle budget.
    uint32_t idx = head + count;
    if (idx >= cap_frames) idx -= cap_frames;
    descs[idx] = {tail_off, len};
    tail_off += len;
    ++count;
  }

  bool push_one_locked(const uint8_t* data, uint32_t len) {
    uint8_t* dst = reserve_locked(len);
    if (dst == nullptr) {
      ++dropped;
      return false;
    }
    copy_frame_bytes(dst, data, len);
    commit_locked(len);
    return true;
  }

  // Free k read frames from the front (FIFO).  Caller must hold mu.
  void release_locked(uint32_t k) {
    head += k;  // k <= count <= cap: one conditional subtract suffices
    if (head >= cap_frames) head -= cap_frames;
    count -= k;
    read_pos -= k;
  }
};

extern "C" {

HsRing* hs_ring_new(uint64_t arena_bytes, uint32_t max_frames) {
  if (arena_bytes == 0 || max_frames == 0) return nullptr;
  return new HsRing(arena_bytes, max_frames);
}

void hs_ring_free(HsRing* r) { delete r; }

uint32_t hs_ring_count(HsRing* r) {
  std::lock_guard<std::mutex> g(r->mu);
  return r->count - r->read_pos;  // frames available to read
}

uint64_t hs_ring_dropped(HsRing* r) {
  std::lock_guard<std::mutex> g(r->mu);
  return r->dropped;
}

// Push n frames described by (offsets, lens) views into buf.
// Returns the number accepted; the rest are counted in dropped.
int32_t hs_ring_push(HsRing* r, const uint8_t* buf, const uint64_t* offsets,
                     const uint32_t* lens, int32_t n) {
  std::lock_guard<std::mutex> g(r->mu);
  int32_t pushed = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (r->push_one_locked(buf + offsets[i], lens[i])) ++pushed;
  }
  return pushed;
}

// Pop up to max_frames frames, packing them contiguously into out_buf
// (capacity out_cap bytes) and recording (out_offsets, out_lens).
// Returns the number popped; stops early when out_buf is full.
// Returns -1 if zero-copy readers hold pinned frames (a ring being
// consumed by a live HsLoop batch must not be popped concurrently —
// that is a caller bug, not a transient state).
int32_t hs_ring_pop(HsRing* r, uint8_t* out_buf, uint64_t out_cap,
                    uint64_t* out_offsets, uint32_t* out_lens,
                    int32_t max_frames) {
  std::lock_guard<std::mutex> g(r->mu);
  if (r->read_pos != 0) return -1;
  int32_t popped = 0;
  uint64_t used = 0;
  while (r->count > 0 && popped < max_frames) {
    Desc d = r->descs[r->head];
    if (used + d.len > out_cap) break;
    std::memcpy(out_buf + used, r->arena.data() + d.off, d.len);
    out_offsets[popped] = used;
    out_lens[popped] = d.len;
    used += d.len;
    if (++r->head == r->cap_frames) r->head = 0;
    --r->count;
    ++popped;
  }
  return popped;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// HsLoop — the per-node admit/harvest engine
// ---------------------------------------------------------------------------

namespace {

// One admitted frame: a view into the rx-ring arena plus the parse
// offsets AND the pre-pipeline 5-tuple cached at admit, so harvest
// never re-parses — and never even touches the frame bytes when the
// pipeline's rewrite values match what admit read (the pass-through
// case, most frames of a policy-allow / non-service mix).
//
// Layout note (measured): keeping the cached tuple INLINE here beats a
// separate-SoA layout with a vectorized change-detection pass by ~10%
// through the whole loop — harvest touches each FrameRef row anyway
// for off/len, so the tuple rides the same cache line, while the SoA
// variant paid five extra array streams for a compare that was never
// the bottleneck.
struct FrameRef {
  uint64_t off;      // inner-frame start within the rx arena
  uint32_t len;      // inner-frame length
  uint32_t old_src;  // 5-tuple as parsed at admit (host byte order)
  uint32_t old_dst;
  uint32_t old_ports;  // sport << 16 | dport (0 when no port view)
  uint16_t ip_off;   // IPv4 header offset within the inner frame
  uint16_t l4_off;   // L4 header offset (0 = no port view)
  uint8_t proto;
  uint8_t flags;     // bit0 = valid IPv4, bit1 = has ports
};

constexpr uint8_t kFrValid = 1;
constexpr uint8_t kFrPorts = 2;

struct Slot {
  std::vector<FrameRef> frames;
  int32_t n = 0;
  uint32_t ring_descs = 0;  // rx descriptors consumed (incl. drops)
  bool live = false;        // admitted, not yet harvested/released
};

}  // namespace

struct HsLoop {
  HsRing* rx;
  HsRing* tx_remote;
  HsRing* tx_local;
  HsRing* tx_host;
  uint32_t batch_size;
  uint32_t max_vectors;
  uint32_t vni;
  std::vector<Slot> slots;
  std::deque<int32_t> order;  // admitted-slot FIFO (release order)

  // Route-split scratch (persistent across harvests: the 60%-local mix
  // was reallocating local_rows every batch).
  std::vector<int32_t> remote_rows, local_rows, host_rows;

  // Host-bypass scratch (lazily sized): route/node buffers for the
  // fused admit→route→harvest path (hs_loop_hostpath).  The bypass
  // writes NO header SoA — route is computed inline during the parse.
  std::vector<int32_t> hp_route, hp_node;

  // VXLAN outer-header template (see build_tmpl): everything constant
  // across frames of one (local_ip, vni) is pre-stamped; per-frame
  // fields are patched and the IP checksum updated incrementally from
  // tmpl_csum_partial instead of recomputed over 20 bytes.
  uint8_t tmpl[kOuterBytes];
  uint32_t tmpl_local_ip = 0;
  uint32_t tmpl_local_node = ~0u;
  uint32_t tmpl_csum_partial = 0;  // folded sum of the constant IP words

  HsLoop(HsRing* rx_, HsRing* txr, HsRing* txl, HsRing* txh, uint32_t bs,
         uint32_t mv, uint32_t vni_, uint32_t n_slots)
      : rx(rx_), tx_remote(txr), tx_local(txl), tx_host(txh), batch_size(bs),
        max_vectors(mv), vni(vni_), slots(n_slots) {
    size_t cap = static_cast<size_t>(bs) * mv;
    for (auto& s : slots) s.frames.resize(cap);
    remote_rows.reserve(cap);
    local_rows.reserve(cap);
    host_rows.reserve(cap);
    std::memset(tmpl, 0, sizeof(tmpl));
  }

  void build_tmpl(uint32_t local_ip, uint32_t local_node_id) {
    node_mac(0, tmpl);                 // dst MAC patched per frame
    node_mac(local_node_id, tmpl + 6);
    store_be16(tmpl + 12, kEthertypeIPv4);
    uint8_t* ip = tmpl + 14;
    ip[0] = 0x45;
    ip[1] = 0;
    store_be16(ip + 2, 0);        // total len: per frame
    store_be16(ip + 4, 0);        // identification
    store_be16(ip + 6, 0x4000);   // DF
    ip[8] = 64;                   // TTL
    ip[9] = kProtoUDP;
    store_be16(ip + 10, 0);       // checksum: per frame
    store_be32(ip + 12, local_ip);
    store_be32(ip + 16, 0);       // dst ip: per frame
    uint8_t* udp = ip + 20;
    store_be16(udp, 0);           // sport (entropy): per frame
    store_be16(udp + 2, kVxlanPort);
    store_be16(udp + 4, 0);       // udp len: per frame
    store_be16(udp + 6, 0);       // UDP checksum optional (RFC 7348 §5)
    uint8_t* vx = udp + 8;
    vx[0] = 0x08;
    vx[1] = vx[2] = vx[3] = 0;
    store_be32(vx + 4, (vni << 8) & 0xffffff00);
    // Partial IP checksum over the CONSTANT words (skip total-len at
    // +2, csum at +10, dst ip at +16).
    uint32_t sum = 0;
    for (int i = 0; i < 20; i += 2) {
      if (i == 2 || i == 10 || i == 16 || i == 18) continue;
      sum += load_be16(ip + i);
    }
    tmpl_csum_partial = sum;
    tmpl_local_ip = local_ip;
    tmpl_local_node = local_node_id;
  }

  // Stamp one outer header into dst for an inner frame of inner_len.
  void stamp_outer(uint8_t* dst, uint32_t inner_len, uint32_t dst_ip,
                   uint32_t dst_node_id, uint32_t entropy_h) {
    std::memcpy(dst, tmpl, kOuterBytes);
    node_mac(dst_node_id, dst);
    uint8_t* ip = dst + 14;
    uint16_t total = static_cast<uint16_t>(20 + 8 + kVxlanHdrBytes + inner_len);
    store_be16(ip + 2, total);
    store_be32(ip + 16, dst_ip);
    uint32_t sum = tmpl_csum_partial + total + (dst_ip >> 16) + (dst_ip & 0xffff);
    while (sum >> 16) sum = (sum & 0xffff) + (sum >> 16);
    store_be16(ip + 10, static_cast<uint16_t>(~sum));
    uint8_t* udp = ip + 20;
    store_be16(udp, static_cast<uint16_t>(49152 + (entropy_h & 16383)));
    store_be16(udp + 4, static_cast<uint16_t>(8 + kVxlanHdrBytes + inner_len));
  }
};

namespace {

// Verdict + 5-tuple rewrite against admit's cached offsets (the
// parse-once path; semantics identical to hs::apply_rewrite).
inline void apply_rewrite_cached(uint8_t* frame, const FrameRef& ref,
                                 uint32_t new_src_ip, uint32_t new_dst_ip,
                                 uint16_t new_sport, uint16_t new_dport) {
  uint8_t* ip = frame + ref.ip_off;
  uint32_t old_src = load_be32(ip + 12);
  uint32_t old_dst = load_be32(ip + 16);
  uint16_t ip_csum = load_be16(ip + 10);

  uint8_t* l4 = (ref.flags & kFrPorts) ? frame + ref.l4_off : nullptr;
  uint8_t* l4_csum_p = nullptr;
  if (l4 != nullptr) {
    if (ref.proto == kProtoTCP) {
      l4_csum_p = l4 + 16;
    } else if (ref.proto == kProtoUDP && load_be16(l4 + 6) != 0) {
      l4_csum_p = l4 + 6;  // UDP checksum 0 = disabled, keep it so
    }
  }
  uint16_t l4_csum = l4_csum_p ? load_be16(l4_csum_p) : 0;

  if (new_src_ip != old_src) {
    ip_csum = csum_update32(ip_csum, old_src, new_src_ip);
    if (l4_csum_p) l4_csum = csum_update32(l4_csum, old_src, new_src_ip);
    store_be32(ip + 12, new_src_ip);
  }
  if (new_dst_ip != old_dst) {
    ip_csum = csum_update32(ip_csum, old_dst, new_dst_ip);
    if (l4_csum_p) l4_csum = csum_update32(l4_csum, old_dst, new_dst_ip);
    store_be32(ip + 16, new_dst_ip);
  }
  store_be16(ip + 10, ip_csum);

  if (l4 != nullptr) {
    uint16_t old_sport = load_be16(l4);
    uint16_t old_dport = load_be16(l4 + 2);
    if (new_sport != old_sport) {
      if (l4_csum_p) l4_csum = csum_update16(l4_csum, old_sport, new_sport);
      store_be16(l4, new_sport);
    }
    if (new_dport != old_dport) {
      if (l4_csum_p) l4_csum = csum_update16(l4_csum, old_dport, new_dport);
      store_be16(l4 + 2, new_dport);
    }
  }
  if (l4_csum_p) store_be16(l4_csum_p, l4_csum);
}

}  // namespace

extern "C" {

HsLoop* hs_loop_new(HsRing* rx, HsRing* tx_remote, HsRing* tx_local,
                    HsRing* tx_host, uint32_t batch_size, uint32_t max_vectors,
                    uint32_t vni, uint32_t n_slots) {
  if (rx == nullptr || batch_size == 0 || max_vectors == 0 || n_slots == 0)
    return nullptr;
  return new HsLoop(rx, tx_remote, tx_local, tx_host, batch_size, max_vectors,
                    vni, n_slots);
}

// Free the loop WITHOUT touching its rings: teardown may finalise the
// rings first (Python GC breaks reference cycles in arbitrary order),
// so dereferencing rx here would be use-after-free.  A caller that
// wants the rings back in a clean state (loop rebuild on resize) calls
// hs_loop_release_all first, while the rings are provably alive.
void hs_loop_free(HsLoop* lp) { delete lp; }

// Release any still-pinned batches so the rx ring stays usable after
// the loop is torn down mid-flight.  Only call when the rings outlive
// the loop (Python checks their handles are still open).
void hs_loop_release_all(HsLoop* lp) {
  if (lp == nullptr) return;
  std::lock_guard<std::mutex> g(lp->rx->mu);
  while (!lp->order.empty()) {
    Slot& s = lp->slots[lp->order.front()];
    lp->rx->release_locked(s.ring_descs);
    s.live = false;
    lp->order.pop_front();
  }
}

// Admit one batch into slot `slot` — ZERO-COPY:
//   - read (do not pop) up to batch_size*max_vectors frames from the
//     rx ring; they stay pinned in the arena until this slot's harvest
//     releases them;
//   - VXLAN-declassify each in place: our-VNI frames yield their inner
//     frame (offset math only), foreign-VNI frames are dropped, native
//     frames pass through;
//   - parse each kept frame ONCE into the SoA header arrays
//     (src/dst/proto/sport/dport), caching the IP/L4 offsets for the
//     harvest rewrite; zero-pad up to k*batch_size where k is the
//     power-of-two vector count.
//
// counters (uint64[3]) += {rx_frames, rx_decapped, dropped_foreign_vni}.
// *k_out = vector count for the dispatch.  Returns n_kept, or -1 when
// the slot is still live (admitted but not harvested — a caller bug).
//
// Two template instantiations share the body: the DISPATCH admit
// (kBypass=false) fills the 5-field SoA the jit pipeline consumes and
// zero-pads to the vector bucket; the BYPASS admit (kBypass=true)
// writes no SoA at all — nothing downstream reads headers, so it
// computes route_tag/node_id INLINE from the freshly-parsed dst while
// the header is still in registers.  The bypass batch thereby touches
// five fewer 64 KB output streams per 16k-frame batch.
}  // extern "C"

namespace {

struct RouteParams {
  uint32_t pod_base, pod_mask, node_base, node_mask, host_bits;
};

template <bool kBypass>
int32_t admit_impl(HsLoop* lp, int32_t slot_idx, uint32_t* src_ip,
                   uint32_t* dst_ip, int32_t* protocol, int32_t* src_port,
                   int32_t* dst_port, int32_t* k_out, uint64_t* counters,
                   const RouteParams* rp, int32_t* route_tag,
                   int32_t* node_id, int32_t k_cap = 0) {
  Slot& slot = lp->slots[slot_idx];
  if (slot.live) {
    *k_out = 1;
    return -1;
  }
  slot.n = 0;
  // Per-admit vector cap from the coalesce governor (0 = uncapped):
  // bounds both the ring read budget and the pow2 bucket below, so an
  // SLO-capped admit leaves the excess backlog queued for the next
  // in-flight slot instead of over-filling this one.
  uint32_t cap = lp->max_vectors;
  if (k_cap > 0 && static_cast<uint32_t>(k_cap) < cap)
    cap = static_cast<uint32_t>(k_cap);
  uint32_t budget = lp->batch_size * cap;
  uint64_t decapped = 0, foreign = 0;
  uint32_t consumed = 0;
  {
    // Minimal critical section: snapshot the unread descriptors into
    // the slot.  Classification and parsing happen after the lock
    // drops — the frames are pinned (read_pos) so producers cannot
    // overwrite them, and this loop is the ring's only reader.
    std::lock_guard<std::mutex> g(lp->rx->mu);
    HsRing& rx = *lp->rx;
    uint32_t idx = rx.head + rx.read_pos;
    if (idx >= rx.cap_frames) idx -= rx.cap_frames;  // both < cap
    while (rx.read_pos < rx.count && consumed < budget) {
      Desc d = rx.descs[idx];
      if (++idx == rx.cap_frames) idx = 0;
      ++rx.read_pos;
      FrameRef& ref = slot.frames[consumed++];
      ref.off = d.off;
      ref.len = d.len;
    }
  }
  counters[0] += consumed;
  uint8_t* arena0 = lp->rx->arena.data();
  // Classify + parse in ONE pass, compacting kept frames in place
  // (read index >= write index, so the overwrite is safe).  A native
  // frame is parsed exactly once — the parse that used to live inside
  // vxlan_classify is reused for the SoA fill; only genuine VXLAN
  // ingress pays a second (inner) parse.
  int32_t kept = 0;
  for (uint32_t ci = 0; ci < consumed; ++ci) {
    uint64_t f_off = slot.frames[ci].off;
    uint32_t f_len = slot.frames[ci].len;
    if (ci + 1 < consumed) __builtin_prefetch(arena0 + slot.frames[ci + 1].off);
    uint8_t* f = arena0 + f_off;
    FrameView v = parse_frame(f, f_len);
    if (v.valid && v.proto == kProtoUDP && v.has_ports &&
        load_be16(v.l4 + 2) == kVxlanPort) {
      // Same acceptance rules as hs::vxlan_classify: malformed VXLAN
      // candidates fall through as native frames.
      const uint8_t* vx = v.l4 + 8;
      uint64_t l4_off = static_cast<uint64_t>(v.l4 - f);
      if (f_len >= l4_off + 8 + kVxlanHdrBytes + 14 && (vx[0] & 0x08) != 0) {
        uint32_t frame_vni = load_be32(vx + 4) >> 8;
        if (frame_vni != lp->vni) {
          ++foreign;  // not our overlay segment: drop, never classify
          continue;
        }
        ++decapped;
        uint32_t inner_off = static_cast<uint32_t>(l4_off + 8 + kVxlanHdrBytes);
        f_off += inner_off;
        f_len -= inner_off;
        f = arena0 + f_off;
        v = parse_frame(f, f_len);
      }
    }
    FrameRef& ref = slot.frames[kept];
    ref.off = f_off;
    ref.len = f_len;
    if (!v.valid) {
      ref.flags = 0;
      ref.proto = 0;
      ref.old_src = ref.old_dst = ref.old_ports = 0;
      if constexpr (kBypass) {
        route_tag[kept] = 0;  // harvest skips invalid rows before routing
        node_id[kept] = 0;
      } else {
        src_ip[kept] = dst_ip[kept] = 0;
        protocol[kept] = src_port[kept] = dst_port[kept] = 0;
      }
      ++kept;
      continue;
    }
    ref.ip_off = static_cast<uint16_t>(v.ip - f);
    ref.l4_off = v.has_ports ? static_cast<uint16_t>(v.l4 - f) : 0;
    ref.proto = v.proto;
    ref.flags = kFrValid | (v.has_ports ? kFrPorts : 0);
    uint32_t s = load_be32(v.ip + 12);
    uint32_t d = load_be32(v.ip + 16);
    uint32_t sp = v.has_ports ? load_be16(v.l4) : 0;
    uint32_t dp = v.has_ports ? load_be16(v.l4 + 2) : 0;
    ref.old_src = s;
    ref.old_dst = d;
    ref.old_ports = (sp << 16) | dp;
    if constexpr (kBypass) {
      route_tag[kept] = (d & rp->node_mask) == rp->node_base   ? 1
                        : (d & rp->pod_mask) == rp->pod_base   ? 2
                                                               : 3;
      node_id[kept] = static_cast<int32_t>((d - rp->pod_base) >> rp->host_bits);
    } else {
      src_ip[kept] = s;
      dst_ip[kept] = d;
      protocol[kept] = v.proto;
      src_port[kept] = static_cast<int32_t>(sp);
      dst_port[kept] = static_cast<int32_t>(dp);
    }
    ++kept;
  }
  slot.n = kept;
  counters[1] += decapped;
  counters[2] += foreign;
  if (slot.n == 0) {
    // Nothing kept (idle ring, or all frames were foreign-VNI drops):
    // the runner will not dispatch or harvest this slot, so its
    // consumed descriptors must be freed another way — immediately if
    // nothing older is pinned, else by the newest in-flight batch's
    // release (descriptors free strictly FIFO; these sit at the END of
    // the read region, so they cannot be released before the batches
    // admitted ahead of them).
    if (consumed > 0) {
      std::lock_guard<std::mutex> g(lp->rx->mu);
      if (lp->order.empty()) {
        lp->rx->release_locked(consumed);
      } else {
        lp->slots[lp->order.back()].ring_descs += consumed;
      }
    }
    *k_out = 1;
    return 0;
  }
  slot.ring_descs = consumed;
  slot.live = true;
  lp->order.push_back(slot_idx);

  int32_t n = slot.n;
  if constexpr (kBypass) {
    *k_out = 1;  // no dispatch, no vector bucketing, no padding
    return n;
  }
  // Vector count: enough batch_size-packet vectors for the kept frames,
  // bucketed to a power of two (bounded jit recompiles).
  int32_t k = 1;
  while (static_cast<uint32_t>(k) * lp->batch_size < static_cast<uint32_t>(n) &&
         static_cast<uint32_t>(k) < cap)
    k *= 2;
  *k_out = k;
  int32_t padded = k * static_cast<int32_t>(lp->batch_size);
  if (n < padded) {
    size_t tail = static_cast<size_t>(padded - n);
    std::memset(src_ip + n, 0, tail * sizeof(uint32_t));
    std::memset(dst_ip + n, 0, tail * sizeof(uint32_t));
    std::memset(protocol + n, 0, tail * sizeof(int32_t));
    std::memset(src_port + n, 0, tail * sizeof(int32_t));
    std::memset(dst_port + n, 0, tail * sizeof(int32_t));
  }
  return n;
}

// Harvest body, shared by the dispatch path (kBypass=false: verdicts
// and rewrite values come from the jit pipeline) and the bypass path
// (kBypass=true: every frame is allowed and pass-through by
// construction — no allowed[] loads, no change detection, no rewrite;
// the remote encap entropy reads the tuple admit cached in FrameRef).
template <bool kBypass>
int32_t harvest_impl(HsLoop* lp, int32_t slot_idx, const uint8_t* allowed,
                     const uint32_t* new_src, const uint32_t* new_dst,
                     const int32_t* new_sport, const int32_t* new_dport,
                     const int32_t* route_tag, const int32_t* node_id,
                     const uint32_t* remote_ips, int32_t max_node_id,
                     uint32_t local_ip, uint32_t local_node_id,
                     uint64_t* counters) {
  constexpr int32_t kRouteLocal = 1, kRouteRemote = 2, kRouteHost = 3;
  Slot& slot = lp->slots[slot_idx];
  if (!slot.live || lp->order.empty() || lp->order.front() != slot_idx)
    return -2;
  if (lp->tmpl_local_ip != local_ip || lp->tmpl_local_node != local_node_id)
    lp->build_tmpl(local_ip, local_node_id);
  uint8_t* arena = lp->rx->arena.data();
  uint64_t denied = 0, unparseable = 0, unroutable = 0;
  std::vector<int32_t>& remote_rows = lp->remote_rows;
  std::vector<int32_t>& local_rows = lp->local_rows;
  std::vector<int32_t>& host_rows = lp->host_rows;
  remote_rows.clear();
  local_rows.clear();
  host_rows.clear();
  for (int32_t i = 0; i < slot.n; ++i) {
    if constexpr (!kBypass) {
      if (!allowed[i]) {
        ++denied;
        continue;
      }
    }
    const FrameRef& ref = slot.frames[i];
    if (!(ref.flags & kFrValid)) {
      ++unparseable;
      continue;
    }
    if constexpr (!kBypass) {
      // Pass-through fast path: when the pipeline's rewrite values
      // match the 5-tuple admit parsed, the frame bytes are already
      // correct — no loads, no checksum math, no stores.  Only
      // genuinely rewritten frames (service DNAT/SNAT rows) touch the
      // arena here.  (The bypass instantiation has no rewrite values
      // at all: pass-through by construction.)
      bool changed = new_src[i] != ref.old_src || new_dst[i] != ref.old_dst;
      if (!changed && (ref.flags & kFrPorts)) {
        uint32_t ports = (static_cast<uint32_t>(new_sport[i] & 0xffff) << 16) |
                         static_cast<uint32_t>(new_dport[i] & 0xffff);
        changed = ports != ref.old_ports;
      }
      if (changed) {
        apply_rewrite_cached(arena + ref.off, ref, new_src[i], new_dst[i],
                             static_cast<uint16_t>(new_sport[i]),
                             static_cast<uint16_t>(new_dport[i]));
      }
    }
    switch (route_tag[i]) {
      case kRouteRemote: {
        int32_t nid = node_id[i];
        uint32_t dst = (nid >= 0 && nid <= max_node_id) ? remote_ips[nid] : 0;
        if (dst == 0) {
          ++unroutable;
        } else {
          remote_rows.push_back(i);
        }
        break;
      }
      case kRouteLocal:
        local_rows.push_back(i);
        break;
      case kRouteHost:
        host_rows.push_back(i);
        break;
      default:
        break;  // ROUTE_DROP falls through silently (Python-loop parity)
    }
  }
  int32_t sent = 0;
  // The route split leaves each class's rows SCATTERED in the arena
  // (a mixed pattern costs ~15 cycles/frame over uniform traffic in
  // cache misses alone) — prefetch a few frames ahead in every flush.
  constexpr size_t kPf = 8;
  if (!remote_rows.empty() && lp->tx_remote != nullptr) {
    HsRing* txr = lp->tx_remote;
    std::lock_guard<std::mutex> g(txr->mu);
    size_t nrow = remote_rows.size();
    // Hoisted reservation (see flush below): when every encapped frame
    // fits the tail segment, the inner loop skips the per-frame
    // reserve branches and writes straight at the cursor.
    uint64_t total_bytes = 0;
    for (int32_t i : remote_rows)
      total_bytes += kOuterBytes + slot.frames[i].len;
    if (txr->count == 0) txr->tail_off = 0;
    uint64_t head_off = txr->count ? txr->descs[txr->head].off : 0;
    bool fast = (txr->count == 0 || head_off <= txr->tail_off) &&
                txr->tail_off + total_bytes <= txr->arena.size() &&
                txr->count + nrow <= txr->cap_frames;
    for (size_t r = 0; r < nrow; ++r) {
      if (r + kPf < nrow)
        __builtin_prefetch(arena + slot.frames[remote_rows[r + kPf]].off);
      int32_t i = remote_rows[r];
      const FrameRef& ref = slot.frames[i];
      const uint8_t* inner = arena + ref.off;
      uint32_t total = kOuterBytes + ref.len;
      uint8_t* dst = fast ? txr->arena.data() + txr->tail_off
                          : txr->reserve_locked(total);
      if (dst == nullptr) {
        ++txr->dropped;
      } else {
        // ECMP entropy over the (rewritten) inner flow — computed from
        // the rewrite values instead of re-parsing the frame; matches
        // hs::flow_entropy on the post-rewrite header bit for bit.
        // The bypass reads the tuple admit cached (== the frame's, no
        // rewrite happened), keeping the entropy bit-identical.
        uint32_t e_src, e_dst, e_ports;
        if constexpr (kBypass) {
          e_src = ref.old_src;
          e_dst = ref.old_dst;
          e_ports = ref.old_ports;
        } else {
          e_src = new_src[i];
          e_dst = new_dst[i];
          e_ports = ((static_cast<uint32_t>(new_sport[i]) & 0xffff) << 16) |
                    (static_cast<uint32_t>(new_dport[i]) & 0xffff);
        }
        uint32_t h = e_src ^ (e_dst * 2654435761u);
        if (ref.flags & kFrPorts) h ^= e_ports;
        h ^= h >> 16;
        lp->stamp_outer(dst, ref.len, remote_ips[node_id[i]],
                        static_cast<uint32_t>(node_id[i]), h);
        copy_frame_bytes(dst + kOuterBytes, inner, ref.len);
        txr->commit_locked(total);
      }
    }
    counters[0] += remote_rows.size();
    sent += static_cast<int32_t>(remote_rows.size());
  }
  // Per-frame pushes under ONE lock hold per ring.  A run-coalescing
  // variant (one memcpy per arena-contiguous same-route run) was
  // measured ~8 cycles/frame SLOWER on the mixed-route bench — the
  // run detection costs more than the memcpy calls it saves, because
  // libc's small-copy path is already near the per-frame floor.  What
  // DOES pay is hoisting the reservation checks: when the whole flush
  // provably fits in the tail segment (one bounds test), the inner
  // loop is just copy + desc store + cursor advance, no per-frame
  // wrap/full branches.
  auto flush = [&](const std::vector<int32_t>& rows, HsRing* ring,
                   uint64_t* counter) {
    if (rows.empty() || ring == nullptr) return;
    std::lock_guard<std::mutex> g(ring->mu);
    size_t nrow = rows.size();
    uint64_t total_bytes = 0;
    for (int32_t i : rows) total_bytes += slot.frames[i].len;
    if (ring->count == 0) ring->tail_off = 0;
    uint64_t head_off = ring->count ? ring->descs[ring->head].off : 0;
    bool linear = ring->count == 0 || head_off <= ring->tail_off;
    if (linear && ring->tail_off + total_bytes <= ring->arena.size() &&
        ring->count + nrow <= ring->cap_frames) {
      for (size_t r = 0; r < nrow; ++r) {
        if (r + kPf < nrow)
          __builtin_prefetch(arena + slot.frames[rows[r + kPf]].off);
        const FrameRef& ref = slot.frames[rows[r]];
        copy_frame_bytes(ring->arena.data() + ring->tail_off,
                         arena + ref.off, ref.len);
        ring->commit_locked(ref.len);
      }
    } else {
      for (size_t r = 0; r < nrow; ++r) {
        if (r + kPf < nrow)
          __builtin_prefetch(arena + slot.frames[rows[r + kPf]].off);
        int32_t i = rows[r];
        ring->push_one_locked(arena + slot.frames[i].off, slot.frames[i].len);
      }
    }
    *counter += rows.size();
    sent += static_cast<int32_t>(rows.size());
  };
  flush(local_rows, lp->tx_local, &counters[1]);
  flush(host_rows, lp->tx_host, &counters[2]);
  counters[3] += denied;
  counters[4] += unparseable;
  counters[5] += unroutable;
  // Release this batch's arena pin (FIFO — checked on entry).
  {
    std::lock_guard<std::mutex> g(lp->rx->mu);
    lp->rx->release_locked(slot.ring_descs);
  }
  slot.live = false;
  lp->order.pop_front();
  return sent;
}

}  // namespace

extern "C" {

// k_cap: per-admit pow2 vector cap from the coalesce governor
// (0 = uncapped, the historical behavior).
int32_t hs_loop_admit(HsLoop* lp, int32_t slot_idx, uint32_t* src_ip,
                      uint32_t* dst_ip, int32_t* protocol, int32_t* src_port,
                      int32_t* dst_port, int32_t* k_out, uint64_t* counters,
                      int32_t k_cap) {
  return admit_impl<false>(lp, slot_idx, src_ip, dst_ip, protocol, src_port,
                           dst_port, k_out, counters, nullptr, nullptr,
                           nullptr, k_cap);
}

// Harvest slot `slot`: apply verdicts + rewrites in place in the rx
// arena (incremental checksums against admit's cached offsets),
// VXLAN-encap ROUTE_REMOTE frames from the header template, route to
// the TX rings, then release the batch's pinned arena bytes.
//
// route_tag uses the pipeline's encoding (1 local / 2 remote / 3 host;
// anything else is a silent drop, matching the Python loop).
// counters (uint64[6]) += {tx_remote, tx_local, tx_host, denied,
// unparseable, unroutable}.  TX counts are frames handed to a ring —
// a full ring records the loss in its own dropped counter, the same
// split the Python loop + InMemoryRing kept.  Returns frames sent, or
// -2 when called out of admit order (batches must release FIFO).
int32_t hs_loop_harvest(HsLoop* lp, int32_t slot_idx, const uint8_t* allowed,
                        const uint32_t* new_src, const uint32_t* new_dst,
                        const int32_t* new_sport, const int32_t* new_dport,
                        const int32_t* route_tag, const int32_t* node_id,
                        const uint32_t* remote_ips, int32_t max_node_id,
                        uint32_t local_ip, uint32_t local_node_id,
                        uint64_t* counters) {
  return harvest_impl<false>(lp, slot_idx, allowed, new_src, new_dst,
                             new_sport, new_dport, route_tag, node_id,
                             remote_ips, max_node_id, local_ip, local_node_id,
                             counters);
}

// Read back one frame of a slot (slow path / trace tooling, not hot).
// Only valid while the slot is live (admitted, not yet harvested).
int32_t hs_loop_slot_frame(HsLoop* lp, int32_t slot_idx, int32_t row,
                           uint8_t* out, uint32_t out_cap) {
  Slot& slot = lp->slots[slot_idx];
  if (!slot.live || row < 0 || row >= slot.n) return -1;
  uint32_t len = slot.frames[row].len;
  if (len > out_cap) return -1;
  std::memcpy(out, lp->rx->arena.data() + slot.frames[row].off, len);
  return static_cast<int32_t>(len);
}

// Fused HOST-BYPASS batch: admit → subnet route classify → harvest in
// ONE call, no device dispatch and no FFI crossings between phases —
// the runner's fast path when its tables are trivially permissive (no
// ACL rules, no NAT mappings, SNAT off): every frame is pass-through
// (allowed, unrewritten), so classify/NAT compute nothing and the
// whole per-frame cost is this loop.  The VPP analog is a feature-less
// interface path that skips the acl/nat graph nodes entirely.
// Returns n admitted (0 = idle ring / all-foreign batch); *sent_out =
// frames pushed to TX rings.  Counter layouts match admit/harvest.
int32_t hs_loop_hostpath(HsLoop* lp, int32_t slot_idx, uint32_t pod_base,
                         uint32_t pod_mask, uint32_t node_base,
                         uint32_t node_mask, uint32_t host_bits,
                         const uint32_t* remote_ips, int32_t max_node_id,
                         uint32_t local_ip, uint32_t local_node_id,
                         uint64_t* admit_counters, uint64_t* harvest_counters,
                         int32_t* sent_out) {
  *sent_out = 0;
  size_t budget = static_cast<size_t>(lp->batch_size) * lp->max_vectors;
  if (lp->hp_route.size() < budget) {
    lp->hp_route.resize(budget);
    lp->hp_node.resize(budget);
  }
  RouteParams rp{pod_base, pod_mask, node_base, node_mask, host_bits};
  int32_t k = 0;
  int32_t n = admit_impl<true>(lp, slot_idx, nullptr, nullptr, nullptr,
                               nullptr, nullptr, &k, admit_counters, &rp,
                               lp->hp_route.data(), lp->hp_node.data());
  if (n <= 0) return n;
  *sent_out = harvest_impl<true>(
      lp, slot_idx, nullptr, nullptr, nullptr, nullptr, nullptr,
      lp->hp_route.data(), lp->hp_node.data(), remote_ips, max_node_id,
      local_ip, local_node_id, harvest_counters);
  return n;
}

// Drain variant of the host-bypass batch (ISSUE 12): loop
// admit→route→harvest until the rx ring is empty, in ONE call.  The
// many-core front end drives one of these per shard worker wakeup —
// at N shards the per-batch FFI/GIL crossings would otherwise
// serialise exactly the work the scale-out exists to parallelise.
// Returns total frames admitted; *sent_out accumulates TX counts.
int32_t hs_loop_hostpath_drain(HsLoop* lp, int32_t slot_idx,
                               uint32_t pod_base, uint32_t pod_mask,
                               uint32_t node_base, uint32_t node_mask,
                               uint32_t host_bits, const uint32_t* remote_ips,
                               int32_t max_node_id, uint32_t local_ip,
                               uint32_t local_node_id,
                               uint64_t* admit_counters,
                               uint64_t* harvest_counters,
                               int32_t* sent_out) {
  *sent_out = 0;
  int64_t total = 0;
  while (true) {
    int32_t sent = 0;
    int32_t n = hs_loop_hostpath(lp, slot_idx, pod_base, pod_mask, node_base,
                                 node_mask, host_bits, remote_ips, max_node_id,
                                 local_ip, local_node_id, admit_counters,
                                 harvest_counters, &sent);
    if (n < 0) return n;
    *sent_out += sent;
    if (n == 0) break;
    total += n;
  }
  return static_cast<int32_t>(total > 0x7fffffff ? 0x7fffffff : total);
}

// ---------------------------------------------------------------------------
// Fanout handoff — ONE feeder, N single-reader shard rings (ISSUE 12)
// ---------------------------------------------------------------------------
//
// The many-core admit front end gives every shard its OWN HsRing arena
// (frames stay pinned shard-locally from ingest to TX, exactly like
// the solo loop), so N admit threads never contend on one ring head.
// What remains is the handoff: a feeder (recvmmsg burst, virtual wire,
// bench driver) that must spread one frame stream across the N rings.
// hs_fanout_push does that in ONE call: flow-hash (symmetric, so a
// flow's forward AND reply land on the same shard — the cache-locality
// property PACKET_FANOUT_HASH gives kernel-socket ingest) or
// round-robin, with ONE lock hold per target ring per call (never one
// per frame).  Each shard ring stays effectively single-writer
// (feeder) + single-reader (that shard's admit), so cross-shard
// contention is pairwise on ring mutexes, never a shared cursor.

}  // extern "C"

namespace {

// Symmetric flow hash over the 5-tuple: XOR folds src/dst (and the
// port pair) so (a→b) and (b→a) hash identically — a shard serves both
// directions of the flows it owns.  Non-IPv4 frames spread by length.
inline uint32_t fanout_flow_hash(const uint8_t* frame, uint32_t len) {
  FrameView v = parse_frame(const_cast<uint8_t*>(frame), len);
  if (!v.valid) return len * 2654435761u;
  uint32_t s = load_be32(v.ip + 12);
  uint32_t d = load_be32(v.ip + 16);
  uint32_t h = (s ^ d) * 2654435761u;
  if (v.has_ports) {
    uint32_t ports = static_cast<uint32_t>(load_be16(v.l4)) ^
                     static_cast<uint32_t>(load_be16(v.l4 + 2));
    h ^= ports * 40503u;
  }
  h ^= v.proto;
  h ^= h >> 16;
  return h;
}

}  // namespace

extern "C" {

// Distribute n frames described by (offsets, lens) views into buf
// across n_rings shard rings.  mode 0 = symmetric flow hash (shard-
// sticky flows), mode 1 = round-robin (uniform spread regardless of
// flow count).  Returns frames accepted; rejects land in the target
// ring's own dropped counter (full-ring semantics unchanged).
int32_t hs_fanout_push(HsRing* const* rings, int32_t n_rings,
                       const uint8_t* buf, const uint64_t* offsets,
                       const uint32_t* lens, int32_t n, int32_t mode) {
  if (n_rings <= 0 || n <= 0) return 0;
  if (n_rings == 1) return hs_ring_push(rings[0], buf, offsets, lens, n);
  static thread_local std::vector<int32_t> target;
  static thread_local uint32_t rr_cursor = 0;
  target.resize(static_cast<size_t>(n));
  for (int32_t i = 0; i < n; ++i) {
    uint32_t h = (mode == 1) ? rr_cursor++
                             : fanout_flow_hash(buf + offsets[i], lens[i]);
    target[i] = static_cast<int32_t>(h % static_cast<uint32_t>(n_rings));
  }
  int32_t pushed = 0;
  for (int32_t r = 0; r < n_rings; ++r) {
    // One lock hold per ring per call: the feeder's cost per frame is
    // the hash + one compare, not a mutex round trip.
    std::lock_guard<std::mutex> g(rings[r]->mu);
    for (int32_t i = 0; i < n; ++i) {
      if (target[i] == r &&
          rings[r]->push_one_locked(buf + offsets[i], lens[i]))
        ++pushed;
    }
  }
  return pushed;
}

// ---------------------------------------------------------------------------
// AF_PACKET burst IO — recvmmsg/sendmmsg between a socket and a ring
// ---------------------------------------------------------------------------

// Receive up to max_frames from fd into the ring (non-blocking bursts).
// Returns frames received (0 = nothing pending, <0 = errno-style error).
int32_t hs_afp_rx(int32_t fd, HsRing* ring, int32_t max_frames) {
  static thread_local std::vector<uint8_t> stage(kAfpBurst * kAfpFrameCap);
  mmsghdr msgs[kAfpBurst];
  iovec iovs[kAfpBurst];
  int32_t total = 0;
  while (total < max_frames) {
    uint32_t want = static_cast<uint32_t>(max_frames - total);
    if (want > kAfpBurst) want = kAfpBurst;
    for (uint32_t i = 0; i < want; ++i) {
      iovs[i] = {stage.data() + i * kAfpFrameCap, kAfpFrameCap};
      std::memset(&msgs[i], 0, sizeof(mmsghdr));
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
    }
    int got = recvmmsg(fd, msgs, want, MSG_DONTWAIT, nullptr);
    if (got <= 0) break;
    {
      std::lock_guard<std::mutex> g(ring->mu);
      for (int i = 0; i < got; ++i) {
        if (msgs[i].msg_hdr.msg_flags & MSG_TRUNC) {
          // Frame larger than the burst stage (jumbo): forwarding the
          // truncated prefix would corrupt it — count as a ring drop.
          ++ring->dropped;
          continue;
        }
        ring->push_one_locked(stage.data() + i * kAfpFrameCap, msgs[i].msg_len);
      }
    }
    total += got;
    if (static_cast<uint32_t>(got) < want) break;
  }
  return total;
}

// Receive up to max_frames from fd and fan them out across n_rings
// shard rings in the SAME call (recvmmsg burst → hs_fanout_push-style
// distribution, no intermediate ring): the batched-ingest shape for a
// single uplink socket feeding a many-shard admit front end where
// PACKET_FANOUT is unavailable (one queue, no kernel fanout group).
// mode as in hs_fanout_push.  Returns frames received.
int32_t hs_afp_rx_fanout(int32_t fd, HsRing* const* rings, int32_t n_rings,
                         int32_t max_frames, int32_t mode) {
  if (n_rings <= 0) return 0;
  static thread_local std::vector<uint8_t> stage(kAfpBurst * kAfpFrameCap);
  mmsghdr msgs[kAfpBurst];
  iovec iovs[kAfpBurst];
  uint64_t offs[kAfpBurst];
  uint32_t lens[kAfpBurst];
  int32_t total = 0;
  while (total < max_frames) {
    uint32_t want = static_cast<uint32_t>(max_frames - total);
    if (want > kAfpBurst) want = kAfpBurst;
    for (uint32_t i = 0; i < want; ++i) {
      iovs[i] = {stage.data() + i * kAfpFrameCap, kAfpFrameCap};
      std::memset(&msgs[i], 0, sizeof(mmsghdr));
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
    }
    int got = recvmmsg(fd, msgs, want, MSG_DONTWAIT, nullptr);
    if (got <= 0) break;
    int32_t kept = 0;
    for (int i = 0; i < got; ++i) {
      if (msgs[i].msg_hdr.msg_flags & MSG_TRUNC) {
        // Jumbo beyond the stage: forwarding a truncated prefix would
        // corrupt it — count on ring 0 (the burst's drop ledger).
        std::lock_guard<std::mutex> g(rings[0]->mu);
        ++rings[0]->dropped;
        continue;
      }
      offs[kept] = static_cast<uint64_t>(i) * kAfpFrameCap;
      lens[kept] = msgs[i].msg_len;
      ++kept;
    }
    hs_fanout_push(rings, n_rings, stage.data(), offs, lens, kept, mode);
    total += got;
    if (static_cast<uint32_t>(got) < want) break;
  }
  return total;
}

// Transmit up to max_frames from the ring out of fd.  Frames the kernel
// refuses (EAGAIN on a full TX queue) are dropped — kernel-drop
// semantics, like the Python AfPacketIO sink.  Returns frames taken
// off the ring.
int32_t hs_afp_tx(int32_t fd, HsRing* ring, int32_t max_frames) {
  static thread_local std::vector<uint8_t> stage(kAfpBurst * kAfpFrameCap);
  uint64_t offs[kAfpBurst];
  uint32_t lens[kAfpBurst];
  mmsghdr msgs[kAfpBurst];
  iovec iovs[kAfpBurst];
  int32_t total = 0;
  while (total < max_frames) {
    int32_t want = max_frames - total;
    if (want > static_cast<int32_t>(kAfpBurst)) want = kAfpBurst;
    int32_t n = hs_ring_pop(ring, stage.data(), stage.size(), offs, lens, want);
    if (n <= 0) break;
    for (int32_t i = 0; i < n; ++i) {
      iovs[i] = {stage.data() + offs[i], lens[i]};
      std::memset(&msgs[i], 0, sizeof(mmsghdr));
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
    }
    int32_t done = 0;
    while (done < n) {
      int rc = sendmmsg(fd, msgs + done, n - done, 0);
      if (rc <= 0) break;  // EAGAIN etc: remaining frames drop
      done += rc;
    }
    total += n;
    if (n < want) break;
  }
  return total;
}

}  // extern "C"
