// Native runner loop — ring buffers + admit/harvest in C++.
//
// Round-2 verdict item 1: the DataplaneRunner's orchestration (ring
// handling, per-frame bytes objects, harvest bookkeeping) was Python
// and capped the frame path at ~0.2 Mpps while the TPU kernel did
// hundreds.  This file moves the whole frame side native — the role
// VPP's C main loop + dpdk-input plays in the reference
// (/root/reference/vpp.env:1-3, docs/ARCHITECTURE.md:20):
//
//   HsRing   — thread-safe frame ring: contiguous byte arena +
//              (offset, len) descriptor FIFO.  Producers (AF_PACKET
//              RX, the virtual wire, Python test harnesses) push
//              frames in; the loop pops them without per-frame Python.
//   HsLoop   — per-node datapath state: admit pops up to
//              batch_size*max_vectors frames, VXLAN-declassifies,
//              VNI-filters, copies the inner frames into a per-slot
//              batch buffer and parses them straight into the SoA
//              header arrays the jit pipeline consumes — ONE ctypes
//              call.  harvest applies verdicts + NAT rewrites with
//              RFC 1624 checksums, VXLAN-encapsulates ROUTE_REMOTE
//              frames, and pushes to the remote/local/host TX rings —
//              ONE ctypes call.
//
// Python's remaining per-batch work is dispatching the jit pipeline,
// servicing punts through the host slow path, and swapping tables.
//
// AF_PACKET ingest/egress ride recvmmsg/sendmmsg directly between the
// socket and a ring (the DPDK-burst analog on kernel sockets).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

#include <sys/socket.h>

#include "common.h"

using namespace hs;

namespace {

constexpr uint32_t kAfpBurst = 64;
constexpr uint32_t kAfpFrameCap = 2048;

struct Desc {
  uint64_t off;
  uint32_t len;
};

}  // namespace

// ---------------------------------------------------------------------------
// HsRing
// ---------------------------------------------------------------------------

struct HsRing {
  std::mutex mu;
  std::vector<uint8_t> arena;
  std::vector<Desc> descs;
  uint32_t cap_frames;
  uint32_t head = 0;       // descriptor index of the oldest frame
  uint32_t count = 0;      // live frames
  uint64_t tail_off = 0;   // next arena write offset
  uint64_t dropped = 0;    // frames dropped because the ring was full

  HsRing(uint64_t arena_bytes, uint32_t max_frames)
      : arena(arena_bytes), descs(max_frames), cap_frames(max_frames) {}

  // Contiguous-arena reservation with wraparound (bip-buffer style:
  // frames never straddle the arena end; the writer wraps to 0 when
  // the tail region is too small and the head has moved on).
  // Caller must hold mu.  Returns nullptr when there is no room.
  uint8_t* reserve_locked(uint32_t len) {
    if (count == cap_frames) return nullptr;
    if (count == 0) tail_off = 0;
    uint64_t cap_b = arena.size();
    if (len > cap_b) return nullptr;
    uint64_t head_off = count ? descs[head].off : 0;
    if (count == 0 || head_off <= tail_off) {
      // Live bytes (if any) sit in [head_off, tail_off); free space is
      // the tail segment plus the wrapped prefix before head_off.
      if (tail_off + len <= cap_b) return arena.data() + tail_off;
      if (len < head_off) {
        tail_off = 0;  // wrap; the skipped tail bytes are implicitly free
        return arena.data();
      }
      return nullptr;
    }
    // Wrapped: live bytes in [head_off, end) + [0, tail_off); free is
    // [tail_off, head_off).  Strict < keeps tail != head while live.
    if (tail_off + len < head_off) return arena.data() + tail_off;
    return nullptr;
  }

  void commit_locked(uint32_t len) {
    descs[(head + count) % cap_frames] = {tail_off, len};
    tail_off += len;
    ++count;
  }

  bool push_one_locked(const uint8_t* data, uint32_t len) {
    uint8_t* dst = reserve_locked(len);
    if (dst == nullptr) {
      ++dropped;
      return false;
    }
    std::memcpy(dst, data, len);
    commit_locked(len);
    return true;
  }
};

extern "C" {

HsRing* hs_ring_new(uint64_t arena_bytes, uint32_t max_frames) {
  if (arena_bytes == 0 || max_frames == 0) return nullptr;
  return new HsRing(arena_bytes, max_frames);
}

void hs_ring_free(HsRing* r) { delete r; }

uint32_t hs_ring_count(HsRing* r) {
  std::lock_guard<std::mutex> g(r->mu);
  return r->count;
}

uint64_t hs_ring_dropped(HsRing* r) {
  std::lock_guard<std::mutex> g(r->mu);
  return r->dropped;
}

// Push n frames described by (offsets, lens) views into buf.
// Returns the number accepted; the rest are counted in dropped.
int32_t hs_ring_push(HsRing* r, const uint8_t* buf, const uint64_t* offsets,
                     const uint32_t* lens, int32_t n) {
  std::lock_guard<std::mutex> g(r->mu);
  int32_t pushed = 0;
  for (int32_t i = 0; i < n; ++i) {
    if (r->push_one_locked(buf + offsets[i], lens[i])) ++pushed;
  }
  return pushed;
}

// Pop up to max_frames frames, packing them contiguously into out_buf
// (capacity out_cap bytes) and recording (out_offsets, out_lens).
// Returns the number popped; stops early when out_buf is full.
int32_t hs_ring_pop(HsRing* r, uint8_t* out_buf, uint64_t out_cap,
                    uint64_t* out_offsets, uint32_t* out_lens,
                    int32_t max_frames) {
  std::lock_guard<std::mutex> g(r->mu);
  int32_t popped = 0;
  uint64_t used = 0;
  while (r->count > 0 && popped < max_frames) {
    Desc d = r->descs[r->head];
    if (used + d.len > out_cap) break;
    std::memcpy(out_buf + used, r->arena.data() + d.off, d.len);
    out_offsets[popped] = used;
    out_lens[popped] = d.len;
    used += d.len;
    r->head = (r->head + 1) % r->cap_frames;
    --r->count;
    ++popped;
  }
  return popped;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// HsLoop — the per-node admit/harvest engine
// ---------------------------------------------------------------------------

namespace {

struct Slot {
  std::vector<uint8_t> buf;    // packed inner frames for this batch
  std::vector<Desc> frames;    // per-frame (offset, len) into buf
  int32_t n = 0;
};

}  // namespace

struct HsLoop {
  HsRing* rx;
  HsRing* tx_remote;
  HsRing* tx_local;
  HsRing* tx_host;
  uint32_t batch_size;
  uint32_t max_vectors;
  uint32_t vni;
  std::vector<Slot> slots;

  HsLoop(HsRing* rx_, HsRing* txr, HsRing* txl, HsRing* txh, uint32_t bs,
         uint32_t mv, uint32_t vni_, uint32_t n_slots)
      : rx(rx_), tx_remote(txr), tx_local(txl), tx_host(txh), batch_size(bs),
        max_vectors(mv), vni(vni_), slots(n_slots) {
    for (auto& s : slots) {
      s.buf.reserve(static_cast<size_t>(bs) * mv * 256);
      s.frames.resize(static_cast<size_t>(bs) * mv);
    }
  }
};

extern "C" {

HsLoop* hs_loop_new(HsRing* rx, HsRing* tx_remote, HsRing* tx_local,
                    HsRing* tx_host, uint32_t batch_size, uint32_t max_vectors,
                    uint32_t vni, uint32_t n_slots) {
  if (rx == nullptr || batch_size == 0 || max_vectors == 0 || n_slots == 0)
    return nullptr;
  return new HsLoop(rx, tx_remote, tx_local, tx_host, batch_size, max_vectors,
                    vni, n_slots);
}

void hs_loop_free(HsLoop* lp) { delete lp; }

// Admit one batch into slot `slot`:
//   - pop up to batch_size*max_vectors frames from the rx ring;
//   - VXLAN-declassify each: our-VNI frames are de-encapsulated (inner
//     frame only is copied), foreign-VNI frames are dropped, native
//     frames pass through;
//   - pack kept frames into the slot buffer and parse them into the
//     SoA header arrays (src/dst/proto/sport/dport), zero-padding up
//     to k*batch_size where k is the power-of-two vector count.
//
// counters (uint64[3]) += {rx_frames, rx_decapped, dropped_foreign_vni}.
// *k_out = vector count for the dispatch.  Returns n_kept.
int32_t hs_loop_admit(HsLoop* lp, int32_t slot_idx, uint32_t* src_ip,
                      uint32_t* dst_ip, int32_t* protocol, int32_t* src_port,
                      int32_t* dst_port, int32_t* k_out, uint64_t* counters) {
  Slot& slot = lp->slots[slot_idx];
  slot.buf.clear();
  slot.n = 0;
  uint32_t budget = lp->batch_size * lp->max_vectors;
  uint64_t popped = 0, decapped = 0, foreign = 0;
  {
    std::lock_guard<std::mutex> g(lp->rx->mu);
    HsRing& rx = *lp->rx;
    while (rx.count > 0 && static_cast<uint32_t>(slot.n) < budget) {
      Desc d = rx.descs[rx.head];
      const uint8_t* frame = rx.arena.data() + d.off;
      uint32_t inner_off, inner_len;
      int32_t frame_vni = vxlan_classify(frame, d.len, &inner_off, &inner_len);
      rx.head = (rx.head + 1) % rx.cap_frames;
      --rx.count;
      ++popped;
      if (frame_vni >= 0) {
        if (static_cast<uint32_t>(frame_vni) != lp->vni) {
          ++foreign;  // not our overlay segment: drop, never classify
          continue;
        }
        ++decapped;
      }
      uint64_t at = slot.buf.size();
      slot.buf.resize(at + inner_len);
      std::memcpy(slot.buf.data() + at, frame + inner_off, inner_len);
      slot.frames[slot.n] = {at, inner_len};
      ++slot.n;
    }
  }
  counters[0] += popped;
  counters[1] += decapped;
  counters[2] += foreign;
  int32_t n = slot.n;
  // Vector count: enough batch_size-packet vectors for the kept frames,
  // bucketed to a power of two (bounded jit recompiles).
  int32_t k = 1;
  while (static_cast<uint32_t>(k) * lp->batch_size < static_cast<uint32_t>(n) &&
         static_cast<uint32_t>(k) < lp->max_vectors)
    k *= 2;
  *k_out = k;
  int32_t padded = k * static_cast<int32_t>(lp->batch_size);
  for (int32_t i = 0; i < n; ++i) {
    uint8_t* f = slot.buf.data() + slot.frames[i].off;
    FrameView v = parse_frame(f, slot.frames[i].len);
    if (!v.valid) {
      src_ip[i] = dst_ip[i] = 0;
      protocol[i] = src_port[i] = dst_port[i] = 0;
      continue;
    }
    src_ip[i] = load_be32(v.ip + 12);
    dst_ip[i] = load_be32(v.ip + 16);
    protocol[i] = v.proto;
    src_port[i] = v.has_ports ? load_be16(v.l4) : 0;
    dst_port[i] = v.has_ports ? load_be16(v.l4 + 2) : 0;
  }
  if (n < padded) {
    size_t tail = static_cast<size_t>(padded - n);
    std::memset(src_ip + n, 0, tail * sizeof(uint32_t));
    std::memset(dst_ip + n, 0, tail * sizeof(uint32_t));
    std::memset(protocol + n, 0, tail * sizeof(int32_t));
    std::memset(src_port + n, 0, tail * sizeof(int32_t));
    std::memset(dst_port + n, 0, tail * sizeof(int32_t));
  }
  return n;
}

// Harvest slot `slot`: apply verdicts + rewrites (incremental
// checksums), VXLAN-encap ROUTE_REMOTE frames, route to the TX rings.
//
// route_tag uses the pipeline's encoding (1 local / 2 remote / 3 host;
// anything else is a silent drop, matching the Python loop).
// counters (uint64[6]) += {tx_remote, tx_local, tx_host, denied,
// unparseable, unroutable}.  TX counts are frames handed to a ring —
// a full ring records the loss in its own dropped counter, the same
// split the Python loop + InMemoryRing kept.  Returns frames sent.
int32_t hs_loop_harvest(HsLoop* lp, int32_t slot_idx, const uint8_t* allowed,
                        const uint32_t* new_src, const uint32_t* new_dst,
                        const int32_t* new_sport, const int32_t* new_dport,
                        const int32_t* route_tag, const int32_t* node_id,
                        const uint32_t* remote_ips, int32_t max_node_id,
                        uint32_t local_ip, uint32_t local_node_id,
                        uint64_t* counters) {
  constexpr int32_t kRouteLocal = 1, kRouteRemote = 2, kRouteHost = 3;
  Slot& slot = lp->slots[slot_idx];
  uint64_t denied = 0, unparseable = 0, unroutable = 0;
  std::vector<int32_t> remote_rows, local_rows, host_rows;
  remote_rows.reserve(slot.n);
  for (int32_t i = 0; i < slot.n; ++i) {
    if (!allowed[i]) {
      ++denied;
      continue;
    }
    uint8_t* f = slot.buf.data() + slot.frames[i].off;
    if (!apply_rewrite(f, slot.frames[i].len, new_src[i], new_dst[i],
                       static_cast<uint16_t>(new_sport[i]),
                       static_cast<uint16_t>(new_dport[i]))) {
      ++unparseable;
      continue;
    }
    switch (route_tag[i]) {
      case kRouteRemote: {
        int32_t nid = node_id[i];
        uint32_t dst = (nid >= 0 && nid <= max_node_id) ? remote_ips[nid] : 0;
        if (dst == 0) {
          ++unroutable;
        } else {
          remote_rows.push_back(i);
        }
        break;
      }
      case kRouteLocal:
        local_rows.push_back(i);
        break;
      case kRouteHost:
        host_rows.push_back(i);
        break;
      default:
        break;  // ROUTE_DROP falls through silently (Python-loop parity)
    }
  }
  int32_t sent = 0;
  if (!remote_rows.empty() && lp->tx_remote != nullptr) {
    std::lock_guard<std::mutex> g(lp->tx_remote->mu);
    for (int32_t i : remote_rows) {
      const uint8_t* inner = slot.buf.data() + slot.frames[i].off;
      uint32_t inner_len = slot.frames[i].len;
      uint32_t total = kOuterBytes + inner_len;
      uint8_t* dst = lp->tx_remote->reserve_locked(total);
      if (dst == nullptr) {
        ++lp->tx_remote->dropped;
      } else {
        write_vxlan_outer(dst, inner_len, local_ip, remote_ips[node_id[i]],
                          local_node_id, static_cast<uint32_t>(node_id[i]),
                          lp->vni, flow_entropy(inner, inner_len));
        std::memcpy(dst + kOuterBytes, inner, inner_len);
        lp->tx_remote->commit_locked(total);
      }
    }
    counters[0] += remote_rows.size();
    sent += static_cast<int32_t>(remote_rows.size());
  }
  auto flush = [&](const std::vector<int32_t>& rows, HsRing* ring,
                   uint64_t* counter) {
    if (rows.empty() || ring == nullptr) return;
    std::lock_guard<std::mutex> g(ring->mu);
    for (int32_t i : rows) {
      ring->push_one_locked(slot.buf.data() + slot.frames[i].off,
                            slot.frames[i].len);
    }
    *counter += rows.size();
    sent += static_cast<int32_t>(rows.size());
  };
  flush(local_rows, lp->tx_local, &counters[1]);
  flush(host_rows, lp->tx_host, &counters[2]);
  counters[3] += denied;
  counters[4] += unparseable;
  counters[5] += unroutable;
  return sent;
}

// Read back one frame of a slot (slow path / trace tooling, not hot).
int32_t hs_loop_slot_frame(HsLoop* lp, int32_t slot_idx, int32_t row,
                           uint8_t* out, uint32_t out_cap) {
  Slot& slot = lp->slots[slot_idx];
  if (row < 0 || row >= slot.n) return -1;
  uint32_t len = slot.frames[row].len;
  if (len > out_cap) return -1;
  std::memcpy(out, slot.buf.data() + slot.frames[row].off, len);
  return static_cast<int32_t>(len);
}

// ---------------------------------------------------------------------------
// AF_PACKET burst IO — recvmmsg/sendmmsg between a socket and a ring
// ---------------------------------------------------------------------------

// Receive up to max_frames from fd into the ring (non-blocking bursts).
// Returns frames received (0 = nothing pending, <0 = errno-style error).
int32_t hs_afp_rx(int32_t fd, HsRing* ring, int32_t max_frames) {
  static thread_local std::vector<uint8_t> stage(kAfpBurst * kAfpFrameCap);
  mmsghdr msgs[kAfpBurst];
  iovec iovs[kAfpBurst];
  int32_t total = 0;
  while (total < max_frames) {
    uint32_t want = static_cast<uint32_t>(max_frames - total);
    if (want > kAfpBurst) want = kAfpBurst;
    for (uint32_t i = 0; i < want; ++i) {
      iovs[i] = {stage.data() + i * kAfpFrameCap, kAfpFrameCap};
      std::memset(&msgs[i], 0, sizeof(mmsghdr));
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
    }
    int got = recvmmsg(fd, msgs, want, MSG_DONTWAIT, nullptr);
    if (got <= 0) break;
    {
      std::lock_guard<std::mutex> g(ring->mu);
      for (int i = 0; i < got; ++i) {
        if (msgs[i].msg_hdr.msg_flags & MSG_TRUNC) {
          // Frame larger than the burst stage (jumbo): forwarding the
          // truncated prefix would corrupt it — count as a ring drop.
          ++ring->dropped;
          continue;
        }
        ring->push_one_locked(stage.data() + i * kAfpFrameCap, msgs[i].msg_len);
      }
    }
    total += got;
    if (static_cast<uint32_t>(got) < want) break;
  }
  return total;
}

// Transmit up to max_frames from the ring out of fd.  Frames the kernel
// refuses (EAGAIN on a full TX queue) are dropped — kernel-drop
// semantics, like the Python AfPacketIO sink.  Returns frames taken
// off the ring.
int32_t hs_afp_tx(int32_t fd, HsRing* ring, int32_t max_frames) {
  static thread_local std::vector<uint8_t> stage(kAfpBurst * kAfpFrameCap);
  uint64_t offs[kAfpBurst];
  uint32_t lens[kAfpBurst];
  mmsghdr msgs[kAfpBurst];
  iovec iovs[kAfpBurst];
  int32_t total = 0;
  while (total < max_frames) {
    int32_t want = max_frames - total;
    if (want > static_cast<int32_t>(kAfpBurst)) want = kAfpBurst;
    int32_t n = hs_ring_pop(ring, stage.data(), stage.size(), offs, lens, want);
    if (n == 0) break;
    for (int32_t i = 0; i < n; ++i) {
      iovs[i] = {stage.data() + offs[i], lens[i]};
      std::memset(&msgs[i], 0, sizeof(mmsghdr));
      msgs[i].msg_hdr.msg_iov = &iovs[i];
      msgs[i].msg_hdr.msg_iovlen = 1;
    }
    int32_t done = 0;
    while (done < n) {
      int rc = sendmmsg(fd, msgs + done, n - done, 0);
      if (rc <= 0) break;  // EAGAIN etc: remaining frames drop
      done += rc;
    }
    total += n;
    if (n < want) break;
  }
  return total;
}

}  // extern "C"
