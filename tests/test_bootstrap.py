"""Bootstrap tests: STN steal/revert/watchdog + config merge + local
snapshot pre-seed."""

import os

from vpp_tpu.bootstrap import (
    STNDaemon,
    bootstrap_config,
    load_local_snapshot,
    preseed_local_snapshot,
)
from vpp_tpu.conf.config import InterfaceConfig, NetworkConfig
from vpp_tpu.crd.models import NodeConfig, NodeInterfaceConfig
from vpp_tpu.kvstore import KVStore
from vpp_tpu.models import Pod
from vpp_tpu.models.registry import key_for
from vpp_tpu.testing.netlink import FakeHostNetwork


def _host():
    net = FakeHostNetwork()
    net.add_interface("eth0", addresses=("192.168.1.5/24",), mac="aa:bb:cc:00:00:01")
    net.add_route("0.0.0.0/0", gateway="192.168.1.1", interface="eth0")
    net.add_route("10.8.0.0/16", gateway="192.168.1.254", interface="eth0")
    return net


class TestSTN:
    def test_steal_flushes_and_saves(self):
        net = _host()
        stn = STNDaemon(net)
        saved = stn.steal_interface("eth0")
        assert saved.addresses == ("192.168.1.5/24",)
        assert len(saved.routes) == 2
        assert net.get_interface("eth0").addresses == ()
        assert not net.get_interface("eth0").up
        assert net.interface_routes("eth0") == []
        # Idempotent: a second steal returns the same saved identity.
        assert stn.steal_interface("eth0").addresses == ("192.168.1.5/24",)
        assert stn.stolen_interface_info("eth0").mac == "aa:bb:cc:00:00:01"

    def test_release_restores(self):
        net = _host()
        stn = STNDaemon(net)
        stn.steal_interface("eth0")
        stn.release_interface("eth0")
        iface = net.get_interface("eth0")
        assert iface.addresses == ("192.168.1.5/24",) and iface.up
        assert len(net.interface_routes("eth0")) == 2
        assert stn.stolen_interface_info("eth0") is None

    def test_watchdog_reverts_after_agent_death(self):
        net = _host()
        alive = {"v": True}
        stn = STNDaemon(net, agent_alive=lambda: alive["v"], revert_timeout=5.0)
        stn.steal_interface("eth0")
        assert stn.check_agent(now=100.0) is True
        alive["v"] = False
        assert stn.check_agent(now=101.0) is False   # down, not yet timed out
        assert net.get_interface("eth0").addresses == ()
        stn.check_agent(now=107.0)                   # past timeout -> revert
        assert net.get_interface("eth0").addresses == ("192.168.1.5/24",)
        # Agent returning later does not re-steal anything by itself.
        alive["v"] = True
        assert stn.check_agent(now=108.0) is True


class TestBootstrapConfig:
    def test_plain_config_passthrough(self):
        cfg = NetworkConfig(interface=InterfaceConfig(main_interface="eth1"))
        merged, stn = bootstrap_config(cfg)
        assert merged.interface.main_interface == "eth1"
        assert stn is None

    def test_node_config_overrides_file(self):
        cfg = NetworkConfig(interface=InterfaceConfig(main_interface="eth1"))
        merged, _ = bootstrap_config(
            cfg, NodeConfig(name="n1", main_interface=NodeInterfaceConfig(name="eth7"))
        )
        assert merged.interface.main_interface == "eth7"

    def test_stn_mode_steals_and_reports(self):
        net = _host()
        stn_daemon = STNDaemon(net)
        cfg = NetworkConfig(
            interface=InterfaceConfig(main_interface="eth0", stn_mode=True)
        )
        merged, stn_cfg = bootstrap_config(cfg, stn_daemon=stn_daemon)
        assert merged.interface.stn_mode
        assert stn_cfg.interface == "eth0"
        assert stn_cfg.ip_addresses == ("192.168.1.5/24",)
        assert stn_cfg.gateway == "192.168.1.1"
        assert net.get_interface("eth0").addresses == ()  # actually stolen

    def test_many_core_ingress_knobs_parse_from_dict(self):
        """ISSUE 12 deploy knobs: datapath_shards + shard_cores ride
        net.conf → NetworkConfig (defaults keep the solo runner)."""
        assert NetworkConfig.from_dict({}).datapath_shards == 1
        assert NetworkConfig.from_dict({}).shard_cores == ""
        cfg = NetworkConfig.from_dict(
            {"datapath_shards": 4, "shard_cores": "0-3;4-7;8,9;10"})
        assert cfg.datapath_shards == 4
        assert cfg.shard_cores == "0-3;4-7;8,9;10"

    def test_nodeconfig_stealth_interface_triggers_stn(self):
        net = _host()
        merged, stn_cfg = bootstrap_config(
            NetworkConfig(),
            NodeConfig(name="n1", stealth_interface="eth0"),
            stn_daemon=STNDaemon(net),
        )
        assert stn_cfg is not None and merged.interface.main_interface == "eth0"


def test_local_snapshot_roundtrip(tmp_path):
    path = os.path.join(tmp_path, "local.db")
    remote = KVStore()
    pod = Pod(name="web-1", ip_address="10.1.1.2")
    remote.put(key_for(pod), pod)
    remote.put("/vpp-tpu/external-config/x", {"v": 1})
    remote.put("/other/ignored", "nope")
    assert preseed_local_snapshot(remote, path) == 2

    local = KVStore()
    assert load_local_snapshot(local, path) == 2
    assert local.get(key_for(pod)).ip_address == "10.1.1.2"
    assert local.get("/other/ignored") is None
