"""End-to-end: fake K8s API → KSR → KV store → dbwatcher → controller →
policy stack → TPU classify verdicts.

The full control-plane path of SURVEY.md §3.3, with the K8s API played
by FakeK8sCluster and the data plane by the real jit classify kernel.
"""

import time

from vpp_tpu.conf import IPAMConfig
from vpp_tpu.controller.dbwatcher import DBWatcher
from vpp_tpu.controller.eventloop import Controller
from vpp_tpu.controller.txn import TxnSink
from vpp_tpu.ipam import IPAM
from vpp_tpu.ksr import KSRPlugin, KVBroker
from vpp_tpu.kvstore import KVStore
from vpp_tpu.ops.classify import classify
from vpp_tpu.ops.packets import make_batch
from vpp_tpu.policy import PolicyPlugin
from vpp_tpu.policy.renderer.tpu import TpuPolicyRenderer
from vpp_tpu.testing.k8s import FakeK8sCluster
from vpp_tpu.testing.cluster import wait_for as _shared_wait_for


class RecordingSink(TxnSink):
    def __init__(self):
        self.txns = []

    def commit(self, txn):
        self.txns.append(txn)


# Shared poll-until-deadline helper (machine-speed-scaled).
_wait = _shared_wait_for


def test_k8s_to_tpu_verdicts():
    store = KVStore()
    cluster = FakeK8sCluster()
    ksr = KSRPlugin(cluster, KVBroker(store))
    ksr.init(start_monitor=False)
    assert ksr.has_synced()

    renderer = TpuPolicyRenderer()
    policy = PolicyPlugin(ipam=IPAM(IPAMConfig(), node_id=1))
    policy.register_renderer(renderer)
    ctl = Controller(handlers=[policy], sink=RecordingSink())
    ctl.start()
    watcher = DBWatcher(ctl, store)
    watcher.start()

    try:
        for i in range(3):
            cluster.apply("pods", {
                "metadata": {"name": f"web-{i}", "namespace": "default",
                             "labels": {"app": "web"}},
                "status": {"podIP": f"10.1.1.{i + 2}"}, "spec": {}})
        cluster.apply("pods", {
            "metadata": {"name": "intruder", "namespace": "default",
                         "labels": {"app": "other"}},
            "status": {"podIP": "10.1.1.99"}, "spec": {}})
        cluster.apply("networkpolicies", {
            "metadata": {"name": "web-isolate", "namespace": "default"},
            "spec": {"podSelector": {"matchLabels": {"app": "web"}},
                     "policyTypes": ["Ingress"],
                     "ingress": [{"ports": [{"protocol": "TCP", "port": 80}],
                                  "from": [{"podSelector":
                                            {"matchLabels": {"app": "web"}}}]}]}})
        assert _wait(lambda: renderer.tables is not None
                     and int(renderer.tables.rule_valid.sum()) > 0)

        batch = make_batch([
            ("10.1.1.2", "10.1.1.3", 6, 4444, 80),    # web -> web :80
            ("10.1.1.99", "10.1.1.3", 6, 4444, 80),   # intruder
            ("10.1.1.2", "10.1.1.3", 6, 4444, 443),   # wrong port
        ])
        allowed = [int(v) for v in classify(renderer.tables, batch).allowed]
        assert allowed == [1, 0, 0]

        # Policy withdrawn via the API -> traffic opens up.
        cluster.delete("networkpolicies", "web-isolate")
        assert _wait(lambda: renderer.tables is not None
                     and int(renderer.tables.rule_valid.sum()) == 0)
        allowed = [int(v) for v in classify(renderer.tables, batch).allowed]
        assert allowed == [1, 1, 1]
    finally:
        watcher.stop()
        ctl.stop()
        ksr.close()
