"""KSR reflector tests.

Modeled on the reference's ``plugins/ksr/*_reflector_test.go`` pattern:
a fake K8s ListWatch + a KV broker, asserting on data-store contents and
reflector stats, including the data-store failure → mark-and-sweep
reconciliation path.
"""

import time

import pytest

from vpp_tpu.ksr import KSRPlugin, KVBroker, make_reflectors
from vpp_tpu.kvstore import KVStore
from vpp_tpu.models import (
    Namespace,
    Policy,
    PolicyType,
    Pod,
    Service,
)
from vpp_tpu.models.registry import key_for, resource
from vpp_tpu.testing.k8s import FakeK8sCluster
from vpp_tpu.testing.cluster import timeout_mult


def k8s_pod(name, namespace="default", labels=None, ip="", host_ip="", containers=None):
    return {
        "metadata": {"name": name, "namespace": namespace, "labels": labels or {}},
        "spec": {"containers": containers or []},
        "status": {"podIP": ip, "hostIP": host_ip},
    }


@pytest.fixture()
def setup():
    cluster = FakeK8sCluster()
    store = KVStore()
    broker = KVBroker(store)
    reflectors = make_reflectors(cluster, broker,
                                 min_resync_timeout=0.01, max_resync_timeout=0.05)
    return cluster, store, broker, reflectors


class TestPodReflector:
    def test_initial_list_reflected(self, setup):
        cluster, store, _, reflectors = setup
        cluster.apply("pods", k8s_pod("web-1", labels={"app": "web"}, ip="10.1.1.2"))
        cluster.apply("pods", k8s_pod("db-1", namespace="prod", ip="10.1.1.3"))
        r = reflectors["pods"]
        r.start()
        assert r.has_synced
        assert r.stats.adds == 2
        pod = store.get(resource("pod").key_prefix + "default/web-1")
        assert isinstance(pod, Pod)
        assert pod.ip_address == "10.1.1.2"
        assert dict(pod.labels) == {"app": "web"}

    def test_add_update_delete_flow(self, setup):
        cluster, store, _, reflectors = setup
        r = reflectors["pods"]
        r.start()
        cluster.apply("pods", k8s_pod("web-1", ip=""))
        key = resource("pod").key_prefix + "default/web-1"
        assert store.get(key).ip_address == ""
        # IP assignment arrives as an update.
        cluster.apply("pods", k8s_pod("web-1", ip="10.1.1.7"))
        assert store.get(key).ip_address == "10.1.1.7"
        assert r.stats.updates == 1
        # No-op update is skipped (proto.Equal analog).
        cluster.apply("pods", k8s_pod("web-1", ip="10.1.1.7"))
        assert r.stats.updates == 1
        cluster.delete("pods", "web-1")
        assert store.get(key) is None
        assert r.stats.deletes == 1

    def test_stale_data_store_entries_swept(self, setup):
        cluster, store, _, reflectors = setup
        stale_key = resource("pod").key_prefix + "default/gone"
        store.put(stale_key, Pod(name="gone"))
        changed_key = resource("pod").key_prefix + "default/web-1"
        store.put(changed_key, Pod(name="web-1", ip_address="10.9.9.9"))
        cluster.apply("pods", k8s_pod("web-1", ip="10.1.1.2"))
        r = reflectors["pods"]
        r.start()
        assert store.get(stale_key) is None
        assert store.get(changed_key).ip_address == "10.1.1.2"
        assert r.stats.deletes == 1 and r.stats.updates == 1

    def test_malformed_object_counts_arg_error(self, setup):
        cluster, _, _, reflectors = setup
        r = reflectors["pods"]
        r.start()
        cluster.apply("pods", {"metadata": {}})  # no name
        assert r.stats.arg_errors == 1
        assert r.stats.adds == 0


class FlakyBroker(KVBroker):
    """Broker whose writes can be switched off (etcd outage analog)."""

    def __init__(self, store):
        super().__init__(store)
        self.down = False

    def _check(self):
        if self.down:
            raise ConnectionError("store down")

    def put(self, key, value):
        self._check()
        super().put(key, value)

    def delete(self, key):
        self._check()
        super().delete(key)

    def list_values(self, prefix):
        self._check()
        return super().list_values(prefix)

    def probe(self):
        return not self.down


class TestResync:
    def test_write_failure_triggers_background_resync(self):
        cluster = FakeK8sCluster()
        store = KVStore()
        broker = FlakyBroker(store)
        r = make_reflectors(cluster, broker,
                            min_resync_timeout=0.01, max_resync_timeout=0.05)["pods"]
        r.start()
        assert r.has_synced
        broker.down = True
        cluster.apply("pods", k8s_pod("web-1", ip="10.1.1.2"))
        assert not r.has_synced
        assert r.stats.add_errors == 1
        # While out of sync, further changes only land in the K8s cache.
        cluster.apply("pods", k8s_pod("web-2", ip="10.1.1.3"))
        key1 = resource("pod").key_prefix + "default/web-1"
        key2 = resource("pod").key_prefix + "default/web-2"
        assert store.get(key1) is None and store.get(key2) is None
        # Store recovers; the backoff loop reconciles both pods.
        broker.down = False
        deadline = time.time() + 2.0 * timeout_mult()
        while not r.has_synced and time.time() < deadline:
            time.sleep(0.01)
        assert r.has_synced
        assert store.get(key1).ip_address == "10.1.1.2"
        assert store.get(key2).ip_address == "10.1.1.3"


class TestConverters:
    def test_network_policy_conversion(self, setup):
        cluster, store, _, reflectors = setup
        reflectors["networkpolicies"].start()
        cluster.apply(
            "networkpolicies",
            {
                "metadata": {"name": "allow-web", "namespace": "prod"},
                "spec": {
                    "podSelector": {"matchLabels": {"app": "web"}},
                    "policyTypes": ["Ingress", "Egress"],
                    "ingress": [
                        {
                            "ports": [{"protocol": "TCP", "port": 80}],
                            "from": [
                                {"podSelector": {"matchLabels": {"role": "fe"}}},
                                {"ipBlock": {"cidr": "10.0.0.0/8",
                                             "except": ["10.1.0.0/16"]}},
                            ],
                        }
                    ],
                    "egress": [
                        {"to": [{"namespaceSelector": {
                            "matchExpressions": [
                                {"key": "env", "operator": "In",
                                 "values": ["prod", "stage"]}]}}]}
                    ],
                },
            },
        )
        pol = store.get(resource("policy").key_prefix + "prod/allow-web")
        assert isinstance(pol, Policy)
        assert pol.policy_type == PolicyType.INGRESS_AND_EGRESS
        assert dict(pol.pods.match_labels) == {"app": "web"}
        rule = pol.ingress_rules[0]
        assert rule.ports[0].port == 80
        assert rule.from_peers[1].ip_block.cidr == "10.0.0.0/8"
        assert rule.from_peers[1].ip_block.except_cidrs == ("10.1.0.0/16",)
        expr = pol.egress_rules[0].to_peers[0].namespaces.match_expressions[0]
        assert expr.key == "env" and expr.values == ("prod", "stage")

    def test_service_and_endpoints_conversion(self, setup):
        cluster, store, _, reflectors = setup
        reflectors["services"].start()
        reflectors["endpoints"].start()
        cluster.apply(
            "services",
            {
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {
                    "type": "NodePort",
                    "clusterIP": "10.96.0.10",
                    "selector": {"app": "web"},
                    "externalTrafficPolicy": "Local",
                    "ports": [{"name": "http", "protocol": "TCP", "port": 80,
                               "targetPort": 8080, "nodePort": 30080}],
                },
            },
        )
        svc = store.get(resource("service").key_prefix + "default/web")
        assert isinstance(svc, Service)
        assert svc.service_type == "NodePort"
        assert svc.ports[0].node_port == 30080
        assert svc.external_traffic_policy == "Local"

        cluster.apply(
            "endpoints",
            {
                "metadata": {"name": "web", "namespace": "default"},
                "subsets": [
                    {
                        "addresses": [
                            {"ip": "10.1.1.2", "nodeName": "node-1",
                             "targetRef": {"kind": "Pod", "name": "web-1",
                                           "namespace": "default"}}],
                        "ports": [{"name": "http", "port": 8080, "protocol": "TCP"}],
                    }
                ],
            },
        )
        eps = store.get(resource("endpoints").key_prefix + "default/web")
        addr = eps.subsets[0].addresses[0]
        assert addr.ip == "10.1.1.2" and addr.target_pod.name == "web-1"

    def test_namespace_and_node_conversion(self, setup):
        cluster, store, _, reflectors = setup
        reflectors["namespaces"].start()
        reflectors["nodes"].start()
        cluster.apply("namespaces",
                      {"metadata": {"name": "prod", "labels": {"env": "prod"}}})
        ns = store.get(resource("namespace").key_prefix + "prod")
        assert isinstance(ns, Namespace) and dict(ns.labels) == {"env": "prod"}
        cluster.apply(
            "nodes",
            {
                "metadata": {"name": "node-1"},
                "spec": {"podCIDR": "10.1.1.0/24"},
                "status": {"addresses": [
                    {"type": "InternalIP", "address": "192.168.16.1"},
                    {"type": "Hostname", "address": "node-1"}]},
            },
        )
        node = store.get(resource("node").key_prefix + "node-1")
        assert node.internal_ip() == "192.168.16.1"
        assert node.pod_cidr == "10.1.1.0/24"


class TestPlugin:
    def test_store_outage_and_recovery_via_monitor(self):
        cluster = FakeK8sCluster()
        store = KVStore()
        broker = FlakyBroker(store)
        plugin = KSRPlugin(cluster, broker, probe_interval=0.01,
                           min_resync_timeout=0.01, max_resync_timeout=0.05)
        plugin.init(start_monitor=False)
        assert plugin.has_synced()
        # Outage: monitor notices, reflectors hold updates.
        broker.down = True
        assert plugin.check_data_store() is False
        cluster.apply("pods", k8s_pod("web-1", ip="10.1.1.2"))
        assert not plugin.has_synced()
        # Recovery: up event reconciles everything.
        broker.down = False
        assert plugin.check_data_store() is True
        deadline = time.time() + 2.0 * timeout_mult()
        while not plugin.has_synced() and time.time() < deadline:
            time.sleep(0.01)
        assert plugin.has_synced()
        key = resource("pod").key_prefix + "default/web-1"
        assert store.get(key).ip_address == "10.1.1.2"
        stats = plugin.get_stats()
        assert stats["pods"]["adds"] >= 1
        plugin.close()


class TestSfcReflector:
    """sfc_pod_reflector.go analog: pods labeled sfc=true reflected as
    {pod, node} records under the sfc/ prefix."""

    def test_only_sfc_labeled_pods_reflected(self, setup):
        cluster, store, _, reflectors = setup
        sfc_pod = k8s_pod("chain-1", labels={"sfc": "true"})
        sfc_pod["spec"]["nodeName"] = "node-7"
        cluster.apply("pods", sfc_pod)
        cluster.apply("pods", k8s_pod("plain", labels={"app": "web"}))
        r = reflectors["sfc-pods"]
        r.start()
        assert r.has_synced
        from vpp_tpu.models import Sfc

        rec = store.get(resource("sfc").key_prefix + "default/chain-1")
        assert rec == Sfc(pod="chain-1", node="node-7", namespace="default")
        assert store.get(resource("sfc").key_prefix + "default/plain") is None
        # Filtered misses are not "malformed" errors.
        assert r.stats.arg_errors == 0

    def test_label_removal_deletes_sfc_record(self, setup):
        cluster, store, _, reflectors = setup
        r = reflectors["sfc-pods"]
        r.start()
        sfc_pod = k8s_pod("chain-1", labels={"sfc": "true"})
        sfc_pod["spec"]["nodeName"] = "node-7"
        cluster.apply("pods", sfc_pod)
        key = resource("sfc").key_prefix + "default/chain-1"
        assert store.get(key) is not None
        # Label flips off: the record must be deleted, not left stale.
        plain = k8s_pod("chain-1", labels={})
        plain["spec"]["nodeName"] = "node-7"
        cluster.apply("pods", plain)
        assert store.get(key) is None
        # Pod deletion with the label present also cleans up.
        cluster.apply("pods", sfc_pod)
        assert store.get(key) is not None
        cluster.delete("pods", "chain-1", "default")
        assert store.get(key) is None
