"""End-to-end telemetry suite (ISSUE 8).

Three pillars, each tested at its own layer and then through the full
stack:

- **Histogram recorder units**: log2 bucket boundaries, percentile
  interpolation, read-side merge, the frame-weighted e2e view, and the
  property that matters for the lock-free design — a reader
  snapshotting/merging CONCURRENTLY with a single hot writer never
  crashes, never goes backwards, and converges to the exact totals.
- **Datapath integration**: a driven runner fills all four latency
  histograms and the flight recorder; table generations stamp flight
  rows AND packet traces; the sharded engine merges per-shard
  recorders; ejection/quarantine snapshot the ring next to the pcap.
- **Span lifecycle**: a policy txn driven through a REAL controller
  with the mock-engine oracle + scheduler applicators + a live runner
  stamps every stage (handler → compile → swap → shard adoption) and
  advances the config-propagation histogram, visible via REST
  ``/contiv/v1/spans`` and ``netctl spans``.
- **Export surfaces**: ``*_total`` counters leave /metrics as COUNTER
  families (rate() survives restarts), histograms as cumulative-le
  HISTOGRAM families with derived-percentile gauges alongside.
"""

import io
import json
import threading
import time

import pytest

import jax.numpy as jnp

from vpp_tpu.controller import Controller, DBResync, KubeStateChange
from vpp_tpu.datapath import (
    DataplaneRunner,
    InMemoryRing,
    NativeRing,
    ShardedDataplane,
    VxlanOverlay,
)
from vpp_tpu.models import (
    IngressRule,
    LabelSelector,
    Pod,
    Policy,
    PolicyPort,
    PolicyType,
    key_for,
)
from vpp_tpu.netctl.cli import main as netctl_main
from vpp_tpu.ops.classify import build_rule_tables
from vpp_tpu.ops.nat import build_nat_tables
from vpp_tpu.ops.packets import ip_to_u32
from vpp_tpu.ops.pipeline import RouteConfig
from vpp_tpu.policy import PolicyPlugin
from vpp_tpu.policy.renderer.sched import SchedPolicyRenderer
from vpp_tpu.rest.server import AgentRestServer
from vpp_tpu.scheduler import TxnScheduler
from vpp_tpu.scheduler.tpu_applicators import TpuAclApplicator
from vpp_tpu.telemetry import (
    FlightRecorder,
    LatencyRecorder,
    Log2Histogram,
    SpanTracker,
    record_stage,
)
from vpp_tpu.telemetry.hist import N_BUCKETS
from vpp_tpu.testing import MockACLEngine
from vpp_tpu.testing.faults import SITE_DISPATCH_RAISE
from vpp_tpu.testing.frames import build_frame


def make_route():
    return RouteConfig(
        pod_subnet_base=jnp.asarray(ip_to_u32("10.1.0.0"), dtype=jnp.uint32),
        pod_subnet_mask=jnp.asarray(0xFFFF0000, dtype=jnp.uint32),
        this_node_base=jnp.asarray(ip_to_u32("10.1.1.0"), dtype=jnp.uint32),
        this_node_mask=jnp.asarray(0xFFFFFF00, dtype=jnp.uint32),
        host_bits=jnp.asarray(8, dtype=jnp.int32),
    )


def make_runner(engine="python", **kw):
    rings = [NativeRing() if engine == "native" else InMemoryRing()
             for _ in range(4)]
    kw.setdefault("batch_size", 8)
    kw.setdefault("max_vectors", 2)
    runner = DataplaneRunner(
        acl=build_rule_tables([], {}),
        nat=build_nat_tables(
            [], nat_loopback="10.1.1.254", snat_ip="192.168.16.1",
            snat_enabled=True, pod_subnet="10.1.0.0/16",
        ),
        route=make_route(),
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                             local_node_id=1),
        source=rings[0], tx=rings[1], local=rings[2], host=rings[3],
        **kw,
    )
    return runner, rings


# ------------------------------------------------------- histogram units


def test_bucket_boundaries():
    h = Log2Histogram()
    # bucket 0 = (-inf clamps), ≤1 µs; bucket i covers (2^(i-1), 2^i].
    h.record_us(0.0)
    h.record_us(-5.0)      # clamps to 0, never a negative index
    h.record_us(1.0)       # int(1).bit_length() == 1 → bucket 1
    h.record_us(1.5)       # still bucket 1 (≤2 µs)
    h.record_us(2.5)       # bucket 2 (≤4 µs)
    h.record_us(float(1 << 20))
    h.record_us(1e30)      # far past the range → +Inf catch-all
    assert h.counts[0] == 2
    assert h.counts[1] == 2
    assert h.counts[2] == 1
    assert h.counts[21] == 1  # 2^20 µs lands in bucket 21 ((2^20, 2^21])
    assert h.counts[N_BUCKETS - 1] == 1
    assert h.count == 7
    # The +Inf bucket's percentile reports its LOWER edge (no upper).
    only_inf = Log2Histogram()
    only_inf.record_us(1e30)
    assert only_inf.percentile_us(0.5) == Log2Histogram.bound_us(N_BUCKETS - 2)


def test_percentiles_interpolate_within_bucket():
    h = Log2Histogram()
    for _ in range(100):
        h.record_us(300.0)  # all in bucket (256, 512]
    p50 = h.percentile_us(0.50)
    assert 256.0 <= p50 <= 512.0
    # Two-bucket split: 90 low + 10 high → p50 in the low bucket, p99
    # in the high one.
    h2 = Log2Histogram()
    for _ in range(90):
        h2.record_us(10.0)
    for _ in range(10):
        h2.record_us(5000.0)
    assert h2.percentile_us(0.50) <= 16.0
    assert 4096.0 <= h2.percentile_us(0.99) <= 8192.0
    snap = h2.snapshot()
    assert snap["count"] == 100
    assert snap["p999"] >= snap["p99"] >= snap["p90"] >= snap["p50"]


def test_merge_equals_combined():
    a, b, c = Log2Histogram(), Log2Histogram(), Log2Histogram()
    for i in range(50):
        a.record_us(float(i))
        c.record_us(float(i))
    for i in range(50):
        b.record_us(float(i * 100))
        c.record_us(float(i * 100))
    m = a.merged([b])
    assert m.counts == c.counts
    assert m.count == c.count == 100
    assert abs(m.sum_us - c.sum_us) < 1e-6
    # Merging never mutates the sources.
    assert a.count == 50 and b.count == 50


def test_frame_weighted_e2e():
    rec = LatencyRecorder()
    rec.record_harvest(t_admit=0.0, t_harvest=0.001, t_done=0.002, frames=64)
    assert rec.dispatch_rt.count == 1
    assert rec.frame_e2e.count == 64  # one batch sample stands for its frames
    assert rec.admit_wait.count == 1
    assert rec.harvest.count == 1


def test_recorder_disabled_is_noop():
    rec = LatencyRecorder(enabled=False)
    rec.record_harvest(0.0, 0.001, 0.002, 10)
    assert rec.dispatch_rt.count == 0
    rec.enabled = True
    rec.record_harvest(0.0, 0.001, 0.002, 10)
    assert rec.dispatch_rt.count == 1


def test_concurrent_single_writer_vs_reader_merge():
    """The lock-free contract: one hot writer, readers snapshotting and
    merging concurrently.  Readers must never crash, observed counts
    must be monotonically non-decreasing, and after the writer joins
    the totals must be EXACT (nothing torn, nothing lost)."""
    h = Log2Histogram()
    n = 20000
    stop = threading.Event()
    seen = []
    errors = []

    def writer():
        for i in range(n):
            h.record_us(float(i % 4096), weight=1)
        stop.set()

    def reader():
        last = 0
        while not stop.is_set():
            try:
                snap = h.snapshot()
                merged = h.merged([Log2Histogram()])
                assert merged.count == sum(merged.counts)
            except Exception as err:  # noqa: BLE001 - the property under test
                errors.append(err)
                return
            # Bucket-sum monotonicity: the ring only ever grows.
            total = snap["count"]
            if total < last:
                errors.append(AssertionError(f"count went back: {total} < {last}"))
                return
            last = total
            seen.append(total)

    w = threading.Thread(target=writer)
    r = threading.Thread(target=reader)
    r.start()
    w.start()
    w.join(30)
    r.join(30)
    assert not errors, errors[:3]
    assert h.count == n
    assert sum(h.counts) == n
    assert h.snapshot()["count"] == n
    assert len(seen) > 0  # the reader actually raced the writer


# --------------------------------------------------- datapath integration


@pytest.mark.parametrize("engine", ["python", "native"])
def test_runner_fills_latency_and_flight(engine):
    runner, rings = make_runner(engine=engine)
    frames = [build_frame("10.1.1.2", "10.1.1.3", 6, 40000 + i, 80)
              for i in range(24)]
    rings[0].send(frames)
    sent = runner.drain()
    assert sent == 24
    lat = runner.inspect()["latency"]
    for name in ("admit_wait", "dispatch_rt", "harvest", "frame_e2e"):
        assert lat[name]["count"] > 0, name
        assert lat[name]["p999"] >= lat[name]["p50"] >= 0.0
    # frame_e2e is frame-weighted: as many samples as frames dispatched.
    assert lat["frame_e2e"]["count"] == 24
    # Flight rows carry the batch context.
    flight = runner.dump_flight()["shards"][0]
    assert flight["shard"] == 0
    assert flight["recorded"] >= 1
    row = flight["records"][-1]
    assert row["frames"] > 0 and row["sent"] > 0
    assert row["k"] >= 1 and row["rt_us"] > 0.0
    assert row["table_gen"] == 0  # no swap yet
    assert runner.inspect()["flight"]["dispatches_total"] >= 1
    runner.close()


def test_table_gen_stamps_flight_and_trace():
    runner, rings = make_runner(engine="python")
    runner.tracer.enable()
    rings[0].send([build_frame("10.1.1.2", "10.1.1.3", 6, 40000, 80)])
    runner.drain()
    assert runner.tracer.dump()[-1]["table_gen"] == 0
    # A swap bumps the generation; later batches stamp the new one.
    runner.update_tables(acl=build_rule_tables([], {}))
    assert runner.inspect_dispatch()["table_gen"] == 1
    rings[0].send([build_frame("10.1.1.2", "10.1.1.3", 6, 40001, 80)])
    runner.drain()
    entry = runner.tracer.dump()[-1]
    assert entry["table_gen"] == 1
    assert entry["k"] >= 1
    assert runner.flight.dump()[-1]["table_gen"] == 1
    runner.close()


def test_sharded_merges_latency_and_flight():
    def ios(n):
        return [tuple(NativeRing() for _ in range(4)) for _ in range(n)]

    dp = ShardedDataplane(
        acl=build_rule_tables([], {}),
        nat=build_nat_tables(
            [], nat_loopback="10.1.1.254", snat_ip="192.168.16.1",
            snat_enabled=True, pod_subnet="10.1.0.0/16",
        ),
        route=make_route(),
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                             local_node_id=1),
        shard_ios=ios(2), batch_size=8, max_vectors=2,
    )
    for i, r in enumerate(dp.shards):
        r.source.send(
            [build_frame("10.1.1.2", "10.1.1.3", 6, 41000 + 10 * i + j, 80)
             for j in range(8)])
    dp.drain()
    merged = dp.inspect()["latency"]
    per_shard = [r.telemetry.dispatch_rt.count for r in dp.shards]
    assert merged["dispatch_rt"]["count"] == sum(per_shard)
    assert all(c > 0 for c in per_shard)  # both shards really dispatched
    shards = dp.dump_flight()["shards"]
    assert [s["shard"] for s in shards] == [0, 1]
    assert all(s["recorded"] >= 1 for s in shards)
    assert dp.inspect()["flight"]["recorded"] == sum(
        s["recorded"] for s in shards)
    dp.close()


# -------------------------------------------------------- flight forensics


def test_quarantine_snapshots_flight_next_to_pcap(tmp_path):
    pcap = str(tmp_path / "q.pcap")
    runner, rings = make_runner(engine="python", quarantine_pcap=pcap)
    # Build some pre-fault history so the snapshot has context rows.
    rings[0].send([build_frame("10.1.1.2", "10.1.1.3", 6, 40000, 80)])
    runner.drain()
    runner.faults.arm(SITE_DISPATCH_RAISE, match={"src_port": 4242})
    frames = [build_frame("10.1.1.2", "10.1.1.3", 6, 40001, 80),
              build_frame("10.1.1.4", "10.1.1.3", 6, 4242, 80)]
    rings[0].send(frames)
    runner.drain()
    assert runner.counters.quarantined_batches == 1
    path = tmp_path / "q.pcap.flight.jsonl"
    assert path.exists(), "flight snapshot must land next to the pcap"
    snap = json.loads(path.read_text().splitlines()[-1])
    assert snap["reason"] == "quarantine"
    assert snap["shard"] == 0
    assert len(snap["records"]) >= 1  # the pre-fault dispatch context
    runner.faults.disarm()
    runner.close()


def test_ejection_snapshots_flight(tmp_path):
    pcap = str(tmp_path / "ej.pcap")
    dp = ShardedDataplane(
        acl=build_rule_tables([], {}),
        nat=build_nat_tables(
            [], nat_loopback="10.1.1.254", snat_ip="192.168.16.1",
            snat_enabled=True, pod_subnet="10.1.0.0/16",
        ),
        route=make_route(),
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                             local_node_id=1),
        shard_ios=[tuple(NativeRing() for _ in range(4))],
        batch_size=8, max_vectors=2,
        eject_errors=1, quarantine=False, quarantine_pcap=pcap,
    )
    # Healthy history first, then every dispatch fails → instant eject.
    dp.shards[0].source.send(
        [build_frame("10.1.1.2", "10.1.1.3", 6, 40000, 80)])
    dp.drain()
    dp.faults.arm(SITE_DISPATCH_RAISE, shard=0)
    dp.shards[0].source.send(
        [build_frame("10.1.1.2", "10.1.1.3", 6, 40001, 80)])
    deadline = time.monotonic() + 10
    while dp.health_of[0].state != "ejected" and time.monotonic() < deadline:
        dp.poll()
    assert dp.health_of[0].state == "ejected"
    path = tmp_path / "ej.pcap.flight.jsonl"
    assert path.exists(), "ejection must dump the flight ring"
    snap = json.loads(path.read_text().splitlines()[-1])
    assert snap["reason"].startswith("ejection")
    assert len(snap["records"]) >= 1
    dp.faults.disarm()
    dp.close()


# ------------------------------------------------------ span lifecycle


WEB = Pod(name="web", namespace="default", labels={"app": "web"},
          ip_address="10.1.1.2")


def _policy(name="deny-all", port=None):
    return Policy(
        name=name, namespace="default",
        pods=LabelSelector(match_labels={"app": "web"}),
        policy_type=PolicyType.INGRESS,
        # With a port the policy renders an allow rule; without it the
        # rendered tables differ — which is what makes an UPDATE event
        # actually recompile (identical rendered state is correctly
        # skipped by the scheduler diff).
        ingress_rules=(
            (IngressRule(ports=(PolicyPort(port=port),)),)
            if port is not None else ()
        ),
    )


def test_full_span_lifecycle_policy_txn():
    """The acceptance scenario: a controller-driven policy update with
    the mock engines yields a COMPLETE span — handler processing,
    applicator compile (delta/full labelled), device swap, per-shard
    adoption — and a nonzero config-propagation histogram, correlated
    to the committed txn by span id and visible via REST + netctl."""
    runner, _rings = make_runner(engine="python")
    oracle = MockACLEngine()
    oracle.register_pod(WEB.id, WEB.ip_address)
    acl_app = TpuAclApplicator()
    acl_app.on_compiled = lambda t: runner.update_tables(acl=t)
    scheduler = TxnScheduler()
    scheduler.register_applicator(acl_app)
    plugin = PolicyPlugin()
    plugin.register_renderer(
        SchedPolicyRenderer(lambda: ctl.current_txn, applicator=acl_app))
    plugin.register_renderer(oracle)
    ctl = Controller([plugin], scheduler)
    ctl.start()
    try:
        resync = DBResync(kube_state={
            "pod": {key_for(WEB): WEB},
            "policy": {key_for(_policy()): _policy()},
            "namespace": {},
        })
        ctl.push_event(resync)
        assert resync.wait(30) is None
        gen_after_resync = runner.inspect_dispatch()["table_gen"]
        assert gen_after_resync >= 1  # resync compiled + swapped + adopted
        update = KubeStateChange(
            "policy", key_for(_policy()), _policy(),
            _policy("deny-all", port=80))
        ctl.push_event(update)
        assert update.wait(30) is None

        spans = ctl.spans.dump()
        assert len(spans) >= 2
        span = spans[-1]
        assert span["event"] == "Kubernetes State Change"
        stages = [s["stage"] for s in span["stages"]]
        # Every propagation stage stamped, in execution order.
        for expected in ("handler:policy", "compile:acl", "swap:acl",
                         "adopt:shard0", "commit"):
            assert expected in stages, (expected, stages)
        assert stages.index("compile:acl") < stages.index("swap:acl")
        # Adoption nests INSIDE the swap, so its stamp lands first.
        assert stages.index("adopt:shard0") < stages.index("swap:acl")
        compile_stage = next(s for s in span["stages"]
                             if s["stage"] == "compile:acl")
        assert compile_stage["mode"] in ("delta", "full")
        assert span["propagated"] is True
        assert span["total_us"] > 0.0

        # The propagation histogram advanced (end-to-end latency is now
        # a first-class distribution).
        status = ctl.spans.status()
        assert status["propagation_us"]["count"] >= 2
        assert status["propagation_us"]["p50"] > 0.0

        # Span id correlates event history ↔ scheduler txn log.
        record = ctl.event_history[-1]
        assert record.span_id == span["span_id"]
        assert record.txn is not None and record.txn.span_id == span["span_id"]
        assert scheduler.txn_log[-1].span_id == span["span_id"]

        # The device really adopted again on the update.
        assert runner.inspect_dispatch()["table_gen"] > gen_after_resync

        # REST + netctl read the same ring.
        rest = AgentRestServer(node_name="n1", controller=ctl,
                               datapath=runner)
        port = rest.start()
        try:
            out = io.StringIO()
            assert netctl_main(
                ["spans", "--server", f"127.0.0.1:{port}"], out=out) == 0
            text = out.getvalue()
            assert "compile:acl" in text and "adopt:shard0" in text
            assert "propagation:" in text
            out = io.StringIO()
            assert netctl_main(
                ["flight", "--server", f"127.0.0.1:{port}"], out=out) == 0
            # No traffic flowed in this control-plane test — the dump
            # is an empty ring, not an error.
            assert "shard 0  dispatches=0" in out.getvalue()
        finally:
            rest.stop()
    finally:
        ctl.stop()
        runner.close()


# --------------------------------------------------------- export surfaces


def test_metrics_exporter_counter_histogram_and_spans():
    from prometheus_client import CollectorRegistry, generate_latest

    from vpp_tpu.statscollector.plugin import StatsCollector

    runner, rings = make_runner(engine="python")
    rings[0].send([build_frame("10.1.1.2", "10.1.1.3", 6, 40000 + i, 80)
                   for i in range(8)])
    runner.drain()
    collector = StatsCollector(registry=CollectorRegistry())
    collector.register_datapath(runner)
    tracker = SpanTracker()
    span = tracker.start("Kubernetes State Change")
    record_stage("swap:acl", 0.0015)
    tracker.finish(span)
    collector.register_spans(tracker)
    text = generate_latest(collector.registry).decode()
    # Satellite: monotonic *_total series are COUNTERS now (rate()
    # survives agent restarts); gauges stay gauges.
    assert "# TYPE datapath_rx_frames_total counter" in text
    assert "# TYPE datapath_batches_total counter" in text
    assert "# TYPE datapath_inflight gauge" in text
    assert "# TYPE datapath_governor_k gauge" in text
    # Tentpole: latency histograms in cumulative-le form + derived
    # percentile gauges, and the control-plane propagation histogram.
    assert 'datapath_latency_dispatch_rt_us_bucket{le="+Inf"}' in text
    assert "datapath_latency_frame_e2e_us_count" in text
    assert "# TYPE datapath_latency_harvest_p999_us gauge" in text
    assert "controlplane_config_propagation_us_bucket" in text
    assert "controlplane_spans_propagated_total 1.0" in text
    runner.close()


def test_dashboard_latency_panel_schema():
    """shape_latency consumes exactly what inspect() produces — the
    obs-parity checker enforces this statically; this is the runtime
    proof on a real runner."""
    from vpp_tpu.uibackend.views import shape_latency

    runner, rings = make_runner(engine="python")
    rings[0].send([build_frame("10.1.1.2", "10.1.1.3", 6, 40000, 80)])
    runner.drain()
    panel = shape_latency(runner.inspect())
    assert panel["dispatch_rt"]["count"] == 1
    assert panel["frame_e2e"]["count"] == 1
    assert panel["dispatch_rt"]["p999"] >= panel["dispatch_rt"]["p50"] > 0
    assert panel["flight"]["dispatches_total"] == 1
    assert shape_latency(None) == {}
    runner.close()


def test_flight_snapshots_are_incremental(tmp_path):
    """A poison storm snapshots per batch — each snapshot must append
    only the records since the previous one (not re-dump the whole
    ring), or the forensic file grows by ~ring-size per batch."""
    fr = FlightRecorder(capacity=8)
    path = str(tmp_path / "f.jsonl")
    for i in range(3):
        fr.note_dispatch(ts=i, k=1, frames=8, sent=8, denied=0, backlog=0,
                         inflight=0, table_gen=0, rt_us=1.0)
    fr.snapshot_to(path, reason="quarantine")
    fr.note_dispatch(ts=3, k=1, frames=8, sent=8, denied=0, backlog=0,
                     inflight=0, table_gen=0, rt_us=1.0)
    fr.snapshot_to(path, reason="quarantine")
    fr.snapshot_to(path, reason="ejection: x")  # nothing new: header only
    lines = [json.loads(ln) for ln in open(path)]
    assert [len(ln["records"]) for ln in lines] == [3, 1, 0]
    assert lines[1]["records"][0]["seq"] == 4
    # The concatenation reconstructs the full history.
    assert [r["seq"] for ln in lines for r in ln["records"]] == [1, 2, 3, 4]


def test_flight_recorder_ring_bounds_and_status():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.note_dispatch(ts=i, k=1, frames=8, sent=8, denied=0, backlog=0,
                         inflight=0, table_gen=0, rt_us=100.0)
    assert len(fr) == 4
    assert fr.status()["dispatches_total"] == 10
    rows = fr.dump()
    assert [r["seq"] for r in rows] == [7, 8, 9, 10]
    assert fr.dump(limit=2)[0]["seq"] == 9
