"""NodeSync ID allocation and PodManager CNI-event tests."""

import threading
import time

from vpp_tpu.controller import Controller, DBResync, EventHandler
from vpp_tpu.kvstore import KVStore
from vpp_tpu.models import PodID, VppNode
from vpp_tpu.nodesync import NodeSync
from vpp_tpu.nodesync.nodesync import VPPNODE_PREFIX
from vpp_tpu.podmanager import AddPod, DeletePod, PodManager
from vpp_tpu.scheduler import TxnScheduler


def test_first_free_id_allocation():
    store = KVStore()
    a = NodeSync(store, "node-a")
    b = NodeSync(store, "node-b")
    assert a.allocate_id() == 1
    assert b.allocate_id() == 2
    # Departure frees the ID for reuse.
    a.release_id()
    c = NodeSync(store, "node-c")
    assert c.allocate_id() == 1
    # Restarted agent adopts its old record.
    b2 = NodeSync(store, "node-b")
    assert b2.allocate_id() == 2


def test_concurrent_allocation_unique_ids():
    store = KVStore()
    results = {}

    def alloc(name):
        ns = NodeSync(store, name)
        results[name] = ns.allocate_id()

    threads = [threading.Thread(target=alloc, args=(f"n{i}",)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ids = sorted(results.values())
    assert ids == list(range(1, 17))  # all unique, first-free


def test_publish_and_track_nodes():
    store = KVStore()
    ns = NodeSync(store, "node-a")
    ns.allocate_id()
    rec = ns.publish_node_ips(("192.168.16.1/24",), ("10.0.0.1",))
    assert store.get(VPPNODE_PREFIX + "1") == rec

    other = VppNode(id=2, name="node-b", ip_addresses=("192.168.16.2/24",))
    kube_state = {"vppnode": {VPPNODE_PREFIX + "1": rec, VPPNODE_PREFIX + "2": other}}
    ns.resync(None, kube_state, 1, None)
    assert set(ns.get_all_nodes()) == {"node-a", "node-b"}
    assert set(ns.other_nodes()) == {"node-b"}


def test_podmanager_add_delete_flow():
    """CNI add/del through the real event loop with a wiring handler that
    fills the CNI reply (the ipv4net role)."""

    class Wiring(EventHandler):
        name = "wiring"

        def resync(self, event, kube_state, resync_count, txn):
            pass

        def update(self, event, txn):
            if isinstance(event, AddPod):
                event.reply.ip_address = "10.1.1.2/32"
                event.reply.interfaces.append({"name": "tap-" + event.pod.id.name})
                txn.put(f"/cfg/pod/{event.pod.id}", {"wired": True})
            if isinstance(event, DeletePod):
                txn.delete(f"/cfg/pod/{event.pod_id}")
            return ""

    pm = PodManager()
    sched = TxnScheduler()
    ctl = Controller([pm, Wiring()], sched, healing_delay=0.05)
    pm.event_loop = ctl
    ctl.start()
    try:
        ctl.push_event(DBResync())
        reply = pm.add_pod("web", "default", container_id="c1", network_namespace="/proc/1/ns/net")
        assert reply.ip_address == "10.1.1.2/32"
        assert reply.interfaces == [{"name": "tap-web"}]
        assert PodID("web", "default") in pm.local_pods
        assert sched.dump("/cfg/pod/")[0].key == "/cfg/pod/default/web"

        pm.delete_pod("web", "default")
        assert pm.local_pods == {}
        assert sched.dump("/cfg/pod/") == []
    finally:
        ctl.stop()


def test_podmanager_addpod_revert_on_failure():
    """A failing downstream handler must revert podmanager's record."""

    class Failing(EventHandler):
        name = "failing"

        def resync(self, event, kube_state, resync_count, txn):
            pass

        def update(self, event, txn):
            if isinstance(event, AddPod):
                raise RuntimeError("no connectivity for you")
            return ""

    pm = PodManager()
    ctl = Controller([pm, Failing()], TxnScheduler(), healing_delay=0.05)
    pm.event_loop = ctl
    ctl.start()
    try:
        ctl.push_event(DBResync())
        try:
            pm.add_pod("web", "default")
            raise AssertionError("expected failure")
        except RuntimeError as e:
            assert "no connectivity" in str(e)
        # Reverted: no local pod recorded.
        assert pm.local_pods == {}
    finally:
        ctl.stop()


class FakeRuntime:
    """Injectable container-runtime client (the Docker-client analog)."""

    def __init__(self, sandboxes=(), fail=False):
        self.sandboxes = list(sandboxes)
        self.fail = fail

    def list_sandboxes(self):
        if self.fail:
            raise RuntimeError("runtime down")
        return list(self.sandboxes)


def test_podmanager_resyncs_from_container_runtime():
    """podmanager.go Resync :137-200: local pods re-learned from the
    runtime on the first resync and on healing resyncs only; non-running,
    unlabeled and bare sandboxes are skipped."""
    from vpp_tpu.controller.api import DBResync, HealingResync, HealingResyncType
    from vpp_tpu.models import PodID
    from vpp_tpu.podmanager import PodManager, Sandbox

    runtime = FakeRuntime([
        Sandbox("c1", "web-1", "default", "/var/run/netns/c1"),
        Sandbox("c2", "db-1", "prod", "", pid=42),
        Sandbox("c3", "gone", "default", state="exited"),
        Sandbox("c4", "", ""),                      # missing identification
        Sandbox("c5", "bare", "default", pid=0),    # no process
    ])
    pm = PodManager(runtime=runtime)
    pm.resync(DBResync(), {}, 1, None)
    pods = pm.local_pods
    assert set(pods) == {PodID("web-1", "default"), PodID("db-1", "prod")}
    assert pods[PodID("web-1", "default")].container_id == "c1"
    assert pods[PodID("db-1", "prod")].network_namespace == "/proc/42/ns/net"

    # Later plain resyncs do NOT re-read the runtime...
    runtime.sandboxes.append(Sandbox("c9", "late", "default"))
    pm.resync(DBResync(), {}, 2, None)
    assert PodID("late", "default") not in pm.local_pods
    # ...but healing resyncs do.
    pm.resync(HealingResync(HealingResyncType.AFTER_ERROR), {}, 3, None)
    assert PodID("late", "default") in pm.local_pods


def test_podmanager_runtime_failure_is_fatal():
    import pytest
    from vpp_tpu.controller.api import DBResync, FatalError
    from vpp_tpu.podmanager import PodManager

    pm = PodManager(runtime=FakeRuntime(fail=True))
    with pytest.raises(FatalError):
        pm.resync(DBResync(), {}, 1, None)
