"""Native host shim tests: C++ parse/apply vs the pure-Python oracle,
and the full frames → pipeline → rewritten-frames round trip."""

import numpy as np
import pytest

from vpp_tpu.ops.packets import PacketBatch, ip_to_u32, u32_to_ip
from vpp_tpu.shim import HostShim
from vpp_tpu.testing.frames import build_frame, frame_tuple, verify_checksums


@pytest.fixture(scope="module")
def shim():
    return HostShim()


class TestParse:
    def test_parse_matches_python_oracle(self, shim):
        rng = np.random.default_rng(7)
        frames = []
        for i in range(64):
            proto = [6, 17, 1][i % 3]
            frames.append(
                build_frame(
                    src_ip=f"10.1.1.{rng.integers(2, 250)}",
                    dst_ip=f"10.96.0.{rng.integers(1, 250)}",
                    protocol=proto,
                    src_port=int(rng.integers(1024, 65535)),
                    dst_port=[80, 443, 53][i % 3],
                    vlan=100 if i % 5 == 0 else None,
                    payload=bytes(rng.integers(0, 256, rng.integers(0, 64), dtype=np.uint8)),
                )
            )
        fb = shim.parse(frames)
        assert fb.n == 64
        assert fb.batch.src_ip.shape[0] == 256  # padded to the vector size
        for i, frame in enumerate(frames):
            src, dst, proto, sport, dport = frame_tuple(frame)
            assert int(fb.batch.src_ip[i]) == ip_to_u32(src)
            assert int(fb.batch.dst_ip[i]) == ip_to_u32(dst)
            assert int(fb.batch.protocol[i]) == proto
            assert int(fb.batch.src_port[i]) == sport
            assert int(fb.batch.dst_port[i]) == dport
            assert fb.flags[i] & 1
            assert bool(fb.flags[i] & 2) == (proto in (6, 17))

    def test_non_ip_and_truncated_frames(self, shim):
        arp = b"\xff" * 12 + b"\x08\x06" + b"\x00" * 28
        runt = b"\x02\x00"
        fb = shim.parse([arp, runt, build_frame("10.0.0.1", "10.0.0.2")])
        assert fb.flags[0] == 0 and fb.flags[1] == 0
        assert fb.flags[2] & 1
        assert int(fb.batch.src_ip[0]) == 0

    def test_fragment_has_no_ports(self, shim):
        f = bytearray(build_frame("10.0.0.1", "10.0.0.2", protocol=17))
        # Set fragment offset 185 (non-first fragment).
        f[14 + 6] = 0x00 | (185 >> 8)
        f[14 + 7] = 185 & 0xFF
        fb = shim.parse([bytes(f)])
        assert fb.flags[0] & 1 and not (fb.flags[0] & 2)
        assert int(fb.batch.src_port[0]) == 0


class TestApply:
    def _rewrite(self, fb, **overrides):
        b = fb.batch
        fields = dict(
            src_ip=np.asarray(b.src_ip).copy(), dst_ip=np.asarray(b.dst_ip).copy(),
            protocol=np.asarray(b.protocol).copy(),
            src_port=np.asarray(b.src_port).copy(),
            dst_port=np.asarray(b.dst_port).copy(),
        )
        for k, v in overrides.items():
            fields[k][: len(v)] = v
        return PacketBatch(**fields)

    def test_dnat_rewrite_keeps_checksums_valid(self, shim):
        for proto in (6, 17):
            frames = [
                build_frame("10.1.1.2", "10.96.0.10", protocol=proto,
                            src_port=40000, dst_port=80),
            ]
            fb = shim.parse(frames)
            rewritten = self._rewrite(
                fb,
                dst_ip=[ip_to_u32("10.1.1.7")],
                dst_port=[8080],
            )
            out = shim.apply(fb, np.ones(fb.n), rewritten)
            assert len(out) == 1
            src, dst, p, sport, dport = frame_tuple(out[0])
            assert (dst, dport) == ("10.1.1.7", 8080)
            assert verify_checksums(out[0]), "incremental checksum broke the frame"

    def test_snat_rewrite_and_drop(self, shim):
        frames = [
            build_frame("10.1.1.2", "93.184.216.34", src_port=40000, dst_port=443),
            build_frame("10.1.1.3", "10.1.1.4", src_port=1000, dst_port=80),
        ]
        fb = shim.parse(frames)
        rewritten = self._rewrite(
            fb,
            src_ip=[ip_to_u32("192.168.16.1"), ip_to_u32("10.1.1.3")],
            src_port=[61000, 1000],
        )
        out = shim.apply(fb, np.array([1, 0]), rewritten)
        assert len(out) == 1  # second dropped
        src, dst, p, sport, dport = frame_tuple(out[0])
        assert (src, sport) == ("192.168.16.1", 61000)
        assert verify_checksums(out[0])

    def test_udp_disabled_checksum_stays_disabled(self, shim):
        frames = [build_frame("10.1.1.2", "10.96.0.10", protocol=17,
                              src_port=5000, dst_port=53, udp_checksum=False)]
        fb = shim.parse(frames)
        rewritten = self._rewrite(fb, dst_ip=[ip_to_u32("10.1.1.9")])
        out = shim.apply(fb, np.ones(1), rewritten)
        # Checksum field must remain 0 (disabled), frame otherwise valid.
        assert verify_checksums(out[0])
        _, dst, _, _, _ = frame_tuple(out[0])
        assert dst == "10.1.1.9"

    def test_vlan_frame_rewrite(self, shim):
        frames = [build_frame("10.1.1.2", "10.96.0.10", vlan=42,
                              src_port=40000, dst_port=80)]
        fb = shim.parse(frames)
        rewritten = self._rewrite(fb, dst_ip=[ip_to_u32("10.1.1.7")], dst_port=[8080])
        out = shim.apply(fb, np.ones(1), rewritten)
        assert verify_checksums(out[0])
        assert frame_tuple(out[0])[1] == "10.1.1.7"


class TestEndToEnd:
    def test_frames_through_pipeline(self, shim):
        """frames -> shim.parse -> jit pipeline -> shim.apply -> frames."""
        import jax.numpy as jnp

        from vpp_tpu.conf import IPAMConfig
        from vpp_tpu.ipam import IPAM
        from vpp_tpu.ops.classify import build_rule_tables
        from vpp_tpu.ops.nat import NatMapping, build_nat_tables, empty_sessions
        from vpp_tpu.ops.pipeline import make_route_config, pipeline_step
        from vpp_tpu.policy.renderer.api import Action, ContivRule

        ipam = IPAM(IPAMConfig(), node_id=1)
        acl = build_rule_tables([], {})
        nat = build_nat_tables(
            [NatMapping("10.96.0.10", 80, 6, [("10.1.1.7", 8080, 1)])],
            nat_loopback=str(ipam.nat_loopback_ip()),
            snat_ip="192.168.16.1",
            snat_enabled=True,
            pod_subnet=str(ipam.pod_subnet_all_nodes),
        )
        route = make_route_config(ipam)
        frames = [
            build_frame("10.1.1.2", "10.96.0.10", src_port=40000 + i, dst_port=80)
            for i in range(8)
        ]
        fb = shim.parse(frames)
        res = pipeline_step(acl, nat, route, empty_sessions(1024),
                            fb.batch, jnp.int32(0))
        out = shim.apply(fb, res.allowed, res.batch)
        assert len(out) == 8
        for frame in out:
            src, dst, proto, sport, dport = frame_tuple(frame)
            assert (dst, dport) == ("10.1.1.7", 8080), "DNAT not applied"
            assert verify_checksums(frame)
