"""Restart-chaos system tests (VERDICT r2 item 6).

The reference's Robot suites restart nodes and agents with traffic in
flight (tests/robot/suites/two_node_two_pods.robot; SURVEY §5.3).  The
analogs here run on the FrameCluster — REAL Ethernet frames through
the native runner loop — and assert the healing/resync machinery
restores frame delivery:

- agent restart mid-traffic: the node's whole agent stack (controller,
  dbwatcher, renderers, runner, device tables) is torn down and
  rebuilt against the cluster store; the startup resync recompiles the
  tables and cross-node service traffic flows again, including replies
  for sessions created BEFORE the restart (which die with the device
  table — replies ride the re-established forward path instead);
- store outage mid-traffic: the cluster store becomes unreachable; the
  DATA PLANE keeps forwarding (tables live on device — the reference's
  "VPP keeps switching while etcd is down" property), control-plane
  changes queue, and on store recovery the reconnect resync applies
  them; frame delivery reflects the new policy.
- store-leader kill mid-traffic: the cluster store is a 3-replica HA
  ensemble (the clustered-etcd analog, kvstore/ha.py); SIGKILL-ing the
  leader elects a follower, the agents' clients fail over transparently,
  KSR writes resume, and no policy/service state is lost.
"""

from vpp_tpu.kvstore import KVStoreServer, RemoteKVStore
from vpp_tpu.kvstore.ha import HAEnsemble
from vpp_tpu.testing.cluster import timeout_mult, wait_for
from vpp_tpu.testing.framecluster import FrameCluster, FrameNode
from vpp_tpu.testing.frames import build_frame, frame_tuple, verify_checksums

WEB = {"app": "web"}


def _service_state(cluster, backend_node, backend_ip):
    cluster.apply_service({
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"clusterIP": "10.96.0.10", "selector": WEB,
                 "ports": [{"name": "http", "protocol": "TCP", "port": 80,
                            "targetPort": 8080}]},
    })
    cluster.apply_endpoints({
        "metadata": {"name": "web", "namespace": "default"},
        "subsets": [{
            "addresses": [{"ip": backend_ip, "nodeName": backend_node,
                           "targetRef": {"kind": "Pod", "name": "web-1",
                                         "namespace": "default"}}],
            "ports": [{"name": "http", "port": 8080, "protocol": "TCP"}],
        }],
    })


def test_agent_restart_mid_traffic_resyncs_and_traffic_resumes():
    """Kill node-2's agent while service traffic flows; the rebuilt
    agent resyncs from the store and cross-node delivery resumes."""
    cluster = FrameCluster()
    try:
        n1 = cluster.add_node("node-1")
        cluster.add_node("node-2")
        client_ip = cluster.deploy_pod("node-1", "client")
        backend_ip = cluster.deploy_pod("node-2", "web-1", labels=WEB)
        _service_state(cluster, "node-2", backend_ip)
        assert wait_for(lambda: len(n1.nat_renderer.mappings()) > 0)

        # Traffic flows before the chaos.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 43000, 80)])
        cluster.run_datapaths()
        out = cluster.delivered_frames("node-2")
        assert len(out) == 1
        assert frame_tuple(out[0]) == (client_ip, backend_ip, 6, 43000, 8080)

        # ---- kill the agent mid-traffic --------------------------------
        # Frames are sitting in node-2's rx ring (its NIC queue) when
        # the whole agent stack dies: controller, dbwatcher, renderers,
        # runner, device tables, rings — gone.  Like a vswitch crash,
        # queued frames are lost; transports retransmit.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6,
                                              43001 + i, 80) for i in range(4)])
        cluster.frame_nodes["node-1"].drain()  # frames now on node-2's wire ring
        dead = cluster.nodes["node-2"]
        dead_rx = cluster.frame_nodes["node-2"].rx
        assert len(dead_rx) == 4  # in flight at the moment of death
        dead.stop()

        # ---- restart: a fresh agent against the same cluster store -----
        node2 = cluster.add_node("node-2")  # adopts its node ID, resyncs
        assert node2.nodesync.node_id == dead.nodesync.node_id
        # The startup resync recompiled the NAT/policy tables from the
        # store (no KubeState replay needed — the store retained it).
        assert wait_for(lambda: len(node2.nat_renderer.mappings()) > 0)

        # The client retransmits the lost frames; the rebuilt node
        # delivers them through its freshly compiled tables.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6,
                                              43001 + i, 80) for i in range(4)])
        cluster.run_datapaths()
        out = cluster.delivered_frames("node-2")
        assert len(out) == 4
        for i, f in enumerate(sorted(out, key=lambda f: frame_tuple(f)[3])):
            assert frame_tuple(f) == (client_ip, backend_ip, 6, 43001 + i, 8080)
            assert verify_checksums(f)

        # New traffic after the restart flows end to end, and replies for
        # POST-restart sessions restore through the new session table.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 44000, 80)])
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-2")) == 1
        cluster.inject("node-2", [build_frame(backend_ip, client_ip, 6, 8080, 44000)])
        cluster.run_datapaths()
        rep = cluster.delivered_frames("node-1")
        assert len(rep) == 1
        assert frame_tuple(rep[0]) == ("10.96.0.10", client_ip, 6, 80, 44000)
    finally:
        cluster.stop()


class RemoteStoreFrameCluster(FrameCluster):
    """FrameCluster whose agents reach the store over gRPC, so the
    store can suffer a real outage (server down) mid-traffic."""

    def __init__(self):
        super().__init__()
        self.server = KVStoreServer(self.store)
        self.port = self.server.start()
        self._clients = []

    def add_node(self, name):
        client = RemoteKVStore(f"127.0.0.1:{self.port}", timeout=2.0)
        self._clients.append(client)
        real = self.store
        self.store = client       # SimNode consumes cluster.store
        try:
            return super().add_node(name)
        finally:
            self.store = real

    def outage(self):
        # grace=0: sever open watch streams NOW — a real outage does not
        # drain in-flight RPCs for 200ms first.
        self.server.stop(grace=0.0)

    def recover(self):
        self.server = KVStoreServer(self.store, port=self.port)
        self.server.start()

    def stop(self):
        super().stop()
        for c in self._clients:
            c.close()
        self.server.stop()


def test_store_outage_mid_traffic_dataplane_survives_and_heals():
    """The store dies under traffic: frames keep flowing on the device
    tables; a policy applied during the outage lands after recovery via
    the reconnect resync and is then enforced on frames."""
    cluster = RemoteStoreFrameCluster()
    try:
        cluster.add_node("node-1")
        ip1 = cluster.deploy_pod("node-1", "web-1", labels=WEB)
        ip2 = cluster.deploy_pod("node-1", "web-2", labels=WEB)
        node = cluster.nodes["node-1"]
        assert wait_for(lambda: len(node.podmanager.local_pods) == 2)

        cluster.inject("node-1", [build_frame(ip1, ip2, 6, 45000, 80)])
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-1")) == 1

        # ---- outage ----------------------------------------------------
        cluster.outage()

        # The data plane keeps forwarding while the store is down — the
        # reference's central resilience property (device tables are
        # node-local state).
        cluster.inject("node-1", [build_frame(ip1, ip2, 6, 45001 + i, 80)
                                  for i in range(8)])
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-1")) == 8

        # A deny-all policy lands in K8s/KSR during the outage; the
        # agent cannot see it yet (its watch stream is down).
        cluster.apply_policy({
            "metadata": {"name": "deny-all", "namespace": "default"},
            "spec": {"podSelector": {"matchLabels": WEB},
                     "policyTypes": ["Ingress"], "ingress": []},
        })
        cluster.inject("node-1", [build_frame(ip1, ip2, 6, 46000, 80)])
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-1")) == 1  # still open

        # ---- recovery --------------------------------------------------
        cluster.recover()
        # Reconnect resync pulls the policy and recompiles the tables.
        assert wait_for(
            lambda: node.policy_renderer.tables is not None
            and int(node.policy_renderer.tables.rule_valid.sum()) > 0,
            timeout=10.0,
        )
        cluster.inject("node-1", [build_frame(ip1, ip2, 6, 47000, 80)])
        cluster.run_datapaths()  # syncs tables, then drives the frames
        assert cluster.delivered_frames("node-1") == []  # now denied
        assert cluster.frame_nodes["node-1"].runner.counters.dropped_denied >= 1
    finally:
        cluster.stop()


class HAStoreFrameCluster(FrameCluster):
    """FrameCluster on a 3-replica HA store ensemble: the KSR and every
    agent reach the store through leader-following multi-address
    clients, so the LEADER can be killed mid-traffic."""

    def __init__(self):
        self.ensemble = HAEnsemble(3, heartbeat_interval=0.05,
                                   lease_timeout=0.4 * timeout_mult())
        self.ensemble.wait_leader()
        self._clients = []
        super().__init__(store=self._client())  # the KSR-side client

    def _client(self):
        client = self.ensemble.client(
            timeout=1.0, failover_deadline=20.0 * timeout_mult())
        self._clients.append(client)
        return client

    def add_node(self, name):
        client = self._client()      # one leader-following client per agent
        ksr_client = self.store
        self.store = client          # SimNode consumes cluster.store
        try:
            return super().add_node(name)
        finally:
            self.store = ksr_client

    def stop(self):
        super().stop()
        for client in self._clients:
            client.close()
        self.ensemble.stop()


def test_store_leader_kill_mid_traffic_failover_and_no_lost_state():
    """SIGKILL the store leader under service traffic: frames keep
    flowing on the device tables during the election, a follower takes
    over, KSR writes resume through the failed-over clients, and no
    policy/service state is lost — the surviving replicas hold
    identical state and a post-kill policy lands on the agents."""
    cluster = HAStoreFrameCluster()
    try:
        n1 = cluster.add_node("node-1")
        n2 = cluster.add_node("node-2")
        client_ip = cluster.deploy_pod("node-1", "client")
        backend_ip = cluster.deploy_pod("node-2", "web-1", labels=WEB)
        _service_state(cluster, "node-2", backend_ip)
        assert wait_for(lambda: len(n1.nat_renderer.mappings()) > 0)

        # Service traffic flows before the chaos.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 43000, 80)])
        cluster.run_datapaths()
        out = cluster.delivered_frames("node-2")
        assert len(out) == 1
        assert frame_tuple(out[0]) == (client_ip, backend_ip, 6, 43000, 8080)

        # ---- SIGKILL the store leader mid-traffic ----------------------
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6,
                                              43001 + i, 80) for i in range(4)])
        dead = cluster.ensemble.kill_leader()
        # The DATA PLANE keeps forwarding while the election runs —
        # tables live on device, the reference's central resilience
        # property, now under leader loss instead of full outage.
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-2")) == 4

        # A follower is elected within the lease window.
        new = cluster.ensemble.wait_leader(timeout=10.0 * timeout_mult())
        assert new.address != dead.address

        # No lost service state: the surviving replicas hold identical
        # contents, still including the reflected service + endpoints.
        live = [r for r in cluster.ensemble.replicas
                if r.address != dead.address]
        assert wait_for(lambda: (
            live[0].store.snapshot_with_revision([""])
            == live[1].store.snapshot_with_revision([""])
        ), timeout=10.0)
        assert any("service" in k for k, _ in new.store.list(""))

        # KSR writes resume: a policy applied AFTER the kill reaches the
        # agents through the failed-over clients and is ENFORCED on
        # frames (deny-all on the backend).
        cluster.apply_policy({
            "metadata": {"name": "deny-all", "namespace": "default"},
            "spec": {"podSelector": {"matchLabels": WEB},
                     "policyTypes": ["Ingress"], "ingress": []},
        })
        assert wait_for(
            lambda: n2.policy_renderer.tables is not None
            and int(n2.policy_renderer.tables.rule_valid.sum()) > 0,
            timeout=15.0,
        ), "post-kill policy never reached the agents"
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 44000, 80)])
        cluster.run_datapaths()
        assert cluster.delivered_frames("node-2") == []  # denied
        # Enforced wherever the reflected rule lands first (the source
        # node drops at egress when its tables already carry it).
        assert sum(fn.runner.counters.dropped_denied
                   for fn in cluster.frame_nodes.values()) >= 1
    finally:
        cluster.stop()
