"""Restart-chaos system tests (VERDICT r2 item 6).

The reference's Robot suites restart nodes and agents with traffic in
flight (tests/robot/suites/two_node_two_pods.robot; SURVEY §5.3).  The
analogs here run on the FrameCluster — REAL Ethernet frames through
the native runner loop — and assert the healing/resync machinery
restores frame delivery:

- agent restart mid-traffic: the node's whole agent stack (controller,
  dbwatcher, renderers, runner, device tables) is torn down and
  rebuilt against the cluster store; the startup resync recompiles the
  tables and cross-node service traffic flows again, including replies
  for sessions created BEFORE the restart (which die with the device
  table — replies ride the re-established forward path instead);
- store outage mid-traffic: the cluster store becomes unreachable; the
  DATA PLANE keeps forwarding (tables live on device — the reference's
  "VPP keeps switching while etcd is down" property), control-plane
  changes queue, and on store recovery the reconnect resync applies
  them; frame delivery reflects the new policy.
- store-leader kill mid-traffic: the cluster store is a 3-replica HA
  ensemble (the clustered-etcd analog, kvstore/ha.py); SIGKILL-ing the
  leader elects a follower, the agents' clients fail over transparently,
  KSR writes resume, and no policy/service state is lost.
"""

import ipaddress

import pytest

import jax.numpy as jnp

from vpp_tpu.datapath import NativeRing, ShardedDataplane, TableSwapError, VxlanOverlay
from vpp_tpu.kvstore import KVStoreServer, RemoteKVStore
from vpp_tpu.kvstore.ha import HAEnsemble
from vpp_tpu.models import ProtocolType
from vpp_tpu.ops.classify import NO_TABLE, build_rule_tables
from vpp_tpu.ops.nat import NatMapping, build_nat_tables
from vpp_tpu.ops.packets import ip_to_u32
from vpp_tpu.ops.pipeline import RouteConfig
from vpp_tpu.policy.renderer.api import Action, ContivRule
from vpp_tpu.testing.aclengine import Verdict, evaluate_table
from vpp_tpu.testing.cluster import timeout_mult, wait_for
from vpp_tpu.testing.faults import SITE_DISPATCH_HANG, SITE_DISPATCH_RAISE, SITE_SWAP_FAIL
from vpp_tpu.testing.framecluster import FrameCluster, FrameNode
from vpp_tpu.testing.frames import build_frame, frame_tuple, verify_checksums

WEB = {"app": "web"}


def _service_state(cluster, backend_node, backend_ip):
    cluster.apply_service({
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"clusterIP": "10.96.0.10", "selector": WEB,
                 "ports": [{"name": "http", "protocol": "TCP", "port": 80,
                            "targetPort": 8080}]},
    })
    cluster.apply_endpoints({
        "metadata": {"name": "web", "namespace": "default"},
        "subsets": [{
            "addresses": [{"ip": backend_ip, "nodeName": backend_node,
                           "targetRef": {"kind": "Pod", "name": "web-1",
                                         "namespace": "default"}}],
            "ports": [{"name": "http", "port": 8080, "protocol": "TCP"}],
        }],
    })


def test_agent_restart_mid_traffic_resyncs_and_traffic_resumes():
    """Kill node-2's agent while service traffic flows; the rebuilt
    agent resyncs from the store and cross-node delivery resumes."""
    cluster = FrameCluster()
    try:
        n1 = cluster.add_node("node-1")
        cluster.add_node("node-2")
        client_ip = cluster.deploy_pod("node-1", "client")
        backend_ip = cluster.deploy_pod("node-2", "web-1", labels=WEB)
        _service_state(cluster, "node-2", backend_ip)
        assert wait_for(lambda: len(n1.nat_renderer.mappings()) > 0)

        # Traffic flows before the chaos.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 43000, 80)])
        cluster.run_datapaths()
        out = cluster.delivered_frames("node-2")
        assert len(out) == 1
        assert frame_tuple(out[0]) == (client_ip, backend_ip, 6, 43000, 8080)

        # ---- kill the agent mid-traffic --------------------------------
        # Frames are sitting in node-2's rx ring (its NIC queue) when
        # the whole agent stack dies: controller, dbwatcher, renderers,
        # runner, device tables, rings — gone.  Like a vswitch crash,
        # queued frames are lost; transports retransmit.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6,
                                              43001 + i, 80) for i in range(4)])
        cluster.frame_nodes["node-1"].drain()  # frames now on node-2's wire ring
        dead = cluster.nodes["node-2"]
        dead_rx = cluster.frame_nodes["node-2"].rx
        assert len(dead_rx) == 4  # in flight at the moment of death
        dead.stop()

        # ---- restart: a fresh agent against the same cluster store -----
        node2 = cluster.add_node("node-2")  # adopts its node ID, resyncs
        assert node2.nodesync.node_id == dead.nodesync.node_id
        # The startup resync recompiled the NAT/policy tables from the
        # store (no KubeState replay needed — the store retained it).
        assert wait_for(lambda: len(node2.nat_renderer.mappings()) > 0)

        # The client retransmits the lost frames; the rebuilt node
        # delivers them through its freshly compiled tables.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6,
                                              43001 + i, 80) for i in range(4)])
        cluster.run_datapaths()
        out = cluster.delivered_frames("node-2")
        assert len(out) == 4
        for i, f in enumerate(sorted(out, key=lambda f: frame_tuple(f)[3])):
            assert frame_tuple(f) == (client_ip, backend_ip, 6, 43001 + i, 8080)
            assert verify_checksums(f)

        # New traffic after the restart flows end to end, and replies for
        # POST-restart sessions restore through the new session table.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 44000, 80)])
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-2")) == 1
        cluster.inject("node-2", [build_frame(backend_ip, client_ip, 6, 8080, 44000)])
        cluster.run_datapaths()
        rep = cluster.delivered_frames("node-1")
        assert len(rep) == 1
        assert frame_tuple(rep[0]) == ("10.96.0.10", client_ip, 6, 80, 44000)
    finally:
        cluster.stop()


class RemoteStoreFrameCluster(FrameCluster):
    """FrameCluster whose agents reach the store over gRPC, so the
    store can suffer a real outage (server down) mid-traffic."""

    def __init__(self):
        super().__init__()
        self.server = KVStoreServer(self.store)
        self.port = self.server.start()
        self._clients = []

    def add_node(self, name):
        client = RemoteKVStore(f"127.0.0.1:{self.port}", timeout=2.0)
        self._clients.append(client)
        real = self.store
        self.store = client       # SimNode consumes cluster.store
        try:
            return super().add_node(name)
        finally:
            self.store = real

    def outage(self):
        # grace=0: sever open watch streams NOW — a real outage does not
        # drain in-flight RPCs for 200ms first.
        self.server.stop(grace=0.0)

    def recover(self):
        self.server = KVStoreServer(self.store, port=self.port)
        self.server.start()

    def stop(self):
        super().stop()
        for c in self._clients:
            c.close()
        self.server.stop()


def test_store_outage_mid_traffic_dataplane_survives_and_heals():
    """The store dies under traffic: frames keep flowing on the device
    tables; a policy applied during the outage lands after recovery via
    the reconnect resync and is then enforced on frames."""
    cluster = RemoteStoreFrameCluster()
    try:
        cluster.add_node("node-1")
        ip1 = cluster.deploy_pod("node-1", "web-1", labels=WEB)
        ip2 = cluster.deploy_pod("node-1", "web-2", labels=WEB)
        node = cluster.nodes["node-1"]
        assert wait_for(lambda: len(node.podmanager.local_pods) == 2)

        cluster.inject("node-1", [build_frame(ip1, ip2, 6, 45000, 80)])
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-1")) == 1

        # ---- outage ----------------------------------------------------
        cluster.outage()

        # The data plane keeps forwarding while the store is down — the
        # reference's central resilience property (device tables are
        # node-local state).
        cluster.inject("node-1", [build_frame(ip1, ip2, 6, 45001 + i, 80)
                                  for i in range(8)])
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-1")) == 8

        # A deny-all policy lands in K8s/KSR during the outage; the
        # agent cannot see it yet (its watch stream is down).
        cluster.apply_policy({
            "metadata": {"name": "deny-all", "namespace": "default"},
            "spec": {"podSelector": {"matchLabels": WEB},
                     "policyTypes": ["Ingress"], "ingress": []},
        })
        cluster.inject("node-1", [build_frame(ip1, ip2, 6, 46000, 80)])
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-1")) == 1  # still open

        # ---- recovery --------------------------------------------------
        cluster.recover()
        # Reconnect resync pulls the policy and recompiles the tables.
        assert wait_for(
            lambda: node.policy_renderer.tables is not None
            and int(node.policy_renderer.tables.rule_valid.sum()) > 0,
            timeout=10.0,
        )
        cluster.inject("node-1", [build_frame(ip1, ip2, 6, 47000, 80)])
        cluster.run_datapaths()  # syncs tables, then drives the frames
        assert cluster.delivered_frames("node-1") == []  # now denied
        assert cluster.frame_nodes["node-1"].runner.counters.dropped_denied >= 1
    finally:
        cluster.stop()


class HAStoreFrameCluster(FrameCluster):
    """FrameCluster on a 3-replica HA store ensemble: the KSR and every
    agent reach the store through leader-following multi-address
    clients, so the LEADER can be killed mid-traffic."""

    def __init__(self):
        self.ensemble = HAEnsemble(3, heartbeat_interval=0.05,
                                   lease_timeout=0.4 * timeout_mult())
        self.ensemble.wait_leader()
        self._clients = []
        super().__init__(store=self._client())  # the KSR-side client

    def _client(self):
        client = self.ensemble.client(
            timeout=1.0, failover_deadline=20.0 * timeout_mult())
        self._clients.append(client)
        return client

    def add_node(self, name):
        client = self._client()      # one leader-following client per agent
        ksr_client = self.store
        self.store = client          # SimNode consumes cluster.store
        try:
            return super().add_node(name)
        finally:
            self.store = ksr_client

    def stop(self):
        super().stop()
        for client in self._clients:
            client.close()
        self.ensemble.stop()


def test_store_leader_kill_mid_traffic_failover_and_no_lost_state():
    """SIGKILL the store leader under service traffic: frames keep
    flowing on the device tables during the election, a follower takes
    over, KSR writes resume through the failed-over clients, and no
    policy/service state is lost — the surviving replicas hold
    identical state and a post-kill policy lands on the agents."""
    cluster = HAStoreFrameCluster()
    try:
        n1 = cluster.add_node("node-1")
        n2 = cluster.add_node("node-2")
        client_ip = cluster.deploy_pod("node-1", "client")
        backend_ip = cluster.deploy_pod("node-2", "web-1", labels=WEB)
        _service_state(cluster, "node-2", backend_ip)
        assert wait_for(lambda: len(n1.nat_renderer.mappings()) > 0)

        # Service traffic flows before the chaos.
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 43000, 80)])
        cluster.run_datapaths()
        out = cluster.delivered_frames("node-2")
        assert len(out) == 1
        assert frame_tuple(out[0]) == (client_ip, backend_ip, 6, 43000, 8080)

        # ---- SIGKILL the store leader mid-traffic ----------------------
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6,
                                              43001 + i, 80) for i in range(4)])
        dead = cluster.ensemble.kill_leader()
        # The DATA PLANE keeps forwarding while the election runs —
        # tables live on device, the reference's central resilience
        # property, now under leader loss instead of full outage.
        cluster.run_datapaths()
        assert len(cluster.delivered_frames("node-2")) == 4

        # A follower is elected within the lease window.
        new = cluster.ensemble.wait_leader(timeout=10.0 * timeout_mult())
        assert new.address != dead.address

        # No lost service state: the surviving replicas hold identical
        # contents, still including the reflected service + endpoints.
        live = [r for r in cluster.ensemble.replicas
                if r.address != dead.address]
        assert wait_for(lambda: (
            live[0].store.snapshot_with_revision([""])
            == live[1].store.snapshot_with_revision([""])
        ), timeout=10.0)
        assert any("service" in k for k, _ in new.store.list(""))

        # KSR writes resume: a policy applied AFTER the kill reaches the
        # agents through the failed-over clients and is ENFORCED on
        # frames (deny-all on the backend).
        cluster.apply_policy({
            "metadata": {"name": "deny-all", "namespace": "default"},
            "spec": {"podSelector": {"matchLabels": WEB},
                     "policyTypes": ["Ingress"], "ingress": []},
        })
        assert wait_for(
            lambda: n2.policy_renderer.tables is not None
            and int(n2.policy_renderer.tables.rule_valid.sum()) > 0,
            timeout=15.0,
        ), "post-kill policy never reached the agents"
        cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 44000, 80)])
        cluster.run_datapaths()
        assert cluster.delivered_frames("node-2") == []  # denied
        # Enforced wherever the reflected rule lands first (the source
        # node drops at egress when its tables already carry it).
        assert sum(fn.runner.counters.dropped_denied
                   for fn in cluster.frame_nodes.values()) >= 1
    finally:
        cluster.stop()


# ---------------------------------------------------------------------------
# Datapath fault domains: shard supervision, steer, quarantine, atomic swaps
# (ISSUE 4 tentpole; driven through the fault-injection harness,
# vpp_tpu/testing/faults.py — no monkeypatching of runner internals).
# ---------------------------------------------------------------------------

# Egress policy of pod 10.1.1.30: deny TCP :9, allow the rest.  The
# SAME rule list drives the TPU tables and the mock-engine oracle
# (testing/aclengine.evaluate_table), so surviving shards' verdicts are
# checked against ground truth, not against themselves.
_CHAOS_RULES = [
    ContivRule(action=Action.DENY, protocol=ProtocolType.TCP, dst_port=9),
    ContivRule(action=Action.PERMIT),
]
_GUARDED_POD = "10.1.1.30"
_OPEN_POD = "10.1.1.40"


def _oracle_allows(dst_ip: str, sport: int, dport: int) -> bool:
    if dst_ip != _GUARDED_POD:
        return True  # no tables rendered for that pod -> allow
    return evaluate_table(
        _CHAOS_RULES, ipaddress.ip_address("10.1.1.2"),
        ipaddress.ip_address(dst_ip), ProtocolType.TCP, sport, dport,
    ) is Verdict.ALLOWED


def _chaos_route():
    return RouteConfig(
        pod_subnet_base=jnp.asarray(ip_to_u32("10.1.0.0"), dtype=jnp.uint32),
        pod_subnet_mask=jnp.asarray(0xFFFF0000, dtype=jnp.uint32),
        this_node_base=jnp.asarray(ip_to_u32("10.1.1.0"), dtype=jnp.uint32),
        this_node_mask=jnp.asarray(0xFFFFFF00, dtype=jnp.uint32),
        host_bits=jnp.asarray(8, dtype=jnp.int32),
    )


def _make_chaos_dp(n_shards, **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("max_vectors", 2)
    kw.setdefault("eject_errors", 3)
    kw.setdefault("probation_polls", 2)
    ios = [tuple(NativeRing() for _ in range(4)) for _ in range(n_shards)]
    dp = ShardedDataplane(
        acl=build_rule_tables(
            [_CHAOS_RULES], {ip_to_u32(_GUARDED_POD): (NO_TABLE, 0)}),
        nat=build_nat_tables([], snat_enabled=False,
                             pod_subnet="10.1.0.0/16"),
        route=_chaos_route(),
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                             local_node_id=1),
        shard_ios=ios,
        **kw,
    )
    return dp, ios


def _eject_shard(dp, ios, shard, max_polls=24):
    """Feed sacrificial frames (src ports >= 50000, excluded from every
    parity check) until the armed fault ejects the shard."""
    for i in range(max_polls):
        if dp.health_of[shard].state == "ejected":
            return
        ios[shard][0].send(
            [build_frame("10.1.9.9", _OPEN_POD, 6, 50000 + i, 80)])
        dp.poll()
    raise AssertionError(f"shard {shard} never ejected: "
                         f"{dp.health_of[shard]}")


def _delivered_tuples(ios, lo=40000, hi=50000):
    out = []
    for io_set in ios:
        out += [frame_tuple(f) for f in io_set[2].recv_batch(1 << 12)]
    return sorted(t for t in out if lo <= t[3] < hi)


def test_shard_ejection_mid_traffic_survivors_keep_oracle_parity():
    """ACCEPTANCE: dispatch-raise armed on shard 1 of 4 → the shard is
    ejected, its queued traffic steers onto the survivors, delivery
    stays verdict-faithful to the mock-engine oracle, `netctl health`
    reports the ejection, and the shard rejoins after probation."""
    dp, ios = _make_chaos_dp(4, reinit_backoff=60.0)  # no rejoin while armed
    try:
        dp.faults.arm(SITE_DISPATCH_RAISE, shard=1)
        _eject_shard(dp, ios, 1)
        h = dp.health()
        assert h["shards"][1]["state"] == "ejected"
        assert h["shards_serving"] == 3 and not h["all_down"]
        assert h["ejections"] >= 1

        # Mixed allowed/denied traffic over ALL shards — including the
        # ejected one, whose frames must steer to the survivors.
        flows = []
        for i in range(24):
            dst = _GUARDED_POD if i % 2 else _OPEN_POD
            dport = 9 if i % 3 == 0 else 80
            flows.append(("10.1.1.2", dst, 6, 40000 + i, dport))
        for i, (src, dst, proto, sport, dport) in enumerate(flows):
            ios[i % 4][0].send([build_frame(src, dst, proto, sport, dport)])
        dp.drain()

        expected = sorted(
            (src, dst, proto, sport, dport)
            for (src, dst, proto, sport, dport) in flows
            if _oracle_allows(dst, sport, dport)
        )
        assert _delivered_tuples(ios) == expected
        assert dp.health()["steered_frames"] >= 6  # shard 1's quarter

        # The ejection is visible over REST + `netctl health`.
        import io as _io

        from vpp_tpu.netctl.cli import main as netctl
        from vpp_tpu.rest.server import AgentRestServer

        rest = AgentRestServer(node_name="n1", datapath=dp)
        port = rest.start()
        try:
            out = _io.StringIO()
            assert netctl(["health", "--server", f"127.0.0.1:{port}"],
                          out=out) == 0
            text = out.getvalue()
            assert "ejected" in text and "3/4 serving" in text
        finally:
            rest.stop()

        # ---- recovery: disarm, expedite probation, rejoin ------------
        dp.faults.disarm()
        assert dp.recover(1) == 1
        probes = []
        for i in range(30):
            probe = ("10.1.1.2", _OPEN_POD, 6, 40100 + i, 80)
            probes.append(probe)
            ios[1][0].send([build_frame(*probe)])
            dp.poll()
            if dp.health_of[1].rejoins >= 1:
                break
        assert dp.health_of[1].rejoins >= 1
        assert dp.health_of[1].state in ("rejoined", "healthy")
        dp.drain()
        # Every probe frame (steered or shard-1-served) was delivered.
        assert _delivered_tuples(ios, 40100, 41000) == sorted(probes)
        h = dp.health()
        assert h["shards_serving"] == 4 and h["rejoins"] >= 1
    finally:
        dp.close()


def test_shard_hang_blows_dispatch_deadline_ejects_and_rejoins():
    """dispatch-hang: the shard's worker wedges mid-dispatch; the
    supervisor enforces the dispatch deadline, abandons the thread,
    ejects the shard — survivors keep serving — and the shard rejoins
    once the wedge clears (disarm releases it)."""
    dp, ios = _make_chaos_dp(2, dispatch_deadline=0.3, reinit_backoff=0.05)
    try:
        dp.faults.arm(SITE_DISPATCH_HANG, shard=0, seconds=30.0)
        ios[0][0].send([build_frame("10.1.9.9", _OPEN_POD, 6, 50000, 80)])
        ios[1][0].send([build_frame("10.1.1.2", _OPEN_POD, 6, 40000, 80)])
        dp.poll()
        assert dp.health_of[0].state == "ejected"
        assert "deadline" in dp.health_of[0].last_error
        # The survivor delivered its frame within the same poll.
        assert len(ios[1][2].recv_batch(16)) == 1

        # Traffic queued behind the WEDGED batch is parked, not lost:
        # the hung admit pins the rx arena, so steering skips the ring
        # until the wedge clears (the dispatch-raise test covers live
        # steering of a sanitised shard).
        parked = ("10.1.1.2", _OPEN_POD, 6, 40001, 80)
        ios[0][0].send([build_frame(*parked)])
        dp.drain()
        assert _delivered_tuples(ios) == []
        assert len(ios[0][0]) >= 1

        # While the thread is STILL wedged, probation must not touch
        # the runner: the ejection extends instead.
        dp.poll()
        assert dp.health_of[0].state == "ejected"

        # Release the wedge; the abandoned worker finishes (its resumed
        # poll may consume frames whose batches the rejoin sanitise
        # then discards — vswitch-crash loss semantics, transports
        # retransmit), the shard passes probation and rejoins, and
        # fresh traffic flows through it again.
        dp.faults.disarm()
        assert wait_for(lambda: 0 not in dp._stuck or dp._stuck[0].done(),
                        timeout=5.0)
        dp.recover(0)
        probes = []
        for i in range(30):
            probe = ("10.1.1.2", _OPEN_POD, 6, 40100 + i, 80)
            probes.append(probe)
            ios[0][0].send([build_frame(*probe)])
            dp.poll()
            if dp.health_of[0].rejoins >= 1:
                break
        assert dp.health_of[0].rejoins >= 1
        dp.drain()
        assert _delivered_tuples(ios, 40100, 41000) == sorted(probes)
    finally:
        dp.close()


def test_swap_fail_on_one_shard_rolls_back_every_shard():
    """ACCEPTANCE: a mid-swap failure (swap-fail armed on shard 2 of 3)
    never leaves shards serving different table generations — all roll
    back to last-good, the error is retriable, and the retry lands the
    swap on every shard."""
    dp, ios = _make_chaos_dp(3)
    try:
        old_nat = dp.shards[0].nat
        new_nat = build_nat_tables(
            [NatMapping("10.96.0.10", 80, 6,
                        backends=[("10.1.1.40", 8080, 1)])],
            snat_enabled=False, pod_subnet="10.1.0.0/16",
        )
        dp.faults.arm(SITE_SWAP_FAIL, shard=2, count=1)
        with pytest.raises(TableSwapError, match="shard 2"):
            dp.update_tables(nat=new_nat)
        # ALL shards agree on the last-good generation (identity).
        assert all(r.nat is old_nat for r in dp.shards)
        assert dp.health()["swap_rollbacks"] == 1
        assert dp.metrics()["datapath_swap_rollbacks_total"] == 1

        # Old tables really serve: the service VIP is NOT rewritten on
        # any shard (10.96/12 is off-subnet -> host route, un-DNATed).
        for s in range(3):
            ios[s][0].send(
                [build_frame("10.1.1.2", "10.96.0.10", 6, 40000 + s, 80)])
        dp.drain()
        for s in range(3):
            out = ios[s][3].recv_batch(16)
            assert len(out) == 1 and frame_tuple(out[0])[1] == "10.96.0.10"

        # The retry (count=1 expired) succeeds everywhere atomically.
        dp.update_tables(nat=new_nat)
        assert all(r.nat is not old_nat for r in dp.shards)
        for s in range(3):
            ios[s][0].send(
                [build_frame("10.1.1.2", "10.96.0.10", 6, 41000 + s, 80)])
        dp.drain()
        for s in range(3):
            out = ios[s][2].recv_batch(16)
            assert len(out) == 1 and frame_tuple(out[0])[1] == "10.1.1.40"
    finally:
        dp.close()


def test_all_shards_down_fail_closed_drops_and_counts():
    dp, ios = _make_chaos_dp(2, reinit_backoff=60.0,
                             on_all_down="fail-closed")
    try:
        dp.faults.arm(SITE_DISPATCH_RAISE)  # every shard
        _eject_shard(dp, ios, 0)
        _eject_shard(dp, ios, 1)
        assert dp.health()["all_down"]

        for s in range(2):
            ios[s][0].send([build_frame("10.1.1.2", _OPEN_POD, 6,
                                        40000 + 10 * s + i, 80)
                            for i in range(6)])
        dp.poll()
        assert _delivered_tuples(ios) == []           # fail-closed: nothing
        assert dp.health()["failclosed_drops"] == 12  # ...but counted
        assert dp.metrics()["datapath_failclosed_drops_total"] == 12
    finally:
        dp.close()


def test_all_shards_down_static_bypass_forwards_unfiltered():
    """The opt-in degraded mode: every shard down + on_all_down=bypass
    forwards ingress over the static host path — unfiltered (even the
    oracle-denied flow passes: bypass trades policy for reachability)."""
    dp, ios = _make_chaos_dp(2, reinit_backoff=60.0, on_all_down="bypass")
    try:
        dp.faults.arm(SITE_DISPATCH_RAISE)
        _eject_shard(dp, ios, 0)
        _eject_shard(dp, ios, 1)

        flows = [("10.1.1.2", _OPEN_POD, 6, 40000, 80),
                 ("10.1.1.2", _GUARDED_POD, 6, 40001, 9)]  # ACL would deny
        for s, flow in enumerate(flows):
            ios[s][0].send([build_frame(*flow)])
        dp.poll()
        assert _delivered_tuples(ios) == sorted(flows)
        assert dp.health()["bypass_forwards"] == 2
    finally:
        dp.close()
