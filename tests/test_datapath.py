"""Datapath runner e2e — real Ethernet frames through the TPU pipeline.

The round-2 "actually runs on packets" suite (VERDICT item 1): frames
in → decap → classify/NAT on the jit pipeline → native verdict apply →
VXLAN encap / local delivery, across a 2-node FrameCluster, with the
host slow path engaged for punted NAT flows.
"""

import struct

import numpy as np
import pytest

from vpp_tpu.ops.packets import ip_to_u32, u32_to_ip
from vpp_tpu.shim.hostshim import HostShim
from vpp_tpu.testing.cluster import wait_for
from vpp_tpu.testing.frames import build_frame, frame_tuple, verify_checksums
from vpp_tpu.testing.framecluster import FrameCluster, _outer_dst_ip

WEB_LABELS = {"app": "web"}


@pytest.fixture()
def cluster():
    c = FrameCluster()
    yield c
    c.stop()


def _vxlan_outer(frame):
    """(outer_src_ip, outer_dst_ip, udp_dst, vni) of an encapped frame."""
    ip = frame[14:]
    src = u32_to_ip(int.from_bytes(ip[12:16], "big"))
    dst = u32_to_ip(int.from_bytes(ip[16:20], "big"))
    udp = ip[20:]
    dport = struct.unpack("!H", udp[2:4])[0]
    vni = int.from_bytes(udp[8 + 4:8 + 7], "big")
    return src, dst, dport, vni


# --------------------------------------------------------------- single node


def test_local_pod_to_pod_frames(cluster):
    cluster.add_node("node-1")
    ip1 = cluster.deploy_pod("node-1", "client")
    ip2 = cluster.deploy_pod("node-1", "server")

    frames = [build_frame(ip1, ip2, 6, 40000 + i, 80) for i in range(8)]
    cluster.inject("node-1", frames)
    cluster.run_datapaths()

    out = cluster.delivered_frames("node-1")
    assert len(out) == 8
    for i, f in enumerate(out):
        assert frame_tuple(f) == (ip1, ip2, 6, 40000 + i, 80)
        assert verify_checksums(f)


def test_policy_denied_frames_dropped(cluster):
    cluster.add_node("node-1")
    ip1 = cluster.deploy_pod("node-1", "web-1", labels=WEB_LABELS)
    ip2 = cluster.deploy_pod("node-1", "web-2", labels=WEB_LABELS)
    cluster.apply_policy({
        "metadata": {"name": "deny-all", "namespace": "default"},
        "spec": {"podSelector": {"matchLabels": WEB_LABELS},
                 "policyTypes": ["Ingress"], "ingress": []},
    })
    assert wait_for(
        lambda: cluster.nodes["node-1"].policy_renderer.tables is not None
        and int(cluster.nodes["node-1"].policy_renderer.tables.rule_valid.sum()) > 0
    )
    cluster.inject("node-1", [build_frame(ip1, ip2, 6, 40000, 80)])
    cluster.run_datapaths()
    assert cluster.delivered_frames("node-1") == []
    counters = cluster.frame_nodes["node-1"].runner.counters
    assert counters.dropped_denied == 1


def test_service_dnat_frames_and_reply(cluster):
    n1 = cluster.add_node("node-1")
    client_ip = cluster.deploy_pod("node-1", "client")
    backend_ip = cluster.deploy_pod("node-1", "web-1", labels=WEB_LABELS)

    cluster.apply_service({
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"clusterIP": "10.96.0.10", "selector": WEB_LABELS,
                 "ports": [{"name": "http", "protocol": "TCP", "port": 80,
                            "targetPort": 8080}]},
    })
    cluster.apply_endpoints({
        "metadata": {"name": "web", "namespace": "default"},
        "subsets": [{
            "addresses": [{"ip": backend_ip, "nodeName": "node-1",
                           "targetRef": {"kind": "Pod", "name": "web-1",
                                          "namespace": "default"}}],
            "ports": [{"name": "http", "port": 8080, "protocol": "TCP"}],
        }],
    })
    assert wait_for(lambda: len(n1.nat_renderer.mappings()) > 0)

    cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 40000, 80)])
    cluster.run_datapaths()
    out = cluster.delivered_frames("node-1")
    assert len(out) == 1
    # DNAT rewrote the VIP to the backend, checksums incrementally fixed.
    assert frame_tuple(out[0]) == (client_ip, backend_ip, 6, 40000, 8080)
    assert verify_checksums(out[0])

    # Reply through the same runner's session table restores the VIP.
    cluster.inject("node-1", [build_frame(backend_ip, client_ip, 6, 8080, 40000)])
    cluster.run_datapaths()
    rep = cluster.delivered_frames("node-1")
    assert len(rep) == 1
    assert frame_tuple(rep[0]) == ("10.96.0.10", client_ip, 6, 80, 40000)
    assert verify_checksums(rep[0])


def test_snat_egress_to_host(cluster):
    cluster.add_node("node-1")
    ip1 = cluster.deploy_pod("node-1", "client")
    cluster.inject("node-1", [build_frame(ip1, "93.184.216.34", 6, 40000, 443)])
    cluster.run_datapaths()
    out = cluster.host_frames("node-1")
    assert len(out) == 1
    src, dst, proto, sport, dport = frame_tuple(out[0])
    assert src == "192.168.16.1" and dst == "93.184.216.34"
    assert 32768 <= sport < 65536 and dport == 443
    assert verify_checksums(out[0])


# ----------------------------------------------------------------- two nodes


def test_cross_node_vxlan_encap_decap_delivery(cluster):
    cluster.add_node("node-1")
    cluster.add_node("node-2")
    ip1 = cluster.deploy_pod("node-1", "client")
    ip2 = cluster.deploy_pod("node-2", "server")

    frames = [build_frame(ip1, ip2, 6, 41000 + i, 80) for i in range(4)]
    cluster.inject("node-1", frames)

    # Drive only node-1 first so we can inspect the wire format.
    fn1 = cluster.frame_nodes["node-1"]
    fn1.sync_tables()
    fn1.drain()
    assert fn1.runner.counters.tx_remote == 4

    # Frames crossed the wire into node-2's rx ring, VXLAN-encapped.
    fn2 = cluster.frame_nodes["node-2"]
    staged = fn2.rx.recv_batch(16)
    assert len(staged) == 4
    for f in staged:
        o_src, o_dst, udp_dst, vni = _vxlan_outer(f)
        assert (o_src, o_dst) == ("192.168.16.1", "192.168.16.2")
        assert udp_dst == 4789 and vni == 10
    fn2.rx.send(staged)  # put them back

    cluster.run_datapaths()
    out = cluster.delivered_frames("node-2")
    assert len(out) == 4
    for i, f in enumerate(out):
        assert frame_tuple(f) == (ip1, ip2, 6, 41000 + i, 80)
        assert verify_checksums(f)
    assert fn2.runner.counters.rx_decapped == 4


def test_cross_node_policy_enforced_at_destination(cluster):
    cluster.add_node("node-1")
    cluster.add_node("node-2")
    ip_db = cluster.deploy_pod("node-1", "db-1", labels={"app": "db"})
    ip_web = cluster.deploy_pod("node-2", "web-1", labels=WEB_LABELS)

    cluster.apply_policy({
        "metadata": {"name": "web-only", "namespace": "default"},
        "spec": {"podSelector": {"matchLabels": WEB_LABELS},
                 "policyTypes": ["Ingress"],
                 "ingress": [{"from": [{"podSelector": {"matchLabels": WEB_LABELS}}]}]},
    })
    assert wait_for(
        lambda: all(
            n.policy_renderer.tables is not None
            and int(n.policy_renderer.tables.rule_valid.sum()) > 0
            for n in cluster.nodes.values()
        )
    )
    cluster.inject("node-1", [build_frame(ip_db, ip_web, 6, 40000, 80)])
    cluster.run_datapaths()
    # The destination node's ingress table denies db -> web.
    assert cluster.delivered_frames("node-2") == []


# ------------------------------------------------------- slow-path on frames


def test_snat_collision_fixed_up_on_frames(cluster):
    from vpp_tpu.testing.natengine import flow_hash_py

    cluster.add_node("node-1")
    # Deploy enough pods to find two whose SNAT hash ports collide for
    # the same remote endpoint.
    ips = [cluster.deploy_pod("node-1", f"p{i}") for i in range(8)]
    dst = ip_to_u32("93.184.216.34")
    seen = {}
    pair = None
    for ip in ips:
        if pair:
            break
        for sport in range(1025, 22000):
            h = flow_hash_py(ip_to_u32(ip), dst, 6, sport, 443)
            port = (h % 32768) + 32768
            if port in seen and seen[port][0] != ip:
                pair = (seen[port], (ip, sport), port)
                break
            seen.setdefault(port, (ip, sport))
    assert pair, "no collision pair found in search budget"
    (ip_a, p_a), (ip_b, p_b), snat_port = pair

    cluster.inject("node-1", [
        build_frame(ip_a, "93.184.216.34", 6, p_a, 443),
        build_frame(ip_b, "93.184.216.34", 6, p_b, 443),
    ])
    cluster.run_datapaths()
    out = cluster.host_frames("node-1")
    assert len(out) == 2
    ports = sorted(frame_tuple(f)[3] for f in out)
    # The colliding flow was punted and re-ported by the host slow path:
    # the two frames leave with DISTINCT source ports, checksums valid.
    assert ports[0] != ports[1]
    assert snat_port in ports
    for f in out:
        assert verify_checksums(f)
    runner = cluster.frame_nodes["node-1"].runner
    assert runner.counters.punts == 1
    assert runner.slow.counters.snat_reallocs == 1

    # Replies to BOTH external ports come back to the right pods.
    by_port = {frame_tuple(f)[3]: frame_tuple(f) for f in out}
    reply_frames = [
        build_frame("93.184.216.34", "192.168.16.1", 6, 443, port)
        for port in by_port
    ]
    cluster.inject("node-1", reply_frames)
    cluster.run_datapaths()
    restored = cluster.delivered_frames("node-1")
    assert len(restored) == 2
    got = {frame_tuple(f)[1]: frame_tuple(f) for f in restored}
    assert set(got) == {ip_a, ip_b}
    for f in restored:
        assert verify_checksums(f)
    assert runner.counters.host_restores == 1


# -------------------------------------------------------------- shim units


def test_vxlan_encap_decap_roundtrip_unit():
    shim = HostShim()
    inner = build_frame("10.1.1.2", "10.1.2.3", 6, 1234, 80)
    fb = shim.parse([inner], pad_to=None)
    fwd = np.array([1], dtype=np.uint8)
    remote = np.array([1], dtype=np.uint8)
    node_ids = np.array([2], dtype=np.int32)
    remote_ips = np.zeros(8, dtype=np.uint32)
    remote_ips[2] = ip_to_u32("192.168.16.2")
    buf, off, lens, rows, unroutable = shim.vxlan_encap(
        fb, fwd, remote, node_ids, remote_ips,
        local_ip=ip_to_u32("192.168.16.1"), local_node_id=1, vni=10,
    )
    assert unroutable == 0 and len(rows) == 1
    encapped = buf[int(off[0]):int(off[0]) + int(lens[0])].tobytes()
    assert len(encapped) == len(inner) + 50
    assert verify_checksums(encapped)  # outer IP csum; UDP csum 0 is legal
    assert _outer_dst_ip(encapped) == ip_to_u32("192.168.16.2")

    inner_out, vnis = shim.vxlan_decap([encapped, inner])
    assert vnis == [10, -1]
    assert inner_out[0] == inner       # bit-exact round trip
    assert inner_out[1] == inner       # native passthrough


def test_vxlan_encap_unknown_node_counted():
    shim = HostShim()
    inner = build_frame("10.1.1.2", "10.1.9.3", 6, 1234, 80)
    fb = shim.parse([inner], pad_to=None)
    buf, off, lens, rows, unroutable = shim.vxlan_encap(
        fb, np.array([1], dtype=np.uint8), np.array([1], dtype=np.uint8),
        np.array([9], dtype=np.int32), np.zeros(4, dtype=np.uint32),
        local_ip=ip_to_u32("192.168.16.1"), local_node_id=1,
    )
    assert len(rows) == 0 and unroutable == 1


def test_foreign_vni_dropped(cluster):
    cluster.add_node("node-1")
    ip1 = cluster.deploy_pod("node-1", "client")
    ip2 = cluster.deploy_pod("node-1", "server")
    shim = HostShim()
    inner = build_frame(ip1, ip2, 6, 40000, 80)
    fb = shim.parse([inner], pad_to=None)
    remote_ips = np.zeros(4, dtype=np.uint32)
    remote_ips[1] = ip_to_u32("192.168.16.1")
    buf, off, lens, rows, _ = shim.vxlan_encap(
        fb, np.array([1], dtype=np.uint8), np.array([1], dtype=np.uint8),
        np.array([1], dtype=np.int32), remote_ips,
        local_ip=ip_to_u32("192.168.16.9"), local_node_id=9, vni=99,
    )
    foreign = buf[int(off[0]):int(off[0]) + int(lens[0])].tobytes()
    cluster.inject("node-1", [foreign])
    cluster.run_datapaths()
    # VNI 99 is not this overlay's segment: dropped, never classified.
    assert cluster.delivered_frames("node-1") == []
    runner = cluster.frame_nodes["node-1"].runner
    assert runner.counters.dropped_foreign_vni == 1
    assert runner.counters.rx_decapped == 0


def test_non_ipv4_counted_unparseable_not_denied(cluster):
    cluster.add_node("node-1")
    arp = b"\xff" * 6 + b"\x02\x00\x00\x00\x00\x01" + b"\x08\x06" + b"\x00" * 28
    cluster.inject("node-1", [arp])
    cluster.run_datapaths()
    runner = cluster.frame_nodes["node-1"].runner
    assert runner.counters.dropped_unparseable == 1
    assert runner.counters.dropped_denied == 0


def test_multi_vector_scan_dispatch(cluster):
    """max_vectors>1 coalesces queued vectors into one scan dispatch;
    sessions thread between vectors ON DEVICE, so a DNAT forward flow in
    an early vector serves its reply arriving in a later vector of the
    SAME dispatch."""
    n1 = cluster.add_node("node-1")
    client_ip = cluster.deploy_pod("node-1", "client")
    backend_ip = cluster.deploy_pod("node-1", "web-1", labels=WEB_LABELS)
    cluster.apply_service({
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"clusterIP": "10.96.0.10", "selector": WEB_LABELS,
                 "ports": [{"name": "http", "protocol": "TCP", "port": 80,
                            "targetPort": 8080}]},
    })
    cluster.apply_endpoints({
        "metadata": {"name": "web", "namespace": "default"},
        "subsets": [{
            "addresses": [{"ip": backend_ip, "nodeName": "node-1",
                           "targetRef": {"kind": "Pod", "name": "web-1",
                                          "namespace": "default"}}],
            "ports": [{"name": "http", "port": 8080, "protocol": "TCP"}],
        }],
    })
    assert wait_for(lambda: len(n1.nat_renderer.mappings()) > 0)

    fn = cluster.frame_nodes["node-1"]
    fn.runner.batch_size = 8
    fn.runner.max_vectors = 4
    fn.runner.dispatch = "scan"  # pin: the default is flat-safe now

    # 8 forward service flows fill vector 0; their replies land in
    # vectors 1-2 of the same 4-vector dispatch (session visibility
    # requires the on-device scan threading, not a host round-trip).
    frames = [build_frame(client_ip, "10.96.0.10", 6, 40000 + i, 80)
              for i in range(8)]
    frames += [build_frame(backend_ip, client_ip, 6, 8080, 40000 + i)
               for i in range(8)]
    cluster.inject("node-1", frames)
    cluster.run_datapaths()

    out = cluster.delivered_frames("node-1")
    assert len(out) == 16
    assert fn.runner.counters.batches == 1  # ONE coalesced dispatch
    fwd = [frame_tuple(f) for f in out[:8]]
    rep = [frame_tuple(f) for f in out[8:]]
    for i in range(8):
        assert fwd[i] == (client_ip, backend_ip, 6, 40000 + i, 8080)
        assert rep[i] == ("10.96.0.10", client_ip, 6, 80, 40000 + i)
    for f in out:
        assert verify_checksums(f)


def test_cross_node_service_dnat_and_reply_over_vxlan(cluster):
    """Full cross-node service path on frames: client on node-1, backend
    on node-2.  Forward: DNAT on the client's node, VXLAN to node-2,
    delivery to the backend.  Reply: backend frame on node-2 routes back
    over the overlay to node-1, whose session table restores the VIP."""
    n1 = cluster.add_node("node-1")
    cluster.add_node("node-2")
    client_ip = cluster.deploy_pod("node-1", "client")
    backend_ip = cluster.deploy_pod("node-2", "web-1", labels=WEB_LABELS)

    cluster.apply_service({
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"clusterIP": "10.96.0.10", "selector": WEB_LABELS,
                 "ports": [{"name": "http", "protocol": "TCP", "port": 80,
                            "targetPort": 8080}]},
    })
    cluster.apply_endpoints({
        "metadata": {"name": "web", "namespace": "default"},
        "subsets": [{
            "addresses": [{"ip": backend_ip, "nodeName": "node-2",
                           "targetRef": {"kind": "Pod", "name": "web-1",
                                         "namespace": "default"}}],
            "ports": [{"name": "http", "port": 8080, "protocol": "TCP"}],
        }],
    })
    assert wait_for(lambda: len(n1.nat_renderer.mappings()) > 0)

    # Forward: client -> VIP, DNATed on node-1, encapped to node-2.
    cluster.inject("node-1", [build_frame(client_ip, "10.96.0.10", 6, 43000, 80)])
    cluster.run_datapaths()
    out = cluster.delivered_frames("node-2")
    assert len(out) == 1
    assert frame_tuple(out[0]) == (client_ip, backend_ip, 6, 43000, 8080)
    assert verify_checksums(out[0])
    assert cluster.frame_nodes["node-1"].runner.counters.tx_remote == 1

    # Reply: backend -> client rides the overlay back to node-1, where
    # the forward session restores the VIP as the source.
    cluster.inject("node-2", [build_frame(backend_ip, client_ip, 6, 8080, 43000)])
    cluster.run_datapaths()
    rep = cluster.delivered_frames("node-1")
    assert len(rep) == 1
    assert frame_tuple(rep[0]) == ("10.96.0.10", client_ip, 6, 80, 43000)
    assert verify_checksums(rep[0])
    assert cluster.frame_nodes["node-2"].runner.counters.tx_remote == 1


def test_native_ring_roundtrip_and_wraparound():
    """NativeRing: bytes-compat FIFO order, drop counting when full,
    and arena wraparound integrity under mixed push/pop."""
    from vpp_tpu.datapath.io import NativeRing

    ring = NativeRing(arena_bytes=1 << 16, max_frames=256)
    frames = [build_frame("10.1.1.2", "10.1.2.3", 6, 1000 + i, 80)
              for i in range(10)]
    ring.send(frames)
    assert len(ring) == 10
    assert ring.recv_batch(100) == frames
    # capacity: tiny ring drops excess and counts it
    tiny = NativeRing(arena_bytes=256, max_frames=8)
    big = [b"\xab" * 100 for _ in range(5)]
    tiny.send(big)
    assert len(tiny) == 2 and tiny.dropped == 3
    # wraparound: cycle far past the arena size, order preserved
    ring2 = NativeRing(arena_bytes=2048, max_frames=16)
    expect = []
    got = []
    for i in range(300):
        f = bytes([i % 251]) * (60 + i % 90)
        before = len(ring2)
        ring2.send([f])
        if len(ring2) == before + 1:
            expect.append(f)
        got += ring2.recv_batch(2)
    got += ring2.recv_batch(100)
    assert got == expect


def test_native_python_engine_counter_parity():
    """VERDICT r2 item 1: the C++ loop must be behaviorally identical
    to the Python loop.  Same mixed traffic (local / remote / host /
    denied-unparseable / foreign-VNI / VXLAN-ingress) through both
    engines -> identical counters and identical output frames."""
    from vpp_tpu.datapath import DataplaneRunner, InMemoryRing, NativeRing, VxlanOverlay
    from vpp_tpu.ops.classify import build_rule_tables
    from vpp_tpu.ops.nat import build_nat_tables
    from vpp_tpu.ops.pipeline import RouteConfig
    from vpp_tpu.shim.hostshim import HostShim

    import jax.numpy as jnp

    # Stand-alone tables: pod subnet 10.1.0.0/16, this node 10.1.1.0/24.
    acl = build_rule_tables([], {})
    nat = build_nat_tables([], snat_ip="192.168.16.1", snat_enabled=True)
    route = RouteConfig(
        pod_subnet_base=jnp.asarray(ip_to_u32("10.1.0.0"), dtype=jnp.uint32),
        pod_subnet_mask=jnp.asarray(0xFFFF0000, dtype=jnp.uint32),
        this_node_base=jnp.asarray(ip_to_u32("10.1.1.0"), dtype=jnp.uint32),
        this_node_mask=jnp.asarray(0xFFFFFF00, dtype=jnp.uint32),
        host_bits=jnp.asarray(8, dtype=jnp.int32),
    )
    shim = HostShim()

    def mixed_traffic():
        frames = []
        # local pod-to-pod
        frames += [build_frame("10.1.1.2", "10.1.1.3", 6, 40000 + i, 80)
                   for i in range(5)]
        # remote (node 2) and unroutable-remote (node 9, no VTEP)
        frames += [build_frame("10.1.1.2", "10.1.2.9", 6, 41000 + i, 80)
                   for i in range(4)]
        frames += [build_frame("10.1.1.2", "10.1.9.9", 17, 42000, 53)]
        # egress to the world (SNAT -> host)
        frames += [build_frame("10.1.1.4", "93.184.216.34", 6, 43000 + i, 443)
                   for i in range(3)]
        # non-IPv4 (ARP) -> unparseable
        frames += [b"\xff" * 6 + b"\x02\x00\x00\x00\x00\x01" + b"\x08\x06"
                   + b"\x00" * 40]
        # VXLAN ingress for our VNI + a foreign VNI
        inner = build_frame("10.1.2.7", "10.1.1.3", 6, 44000, 8080)
        fb = shim.parse([inner], pad_to=None)
        remote_ips = np.zeros(4, dtype=np.uint32)
        remote_ips[1] = ip_to_u32("192.168.16.1")
        for vni in (10, 99):
            buf, off, lens, rows, _ = shim.vxlan_encap(
                fb, np.array([1], np.uint8), np.array([1], np.uint8),
                np.array([1], np.int32), remote_ips,
                local_ip=ip_to_u32("192.168.16.2"), local_node_id=2, vni=vni,
            )
            frames += [buf[int(off[0]):int(off[0]) + int(lens[0])].tobytes()]
        return frames

    results = {}
    for engine in ("python", "native"):
        if engine == "native":
            rings = [NativeRing() for _ in range(4)]
        else:
            rings = [InMemoryRing() for _ in range(4)]
        rx, tx, local, host = rings
        runner = DataplaneRunner(
            acl=acl, nat=nat, route=route,
            overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                                 local_node_id=1),
            source=rx, tx=tx, local=local, host=host,
            batch_size=8, max_vectors=2, shim=shim,
        )
        assert runner.engine == engine
        runner.overlay.set_remote(2, ip_to_u32("192.168.16.2"))
        rx.send(mixed_traffic())
        runner.drain()
        results[engine] = {
            "counters": dict(runner.counters.as_dict()),
            "tx": tx.recv_batch(1 << 16),
            "local": sorted(local.recv_batch(1 << 16)),
            "host": host.recv_batch(1 << 16),
        }
    pc, nc = results["python"]["counters"], results["native"]["counters"]
    # The saved-copy byte counter records a python-admit-only
    # optimisation (the native admit is zero-copy by construction, so
    # there is no second copy to save there).
    for c in (pc, nc):
        c.pop("datapath_admit_copy_saved_bytes_total", None)
    assert pc == nc, f"counter divergence: {pc} vs {nc}"
    assert results["python"]["local"] == results["native"]["local"]
    assert results["python"]["host"] == results["native"]["host"]
    # Encapped frames: same inner payloads and outer VTEPs (the outer
    # UDP source port is flow-derived and deterministic -> bit equal).
    assert results["python"]["tx"] == results["native"]["tx"]


def _permissive_state():
    """Trivially-permissive tables: no ACL, no NAT, SNAT off — the
    host-bypass eligibility conditions."""
    from vpp_tpu.ops.classify import build_rule_tables
    from vpp_tpu.ops.nat import build_nat_tables
    from vpp_tpu.ops.pipeline import RouteConfig

    import jax.numpy as jnp

    acl = build_rule_tables([], {})
    nat = build_nat_tables([], snat_enabled=False)
    route = RouteConfig(
        pod_subnet_base=jnp.asarray(ip_to_u32("10.1.0.0"), dtype=jnp.uint32),
        pod_subnet_mask=jnp.asarray(0xFFFF0000, dtype=jnp.uint32),
        this_node_base=jnp.asarray(ip_to_u32("10.1.1.0"), dtype=jnp.uint32),
        this_node_mask=jnp.asarray(0xFFFFFF00, dtype=jnp.uint32),
        host_bits=jnp.asarray(8, dtype=jnp.int32),
    )
    return acl, nat, route


def _bypass_traffic(shim):
    """local / remote / egress / unparseable / VXLAN-ingress (ours +
    foreign) — every admit/harvest path the bypass must mirror."""
    frames = []
    frames += [build_frame("10.1.1.2", "10.1.1.3", 6, 40000 + i, 80)
               for i in range(5)]
    frames += [build_frame("10.1.1.2", "10.1.2.9", 6, 41000 + i, 80)
               for i in range(4)]
    frames += [build_frame("10.1.1.2", "10.1.9.9", 17, 42000, 53)]
    frames += [build_frame("10.1.1.4", "93.184.216.34", 6, 43000 + i, 443)
               for i in range(3)]
    frames += [b"\xff" * 6 + b"\x02\x00\x00\x00\x00\x01" + b"\x08\x06"
               + b"\x00" * 40]
    inner = build_frame("10.1.2.7", "10.1.1.3", 6, 44000, 8080)
    fb = shim.parse([inner], pad_to=None)
    remote_ips = np.zeros(4, dtype=np.uint32)
    remote_ips[1] = ip_to_u32("192.168.16.1")
    for vni in (10, 99):
        buf, off, lens, rows, _ = shim.vxlan_encap(
            fb, np.array([1], np.uint8), np.array([1], np.uint8),
            np.array([1], np.int32), remote_ips,
            local_ip=ip_to_u32("192.168.16.2"), local_node_id=2, vni=vni,
        )
        frames += [buf[int(off[0]):int(off[0]) + int(lens[0])].tobytes()]
    return frames


def test_host_bypass_matches_full_pipeline():
    """With trivially-permissive tables the native runner takes the
    HOST BYPASS (fused admit→route→harvest, no device dispatch); its
    outputs and counters must be identical to the full-pipeline python
    engine on the same traffic."""
    from vpp_tpu.datapath import DataplaneRunner, InMemoryRing, NativeRing, VxlanOverlay
    from vpp_tpu.shim.hostshim import HostShim

    acl, nat, route = _permissive_state()
    shim = HostShim()
    results = {}
    for engine in ("python", "native"):
        if engine == "native":
            rings = [NativeRing() for _ in range(4)]
        else:
            rings = [InMemoryRing() for _ in range(4)]
        rx, tx, local, host = rings
        runner = DataplaneRunner(
            acl=acl, nat=nat, route=route,
            overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                                 local_node_id=1),
            source=rx, tx=tx, local=local, host=host,
            batch_size=8, max_vectors=2, shim=shim,
        )
        assert runner.engine == engine
        runner.overlay.set_remote(2, ip_to_u32("192.168.16.2"))
        if engine == "native":
            assert runner._bypass_tables, "bypass must be eligible"
        rx.send(_bypass_traffic(shim))
        runner.drain()
        results[engine] = {
            "counters": dict(runner.counters.as_dict()),
            "tx": tx.recv_batch(1 << 16),
            "local": sorted(local.recv_batch(1 << 16)),
            "host": host.recv_batch(1 << 16),
        }
    nc = results["native"]["counters"]
    assert nc["datapath_bypass_batches_total"] > 0
    assert nc["datapath_batches_total"] == 0  # never touched the device
    pc = results["python"]["counters"]
    for key, value in pc.items():
        if key in ("datapath_batches_total", "datapath_bypass_batches_total",
                   "datapath_admit_copy_saved_bytes_total",
                   "datapath_harvest_copy_saved_bytes_total"):
            # Batch-shape counters differ by construction; the saved-
            # copy bytes record path-local optimisations (python-admit
            # single-pass packing; the packed-harvest zero-copy fast
            # path — the native BYPASS skips the device harvest
            # entirely, so it has no packed copy to save).
            continue
        assert nc[key] == value, f"{key}: {nc[key]} != {value}"
    assert results["python"]["local"] == results["native"]["local"]
    assert results["python"]["host"] == results["native"]["host"]
    assert results["python"]["tx"] == results["native"]["tx"]


def test_host_bypass_gating_and_transitions():
    """The bypass must NOT engage with rules / NAT / SNAT / an enabled
    tracer, and a table swap to a service config must re-enter the
    dispatch path (and back)."""
    from vpp_tpu.datapath import DataplaneRunner, NativeRing, VxlanOverlay
    from vpp_tpu.ops.classify import build_rule_tables
    from vpp_tpu.ops.nat import NatMapping, build_nat_tables

    acl, nat, route = _permissive_state()
    rx, tx, local, host = (NativeRing() for _ in range(4))
    runner = DataplaneRunner(
        acl=acl, nat=nat, route=route,
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                             local_node_id=1),
        source=rx, tx=tx, local=local, host=host,
        batch_size=8, max_vectors=2,
    )
    assert runner._bypass_tables

    # SNAT on -> ineligible.
    runner.update_tables(nat=build_nat_tables([], snat_ip="192.168.16.1",
                                              snat_enabled=True))
    assert not runner._bypass_tables
    # Back to permissive -> eligible again.
    runner.update_tables(nat=build_nat_tables([], snat_enabled=False))
    assert runner._bypass_tables
    # A service mapping -> ineligible, and the dispatch path DNATs.
    svc = NatMapping("10.96.0.10", 80, 6, backends=[("10.1.1.3", 8080, 1)])
    runner.update_tables(nat=build_nat_tables([svc], snat_enabled=False))
    assert not runner._bypass_tables
    rx.send([build_frame("10.1.1.2", "10.96.0.10", 6, 40000, 80)])
    runner.drain()
    assert runner.counters.batches > 0
    out = local.recv_batch(16)
    assert len(out) == 1
    assert frame_tuple(out[0]) == ("10.1.1.2", "10.1.1.3", 6, 40000, 8080)

    # Sessions now live -> even back-to-permissive stays ineligible
    # until they decay (replies of existing flows must keep restoring).
    runner.update_tables(nat=build_nat_tables([], snat_enabled=False))
    assert not runner._bypass_tables

    # An enabled tracer suppresses the bypass dynamically.
    rx2, tx2, local2, host2 = (NativeRing() for _ in range(4))
    acl2, nat2, route2 = _permissive_state()
    r2 = DataplaneRunner(
        acl=acl2, nat=nat2, route=route2,
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                             local_node_id=1),
        source=rx2, tx=tx2, local=local2, host=host2,
        batch_size=8, max_vectors=2,
    )
    r2.tracer.enable()
    rx2.send([build_frame("10.1.1.2", "10.1.1.3", 6, 40000, 80)])
    r2.drain()
    assert r2.counters.bypass_batches == 0
    assert r2.counters.batches > 0  # went through dispatch for tracing
    assert len(r2.tracer.dump()) == 1
    r2.tracer.disable()
    rx2.send([build_frame("10.1.1.2", "10.1.1.3", 6, 41000, 80)])
    r2.drain()
    assert r2.counters.bypass_batches > 0


def test_orphaned_affinity_pins_drain_after_service_deletion():
    """Deleting the LAST ClientIP-affinity service must not leak its
    pins: sweep_sessions deliberately skips affinity rows, so the
    affinity sweep has to keep running on no-affinity tables until the
    orphaned (now unmapped) pins have drained."""
    from vpp_tpu.datapath import DataplaneRunner, InMemoryRing, VxlanOverlay
    from vpp_tpu.ops.classify import build_rule_tables
    from vpp_tpu.ops.nat import NatMapping, build_nat_tables
    from vpp_tpu.ops.pipeline import RouteConfig

    import jax.numpy as jnp

    acl = build_rule_tables([], {})
    aff = NatMapping("10.96.0.10", 80, 6,
                     backends=[("10.1.1.3", 8080, 1)],
                     session_affinity_timeout=3600)
    kw = dict(snat_ip="192.168.16.1", snat_enabled=True,
              pod_subnet="10.1.0.0/16")
    route = RouteConfig(
        pod_subnet_base=jnp.asarray(ip_to_u32("10.1.0.0"), dtype=jnp.uint32),
        pod_subnet_mask=jnp.asarray(0xFFFF0000, dtype=jnp.uint32),
        this_node_base=jnp.asarray(ip_to_u32("10.1.1.0"), dtype=jnp.uint32),
        this_node_mask=jnp.asarray(0xFFFFFF00, dtype=jnp.uint32),
        host_bits=jnp.asarray(8, dtype=jnp.int32),
    )
    rx, tx = InMemoryRing(), InMemoryRing()
    runner = DataplaneRunner(
        acl=acl, nat=build_nat_tables([aff], **kw), route=route,
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                             local_node_id=1),
        source=rx, tx=tx, batch_size=8, max_vectors=1, sweep_interval=1,
    )
    rx.send([build_frame("10.1.1.2", "10.96.0.10", 6, 40000, 80)])
    runner.drain()
    assert runner.metrics()["datapath_affinity_active"] == 1

    # The service is deleted: tables rebuild with has_affinity=False.
    runner.update_tables(nat=build_nat_tables([], **kw))
    for sport in (41000, 42000):  # unrelated traffic drives sweeps
        rx.send([build_frame("10.1.1.2", "10.1.1.3", 6, sport, 80)])
        runner.drain()
    assert runner.metrics()["datapath_affinity_active"] == 0
    assert not runner._state.aff_pinned  # sweep stood down


def test_host_bypass_waits_for_orphan_pins_then_engages():
    """Code-review r5: trivially-permissive tables with residual
    affinity pins (or sessions) must NOT engage the host bypass —
    bypassing would park the drain sweep forever.  Once the sweeps
    drain them, the stand-down re-evaluates and the bypass engages
    without another table update."""
    from vpp_tpu.datapath import DataplaneRunner, NativeRing, VxlanOverlay
    from vpp_tpu.ops.classify import build_rule_tables
    from vpp_tpu.ops.nat import NatMapping, build_nat_tables

    _, _, route = _permissive_state()
    acl = build_rule_tables([], {})
    aff = NatMapping("10.96.0.10", 80, 6,
                     backends=[("10.1.1.3", 8080, 1)],
                     session_affinity_timeout=3600)
    rx, tx, local, host = (NativeRing() for _ in range(4))
    runner = DataplaneRunner(
        acl=acl, nat=build_nat_tables([aff], snat_enabled=False,
                                      pod_subnet="10.1.0.0/16"),
        route=route,
        overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                             local_node_id=1),
        source=rx, tx=tx, local=local, host=host,
        batch_size=8, max_vectors=1, sweep_interval=1, sweep_max_age=1,
    )
    rx.send([build_frame("10.1.1.2", "10.96.0.10", 6, 40000, 80)])
    runner.drain()
    assert runner.metrics()["datapath_affinity_active"] == 1

    # All services deleted -> tables are trivially permissive, but the
    # orphan pin (and the session until it ages out) must block bypass.
    runner.update_tables(nat=build_nat_tables([], snat_enabled=False,
                                              pod_subnet="10.1.0.0/16"))
    assert not runner._bypass_tables
    # Traffic drives sweeps: session expires (max_age=1), orphan pin
    # drops (unmapped), and the sweep's stand-down re-evaluates bypass.
    for sport in (41000, 42000, 43000):
        rx.send([build_frame("10.1.1.2", "10.1.1.3", 6, sport, 80)])
        runner.drain()
    assert runner.metrics()["datapath_affinity_active"] == 0
    assert runner._bypass_tables  # re-engaged without a table update
    before = runner.counters.bypass_batches
    rx.send([build_frame("10.1.1.2", "10.1.1.3", 6, 44000, 80)])
    runner.drain()
    assert runner.counters.bypass_batches > before


def test_afpacket_loopback_roundtrip():
    """Real AF_PACKET sockets (the DPDK-binding stand-in) on loopback:
    frames sent through one socket arrive on another bound to the same
    interface."""
    from vpp_tpu.datapath.io import AfPacketIO

    try:
        tx = AfPacketIO("lo")
        rx = AfPacketIO("lo", blocking_ms=200)
    except (PermissionError, OSError) as e:
        pytest.skip(f"AF_PACKET unavailable: {e}")
    try:
        rx.recv_batch(1 << 12)  # drain anything already on lo
        ip1, ip2 = "10.1.1.2", "10.1.1.3"
        sent = [build_frame(ip1, ip2, 6, 45000 + i, 80) for i in range(3)]
        tx.send(sent)
        def ours(f):
            if len(f) < 34 or f[12:14] != b"\x08\x00":
                return False
            try:
                t = frame_tuple(f)
            except Exception:
                return False  # truncated/foreign frame
            return t[0] == ip1 and t[1] == ip2

        got = []
        for _ in range(20):
            got += [f for f in rx.recv_batch(16) if ours(f)]
            if len(got) >= 6:  # lo duplicates: one copy per direction
                break
        tuples = {frame_tuple(f) for f in got}
        assert tuples == {(ip1, ip2, 6, 45000 + i, 80) for i in range(3)}
    finally:
        tx.close()
        rx.close()


def test_flat_safe_dispatch_restores_same_vector_replies(cluster):
    """dispatch="flat-safe": forwards and their replies packed into the
    SAME 16-packet vector of one dispatch.  The scan discipline cannot
    restore these (a vector's restore probe sees only the pre-vector
    table, and the host slow path only knows host-recorded sessions);
    the flat-safe post-commit re-probe restores them on device."""
    n1 = cluster.add_node("node-1")
    client_ip = cluster.deploy_pod("node-1", "client")
    backend_ip = cluster.deploy_pod("node-1", "web-1", labels=WEB_LABELS)
    cluster.apply_service({
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"clusterIP": "10.96.0.10", "selector": WEB_LABELS,
                 "ports": [{"name": "http", "protocol": "TCP", "port": 80,
                            "targetPort": 8080}]},
    })
    cluster.apply_endpoints({
        "metadata": {"name": "web", "namespace": "default"},
        "subsets": [{
            "addresses": [{"ip": backend_ip, "nodeName": "node-1",
                           "targetRef": {"kind": "Pod", "name": "web-1",
                                          "namespace": "default"}}],
            "ports": [{"name": "http", "port": 8080, "protocol": "TCP"}],
        }],
    })
    assert wait_for(lambda: len(n1.nat_renderer.mappings()) > 0)

    fn = cluster.frame_nodes["node-1"]
    fn.runner.batch_size = 16
    fn.runner.max_vectors = 2
    fn.runner.dispatch = "flat-safe"

    # fwd/reply pairs interleaved: every reply shares a vector with its
    # forward (8 pairs = 16 frames = exactly one vector).
    frames = []
    for i in range(8):
        frames.append(build_frame(client_ip, "10.96.0.10", 6, 41000 + i, 80))
        frames.append(build_frame(backend_ip, client_ip, 6, 8080, 41000 + i))
    cluster.inject("node-1", frames)
    cluster.run_datapaths()

    out = cluster.delivered_frames("node-1")
    assert len(out) == 16
    got = [frame_tuple(f) for f in out]
    for i in range(8):
        assert (client_ip, backend_ip, 6, 41000 + i, 8080) in got
        assert ("10.96.0.10", client_ip, 6, 80, 41000 + i) in got
    for f in out:
        assert verify_checksums(f)
    # Restored ON DEVICE: no host restores, no punts.
    assert fn.runner.counters.host_restores == 0
    assert fn.runner.metrics()["slowpath_punts_total"] == 0


# ------------------------------------------------- double-buffering overlap


def test_double_buffering_overlaps_host_and_device_work():
    """VERDICT r5 "next round" #1: the double-buffered runner must
    MEASURE as overlapped, not just claim it.  With a known host cost h
    injected per batch and a device cost d made non-trivial by a real
    rule table, the pipelined loop (max_inflight=2) must run the same
    workload in ~N*max(h, d) while the serial loop (max_inflight=1)
    pays the N*(h+d) sum."""
    import time

    import jax.numpy as jnp

    from vpp_tpu.datapath import DataplaneRunner, VxlanOverlay
    from vpp_tpu.datapath.io import InMemoryRing
    from vpp_tpu.ops.classify import build_rule_tables
    from vpp_tpu.ops.nat import NatMapping, build_nat_tables
    from vpp_tpu.ops.pipeline import RouteConfig
    from vpp_tpu.policy.renderer.api import Action, ContivRule

    class HostCostRunner(DataplaneRunner):
        """Fixed injected host-side cost per harvested batch — a
        stand-in for the native apply / slow-path work whose overlap
        with device compute the double buffering exists to buy."""

        host_cost = 0.0

        def _slowpath_and_trace(self, *args, **kwargs):
            if self.host_cost:
                time.sleep(self.host_cost)
            return super()._slowpath_and_trace(*args, **kwargs)

    batch_size, max_vectors, n_batches = 256, 32, 6
    per_admit = batch_size * max_vectors
    src_ip, dst_ip = "10.1.1.2", "10.1.1.3"
    # A real classify load: several hundred non-matching rules ahead of
    # the permit, so the device leg is genuine compute, not a no-op.
    rules = [
        ContivRule(action=Action.PERMIT, protocol=6,
                   dst_port=20000 + i)
        for i in range(640)
    ] + [ContivRule(action=Action.PERMIT)]
    acl = build_rule_tables(
        [rules], {ip_to_u32(src_ip): (0, 0), ip_to_u32(dst_ip): (0, 0)})
    nat = build_nat_tables(
        [NatMapping("10.96.0.10", 80, 6, backends=[("10.1.1.9", 8080, 1)])],
        snat_enabled=False, pod_subnet="10.1.0.0/16")
    route = RouteConfig(
        pod_subnet_base=jnp.asarray(ip_to_u32("10.1.0.0"), dtype=jnp.uint32),
        pod_subnet_mask=jnp.asarray(0xFFFF0000, dtype=jnp.uint32),
        this_node_base=jnp.asarray(ip_to_u32("10.1.1.0"), dtype=jnp.uint32),
        this_node_mask=jnp.asarray(0xFFFFFF00, dtype=jnp.uint32),
        host_bits=jnp.asarray(8, dtype=jnp.int32),
    )
    frame = build_frame(src_ip, dst_ip, 6, 40000, 9999)

    def run(host_cost, max_inflight, warm=False):
        """Feed n_batches admits and time the drain; returns (seconds
        per batch, frames delivered locally)."""
        rx, local = InMemoryRing(), InMemoryRing()
        runner = HostCostRunner(
            acl=acl, nat=nat, route=route,
            overlay=VxlanOverlay(local_ip=ip_to_u32("192.168.16.1"),
                                 local_node_id=1),
            source=rx, tx=InMemoryRing(), local=local, host=InMemoryRing(),
            batch_size=batch_size, max_vectors=max_vectors,
            max_inflight=max_inflight, engine="python",
        )
        runner.host_cost = 0.0
        if warm:
            rx.send([frame] * per_admit)  # compile outside the timing
            runner.drain()
        runner.host_cost = host_cost
        for _ in range(n_batches):
            rx.send([frame] * per_admit)
        t0 = time.perf_counter()
        runner.drain()
        elapsed = time.perf_counter() - t0
        expect = n_batches * per_admit + (per_admit if warm else 0)
        assert len(local) == expect, "frames lost in the loop"
        return elapsed / n_batches

    # Best-of-3: overlap needs idle cores to overlap INTO, so a
    # noisy-neighbor burst (another suite process pinning every CPU
    # during one attempt) can mask it; a calibrated quiet attempt
    # proves the machinery.  Each attempt re-measures the device leg
    # so the injected host leg tracks the machine's current speed.
    last = None
    for attempt in range(3):
        t_dev = run(0.0, 1, warm=(attempt == 0))  # device + real host legs
        h = max(t_dev, 0.004)        # injected host leg ~= device leg
        t_serial = run(h, 1)
        t_olap = run(h, 2)
        # The pipelined loop clearly beats the serial sum, and lands
        # near max(host, device) rather than their sum.
        if t_olap < 0.80 * t_serial and t_olap < 1.6 * max(h, t_dev):
            break
        last = (t_dev, h, t_serial, t_olap)
    else:
        t_dev, h, t_serial, t_olap = last
        assert False, (
            f"no overlap in 3 attempts: {t_olap*1e3:.2f} ms/batch "
            f"pipelined vs {t_serial*1e3:.2f} ms/batch serial "
            f"(device {t_dev*1e3:.2f}, host {h*1e3:.2f})")


# -------------------------------------- ISSUE 7 resource-leak regressions


def test_afpacket_failed_construction_closes_socket(monkeypatch):
    """bind/PACKET_FANOUT can fail AFTER the raw socket exists; the
    half-constructed IO must close it (found by the test-race
    ResourceWarning gate: a fanout-unsupported kernel leaked two fds
    per skipped test)."""
    import socket as socket_mod

    from vpp_tpu.datapath import io as dio

    created = []
    real_socket = socket_mod.socket

    class Recorder(real_socket):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            created.append(self)

    monkeypatch.setattr(dio.socket, "socket", Recorder)
    with pytest.raises(OSError) as excinfo:
        dio.AfPacketIO("no-such-iface-zz9")
    if isinstance(excinfo.value, PermissionError):
        # No CAP_NET_RAW: the raw socket never existed, so there is
        # nothing to leak — same skip discipline as the other
        # AF_PACKET tests (PermissionError ⊆ OSError, so it must be
        # told apart AFTER the raises block).
        pytest.skip("AF_PACKET unavailable")
    assert created, "socket never constructed?"
    assert all(s.fileno() == -1 for s in created), "socket leaked open"


def test_pcap_writer_closes_on_gc(tmp_path):
    """Quarantine forensics writers may be dropped without an explicit
    close (runner owners); the GC safety net must close the handle."""
    import gc

    from vpp_tpu.datapath.io import PcapWriter

    w = PcapWriter(str(tmp_path / "x.pcap"))
    w.send([b"\x00" * 60])
    fh = w._fh
    del w
    gc.collect()
    assert fh.closed
